"""Perf-regression diff over bench artifacts.

    python -m distributed_drift_detection_tpu perf BENCH_r04.json BENCH_r05.json

``bench.py`` prints one JSON line per invocation and the driver archives it
per round (``BENCH_r*.json``); until now the trajectory could only be
eyeballed. This CLI loads any mix of those artifacts, normalises each into
a fixed set of **cells** (headline rows/s, Final Time, device-true detect
time, compile split, phase medians, the soak/chunked riders, XLA cost/
memory fields), prints a per-cell diff across rounds, and exits nonzero
when a *gated* cell regresses beyond ``--tolerance`` — so CI can gate on
the bench trajectory instead of a human rereading JSON.

Artifact forms accepted, in order of preference:

* the raw bench JSON line (``python bench.py > out.json``);
* the driver wrapper ``{"cmd", "rc", "tail", "parsed"}`` with ``parsed``
  holding the bench dict;
* a wrapper whose ``tail`` contains the JSON line as text — including the
  **head-truncated** case (the wrapper keeps only the last N bytes of
  output): the line is repaired by re-opening the brace and dropping the
  first, garbled key. Cells the truncation ate are re-derived where the
  surviving fields allow: ``final_time_s`` from ``rep_times_s`` via the
  same stall-aware selection bench.py uses (median of repetitions within
  1.5× the fastest), ``value`` from ``rows / final_time_s``,
  ``detect_time_s`` from the non-stalled ``phase_s`` medians.

Gating semantics: only robust whole-run cells gate (throughput, Final
Time, detect time, collect's share of the span, the soak/chunked headline
rates); compile splits (the warm-start cold/cold-xla pair included), phase
medians, XLA counters and quality cells print informationally. A pair
where either artifact is ``contended`` (≥ half its repetitions stalled —
bench.py's own suspicion marker) reports its regressions as *suspect* and
never fails the exit code: a stalling shared tunnel is not a code
regression. ``--informational`` prints everything and always exits 0 (the
CI trajectory job).

Pure stdlib, no jax — runs wherever the artifacts land (same contract as
the ``report`` CLI).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

# Mirrors bench.py's stall-aware selection: the fastest repetition is
# stall-free by construction; anything beyond 1.5× it is a stall.
STALL_FACTOR = 1.5

_UP, _DOWN = "up", "down"

# (cell, better-direction, gated, unit). Order is the report order.
CELLS = (
    ("value", _UP, True, "rows/s"),
    ("final_time_s", _DOWN, True, "s"),
    ("detect_time_s", _DOWN, True, "s"),
    ("compile_first_call_s", _DOWN, False, "s"),
    ("compile_overhead_s", _DOWN, False, "s"),
    # AOT warm-start split (cold_vs_warm_compile_s, r06+): cold_s is the
    # prepare-phase lower().compile() span, cold_xla_s its backend-compile
    # half (≈0 against a populated persistent cache). Informational —
    # cache state is invocation provenance, not a code property.
    ("compile_cold_s", _DOWN, False, "s"),
    ("compile_cold_xla_s", _DOWN, False, "s"),
    ("phase_upload_s", _DOWN, False, "s"),
    ("phase_collect_s", _DOWN, False, "s"),
    # Collect's share of the Final Time span (r06+): GATED — the compacted
    # collect's whole point is keeping this small, and a regression here
    # is a code property (the absolute phase medians above stay
    # informational because they move with the tunnel).
    ("collect_share", _DOWN, True, ""),
    ("soak_value", _UP, True, "rows/s"),
    ("soak_xl_value", _UP, True, "rows/s"),
    # Host-ingest pipeline (r10+): the chunked headline, the parse-only
    # feed ceiling, and the overlap ratio are ALL GATED — the parallel
    # parse→stripe→upload pipeline's whole claim is feeding the device at
    # ingest speed, and a regression in any of the three is a code
    # property (stall-aware like every gate: contended artifacts report
    # suspect, never fail). The per-stage busy cells below print
    # informationally — they sum across workers and move with the host.
    ("chunked_value", _UP, True, "rows/s"),
    ("chunked_parse_rows_per_sec", _UP, True, "rows/s"),
    ("chunked_overlap_efficiency", _UP, True, ""),
    ("chunked_stage_read_s", _DOWN, False, "s"),
    ("chunked_stage_parse_s", _DOWN, False, "s"),
    ("chunked_stage_sanitize_s", _DOWN, False, "s"),
    ("chunked_stage_stripe_s", _DOWN, False, "s"),
    ("chunked_stage_upload_s", _DOWN, False, "s"),
    ("chunked_feed_wait_s", _DOWN, False, "s"),
    # Multi-tenant aggregate throughput (bench.py --tenants, r09+): the
    # stacked-kernel rows/s at T∈{8,64} is GATED — amortizing dispatch/
    # collect across the tenant plane is the tentpole's whole claim, and
    # a regression here is a code property. The sequential baseline and
    # the speedup ratio print informationally (the baseline moves with
    # host load; the gated cell is the absolute aggregate rate).
    ("tenant_agg_rows_per_sec_t8", _UP, True, "rows/s"),
    ("tenant_agg_rows_per_sec_t64", _UP, True, "rows/s"),
    ("tenant_seq_rows_per_sec_t8", _UP, False, "rows/s"),
    ("tenant_seq_rows_per_sec_t64", _UP, False, "rows/s"),
    ("tenant_speedup_t8", _UP, False, "x"),
    ("tenant_speedup_t64", _UP, False, "x"),
    # Online-serving SLO (bench.py --serve, r07+). Throughput and p50
    # stay informational (they move with host load and the requested
    # replay rate), but p99 row→verdict latency is GATED (r08+): a
    # tail-latency blowup is a code property of the serving pipeline.
    # The gated cell is deliberately the SIDECAR-derived serve_p99_ms
    # (exact per-row wall-clock, what a client experiences); the live-
    # histogram twins serve_registry_p50/p99_ms print informationally —
    # bucket quantization makes them too coarse to gate at a 10%
    # tolerance, and their agreement with the sidecar pair (recorded in
    # the same artifact) is what validates the tracing path itself.
    # Stall-aware like collect_share: an artifact whose serve bench
    # timed out or failed to drain marks its serve cells suspect —
    # reported, never gating (see diff_benches).
    ("serve_rows_per_sec", _UP, False, "rows/s"),
    ("serve_p50_ms", _DOWN, False, "ms"),
    ("serve_p99_ms", _DOWN, True, "ms"),
    ("serve_registry_p50_ms", _DOWN, False, "ms"),
    ("serve_registry_p99_ms", _DOWN, False, "ms"),
    # Serve-pipeline observatory (bench.py --serve rider, r16+): the
    # serve loop's per-stage busy split (serve_pipeline_s dict →
    # serve_stage_*_s cells) prints informationally — absolute stage
    # seconds move with host load and the replay rate. GATED is
    # serve_busy_utilization = stage-busy sum / serve-loop wall: the
    # instrumentation-honesty claim (~1.0 on a single-threaded loop).
    # A drop means the observatory lost track of where the loop's
    # time goes — a code property, exactly what the bottleneck report
    # depends on. Stall-aware via the serve_* suspect markers.
    ("serve_busy_utilization", _UP, True, ""),
    ("serve_stage_seal_wait_s", _DOWN, False, "s"),
    ("serve_stage_feed_s", _DOWN, False, "s"),
    ("serve_stage_device_s", _DOWN, False, "s"),
    ("serve_stage_collect_s", _DOWN, False, "s"),
    ("serve_stage_publish_s", _DOWN, False, "s"),
    ("serve_stage_forensics_s", _DOWN, False, "s"),
    ("serve_stage_adapt_s", _DOWN, False, "s"),
    # Serve-ingress admission rate (bench.py --serve ingest rider, r13+):
    # v2 binary frames through the real loopback socket → event-loop
    # ingress → vectorized frame admission → pooled-striper seals, with
    # NO device feed — the admission-only ceiling of the serve path.
    # GATED: sustaining ≥10M rows/s here is the wire-v2 tentpole's whole
    # claim, and a regression is a code property of the ingress/admission
    # pipeline (the serve_* stall markers apply — a wedged host reports
    # suspect, never gates). The MB/s twin prints informationally.
    ("serve_ingest_rows_per_sec", _UP, True, "rows/s"),
    ("serve_ingest_mb_per_sec", _UP, False, "MB/s"),
    # Fleet-scale serving (bench.py --fleet, r14+): aggregate rows/s of
    # a router-fronted MULTI-PROCESS serve fleet (N subprocess daemons,
    # consistent-hash tenant placement, v2 frames through the router's
    # header-rewrite path, full fleet verdict coverage). GATED — the
    # fleet tentpole's whole claim is aggregate throughput scaling with
    # daemon count instead of plateauing at one process, and a
    # regression is a code property of the router/fleet path. The
    # 1-daemon baseline and the scaling ratio print informationally
    # (both move with host load; the gate is the absolute aggregate
    # rate). Stall-aware via the fleet_timeout/fleet_drained markers,
    # like the serve cells.
    ("fleet_agg_rows_per_sec", _UP, True, "rows/s"),
    ("fleet_agg_rows_per_sec_d1", _UP, False, "rows/s"),
    ("fleet_speedup", _UP, False, "x"),
    # Elastic sweep scheduler (bench.py --sched, r15+): cells completed
    # per wall-clock second of a scheduler-run grid (3 worker
    # subprocesses, lease/heartbeat control plane, registry-audited
    # exactly-once). GATED — the fleet controller's whole claim is
    # finishing a grid faster than walking it serially, and a regression
    # is a code property of the sched/ control plane. The serial rate
    # and the speedup ratio print informationally (both move with host
    # load; the acceptance bar is speedup ≥ 1.5× at 3 workers, recorded
    # in the artifact).
    ("sched_cells_per_sec", _UP, True, "cells/s"),
    ("sched_serial_cells_per_sec", _UP, False, "cells/s"),
    ("sched_speedup", _UP, False, "x"),
    # Adaptation recovery (bench.py --serve adapt rider, r12+): rows from
    # a drift verdict until post-drift chunk error returns within the
    # policy's epsilon of the pre-drift level, on the planted
    # recurring-drift stream. Informational — the span moves with the
    # stream geometry and the chunk span; the adapt-smoke CI job and
    # tests/test_adapt.py own correctness.
    ("serve_adapt_recovery_rows", _DOWN, False, "rows"),
    # Incident autopsy capture span (bench.py --smoke rider, r18+): median
    # wall-clock of one IncidentRecorder.capture() over realistic evidence
    # sources (full flight ring, snapshots, verdict tail). Informational —
    # the capture runs on the SLO evaluator thread, off the serve hot loop
    # (the sidecar bit-parity test owns that claim); this cell keeps the
    # off-loop cost visible round over round.
    ("serve_incident_capture_ms", _DOWN, False, "ms"),
    # History plane micro-bench (bench.py --history, r17+): append and
    # query throughput of the jax-free on-disk series store. Informational
    # — both move with the filesystem under the runner; the history-smoke
    # CI job and tests/test_history.py own correctness.
    ("history_append_samples_per_sec", _UP, False, "samples/s"),
    ("history_rate_query_ms", _DOWN, False, "ms"),
    ("xla_flops", _DOWN, False, "flops"),
    ("xla_bytes_accessed", _DOWN, False, "B"),
    ("xla_temp_bytes", _DOWN, False, "B"),
    ("mean_delay_batches", _DOWN, False, "batches"),
    ("detections", None, False, ""),
)


class ArtifactError(ValueError):
    """The file holds no recoverable bench JSON."""


# --- the summary-line contract ---------------------------------------------
#
# bench.py emits ONE machine-parseable JSON line per invocation, and the
# round driver archives only the last ~2 KB of stdout — BENCH_r05.json
# recorded `parsed: null` because the headline line outgrew that window
# and the driver's last-line parse found a head-truncated fragment. The
# contract is therefore: the FINAL stdout line must carry every cell the
# perf CLI gates on and stay within SUMMARY_LINE_BUDGET bytes. When the
# full artifact line is bigger, bench.py prints it first (humans, full
# archives) and then a trimmed final line — the gate-relevant subset plus
# `"trimmed": true` — and load_bench() below re-merges the pair (trimmed
# wins) so nothing is lost when the full line survives. Emitter and
# parser live together here so they cannot drift apart.

SUMMARY_LINE_BUDGET = 1900

#: Keys the trimmed final line must carry: every perf cell, the fields
#: cells derive from, and the provenance markers the gating logic reads.
SUMMARY_KEYS = tuple(c for c, _, _, _ in CELLS) + (
    "metric",
    "unit",
    "trimmed",
    "rows",
    "rep_times_s",
    "stalled_reps",
    "contended",
    "smoke",
    "device",
    "error",
    "vs_baseline",
    "serve_timeout",
    "serve_drained",
    "serve_ingest_error",
    # nested dicts bench_cells() extracts from
    "compile_s",
    "phase_median_s",
    "cold_vs_warm_compile_s",
    "chunked_pipeline_s",
    "serve_pipeline_s",
    "xla",
)

#: Dropped from an over-budget trimmed line in this order (informational
#: cells first) until it fits — the gated scalars always survive.
_SUMMARY_DROP_ORDER = (
    "xla",
    "serve_pipeline_s",
    "chunked_pipeline_s",
    "phase_median_s",
    "cold_vs_warm_compile_s",
    "compile_s",
    "rep_times_s",
    "stalled_reps",
)


def summary_lines(bench: dict, budget: int = SUMMARY_LINE_BUDGET) -> list[str]:
    """The stdout lines for one bench artifact under the summary-line
    contract: ``[full]`` when the artifact fits ``budget``, else
    ``[full, trimmed]`` with the trimmed line guaranteed to fit and to
    carry every gated cell (see module comment). bench.py routes every
    mode's final print through this."""
    full = json.dumps(bench)
    if len(full) <= budget:
        return [full]
    trimmed = {k: bench[k] for k in SUMMARY_KEYS if k in bench}
    trimmed["trimmed"] = True
    line = json.dumps(trimmed)
    for key in _SUMMARY_DROP_ORDER:
        if len(line) <= budget:
            break
        if trimmed.pop(key, None) is not None:
            line = json.dumps(trimmed)
    return [full, line]


def _scan_lines(lines: list[str], path: str) -> tuple[dict, list[str]]:
    """Reversed scan of stdout/tail lines for the bench dict.

    Handles the full summary-line contract: a trimmed final line
    (``"trimmed": true``) re-merges with the full artifact line above it
    (trimmed wins on conflicts — it is the newer emission); a
    head-truncated full line (the driver kept only the last N bytes) is
    repaired by re-opening the brace and dropping the first, garbled key.
    """
    notes: list[str] = []
    trimmed: "dict | None" = None
    for line in reversed(lines):
        line = line.strip()
        if not line:
            continue
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            # Head-truncated capture, cutting mid-line. Re-open the
            # object and drop the first key — its name is unknowable
            # (the cut may have landed inside it), so its value cannot
            # be trusted either.
            try:
                fixed = json.loads('{"' + line.lstrip('{",'))
            except json.JSONDecodeError:
                continue
            garbled = next(iter(fixed), None)
            if garbled is not None:
                fixed.pop(garbled)
            notes.append(
                "recovered from head-truncated tail "
                f"(dropped garbled first key {garbled!r})"
            )
            if trimmed is not None:
                notes.append(
                    "merged trimmed summary line with the recovered "
                    "full line"
                )
                return {**fixed, **trimmed}, notes
            return fixed, notes
        # a stray scalar line ('0', 'true', an exit-code echo) is valid
        # JSON but not a bench dict — keep scanning upward
        if not isinstance(parsed, dict):
            continue
        if parsed.get("trimmed") and trimmed is None:
            trimmed = parsed  # keep scanning for the full line above
            continue
        if trimmed is not None:
            return {**parsed, **trimmed}, [
                "merged trimmed summary line with full artifact line"
            ]
        return parsed, notes
    if trimmed is not None:
        return trimmed, notes + [
            "trimmed summary line only (full artifact line not captured)"
        ]
    raise ArtifactError(f"{path}: no recoverable bench JSON line")


def load_bench(path: str) -> tuple[dict, list[str]]:
    """Load one bench artifact → ``(bench dict, provenance notes)``."""
    with open(path) as fh:
        text = fh.read()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        # Raw multi-line bench stdout (the summary-line contract emits
        # full + trimmed lines when the artifact outgrows the budget).
        return _scan_lines(text.splitlines(), path)
    if not isinstance(obj, dict):
        raise ArtifactError(f"{path}: expected a JSON object")
    if "metric" in obj or "value" in obj:
        return obj, []  # the raw bench line
    if "parsed" in obj or "tail" in obj:  # driver wrapper
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and not parsed.get("trimmed"):
            return parsed, []
        # `parsed` may be the trimmed final line (the driver parses only
        # the last line) — scan the tail to merge with the full line.
        try:
            return _scan_lines(
                (obj.get("tail") or "").strip().splitlines(), path
            )
        except ArtifactError:
            if isinstance(parsed, dict):
                return parsed, [
                    "trimmed summary line only (tail unrecoverable)"
                ]
            raise ArtifactError(
                f"{path}: wrapper holds no recoverable bench JSON "
                f"(rc={obj.get('rc')})"
            ) from None
    raise ArtifactError(f"{path}: not a bench artifact or driver wrapper")


def _stall_split(times: list[float]) -> tuple[list[float], list[int]]:
    floor = min(times)
    stalled = [i for i, t in enumerate(times) if t > STALL_FACTOR * floor]
    clean = [t for i, t in enumerate(times) if i not in stalled]
    return clean, stalled


def bench_cells(bench: dict) -> tuple[dict[str, float], list[str]]:
    """Normalise one bench dict into the cell map (+ derivation notes)."""
    cells: dict[str, float] = {}
    notes: list[str] = []
    rep = bench.get("rep_times_s") or []
    stalled: list[int] | None = None

    ft = bench.get("final_time_s")
    if ft is None and rep:
        clean, stalled = _stall_split(rep)
        ft = statistics.median(clean)
        notes.append("final_time_s derived from rep_times_s (stall-aware median)")
    if ft is not None:
        cells["final_time_s"] = float(ft)

    val = bench.get("value")
    if val is None and ft and bench.get("rows"):
        val = float(bench["rows"]) / float(ft)
        notes.append("value derived from rows / final_time_s")
    if val is not None:
        cells["value"] = float(val)

    dt = bench.get("detect_time_s")
    phase_s = bench.get("phase_s") or {}
    if dt is None and phase_s.get("detect") and rep:
        if stalled is None:
            _, stalled = _stall_split(rep)
        clean_d = [
            t for i, t in enumerate(phase_s["detect"]) if i not in stalled
        ]
        if clean_d:
            dt = statistics.median(clean_d)
            notes.append("detect_time_s derived from phase_s (non-stalled median)")
    if dt is not None:
        cells["detect_time_s"] = float(dt)

    comp = bench.get("compile_s") or {}
    for src, dst in (
        ("first_call_s", "compile_first_call_s"),
        ("compile_overhead_s", "compile_overhead_s"),
    ):
        if comp.get(src) is not None:
            cells[dst] = float(comp[src])
    # Phase medians are STALL-AWARE (satellite, ISSUE 9): r05's artifact
    # had 11/15 reps stalled, so a raw median of phase_s described the
    # contended tunnel, not the code. Prefer the artifact's own
    # stall-filtered medians (phase_median_s, r09+); derive the same
    # filtering from rep_times_s for older artifacts.
    phase_med = bench.get("phase_median_s") or {}
    for name in ("upload", "collect"):
        if phase_med.get(name) is not None:
            cells[f"phase_{name}_s"] = float(phase_med[name])
        elif phase_s.get(name):
            vals = phase_s[name]
            if rep and len(rep) == len(vals):
                if stalled is None:
                    _, stalled = _stall_split(rep)
                clean_v = [
                    v for i, v in enumerate(vals) if i not in stalled
                ]
                if clean_v and len(clean_v) < len(vals):
                    vals = clean_v
                    notes.append(
                        f"phase_{name}_s derived from phase_s "
                        "(non-stalled median)"
                    )
            cells[f"phase_{name}_s"] = float(statistics.median(vals))

    for k in (
        "collect_share",
        "soak_value",
        "soak_xl_value",
        "chunked_value",
        "chunked_parse_rows_per_sec",
        "chunked_overlap_efficiency",
        "tenant_agg_rows_per_sec_t8",
        "tenant_agg_rows_per_sec_t64",
        "tenant_seq_rows_per_sec_t8",
        "tenant_seq_rows_per_sec_t64",
        "tenant_speedup_t8",
        "tenant_speedup_t64",
        "serve_rows_per_sec",
        "serve_p50_ms",
        "serve_p99_ms",
        "serve_registry_p50_ms",
        "serve_registry_p99_ms",
        "serve_busy_utilization",
        "serve_ingest_rows_per_sec",
        "serve_ingest_mb_per_sec",
        "fleet_agg_rows_per_sec",
        "fleet_agg_rows_per_sec_d1",
        "fleet_speedup",
        "sched_cells_per_sec",
        "sched_serial_cells_per_sec",
        "sched_speedup",
        "serve_adapt_recovery_rows",
        "serve_incident_capture_ms",
        "history_append_samples_per_sec",
        "history_rate_query_ms",
        "mean_delay_batches",
        "detections",
    ):
        if bench.get(k) is not None:
            cells[k] = float(bench[k])
    # Per-stage busy breakdown of the host-ingest pipeline (r10+):
    # bench's chunked rider records `chunked_pipeline_s` as a dict.
    pipe = bench.get("chunked_pipeline_s") or {}
    for name in ("read", "parse", "sanitize", "stripe", "upload"):
        if pipe.get(name) is not None:
            cells[f"chunked_stage_{name}_s"] = float(pipe[name])
    if pipe.get("feed_wait") is not None:
        cells["chunked_feed_wait_s"] = float(pipe["feed_wait"])
    # Per-stage busy breakdown of the serve loop (r16+): bench's
    # --serve rider records `serve_pipeline_s` as a dict (the chunked
    # rider's twin; stage names from telemetry.pipeline.SERVE_STAGES).
    spipe = bench.get("serve_pipeline_s") or {}
    for name in (
        "seal_wait",
        "feed",
        "device",
        "collect",
        "publish",
        "forensics",
        "adapt",
    ):
        if spipe.get(name) is not None:
            cells[f"serve_stage_{name}_s"] = float(spipe[name])
    cvw = bench.get("cold_vs_warm_compile_s") or {}
    for src, dst in (
        ("cold_s", "compile_cold_s"),
        ("cold_xla_s", "compile_cold_xla_s"),
    ):
        if cvw.get(src) is not None:
            cells[dst] = float(cvw[src])
    xla = bench.get("xla") or {}
    for k in ("flops", "bytes_accessed", "temp_bytes"):
        if xla.get(k) is not None:
            cells[f"xla_{k}"] = float(xla[k])
    return cells, notes


def _fmt(v: float | None) -> str:
    if v is None:
        return "-"
    if abs(v) >= 10_000:
        return f"{v:,.0f}"
    if abs(v) >= 100:
        return f"{v:.1f}"
    return f"{v:.4g}"


class Regression:
    def __init__(self, cell, old_name, new_name, pct, suspect):
        self.cell, self.old_name, self.new_name = cell, old_name, new_name
        self.pct, self.suspect = pct, suspect

    def __str__(self):
        s = "  (contended — suspect, not gated)" if self.suspect else ""
        return (
            f"{self.cell}: {self.old_name} → {self.new_name} "
            f"{self.pct:+.1%}{s}"
        )


def diff_benches(
    named: list[tuple[str, dict, list[str]]], tolerance: float
) -> tuple[str, list[Regression]]:
    """Render the per-cell diff table; returns ``(text, regressions)``.

    ``regressions`` includes the *suspect* (contended-pair) ones — the
    caller gates on ``[r for r in regressions if not r.suspect]``.
    """
    rows = []
    cell_maps, all_notes, contended = [], [], []
    serve_suspect, fleet_suspect = [], []
    for name, bench, notes in named:
        cells, derived = bench_cells(bench)
        cell_maps.append(cells)
        contended.append(bool(bench.get("contended")))
        # Serve-cell stall marker: a timed-out probe or an undrained
        # daemon means the latency numbers describe a wedged host, not
        # the code — their regressions report as suspect, never gate.
        serve_suspect.append(
            bool(bench.get("serve_timeout"))
            or bench.get("serve_drained") is False
        )
        fleet_suspect.append(
            bool(bench.get("fleet_timeout"))
            or bench.get("fleet_drained") is False
        )
        all_notes.extend(f"{name}: {n}" for n in notes + derived)

    width = max(12, *(len(n) for n, _, _ in named))
    header = f"{'cell':<34}" + "".join(
        f"{n:>{width + 2}}" for n, _, _ in named
    )
    if len(named) > 1:
        header += f"{'Δ last':>10}"
    rows.append(header)

    regressions: list[Regression] = []
    for cell, direction, gated, unit in CELLS:
        vals = [m.get(cell) for m in cell_maps]
        if all(v is None for v in vals):
            continue
        delta = ""
        if len(vals) > 1 and vals[-2] not in (None, 0) and vals[-1] is not None:
            pct = (vals[-1] - vals[-2]) / abs(vals[-2])
            delta = f"{pct:+9.1%}"
        arrow = ("↑" if direction == _UP else "↓") if direction else ""
        qual = ", ".join(q for q in (unit, arrow) if q)
        label = f"{cell} ({qual})" if qual else cell
        rows.append(
            f"{label:<34}"
            + "".join(f"{_fmt(v):>{width + 2}}" for v in vals)
            + (f"{delta:>10}" if len(named) > 1 else "")
        )
        if direction is None:
            continue
        for i in range(1, len(vals)):
            a, b = vals[i - 1], vals[i]
            if a in (None, 0) or b is None:
                continue
            pct = (b - a) / abs(a)
            adverse = pct > tolerance if direction == _DOWN else pct < -tolerance
            if gated and adverse:
                suspect = contended[i - 1] or contended[i]
                if cell.startswith("serve_"):
                    suspect = (
                        suspect or serve_suspect[i - 1] or serve_suspect[i]
                    )
                if cell.startswith("fleet_"):
                    suspect = (
                        suspect or fleet_suspect[i - 1] or fleet_suspect[i]
                    )
                regressions.append(
                    Regression(
                        cell, named[i - 1][0], named[i][0], pct,
                        suspect=suspect,
                    )
                )

    out = [
        f"perf diff over {len(named)} artifact(s)  "
        f"(gate tolerance {tolerance:.0%} on gated cells)",
        "",
    ]
    out.extend(rows)
    flagged = [n for (n, _, _), c in zip(named, contended) if c]
    if flagged:
        out.append("")
        out.append(
            "contended (≥ half the reps stalled — headline suspect): "
            + ", ".join(flagged)
        )
    if all_notes:
        out.append("")
        out.extend(f"note: {n}" for n in all_notes)
    out.append("")
    if regressions:
        out.append("REGRESSIONS beyond tolerance:")
        out.extend(f"  {r}" for r in regressions)
    else:
        out.append("no gated regressions beyond tolerance")
    return "\n".join(out), regressions


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu perf",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "artifacts", nargs="+",
        help="bench artifact path(s), oldest first (raw bench JSON or "
        "driver wrapper)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.10,
        help="fractional adverse change beyond which a gated cell is a "
        "regression (default 0.10)",
    )
    ap.add_argument(
        "--informational", action="store_true",
        help="print the diff but always exit 0 (the CI trajectory job)",
    )
    args = ap.parse_args(argv)
    named = []
    for p in args.artifacts:
        try:
            bench, notes = load_bench(p)
        except ArtifactError as e:
            raise SystemExit(f"perf: {e}")
        named.append((os.path.basename(p), bench, notes))
    text, regressions = diff_benches(named, args.tolerance)
    print(text)
    gating = [r for r in regressions if not r.suspect]
    if gating and not args.informational:
        raise SystemExit(1)


if __name__ == "__main__":
    main(sys.argv[1:])
