from .base import Model, ModelSpec
from .classifiers import (
    build_model,
    make_centroid,
    make_gnb,
    make_linear,
    make_majority,
    make_mlp,
)
from .rf import make_rf

__all__ = [
    "Model",
    "ModelSpec",
    "build_model",
    "make_centroid",
    "make_gnb",
    "make_linear",
    "make_majority",
    "make_mlp",
    "make_rf",
]
