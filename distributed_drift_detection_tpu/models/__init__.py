from .base import Model, ModelSpec
from .classifiers import (
    build_model,
    make_centroid,
    make_linear,
    make_majority,
    make_mlp,
)

__all__ = [
    "Model",
    "ModelSpec",
    "build_model",
    "make_centroid",
    "make_linear",
    "make_majority",
    "make_mlp",
]
