"""TPU-native classifiers for the train/predict/detect loop.

All families are pure pytrees (see ``base.py`` for the contract):

* ``majority`` — predicts the modal class of the training microbatch. The
  cheapest model and a faithful proxy for what the reference's RandomForest
  (``DDM_Process.py:96-105``) does on the sorted stream, where most training
  batches are single-class: it predicts that class until the concept changes.
  Also the model used for *exact* golden tests of the loop, since it is
  deterministic and shared bit-for-bit with the NumPy oracle.
* ``centroid`` / ``gnb`` — closed-form fits (nearest class centroid;
  Gaussian naive Bayes with axis-aligned covariance): a couple of one-hot
  matmuls each, so the engine's fit-every-step SPMD pattern is nearly free.
* ``linear`` — multinomial logistic regression (softmax), fitted with K
  full-batch gradient steps. One ``[B,F]×[F,C]`` matmul per step — MXU food.
* ``mlp`` — MLP with configurable hidden widths (default (128, 64), the
  BASELINE.json "Per-partition MLP(128,64)" config), fitted with K SGD +
  momentum steps.

Fits run inside ``lax.scan``/``vmap``, so they must be cheap, fixed-shape,
and key-driven. Class count is static (inferred from the dataset).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .base import Model, ModelSpec


# --------------------------------------------------------------------------
# majority-class
# --------------------------------------------------------------------------


def make_majority(spec: ModelSpec) -> Model:
    c = spec.num_classes

    def init(key):
        return jnp.int32(0)

    def fit(key, X, y, w):
        counts = jnp.zeros(c, jnp.float32).at[y].add(w)
        # argmax ties resolve to the lowest class, matching np.unique order
        # in the oracle.
        return jnp.argmax(counts).astype(jnp.int32)

    def predict(params, X):
        return jnp.full(X.shape[0], params, jnp.int32)

    return Model("majority", init, fit, predict)


# --------------------------------------------------------------------------
# nearest-centroid (closed form — the throughput flagship)
# --------------------------------------------------------------------------


class CentroidParams(NamedTuple):
    centroids: jax.Array  # [C, F]
    bias: jax.Array  # [C]: -0.5‖c‖² for present classes, -inf for absent


def make_centroid(spec: ModelSpec) -> Model:
    """Nearest-class-centroid classifier with a closed-form fit.

    ``fit`` is two small matmuls (one-hot segment sums), ``predict`` is one
    ``[B,F]×[F,C]`` matmul — no gradient loop, so the unconditional
    fit-every-step SPMD pattern of the engine costs almost nothing. On
    near-prototype concept streams it is statistically equivalent to the
    reference's batch-memorising RandomForest (both predict the training
    batch's class structure), making it the default throughput flagship.
    Classes absent from the training batch get a -inf score and are never
    predicted.
    """
    f, c = spec.num_features, spec.num_classes

    def init(key):
        return CentroidParams(
            jnp.zeros((c, f), jnp.float32),
            jnp.full(c, -jnp.inf, jnp.float32).at[0].set(0.0),
        )

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]  # [B, C]
        counts = jnp.sum(onehot, axis=0)  # [C]
        sums = onehot.T @ X  # [C, F]
        cent = sums / jnp.maximum(counts, 1.0)[:, None]
        bias = jnp.where(counts > 0, -0.5 * jnp.sum(cent * cent, axis=1), -jnp.inf)
        return CentroidParams(cent, bias)

    def predict(params, X):
        scores = X @ params.centroids.T + params.bias
        return jnp.argmax(scores, axis=-1).astype(jnp.int32)

    return Model("centroid", init, fit, predict)


# --------------------------------------------------------------------------
# Gaussian naive Bayes (closed form)
# --------------------------------------------------------------------------


class GNBParams(NamedTuple):
    """Prediction-ready form: everything predict needs beyond its two
    matmuls is folded in at fit time (σ² is recoverable as ½/half_inv_var)."""

    offset: jax.Array  # [F]: global feature mean the moments are centred on
    half_inv_var: jax.Array  # [C, F]: ½/σ² (smoothed)
    mean_inv_var: jax.Array  # [C, F]: μc/σ² (class means centred on offset)
    bias: jax.Array  # [C]: log prior − ½Σ log σ² − ½Σ μc²/σ² (−inf absent)


def make_gnb(spec: ModelSpec, *, var_smoothing: float = 1e-6) -> Model:
    """Gaussian naive Bayes with a closed-form fit.

    The second closed-form family next to ``centroid`` (C4 replacement
    territory, ``DDM_Process.py:96-105``): per-class feature means and
    variances from weighted one-hot matmuls, prediction as one ``[B,F]×[F,C]``
    matmul pair over the expanded quadratic form — so, like ``centroid``,
    the engine's unconditional fit-every-step SPMD pattern is nearly free,
    while axis-aligned class covariance (which nearest-centroid ignores)
    is modelled. Variances are smoothed by ``var_smoothing ×`` the overall
    feature-variance ceiling (sklearn's ``GaussianNB`` recipe); classes
    absent from the training batch score −inf and are never predicted.
    """
    f, c = spec.num_features, spec.num_classes

    def init(key):
        return GNBParams(
            jnp.zeros(f, jnp.float32),
            jnp.full((c, f), 0.5, jnp.float32),
            jnp.zeros((c, f), jnp.float32),
            jnp.full(c, -jnp.inf, jnp.float32).at[0].set(0.0),
        )

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]  # [B, C]
        counts = jnp.sum(onehot, axis=0)  # [C]
        denom = jnp.maximum(counts, 1.0)[:, None]
        wsum = jnp.maximum(jnp.sum(w), 1.0)
        gmean = jnp.sum(X * w[:, None], axis=0) / wsum  # [F]
        # Moments on globally-centred features: variance is shift-invariant,
        # and the naive f32 E[x²]−E[x]² form catastrophically cancels when a
        # feature's offset dwarfs its spread (raw un-normalized CSV streams).
        Xc = X - gmean
        mean_c = (onehot.T @ Xc) / denom  # [C, F]
        sq_c = (onehot.T @ (Xc * Xc)) / denom
        var = jnp.maximum(sq_c - mean_c * mean_c, 0.0)
        # Relative smoothing: proportional to the largest per-feature
        # variance of the batch (weighted, all classes pooled).
        gvar = jnp.sum(Xc * Xc * w[:, None], axis=0) / wsum
        eps = var_smoothing * jnp.maximum(jnp.max(gvar), 1e-12)
        var = var + eps
        inv_var = 1.0 / var
        # log(0) = -inf for absent classes; the finite variance/mean terms
        # keep the sum -inf, so no further masking is needed.
        log_prior = jnp.log(counts / wsum)
        bias = (
            log_prior
            - 0.5 * jnp.sum(jnp.log(var), axis=1)
            - 0.5 * jnp.sum(mean_c * mean_c * inv_var, axis=1)
        )
        return GNBParams(gmean, 0.5 * inv_var, mean_c * inv_var, bias)

    def predict(params, X):
        # −½ Σ_f (x−μ)²/σ² + log prior − ½Σ log σ², expanded into two matmuls
        # on the centred features (the same cancellation argument as in fit:
        # the expansion is only f32-safe once the offset is removed); the
        # x-independent terms are folded into ``bias`` at fit time.
        Xc = X - params.offset
        scores = (
            -(Xc * Xc) @ params.half_inv_var.T
            + Xc @ params.mean_inv_var.T
            + params.bias
        )
        return jnp.argmax(scores, axis=-1).astype(jnp.int32)

    return Model("gnb", init, fit, predict)


# --------------------------------------------------------------------------
# linear (multinomial logistic regression)
# --------------------------------------------------------------------------


class LinearParams(NamedTuple):
    w: jax.Array  # [F, C]
    b: jax.Array  # [C]


def _softmax_ce_grads(params: LinearParams, X, onehot, wn):
    logits = X @ params.w + params.b
    probs = jax.nn.softmax(logits, axis=-1)
    g = (probs - onehot) * wn[:, None]  # [B, C]
    return LinearParams(X.T @ g, jnp.sum(g, axis=0))


def make_linear(spec: ModelSpec, *, fit_steps: int = 32, learning_rate: float = 0.5) -> Model:
    f, c = spec.num_features, spec.num_classes

    def init(key):
        return LinearParams(jnp.zeros((f, c), jnp.float32), jnp.zeros(c, jnp.float32))

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32)
        wn = w / jnp.maximum(jnp.sum(w), 1.0)

        def step(params, _):
            grads = _softmax_ce_grads(params, X, onehot, wn)
            return (
                LinearParams(
                    params.w - learning_rate * grads.w,
                    params.b - learning_rate * grads.b,
                ),
                None,
            )

        params, _ = lax.scan(step, init(key), None, length=fit_steps)
        return params

    def predict(params, X):
        return jnp.argmax(X @ params.w + params.b, axis=-1).astype(jnp.int32)

    return Model("linear", init, fit, predict)


# --------------------------------------------------------------------------
# MLP
# --------------------------------------------------------------------------


class MLPLayer(NamedTuple):
    w: jax.Array
    b: jax.Array


def make_mlp(
    spec: ModelSpec,
    *,
    hidden: tuple[int, ...] = (128, 64),
    fit_steps: int = 32,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
) -> Model:
    dims = (spec.num_features, *hidden, spec.num_classes)

    def init(key):
        keys = jax.random.split(key, len(dims) - 1)
        layers = []
        for k, din, dout in zip(keys, dims[:-1], dims[1:]):
            scale = jnp.sqrt(2.0 / din)
            layers.append(
                MLPLayer(
                    scale * jax.random.normal(k, (din, dout), jnp.float32),
                    jnp.zeros(dout, jnp.float32),
                )
            )
        return tuple(layers)

    def forward(params, X):
        h = X
        for layer in params[:-1]:
            h = jax.nn.relu(h @ layer.w + layer.b)
        last = params[-1]
        return h @ last.w + last.b

    def loss_fn(params, X, onehot, wn):
        logits = forward(params, X)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(wn * jnp.sum(onehot * logp, axis=-1))

    grad_fn = jax.grad(loss_fn)

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, spec.num_classes, dtype=jnp.float32)
        wn = w / jnp.maximum(jnp.sum(w), 1.0)
        params0 = init(key)
        vel0 = jax.tree.map(jnp.zeros_like, params0)

        def step(carry, _):
            params, vel = carry
            grads = grad_fn(params, X, onehot, wn)
            vel = jax.tree.map(lambda v, g: momentum * v - learning_rate * g, vel, grads)
            params = jax.tree.map(lambda p, v: p + v, params, vel)
            return (params, vel), None

        (params, _), _ = lax.scan(step, (params0, vel0), None, length=fit_steps)
        return params

    def predict(params, X):
        return jnp.argmax(forward(params, X), axis=-1).astype(jnp.int32)

    return Model("mlp", init, fit, predict)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


def build_model(name: str, spec: ModelSpec, cfg=None) -> Model:
    """Build a model by config name (``RunConfig.model``)."""
    kw = {}
    if cfg is not None:
        kw = dict(fit_steps=cfg.fit_steps)
    if name == "majority":
        return make_majority(spec)
    if name == "centroid":
        return make_centroid(spec)
    if name == "gnb":
        return make_gnb(spec)
    if name == "linear":
        lr = cfg.learning_rate if cfg is not None else 0.5
        return make_linear(spec, learning_rate=lr, **kw)
    if name == "mlp":
        hidden = tuple(cfg.mlp_hidden) if cfg is not None else (128, 64)
        lr = cfg.mlp_learning_rate if cfg is not None else 0.05
        return make_mlp(spec, hidden=hidden, learning_rate=lr, **kw)
    if name == "rf":
        from .rf import make_rf

        # LRU must hold at least one live forest per partition (each lane's
        # snapshot interleaves through the shared host cache under
        # vmap_method='sequential'), with headroom for the rotate transition.
        parts = cfg.partitions if cfg is not None else 16
        return make_rf(
            spec,
            batch_size=cfg.per_batch if cfg is not None else 100,
            n_estimators=cfg.rf_estimators if cfg is not None else 100,
            n_jobs=cfg.cores if cfg is not None else 0,
            cache_size=max(64, 2 * parts),
        )
    raise ValueError(
        f"unknown model {name!r}; expected majority|centroid|gnb|linear|mlp|rf"
    )
