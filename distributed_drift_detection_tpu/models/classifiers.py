"""TPU-native classifiers for the train/predict/detect loop.

All families are pure pytrees (see ``base.py`` for the contract):

* ``majority`` — predicts the modal class of the training microbatch. The
  cheapest model and a faithful proxy for what the reference's RandomForest
  (``DDM_Process.py:96-105``) does on the sorted stream, where most training
  batches are single-class: it predicts that class until the concept changes.
  Also the model used for *exact* golden tests of the loop, since it is
  deterministic and shared bit-for-bit with the NumPy oracle.
* ``centroid`` / ``gnb`` — closed-form fits (nearest class centroid;
  Gaussian naive Bayes with axis-aligned covariance): a couple of one-hot
  matmuls each, so the engine's fit-every-step SPMD pattern is nearly free.
* ``linear`` — multinomial logistic regression (softmax), fitted with K
  full-batch gradient steps. One ``[B,F]×[F,C]`` matmul per step — MXU food.
* ``mlp`` — MLP with configurable hidden widths (default (128, 64), the
  BASELINE.json "Per-partition MLP(128,64)" config), fitted with K SGD +
  momentum steps.
* ``forest`` — extremely-randomized *oblique* forest fitted entirely on
  device (no host callback): random-projection splits make every tree a
  column block of one matmul; see :func:`make_forest`.

Fits run inside ``lax.scan``/``vmap``, so they must be cheap, fixed-shape,
and key-driven. Class count is static (inferred from the dataset).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .base import Model, ModelSpec


# --------------------------------------------------------------------------
# majority-class
# --------------------------------------------------------------------------


def make_majority(spec: ModelSpec) -> Model:
    c = spec.num_classes

    def init(key):
        return jnp.int32(0)

    def fit(key, X, y, w):
        counts = jnp.zeros(c, jnp.float32).at[y].add(w)
        # argmax ties resolve to the lowest class, matching np.unique order
        # in the oracle.
        return jnp.argmax(counts).astype(jnp.int32)

    def predict(params, X):
        return jnp.full(X.shape[0], params, jnp.int32)

    return Model("majority", init, fit, predict)


# --------------------------------------------------------------------------
# nearest-centroid (closed form — the throughput flagship)
# --------------------------------------------------------------------------


class CentroidParams(NamedTuple):
    centroids: jax.Array  # [C, F]
    bias: jax.Array  # [C]: -0.5‖c‖² for present classes, -inf for absent


def make_centroid(spec: ModelSpec) -> Model:
    """Nearest-class-centroid classifier with a closed-form fit.

    ``fit`` is two small matmuls (one-hot segment sums), ``predict`` is one
    ``[B,F]×[F,C]`` matmul — no gradient loop, so the unconditional
    fit-every-step SPMD pattern of the engine costs almost nothing. On
    near-prototype concept streams it is statistically equivalent to the
    reference's batch-memorising RandomForest (both predict the training
    batch's class structure), making it the default throughput flagship.
    Classes absent from the training batch get a -inf score and are never
    predicted.
    """
    f, c = spec.num_features, spec.num_classes

    def init(key):
        return CentroidParams(
            jnp.zeros((c, f), jnp.float32),
            jnp.full(c, -jnp.inf, jnp.float32).at[0].set(0.0),
        )

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]  # [B, C]
        counts = jnp.sum(onehot, axis=0)  # [C]
        sums = onehot.T @ X  # [C, F]
        cent = sums / jnp.maximum(counts, 1.0)[:, None]
        bias = jnp.where(counts > 0, -0.5 * jnp.sum(cent * cent, axis=1), -jnp.inf)
        return CentroidParams(cent, bias)

    def predict(params, X):
        scores = X @ params.centroids.T + params.bias
        return jnp.argmax(scores, axis=-1).astype(jnp.int32)

    return Model("centroid", init, fit, predict)


# --------------------------------------------------------------------------
# Gaussian naive Bayes (closed form)
# --------------------------------------------------------------------------


class GNBParams(NamedTuple):
    """Prediction-ready form: everything predict needs beyond its two
    matmuls is folded in at fit time (σ² is recoverable as ½/half_inv_var)."""

    offset: jax.Array  # [F]: global feature mean the moments are centred on
    half_inv_var: jax.Array  # [C, F]: ½/σ² (smoothed)
    mean_inv_var: jax.Array  # [C, F]: μc/σ² (class means centred on offset)
    bias: jax.Array  # [C]: log prior − ½Σ log σ² − ½Σ μc²/σ² (−inf absent)


def make_gnb(spec: ModelSpec, *, var_smoothing: float = 1e-6) -> Model:
    """Gaussian naive Bayes with a closed-form fit.

    The second closed-form family next to ``centroid`` (C4 replacement
    territory, ``DDM_Process.py:96-105``): per-class feature means and
    variances from weighted one-hot matmuls, prediction as one ``[B,F]×[F,C]``
    matmul pair over the expanded quadratic form — so, like ``centroid``,
    the engine's unconditional fit-every-step SPMD pattern is nearly free,
    while axis-aligned class covariance (which nearest-centroid ignores)
    is modelled. Variances are smoothed by ``var_smoothing ×`` the overall
    feature-variance ceiling (sklearn's ``GaussianNB`` recipe); classes
    absent from the training batch score −inf and are never predicted.
    """
    f, c = spec.num_features, spec.num_classes

    def init(key):
        return GNBParams(
            jnp.zeros(f, jnp.float32),
            jnp.full((c, f), 0.5, jnp.float32),
            jnp.zeros((c, f), jnp.float32),
            jnp.full(c, -jnp.inf, jnp.float32).at[0].set(0.0),
        )

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32) * w[:, None]  # [B, C]
        counts = jnp.sum(onehot, axis=0)  # [C]
        denom = jnp.maximum(counts, 1.0)[:, None]
        wsum = jnp.maximum(jnp.sum(w), 1.0)
        gmean = jnp.sum(X * w[:, None], axis=0) / wsum  # [F]
        # Moments on globally-centred features: variance is shift-invariant,
        # and the naive f32 E[x²]−E[x]² form catastrophically cancels when a
        # feature's offset dwarfs its spread (raw un-normalized CSV streams).
        Xc = X - gmean
        mean_c = (onehot.T @ Xc) / denom  # [C, F]
        sq_c = (onehot.T @ (Xc * Xc)) / denom
        var = jnp.maximum(sq_c - mean_c * mean_c, 0.0)
        # Relative smoothing: proportional to the largest per-feature
        # variance of the batch (weighted, all classes pooled).
        gvar = jnp.sum(Xc * Xc * w[:, None], axis=0) / wsum
        eps = var_smoothing * jnp.maximum(jnp.max(gvar), 1e-12)
        var = var + eps
        inv_var = 1.0 / var
        # log(0) = -inf for absent classes; the finite variance/mean terms
        # keep the sum -inf, so no further masking is needed.
        log_prior = jnp.log(counts / wsum)
        bias = (
            log_prior
            - 0.5 * jnp.sum(jnp.log(var), axis=1)
            - 0.5 * jnp.sum(mean_c * mean_c * inv_var, axis=1)
        )
        return GNBParams(gmean, 0.5 * inv_var, mean_c * inv_var, bias)

    def predict(params, X):
        # −½ Σ_f (x−μ)²/σ² + log prior − ½Σ log σ², expanded into two matmuls
        # on the centred features (the same cancellation argument as in fit:
        # the expansion is only f32-safe once the offset is removed); the
        # x-independent terms are folded into ``bias`` at fit time.
        Xc = X - params.offset
        scores = (
            -(Xc * Xc) @ params.half_inv_var.T
            + Xc @ params.mean_inv_var.T
            + params.bias
        )
        return jnp.argmax(scores, axis=-1).astype(jnp.int32)

    # saturation_guard: gnb's batch fit is a memorizer on single-class
    # concept batches (r04 measured rialto-stand-in failure; the guard is
    # the measured mitigation — config.GUARDED_MODELS).
    return Model("gnb", init, fit, predict, saturation_guard=True)


# --------------------------------------------------------------------------
# linear (multinomial logistic regression)
# --------------------------------------------------------------------------


class LinearParams(NamedTuple):
    w: jax.Array  # [F, C]
    b: jax.Array  # [C]


def _softmax_ce_grads(params: LinearParams, X, onehot, wn):
    logits = X @ params.w + params.b
    probs = jax.nn.softmax(logits, axis=-1)
    g = (probs - onehot) * wn[:, None]  # [B, C]
    return LinearParams(X.T @ g, jnp.sum(g, axis=0))


def make_linear(spec: ModelSpec, *, fit_steps: int = 32, learning_rate: float = 0.5) -> Model:
    f, c = spec.num_features, spec.num_classes

    def init(key):
        return LinearParams(jnp.zeros((f, c), jnp.float32), jnp.zeros(c, jnp.float32))

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, c, dtype=jnp.float32)
        wn = w / jnp.maximum(jnp.sum(w), 1.0)

        def step(params, _):
            grads = _softmax_ce_grads(params, X, onehot, wn)
            return (
                LinearParams(
                    params.w - learning_rate * grads.w,
                    params.b - learning_rate * grads.b,
                ),
                None,
            )

        params, _ = lax.scan(step, init(key), None, length=fit_steps)
        return params

    def predict(params, X):
        return jnp.argmax(X @ params.w + params.b, axis=-1).astype(jnp.int32)

    return Model("linear", init, fit, predict)


# --------------------------------------------------------------------------
# MLP
# --------------------------------------------------------------------------


class MLPLayer(NamedTuple):
    w: jax.Array
    b: jax.Array


def make_mlp(
    spec: ModelSpec,
    *,
    hidden: tuple[int, ...] = (128, 64),
    fit_steps: int = 32,
    learning_rate: float = 0.05,
    momentum: float = 0.9,
) -> Model:
    dims = (spec.num_features, *hidden, spec.num_classes)

    def init(key):
        keys = jax.random.split(key, len(dims) - 1)
        layers = []
        for k, din, dout in zip(keys, dims[:-1], dims[1:]):
            scale = jnp.sqrt(2.0 / din)
            layers.append(
                MLPLayer(
                    scale * jax.random.normal(k, (din, dout), jnp.float32),
                    jnp.zeros(dout, jnp.float32),
                )
            )
        return tuple(layers)

    def forward(params, X):
        h = X
        for layer in params[:-1]:
            h = jax.nn.relu(h @ layer.w + layer.b)
        last = params[-1]
        return h @ last.w + last.b

    def loss_fn(params, X, onehot, wn):
        logits = forward(params, X)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(wn * jnp.sum(onehot * logp, axis=-1))

    grad_fn = jax.grad(loss_fn)

    def fit(key, X, y, w):
        onehot = jax.nn.one_hot(y, spec.num_classes, dtype=jnp.float32)
        wn = w / jnp.maximum(jnp.sum(w), 1.0)
        params0 = init(key)
        vel0 = jax.tree.map(jnp.zeros_like, params0)

        def step(carry, _):
            params, vel = carry
            grads = grad_fn(params, X, onehot, wn)
            vel = jax.tree.map(lambda v, g: momentum * v - learning_rate * g, vel, grads)
            params = jax.tree.map(lambda p, v: p + v, params, vel)
            return (params, vel), None

        (params, _), _ = lax.scan(step, (params0, vel0), None, length=fit_steps)
        return params

    def predict(params, X):
        return jnp.argmax(forward(params, X), axis=-1).astype(jnp.int32)

    return Model("mlp", init, fit, predict)


# --------------------------------------------------------------------------
# extremely-randomized oblique forest (on-device trees)
# --------------------------------------------------------------------------


class ForestParams(NamedTuple):
    proj: jax.Array  # [F, T·(2^d − 1)]: oblique node projections
    thresh: jax.Array  # [T·(2^d − 1)]: node thresholds
    leaf_class: jax.Array  # [T, 2^d] i32: majority class per leaf


def make_forest(spec: ModelSpec, *, trees: int = 32, depth: int = 3) -> Model:
    """Extremely-randomized *oblique* forest, fitted entirely on device.

    The TPU-native answer to the reference's ``RandomForestClassifier``
    (C4, ``DDM_Process.py:96-105``) beyond the host-callback parity path
    (``models/rf.py``): axis-aligned greedy tree induction is hostile to
    the MXU (data-dependent shapes, per-node argmin loops), but the
    *extremely-randomized* end of the forest family (Geurts et al. 2006)
    needs no search at all — draw split directions and thresholds at
    random, and let averaging over many trees do the work. Two further
    moves make it matmul-shaped: splits are **oblique** (random Gaussian
    projections of all features, so every tree's every node is one column
    of a single ``[B,F]×[F,T·nodes]`` matmul — MXU food — and oblique
    random splits are strictly more expressive than axis-aligned ones at
    equal depth), and trees are **complete and fixed-depth** (heap-indexed
    routing = ``depth`` gather/compare rounds, no ragged structure).
    Thresholds are random quantiles (u ∈ [0.1, 0.9]) of each node's
    projected *batch* distribution — the classic ERT draw, computed from
    the root sample for every node so shapes stay static; deeper nodes
    therefore split on unconditioned quantiles, which costs some per-node
    discrimination and is repaid by the ensemble vote. Leaves predict
    their majority class (empty leaves fall back to the batch majority);
    the forest predicts the modal leaf vote.

    Like ``mlp``, the fit consumes its PRNG key (fresh projections every
    fit), so flags are seed-equivalent but not bit-equal across execution
    policies that re-key fits differently (window/rotations — see the
    ``RunConfig.window`` caveat).

    **Measured domain limit (r04, results/delay_parity.csv):** on
    outdoorStream ×64 the forest is boundary-perfect (delay 4.0 ± 0.1
    global batches, recall 1.000, zero spurious — indistinguishable from
    the rf/centroid families). On the rialto stand-in it shares gnb's
    documented failure class: trees *memorise* their training batch, so a
    fit carries ≈ zero accuracy across a concept boundary, and one
    DDM reset at a bad position (a handful of hard rows that every family
    mispredicts fire DDM's zero-tolerance ``p_min = 0`` regime just before
    the first boundary) lands the detector in its pinned-``p_min``
    blindspot with a model that will never recover accuracy — recall 0
    from a single stray fire. Smooth-boundary families (centroid/mlp)
    escape because their old-concept fit still gets a fraction of
    new-concept rows right, keeping the minima off the ceiling. The
    measured mitigation is the reference's own (dead) REGRESSION_THRESH
    idea: ``RunConfig(retrain_error_threshold=0.3)`` forces a refit in
    saturated-error regimes and returns rialto recall to 0.889.
    """
    if trees < 1:
        raise ValueError(f"forest_trees must be >= 1, got {trees}")
    if not 1 <= depth <= 16:
        raise ValueError(
            f"forest_depth must be in [1, 16] (2^depth leaves per tree), "
            f"got {depth}"
        )
    f, c = spec.num_features, spec.num_classes
    n_nodes = (1 << depth) - 1
    n_leaves = 1 << depth
    tree_idx = jnp.arange(trees)

    def init(key):
        return ForestParams(
            jnp.zeros((f, trees * n_nodes), jnp.float32),
            jnp.zeros((trees * n_nodes,), jnp.float32),
            jnp.zeros((trees, n_leaves), jnp.int32),
        )

    def _route(proj, thresh, X):
        """Heap-indexed routing: node i's children are 2i+1 / 2i+2; after
        ``depth`` rounds the index lands in the leaf block, whose offset is
        ``n_nodes``. Returns leaf ids ``[B, T]``."""
        b = X.shape[0]
        z = (X @ proj).reshape(b, trees, n_nodes)
        th = thresh.reshape(trees, n_nodes)
        node = jnp.zeros((b, trees), jnp.int32)
        for _ in range(depth):
            zv = jnp.take_along_axis(z, node[:, :, None], axis=2)[:, :, 0]
            tv = th[tree_idx[None, :], node]
            node = 2 * node + 1 + (zv > tv).astype(jnp.int32)
        return node - n_nodes

    def fit(key, X, y, w):
        kp, kt = jax.random.split(key)
        b = X.shape[0]
        proj = jax.random.normal(
            kp, (f, trees * n_nodes), jnp.float32
        ) / jnp.sqrt(jnp.float32(f))
        z = X @ proj  # [B, T·nodes]
        # ERT threshold draw: a random quantile of each node's projected
        # values over the valid rows (invalid rows sort to the end as +inf;
        # an all-invalid batch yields +inf thresholds → everything routes
        # left, and the all-zero leaf counts fall back to batch majority).
        zs = jnp.sort(jnp.where(w[:, None] > 0, z, jnp.inf), axis=0)
        nv = jnp.maximum(jnp.sum(w), 1.0)
        u = jax.random.uniform(
            kt, (trees * n_nodes,), minval=0.1, maxval=0.9
        )
        idx = jnp.clip((u * nv).astype(jnp.int32), 0, b - 1)
        thresh = jnp.take_along_axis(zs, idx[None, :], axis=0)[0]

        leaf = _route(proj, thresh, X)  # [B, T]
        counts = (
            jnp.zeros((trees, n_leaves, c), jnp.float32)
            .at[tree_idx[None, :], leaf, y[:, None]]
            .add(w[:, None])
        )
        totals = jnp.sum(counts, axis=-1)  # [T, L]
        batch_major = jnp.argmax(
            jnp.zeros(c, jnp.float32).at[y].add(w)
        ).astype(jnp.int32)
        leaf_class = jnp.where(
            totals > 0, jnp.argmax(counts, axis=-1).astype(jnp.int32), batch_major
        )
        return ForestParams(proj, thresh, leaf_class)

    def predict(params, X):
        leaf = _route(params.proj, params.thresh, X)
        votes = params.leaf_class[tree_idx[None, :], leaf]  # [B, T]
        tally = jnp.sum(jax.nn.one_hot(votes, c, dtype=jnp.float32), axis=1)
        # argmax ties resolve to the lowest class (the majority-model rule)
        return jnp.argmax(tally, axis=-1).astype(jnp.int32)

    # saturation_guard: pure leaf memorization across concept boundaries —
    # the measured memorizer × blindspot failure in this docstring; the
    # guard is the measured mitigation (config.GUARDED_MODELS).
    return Model("forest", init, fit, predict, saturation_guard=True)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------


def build_model(name: str, spec: ModelSpec, cfg=None) -> Model:
    """Build a model by config name (``RunConfig.model``)."""
    kw = {}
    if cfg is not None:
        kw = dict(fit_steps=cfg.fit_steps)
    if name == "majority":
        return make_majority(spec)
    if name == "centroid":
        return make_centroid(spec)
    if name == "gnb":
        return make_gnb(spec)
    if name == "linear":
        lr = cfg.learning_rate if cfg is not None else 0.5
        return make_linear(spec, learning_rate=lr, **kw)
    if name == "mlp":
        hidden = tuple(cfg.mlp_hidden) if cfg is not None else (128, 64)
        lr = cfg.mlp_learning_rate if cfg is not None else 0.05
        return make_mlp(spec, hidden=hidden, learning_rate=lr, **kw)
    if name == "forest":
        trees = cfg.forest_trees if cfg is not None else 32
        depth = cfg.forest_depth if cfg is not None else 3
        return make_forest(spec, trees=trees, depth=depth)
    if name == "rf":
        from .rf import make_rf

        # LRU must hold at least one live forest per partition (each lane's
        # snapshot interleaves through the shared host cache under
        # vmap_method='sequential'), with headroom for the rotate transition.
        parts = cfg.partitions if cfg is not None else 16
        return make_rf(
            spec,
            batch_size=cfg.per_batch if cfg is not None else 100,
            n_estimators=cfg.rf_estimators if cfg is not None else 100,
            n_jobs=cfg.cores if cfg is not None else 0,
            cache_size=max(64, 2 * parts),
        )
    raise ValueError(
        f"unknown model {name!r}; expected "
        "majority|centroid|gnb|linear|mlp|forest|rf"
    )
