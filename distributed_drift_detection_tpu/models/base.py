"""Model interface for the drift-detection loop.

The reference's classifier contract (C4/C5, ``DDM_Process.py:96-128``) is:
fit a *fresh* model on microbatch *a*, predict labels on microbatch *b*, emit
per-row error indicators. Its ``RandomForestClassifier`` is hostile to TPUs
(dynamic trees, host threads), so models here are **pure parameter pytrees**
with jit-able ``fit``/``predict`` — "retrain on drift" becomes a
``jnp.where``-select of freshly fitted params inside the compiled loop, with
zero recompilation and static shapes.

A model is a :class:`Model` record of three pure functions:

  * ``init(key) -> params`` — params with final shapes (for the scan carry).
  * ``fit(key, X, y, w) -> params`` — fresh fit on one microbatch;
    ``w`` is a {0,1} row-validity weight (padding rows contribute nothing).
  * ``predict(params, X) -> preds`` — int32 class predictions.

All shapes are static: ``X [B, F]``, ``y [B]``, ``w [B]``; the class count is
baked in at construction (inferred from the dataset — SURVEY.md quirk #5).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax


class Model(NamedTuple):
    name: str
    init: Callable[[jax.Array], Any]
    fit: Callable[[jax.Array, jax.Array, jax.Array, jax.Array], Any]
    predict: Callable[[Any, jax.Array], jax.Array]
    # True for models whose predict crosses to the host (jax.pure_callback,
    # e.g. models/rf.py). Such models must not run inside a device-sharded
    # program: the per-device callbacks serialize on the host while other
    # mesh participants block at the drift-vote all-reduce, aborting the
    # process. Engines reject mesh + host_callback combinations.
    host_callback: bool = False
    # True for memorizer families that ship with the saturated-error retrain
    # guard by default (config.GUARDED_MODELS — the RETRAIN_AUTO resolution;
    # see config.resolve_retrain_threshold for the failure mode and why
    # ``majority``, also a memorizer, deliberately stays False).
    saturation_guard: bool = False


def require_shardable(model: Model, mesh) -> None:
    """Reject host-callback models combined with a device mesh (see above)."""
    if mesh is not None and model.host_callback:
        raise ValueError(
            f"model {model.name!r} uses a host callback and cannot run in a "
            "device-sharded program (host callbacks deadlock the collective "
            "rendezvous); drop the mesh or pick an on-device model"
        )


class ModelSpec(NamedTuple):
    """Static problem geometry every model is built against."""

    num_features: int
    num_classes: int
