"""Host-callback RandomForest — the reference-parity model.

The reference's classifier is ``sklearn.RandomForestClassifier(n_jobs=CORES)``
fitted fresh on every retrain (``DDM_Process.py:96-105``) and used for batch
prediction (``:108-128``). Random forests are hostile to TPUs (dynamic tree
topology, host threads), so the framework's flagship models are the pure
pytree classifiers in ``classifiers.py`` — but SURVEY.md §7 layer 2 keeps an
*optional host-callback RF path for parity experiments*: runs whose detection
behaviour should be compared against the reference's actual model family.

Design (TPU-native shape, host-native compute):

* ``fit`` stays pure and on device — it just snapshots the training microbatch
  ``(X, y, w)`` plus a key-derived seed into the params pytree (static shapes,
  scan-carry friendly). No host round-trip on the fit path.
* ``predict`` is a :func:`jax.pure_callback` that ships ``(train snapshot,
  query rows)`` to the host, fits-or-reuses a forest there, and returns int32
  predictions. A content-addressed LRU cache keyed by the training snapshot
  bytes makes the "model frozen between drifts" pattern cheap: the loop calls
  predict once per microbatch with the *same* training batch until the next
  drift, so the forest is actually fitted once per concept — the same
  train-on-demand economics as the reference's ``retrain`` flag
  (``DDM_Process.py:179,194-196``).
* ``vmap_method='sequential'`` makes the callback correct under the engine's
  vmap-over-partitions (each partition's forest is independent, matching one
  sklearn model per Spark group).

This path is for fidelity, not speed: every microbatch crosses the
host↔device link. Use it at reference scale (``mult_data`` ≤ a few, CPU or
single chip) to validate that the pytree flagships detect the same drifts.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from .base import Model, ModelSpec


class RFParams:
    """Params pytree: the training-batch snapshot (see module docstring)."""

    # Plain tuple-ish pytree via registration below keeps leaves static-shaped.

    def __init__(self, X, y, w, seed):
        self.X = X
        self.y = y
        self.w = w
        self.seed = seed

    def tree_flatten(self):
        return (self.X, self.y, self.w, self.seed), None

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    RFParams, RFParams.tree_flatten, RFParams.tree_unflatten
)


class _ForestCache:
    """Content-addressed LRU of fitted forests (host side)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._store: OrderedDict[bytes, object] = OrderedDict()

    def get_or_fit(self, X, y, w, seed, n_estimators, n_jobs):
        key = (
            X.tobytes()
            + y.tobytes()
            + w.tobytes()
            + np.int64(seed).tobytes()
            + np.int64(n_estimators).tobytes()
        )
        if key in self._store:
            self._store.move_to_end(key)
            return self._store[key]
        from sklearn.ensemble import RandomForestClassifier

        mask = w > 0
        forest = RandomForestClassifier(
            n_estimators=n_estimators,
            n_jobs=n_jobs or None,
            random_state=int(seed) & 0x7FFFFFFF,
        )
        if mask.any():
            forest.fit(X[mask], y[mask])
        else:
            forest = None  # nothing to fit on; predict falls back to 0
        self._store[key] = forest
        if len(self._store) > self.capacity:
            self._store.popitem(last=False)
        return forest


def make_rf(
    spec: ModelSpec,
    batch_size: int,
    *,
    n_estimators: int = 100,
    n_jobs: int = 0,
    cache_size: int = 64,
) -> Model:
    """Reference-parity RandomForest as a host-callback :class:`Model`.

    ``batch_size`` fixes the training-snapshot shape (the engine's
    ``where``-select between init and fitted params needs identical leaf
    shapes, so the snapshot is sized to the microbatch up front).
    ``n_estimators=100`` is sklearn's default, which the reference uses
    (``DDM_Process.py:102`` passes only ``n_jobs=CORES``); ``n_jobs`` mirrors
    that knob (0 → sklearn default).
    """
    f, b = spec.num_features, int(batch_size)
    cache = _ForestCache(cache_size)

    def init(key):
        # All-zero-weight snapshot: the host fit skips it, predict falls back
        # to class 0 until the first real fit lands (the engine always fits
        # on batch 0 before the first prediction).
        return RFParams(
            jnp.zeros((b, f), jnp.float32),
            jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.float32),
            jnp.int32(0),
        )

    def fit(key, X, y, w):
        seed = jax.random.randint(key, (), 0, jnp.int32(2**31 - 1))
        return RFParams(X, y, w, seed)

    def host_predict(train_X, train_y, train_w, seed, X):
        forest = cache.get_or_fit(
            np.asarray(train_X),
            np.asarray(train_y),
            np.asarray(train_w),
            int(seed),
            n_estimators,
            n_jobs,
        )
        if forest is None or X.shape[0] == 0:
            return np.zeros(X.shape[0], np.int32)
        return forest.predict(np.asarray(X)).astype(np.int32)

    def predict(params, X):
        out_shape = jax.ShapeDtypeStruct((X.shape[0],), jnp.int32)
        return jax.pure_callback(
            host_predict,
            out_shape,
            params.X,
            params.y,
            params.w,
            params.seed,
            X,
            vmap_method="sequential",
        )

    return Model("rf", init, fit, predict, host_callback=True)
