"""Configuration for the TPU-native distributed drift-detection framework.

One dataclass replaces the reference's module-level constant block
(``DDM_Process.py:1-35``) and its commented-out argv mode (``DDM_Process.py:15-21``).
Knob names are kept recognisable to a user of the reference:

=====================  =============================================
reference knob          here
=====================  =============================================
INSTANCES               ``partitions`` (stream partitions; data-parallel axis)
PER_BATCH               ``per_batch``
MIN_NUM_DDM_VALS        ``min_num_instances``
WARNING_LEVEL           ``warning_level``
CHANGE_LEVEL            ``out_control_level``
MULT_DATA               ``mult_data``
FILENAME                ``dataset``
URL / MEMORY / CORES    ``backend`` (+ backend-specific options); the Spark
                        cluster knobs have no TPU meaning and are recorded
                        verbatim into the results CSV for table parity —
                        except ``cores``, which additionally drives the
                        ``model='rf'`` sklearn ``n_jobs`` (mirroring the
                        reference's ``RandomForestClassifier(n_jobs=CORES)``,
                        ``DDM_Process.py:102``).
=====================  =============================================

Deliberate deviations (SURVEY.md quirk register):
  * ``NUMBER_OF_FEATURES`` (``DDM_Process.py:33``) is inferred from the data.
  * dead ``REGRESSION_THRESH`` (``DDM_Process.py:31``) is dropped.
  * all randomness is keyed off ``seed`` (the reference's shuffles are unseeded,
    ``DDM_Process.py:49,187,190``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple


class DDMParams(NamedTuple):
    """DDM detector hyper-parameters (Gama et al. 2004).

    The reference constructs ``skmultiflow.drift_detection.DDM`` with the
    far-more-sensitive-than-default values ``3 / 0.5 / 1.5``
    (``DDM_Process.py:27-29,139``); those exact values are required to
    reproduce its detection-delay behaviour, so they are the defaults here.
    """

    min_num_instances: int = 3
    warning_level: float = 0.5
    out_control_level: float = 1.5
    # Band-width noise floor Δ (0 = off, classic DDM — the reference-exact
    # default): the minimum running-error-rate excursion treated as change.
    # DDM's change band is ``level · s_min``; a model with any stretch of
    # error-free elements captures ``p_min = s_min = 0``, after which the
    # band is zero-width and a SINGLE residual error fires a change — the
    # measured r04 'linear' over-firing loop (~15×, PARITY.md), which no
    # level setting can fix (any level × 0 is still 0). With a floor, the
    # change band is ``max(out_control_level·s_min, Δ)`` and the warning
    # band scales as ``(warning_level/out_control_level)·Δ``, preserving
    # the reference's band geometry (implemented as a floored band *std*:
    # ``max(s_min, Δ/out_control_level)``, ops/ddm._band_s). Minima
    # tracking is untouched — Δ=0 reproduces classic DDM bit-for-bit. See
    # DDM_ROBUST below for the shipped preset.
    noise_floor: float = 0.0


# The reference cranks DDM's sensitivity to 3/0.5/1.5 (the DDMParams
# defaults above — required for its detection-delay parity). That choice is
# tuned for near-zero in-concept error: a family with a small *residual*
# error rate (linear's ≈1% softmax residue on rialto-like regimes) arms the
# zero-minima trap (see ``noise_floor``) and over-fires ~15× (r04
# PARITY.md) — and the detector's own published 30/2.0/3.0 levels cannot
# fix it, because any level × a zero-width band is still zero (measured:
# 30/2/3 alone leaves 389 spurious fires on the stand-in). DDM_ROBUST
# keeps the reference's levels and adds the excursion floor. Δ = 0.1 is
# the measured r05 sweep optimum (Δ ∈ {0.075, 0.1, 0.15, 0.2} on the
# stand-in, 2 seeds): recall 1.000, spurious rate 0.13 (vs rf's 0.51),
# mean delay 31 global batches (vs rf's 50) — linear passes both parity
# axes with margin; Δ ≥ 0.15 only trades detection delay for little
# further spurious reduction. Committed evidence:
# results/delay_parity.csv 'linear@robust' rows. Usage:
# ``RunConfig(model='linear', ddm=DDM_ROBUST)``.
DDM_ROBUST = DDMParams(noise_floor=0.1)


class PHParams(NamedTuple):
    """Page–Hinkley hyper-parameters (detector='ph', ops/detectors.py).

    ``delta`` is the magnitude tolerance, ``threshold`` (λ) the detection
    bar, ``alpha`` the forgetting factor on the cumulative statistic
    (1.0 = classic unforgetting CUSUM), ``warning_fraction`` the
    reported-only warning bar as a fraction of λ.

    λ is a *cumulative* excess-error budget: the detector needs roughly λ
    error elements beyond the running mean before firing, so it must be
    small relative to the per-partition concept length (λ=50 on 100-element
    concepts detects late or never — the same sensitivity story as the
    reference cranking DDM's defaults 30/2/3 down to 3/0.5/1.5,
    ``DDM_Process.py:27-29``).

    ``threshold = 0`` (the default) means **auto**: ``api.prepare`` resolves
    λ from the stream's planted-drift geometry via
    :func:`auto_ph_threshold` — the same pattern as ``window = 0`` →
    :func:`auto_window` — so ``RunConfig(detector='ph')`` detects out of the
    box at any benchmark geometry. Pass an explicit λ (e.g. the classic 50)
    to pin it; detector kernels refuse an unresolved 0.
    """

    min_num_instances: int = 30
    delta: float = 0.005
    threshold: float = 0.0  # 0 = auto (config.auto_ph_threshold)
    alpha: float = 1.0
    warning_fraction: float = 0.5


class EDDMParams(NamedTuple):
    """EDDM hyper-parameters (detector='eddm', ops/detectors.py;
    Baena-García et al. 2006 defaults).

    ``paper_exact`` selects the distance semantics for the first error after
    init/reset: ``False`` (default) keeps the framework's documented
    deviation — one uniform ``d = t − last_err_t`` recurrence whose first
    post-reset error contributes a synthetic distance measured from the
    reset; ``True`` is Baena-García 2006 exactly — the first error merely
    arms the distance origin and ``min_num_errors`` counts *distances*.
    The deviation is quality-neutral but not flag-neutral (measured numbers
    in PARITY.md "EDDM deviation"), so paper-comparable runs should set
    ``True``; the default preserves the framework's historical flags."""

    min_num_errors: int = 30
    warning_alpha: float = 0.95
    change_beta: float = 0.9
    paper_exact: bool = False


class HDDMParams(NamedTuple):
    """HDDM-A hyper-parameters (detector='hddm', ops/detectors.py;
    Frías-Blanco et al. 2015 "A-test" defaults).

    Both knobs are *confidences* for Hoeffding bounds — scale-free, so
    unlike Page–Hinkley's λ they need no per-stream auto-resolution: the
    bound tightens with sample count automatically. ``drift_confidence``
    gates detection, ``warning_confidence`` the reported-only warning zone
    (larger = more sensitive)."""

    drift_confidence: float = 0.001
    warning_confidence: float = 0.005


class HDDMWParams(NamedTuple):
    """HDDM-W hyper-parameters (detector='hddm_w', ops/detectors.py;
    Frías-Blanco et al. 2015 "W-test" defaults).

    The W-test is the EWMA companion of the A-test (:class:`HDDMParams`):
    ``lam`` is the exponential forgetting weight of the moving averages
    (larger = faster-forgetting, more reactive to abrupt drift, noisier);
    the confidences gate the McDiarmid-style bounds on *weighted* means the
    same way the A-test's gate its Hoeffding bounds — scale-free, so no
    per-stream auto-resolution is needed here either."""

    lam: float = 0.05
    drift_confidence: float = 0.001
    warning_confidence: float = 0.005


class ADWINParams(NamedTuple):
    """ADWIN hyper-parameters (detector='adwin', ops/adwin.py; Bifet &
    Gavaldà 2007 "ADaptive WINdowing").

    ``delta`` is the detection confidence of the adaptive-window cut test
    (smaller = fewer false alarms, longer delay). ``clock`` is both the
    check cadence *and* the bucket granularity (ops/adwin.py "TPU
    restructuring"): cuts are tested — and can only land — every
    ``clock``-th absorbed element (the classic implementations' default
    check cadence of 32), and a level-k histogram bucket spans
    ``clock·2^k`` elements. ``max_buckets`` (the paper's M) bounds the
    per-level bucket count and ``max_levels`` the depth — capacity is
    ``M·clock·(2^max_levels − 1)`` elements (~168 M at the defaults),
    beyond which the oldest bucket is forgotten (bounded-memory sliding
    window); the capacity must fit int32 (validated), and the absorb
    counter shares that 2³¹ ceiling per reset-free stream — the engines
    reset on every change, and the >2³¹ soak machinery runs chained legs,
    so neither limit binds in practice. ``min_window`` / ``min_side``
    gate the test on minimum evidence (whole window / either side of a
    split). All knobs are scale-free — no per-stream auto-resolution is
    needed."""

    delta: float = 0.002
    clock: int = 32
    max_buckets: int = 5
    max_levels: int = 20
    min_window: int = 10
    min_side: int = 5


class KSWINParams(NamedTuple):
    """KSWIN hyper-parameters (detector='kswin', ops/detectors.py; Raab,
    Heusinger & Schleif 2020 defaults).

    A sliding window of the last ``window_size`` error indicators is split
    into its newest ``stat_size`` elements and the remainder; change fires
    when the two-sample Kolmogorov–Smirnov test rejects at significance
    ``alpha``. On Bernoulli inputs the KS statistic degenerates to the
    proportion gap ``|p̂_recent − p̂_old|`` (the module docstring derives
    this), so the whole test is a rolling-mean comparison against the
    closed-form KS critical value — no empirical CDFs needed. Three
    documented deviations from the reference implementation: the "old"
    sample is the *entire* older window rather than a ``stat_size``-sized
    uniform subsample (the subsample exists to cheapen a host KS test;
    here the full comparison is free and strictly lower-variance); the
    decision uses the asymptotic critical-value form of the test rather
    than the exact p-value; and on detection the window is *emptied* (the
    framework's uniform caller-reset contract — the engines discard
    detector state and retrain) rather than retaining the newest
    ``stat_size`` elements, so re-arming after a change takes a full
    ``window_size`` warm-up instead of ``window_size − stat_size``."""

    alpha: float = 0.005
    window_size: int = 100
    stat_size: int = 30


class STEPDParams(NamedTuple):
    """STEPD hyper-parameters (detector='stepd', ops/detectors.py; Nishida
    & Yamauchi 2007 defaults).

    *Statistical Test of Equal Proportions*: the error rate of the most
    recent ``window_size`` elements against the overall rate since the
    last reset, via the two-proportion z-test with pooled variance and
    continuity correction. Change fires when the test rejects at
    ``alpha_drift`` with the recent rate *higher* (the direction the
    engines' rotate-on-drift loop consumes); ``alpha_warning`` gates the
    reported-only warning zone the same way (the paper's two-level
    scheme — like DDM's, and unlike ADWIN/KSWIN, STEPD has a real warning
    level). Tested once at least ``2·window_size`` elements have been
    absorbed."""

    alpha_drift: float = 0.003
    alpha_warning: float = 0.05
    window_size: int = 30


# Valid RunConfig.detector values (kernels in ops/detectors.py +
# ops/adwin.py). Lives here, not in ops/, so jax-free consumers (the grid
# harness CLI) can validate without initialising a backend.
DETECTOR_NAMES = (
    "ddm", "ph", "eddm", "hddm", "hddm_w", "adwin", "kswin", "stepd",
)

# Valid RunConfig.data_policy values (io/sanitize.py POLICIES — mirrored
# here, like DETECTOR_NAMES, so jax-free consumers (grid/heal/doctor CLIs)
# validate without importing the io package, which pulls in jax).
DATA_POLICIES = ("strict", "quarantine", "repair")

# Valid RunConfig.collect values (parallel/mesh.py collect epilogue):
# 'compact' ships the device-compacted detection table, 'full' the packed
# [5,P,NB-1] flag plane. Flags are bit-identical either way (tested).
COLLECT_MODES = ("compact", "full")


class ServeParams(NamedTuple):
    """Deployment knobs of the online serving daemon (``serve`` subsystem).

    Everything *model/detector/stream-shaped* stays on :class:`RunConfig`
    (the serve loop runs the same engines); this tuple holds only what a
    long-lived service adds on top. jax-free, like the rest of this module,
    so the ``serve``/``loadgen`` CLIs can validate argv without a backend.

    ``num_features``/``num_classes`` are **required** (> 0): a daemon must
    know its row geometry before the first row arrives — chunk shapes are
    static (the no-recompile contract), and the model spec is built from
    them, not inferred from data the way the batch loader does.
    """

    num_features: int = 0  # required: feature count of every ingress row
    num_classes: int = 0  # required: label domain 0..C-1
    host: str = "127.0.0.1"
    # TCP ingress port (0 = OS-assigned, printed in the startup banner);
    # None = no socket at all — the in-process embedding used by tests and
    # bench --serve drives the admission controller directly.
    port: "int | None" = 0
    # Microbatch geometry: one flushed chunk is [partitions, chunk_batches,
    # per_batch] rows (partitions/per_batch from the RunConfig).
    chunk_batches: int = 4
    # Max-linger deadline: a partial microbatch older than this is flushed
    # short (padded through the validity plane — static shapes, no
    # recompile) rather than waiting for the grid to fill.
    linger_s: float = 0.25
    # Serving-loop poll granularity (batcher waits, stop checks).
    poll_s: float = 0.05
    # Wire-protocol-v2 decoder bound: a binary frame header declaring
    # more rows than this is malformed, not merely large — the ingress
    # refuses it (ERR + connection close) BEFORE allocating its payload
    # buffer, so a corrupt or hostile header cannot OOM the daemon.
    # 0 = the codec's own default (serve.wire.MAX_FRAME_ROWS — the one
    # copy of the constant; this jax-free module must not import it).
    # The v1 text protocol has no equivalent knob (lines are admitted
    # per recv block).
    max_frame_rows: int = 0
    # Checkpoint path ('' = stateless serving): the detector carry +
    # stream-position meta, written atomically after every
    # ``checkpoint_every``-th published microbatch and at drain — the
    # kill-and-resume contract.
    checkpoint: str = ""
    checkpoint_every: int = 1
    # Fleet identity of this daemon (serve --name): stamped into every
    # verdict record as "daemon" so a router-fronted fleet's sidecars
    # stay attributable per backend — the join key loadgen --router uses
    # against the router's placement journal. '' (default) = unstamped
    # (solo daemons need no identity).
    name: str = ""
    # Global tenant identity per slot (serve --tenant-ids): a fleet
    # daemon's T slots serve T *global* tenants — slot s runs global
    # tenant ``tenant_ids[s]``'s solo identity (stream seed + stripe
    # shuffle seed = config.tenant_configs' ``seed + id`` convention), so
    # its flags stay bit-identical to THAT tenant's solo run wherever
    # the router places it. ``-1`` marks a vacant spare slot (masked,
    # receives no traffic) the router can LOADTENANT a migrating or
    # orphaned tenant into — slot counts are compiled into the kernel
    # (static shapes), so failover capacity is provisioned, not grown.
    # Empty (default) = the identity mapping 0..T-1 (the PR-9 solo-host
    # posture; nothing changes for existing daemons).
    tenant_ids: tuple = ()
    # Per-tenant solo-shaped checkpoints (serve --tenant-checkpoints):
    # next to every plane checkpoint, also write one
    # ``<checkpoint>.t<slot>`` per tenant slot via
    # ChunkedDetector.save_tenant — the migration currency of the tenant
    # router: when THIS daemon dies, the router re-places each orphaned
    # tenant by LOADTENANT-ing its solo checkpoint into a surviving
    # daemon and replaying the delta. Off by default (T extra files per
    # checkpoint is a fleet posture, not a solo one).
    tenant_checkpoints: bool = False
    # Idle liveness: emit a heartbeat event at least this often even with
    # no traffic, so `watch --stall-after` can tell "idle" from "dead".
    heartbeat_s: float = 10.0
    # --- ops plane (telemetry.ops / .slo / .trace) ---
    # HTTP ops port (None = no ops server; 0 = OS-assigned, see banner):
    # /metrics (live Prometheus text), /healthz (200 healthy / 503 while
    # an SLO alert fires or the ingress poisoned the batcher), /statusz
    # (JSON snapshot). Binds to `host`, like the ingress.
    ops_port: "int | None" = None
    # Declarative SLO rules, `kind=threshold` each (telemetry.slo
    # RULE_KINDS: p99_ms, verdict_age_s, quarantine_pct, stall_s) or a
    # multi-window `burn_rate=SERIES:OBJECTIVE:FAST/SLOW:FACTOR` pair
    # over any snapshot series; ("none",) disables alerting. The default
    # ships a stall alarm so an out-of-the-box daemon can tell "wedged"
    # from "idle".
    slo: tuple = ("stall_s=60",)
    # Per-tenant hotness series (serve --tenant-series): export
    # serve_tenant_rows_total{tenant=<global id>} on /metrics so the
    # history plane can rank tenant activity (`history top-tenants`).
    # Off by default — per-tenant label values are a cardinality cost
    # every scrape pays forever, so hotness is an opt-in fleet posture.
    tenant_series: bool = False
    # Cardinality guard for the above: a daemon with more tenant slots
    # than this refuses --tenant-series at startup instead of silently
    # flooding every scrape (raise it explicitly if you mean it).
    tenant_series_max: int = 512
    # Evaluator cadence (its own daemon thread — the serve loop being
    # wedged is exactly what stall_s must catch).
    slo_interval_s: float = 1.0
    # Crash flight recorder: ring capacity in events. On an unhandled
    # exception the last N run-log events dump to
    # `<run-log>.flightrec.jsonl`; a clean drain leaves no dump. 0 = off.
    flightrec_events: int = 256
    # Serve-pipeline observatory (telemetry.pipeline): per-stage busy
    # accounting (serve_stage_busy_seconds_total), the /statusz
    # `pipeline` section, and per-chunk stage spans. Stamps are cheap
    # monotonic reads folded in outside the hot dispatch; False turns
    # the accounting off entirely (the CLI's --no-pipeline-metrics) —
    # verdict sidecars are bit-identical either way.
    pipeline_metrics: bool = True
    # --- trace plane (telemetry.tracing / .forensics) ---
    # Daemon-side head-sampling rate for rows the client did NOT stamp
    # with a TRACE wire line: each sampled row gets a fresh root trace
    # and the full serving span chain in the run log. 0 (default) = off:
    # zero hot-path tracing work — client-stamped rows are still always
    # honored (the client already paid the head decision).
    trace_sample: float = 0.0
    # Drift forensics: on a drift verdict, extract an evidence bundle
    # (error-rate trajectory, warn/drift thresholds, window stats,
    # context rows, sampled trace ids) host-side into
    # `<run-log>.forensics/` and emit a `drift_forensics` event.
    # Requires a telemetry dir (bundles anchor to the run log's stem);
    # False disables capture entirely.
    forensics: bool = True
    # --- adaptation plane (adapt/ subsystem) ---
    # Per-tenant drift-reaction policy specs (adapt.policy grammar; the
    # CLI's repeatable --on-drift). Each spec is `POLICY[,k=v...]`
    # plane-wide or `T=POLICY[,k=v...]` per tenant, POLICY one of
    # alert_only|retrain|shadow. Empty (the default) = alert_only for
    # every tenant: verdicts only publish — today's behaviour,
    # byte-identical (no adaptation code runs at all).
    on_drift: tuple = ()
    # --- incident autopsy plane (telemetry.incident) ---
    # Alert-triggered cross-plane evidence capture: when an SLO alert
    # fires (or the daemon crashes), snapshot the flight ring, pipeline
    # attribution, /statusz, verdict/quarantine tails, and (with a
    # history store) the recent fleet window into one numbered
    # `<run-log>.incidents/incident-NNNN/` bundle — captured on the SLO
    # evaluator thread, never the serve loop; verdict sidecars are
    # bit-identical either way. Requires a telemetry dir (bundles
    # anchor to the run-log stem); False (--no-incidents) disables.
    incidents: bool = True
    # Bundle cap per run: alert flapping must not fill the disk —
    # captures beyond this are counted (`skipped`), not written.
    incident_max: int = 32
    # History-store directory (the collector's --store): when set, each
    # bundle also extracts the recent time-series window + top-tenant
    # ranking. '' = no history extract (a solo daemon has no store).
    incident_store: str = ""
    # Seconds of history extracted into each bundle.
    incident_window_s: float = 120.0


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Full configuration of one drift-detection run."""

    # --- data (reference C2, DDM_Process.py:38-55) ---
    dataset: str = "outdoorStream.csv"
    mult_data: float = 1.0
    standardize: bool = True
    # Ingest contract policy for CSV datasets (io/sanitize.py): 'strict'
    # (default) raises a structured StreamContractError naming
    # file/row/column on any violation — non-numeric cell, non-finite
    # value, ragged row, bad label domain — instead of the reference's
    # crash-or-poison behaviour; 'quarantine' drops violating rows into a
    # quarantine.jsonl sidecar and carries them as masked positions
    # (inside jit they read as padding — static shapes, and the detector
    # statistics are exactly the clean stream's with those rows masked);
    # 'repair' imputes finite column means for NaN cells and clamps
    # non-integral labels, quarantining what it cannot fix. Clean streams
    # are bit-identical under every policy. Synthetic datasets ('synth:')
    # generate by construction and skip the scan.
    data_policy: str = "strict"
    # Quarantine sidecar path ('' = auto: telemetered runs write a
    # per-run `<run-log>.quarantine.jsonl` next to the run log so
    # repeated trials stay attributable; without telemetry it is
    # ./quarantine.jsonl — resolve_quarantine_path). Appended to, one
    # JSON line per quarantined row; written only when a row is actually
    # quarantined (quarantine AND repair policies — repair drops what it
    # cannot fix).
    quarantine_path: str = ""

    # --- loop (reference C7, DDM_Process.py:162-213) ---
    per_batch: int = 100
    shuffle_batches: bool = True  # seeded analog of .sample(frac=1) at :187,190
    # 'majority' | 'centroid' | 'gnb' | 'linear' | 'mlp' | 'forest' | 'rf'
    # ('forest' is the on-device extremely-randomized oblique forest; 'rf'
    # is the host-callback reference-parity RandomForest, models/rf.py;
    # like 'mlp' their fits consume a PRNG key, so their flags are
    # seed-equivalent but not bit-equal across different `window` values). 'centroid' is the
    # documented flagship (PARITY.md: closed-form fit, rf-grade delay) and
    # what bench.py measures; 'linear' over-fires ~15× on rialto-like
    # regimes, so it is deliberately not the default.
    model: str = "centroid"

    # --- detector (reference C6) ---
    # 'ddm' (the reference's statistic) | 'ph' (Page–Hinkley) | 'eddm' |
    # 'hddm' (HDDM-A, Hoeffding-bound) | 'hddm_w' (HDDM-W, its EWMA
    # companion) | 'adwin' (adaptive windowing; the zoo's only
    # scan-based kernel — see ops/adwin.py) | 'kswin' (sliding-window
    # KS test) | 'stepd' (two-proportion test, recent vs overall) — the
    # detector zoo, ops/detectors.py. Non-DDM detectors are a framework
    # extension: the reference only ships DDM, so cross-reference parity
    # claims (delay tables, oracle goldens) hold for detector='ddm'.
    detector: str = "ddm"
    ddm: DDMParams = DDMParams()
    ph: PHParams = PHParams()
    eddm: EDDMParams = EDDMParams()
    hddm: HDDMParams = HDDMParams()
    hddm_w: HDDMWParams = HDDMWParams()
    adwin: ADWINParams = ADWINParams()
    kswin: KSWINParams = KSWINParams()
    stepd: STEPDParams = STEPDParams()
    # Fallback retrain: force rotate+reset+retrain (without recording a DDM
    # change) when a batch's error rate exceeds this threshold. Cures DDM's
    # structural blindspot — a detector reset immediately before a ~100%-error
    # regime pins p_min at 1.0 and never fires again. The reference ships the
    # same idea as the *dead* constant REGRESSION_THRESH = 0.3
    # (DDM_Process.py:31, never referenced).
    #
    # Default RETRAIN_AUTO (VERDICT r4 #1 saturation guardrail): resolved by
    # :func:`resolve_retrain_threshold` to AUTO_RETRAIN_THRESHOLD for the
    # model families that *need* it (``GUARDED_MODELS`` — the memorizer
    # families whose measured failure mode is exactly the blindspot above)
    # and to None (reference-exact behaviour) for every other family. Pass
    # None to disable explicitly, or a float to pin.
    retrain_error_threshold: float | None = -1.0  # RETRAIN_AUTO sentinel

    # --- distribution (reference C8, DDM_Process.py:216-226) ---
    partitions: int = 8  # reference INSTANCES: row-striped stream partitions
    mesh_devices: int = 0  # 0 = all visible devices
    # Tenant-axis rows of a 2-D (tenant, partition) device mesh (ROADMAP
    # item 1, fleet-scale serving): > 1 reshapes the mesh to
    # [mesh_tenant_devices, rest] named (tenants, partitions), so the
    # stacked [T·P, ...] tenant plane shards whole tenants across
    # tenant-axis rows while each tenant's partitions spread along the
    # partition axis (parallel.mesh.make_mesh(tenant_devices=...),
    # per-leaf placement via the plane_rules regex→PartitionSpec tree).
    # Per-tenant flags are bit-identical at EVERY mesh shape — the PR-9
    # parity contract quantified over shardings (tested). Constraints,
    # checked loudly: the tenant count and the device count must both
    # split over the tenant axis. 0 (default) keeps the historical 1-D
    # partition mesh everywhere.
    mesh_tenant_devices: int = 0
    # Multi-tenant stream plane (api.prepare_multi / api.run_multi): run N
    # INDEPENDENT streams — each with its own detector + classifier state —
    # through ONE compiled kernel by stacking their [P, NB, B] grids on the
    # leading axis into [T·P, NB_max, B]. Tenant t's stream is the solo
    # config with seed = seed + t and any '{tenant}' placeholder in
    # `dataset` substituted (config.tenant_configs); ragged tenant lengths
    # are absorbed by the validity plane (masked rows == padding inside
    # jit — static shapes, zero recompiles), and per-tenant flags are
    # bit-identical to N solo runs. 1 (default) = the classic single-stream
    # path, byte-for-byte unchanged. `api.run` rejects tenants > 1 — the
    # multi-tenant result is per-tenant structured (use run_multi).
    tenants: int = 1

    # --- execution strategy ---
    # Speculative window width (engine.window): microbatches processed per
    # sequential step between drift checks. 1 = faithful batch-per-step scan;
    # >1 commits up to the first in-window change and replays the rest —
    # identical flags for deterministic-fit models (majority/centroid/gnb/linear),
    # ~window× fewer sequential steps. 0 (the default) = auto: co-resolve the
    # width with ``window_rotations`` from the stream's planted drift spacing
    # (config.auto_window; at the headline benchmark geometry the resolution
    # is the measured r03 W×R sweep optimum, 128×4 — see bench.py's sweep
    # table). Pass an explicit width to pin it. Caveat: the key-consuming
    # 'mlp' fit draws its init keys per *window*, not per batch, so its flags
    # are seed-equivalent but not bit-equal across different window values —
    # pin window=1 for run-to-run bit-reproducibility of 'mlp' experiments.
    window: int = 0
    # Speculation depth of the window engine (engine.window): how many
    # rotate-and-replay passes one sequential step may commit. 1 = classic
    # single-rotation speculation; R > 1 replays up to R−1 times inside the
    # same step — after an in-window change the model refits and the tail
    # re-runs immediately — cutting sequential steps from ≈ NB/W + drifts
    # toward ≈ NB/W + drifts/R. Flags are bit-identical to the sequential
    # engine for deterministic-fit models at any depth (tested); like
    # `window`, the depth is part of 'mlp'/'rf''s seed story. Each level
    # costs one extra predict + detector pass of device work per step —
    # pure win in the dispatch-latency-bound regimes the window engine
    # exists for, wasted FLOPs where drift is absent (keep 1 there).
    # 0 (the default) = auto: resolve the depth from stream geometry (the
    # concepts one window spans; config.auto_rotations — co-tuned with
    # auto_window so the defaults land on the measured W×R optimum).
    window_rotations: int = 0
    # Collect-phase transport (parallel/mesh.py): 'compact' (default) fuses
    # a segment-compaction epilogue into the detect program — the device
    # returns a small dense detection table (partition, batch, flag values;
    # fixed capacity, sentinel fill, embedded event counter) and the host
    # reconstructs the full flag table from it, so the latency-bound d2h
    # collect ships O(detections) bytes instead of the whole packed
    # [5, P, NB-1] plane. 'full' keeps the round-5 full-plane path — the
    # escape hatch for parity A/Bs; ``validate=True`` forces it too (the
    # structural audit wants the plane the device actually produced, not a
    # reconstruction). Flags are bit-identical across modes (tested); a
    # table overflow (more flagged slots than capacity) falls back to the
    # full plane loudly (RuntimeWarning), never truncates.
    collect: str = "compact"
    # Compacted-table capacity in entries (0 = auto: sized from the stripe
    # geometry, parallel.mesh.auto_compact_capacity — ~P·NB/8 slots, the
    # point where the table is still ~6× smaller than the plane while
    # overflow needs >12.5% of all slots flagged). Explicit values exist
    # for overflow tests and for streams known to flag densely.
    collect_capacity: int = 0
    # Host-ingest parse fan-out of the streaming CSV path
    # (io.feeder.csv_chunks ``workers=``; the `chunked` CLI's
    # --ingest-workers and bench.py's flag of the same name). 0 = auto:
    # one parse worker per core, capped at 4
    # (io.feeder.resolve_ingest_workers). Blocks are parsed concurrently
    # but reassembled in file order, so ANY worker count yields
    # bit-identical chunks, flags and quarantine sidecars (pinned by test
    # + the ingest-smoke CI job) — an execution knob, not experiment
    # identity, so it stays out of the telemetry config digest like
    # collect/compile_cache_dir.
    ingest_workers: int = 0
    # Persistent XLA compilation cache directory ('' = off). When set,
    # compiled executables are cached across *processes* (jax
    # jax_compilation_cache_dir), so repeated sweep cells and restarted
    # soak legs skip compilation entirely; api.prepare additionally
    # AOT-compiles the runner against the stripe geometry
    # (jit.lower().compile()) so even a cold process pays the compile in
    # the prepare phase, never inside the Final Time span. CLI:
    # --compile-cache-dir; bench.py defaults to its own .jax_cache.
    compile_cache_dir: str = ""
    # (Two rejected-by-measurement alternatives are documented in PARITY.md:
    # a `ddm_kernel='pallas'` fused kernel — ~78× slower than the XLA
    # lowering, removed in round 2 ("Pallas post-mortem") — and a
    # `stream_on_device` in-jit stream synthesis — TPU large-array sorts
    # made it ~6× slower end-to-end than the packed host stripe
    # ("Device-synthesis post-mortem").)

    # --- model hyper-parameters (TPU-native replacements for RandomForest) ---
    fit_steps: int = 32
    learning_rate: float = 0.5
    mlp_hidden: tuple[int, ...] = (128, 64)
    mlp_learning_rate: float = 0.05
    # model='rf' (host-callback parity path, models/rf.py): forest size; the
    # reference uses sklearn's default 100 trees (DDM_Process.py:102).
    rf_estimators: int = 100
    # model='forest' (on-device extremely-randomized oblique forest,
    # models/classifiers.py make_forest): ensemble size and complete-tree
    # depth (2^depth leaves per tree).
    forest_trees: int = 32
    forest_depth: int = 3

    # --- execution ---
    backend: str = "jax"  # 'jax' ('spark' is formally retired — api.run)
    seed: int = 0
    # Host-side structural audit of the collected flag table after every run
    # (utils.validate.validate_flag_rows); raises on corruption. Cheap (runs
    # on the tiny flag table), off by default for exact reference parity of
    # the timed span.
    validate: bool = False
    # When set, wrap the detection phase in a jax.profiler trace written to
    # this directory (aux subsystem: tracing/profiling, SURVEY.md §5) —
    # inspect with TensorBoard or Perfetto.
    trace_dir: str = ""
    # When set, wrap the ENTIRE Final Time span (upload + detect + collect)
    # in a jax.profiler trace written to this directory, so the
    # TensorBoard/Perfetto-readable capture lands next to the run's
    # telemetry artifacts (CLI: --profile-dir). Profiling inevitably
    # perturbs what it measures — the capture rides *inside* the timed
    # span by design (that is the span being profiled); treat the run's
    # Final Time as diagnostic, not a headline. Mutually exclusive with
    # trace_dir (jax rejects nested profiler sessions; api.run fails
    # loudly before starting work).
    profile_dir: str = ""
    # When set, api.run persists a structured JSONL event log for the run
    # into this directory (one file per run; schema docs/OBSERVABILITY.md)
    # plus JSON/Prometheus metric exports, summarizable offline with
    # `python -m distributed_drift_detection_tpu report <run.jsonl>`.
    # None (default) = off: no telemetry code executes, and every event is
    # emitted outside the reference-parity Final Time span either way.
    telemetry_dir: str | None = None

    # --- bookkeeping (recorded verbatim into the results CSV, C11 parity) ---
    app_name: str = ""
    time_string: str = "Placeholder"
    url: str = "jax://local"
    memory: str = "-"
    cores: int = 0
    results_csv: str = "ddm_cluster_runs.csv"  # fixed: ref wrote sparse_* (:273)

    def resolved_app_name(self) -> str:
        # Reference: APP_NAME = "%s-%s" % (FILENAME, TIME_STRING)  (:23)
        return self.app_name or f"{self.dataset}-{self.time_string}"


def replace(cfg: RunConfig, **kw: Any) -> RunConfig:
    return dataclasses.replace(cfg, **kw)


def resolve_quarantine_path(cfg: RunConfig) -> str:
    """The quarantine sidecar path a config implies: an explicit
    ``quarantine_path`` wins; otherwise it lands next to the run's other
    artifacts (``<telemetry_dir>/quarantine.jsonl``) when telemetry is
    on, or in the working directory when not. jax-free (CLI-safe)."""
    if cfg.quarantine_path:
        return cfg.quarantine_path
    if cfg.telemetry_dir:
        import os

        return os.path.join(cfg.telemetry_dir, "quarantine.jsonl")
    return "quarantine.jsonl"


def telemetry_config_payload(cfg: RunConfig) -> dict:
    """The config dict ``api.run`` emits in ``run_started`` and digests
    into the registry (``telemetry.registry.config_digest``).

    Single source of truth shared with ``resilience.heal``: the heal
    planner recomputes every expected trial's digest from its spec, and
    a drifted field set would make completed trials read as missing (or
    worse, missing ones as completed). ``window``/``window_rotations``
    are the *requested* values (0 = auto, resolved later by prepare);
    bookkeeping fields (time_string, telemetry_dir, ...) stay out — two
    runs of the same experiment must share a digest.

    Values are type-normalized (``mult_data`` → float, counts → int):
    JSON renders ``1`` and ``1.0`` differently, so without this a sweep
    launched with integer mults and a heal planner normalizing to float
    would digest the *same cell* two ways and re-run completed work.
    """
    payload = {
        "dataset": str(cfg.dataset),
        "model": cfg.model,
        "detector": cfg.detector,
        "partitions": int(cfg.partitions),
        "per_batch": int(cfg.per_batch),
        "mult_data": float(cfg.mult_data),
        "seed": int(cfg.seed),
        "backend": cfg.backend,
        "window": int(cfg.window),
        "window_rotations": int(cfg.window_rotations),
    }
    # A non-default data policy is experiment identity: on a dirty stream
    # it changes which rows reach the detector, hence the flags. The
    # default stays OUT of the payload — same rule as the grid's
    # _config_key `-dp` segment — so registries recorded before the
    # policy existed keep matching their cells (heal must not re-run a
    # whole completed sweep over a digest-schema change).
    if cfg.data_policy != "strict":
        payload["data_policy"] = str(cfg.data_policy)
    # Same default-stays-out rule for the tenant count: a T-tenant run is a
    # different experiment from a solo run, but pre-tenancy registries must
    # keep matching their solo cells.
    if cfg.tenants != 1:
        payload["tenants"] = int(cfg.tenants)
    return payload


# The payload fields config_from_payload accepts, with their normalizing
# types — exactly the fields telemetry_config_payload can emit. One table
# so the two directions cannot drift silently.
_PAYLOAD_FIELDS: "dict[str, type]" = {
    "dataset": str,
    "model": str,
    "detector": str,
    "partitions": int,
    "per_batch": int,
    "mult_data": float,
    "seed": int,
    "backend": str,
    "window": int,
    "window_rotations": int,
    "data_policy": str,
    "tenants": int,
}


def config_from_payload(payload: dict, **extras) -> RunConfig:
    """The inverse of :func:`telemetry_config_payload`: rebuild a runnable
    :class:`RunConfig` from a digest payload plus the bookkeeping fields
    the digest deliberately excludes (``results_csv``, ``time_string``,
    ``telemetry_dir``, ...).

    The ``sched/`` worker's cell-rebuild contract: a scheduler ships each
    cell as its payload, the worker rebuilds and re-digests, and the two
    must match byte-for-byte — so an *unknown* payload field fails loudly
    here (schema drift between a newer scheduler and an older worker must
    refuse to run the wrong experiment, the same posture as heal's
    unknown-spec-key check). jax-free, like the rest of this module."""
    unknown = set(payload) - set(_PAYLOAD_FIELDS)
    if unknown:
        raise ValueError(
            f"unknown config payload field(s) {sorted(unknown)}; known: "
            f"{sorted(_PAYLOAD_FIELDS)}"
        )
    kw = {k: _PAYLOAD_FIELDS[k](v) for k, v in payload.items()}
    kw.update(extras)
    return RunConfig(**kw)


def tenant_dataset(dataset: str, tenant: int) -> str:
    """Tenant ``t``'s dataset spec: any ``{tenant}`` placeholder in the
    configured dataset string is substituted with the tenant index, so one
    config can fan out over per-tenant sources (e.g.
    ``synth:rialto,seed={tenant},rows_per_class=4{tenant}`` gives every
    tenant its own seed AND a ragged length). Without a placeholder every
    tenant reads the same source (seeds still differ — see
    :func:`tenant_configs`)."""
    return dataset.replace("{tenant}", str(tenant))


def tenant_configs(cfg: RunConfig) -> "list[RunConfig]":
    """Expand a ``tenants = T`` config into the T solo configs it means.

    Tenant ``t`` is the single-stream run with ``seed = cfg.seed + t``
    (its own stream synthesis, PRNG keys and stripe-time shuffle) and
    ``{tenant}``-substituted dataset — the exact runs
    ``api.run_multi``'s per-tenant flags are bit-identical to. jax-free,
    like the rest of this module, so CLIs can expand without a backend.
    """
    if cfg.tenants < 1:
        raise ValueError(f"tenants must be >= 1, got {cfg.tenants}")
    return [
        replace(
            cfg,
            tenants=1,
            seed=cfg.seed + t,
            dataset=tenant_dataset(cfg.dataset, t),
        )
        for t in range(cfg.tenants)
    ]


# Version of the auto W×R resolution policy (auto_window / auto_rotations).
# Bump whenever the resolution *algorithm* changes (v2 = the r04 co-resolved
# depth-4 policy): grid trial keys embed it for auto-mode configs
# (harness.grid._config_key), so trials recorded under an older policy are
# retired on re-run instead of silently resumed onto stale-policy timings —
# '-w0r0' alone names the sentinel, not what it resolves to.
AUTO_POLICY_VERSION = 2


def auto_window(cfg: RunConfig, dist_between_changes: int) -> int:
    """Resolve ``window == 0`` from stream geometry (W of the W×R policy).

    With the multi-rotation engine the sequential-step count is
    ≈ NB/W + drifts/R, and the depth auto-resolution (:func:`auto_rotations`)
    sizes R to the boundaries one window spans — so W gains past the
    per-partition drift spacing ``bpc`` (in batches) up to roughly
    ``R*·bpc``, where each step commits ~R* concepts. The r03 on-hardware
    W×R sweep (table in ``bench.py``) measured the sweet spot at depth
    R* = 4: at the headline geometry (bpc = 32) W=128 R=4 beat both the
    single-rotation optimum (W=64 R=1: 0.165 s → 0.156 s) and every wider /
    deeper cell (W=192 R=4: 0.191 s — per-iteration slice cost; R=8:
    0.199 s — per-level replay cost). Pick the power of two nearest
    ``R*·bpc`` (R* from the pinned depth when the user set one), clamped to
    [4, 128] (tiny windows forfeit the batching win; the cap is where
    measured slice cost overtakes saved iterations). A pinned depth of 1
    reduces to the round-2 policy: W ≈ bpc, one concept per window.
    Streams without planted geometry get 16 (speculation budget without a
    spacing to size against).
    """
    if cfg.window:
        return cfg.window
    bpc = dist_between_changes / max(cfg.partitions * cfg.per_batch, 1)
    if bpc <= 0:
        return 16
    import math

    depth = 4 if cfg.window_rotations == 0 else max(cfg.window_rotations, 1)
    target = max(bpc * depth, 1.0)
    w = 1 << round(math.log2(target))
    return int(min(128, max(4, w)))


def auto_rotations(cfg: RunConfig, dist_between_changes: int) -> int:
    """Resolve ``window_rotations == 0`` (auto) from stream geometry.

    A window of ``W`` batches covers ``W · per_batch`` elements of one
    partition's stream; with planted concepts of ``dist_between_changes /
    partitions`` elements per partition it spans ≈ ``per_window/cpp``
    boundaries, each costing one replay level. Depth =
    round(boundaries-per-window) commits a typical window's boundaries in
    one step (the r03 sweep measured this exact point — R=4 at 4
    boundaries/window — as the optimum, with the +1 safety level R=5
    ~2% slower), clamped to [1, 8] (beyond ~8 the per-level
    predict/detector cost rivals the saved iterations at typical shapes).
    Windows much smaller than a concept round to depth 1 — paying an
    every-step replay level for a rare boundary-straddling window is a
    loss. Resolution needs the *resolved* window — call after
    :func:`auto_window`; at auto W the pair lands on the measured 128×4 at
    headline geometry (pinned by tests). Streams without planted geometry
    keep depth 1 (speculating on absent drift is waste).
    """
    if cfg.window_rotations:
        return cfg.window_rotations
    if dist_between_changes <= 0 or cfg.window <= 1:
        return 1
    concept_pp = dist_between_changes / max(cfg.partitions, 1)
    per_window = cfg.window * cfg.per_batch
    return int(min(8, max(1, round(per_window / concept_pp))))


def auto_ph_threshold(cfg: RunConfig, dist_between_changes: int) -> float:
    """Resolve ``PHParams.threshold == 0`` (auto) from stream geometry.

    λ is Page–Hinkley's cumulative excess-error budget in *elements*: after
    a drift the statistic grows by ≈ (1 − x̄ − δ) per element, so detection
    delay is ≈ λ elements while noise immunity grows with λ. Scale it to the
    per-partition concept length (``dist_between_changes / partitions`` —
    each partition sees a 1/P stripe of every planted concept): λ =
    ``concept_pp / 16``, clamped to [4, 32]. The floor keeps the statistic
    above single-element noise at tiny test geometries; the cap bounds
    detection delay to well under one worker-batch at benchmark geometries
    (measured: λ ∈ [8, 32] detects every planted outdoorStream boundary,
    delay-minimal around λ ≈ 16, while the classic λ = 50 on a 128-element
    concept eats half the concept in delay). Streams with no planted-drift
    geometry (``dist_between_changes <= 0``) fall back to the classic 50.
    """
    if cfg.ph.threshold:
        return cfg.ph.threshold
    if dist_between_changes <= 0:
        return 50.0
    return auto_ph_threshold_rows(
        dist_between_changes / max(cfg.partitions, 1)
    )


def auto_ph_threshold_rows(concept_pp: float) -> float:
    """The λ auto-resolution formula on a *per-partition* concept length in
    rows — the config-free core of :func:`auto_ph_threshold`, shared with
    engines that know their drift geometry directly (``engine.soak``'s
    ``drift_every`` is exactly this quantity)."""
    return float(min(32.0, max(4.0, concept_pp / 16.0)))


# retrain_error_threshold auto-resolution (VERDICT r4 #1) ------------------
#
# RETRAIN_AUTO is the RunConfig default: a negative threshold is meaningless
# as an active setting (err_rate > -1 would force a retrain every batch,
# which 0.0 already expresses more honestly), so it is safe as a sentinel.
RETRAIN_AUTO = -1.0

# The resolved guard value — the reference's own (dead) REGRESSION_THRESH
# idea, DDM_Process.py:31: a batch error rate above 0.3 forces
# rotate+reset+retrain without recording a change.
AUTO_RETRAIN_THRESHOLD = 0.3

# Model families that ship with the guard ON by default: the *memorizer*
# families, whose fits carry ≈ zero accuracy across a concept boundary, so
# one detector reset at a saturated-error position pins DDM's minima at the
# ceiling and silences it forever (the measured r04 failure: gnb and forest
# at recall 0.000 on the rialto stand-in — PARITY.md "domain limit"
# sections; the guard is the measured mitigation). ``majority`` is equally a
# memorizer but stays UNGUARDED by design: it is the bit-exact golden family
# pinned against the NumPy oracle's reference semantics (tests/oracle.py),
# and the guard is not part of those semantics — guard it explicitly via
# ``retrain_error_threshold=0.3`` when using it outside golden tests.
# Mirrored by the per-model ``Model.saturation_guard`` flag
# (models/base.py); ``tests/test_models.py`` pins the two in sync.
GUARDED_MODELS = frozenset({"gnb", "forest"})


def resolve_retrain_threshold(cfg: RunConfig) -> float | None:
    """Resolve ``retrain_error_threshold`` (RETRAIN_AUTO → per-family).

    None and explicit non-negative floats pass through; any negative value
    is the auto sentinel: ``AUTO_RETRAIN_THRESHOLD`` for ``GUARDED_MODELS``,
    None (reference-exact) otherwise. Shared by ``api.prepare`` and the
    grid harness's trial keys (the key must embed what actually ran).
    """
    thr = cfg.retrain_error_threshold
    if thr is None or thr >= 0.0:
        return thr
    return AUTO_RETRAIN_THRESHOLD if cfg.model in GUARDED_MODELS else None


def parse_model_spec(spec: str) -> tuple[str, dict]:
    """Parse a ``family[@variant]`` model spec → (family, RunConfig kwargs).

    The one grammar shared by the parity harness's sweep specs and the
    zoo examples: ``@robust`` selects the shipped ``DDM_ROBUST`` detector
    preset; unknown variants fail loudly here rather than leaking a bogus
    family name downstream.
    """
    family, _, variant = spec.partition("@")
    if variant == "robust":
        return family, {"ddm": DDM_ROBUST}
    if variant:
        raise ValueError(f"unknown model variant {spec!r}; known: @robust")
    return family, {}


def host_shuffle_seed(cfg: RunConfig) -> int | None:
    """The stripe-time shuffle seed a config implies (None = no shuffle).

    Single source of truth shared by ``api.prepare`` and any chunked/soak
    pipeline that wants bit-identical results to the one-shot path — pass
    this as ``shuffle_seed`` to the feeder and run the engine with
    ``shuffle=False``.
    """
    return cfg.seed + 0x5EED if cfg.shuffle_batches else None
