"""Command-line entry point mirroring the reference's argv mode.

The reference script accepts (commented-out but documented, README.md:11)
positional arguments ``URL INSTANCES MEMORY CORES TIME_STRING MULT_DATA``
(``DDM_Process.py:15-21``), which ``run_experiments.sh`` passes. Same
contract here — the Spark-only knobs are recorded verbatim into the results
CSV for table parity — plus an optional trailing ``DATASET`` (the reference
requires editing the script per dataset, ``README.md:12``; quirk #5 fixed):

    python -m distributed_drift_detection_tpu \\
        jax://local 16 8g 4 "$(date | sed 's/ /_/g')" 512 outdoorStream.csv

With no arguments, runs the module-default config like executing the
reference script unedited.
"""

import sys

from .api import run
from .config import RunConfig


_USAGE = (
    "usage: python -m distributed_drift_detection_tpu "
    "[URL INSTANCES MEMORY CORES TIME_STRING MULT_DATA [DATASET]]"
)


def main(argv: list[str]) -> None:
    kw = {}
    if argv and len(argv) not in (6, 7):
        raise SystemExit(_USAGE)
    if argv:
        try:
            kw = dict(
                url=argv[0],
                partitions=int(argv[1]),  # reference INSTANCES
                memory=argv[2],
                cores=int(argv[3]),
                time_string=argv[4],
                mult_data=float(argv[5]),
            )
        except ValueError as e:
            raise SystemExit(f"{_USAGE}\n({e})") from None
        if len(argv) == 7:
            kw["dataset"] = argv[6]
    res = run(RunConfig(**kw))
    m = res.metrics
    print(
        f"rows={res.stream.num_rows} detections={m.num_detections} "
        f"mean_delay_rows={m.mean_delay_rows:.1f} "
        f"final_time={res.total_time:.3f}s"
    )


if __name__ == "__main__":
    main(sys.argv[1:])
