"""Command-line entry point mirroring the reference's argv mode.

The reference script accepts (commented-out but documented, README.md:11)
positional arguments ``URL INSTANCES MEMORY CORES TIME_STRING MULT_DATA``
(``DDM_Process.py:15-21``), which ``run_experiments.sh`` passes. Same
contract here — the Spark-only knobs are recorded verbatim into the results
CSV for table parity — plus an optional trailing ``DATASET`` (the reference
requires editing the script per dataset, ``README.md:12``; quirk #5 fixed):

    python -m distributed_drift_detection_tpu \\
        jax://local 16 8g 4 "$(date | sed 's/ /_/g')" 512 outdoorStream.csv

With no arguments, runs the module-default config like executing the
reference script unedited. Three optional flags (anywhere in argv) reach
the aux subsystems without writing Python: ``--trace-dir DIR`` wraps the
detect phase in a ``jax.profiler`` trace, ``--profile-dir DIR`` wraps the
whole Final Time span in one (TensorBoard/Perfetto-readable, next to the
run's telemetry artifacts; mutually exclusive with ``--trace-dir``), and
``--telemetry-dir DIR`` persists the structured JSONL run log + metric
exports (telemetry subsystem).

A fourth flag, ``--data-policy {strict,quarantine,repair}``, selects the
ingest contract policy for dirty CSVs (``io.sanitize``; strict is the
default — fail loudly, never compute on garbage). ``--compile-cache-dir
DIR`` points jax's persistent compilation cache at DIR (warm-start:
repeated invocations skip XLA compilation — ``utils.compile_cache``), and
``--collect {compact,full}`` pins the collect-phase transport
(device-compacted detection table vs full flag plane; flags identical).

Two serving subcommands run the online daemon and its load generator
(``serve`` subsystem, docs/SERVING.md):

    python -m distributed_drift_detection_tpu serve --features F --classes C [...]
    python -m distributed_drift_detection_tpu loadgen SOURCE --port P [...]

``serve`` is the always-on drift-serving daemon: a socket line-protocol
ingress sanitized at admission (strict|quarantine|repair), microbatched
into fixed-geometry chunks, detected by the AOT-warmed chunked engine,
verdicts + heartbeats published through the telemetry registry so
``watch``/``report`` work unchanged on the live service; SIGTERM drains
and checkpoints. ``--on-drift retrain|shadow`` additionally *consumes*
the verdicts (adapt/ subsystem, docs/SERVING.md "Adaptation"):
per-tenant post-drift window refit hot-swapped at a chunk boundary with
zero recompiles, champion/challenger gating, `adaptation` events.
``loadgen`` replays an ``io/synth`` spec or CSV at a target rows/s
(optionally with seeded dirty rows, or with ``--delayed-labels K``
label lag) and reports achieved rate + p50/p99 row→verdict latency as
JSON.

A ``chunked`` subcommand drives the streaming ingest pipeline end to end
on a CSV (``harness.chunked_cli``): mmap'd line-aligned blocks fan out to
``--ingest-workers`` parse workers, reassemble in order (bit-identical at
any worker count), stripe through the pooled striper and feed the
AOT-warmed chunked engine — the disk path the chunked benchmark measures,
runnable on any file:

    python -m distributed_drift_detection_tpu chunked stream.csv --classes 10 [...]

Seven further subcommands work offline (no accelerator — ``doctor`` reads
the data, the rest just the artifacts; ``heal --execute`` is the one that
runs experiments):

    python -m distributed_drift_detection_tpu report <run.jsonl | --dir DIR>
    python -m distributed_drift_detection_tpu perf BENCH_r*.json [...]
    python -m distributed_drift_detection_tpu watch <run.jsonl | DIR> [...]
    python -m distributed_drift_detection_tpu top <run.jsonl | DIR>... [--statusz URL]
    python -m distributed_drift_detection_tpu pipeline <.prom | run.jsonl | URL>
    python -m distributed_drift_detection_tpu history <range|rate|quantile|top-tenants> STORE [...]
    python -m distributed_drift_detection_tpu collector --store DIR [--statusz URL | --fleetz URL | --registry DIR]
    python -m distributed_drift_detection_tpu correlate <DIR | logs...>
    python -m distributed_drift_detection_tpu timeline <DIR | logs...> [-o OUT]
    python -m distributed_drift_detection_tpu explain <DIR | run.jsonl | bundle>
    python -m distributed_drift_detection_tpu incident <list|show|diagnose> <DIR | run.jsonl | bundle>
    python -m distributed_drift_detection_tpu heal SPEC --telemetry-dir DIR [...]
    python -m distributed_drift_detection_tpu sched [SPEC] --telemetry-dir DIR [...]
    python -m distributed_drift_detection_tpu sched-worker --connect HOST:PORT [...]
    python -m distributed_drift_detection_tpu registry compact DIR [...]
    python -m distributed_drift_detection_tpu doctor CSV [CSV ...]

``report`` renders a persisted run log (``--dir`` picks a telemetry
directory's newest run); ``perf`` diffs bench artifacts across rounds per
cell and exits nonzero on gated regressions beyond a tolerance
(telemetry.perf); ``watch`` live-tails a run log — progress/ETA from
heartbeats, exit 3 past ``--stall-after`` (telemetry.watch, the
scriptable health check); ``top`` renders one refreshing dashboard
over many runs — throughput, latency percentiles, drift/quarantine
rates, active alerts — from tailed logs and/or serving daemons'
``--ops-port`` ``/statusz`` endpoints (telemetry.top); ``pipeline``
renders the serve-pipeline observatory — per-stage busy share,
utilization, implied rows/s ceiling and the dominant (bottleneck)
stage — from a metrics export or a live daemon
(telemetry.pipeline); ``history`` queries a durable time-series store
— range/rate/quantile over any stored series, per-tenant hotness
ranking, sparkline or JSON output (telemetry.history); ``collector``
is the scraper daemon that builds such a store from a fleet's ops
endpoints and can judge burn-rate SLO rules against it
(telemetry.collector); ``correlate`` merges a multi-host run's
per-process logs into one timeline with straggler diagnostics
(telemetry.correlate); ``heal`` diffs a sweep spec against the
registry's completed runs and emits — or ``--execute``s under the
retry supervisor — the re-run plan for whatever a crash left missing
(resilience.heal; plan mode is jax-free, exit 0 = sweep whole);
``sched`` is the elastic sweep scheduler (sched subsystem,
docs/SCHEDULER.md): it expands a sweep spec into cells, leases them to
``sched-worker`` agents over a jax-free TCP control protocol, revokes
dead/wedged workers' leases (the watch stall contract) and re-leases
until the registry shows every cell completed exactly once — the
paper's ``run_experiments.sh`` as a fleet controller; ``registry
compact`` bounds a long-lived directory's ``index.jsonl``
(telemetry.registry.compact_index); ``doctor`` validates CSV inputs
against the ingest contract jax-free and
exits nonzero on violations (io.sanitize — the pre-flight for sweeps);
``timeline`` merges one or many run logs (daemon + loadgen, or a
multi-host fleet's per-process logs, clock-skew aligned) into a
Chrome-trace/Perfetto ``.trace.json`` with the causal serving span
chains (telemetry.timeline); ``explain`` renders the drift evidence
bundles a serving daemon extracted under ``<run>.forensics/``
(telemetry.forensics); ``incident`` lists/renders/diagnoses the
alert-triggered cross-plane autopsy bundles under
``<run>.incidents/`` — ``diagnose`` ranks probable causes
deterministically from the bundle alone (telemetry.incident).
"""

import sys

_USAGE = (
    "usage: python -m distributed_drift_detection_tpu "
    "[--trace-dir DIR] [--profile-dir DIR] [--telemetry-dir DIR] "
    "[--data-policy strict|quarantine|repair] "
    "[--compile-cache-dir DIR] [--collect compact|full] [--tenants N] "
    "[URL INSTANCES MEMORY CORES TIME_STRING MULT_DATA [DATASET]]\n"
    "       python -m distributed_drift_detection_tpu serve --features F --classes C [...]\n"
    "       python -m distributed_drift_detection_tpu loadgen SOURCE --port P [...]\n"
    "       python -m distributed_drift_detection_tpu router --backend H:P:OP [...]\n"
    "       python -m distributed_drift_detection_tpu report RUN_JSONL [...]\n"
    "       python -m distributed_drift_detection_tpu perf BENCH_JSON [...]\n"
    "       python -m distributed_drift_detection_tpu watch RUN_JSONL_OR_DIR\n"
    "       python -m distributed_drift_detection_tpu top DIR_OR_LOGS [--statusz URL]\n"
    "       python -m distributed_drift_detection_tpu pipeline PROM_OR_LOG_OR_URL [--json]\n"
    "       python -m distributed_drift_detection_tpu history QUERY STORE [SERIES] [...]\n"
    "       python -m distributed_drift_detection_tpu collector --store DIR [--statusz URL ...]\n"
    "       python -m distributed_drift_detection_tpu correlate DIR_OR_LOGS\n"
    "       python -m distributed_drift_detection_tpu timeline DIR_OR_LOGS [-o OUT]\n"
    "       python -m distributed_drift_detection_tpu explain DIR_OR_LOG_OR_BUNDLE\n"
    "       python -m distributed_drift_detection_tpu incident list|show|diagnose DIR_OR_LOG_OR_BUNDLE [...]\n"
    "       python -m distributed_drift_detection_tpu heal SPEC --telemetry-dir DIR\n"
    "       python -m distributed_drift_detection_tpu sched [SPEC] --telemetry-dir DIR [...]\n"
    "       python -m distributed_drift_detection_tpu sched-worker --connect HOST:PORT [...]\n"
    "       python -m distributed_drift_detection_tpu registry compact DIR [...]\n"
    "       python -m distributed_drift_detection_tpu doctor [--jobs N] CSV [CSV ...]\n"
    "       python -m distributed_drift_detection_tpu chunked CSV --classes C [...]"
)


def _pop_flag(argv: list[str], flag: str) -> str | None:
    """Extract ``--flag VALUE`` / ``--flag=VALUE`` from argv (mutating it)."""
    for i, arg in enumerate(argv):
        if arg == flag:
            if i + 1 >= len(argv):
                raise SystemExit(f"{_USAGE}\n({flag} needs a value)")
            value = argv[i + 1]
            del argv[i : i + 2]
            return value
        if arg.startswith(flag + "="):
            del argv[i]
            return arg[len(flag) + 1 :]
    return None


def main(argv: list[str]) -> None:
    if argv and argv[0] == "report":
        # jax-free path: the report CLI must work wherever the artifact is.
        from .telemetry.report import main as report_main

        report_main(argv[1:])
        return
    if argv and argv[0] == "perf":
        # jax-free path too: bench artifacts are diffed wherever they land.
        from .telemetry.perf import main as perf_main

        perf_main(argv[1:])
        return
    if argv and argv[0] == "watch":
        # jax-free: the health check runs on pod hosts and in CI gates.
        from .telemetry.watch import main as watch_main

        watch_main(argv[1:])
        return
    if argv and argv[0] == "top":
        # jax-free: the live dashboard tails logs and scrapes /statusz
        # wherever the artifacts or ops endpoints are reachable.
        from .telemetry.top import main as top_main

        top_main(argv[1:])
        return
    if argv and argv[0] == "pipeline":
        # jax-free: the serve-pipeline bottleneck report reads a .prom /
        # .metrics.json export, a run-log sibling, or a live /statusz.
        from .telemetry.pipeline import main as pipeline_main

        raise SystemExit(pipeline_main(argv[1:]))
    if argv and argv[0] == "history":
        # jax-free: the time-series store is queried wherever it lands
        # (telemetry.history — the fleet's durable metrics memory).
        from .telemetry.history import main as history_main

        raise SystemExit(history_main(argv[1:]))
    if argv and argv[0] == "collector":
        # jax-free: the fleet scraper daemon only GETs ops endpoints and
        # appends to a history store (telemetry.collector).
        from .telemetry.collector import main as collector_main

        raise SystemExit(collector_main(argv[1:]))
    if argv and argv[0] == "correlate":
        # jax-free: multi-host logs are merged wherever they are mirrored.
        from .telemetry.correlate import main as correlate_main

        correlate_main(argv[1:])
        return
    if argv and argv[0] == "timeline":
        # jax-free: run logs merge into a Chrome-trace artifact anywhere.
        from .telemetry.timeline import main as timeline_main

        timeline_main(argv[1:])
        return
    if argv and argv[0] == "explain":
        # jax-free: forensics bundles render wherever the artifacts land.
        from .telemetry.forensics import main as explain_main

        explain_main(argv[1:])
        return
    if argv and argv[0] == "incident":
        # jax-free: incident autopsy bundles (alert-triggered cross-plane
        # evidence, <run-log>.incidents/) list/render/diagnose wherever
        # the artifacts land (telemetry.incident).
        from .telemetry.incident import main as incident_main

        raise SystemExit(incident_main(argv[1:]))
    if argv and argv[0] == "heal":
        # jax-free in plan mode; --execute pulls in the api lazily.
        from .resilience.heal import main as heal_main

        heal_main(argv[1:])
        return
    if argv and argv[0] == "sched":
        # jax-free: the sweep scheduler daemon runs wherever the
        # registry lands; only its WORKERS touch jax (sched subsystem,
        # docs/SCHEDULER.md).
        from .sched.scheduler import main as sched_main

        sched_main(argv[1:])
        return
    if argv and argv[0] == "sched-worker":
        # The worker agent: leases cells from a scheduler and runs them
        # under the supervisor (jax lazily, per cell).
        from .sched.worker import main as sched_worker_main

        sched_worker_main(argv[1:])
        return
    if argv and argv[0] == "registry":
        # jax-free: index.jsonl maintenance (compaction) wherever the
        # artifact lands.
        from .telemetry.registry import main as registry_main

        registry_main(argv[1:])
        return
    if argv and argv[0] == "doctor":
        # jax-free: the ingest pre-flight runs wherever the data lands.
        from .io.sanitize import main as doctor_main

        doctor_main(argv[1:])
        return
    if argv and argv[0] == "chunked":
        # Streaming ingest pipeline end to end on a CSV (harness.chunked_cli):
        # parallel parse → stripe → AOT-warmed ChunkedDetector.
        from .harness.chunked_cli import main as chunked_main

        chunked_main(argv[1:])
        return
    if argv and argv[0] == "serve":
        # The always-on serving daemon (serve subsystem, docs/SERVING.md).
        from .serve.runner import main as serve_main

        serve_main(argv[1:])
        return
    if argv and argv[0] == "loadgen":
        # Stream replay + row→verdict latency SLO probe for `serve`.
        from .serve.loadgen import main as loadgen_main

        loadgen_main(argv[1:])
        return
    if argv and argv[0] == "router":
        # jax-free: the fleet front daemon routes tenants across N
        # serving daemons with live migration (serve.router).
        from .serve.router import main as router_main

        router_main(argv[1:])
        return

    argv = list(argv)
    kw = {}
    trace_dir = _pop_flag(argv, "--trace-dir")
    if trace_dir is not None:
        kw["trace_dir"] = trace_dir
    profile_dir = _pop_flag(argv, "--profile-dir")
    if profile_dir is not None:
        kw["profile_dir"] = profile_dir
    telemetry_dir = _pop_flag(argv, "--telemetry-dir")
    if telemetry_dir is not None:
        kw["telemetry_dir"] = telemetry_dir
    data_policy = _pop_flag(argv, "--data-policy")
    if data_policy is not None:
        from .config import DATA_POLICIES

        if data_policy not in DATA_POLICIES:
            raise SystemExit(
                f"{_USAGE}\n(--data-policy must be one of "
                f"{'|'.join(DATA_POLICIES)}, got {data_policy!r})"
            )
        kw["data_policy"] = data_policy
    compile_cache_dir = _pop_flag(argv, "--compile-cache-dir")
    if compile_cache_dir is not None:
        kw["compile_cache_dir"] = compile_cache_dir
    collect = _pop_flag(argv, "--collect")
    if collect is not None:
        from .config import COLLECT_MODES

        if collect not in COLLECT_MODES:
            raise SystemExit(
                f"{_USAGE}\n(--collect must be one of "
                f"{'|'.join(COLLECT_MODES)}, got {collect!r})"
            )
        kw["collect"] = collect
    tenants = _pop_flag(argv, "--tenants")
    if tenants is not None:
        try:
            kw["tenants"] = int(tenants)
        except ValueError as e:
            raise SystemExit(f"{_USAGE}\n({e})") from None
        if kw["tenants"] < 1:
            raise SystemExit(f"{_USAGE}\n(--tenants must be >= 1)")
    if argv and len(argv) not in (6, 7):
        raise SystemExit(_USAGE)
    if argv:
        try:
            kw.update(
                url=argv[0],
                partitions=int(argv[1]),  # reference INSTANCES
                memory=argv[2],
                cores=int(argv[3]),
                time_string=argv[4],
                mult_data=float(argv[5]),
            )
        except ValueError as e:
            raise SystemExit(f"{_USAGE}\n({e})") from None
        if len(argv) == 7:
            kw["dataset"] = argv[6]

    from .config import RunConfig

    cfg = RunConfig(**kw)
    if cfg.tenants > 1:
        # Multi-tenant plane: ONE compiled kernel runs every tenant; the
        # summary is per-tenant (each bit-identical to its solo run) plus
        # the aggregate throughput the stacked dispatch buys.
        from .api import run_multi

        mr = run_multi(cfg)
        for t, r in enumerate(mr.results):
            m = r.metrics
            print(
                f"tenant={t} rows={r.stream.num_rows} "
                f"detections={m.num_detections} "
                f"mean_delay_rows={m.mean_delay_rows:.1f}"
            )
        print(
            f"tenants={cfg.tenants} rows={mr.rows} "
            f"final_time={mr.total_time:.3f}s "
            f"agg_rows_per_sec={mr.agg_rows_per_sec:.1f}"
        )
        if mr.telemetry_path:
            print(f"telemetry={mr.telemetry_path}")
        return

    from .api import run  # lazy: `report` above must not initialise jax

    res = run(cfg)
    m = res.metrics
    print(
        f"rows={res.stream.num_rows} detections={m.num_detections} "
        f"mean_delay_rows={m.mean_delay_rows:.1f} "
        f"final_time={res.total_time:.3f}s"
    )
    if res.telemetry_path:
        print(f"telemetry={res.telemetry_path}")


if __name__ == "__main__":
    main(sys.argv[1:])
