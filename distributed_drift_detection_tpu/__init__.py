"""TPU-native distributed concept-drift-detection framework.

A from-scratch JAX/XLA rebuild of the capabilities of
``rcorizzo/distributed-drift-detection`` (Spark + sklearn + skmultiflow; see
SURVEY.md): DDM drift detection with a paired train/predict/detect/retrain
microbatch loop, data-parallel over row-striped stream partitions — as a
jit-compiled streaming kernel vmapped over partitions and sharded over a
``jax.sharding.Mesh`` instead of a Spark cluster.
"""

from .config import (
    DDMParams,
    EDDMParams,
    ADWINParams,
    HDDMParams,
    HDDMWParams,
    KSWINParams,
    PHParams,
    STEPDParams,
    RunConfig,
    replace,
)

__version__ = "0.1.0"

# The kernel exports pull in jax at module level; resolving them lazily
# (PEP 562) keeps `import distributed_drift_detection_tpu` jax-free, so the
# telemetry tooling — `python -m distributed_drift_detection_tpu report`,
# the exporters — runs wherever the run-log artifact lands, jax installed
# or not.
_OPS_EXPORTS = frozenset(
    {
        "DDMState",
        "DetectorKernel",
        "ddm_batch",
        "ddm_init",
        "ddm_scan",
        "ddm_step",
        "make_detector",
    }
)


def __getattr__(name):
    if name in _OPS_EXPORTS:
        from . import ops

        return getattr(ops, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def run(cfg, stream=None):
    """Execute one drift-detection run (lazy import to keep `import
    distributed_drift_detection_tpu` light)."""
    from .api import run as _run

    return _run(cfg, stream)


def run_multi(cfg, streams=None):
    """Execute a stacked multi-tenant run — T independent streams through
    one compiled kernel (lazy import, same contract as :func:`run`; see
    ``api.run_multi``)."""
    from .api import run_multi as _run_multi

    return _run_multi(cfg, streams)


__all__ = [
    "DDMParams",
    "EDDMParams",
    "ADWINParams",
    "HDDMParams",
    "HDDMWParams",
    "KSWINParams",
    "PHParams",
    "STEPDParams",
    "RunConfig",
    "replace",
    "DDMState",
    "DetectorKernel",
    "ddm_batch",
    "ddm_init",
    "ddm_scan",
    "ddm_step",
    "make_detector",
    "run",
    "run_multi",
    "__version__",
]
