"""TPU-native distributed concept-drift-detection framework.

A from-scratch JAX/XLA rebuild of the capabilities of
``rcorizzo/distributed-drift-detection`` (Spark + sklearn + skmultiflow; see
SURVEY.md): DDM drift detection with a paired train/predict/detect/retrain
microbatch loop, data-parallel over row-striped stream partitions — as a
jit-compiled streaming kernel vmapped over partitions and sharded over a
``jax.sharding.Mesh`` instead of a Spark cluster.
"""

from .config import (
    DDMParams,
    EDDMParams,
    ADWINParams,
    HDDMParams,
    HDDMWParams,
    KSWINParams,
    PHParams,
    STEPDParams,
    RunConfig,
    replace,
)
from .ops import (
    DDMState,
    DetectorKernel,
    ddm_batch,
    ddm_init,
    ddm_scan,
    ddm_step,
    make_detector,
)

__version__ = "0.1.0"


def run(cfg, stream=None):
    """Execute one drift-detection run (lazy import to keep `import
    distributed_drift_detection_tpu` light)."""
    from .api import run as _run

    return _run(cfg, stream)


__all__ = [
    "DDMParams",
    "EDDMParams",
    "ADWINParams",
    "HDDMParams",
    "HDDMWParams",
    "KSWINParams",
    "PHParams",
    "STEPDParams",
    "RunConfig",
    "replace",
    "DDMState",
    "DetectorKernel",
    "ddm_batch",
    "ddm_init",
    "ddm_scan",
    "ddm_step",
    "make_detector",
    "run",
    "__version__",
]
