"""Speculative window execution of the per-partition microbatch loop.

The sequential engine (``engine.loop``) maps the reference's
``for batch_b in batches[1:]`` (``DDM_Process.py:189``) onto a ``lax.scan``
with one microbatch per step. That is faithful but latency-bound on TPU: at
``per_batch = 100`` every step is a handful of tiny VPU ops, so a 2 M-row
stream costs ~1.3 k sequential steps of mostly dead time per partition.

This engine exploits the workload's key property: **drift is rare** (the
reference's planted streams change once per concept — every ~30+ batches at
its benchmark scale). Between drifts the loop is embarrassingly parallel
across batches: the model is frozen (no retrain), and the DDM statistic over
consecutive batches is one prefix computation (``ops.ddm_window``). So the
engine *speculates*: it processes a window of ``W`` consecutive microbatches
as one chunky step — one ``[W·B, F]`` prediction matmul + one flattened DDM
prefix scan — and checks afterwards which batch (if any) first signalled a
change/rotate. Everything up to and including that batch is committed;
everything after it is discarded and re-executed after the rotate, exactly as
the sequential loop would have (``DDM_Process.py:207-210``). With drift every
``D`` batches this cuts sequential steps from ``NB`` to ``≈ NB/W + NB/D``
(~10× at the reference's benchmark shape) while making each step matmul-shaped
instead of scalar-shaped — the TPU-native way to run an inherently sequential
detector fast.

Exactness: for deterministic-fit models (majority/centroid/gnb/linear) with
host-side shuffling, the committed flags are **bit-identical** to
``engine.loop`` (tested in ``tests/test_window.py``). For key-consuming fits
(MLP) the PRNG stream differs (keys split per window, not per batch), so
parity is statistical, like any reseeding.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import DDMParams
from ..models.base import Model
from ..ops.ddm import DDMState
from .loop import (
    Batches,
    FlagRows,
    IndexedBatches,
    LoopCarry,
    _gather_row,
    _select,
    resolve_detector,
)


class _WinState(NamedTuple):
    ptr: jax.Array  # i32: next uncommitted batch index in [0, NBF]
    params: object
    ddm: DDMState | object  # detector state (DDMState for the default kernel)
    a_X: jax.Array  # [B, F]
    a_y: jax.Array  # [B]
    a_w: jax.Array  # [B] f32
    retrain: jax.Array  # bool
    key: jax.Array
    flags: FlagRows  # output buffers, leaves [NBF + W]


def make_window_span(
    model: Model,
    ddm_params: DDMParams,
    *,
    window: int = 16,
    shuffle: bool = False,
    retrain_error_threshold: float | None = None,
    detector=None,
    rotations: int = 1,
):
    """Build ``span(carry: LoopCarry, batches) -> (LoopCarry, FlagRows)``.

    The carry-in/carry-out form of the speculative window engine: processes
    **every** batch of ``batches`` (no ``batch_a`` seeding — the caller owns
    the carry), emitting one flag row per batch. This is the building block
    for both the one-shot runner (:func:`make_window_runner`) and chunked
    streaming (``engine.chunked`` with ``window > 1``), where the carry flows
    across chunk boundaries exactly as the sequential step's does. Windows
    never span a chunk boundary; with chunk length ≫ window the lost
    speculation is negligible.

    ``rotations`` is the **speculation depth**: how many rotate-and-replay
    passes one sequential iteration may commit. At the default 1, an
    iteration commits up to the first in-window change and the discarded
    tail re-executes next iteration, so the sequential-step count is
    ``≈ NB/W + drifts`` — on a latency-bound device (remote-TPU dispatch,
    small per-step FLOPs) the ``drifts`` term dominates at benchmark
    geometry (39 of ~59 steps at the mult=512 headline). ``rotations = R``
    replays up to ``R−1`` times *inside the same iteration*: after a change
    at window row ``c``, rows ``≤ c`` are masked invalid, the model refits
    on batch ``c`` (exactly the sequential rotate), the detector restarts
    from a reset state, and the remaining rows are re-predicted — committing
    up to ``R`` changes per step and cutting the count toward
    ``≈ NB/W + drifts/R``. Each level adds one predict + one detector
    prefix pass of device work (trivial at these shapes, so the trade is
    pure win in the latency-bound regime). With ``shuffle=False`` (host-side
    shuffling, the api path) flags are bit-identical to the sequential
    engine for deterministic-fit models regardless of ``R`` (tested); under
    the in-jit ``shuffle=True`` mode replayed tail rows reuse the level-0
    permutations while the sequential engine redraws ``k_shuf`` on
    re-execution, so even deterministic fits vary with ``R`` there (parity
    is statistical, like any reseeding). Key-consuming fits
    ('mlp', 'rf') draw their fit keys per
    *level*, so — exactly like the ``window`` width — ``rotations`` is part
    of their seed story ('seed-equivalent, not bit-equal' across different
    values).

    Pure and jit/vmap-compatible; under ``vmap`` partitions advance their own
    window pointers in lock-step iterations (finished lanes freeze — their
    writes land in the pad region).
    """
    w = int(window)
    r_levels = int(rotations)
    assert w >= 1
    if r_levels < 1:
        raise ValueError(f"rotations must be >= 1, got {rotations}")
    from .loop import _check_retrain_threshold

    _check_retrain_threshold(retrain_error_threshold)
    det = resolve_detector(ddm_params, detector)
    # The window statistic runs as XLA primitives (cumsum + associative_scan,
    # ops/ddm.py). A fused Pallas twin was measured and removed in round 2 —
    # numbers in PARITY.md "Pallas post-mortem".
    _det_window = det.window

    def span(
        carry_in: LoopCarry, batches: Batches | IndexedBatches
    ) -> tuple[LoopCarry, FlagRows]:
        indexed = isinstance(batches, IndexedBatches)
        grid_y = batches.idx if indexed else batches.y
        nbf = grid_y.shape[0]  # flag rows == batches to process
        b = grid_y.shape[1]
        key = carry_in.key

        # Pad the scanned region to NBF + W so a window slice starting at any
        # committed ptr ∈ [0, NBF] stays in bounds; pad batches are invalid.
        def pad_tail(x, fill):
            tail = jnp.full((w, *x.shape[1:]), fill, x.dtype)
            return jnp.concatenate([x, tail], axis=0)

        if (not indexed) and batches.X.dtype != jnp.float32:
            # Transport-dtype seam: engines compute in f32 (engine/loop).
            batches = batches._replace(X=batches.X.astype(jnp.float32))
        if indexed:
            # Compressed stream: slice index planes, gather X/y from the
            # (replicated, cache-resident) row table on device. The row
            # table honors the same transport-dtype seam as the dense
            # branch above: engines compute in f32 for every plane layout.
            base_X = batches.base_X
            if base_X.dtype != jnp.float32:
                base_X = base_X.astype(jnp.float32)
            base_y = batches.base_y
            r_idx = pad_tail(batches.idx, 0)  # [NBF+W, B]
            mat_X = lambda i: base_X[i.astype(jnp.int32)]  # noqa: E731
            mat_y = lambda i: base_y[i.astype(jnp.int32)]  # noqa: E731
        else:
            r_X = pad_tail(batches.X, 0.0)  # [NBF+W, B, F]
            r_y = pad_tail(batches.y, 0)
        r_rows = pad_tail(batches.rows, -1)
        r_valid = pad_tail(batches.valid, False)

        i32 = jnp.int32
        buf = FlagRows(
            warning_local=jnp.full(nbf + w, -1, i32),
            warning_global=jnp.full(nbf + w, -1, i32),
            change_local=jnp.full(nbf + w, -1, i32),
            change_global=jnp.full(nbf + w, -1, i32),
            forced_retrain=jnp.zeros(nbf + w, bool),
        )
        st0 = _WinState(
            ptr=i32(0),
            params=carry_in.params,
            ddm=carry_in.ddm,
            a_X=carry_in.a_X,
            a_y=carry_in.a_y,
            a_w=carry_in.a_w,
            retrain=carry_in.retrain,
            key=key,
            flags=buf,
        )

        def cond(st: _WinState):
            return st.ptr < nbf

        def body(st: _WinState) -> _WinState:
            # Under vmap, lanes whose cond is already False still execute the
            # body; `active` freezes their state so per-partition results are
            # independent of other lanes' progress.
            active = st.ptr < nbf
            key, k_fit, k_shuf = jax.random.split(st.key, 3)
            # One fit key per speculation level; level 0 uses k_fit directly
            # so rotations=1 reproduces the historical key stream bit-exactly
            # (the window engine's 'mlp'/'rf' seed contract).
            k_fits = [k_fit] if r_levels == 1 else list(
                jax.random.split(k_fit, r_levels)
            )

            sl_rows = lax.dynamic_slice_in_dim(r_rows, st.ptr, w, 0)
            sl_valid = lax.dynamic_slice_in_dim(r_valid, st.ptr, w, 0)
            if indexed:
                sl_idx = lax.dynamic_slice_in_dim(r_idx, st.ptr, w, 0)
            else:
                sl_X = lax.dynamic_slice_in_dim(r_X, st.ptr, w, 0)  # [W,B,F]
                sl_y = lax.dynamic_slice_in_dim(r_y, st.ptr, w, 0)

            if shuffle:
                # In-jit per-batch shuffle (feeders that cannot pre-shuffle).
                perms = jax.vmap(
                    lambda k: jax.random.permutation(k, b)
                )(jax.random.split(k_shuf, w))  # [W, B]
                take = lambda a: jnp.take_along_axis(  # noqa: E731
                    a, perms.reshape(perms.shape + (1,) * (a.ndim - 2)), axis=1
                )
                sl_rows, sl_valid = take(sl_rows), take(sl_valid)
                if indexed:
                    sl_idx = take(sl_idx)
                else:
                    sl_X, sl_y = take(sl_X), take(sl_y)

            if indexed:
                sl_X, sl_y = mat_X(sl_idx), mat_y(sl_idx)

            rows_w = jnp.arange(w, dtype=i32)
            remaining = nbf - st.ptr

            # Speculation-level loop (unrolled: r_levels is static). Level 0
            # is the classic speculative pass over the whole window; each
            # further level replays the uncommitted tail after an in-window
            # rotate — mask rows ≤ the change point invalid, refit on the
            # change batch (the sequential rotate, DDM_Process.py:207-210),
            # restart the detector from a reset state, re-predict. All level
            # state is data (where-selected), so the unrolled code is one
            # straight-line XLA program.
            params_c, ddm_c = st.params, st.ddm
            a_X_c, a_y_c, a_w_c = st.a_X, st.a_y, st.a_w
            retr_c = st.retrain
            start = i32(0)  # first uncommitted window row
            open_ = jnp.bool_(True)  # this window still has rows to process
            slab = FlagRows(
                warning_local=jnp.full(w, -1, i32),
                warning_global=jnp.full(w, -1, i32),
                change_local=jnp.full(w, -1, i32),
                change_global=jnp.full(w, -1, i32),
                forced_retrain=jnp.zeros(w, bool),
            )

            for lvl in range(r_levels):
                live = sl_valid & (rows_w >= start)[:, None] & open_
                ne = jnp.any(live, axis=1)  # [W] nonempty live batches
                any_ne = jnp.any(ne)

                # Train-on-demand (C7 :194-196): the model is frozen within
                # a level — retrain can only be pending at level start.
                fitted = model.fit(k_fits[lvl], a_X_c, a_y_c, a_w_c)
                use_fit = retr_c & any_ne
                pred_params = _select(use_fit, fitted, params_c)

                # One chunky prediction for the whole window (W·B rows).
                preds = model.predict(
                    pred_params, sl_X.reshape(w * b, -1)
                ).reshape(w, b)
                errs = (preds != sl_y).astype(jnp.float32)

                # Speculative detector pass over the flattened live region
                # (state flows across batch boundaries — DDM_Process.py:202).
                new_ddm, res = _det_window(ddm_c, errs, live)
                change = (res.first_change >= 0) & ne  # [W]

                if retrain_error_threshold is not None:
                    bw = live.astype(jnp.float32)
                    err_rate = jnp.sum(errs * bw, axis=1) / jnp.maximum(
                        jnp.sum(bw, axis=1), 1.0
                    )
                    forced = ne & ~change & (err_rate > retrain_error_threshold)
                else:
                    forced = jnp.zeros(w, bool)
                rotate = change | forced

                # This level commits rows [start, end): up to and including
                # the first rotating batch, or the whole tail if none.
                any_rot = jnp.any(rotate)
                rpos = jnp.argmax(rotate).astype(i32)
                end = jnp.where(any_rot, rpos + 1, i32(w))
                row_mask = open_ & (rows_w >= start) & (rows_w < end)
                lvl_slab = FlagRows(
                    warning_local=res.first_warning,
                    warning_global=jax.vmap(_gather_row)(
                        sl_rows, res.first_warning
                    ),
                    change_local=res.first_change,
                    change_global=jax.vmap(_gather_row)(
                        sl_rows, res.first_change
                    ),
                    forced_retrain=forced,
                )
                slab = jax.tree.map(
                    lambda part, full: jnp.where(row_mask, part, full),
                    lvl_slab, slab,
                )

                # Rotate state from the first rotating batch; commit the fit
                # if a nonempty batch was actually processed with it.
                ne_cov = ne & (rows_w < end)
                any_ne_cov = jnp.any(ne_cov)
                take_rot = open_ & any_rot
                params_c = _select(
                    open_ & retr_c & any_ne_cov, fitted, params_c
                )
                ddm_c = _select(
                    open_, _select(any_rot, det.init(), new_ddm), ddm_c
                )
                a_X_c = _select(take_rot, sl_X[rpos], a_X_c)
                a_y_c = _select(take_rot, sl_y[rpos], a_y_c)
                a_w_c = _select(
                    take_rot, sl_valid[rpos].astype(jnp.float32), a_w_c
                )
                retr_c = jnp.where(open_ & any_ne_cov, any_rot, retr_c)
                start = jnp.where(open_, end, start)
                open_ = open_ & any_rot

            adv = jnp.where(active, jnp.minimum(start, remaining), i32(0))

            # Write the committed slab; rows past the commit point hold −1
            # and are overwritten by the next window (monotone ptr), rows
            # past NBF land in the pad region and are sliced off at the end.
            write_at = jnp.where(active, st.ptr, i32(nbf))
            flags = FlagRows(*(
                lax.dynamic_update_slice_in_dim(full, part, write_at, 0)
                for full, part in zip(st.flags, slab)
            ))

            upd = lambda new, old: _select(active, new, old)  # noqa: E731
            return _WinState(
                ptr=st.ptr + adv,
                params=upd(params_c, st.params),
                ddm=upd(ddm_c, st.ddm),
                a_X=upd(a_X_c, st.a_X),
                a_y=upd(a_y_c, st.a_y),
                a_w=upd(a_w_c, st.a_w),
                retrain=jnp.where(active, retr_c, st.retrain),
                key=upd(key, st.key),
                flags=flags,
            )

        out = lax.while_loop(cond, body, st0)
        carry_out = LoopCarry(
            params=out.params,
            ddm=out.ddm,
            a_X=out.a_X,
            a_y=out.a_y,
            a_w=out.a_w,
            retrain=out.retrain,
            key=out.key,
        )
        return carry_out, jax.tree.map(lambda x: x[:nbf], out.flags)

    return span


def make_window_runner(
    model: Model,
    ddm_params: DDMParams,
    *,
    window: int = 16,
    shuffle: bool = False,
    retrain_error_threshold: float | None = None,
    detector=None,
    rotations: int = 1,
):
    """Build ``run(batches: Batches, key) -> FlagRows`` for one partition.

    Output contract is identical to ``engine.loop.make_partition_runner``:
    ``FlagRows`` leaves of shape ``[NB - 1]`` (batch 0 seeds ``batch_a``).
    ``rotations`` is the speculation depth (:func:`make_window_span`).
    """
    det = resolve_detector(ddm_params, detector)
    span = make_window_span(
        model,
        ddm_params,
        window=window,
        shuffle=shuffle,
        retrain_error_threshold=retrain_error_threshold,
        detector=det,
        rotations=rotations,
    )

    def run(batches: Batches | IndexedBatches, key: jax.Array) -> FlagRows:
        indexed = isinstance(batches, IndexedBatches)
        key, k_init = jax.random.split(key)
        if indexed:
            # f32 like the span's gathers — batch_a must not smuggle a
            # narrower transport dtype into the first fit.
            a_X = batches.base_X[batches.idx[0].astype(jnp.int32)].astype(
                jnp.float32
            )
            a_y = batches.base_y[batches.idx[0].astype(jnp.int32)]
        else:
            a_X, a_y = batches.X[0], batches.y[0]
        carry = LoopCarry(
            params=model.init(k_init),
            ddm=det.init(),
            a_X=a_X,
            a_y=a_y,
            a_w=batches.valid[0].astype(jnp.float32),
            retrain=jnp.bool_(True),
            key=key,
        )
        rest = jax.tree.map(lambda x: x[1:], batches)
        if indexed:  # the replicated row table must not be sliced
            rest = rest._replace(base_X=batches.base_X, base_y=batches.base_y)
        _, flags = span(carry, rest)
        return flags

    return run
