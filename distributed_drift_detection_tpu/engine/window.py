"""Speculative window execution of the per-partition microbatch loop.

The sequential engine (``engine.loop``) maps the reference's
``for batch_b in batches[1:]`` (``DDM_Process.py:189``) onto a ``lax.scan``
with one microbatch per step. That is faithful but latency-bound on TPU: at
``per_batch = 100`` every step is a handful of tiny VPU ops, so a 2 M-row
stream costs ~1.3 k sequential steps of mostly dead time per partition.

This engine exploits the workload's key property: **drift is rare** (the
reference's planted streams change once per concept — every ~30+ batches at
its benchmark scale). Between drifts the loop is embarrassingly parallel
across batches: the model is frozen (no retrain), and the DDM statistic over
consecutive batches is one prefix computation (``ops.ddm_window``). So the
engine *speculates*: it processes a window of ``W`` consecutive microbatches
as one chunky step — one ``[W·B, F]`` prediction matmul + one flattened DDM
prefix scan — and checks afterwards which batch (if any) first signalled a
change/rotate. Everything up to and including that batch is committed;
everything after it is discarded and re-executed after the rotate, exactly as
the sequential loop would have (``DDM_Process.py:207-210``). With drift every
``D`` batches this cuts sequential steps from ``NB`` to ``≈ NB/W + NB/D``
(~10× at the reference's benchmark shape) while making each step matmul-shaped
instead of scalar-shaped — the TPU-native way to run an inherently sequential
detector fast.

Exactness: for deterministic-fit models (majority/centroid/gnb/linear) with
host-side shuffling, the committed flags are **bit-identical** to
``engine.loop`` (tested in ``tests/test_window.py``). For key-consuming fits
(MLP) the PRNG stream differs (keys split per window, not per batch), so
parity is statistical, like any reseeding.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import DDMParams
from ..models.base import Model
from ..ops.ddm import DDMState
from .loop import (
    Batches,
    FlagRows,
    IndexedBatches,
    LoopCarry,
    _gather_row,
    _select,
    resolve_detector,
)


class _WinState(NamedTuple):
    ptr: jax.Array  # i32: next uncommitted batch index in [0, NBF]
    params: object
    ddm: DDMState | object  # detector state (DDMState for the default kernel)
    a_X: jax.Array  # [B, F]
    a_y: jax.Array  # [B]
    a_w: jax.Array  # [B] f32
    retrain: jax.Array  # bool
    key: jax.Array
    flags: FlagRows  # output buffers, leaves [NBF + W]


def make_window_span(
    model: Model,
    ddm_params: DDMParams,
    *,
    window: int = 16,
    shuffle: bool = False,
    retrain_error_threshold: float | None = None,
    detector=None,
):
    """Build ``span(carry: LoopCarry, batches) -> (LoopCarry, FlagRows)``.

    The carry-in/carry-out form of the speculative window engine: processes
    **every** batch of ``batches`` (no ``batch_a`` seeding — the caller owns
    the carry), emitting one flag row per batch. This is the building block
    for both the one-shot runner (:func:`make_window_runner`) and chunked
    streaming (``engine.chunked`` with ``window > 1``), where the carry flows
    across chunk boundaries exactly as the sequential step's does. Windows
    never span a chunk boundary; with chunk length ≫ window the lost
    speculation is negligible.

    Pure and jit/vmap-compatible; under ``vmap`` partitions advance their own
    window pointers in lock-step iterations (finished lanes freeze — their
    writes land in the pad region).
    """
    w = int(window)
    assert w >= 1
    det = resolve_detector(ddm_params, detector)
    # The window statistic runs as XLA primitives (cumsum + associative_scan,
    # ops/ddm.py). A fused Pallas twin was measured and removed in round 2 —
    # numbers in PARITY.md "Pallas post-mortem".
    _det_window = det.window

    def span(
        carry_in: LoopCarry, batches: Batches | IndexedBatches
    ) -> tuple[LoopCarry, FlagRows]:
        indexed = isinstance(batches, IndexedBatches)
        grid_y = batches.idx if indexed else batches.y
        nbf = grid_y.shape[0]  # flag rows == batches to process
        b = grid_y.shape[1]
        key = carry_in.key

        # Pad the scanned region to NBF + W so a window slice starting at any
        # committed ptr ∈ [0, NBF] stays in bounds; pad batches are invalid.
        def pad_tail(x, fill):
            tail = jnp.full((w, *x.shape[1:]), fill, x.dtype)
            return jnp.concatenate([x, tail], axis=0)

        if indexed:
            # Compressed stream: slice index planes, gather X/y from the
            # (replicated, cache-resident) row table on device.
            base_X = batches.base_X
            base_y = batches.base_y
            r_idx = pad_tail(batches.idx, 0)  # [NBF+W, B]
            mat_X = lambda i: base_X[i.astype(jnp.int32)]  # noqa: E731
            mat_y = lambda i: base_y[i.astype(jnp.int32)]  # noqa: E731
        else:
            r_X = pad_tail(batches.X, 0.0)  # [NBF+W, B, F]
            r_y = pad_tail(batches.y, 0)
        r_rows = pad_tail(batches.rows, -1)
        r_valid = pad_tail(batches.valid, False)

        i32 = jnp.int32
        buf = FlagRows(
            warning_local=jnp.full(nbf + w, -1, i32),
            warning_global=jnp.full(nbf + w, -1, i32),
            change_local=jnp.full(nbf + w, -1, i32),
            change_global=jnp.full(nbf + w, -1, i32),
            forced_retrain=jnp.zeros(nbf + w, bool),
        )
        st0 = _WinState(
            ptr=i32(0),
            params=carry_in.params,
            ddm=carry_in.ddm,
            a_X=carry_in.a_X,
            a_y=carry_in.a_y,
            a_w=carry_in.a_w,
            retrain=carry_in.retrain,
            key=key,
            flags=buf,
        )

        def cond(st: _WinState):
            return st.ptr < nbf

        def body(st: _WinState) -> _WinState:
            # Under vmap, lanes whose cond is already False still execute the
            # body; `active` freezes their state so per-partition results are
            # independent of other lanes' progress.
            active = st.ptr < nbf
            key, k_fit, k_shuf = jax.random.split(st.key, 3)

            sl_rows = lax.dynamic_slice_in_dim(r_rows, st.ptr, w, 0)
            sl_valid = lax.dynamic_slice_in_dim(r_valid, st.ptr, w, 0)
            if indexed:
                sl_idx = lax.dynamic_slice_in_dim(r_idx, st.ptr, w, 0)
            else:
                sl_X = lax.dynamic_slice_in_dim(r_X, st.ptr, w, 0)  # [W,B,F]
                sl_y = lax.dynamic_slice_in_dim(r_y, st.ptr, w, 0)

            if shuffle:
                # In-jit per-batch shuffle (feeders that cannot pre-shuffle).
                perms = jax.vmap(
                    lambda k: jax.random.permutation(k, b)
                )(jax.random.split(k_shuf, w))  # [W, B]
                take = lambda a: jnp.take_along_axis(  # noqa: E731
                    a, perms.reshape(perms.shape + (1,) * (a.ndim - 2)), axis=1
                )
                sl_rows, sl_valid = take(sl_rows), take(sl_valid)
                if indexed:
                    sl_idx = take(sl_idx)
                else:
                    sl_X, sl_y = take(sl_X), take(sl_y)

            if indexed:
                sl_X, sl_y = mat_X(sl_idx), mat_y(sl_idx)

            ne = jnp.any(sl_valid, axis=1)  # [W] nonempty batches
            any_ne = jnp.any(ne)

            # Train-on-demand (C7 :194-196): the model is frozen inside the
            # window — retrain can only be pending at window start.
            fitted = model.fit(k_fit, st.a_X, st.a_y, st.a_w)
            pred_params = _select(st.retrain & any_ne, fitted, st.params)

            # One chunky prediction for the whole window (W·B rows).
            preds = model.predict(
                pred_params, sl_X.reshape(w * b, -1)
            ).reshape(w, b)
            errs = (preds != sl_y).astype(jnp.float32)

            # Speculative DDM over the flattened window (state flows across
            # batch boundaries — ``DDM_Process.py:202``).
            new_ddm, res = _det_window(st.ddm, errs, sl_valid)
            change = (res.first_change >= 0) & ne  # [W]

            if retrain_error_threshold is not None:
                bw = sl_valid.astype(jnp.float32)
                err_rate = jnp.sum(errs * bw, axis=1) / jnp.maximum(
                    jnp.sum(bw, axis=1), 1.0
                )
                forced = ne & ~change & (err_rate > retrain_error_threshold)
            else:
                forced = jnp.zeros(w, bool)
            rotate = change | forced

            # Commit everything up to (and including) the first rotating
            # batch; discard + re-execute the rest (the sequential loop would
            # have reset + retrained there, DDM_Process.py:207-210).
            any_rot = jnp.any(rotate)
            rpos = jnp.argmax(rotate).astype(i32)
            remaining = nbf - st.ptr
            adv = jnp.where(any_rot, rpos + 1, i32(w))
            adv = jnp.where(active, jnp.minimum(adv, remaining), i32(0))

            # Flag slabs for the whole window; rows past the commit point are
            # overwritten by the next window (monotone ptr), rows past NBF
            # land in the pad region and are sliced off at the end.
            slab = FlagRows(
                warning_local=res.first_warning,
                warning_global=jax.vmap(_gather_row)(sl_rows, res.first_warning),
                change_local=res.first_change,
                change_global=jax.vmap(_gather_row)(sl_rows, res.first_change),
                forced_retrain=forced,
            )
            write_at = jnp.where(active, st.ptr, i32(nbf))
            flags = FlagRows(*(
                lax.dynamic_update_slice_in_dim(full, part, write_at, 0)
                for full, part in zip(st.flags, slab)
            ))

            # Rotate state (C7 :207-210), from the first rotating batch.
            ne_cov = ne & (jnp.arange(w, dtype=i32) < adv)
            any_ne_cov = jnp.any(ne_cov)
            take_rot = active & any_rot
            upd = lambda new, old: _select(active, new, old)  # noqa: E731
            return _WinState(
                ptr=st.ptr + adv,
                params=upd(
                    _select(st.retrain & any_ne_cov, fitted, st.params),
                    st.params,
                ),
                ddm=upd(_select(any_rot, det.init(), new_ddm), st.ddm),
                a_X=_select(take_rot, sl_X[rpos], st.a_X),
                a_y=_select(take_rot, sl_y[rpos], st.a_y),
                a_w=_select(
                    take_rot, sl_valid[rpos].astype(jnp.float32), st.a_w
                ),
                retrain=jnp.where(
                    active & any_ne_cov, any_rot, st.retrain
                ),
                key=upd(key, st.key),
                flags=flags,
            )

        out = lax.while_loop(cond, body, st0)
        carry_out = LoopCarry(
            params=out.params,
            ddm=out.ddm,
            a_X=out.a_X,
            a_y=out.a_y,
            a_w=out.a_w,
            retrain=out.retrain,
            key=out.key,
        )
        return carry_out, jax.tree.map(lambda x: x[:nbf], out.flags)

    return span


def make_window_runner(
    model: Model,
    ddm_params: DDMParams,
    *,
    window: int = 16,
    shuffle: bool = False,
    retrain_error_threshold: float | None = None,
    detector=None,
):
    """Build ``run(batches: Batches, key) -> FlagRows`` for one partition.

    Output contract is identical to ``engine.loop.make_partition_runner``:
    ``FlagRows`` leaves of shape ``[NB - 1]`` (batch 0 seeds ``batch_a``).
    """
    det = resolve_detector(ddm_params, detector)
    span = make_window_span(
        model,
        ddm_params,
        window=window,
        shuffle=shuffle,
        retrain_error_threshold=retrain_error_threshold,
        detector=det,
    )

    def run(batches: Batches | IndexedBatches, key: jax.Array) -> FlagRows:
        indexed = isinstance(batches, IndexedBatches)
        key, k_init = jax.random.split(key)
        if indexed:
            a_X = batches.base_X[batches.idx[0].astype(jnp.int32)]
            a_y = batches.base_y[batches.idx[0].astype(jnp.int32)]
        else:
            a_X, a_y = batches.X[0], batches.y[0]
        carry = LoopCarry(
            params=model.init(k_init),
            ddm=det.init(),
            a_X=a_X,
            a_y=a_y,
            a_w=batches.valid[0].astype(jnp.float32),
            retrain=jnp.bool_(True),
            key=key,
        )
        rest = jax.tree.map(lambda x: x[1:], batches)
        if indexed:  # the replicated row table must not be sliced
            rest = rest._replace(base_X=batches.base_X, base_y=batches.base_y)
        _, flags = span(carry, rest)
        return flags

    return run
