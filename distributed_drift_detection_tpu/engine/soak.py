"""Device-native soak engine: the 1e9-row sustained-throughput config.

The BASELINE.json soak config ("synthetic SEA/HYPERPLANE generator, 1e9 rows")
is host-bound when fed the obvious way: generating SEA rows in NumPy costs
more than the detection loop itself (measured ~3× the device time), and every
row crosses the host→device link. The TPU-native fix is to move the
*generator* into the compiled program: each partition synthesises its own
microbatches in-jit (`jax.random` keyed by ``fold_in(key, batch_index)`` —
deterministic, replayable, chunk-free) and feeds them straight into the
detection step. Zero host traffic during the soak; the only transfer is the
final flag table.

This mirrors the reference's methodology boundary honestly: its Spark driver
also synthesises the stream in memory before the timed span
(``DDM_Process.py:38-55``), so generation is not part of the measured
workload there either — here it simply runs on device, where it is
effectively free against the detector's sequential latency.

Generators (per-row semantics match ``io.synth`` conceptually, not
bit-for-bit — device PRNG is threefry on (key, batch), host PRNG is
(seed, row) hashing):

* ``'sea'`` — Street & Kim (2001): features ~ U[0,10)³, label =
  ``f0 + f1 <= theta`` with the concept's theta cycling through the four SEA
  thresholds every ``drift_every`` rows (abrupt drift).
* ``'hyperplane'`` — rotating hyperplane: label = sign of ``w_c·x − 0.5·Σw_c``
  with per-concept weights redrawn every ``drift_every`` rows.
* ``'prototypes'`` (default) — the reference's own benchmark regime
  (``io.synth.rialto_like_xy``; the sorted-by-target CSV streams of C2 behave
  the same way): every concept is a fresh set of Gaussian class blobs, so a
  fitted classifier is near-perfect *within* a concept and its error rate
  spikes exactly at the planted boundary. This is the regime the reference's
  hyper-sensitive ``3/0.5/1.5`` DDM thresholds are tuned for — under steady
  nonzero error (e.g. SEA's irreducible ~5%) those thresholds fire on noise,
  in the reference just as here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import DDMParams
from ..models.base import Model
from .loop import (
    Batches,
    FlagRows,
    LoopCarry,
    make_partition_step,
    resolve_detector,
)

_SEA_THETAS = (8.0, 9.0, 7.0, 9.5)  # io.synth._SEA_THETAS


class SoakResult(NamedTuple):
    flags: FlagRows  # leaves [P, NB-1]
    rows_processed: int  # static: P * NB * B


def _sea_batch(key, rows, drift_every, features):
    u = jax.random.uniform(key, (rows.shape[0], 3))
    X = u * 10.0
    theta = jnp.asarray(_SEA_THETAS, jnp.float32)[
        (rows // drift_every) % len(_SEA_THETAS)
    ]
    y = (X[:, 0] + X[:, 1] <= theta).astype(jnp.int32)
    return X, y


def _hyperplane_batch(key, rows, drift_every, features, rotate_period=0):
    kx, _ = jax.random.split(key)
    X = jax.random.uniform(kx, (rows.shape[0], features))
    block = rows // drift_every
    # Per-concept weights, deterministic in the block id (same for every
    # batch of the concept): one uniform per (block, feature).
    def w_for(b):
        return jax.random.uniform(
            jax.random.fold_in(jax.random.key(7), b), (features,)
        )

    w = jax.vmap(w_for)(block)  # [B, F]
    if rotate_period:
        # Gradual drift (io.synth.hyperplane_chunk's rotation, made
        # f32-exact at 1e9-row scale): a smooth per-row rotation of the
        # weight vector on top of the abrupt per-concept redraws — the
        # "abrupt+gradual" soak regime of the BASELINE.json config. The
        # phase is reduced modulo the integer rotation period *before* the
        # float cast; a raw f32 global row index quantizes to 64-row steps
        # near 1e9 and would silently turn the gradual sweep into plateaus.
        frac = (rows % rotate_period).astype(jnp.float32) / rotate_period
        phase = (2.0 * jnp.pi) * frac[:, None]
        w = w + 0.3 * jnp.sin(phase + jnp.arange(features, dtype=jnp.float32))
    margin = jnp.sum(X * w, axis=1) - 0.5 * jnp.sum(w, axis=1)
    y = (margin > 0).astype(jnp.int32)
    return X, y


def _hyperplane_gradual_batch(key, rows, drift_every, features):
    # One full boundary rotation per concept: gradual within, abrupt across.
    return _hyperplane_batch(
        key, rows, drift_every, features, rotate_period=max(drift_every, 1)
    )


def _prototype_batch(key, rows, drift_every, features, classes=8, noise=0.08):
    kc, kn = jax.random.split(key)
    block = rows // drift_every
    # Per-concept class prototypes, deterministic in the block id.
    def protos_for(b):
        return jax.random.normal(
            jax.random.fold_in(jax.random.key(11), b), (classes, features)
        ) * 3.0

    protos = jax.vmap(protos_for)(block)  # [B, C, F]
    y = jax.random.randint(kc, (rows.shape[0],), 0, classes)
    X = jnp.take_along_axis(protos, y[:, None, None], axis=1)[:, 0]
    X = X + noise * jax.random.normal(kn, X.shape)
    return X, y.astype(jnp.int32)


_GENERATORS = {
    "sea": (_sea_batch, 3),
    "hyperplane": (_hyperplane_batch, 10),
    "hyperplane_gradual": (_hyperplane_gradual_batch, 10),
    "prototypes": (_prototype_batch, 8),
}


def make_soak_runner(
    model: Model,
    ddm_params: DDMParams = DDMParams(),
    *,
    partitions: int,
    per_batch: int,
    num_batches: int,
    drift_every: int,
    generator: str = "prototypes",
    features: int | None = None,
    mesh=None,
    detector=None,
    window: int = 1,
    chunk_batches: int = 0,
):
    """Build ``run(key) -> SoakResult``: the full soak as ONE device program.

    Each partition runs an independent ``num_batches``-long stream (contiguous
    rows, drift every ``drift_every`` rows); total workload is
    ``partitions * num_batches * per_batch`` rows with zero host feeding.
    ``jax.jit`` the result; flags come back as ``[P, NB-1]`` like every other
    engine (batch 0 seeds ``batch_a``). With ``mesh`` the partition axis is
    device-sharded (generation included — each device synthesises only its
    own partitions' rows); without it, jit the returned function yourself.

    ``window > 1`` runs the speculative window engine over device-generated
    chunks: a ``lax.scan`` over chunks of ``chunk_batches`` batches
    (default ``2·window``; generated in one vmapped shot, bounding the
    transient generator buffer), each processed by ``engine.window``'s span —
    cutting the sequential iteration count from ``NB`` to roughly
    ``NB/chunk_batches + NB/window + drifts``. Same flags as the sequential
    scan for deterministic-fit models (the window engine's exactness
    contract; keys split per window, so 'mlp' is seed-equivalent only).

    When it helps: small per-step workloads (small ``per_batch`` × few
    partitions), where the scan is iteration-latency-bound — the same regime
    the one-shot window engine accelerates ~W×. At the BASELINE.json soak
    geometry (64 partitions × 1000-row batches ≈ 64 k rows *per step*) each
    sequential step is already chunky and speculation only adds window
    slicing + drift-replay overhead: measured on one TPU chip at 1e8 rows,
    ``window=64`` runs ~0.6× the sequential engine's throughput. The
    benchmark therefore keeps ``window=1`` for the soak.
    """
    try:
        gen, default_f = _GENERATORS[generator]
    except KeyError:
        raise ValueError(
            f"unknown generator {generator!r}; expected one of {sorted(_GENERATORS)}"
        ) from None
    f = features or default_f
    b, nb, p = int(per_batch), int(num_batches), int(partitions)
    if p * nb * b > 2**31 - 1:
        # Global row positions are int32 framework-wide (FlagRows globals);
        # beyond 2^31 rows the indices would silently wrap. Split larger
        # soaks across multiple runs (fresh key each) instead.
        raise ValueError(
            f"soak of {p * nb * b:,} rows exceeds the int32 global-row-index "
            "range (2^31-1); run multiple soaks instead"
        )
    det = resolve_detector(ddm_params, detector)
    if window < 1:
        # window=0 means "auto" framework-wide (config.auto_window); the
        # soak could resolve it from drift_every but a caller wiring
        # RunConfig.window straight through should get the same loud
        # behaviour as engine.chunked, not a silent sequential fallback.
        raise ValueError(
            "window must be >= 1 for the soak engine (0 = auto is resolved "
            "by config.auto_window; pass an explicit width here)"
        )
    if chunk_batches < 0:
        raise ValueError(
            f"chunk_batches must be >= 0 (0 = auto), got {chunk_batches}"
        )
    if chunk_batches and window <= 1:
        raise ValueError(
            "chunk_batches only applies to the windowed soak (window > 1); "
            "the sequential scan does not chunk"
        )
    if window > 1:
        from .window import make_window_span

        span = make_window_span(
            model, ddm_params, window=window, shuffle=False, detector=det
        )
        cb = int(chunk_batches) or 2 * int(window)
    else:
        step = make_partition_step(
            model, ddm_params, shuffle=False, detector=det
        )

    def run_partition(part_idx: jax.Array, key: jax.Array) -> FlagRows:
        offset = part_idx.astype(jnp.int32) * (nb * b)
        gen_key, init_key = jax.random.split(key)

        def batch_at(t):
            rows = offset + t * b + jnp.arange(b, dtype=jnp.int32)
            X, y = gen(jax.random.fold_in(gen_key, t), rows, drift_every, f)
            return X, y, rows, jnp.ones(b, bool)

        X0, y0, _, v0 = batch_at(jnp.int32(0))
        carry = LoopCarry(
            params=model.init(init_key),
            ddm=det.init(),
            a_X=X0,
            a_y=y0,
            a_w=v0.astype(jnp.float32),
            retrain=jnp.bool_(True),
            key=key,
        )

        if window <= 1:
            def scan_step(c, t):
                return step(c, batch_at(t))

            _, flags = lax.scan(
                scan_step, carry, jnp.arange(1, nb, dtype=jnp.int32)
            )
            return flags

        # Window mode: generate CB batches per chunk in one vmapped shot and
        # run the speculative span over them; the carry crosses chunks
        # exactly as in engine.chunked. Batches past nb-1 (the last chunk's
        # tail) are invalid — inert in the span, flag rows stay −1.
        nbf = nb - 1
        num_chunks = -(-nbf // cb)

        def gen_chunk(ci):
            ts = 1 + ci * cb + jnp.arange(cb, dtype=jnp.int32)
            in_range = ts < nb
            X, y, rows, _ = jax.vmap(
                lambda t: batch_at(jnp.minimum(t, nb - 1))
            )(ts)
            valid = jnp.broadcast_to(in_range[:, None], (cb, b))
            rows = jnp.where(valid, rows, -1)
            return Batches(X, y, rows, valid)

        def chunk_body(c, ci):
            return span(c, gen_chunk(ci))

        _, flags = lax.scan(
            chunk_body, carry, jnp.arange(num_chunks, dtype=jnp.int32)
        )
        # [NC, CB] chunk-major flag rows → flat [NBF]
        return jax.tree.map(
            lambda x: x.reshape(num_chunks * cb, *x.shape[2:])[:nbf], flags
        )

    if mesh is not None:
        from ..models.base import require_shardable
        from ..parallel.mesh import partition_sharding

        require_shardable(model, mesh)
        sh = partition_sharding(mesh, p)
    else:
        sh = None

    def run(key: jax.Array) -> SoakResult:
        keys = jax.random.split(key, p)
        parts = jnp.arange(p)
        if sh is not None:
            keys = jax.lax.with_sharding_constraint(keys, sh)
            parts = jax.lax.with_sharding_constraint(parts, sh)
        flags = jax.vmap(run_partition)(parts, keys)
        return SoakResult(flags=flags, rows_processed=p * nb * b)

    if sh is not None:
        return jax.jit(
            run,
            out_shardings=SoakResult(
                flags=FlagRows(*(sh,) * len(FlagRows._fields)),
                rows_processed=None,
            ),
        )
    return run
