"""Device-native soak engine: the 1e9-row sustained-throughput config.

The BASELINE.json soak config ("synthetic SEA/HYPERPLANE generator, 1e9 rows")
is host-bound when fed the obvious way: generating SEA rows in NumPy costs
more than the detection loop itself (measured ~3× the device time), and every
row crosses the host→device link. The TPU-native fix is to move the
*generator* into the compiled program: each partition synthesises its own
microbatches in-jit (`jax.random` keyed by ``fold_in(key, batch_index)`` —
deterministic, replayable, chunk-free) and feeds them straight into the
detection step. Zero host traffic during the soak; the only transfer is the
final flag table.

This mirrors the reference's methodology boundary honestly: its Spark driver
also synthesises the stream in memory before the timed span
(``DDM_Process.py:38-55``), so generation is not part of the measured
workload there either — here it simply runs on device, where it is
effectively free against the detector's sequential latency.

Generators (per-row semantics match ``io.synth`` conceptually, not
bit-for-bit — device PRNG is threefry on (key, batch), host PRNG is
(seed, row) hashing):

* ``'sea'`` — Street & Kim (2001): features ~ U[0,10)³, label =
  ``f0 + f1 <= theta`` with the concept's theta cycling through the four SEA
  thresholds every ``drift_every`` rows (abrupt drift).
* ``'hyperplane'`` — rotating hyperplane: label = sign of ``w_c·x − 0.5·Σw_c``
  with per-concept weights redrawn every ``drift_every`` rows.
* ``'prototypes'`` (default) — the reference's own benchmark regime
  (``io.synth.rialto_like_xy``; the sorted-by-target CSV streams of C2 behave
  the same way): every concept is a fresh set of Gaussian class blobs, so a
  fitted classifier is near-perfect *within* a concept and its error rate
  spikes exactly at the planted boundary. This is the regime the reference's
  hyper-sensitive ``3/0.5/1.5`` DDM thresholds are tuned for — under steady
  nonzero error (e.g. SEA's irreducible ~5%) those thresholds fire on noise,
  in the reference just as here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import DDMParams
from ..models.base import Model
from .loop import (
    Batches,
    FlagRows,
    LoopCarry,
    make_partition_step,
    resolve_detector,
)

_SEA_THETAS = (8.0, 9.0, 7.0, 9.5)  # io.synth._SEA_THETAS


class SoakResult(NamedTuple):
    flags: FlagRows  # leaves [P, NB-1]
    rows_processed: int  # static: P * NB * B


def _sea_batch(key, rows, drift_every, features, block0=0):
    u = jax.random.uniform(key, (rows.shape[0], 3))
    X = u * 10.0
    theta = jnp.asarray(_SEA_THETAS, jnp.float32)[
        (block0 + rows // drift_every) % len(_SEA_THETAS)
    ]
    y = (X[:, 0] + X[:, 1] <= theta).astype(jnp.int32)
    return X, y


def _hyperplane_batch(key, rows, drift_every, features, rotate_period=0, block0=0):
    kx, _ = jax.random.split(key)
    X = jax.random.uniform(kx, (rows.shape[0], features))
    block = block0 + rows // drift_every
    # Per-concept weights, deterministic in the block id (same for every
    # batch of the concept): one uniform per (block, feature).
    def w_for(b):
        return jax.random.uniform(
            jax.random.fold_in(jax.random.key(7), b), (features,)
        )

    w = jax.vmap(w_for)(block)  # [B, F]
    if rotate_period:
        # Gradual drift (io.synth.hyperplane_chunk's rotation, made
        # f32-exact at 1e9-row scale): a smooth per-row rotation of the
        # weight vector on top of the abrupt per-concept redraws — the
        # "abrupt+gradual" soak regime of the BASELINE.json config. The
        # phase is reduced modulo the integer rotation period *before* the
        # float cast; a raw f32 global row index quantizes to 64-row steps
        # near 1e9 and would silently turn the gradual sweep into plateaus.
        frac = (rows % rotate_period).astype(jnp.float32) / rotate_period
        phase = (2.0 * jnp.pi) * frac[:, None]
        w = w + 0.3 * jnp.sin(phase + jnp.arange(features, dtype=jnp.float32))
    margin = jnp.sum(X * w, axis=1) - 0.5 * jnp.sum(w, axis=1)
    y = (margin > 0).astype(jnp.int32)
    return X, y


def _hyperplane_gradual_batch(key, rows, drift_every, features, block0=0):
    # One full boundary rotation per concept: gradual within, abrupt across.
    # The rotation phase uses `rows % rotate_period` directly, so chained
    # legs stay phase-continuous as long as leg boundaries are aligned to
    # drift_every (make_soak_chain enforces this).
    return _hyperplane_batch(
        key, rows, drift_every, features,
        rotate_period=max(drift_every, 1), block0=block0,
    )


def _prototype_batch(
    key, rows, drift_every, features, classes=8, noise=0.08, block0=0
):
    kc, kn = jax.random.split(key)
    block = block0 + rows // drift_every
    # Per-concept class prototypes, deterministic in the block id.
    def protos_for(b):
        return jax.random.normal(
            jax.random.fold_in(jax.random.key(11), b), (classes, features)
        ) * 3.0

    protos = jax.vmap(protos_for)(block)  # [B, C, F]
    y = jax.random.randint(kc, (rows.shape[0],), 0, classes)
    X = jnp.take_along_axis(protos, y[:, None, None], axis=1)[:, 0]
    X = X + noise * jax.random.normal(kn, X.shape)
    return X, y.astype(jnp.int32)


_GENERATORS = {
    "sea": (_sea_batch, 3),
    "hyperplane": (_hyperplane_batch, 10),
    "hyperplane_gradual": (_hyperplane_gradual_batch, 10),
    "prototypes": (_prototype_batch, 8),
}


def _mesh_sharding(model: Model, mesh, partitions: int):
    """Validated partition-axis sharding for a soak engine; ``None`` without
    a mesh. Shared by the one-shot runner and the chain so the sharding
    invariant (divisibility check + host-callback rejection) can't diverge
    between them."""
    if mesh is None:
        return None
    from ..models.base import require_shardable
    from ..parallel.mesh import partition_sharding

    require_shardable(model, mesh)
    return partition_sharding(mesh, partitions)


def resolve_soak_detector(ddm_params: DDMParams, detector, drift_every: int):
    """Detector for a soak engine: a :class:`DetectorKernel` passes through
    (``None`` → DDM, exactly ``resolve_detector``); a **name string** is
    built here, with Page–Hinkley's ``threshold = 0`` auto sentinel resolved
    from the soak's own drift geometry — ``drift_every`` *is* the
    per-partition concept length that ``config.auto_ph_threshold`` derives
    for api streams, so ``detector='ph'`` works out of the box on every soak
    entry point instead of tripping the kernels' unresolved-λ rejection
    (``ops.detectors.make_detector``). Non-default parameters still go the
    explicit route: build the kernel yourself with a concrete λ."""
    if isinstance(detector, str):
        from ..config import PHParams, auto_ph_threshold_rows
        from ..ops.detectors import make_detector

        ph = PHParams()
        if detector == "ph":
            ph = ph._replace(
                threshold=auto_ph_threshold_rows(float(drift_every))
            )
        return make_detector(detector, ddm=ddm_params, ph=ph)
    return resolve_detector(ddm_params, detector)


def make_soak_runner(
    model: Model,
    ddm_params: DDMParams = DDMParams(),
    *,
    partitions: int,
    per_batch: int,
    num_batches: int,
    drift_every: int,
    generator: str = "prototypes",
    features: int | None = None,
    mesh=None,
    detector=None,
    window: int = 1,
    chunk_batches: int = 0,
    rotations: int = 1,
    tenants: int = 1,
):
    """Build ``run(key) -> SoakResult``: the full soak as ONE device program.

    Each partition runs an independent ``num_batches``-long stream (contiguous
    rows, drift every ``drift_every`` rows); total workload is
    ``partitions * num_batches * per_batch`` rows with zero host feeding.
    ``jax.jit`` the result; flags come back as ``[P, NB-1]`` like every other
    engine (batch 0 seeds ``batch_a``). With ``mesh`` the partition axis is
    device-sharded (generation included — each device synthesises only its
    own partitions' rows); without it, jit the returned function yourself.

    ``window > 1`` runs the speculative window engine over device-generated
    chunks: a ``lax.scan`` over chunks of ``chunk_batches`` batches
    (default ``2·window``; generated in one vmapped shot, bounding the
    transient generator buffer), each processed by ``engine.window``'s span —
    cutting the sequential iteration count from ``NB`` to roughly
    ``NB/chunk_batches + NB/window + drifts``. ``rotations`` is that span's
    speculation depth (``engine.window.make_window_span``: commit up to R
    changes per step, shrinking the ``drifts`` term toward ``drifts/R``);
    it requires ``window > 1`` (rejected otherwise, like every other engine
    surface). Same flags as the sequential scan for deterministic-fit
    models (the window engine's exactness contract; keys split per
    window/level, so 'mlp' is seed-equivalent only).

    When it helps: small per-step workloads (small ``per_batch`` × few
    partitions), where the scan is iteration-latency-bound — the same regime
    the one-shot window engine accelerates ~W×. At the benchmark soak
    geometries (the former 64 × 1000 ≈ 64 k rows/step, and the r04 sweep
    optimum 128 × 2000 ≈ 256 k — bench.py ``_soak_stats``) each sequential
    step is already chunky and speculation only adds window slicing +
    drift-replay overhead: measured on one TPU chip at 1e8 rows,
    ``window=64`` runs ~0.6× the sequential engine's throughput. The
    benchmark therefore keeps ``window=1`` for the soak.

    ``tenants > 1`` widens the plane to ``T·P`` independent streams in the
    same single program (ROADMAP item 1): ``run(key)`` splits the key into
    T tenant keys first, and tenant t's P-partition block generates and
    detects exactly what a solo soak run with ``key =
    jax.random.split(key, T)[t]`` would — flags ``[T·P, NB-1]`` slice
    per-tenant bit-identically (tested). Total workload scales to
    ``T·P·NB·B`` rows; partition-local row positions (and hence the int32
    ceiling) are per-tenant, unchanged.
    """
    try:
        gen, default_f = _GENERATORS[generator]
    except KeyError:
        raise ValueError(
            f"unknown generator {generator!r}; expected one of {sorted(_GENERATORS)}"
        ) from None
    f = features or default_f
    b, nb, p = int(per_batch), int(num_batches), int(partitions)
    if p * nb * b > 2**31 - 1:
        # Global row positions are int32 framework-wide (FlagRows globals);
        # beyond 2^31 rows the indices would silently wrap. The chained soak
        # carries (params, detector state, batch_a, key) across legs with
        # exact single-stream semantics and partition-local positions.
        raise ValueError(
            f"soak of {p * nb * b:,} rows exceeds the int32 global-row-index "
            "range (2^31-1); use run_soak_chained / make_soak_chain"
        )
    det = resolve_soak_detector(ddm_params, detector, drift_every)
    if window < 1:
        # window=0 means "auto" framework-wide (config.auto_window); the
        # soak could resolve it from drift_every but a caller wiring
        # RunConfig.window straight through should get the same loud
        # behaviour as engine.chunked, not a silent sequential fallback.
        raise ValueError(
            "window must be >= 1 for the soak engine (0 = auto is resolved "
            "by config.auto_window; pass an explicit width here)"
        )
    if chunk_batches < 0:
        raise ValueError(
            f"chunk_batches must be >= 0 (0 = auto), got {chunk_batches}"
        )
    if chunk_batches and window <= 1:
        raise ValueError(
            "chunk_batches only applies to the windowed soak (window > 1); "
            "the sequential scan does not chunk"
        )
    if window <= 1 and rotations != 1:
        raise ValueError(
            "rotations only applies to the window engine (window > 1)"
        )
    if window > 1:
        from .window import make_window_span

        span = make_window_span(
            model, ddm_params, window=window, shuffle=False, detector=det,
            rotations=rotations,
        )
        cb = int(chunk_batches) or 2 * int(window)
    else:
        step = make_partition_step(
            model, ddm_params, shuffle=False, detector=det
        )

    def run_partition(part_idx: jax.Array, key: jax.Array) -> FlagRows:
        offset = part_idx.astype(jnp.int32) * (nb * b)
        gen_key, init_key = jax.random.split(key)

        def batch_at(t):
            rows = offset + t * b + jnp.arange(b, dtype=jnp.int32)
            X, y = gen(jax.random.fold_in(gen_key, t), rows, drift_every, f)
            return X, y, rows, jnp.ones(b, bool)

        X0, y0, _, v0 = batch_at(jnp.int32(0))
        carry = LoopCarry(
            params=model.init(init_key),
            ddm=det.init(),
            a_X=X0,
            a_y=y0,
            a_w=v0.astype(jnp.float32),
            retrain=jnp.bool_(True),
            key=key,
        )

        if window <= 1:
            def scan_step(c, t):
                return step(c, batch_at(t))

            _, flags = lax.scan(
                scan_step, carry, jnp.arange(1, nb, dtype=jnp.int32)
            )
            return flags

        # Window mode: generate CB batches per chunk in one vmapped shot and
        # run the speculative span over them; the carry crosses chunks
        # exactly as in engine.chunked. Batches past nb-1 (the last chunk's
        # tail) are invalid — inert in the span, flag rows stay −1.
        nbf = nb - 1
        num_chunks = -(-nbf // cb)

        def gen_chunk(ci):
            ts = 1 + ci * cb + jnp.arange(cb, dtype=jnp.int32)
            in_range = ts < nb
            X, y, rows, _ = jax.vmap(
                lambda t: batch_at(jnp.minimum(t, nb - 1))
            )(ts)
            valid = jnp.broadcast_to(in_range[:, None], (cb, b))
            rows = jnp.where(valid, rows, -1)
            return Batches(X, y, rows, valid)

        def chunk_body(c, ci):
            return span(c, gen_chunk(ci))

        _, flags = lax.scan(
            chunk_body, carry, jnp.arange(num_chunks, dtype=jnp.int32)
        )
        # [NC, CB] chunk-major flag rows → flat [NBF]
        return jax.tree.map(
            lambda x: x.reshape(num_chunks * cb, *x.shape[2:])[:nbf], flags
        )

    if tenants < 1:
        raise ValueError(f"tenants must be >= 1, got {tenants}")
    t_count = int(tenants)
    sh = _mesh_sharding(model, mesh, p * t_count)

    def run(key: jax.Array) -> SoakResult:
        if t_count == 1:
            keys = jax.random.split(key, p)
            parts = jnp.arange(p)
        else:
            # Tenant t's block == the solo soak run keyed by
            # split(key, T)[t]: same per-partition keys, same
            # partition-local offsets, bit-identical per-tenant flags.
            tkeys = jax.random.split(key, t_count)
            keys = jax.vmap(lambda k: jax.random.split(k, p))(
                tkeys
            ).reshape((t_count * p,))
            parts = jnp.tile(jnp.arange(p), t_count)
        if sh is not None:
            keys = jax.lax.with_sharding_constraint(keys, sh)
            parts = jax.lax.with_sharding_constraint(parts, sh)
        flags = jax.vmap(run_partition)(parts, keys)
        return SoakResult(flags=flags, rows_processed=t_count * p * nb * b)

    if sh is not None:
        return jax.jit(
            run,
            out_shardings=SoakResult(
                flags=FlagRows(*(sh,) * len(FlagRows._fields)),
                rows_processed=None,
            ),
        )
    return run


# --------------------------------------------------------------------------
# Chained soak: beyond the int32 row-index ceiling with exact semantics
# --------------------------------------------------------------------------


class SoakChainState(NamedTuple):
    """Cross-leg carry of the chained soak.

    ``carry`` is the vmapped :class:`LoopCarry` ([P] leading axes) — model
    params, detector state, ``batch_a`` and the engine's PRNG key flow
    across legs exactly as they flow across batches inside one leg, so a
    chained soak is semantically ONE long stream, not S independent ones.
    ``gen_keys`` [P] are the per-partition *generator* keys, kept separate
    from the loop key because the engine step advances ``carry.key`` every
    batch (``engine.loop:134``) while the generator must stay replayable
    from the absolute batch index.
    """

    carry: LoopCarry
    gen_keys: jax.Array  # [P]


class SoakLegFlags(NamedTuple):
    state: SoakChainState
    flags: FlagRows  # [P, L] (leg 0: [P, L-1] — batch 0 seeds batch_a)


def _make_soak_chain_impl(
    model: Model,
    ddm_params: DDMParams = DDMParams(),
    *,
    partitions: int,
    per_batch: int,
    batches_per_leg: int,
    legs: int,
    drift_every: int,
    generator: str = "prototypes",
    features: int | None = None,
    detector=None,
    mesh=None,
    donate: bool = False,
):
    """Build the state-carrying chained soak (impl form — use
    :func:`make_soak_chain` for the bound ``(first_leg, next_leg)`` pair).

    Lifts the one-shot runner's int32 global-row ceiling (``p·nb·b ≤ 2³¹−1``)
    by splitting the stream into ``legs`` device programs of
    ``batches_per_leg`` batches each, with the full detection state —
    ``(model params, detector state, batch_a, loop key)`` — carried across
    legs host-side. Row indices inside a leg are **partition-local** stream
    positions (``< legs·batches_per_leg·per_batch``, which must fit int32 —
    at 64 partitions that is a ~1.4e11-row total ceiling); the generator
    receives the cross-partition concept offset separately as a block id
    (``block0``), so concept identities and boundaries are exactly those of
    the equivalent unchained stream.

    Exactness contract (tested in ``tests/test_soak.py``): with the same
    total geometry and leg boundaries aligned to ``drift_every`` (enforced:
    ``batches_per_leg·per_batch % drift_every == 0`` — also what keeps
    ``position % drift_every`` delay arithmetic and the gradual-rotation
    phase leg-invariant), the concatenated chained flag rows equal the
    one-shot runner's bit-for-bit, modulo the partition row offset
    (one-shot rows are global, chain rows partition-local; both key the
    generator by absolute batch index, ``fold_in(gen_key, s·L + t)``).

    * ``first_leg(key) -> SoakLegFlags`` — seeds ``batch_a`` from batch 0,
      returns flags ``[P, L-1]``.
    * ``next_leg(state, leg_idx) -> SoakLegFlags`` — processes all L batches
      of leg ``leg_idx`` (traced scalar: one executable serves every leg),
      returns flags ``[P, L]``.

    Sequential engine only (``window=1``): at soak geometry each sequential
    step is already chunky and speculation loses (see
    :func:`make_soak_runner`'s window note). ``jax.jit`` both returns.

    ``mesh`` shards the partition axis across devices exactly like every
    other engine (the one-shot soak's pattern: generation included, each
    device synthesises only its own partitions' rows; state and flag
    outputs come back partition-sharded, so the carried chain state never
    gathers to one device between legs).

    ``donate`` donates the incoming chain state to each ``next_leg``
    dispatch (``donate_argnums``): the output state aliases it leaf-for-
    leaf, so the carried pytree is updated in place instead of doubling
    per leg. Off by default on this impl surface — a caller holding the
    public :func:`make_soak_chain` pair may legitimately reuse a state
    (A/B two continuations) — and on in :func:`run_soak_chained`, whose
    driver provably consumes each state exactly once.
    """
    try:
        gen, default_f = _GENERATORS[generator]
    except KeyError:
        raise ValueError(
            f"unknown generator {generator!r}; expected one of {sorted(_GENERATORS)}"
        ) from None
    f = features or default_f
    b, L, p, S = int(per_batch), int(batches_per_leg), int(partitions), int(legs)
    de = int(drift_every)
    if L * b % de:
        raise ValueError(
            f"leg length {L}·{b} rows must be a multiple of drift_every={de} "
            "(keeps concept ids, delay arithmetic and rotation phase exact "
            "across leg boundaries)"
        )
    t_pp = S * L * b  # per-partition stream length
    if t_pp > 2**31 - 1:
        raise ValueError(
            f"per-partition stream of {t_pp:,} rows exceeds int32 positions; "
            "raise `partitions` (the ceiling scales with it)"
        )
    total_blocks = p * (t_pp // de)
    if total_blocks > 2**31 - 1:
        # block0s carries per-partition concept offsets as int32; the last
        # partition's ids reach p·blocks_pp and would wrap silently.
        raise ValueError(
            f"{total_blocks:,} total concept blocks exceed int32 ids; "
            "raise `drift_every` or lower `partitions`"
        )
    det = resolve_soak_detector(ddm_params, detector, drift_every)
    step = make_partition_step(model, ddm_params, shuffle=False, detector=det)
    # Per-partition concept-block offsets. Passed into the jitted legs as a
    # RUNTIME argument, not baked as a constant: blocks_pp depends on the
    # leg count S, and baking it would give every S a different executable —
    # defeating warm-up/AOT and the persistent compile cache.
    blocks_pp = t_pp // de
    block0s = jnp.arange(p, dtype=jnp.int32) * blocks_pp

    def batch_at(gen_key, block0, t_glob):
        # Partition-local position; concept id = block0 + pos // drift_every.
        pos = t_glob * b + jnp.arange(b, dtype=jnp.int32)
        X, y = gen(
            jax.random.fold_in(gen_key, t_glob), pos, de, f, block0=block0
        )
        return X, y, pos, jnp.ones(b, bool)

    def first_one(key, block0):
        gen_key, init_key = jax.random.split(key)
        X0, y0, _, v0 = batch_at(gen_key, block0, jnp.int32(0))
        carry = LoopCarry(
            params=model.init(init_key),
            ddm=det.init(),
            a_X=X0,
            a_y=y0,
            a_w=v0.astype(jnp.float32),
            retrain=jnp.bool_(True),
            key=key,
        )

        def scan_step(c, t):
            return step(c, batch_at(gen_key, block0, t))

        carry, flags = lax.scan(
            scan_step, carry, jnp.arange(1, L, dtype=jnp.int32)
        )
        return carry, gen_key, flags

    def next_one(carry, gen_key, block0, leg_idx):
        t0 = leg_idx.astype(jnp.int32) * L

        def scan_step(c, t):
            return step(c, batch_at(gen_key, block0, t0 + t))

        carry, flags = lax.scan(
            scan_step, carry, jnp.arange(L, dtype=jnp.int32)
        )
        return carry, flags

    sh = _mesh_sharding(model, mesh, p)

    def _constrain(x):
        return lax.with_sharding_constraint(x, sh) if sh is not None else x

    def first_leg_impl(key: jax.Array, block0s: jax.Array) -> SoakLegFlags:
        keys = _constrain(jax.random.split(key, p))
        carry, gen_keys, flags = jax.vmap(first_one)(keys, _constrain(block0s))
        return SoakLegFlags(SoakChainState(carry, gen_keys), flags)

    def next_leg_impl(
        state: SoakChainState, leg_idx: jax.Array, block0s: jax.Array
    ) -> SoakLegFlags:
        carry, flags = jax.vmap(next_one, in_axes=(0, 0, 0, None))(
            state.carry, state.gen_keys, _constrain(block0s), leg_idx
        )
        return SoakLegFlags(SoakChainState(carry, state.gen_keys), flags)

    # Every output leaf carries a leading partition axis, so one sharding
    # broadcasts as the out_shardings prefix for the whole SoakLegFlags tree.
    jit_kw = {} if sh is None else {"out_shardings": sh}
    # Only the state is donated — leg_idx is a scalar and block0s is the
    # shared offset vector reused by every leg. Donation is single-device
    # only for now: with a mesh, XLA's input/output aliasing pass rejects
    # the sharded rank-2 PRNG-key-data leaves of the carried state
    # ("tile assignment dimensions != input rank", jax 0.4.x), so sharded
    # chains keep the copy-on-carry semantics — the donation win targets
    # the single-chip bench path, where the whole state is one device's.
    next_kw = dict(jit_kw)
    if donate and sh is None:
        next_kw["donate_argnums"] = (0,)
    return _SoakChainImpl(
        first=jax.jit(first_leg_impl, **jit_kw),
        next=jax.jit(next_leg_impl, **next_kw),
        block0s=block0s,
    )


class _SoakChainImpl(NamedTuple):
    """Jitted chain legs with the block-offset vector as a runtime arg
    (see :func:`make_soak_chain` for why it is not a baked constant)."""

    first: object  # jit: (key, block0s) -> SoakLegFlags
    next: object  # jit: (state, leg_idx, block0s) -> SoakLegFlags
    block0s: jax.Array  # [P] i32


def make_soak_chain(*args, **kwargs):
    """Public form of :func:`_make_soak_chain_impl`: ``(first_leg, next_leg)``
    with the block offsets bound — ``first_leg(key)``,
    ``next_leg(state, leg_idx)``."""
    impl = _make_soak_chain_impl(*args, **kwargs)

    def first_leg(key: jax.Array) -> SoakLegFlags:
        return impl.first(key, impl.block0s)

    def next_leg(state: SoakChainState, leg_idx) -> SoakLegFlags:
        return impl.next(state, jnp.int32(leg_idx), impl.block0s)

    return first_leg, next_leg


def _materialize_like(sds):
    """A zero-filled concrete array matching a ``ShapeDtypeStruct`` — the
    structural template ``utils.checkpoint.load_checkpoint`` needs, built
    without executing a leg. Typed PRNG keys are wrapped from zero key
    data (the checkpoint stores keys as key data, so impl must only match
    the default)."""
    if jnp.issubdtype(sds.dtype, jax.dtypes.prng_key):
        impl = jax.random.key_impl(jax.random.key(0))
        data_shape = jax.eval_shape(jax.random.key_data, sds).shape
        return jax.random.wrap_key_data(
            jnp.zeros(data_shape, jnp.uint32), impl=impl
        )
    return jnp.zeros(sds.shape, sds.dtype)


def _key_fingerprint(key: jax.Array) -> str:
    """Stable hex fingerprint of a typed PRNG key (checkpoint geometry
    field): same key data → same string across processes and rounds."""
    import hashlib

    import numpy as np

    data = np.asarray(jax.random.key_data(key))
    return hashlib.sha256(
        data.tobytes() + str(data.shape).encode()
    ).hexdigest()[:16]


def planted_interior_boundaries(
    partitions: int, rows_per_partition: int, drift_every: int
) -> int:
    """Exact count of detectable planted boundaries across the soak.

    Partition ``q`` covers global rows ``[q·R, (q+1)·R)``; a boundary at
    ``m·drift_every`` is detectable only strictly inside that half-open
    range (a boundary landing exactly on a partition start *begins* its
    stream — there is no preceding concept to drift from).
    """
    r, de = int(rows_per_partition), int(drift_every)
    return sum(
        ((q + 1) * r - 1) // de - (q * r) // de for q in range(int(partitions))
    )


class ChainedSoakSummary(NamedTuple):
    rows_processed: int  # p · legs · batches_per_leg · per_batch (executed)
    legs: int
    detections: int
    delays: "object"  # np.ndarray i64: position % drift_every per detection
    planted_boundaries: int  # detectable (strictly-interior) boundaries
    exec_time_s: float  # execution span only (legs AOT-compiled before it)
    # The caller's total_rows before rounding up to whole aligned legs;
    # rows_processed >= requested_rows, and throughput is computed over the
    # executed count (ADVICE r2: surface the distinction, don't hide it).
    requested_rows: int = 0


def run_soak_chained(
    model: Model,
    ddm_params: DDMParams = DDMParams(),
    *,
    partitions: int,
    per_batch: int,
    total_rows: int,
    drift_every: int,
    max_leg_rows: int = 2**30,
    generator: str = "prototypes",
    features: int | None = None,
    detector=None,
    mesh=None,
    key=None,
    on_leg=None,
    checkpoint_path: str = "",
    telemetry=None,
    metrics=None,
    donate: bool = True,
    collect_every: int = 1,
    compile_cache_dir: str = "",
) -> ChainedSoakSummary:
    """Host driver over :func:`make_soak_chain`: run ≥ ``total_rows`` rows.

    Sizes legs to ``≤ max_leg_rows`` rounded to the drift alignment, runs
    them back to back with the carried state, and folds each leg's flag
    table into scalar detection statistics host-side (the full 1e10-row flag
    table is never materialised). ``on_leg(leg_idx, flags)`` is an optional
    observer (``flags.change_global`` arrives host-converted — the driver's
    own d2h is reused, so observers don't pay a second transfer). Rounds the row count *up* to a whole number of aligned legs.

    Both leg executables are AOT-compiled (``.lower().compile()``) before
    the measured span — ``exec_time_s`` in the summary covers execution and
    host-side flag folding only, never compilation, regardless of leg count
    (the block-offset vector is a runtime argument precisely so one
    executable serves every chain length).

    ``checkpoint_path`` turns on crash recovery for long chains (aux
    subsystem, SURVEY.md §5 — strictly more than the reference's re-run-
    everything story): after every completed leg, the full chain state (the
    carried :class:`SoakChainState` pytree) plus accumulated detection
    statistics are written atomically to the path; a rerun with the *same
    geometry* resumes at the first unfinished leg and returns the same
    summary an uninterrupted run would (tested), with ``exec_time_s``
    covering only the resumed span. A geometry mismatch (different leg
    sizing, generator, drift spacing, or detector name/parameters) fails
    loudly rather than resuming garbage. ``on_leg`` fires *before* a leg's
    checkpoint is written — at-least-once delivery: a crash inside the
    observer re-runs that leg (and re-delivers its flags) on resume. The
    file is removed on successful completion.

    ``telemetry`` (a :class:`..telemetry.events.EventLog`) emits one
    ``leg_completed`` progress event per leg — extracted from the leg's
    already-host-converted flag table, so multi-minute chains are visible
    mid-flight from the persisted log — followed by one ``heartbeat``
    (``rows_done`` = stream-absolute progress, checkpointed legs included,
    so the ``watch`` CLI's percent/ETA survive a resume; ``elapsed_s`` =
    monotonic seconds since THIS process started executing legs — watch
    computes rates from heartbeat *deltas*, so the resumed-offset mismatch
    between the two cannot inflate throughput). Same at-least-once
    semantics as ``on_leg`` (events fire before the leg's checkpoint
    lands).

    ``metrics`` (a :class:`..telemetry.metrics.MetricsRegistry`) records a
    per-leg device-memory snapshot (``device_bytes_in_use{when="leg"}``
    latest point + ``device_peak_bytes_in_use`` max across legs —
    telemetry.profile): a chain whose HBM footprint creeps leg over leg is
    visible in the export, not just at the OOM. Cheap host call, no device
    sync, no-op where the backend reports nothing; it does run inside
    ``exec_time_s`` (the driver's own per-leg d2h already syncs there) —
    the same opt-in observability trade as ``telemetry``.

    ``donate`` (default True) donates the carried chain state to each leg
    dispatch — the state is updated in place on device instead of doubled
    per leg; this driver consumes each state exactly once (the checkpoint
    copies to host *before* the next dispatch), so donation is safe here
    where it is opt-in on the raw :func:`make_soak_chain` surface. Flags
    are bit-identical either way (tested).

    ``collect_every`` (default 1 = the historical per-leg cadence) defers
    the host-side flag folding — and with it the per-leg device sync, the
    ``on_leg``/telemetry deliveries and the checkpoint write — to every
    N-th leg boundary (and always the last), so the dispatch queue stays
    full across a group of legs. Deliveries inside a group arrive in leg
    order at the boundary; a crash mid-group resumes from the last group
    boundary (the at-least-once contract, with the group as the unit).

    ``compile_cache_dir`` points jax's persistent compilation cache at the
    directory (``utils.compile_cache``) before the legs AOT-compile, so a
    *restarted* chain — the checkpoint-resume path — skips XLA compilation
    entirely ('' = leave the process's cache config as is).
    """
    import math
    import os
    import time

    import numpy as np

    from ..utils.checkpoint import load_checkpoint, save_checkpoint

    if compile_cache_dir:
        from ..utils.compile_cache import enable_persistent_cache

        enable_persistent_cache(compile_cache_dir)

    b, p, de = int(per_batch), int(partitions), int(drift_every)
    # Leg length in batches: smallest multiple of the concept alignment
    # (L·b ≡ 0 mod drift_every ⇔ L ≡ 0 mod de/gcd(de, b)), capped by
    # max_leg_rows.
    # Resolve once up front (names → kernels, PH auto-λ from drift geometry)
    # so the legs and the checkpoint-geometry record can't disagree about
    # the detector's concrete parameters.
    detector = resolve_soak_detector(ddm_params, detector, de)
    align_b = de // math.gcd(de, b)
    nb_total = max(-(-int(total_rows) // (p * b)), 2)
    L = max(int(max_leg_rows) // (p * b), align_b)
    L -= L % align_b
    L = min(L, -(-nb_total // align_b) * align_b)
    S = max(-(-nb_total // L), 1)

    impl = _make_soak_chain_impl(
        model,
        ddm_params,
        partitions=p,
        per_batch=b,
        batches_per_leg=L,
        legs=S,
        drift_every=de,
        generator=generator,
        features=features,
        detector=detector,
        mesh=mesh,
        donate=donate,
    )
    if key is None:
        key = jax.random.key(0)

    state_sh = jax.eval_shape(impl.first, key, impl.block0s).state
    first_c = impl.first.lower(key, impl.block0s).compile()
    next_c = None
    if S > 1:
        next_c = impl.next.lower(state_sh, jnp.int32(0), impl.block0s).compile()

    geometry = {
        "p": p, "b": b, "L": L, "S": S, "de": de,
        "generator": generator,
        # Name AND full parameter tuple: shapes alone can't tell a resumed
        # chain that its detector thresholds changed between runs.
        "detector": detector.name,
        "detector_params": [float(v) for v in detector.params],
        # PRNG key fingerprint (ADVICE r2): a stale checkpoint at the same
        # path must not silently continue a *different* seed's stream —
        # resuming replays the checkpointed carry, so without this a caller
        # passing a new `key` would get old-seed results with no warning.
        "key_fp": _key_fingerprint(key),
    }
    detections, delays, start_leg, state = 0, [], 0, None
    if checkpoint_path and os.path.exists(checkpoint_path):
        template = jax.tree.map(_materialize_like, state_sh)
        state, meta = load_checkpoint(checkpoint_path, template)
        got = {k: meta.get(k) for k in geometry}
        # Migration shim: EDDMParams grew a trailing `paper_exact` field in
        # r04 (default False = 0.0, bit-identical flags to the pre-r04
        # kernel), so an eddm checkpoint recording the old 3-float tuple is
        # the SAME chain when the current run keeps the default — accept it
        # rather than misdiagnosing a geometry mismatch and discarding
        # completed legs.
        if (
            geometry["detector"] == "eddm"
            and got.get("detector_params") == geometry["detector_params"][:3]
            and geometry["detector_params"][3:] == [0.0]
        ):
            got["detector_params"] = geometry["detector_params"]
        if got != geometry:
            # A genuine geometry difference is the primary diagnosis; only
            # when geometry matches and solely the fingerprint is absent is
            # this a legacy (pre-key_fp) checkpoint — whose key is
            # unknowable, so it cannot be safely resumed under the
            # fail-loudly-on-seed-change contract.
            non_key = {k: v for k, v in got.items() if k != "key_fp"}
            if non_key == {
                k: v for k, v in geometry.items() if k != "key_fp"
            } and got.get("key_fp") is None:
                raise ValueError(
                    f"checkpoint {checkpoint_path} predates the PRNG-key "
                    "fingerprint field and cannot be verified against this "
                    "run's key; delete it to restart the chain"
                )
            raise ValueError(
                f"checkpoint {checkpoint_path} was written by a different "
                f"chain geometry ({got} != {geometry}); delete it or match "
                "the original configuration"
            )
        start_leg = int(meta["next_leg"])
        detections = int(meta["detections"])
        if meta["delays"]:
            delays.append(np.asarray(meta["delays"], np.int64))

    from ..resilience import faults

    start = time.perf_counter()
    hb_start = time.monotonic()  # heartbeat clock: step-proof liveness
    out = None
    group = max(int(collect_every), 1)
    pending: list = []  # (leg_idx, SoakLegFlags) awaiting the group boundary

    def _fold_pending():
        """Group-boundary host work: fold each pending leg's flags into the
        detection stats and fire its observers, in leg order — the only
        device syncs of the drive loop."""
        nonlocal detections
        for ls, lo in pending:
            cg = np.asarray(lo.flags.change_global)
            hit = cg[cg >= 0]
            detections += int(hit.size)
            if hit.size:
                delays.append(hit.astype(np.int64) % de)
            # Observer BEFORE the checkpoint marks the group complete: a
            # crash inside on_leg re-runs the group on resume and delivers
            # its flags again (at-least-once; a post-checkpoint crash would
            # silently drop them, as the checkpoint does not carry flag
            # tables). change_global is handed over host-converted (the
            # driver already paid that d2h for its own folding) so
            # observers reading it don't re-transfer inside the span.
            if on_leg is not None:
                on_leg(ls, lo.flags._replace(change_global=cg))
            if telemetry is not None:
                # rows counts the leg's full consumption (leg 0's batch_a
                # seed included), so legs sum to the summary's
                # rows_processed.
                telemetry.emit(
                    "leg_completed", leg=ls, rows=p * L * b,
                    detections=int(hit.size),
                )
                # rows_done is stream-absolute ((s+1) whole legs, resumed
                # ones included); elapsed is this process's monotonic span
                # — see the docstring for why the pair is safe across
                # resumes.
                telemetry.emit(
                    "heartbeat",
                    rows_done=(ls + 1) * p * L * b,
                    elapsed_s=time.monotonic() - hb_start,
                    leg=ls,
                )
            if metrics is not None:
                from ..telemetry.profile import (
                    device_memory_stats,
                    record_device_memory_gauges,
                )

                record_device_memory_gauges(
                    metrics, device_memory_stats(), when="leg"
                )
        pending.clear()

    for s in range(start_leg, S):
        # Fault-injection site (resilience.faults; no-op unless armed):
        # kill the chain before leg `s` executes — the kill-and-resume
        # tests arm this to prove a resumed chain's flags are bit-
        # identical to an uninterrupted run's.
        faults.fire("soak.leg", leg=s)
        if s == 0:
            out = first_c(key, impl.block0s)
        else:
            # With donate=True the incoming state is consumed here — it
            # was either just produced (and checkpoint-copied at the last
            # boundary) or loaded from the checkpoint, never reused.
            out = next_c(
                (state if out is None else out.state), jnp.int32(s), impl.block0s
            )
        pending.append((s, out))
        if len(pending) < group and s != S - 1:
            continue  # dispatch queue stays full across the group
        _fold_pending()
        if checkpoint_path:
            # save_checkpoint is atomic (same-dir temp + os.replace +
            # fsync — utils.checkpoint), so a crash mid-save can tear
            # only the temp file, never the last good checkpoint. The
            # host copy it takes happens BEFORE the next leg's dispatch
            # donates these state buffers.
            save_checkpoint(
                checkpoint_path,
                out.state,
                meta={
                    **geometry,
                    "next_leg": s + 1,
                    "detections": detections,
                    "delays": np.concatenate(delays).tolist() if delays else [],
                },
            )
    exec_time = time.perf_counter() - start
    if checkpoint_path and os.path.exists(checkpoint_path):
        os.remove(checkpoint_path)

    t_pp = S * L * b
    return ChainedSoakSummary(
        rows_processed=p * t_pp,
        legs=S,
        detections=detections,
        delays=(
            np.concatenate(delays) if delays else np.empty(0, np.int64)
        ),
        planted_boundaries=planted_interior_boundaries(p, t_pp, de),
        exec_time_s=exec_time,
        requested_rows=int(total_rows),
    )
