"""Chunked streaming execution for unbounded streams.

The batch API (``api.run``) materialises the whole stream on device — fine up
to the reference's 2 M-row scale, impossible for the BASELINE.json soak
config (1e9 rows). This module runs the same compiled loop **incrementally**:
the stream arrives in fixed-shape chunks of microbatches, the loop carry
(model params, DDM state, batch_a, retrain flag, PRNG key) flows across
chunks, and JAX's asynchronous dispatch double-buffers host→device transfer
of chunk N+1 against compute of chunk N (the "host-feed bandwidth" hard part
of SURVEY.md §7).

The carry is also the **checkpoint surface** (SURVEY.md §5 checkpoint/resume):
a few KB per partition — see ``utils/checkpoint.py`` and
:meth:`ChunkedDetector.save` / :meth:`ChunkedDetector.restore`.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import AUTO_RETRAIN_THRESHOLD, RETRAIN_AUTO, DDMParams
from ..models.base import Model
from .loop import (
    Batches,
    FlagRows,
    LoopCarry,
    make_partition_step,
    resolve_detector,
)


class ChunkResult(NamedTuple):
    flags: FlagRows  # leaves [P, chunk_batches]
    chunk_index: int


# One-time, process-wide ignore of jax's "Some donated buffers were not
# usable" warning: the donated chunk planes mostly cannot alias the (much
# smaller) flag outputs, so jax flags them per dispatch — but the donation
# still frees them at consumption, which is the point (documented trade).
# Installed at MODULE IMPORT, not per construction or per feed: a filter
# installed inside a running test is discarded by pytest's per-test
# warning-state save/restore (leaving a "was installed" latch stale), and
# a per-feed warnings.catch_warnings context mutates process-global state
# on the hot path and is not thread-safe against the prefetch producer
# thread. Import happens once, outside any test context, so this survives.
import warnings as _warnings

_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)
del _warnings


# Default chunk-group size for host-side flag collection on a *telemetered*
# drain (ChunkedDetector.run collect_every=0): before r06, per-chunk
# telemetry collected each chunk's flag table host-side as a side effect,
# which bounded the device-resident backlog at one chunk; the scalar-count
# events keep the tables deferred, so this group bound replaces it — small
# enough that a long stream never accumulates unbounded device flags, large
# enough that the dispatch queue stays full across the group.
DEFAULT_TELEMETRY_COLLECT_EVERY = 8


def _chunk_sig(chunk: Batches) -> tuple:
    """Shape/dtype signature of a chunk — the AOT-executable lookup key
    (:meth:`ChunkedDetector.prepare`). The carry's avals are fixed for a
    detector's lifetime, so the chunk signature alone identifies the
    compiled program."""
    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves(chunk)
    )


class ChunkedDetector:
    """Stateful driver around the jitted per-chunk scan.

    All chunks must share the shape ``[P, CB, B]`` (+ feature dim); the first
    chunk's first microbatch seeds ``batch_a`` (the reference consumes
    ``batches[0]`` the same way, ``DDM_Process.py:187``).
    """

    def __init__(
        self,
        model: Model,
        ddm_params: DDMParams = DDMParams(),
        *,
        partitions: int,
        shuffle: bool = False,
        retrain_error_threshold: float | None = RETRAIN_AUTO,
        seed: int = 0,
        window: int = 1,
        mesh=None,
        detector=None,
        rotations: int = 1,
        validate: bool = False,
        donate: bool = True,
        tenants: int = 1,
        tenant_seeds=None,
        on_drift=None,
    ):
        # ``shuffle`` here is the *in-jit* per-batch shuffle; the preferred
        # (device-free and api.run-compatible) route is stripe-time shuffling:
        # pass ``config.host_shuffle_seed(cfg)`` as the feeder's
        # ``shuffle_seed`` and leave this False. In-jit shuffle exists for
        # feeders that cannot pre-shuffle.
        #
        # ``window > 1`` runs each chunk through the speculative window
        # engine (``engine.window.make_window_span``) — the carry crosses
        # chunk boundaries identically, windows never span a boundary, and
        # flags are bit-identical for deterministic-fit models with
        # host-side shuffling (shuffle=False here + the feeder's
        # shuffle_seed); with the in-jit shuffle the PRNG streams differ
        # (keys split per window vs per batch). ``rotations`` is the window
        # engine's speculation depth (make_window_span) — same exactness
        # contract, fewer sequential steps per drift; requires window > 1
        # (rejected otherwise, matching parallel.mesh.make_mesh_runner).
        # RETRAIN_AUTO (any negative value): same per-family saturation-guard
        # resolution as api.prepare, driven by the model-spec flag
        # (Model.saturation_guard) since this engine takes a Model, not a
        # RunConfig — config.resolve_retrain_threshold's contract.
        if (
            retrain_error_threshold is not None
            and retrain_error_threshold < 0.0
        ):
            retrain_error_threshold = (
                AUTO_RETRAIN_THRESHOLD if model.saturation_guard else None
            )
        self.retrain_error_threshold = retrain_error_threshold
        self.model = model
        # Multi-tenant chunk plane (ROADMAP item 1, the streaming twin of
        # api.prepare_multi): ``tenants = T`` runs T independent streams —
        # each with its own detector + classifier state — through the one
        # jitted chunk program by widening the leading axis to T·P. Chunks
        # arrive pre-stacked (``engine.loop.stack_tenants`` /
        # serve.admission.TenantMicroBatcher) as ``[T·P, CB, B]`` grids;
        # tenant t's slice ``[t·P:(t+1)·P]`` of the carry IS the solo
        # detector's carry: its PRNG keys derive from ``tenant_seeds[t]``
        # (default ``seed + t`` — the solo convention of
        # config.tenant_configs) exactly as a fresh solo detector's would,
        # so per-tenant flags are bit-identical to T solo detectors fed
        # the per-tenant chunks (tested). ``self.partitions`` stays the
        # TOTAL leading-axis width (T·P) — every existing code path reads
        # it as "the vmapped width"; ``tenant_partitions`` is the
        # per-tenant P.
        if tenants < 1:
            raise ValueError(f"tenants must be >= 1, got {tenants}")
        if tenant_seeds is not None and len(tenant_seeds) != tenants:
            raise ValueError(
                f"{len(tenant_seeds)} tenant_seeds for {tenants} tenants"
            )
        self.tenants = tenants
        self.tenant_partitions = partitions
        self.tenant_seeds = (
            tuple(int(s) for s in tenant_seeds)
            if tenant_seeds is not None
            else tuple(seed + t for t in range(tenants))
        )
        self.partitions = partitions * tenants
        partitions = self.partitions
        self._detector = resolve_detector(ddm_params, detector)
        if window == 0:
            raise ValueError(
                "window=0 (auto) needs stream geometry the chunked engine "
                "does not have; pass an explicit width (config.auto_window "
                "can compute one from a known drift spacing)"
            )
        if window > 1:
            from .window import make_window_span

            span = make_window_span(
                model,
                ddm_params,
                window=window,
                shuffle=shuffle,
                retrain_error_threshold=retrain_error_threshold,
                detector=self._detector,
                rotations=rotations,
            )
            run_chunk = span
        elif rotations != 1:
            raise ValueError(
                "rotations only applies to the window engine (window > 1)"
            )
        else:
            step = make_partition_step(
                model,
                ddm_params,
                shuffle=shuffle,
                retrain_error_threshold=retrain_error_threshold,
                detector=self._detector,
            )

            def run_chunk(carry: LoopCarry, batches: Batches):
                return lax.scan(step, carry, batches)

        # (Transport-dtype seam: feeders may ship the feature plane in a
        # narrower dtype — stripe_chunk(feature_dtype=ml_dtypes.bfloat16)
        # halves host→device bytes on transport-bound feeds; the ENGINES
        # cast the plane back to f32 on device, engine/loop + engine/window,
        # so every driver gets f32 compute for free.)
        # ``mesh``: shard the partition axis over devices, exactly like the
        # one-shot mesh runner (parallel.mesh) — every carry/chunk/flag leaf
        # is partition-major, so one sharding prefix covers the trees.
        #
        # ``donate`` (default True): donate the carry AND the stale input
        # chunk to each per-chunk dispatch (``donate_argnums``) — the old
        # carry is dead the moment the new one exists (out-carry aliases
        # in-carry buffer-for-buffer, so XLA updates state in place instead
        # of allocating a second copy), and a chunk's device buffers are
        # dead once its scan consumed them (freed immediately instead of
        # lingering until Python GC, which is what lets the double-buffered
        # feed keep exactly two chunks resident at any queue depth). Flags
        # are bit-identical either way (tested). Caveat: donation consumes
        # the DEVICE buffers passed in — feeders yield numpy-backed chunks
        # (the host copy is untouched), but a caller feeding jax arrays it
        # wants to reuse must pass ``donate=False``.
        self._sharding = None
        self._mesh = mesh
        donate_kw = {"donate_argnums": (0, 1)} if donate else {}
        if mesh is not None:
            from ..models.base import require_shardable
            from ..parallel.mesh import TENANT_AXIS, plane_sharding

            require_shardable(model, mesh)

            if TENANT_AXIS in mesh.axis_names:
                # 2-D (tenant, partition) mesh (ROADMAP item 1): whole
                # tenants land on tenant-axis rows, so the tenant count
                # must split over that axis — a tenant straddling two
                # rows would still be CORRECT (the flattened sharding is
                # semantics-free) but is never what the operator meant.
                t_rows = mesh.devices.shape[0]
                if self.tenants % t_rows:
                    raise ValueError(
                        f"{self.tenants} tenant(s) do not split over the "
                        f"{t_rows}-row tenant mesh axis"
                    )
            self._sharding = plane_sharding(mesh, partitions)
            self._run_chunk = jax.jit(
                jax.vmap(run_chunk),
                in_shardings=(self._sharding, self._sharding),
                out_shardings=(self._sharding, self._sharding),
                **donate_kw,
            )
        else:
            self._run_chunk = jax.jit(jax.vmap(run_chunk), **donate_kw)
        # ``validate=True``: audit the concatenated flag table at the end
        # of :meth:`run` with the same structural checks the one-shot
        # path runs under RunConfig(validate=True)
        # (utils.validate.validate_flag_rows) — sentinel domain, index
        # ranges, warning/change ordering — so index-plane corruption is
        # caught on the chunked path too, not just api.run's.
        self.validate = validate
        # AOT warm-start surface (:meth:`prepare`): chunk-shape signature →
        # compiled executable. Empty (the default) means every dispatch
        # rides the jitted runner and XLA compiles lazily on first feed;
        # ``prepare`` fills it so the compile is paid *before* traffic.
        # ``_exec_fallen`` is the sticky loud-fallback latch, mirroring
        # ``api._guarded_exec``: one argument-compatibility refusal sends
        # every later feed to the jitted runner (correctness must never
        # depend on the warm-start fast path).
        self._exec: dict = {}
        self._exec_fallen = False
        self._per_batch: int | None = None
        self._seed = seed
        self.carry: LoopCarry | None = None
        self.batches_done = 0
        # Liveness bookkeeping for the heartbeat event: rows fed so far
        # (padding rows included — this is a progress beacon, not delay
        # accounting) and a monotonic feed-start stamp, so a host clock
        # step mid-run cannot fake progress for the `watch` CLI.
        self.rows_done = 0
        self._feed_started: float | None = None
        # Adaptation hook (adapt/ subsystem): the offline chunked loop's
        # twin of the serving daemon's --on-drift routing, so the paper's
        # batch loop and the live daemon share ONE adaptation code path.
        # Accepts a policy spec string (adapt.policy grammar), a list of
        # specs, or a ready AdaptationController; resolved lazily on the
        # first drained chunk (the controller needs the chunk geometry).
        # None (default) = today's behaviour, no adaptation code runs.
        self._on_drift = on_drift
        self.adapt = None  # the resolved AdaptationController (or None)

    # -- lifecycle -----------------------------------------------------------

    def _init_carry(self, first: Batches) -> LoopCarry:
        # Tenant t's key block is exactly the solo detector's
        # split(key(seed_t), P) — one tenant (the default) reduces to the
        # historical split(key(seed), P) bit-for-bit. concat_keys is the
        # shared helper (engine.loop), same one prepare_multi uses.
        from .loop import concat_keys

        p = self.tenant_partitions
        keys = concat_keys(
            [
                jax.random.split(jax.random.key(s), p)
                for s in self.tenant_seeds
            ]
        )
        init_keys = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
        params = jax.vmap(self.model.init)(init_keys[:, 1])
        carry = LoopCarry(
            params=params,
            ddm=jax.vmap(lambda _: self._detector.init())(
                jnp.arange(self.partitions)
            ),
            a_X=first.X[:, 0].astype(jnp.float32),  # transport-dtype seam
            a_y=first.y[:, 0],
            a_w=first.valid[:, 0].astype(jnp.float32),
            retrain=jnp.ones(self.partitions, bool),
            key=init_keys[:, 0],
        )
        if self._mesh is not None:
            # Per-leaf placement via the regex→PartitionSpec rule tree
            # (parallel.mesh.plane_rules): every plane-major leaf shards
            # its leading (tenant·partition) axis over the mesh, scalars
            # replicate — so the first donated feed starts from the
            # layout the jitted program wants instead of resharding.
            from ..parallel.mesh import plane_shardings

            carry = jax.device_put(carry, plane_shardings(self._mesh, carry))
        return carry

    def place(self, chunk: Batches) -> Batches:
        """Dispatch the host→device upload of a chunk (async, non-blocking).

        The double-buffer half of the pipeline: :meth:`run` places chunk
        k+1 right after dispatching chunk k's compute, so the upload
        overlaps the detect scan and the dispatch queue never drains
        between chunks. Idempotent — :meth:`feed` places too, and placing
        an already-placed chunk is a no-op — so callers may use either
        surface. With ``donate=True`` the returned device buffers are
        consumed by the feed that processes them.
        """
        if self._sharding is not None:
            return jax.device_put(chunk, self._sharding)
        return jax.tree.map(jnp.asarray, chunk)

    def feed(self, chunk: Batches) -> FlagRows:
        """Process one ``[P, CB, B]`` chunk; returns flags ``[P, CB']``.

        The first chunk loses its first microbatch to ``batch_a`` seeding.
        Does not block: results are JAX async values, so the caller can
        prefetch/construct the next chunk while the device runs. With
        ``donate=True`` (the default) the carry and the chunk's device
        buffers are donated to the dispatch — pass numpy-backed chunks
        (feeders do) or chunks you won't reuse; see ``__init__``.
        """
        import time

        from ..resilience import faults

        # Fault-injection site (resilience.faults; no-op unless armed):
        # "raise at batch K" at this engine's host granularity — the K-th
        # fed chunk — before any state advances, so a killed-and-resumed
        # stream replays from its last checkpoint exactly.
        faults.fire("chunked.feed", batches_done=self.batches_done)
        if self._feed_started is None:
            self._feed_started = time.monotonic()
        self._per_batch = int(chunk.y.shape[2])
        self.rows_done += int(
            chunk.y.shape[0] * chunk.y.shape[1] * chunk.y.shape[2]
        )
        chunk = self.place(chunk)  # no-op for pre-placed (run()) chunks
        if self.carry is None:
            self.carry = self._init_carry(chunk)
            chunk = jax.tree.map(lambda x: x[:, 1:], chunk)
        self.carry, flags = self._dispatch(self.carry, chunk)
        self.batches_done += int(chunk.y.shape[1])
        return flags

    def _dispatch(self, carry: LoopCarry, chunk: Batches):
        """Run one chunk through the AOT executable when :meth:`prepare`
        compiled this chunk shape, else the jitted runner (identical
        semantics — the executable IS the lowered jitted program)."""
        compiled = None
        if self._exec and not self._exec_fallen:
            compiled = self._exec.get(_chunk_sig(chunk))
        if compiled is None:
            return self._run_chunk(carry, chunk)
        try:
            return compiled(carry, chunk)
        except (TypeError, ValueError) as e:
            # Same contract as api._guarded_exec: a layout/sharding/aval
            # refusal falls back LOUDLY and stickily to the jitted runner;
            # genuine runtime failures (OOM, dying device) propagate.
            import warnings

            self._exec_fallen = True
            warnings.warn(
                "AOT-compiled chunk program rejected its arguments "
                f"({type(e).__name__}: {e}); falling back to the jitted "
                "runner — the lazy XLA compile will land in this feed",
                RuntimeWarning,
                stacklevel=3,
            )
            return self._run_chunk(carry, chunk)

    def prepare(self, example_chunk: Batches) -> dict:
        """AOT warm-start: compile the per-chunk program against
        ``example_chunk``'s geometry *now*, before any traffic.

        ``jit.lower().compile()`` does not populate the jit dispatch cache,
        so the executables are kept on the detector and :meth:`feed`
        dispatches through them directly. On a fresh detector both shapes
        the serving loop will see are compiled — the first chunk (one
        microbatch consumed by ``batch_a`` seeding, so ``CB-1`` batches)
        and the steady-state full chunk; a restored detector (``carry``
        already set) needs only the latter. With
        ``RunConfig.compile_cache_dir`` enabled the backend-compile half is
        additionally served from the persistent cache, so a *restarted*
        daemon warm-starts in milliseconds — the cold-start collapse the
        serve subsystem inherits from the r06 AOT work. Returns the timing
        split ``{aot_seconds, aot_shapes, aot_failed}``; a refusal to
        lower/compile is LOUD (RuntimeWarning) and leaves the lazy path in
        charge, never an error.
        """
        import time as _time

        chunk = self.place(example_chunk)
        fresh = self.carry is None
        template = self.carry if not fresh else self._init_carry(chunk)
        shaped = []
        if fresh:
            shaped.append(jax.tree.map(lambda x: x[:, 1:], chunk))
        shaped.append(chunk)
        t0 = _time.perf_counter()
        compiled_n = 0
        for s in shaped:
            sig = _chunk_sig(s)
            if sig in self._exec:
                continue
            try:
                self._exec[sig] = self._run_chunk.lower(template, s).compile()
                compiled_n += 1
            except Exception as e:
                import warnings

                warnings.warn(
                    "chunked AOT warm-start failed "
                    f"({type(e).__name__}: {e}); falling back to lazy "
                    "compilation — the XLA compile will land inside the "
                    "first feed of this shape",
                    RuntimeWarning,
                    stacklevel=2,
                )
                return {
                    "aot_seconds": _time.perf_counter() - t0,
                    "aot_shapes": compiled_n,
                    "aot_failed": True,
                }
        return {
            "aot_seconds": _time.perf_counter() - t0,
            "aot_shapes": compiled_n,
            "aot_failed": False,
        }

    @staticmethod
    def record_memory_gauges(metrics, when: str = "chunk") -> None:
        """Record a device-memory snapshot into a metrics registry
        (``device_bytes_in_use{when=...}`` latest point +
        ``device_peak_bytes_in_use`` max across calls — telemetry.profile).
        Cheap host call, no device sync; a no-op where the backend reports
        nothing (XLA CPU)."""
        from ..telemetry.profile import (
            device_memory_stats,
            record_device_memory_gauges,
        )

        record_device_memory_gauges(metrics, device_memory_stats(), when=when)

    def emit_chunk_event(
        self, telemetry, chunk: int, flags: FlagRows, metrics=None
    ):
        """Emit one chunk's ``chunk_completed`` progress event; returns
        ``(flags, the chunk's detection count)``.

        Shared by :meth:`run` and feed-level drivers (e.g. the
        ``examples/unbounded_stream.py`` checkpoint-mid-stream loop) so the
        event payload — including the detection count — is engine-defined
        everywhere. The count is reduced DEVICE-side and only the scalar
        crosses the device→host link: the event waits for the chunk's
        compute (the progress beacon must describe completed work —
        heartbeat/watch behavior is unchanged) but the flag table itself
        stays deferred on device, so per-chunk telemetry no longer forces
        the full-table transfer that previously made it a bandwidth trade.
        ``flags`` is returned as given (host callers still work — the
        reduction is array-library agnostic). ``metrics`` (a
        :class:`..telemetry.metrics.MetricsRegistry`) additionally records
        the per-chunk device-memory gauges.
        """
        # jnp on device flags → device reduce + scalar transfer; plain
        # numpy reduce for already-collected tables.
        detections = int((flags.change_global >= 0).sum())
        telemetry.emit(
            "chunk_completed",
            chunk=chunk,
            batches_done=self.batches_done,
            detections=detections,
        )
        if metrics is not None:
            self.record_memory_gauges(metrics)
        return flags, detections

    def emit_heartbeat(self, telemetry) -> dict:
        """Emit the liveness beacon: rows fed so far + monotonic seconds
        since the first ``feed``. Host-side bookkeeping only — no device
        sync, no jitted code; the ``watch`` CLI turns the stream of these
        into progress/ETA and stall detection. ``batches_done`` rides as
        an extra for humans reading the raw log."""
        import time

        elapsed = (
            time.monotonic() - self._feed_started
            if self._feed_started is not None
            else 0.0
        )
        return telemetry.emit(
            "heartbeat",
            rows_done=self.rows_done,
            elapsed_s=elapsed,
            batches_done=self.batches_done,
        )

    def run(
        self,
        chunks: Iterator[Batches],
        progress=None,
        telemetry=None,
        metrics=None,
        collect_every: int = 0,
        tracer=None,
    ) -> FlagRows:
        """Drain an iterator of chunks; concatenates flags on host.

        The drain is double-buffered: chunk k+1's host→device upload
        (:meth:`place`) is dispatched immediately after chunk k's compute,
        so upload overlaps detect and the dispatch queue never drains
        between chunks; with ``donate=True`` the stale chunk's buffers are
        reclaimed as each dispatch consumes them, bounding device memory
        at two chunks regardless of queue depth.

        ``collect_every`` sets the chunk-group boundary at which
        accumulated flag tables are collected host-side: the only full
        device syncs of the drain then happen every N chunks instead of
        implicitly at the final concat — bounding the device-resident
        backlog on very long streams without paying a per-chunk
        round-trip. 0 (the default) means: never for an untelemetered
        drain (unchanged — that path always deferred everything to the
        final concat), and a bounded default group
        (``DEFAULT_TELEMETRY_COLLECT_EVERY``) for a telemetered one —
        before r06, per-chunk telemetry collected every table host-side
        as a side effect, so long telemetered streams relied on that for
        their device-memory bound; the default group keeps the bound
        without reintroducing the per-chunk transfer. Flags are
        bit-identical at any grouping (tested).

        ``telemetry`` (a :class:`..telemetry.events.EventLog`) emits one
        ``chunk_completed`` progress event per chunk (detection count
        reduced device-side — a scalar transfer, the flag table stays
        deferred to the group boundary) followed by one ``heartbeat``
        (rows fed + monotonic elapsed — the ``watch`` CLI's liveness
        signal). ``metrics`` records the per-chunk device-memory gauges
        (no sync — usable with or without the event log).

        ``tracer`` (a :class:`..telemetry.tracing.ChunkTracer`, requires
        ``telemetry``) emits one ``kernel`` span per head-sampled chunk —
        feed dispatch to the chunk-event sync, the batch pipeline's twin
        of the serving span chain; the ``timeline`` CLI renders them.
        Falsy tracers (rate 0 / no log) cost one check per chunk.
        """
        if not collect_every and telemetry is not None:
            collect_every = DEFAULT_TELEMETRY_COLLECT_EVERY
        start_batches = self.batches_done
        out = []
        uncollected = 0  # trailing entries of `out` still device-resident

        # Upload-stage accounting for the host-ingest pipeline gauges
        # (io.feeder.StageClock's metric, stage="upload"): time spent
        # dispatching place()/feed() — host-side dispatch cost only, the
        # device work itself is async behind it.
        c_stage = None
        if metrics is not None:
            from ..io.feeder import STAGE_BUSY_HELP, STAGE_BUSY_METRIC

            c_stage = metrics.counter(STAGE_BUSY_METRIC, help=STAGE_BUSY_HELP)

        def _drain_group():
            nonlocal uncollected
            for j in range(len(out) - uncollected, len(out)):
                out[j] = jax.tree.map(np.asarray, out[j])
            uncollected = 0

        import time as _time

        def _place_timed(chunk):
            if chunk is None:
                return None
            t0 = _time.perf_counter()
            placed = self.place(chunk)
            if c_stage is not None:
                c_stage.inc(_time.perf_counter() - t0, stage="upload")
            return placed

        it = iter(chunks)
        nxt = next(it, None)
        host_chunk = nxt  # the numpy-backed copy (adaptation window rows)
        placed = _place_timed(nxt)
        i = 0
        while placed is not None:
            cur_host = host_chunk
            t_feed = _time.perf_counter()
            t_feed_mono = _time.monotonic()
            flags = self.feed(placed)
            if c_stage is not None:
                c_stage.inc(_time.perf_counter() - t_feed, stage="upload")
            # Double-buffer: dispatch chunk k+1's upload (and pay its host
            # parse/stripe cost) while chunk k computes.
            nxt = next(it, None)
            host_chunk = nxt
            placed = _place_timed(nxt)
            if self._on_drift is not None:
                self._ensure_adapt(cur_host, telemetry)
            if self.adapt is not None and self.adapt.active:
                # The adaptation hook consumes HOST flags, so this chunk
                # syncs here instead of at the group boundary — the
                # documented cost of reacting (vs only reporting) on the
                # offline path; the dispatch pipeline itself is unchanged.
                flags = jax.tree.map(np.asarray, flags)
                per_tenant_rows = self.rows_done // self.tenants
                self.adapt.on_chunk(
                    {
                        "chunk": i,
                        "rows_through": self.rows_done,
                        "t_rows_through": [per_tenant_rows] * self.tenants,
                    },
                    flags,
                    cur_host,
                )
            if telemetry is not None:
                flags, _ = self.emit_chunk_event(telemetry, i, flags, metrics)
                self.emit_heartbeat(telemetry)
                if tracer:
                    # the chunk event's device-side count reduction synced
                    # on this chunk's compute, so "now" closes the span
                    tracer.span(
                        "kernel", i, t_feed_mono, _time.monotonic(),
                        batches_done=self.batches_done,
                    )
            elif metrics is not None:
                self.record_memory_gauges(metrics)
            out.append(flags)  # async; collected at group boundaries/the end
            uncollected += 1
            if collect_every and uncollected >= collect_every:
                _drain_group()
            if progress is not None:
                progress(i, self.batches_done)
            i += 1
        host = [jax.tree.map(np.asarray, f) for f in out]
        flags = FlagRows(*(np.concatenate(xs, axis=1) for xs in zip(*host)))
        if self.validate and self._per_batch is not None:
            from ..utils.validate import validate_flag_rows

            # The expected flag width comes from the independently-counted
            # fed batches (chunk shapes), so a dropped or duplicated
            # chunk boundary is caught like the one-shot path's geometry
            # check; rows_done (padded grid positions fed) upper-bounds
            # every real global stream position. Bounds assume the drain
            # starts at stream position 0 (feeders with a start_row
            # offset resume a stream this audit cannot re-derive).
            validate_flag_rows(
                flags,
                self.batches_done - start_batches + 1,
                self._per_batch,
                self.rows_done,
            )
        return flags

    def _ensure_adapt(self, chunk, telemetry) -> None:
        """Resolve the ``on_drift`` hook into a live controller on the
        first drained chunk (policy specs need the chunk geometry a
        detector does not know until data arrives). Idempotent."""
        if self.adapt is not None or self._on_drift is None:
            return
        from ..adapt.refit import AdaptationController

        if isinstance(self._on_drift, AdaptationController):
            self.adapt = self._on_drift
            return
        from ..adapt.policy import resolve_policies

        specs = (
            [self._on_drift]
            if isinstance(self._on_drift, str)
            else list(self._on_drift)
        )
        cb, per_batch = int(chunk.y.shape[1]), int(chunk.y.shape[2])
        self.adapt = AdaptationController(
            self,
            resolve_policies(specs, self.tenants),
            per_batch=per_batch,
            num_features=int(chunk.X.shape[3]),
            rows_per_chunk=self.tenant_partitions * cb * per_batch,
            log=telemetry,
            seed=self._seed,
        )

    # -- tenant plane --------------------------------------------------------

    def tenant_flags(self, flags: FlagRows) -> "list[FlagRows]":
        """Split a stacked ``[T·P, CB']`` flag table into per-tenant
        ``[P, CB']`` views (``parallel.mesh.split_tenant_flags`` — free
        host slicing; works on device arrays too)."""
        from ..parallel.mesh import split_tenant_flags

        return split_tenant_flags(flags, self.tenants)

    def _tenant_span(self, tenant: int) -> "tuple[int, int]":
        if not 0 <= tenant < self.tenants:
            raise ValueError(
                f"tenant {tenant} out of range (detector has {self.tenants})"
            )
        p = self.tenant_partitions
        return tenant * p, (tenant + 1) * p

    def tenant_carry(self, tenant: int) -> LoopCarry:
        """Tenant t's slice of the carried state — structurally IDENTICAL
        to a solo P-partition detector's carry (the per-tenant checkpoint
        pytree of ROADMAP item 1)."""
        assert self.carry is not None, "no state yet (feed or restore first)"
        lo, hi = self._tenant_span(tenant)
        return jax.tree.map(lambda x: x[lo:hi], self.carry)

    def save_tenant(
        self, path: str, tenant: int, extra_meta: "dict | None" = None
    ) -> None:
        """Checkpoint ONE tenant's detector state as a solo-shaped
        checkpoint: a ``tenants=1`` detector (or a resized tenant plane)
        can :meth:`restore` / :meth:`restore_tenant` it — tenants migrate
        between planes without dragging the other T−1 states along.
        ``extra_meta`` rides in the JSON meta (the serve layer's
        per-tenant stream accounting — ``serve.runner``/``serve.router``
        ship it with the checkpoint across daemons)."""
        from ..utils.checkpoint import save_checkpoint

        save_checkpoint(
            path,
            self.tenant_carry(tenant),
            meta={
                "batches_done": self.batches_done,
                "partitions": self.tenant_partitions,
                "tenant": tenant,
                **(extra_meta or {}),
            },
        )

    def restore_tenant(
        self, path: str, tenant: int, example_chunk: "Batches | None" = None
    ) -> dict:
        """Load a solo-shaped checkpoint into tenant slot ``t`` of the
        stacked carry (the inverse of :meth:`save_tenant`); the other
        tenants' states are untouched. The detector must already hold a
        carry (fed or restored) — slot surgery needs the plane to exist —
        OR be given ``example_chunk`` (any chunk of the serving shapes)
        to build a fresh plane first: the live-migration landing path,
        where a replacement daemon's first state IS the shipped tenant.
        ``batches_done`` stays the plane's own (all tenants advance in
        lock-step through the shared grid)."""
        from ..utils.checkpoint import load_checkpoint

        if self.carry is None and example_chunk is not None:
            self.carry = self._init_carry(
                jax.tree.map(jnp.asarray, example_chunk)
            )
        assert self.carry is not None, (
            "restore_tenant needs an existing carry (feed or restore the "
            "plane first, or pass example_chunk)"
        )
        lo, hi = self._tenant_span(tenant)
        template = jax.tree.map(lambda x: x[lo:hi], self.carry)
        loaded, meta = load_checkpoint(path, template)
        if int(meta.get("partitions", self.tenant_partitions)) != (
            self.tenant_partitions
        ):
            raise ValueError(
                f"checkpoint {path} holds {meta.get('partitions')} "
                f"partitions; this plane's tenants carry "
                f"{self.tenant_partitions}"
            )

        def scatter(leaf, sub):
            # Typed PRNG keys scatter through their key data (portable
            # across jax versions; .at[] on key arrays is not).
            if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
                data = jax.random.key_data(leaf)
                data = data.at[lo:hi].set(jax.random.key_data(sub))
                return jax.random.wrap_key_data(
                    data, impl=jax.random.key_impl(leaf)
                )
            return leaf.at[lo:hi].set(sub)

        self.carry = jax.tree.map(scatter, self.carry, loaded)
        return meta

    # -- checkpoint / resume (SURVEY.md §5) ----------------------------------

    def save(self, path: str) -> None:
        from ..utils.checkpoint import save_checkpoint

        assert self.carry is not None, "nothing to checkpoint yet"
        save_checkpoint(
            path,
            self.carry,
            meta={
                "batches_done": self.batches_done,
                "partitions": self.partitions,
                **({"tenants": self.tenants} if self.tenants != 1 else {}),
            },
        )

    def restore(self, path: str, example_chunk: Batches | None = None) -> dict:
        """Resume from a checkpoint. A fresh detector needs ``example_chunk``
        (any chunk of the right shapes) to rebuild the carry structure."""
        from ..utils.checkpoint import load_checkpoint

        template = self.carry
        if template is None:
            if example_chunk is None:
                raise ValueError(
                    "restore() on a fresh detector needs example_chunk to "
                    "rebuild the carry structure"
                )
            template = self._init_carry(jax.tree.map(jnp.asarray, example_chunk))
        self.carry, meta = load_checkpoint(path, template)
        self.batches_done = int(meta["batches_done"])
        return meta
