"""Per-partition microbatch loop as a single compiled ``lax.scan``.

This is the TPU-native rebuild of the reference's worker kernel
``run_DDM_loop`` (C7, ``DDM_Process.py:162-213``): slice the partition's
stream into ``PER_BATCH`` microbatches; train on batch *a*; predict batch *b*;
feed per-row error indicators to DDM; on change, rotate *a ← b*, reset the
detector and mark retrain; otherwise carry the detector state forward.

Differences from the reference, all deliberate (SURVEY.md §7):

* The Python ``for batch_b in batches[1:]`` becomes one ``lax.scan`` whose
  carry is ``(model params, ddm state, batch_a, retrain, key)`` — fixed
  shapes, no data-dependent recompiles, one XLA program for the whole stream.
* ``if retrain: rf = train_rf(...)`` (``:194-196``) becomes an unconditional
  fit + ``where``-select: under ``vmap`` over partitions both branches of a
  ``cond`` would execute anyway (SPMD), so the select is the honest form.
* The unseeded ``batch.sample(frac=1)`` shuffles (``:187,190``) become seeded
  ``jax.random.permutation``s (quirk register #nondeterminism).
* Short/padded rows are masked via a validity plane instead of ragged frames.
* The per-row detector loop is the vectorised :func:`..ops.ddm_batch` — or
  any other :class:`..ops.detectors.DetectorKernel` (Page–Hinkley, EDDM)
  passed as ``detector=``; the carry's ``ddm`` slot then holds that
  detector's state pytree.

Shapes: a partition's stream is ``Batches(X [NB,B,F], y [NB,B],
rows [NB,B], valid [NB,B])``; batch 0 seeds ``batch_a``; the scan runs over
batches 1..NB-1 and emits one flag row per processed batch — exactly the
reference's GROUPED_MAP output schema (``:166-169``) with −1 sentinels.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import DDMParams
from ..models.base import Model
from ..ops.ddm import DDMState


class Batches(NamedTuple):
    """One partition's stream, sliced into fixed-size microbatches."""

    X: jax.Array  # [NB, B, F] f32
    y: jax.Array  # [NB, B] i32
    rows: jax.Array  # [NB, B] i32  global stream positions
    valid: jax.Array  # [NB, B] bool (False = padding)


class IndexedBatches(NamedTuple):
    """Compressed stream: microbatch grid of *indices into a row table*.

    The reference's volume scaling duplicates a small CSV ``MULT_DATA`` times
    before shipping the whole dataframe to the cluster (``DDM_Process.py:
    44-49,222`` — hence its 512 MB RPC limit). On TPU the host→device link is
    the scarce resource, so the framework ships the information content
    instead: the deduplicated row table (replicated, a few hundred KB) plus
    int16/int32 index planes (~14× smaller than the materialized stream at
    mult=512; :class:`PackedIndexedBatches` is the ~30× form actually
    shipped), and gathers rows on device inside the compiled loop. Identical
    stream semantics — every row still flows through the detector.

    ``X[s] ≡ base_X[idx[s]]``, ``y[s] ≡ base_y[idx[s]]``.
    """

    base_X: jax.Array  # [T, F] f32 row table (replicated across the mesh)
    base_y: jax.Array  # [T] i32
    idx: jax.Array  # [NB, B] i16/i32 row-table index (leading [P,..] sharded)
    rows: jax.Array  # [NB, B] i32 global stream positions
    valid: jax.Array  # [NB, B] bool (False = padding)


class PackedIndexedBatches(NamedTuple):
    """Transport-optimal form of :class:`IndexedBatches`.

    The ``rows`` and ``valid`` planes of the compressed stream (12 MB of
    its ~14 MB at the mult=512 headline shape) are pure functions of the
    stripe geometry and the per-microbatch shuffle permutation
    (``io.stream._stripe_maps``: ``gmap = (slot·B + perm)·P + part``,
    ``rows = gmap``, ``valid = gmap < n``). On a latency/bandwidth-bound
    host→device link there is no reason to ship them: this form carries
    only the data-dependent planes — the row-table gather indices and the
    one-byte permutation — and :func:`expand_packed` synthesizes the rest
    on device inside the jitted runner, where the arithmetic is free.
    Expansion is bit-identical to the host-built planes (tested), so every
    engine downstream is unchanged.
    """

    base_X: jax.Array  # [T, F] f32 row table (replicated across the mesh)
    base_y: jax.Array  # [T] i32
    idx: jax.Array  # [P, NB, B] i16/i32 row-table index (sharded)
    perm: jax.Array  # [P, NB, B] u8/i16 within-batch shuffle permutation
    n_rows: jax.Array  # i32 scalar: stream length (pads the validity mask)


def expand_packed(packed: PackedIndexedBatches) -> IndexedBatches:
    """Synthesize the ``rows``/``valid`` planes on device (see
    :class:`PackedIndexedBatches`). Matches ``io.stream._stripe_maps`` for
    ``start_row = 0`` — the one-shot path this form serves."""
    p, nb, b = packed.idx.shape
    slot = jnp.arange(nb, dtype=jnp.int32)[None, :, None]
    part = jnp.arange(p, dtype=jnp.int32)[:, None, None]
    gmap = (slot * b + packed.perm.astype(jnp.int32)) * p + part
    return IndexedBatches(
        base_X=packed.base_X,
        base_y=packed.base_y,
        idx=packed.idx,
        rows=gmap,
        valid=gmap < packed.n_rows,
    )


def concat_keys(key_arrays):
    """Concatenate typed PRNG key arrays along axis 0, via key data — the
    portable route across jax versions (key arrays reject plain
    ``np.asarray``/``concatenate``). The tenant plane's one key helper,
    shared by ``api.prepare_multi`` and ``ChunkedDetector._init_carry`` so
    the batch and streaming paths cannot diverge in exactly the code their
    bit-parity contract rides on."""
    import numpy as np

    if len(key_arrays) == 1:
        return key_arrays[0]
    impl = jax.random.key_impl(key_arrays[0])
    data = np.concatenate(
        [np.asarray(jax.random.key_data(k)) for k in key_arrays]
    )
    return jax.random.wrap_key_data(jnp.asarray(data), impl=impl)


def stack_tenants(batches_list) -> Batches:
    """Stack T tenants' independent ``[P, NB_t, B]`` grids into ONE
    ``[T·P, NB_max, B]`` plane — the multi-tenant leading axis.

    The engines vmap over the leading axis with fully independent
    per-slice state (model params, detector state, ``batch_a``, PRNG key),
    so T tenants × P partitions stacked here run through one compiled
    kernel exactly as T·P partitions would — one trace, one dispatch, one
    collect — while every tenant keeps its own detector + classifier
    state. Ragged tenant lengths (``NB_t < NB_max``) are padded with fully
    masked microbatches (``valid=False``, ``rows=-1``, zero fill): inside
    the scan a masked batch is inert (flags stay sentinel, the carry's
    data is untouched), and because the padding sits strictly AFTER the
    tenant's real batches it cannot perturb any real flag row — per-tenant
    flags are bit-identical to the solo run (tested,
    ``tests/test_tenancy.py``). Host-side (numpy) — the stacking happens
    at stripe time, before the host→device upload.

    On a 2-D ``(tenant, partition)`` device mesh (``parallel.mesh
    .make_mesh(tenant_devices=...)``, RunConfig.mesh_tenant_devices) the
    stacked plane's leading axis — and the compacted collect table's
    provenance — shard tenant-major over both mesh axes
    (``plane_sharding``): whole tenants land on tenant-axis rows because
    this function lays the axis out tenant-major, ``q = t·P + p``.
    Per-tenant flags stay bit-identical at every mesh shape (tested,
    ``tests/test_fleet_serving.py``).
    """
    import numpy as np

    if not batches_list:
        raise ValueError("stack_tenants needs at least one tenant grid")
    b0 = batches_list[0]
    p, b = b0.y.shape[0], b0.y.shape[2]
    for i, bt in enumerate(batches_list):
        if bt.y.shape[0] != p or bt.y.shape[2] != b:
            raise ValueError(
                f"tenant {i} grid {bt.y.shape} disagrees with tenant 0's "
                f"partitions/per_batch ({p}, {b}); tenants share one kernel "
                "geometry — only NB (stream length) may differ"
            )
    nb_max = max(bt.y.shape[1] for bt in batches_list)

    def pad(bt: Batches) -> Batches:
        extra = nb_max - bt.y.shape[1]
        if not extra:
            return bt
        return Batches(
            X=np.concatenate(
                [bt.X, np.zeros((p, extra, b, bt.X.shape[3]), bt.X.dtype)],
                axis=1,
            ),
            y=np.concatenate(
                [bt.y, np.zeros((p, extra, b), bt.y.dtype)], axis=1
            ),
            rows=np.concatenate(
                [bt.rows, np.full((p, extra, b), -1, bt.rows.dtype)], axis=1
            ),
            valid=np.concatenate(
                [bt.valid, np.zeros((p, extra, b), bool)], axis=1
            ),
        )

    padded = [pad(bt) for bt in batches_list]
    return Batches(
        *(
            np.concatenate([getattr(bt, f) for bt in padded], axis=0)
            for f in Batches._fields
        )
    )


class FlagRows(NamedTuple):
    """Per-batch detection flags — reference output schema (−1 sentinels),
    plus ``forced_retrain`` marking fallback retrains (see
    ``RunConfig.retrain_error_threshold``; always False when disabled)."""

    warning_local: jax.Array  # index within the (shuffled) batch
    warning_global: jax.Array  # global stream position
    change_local: jax.Array
    change_global: jax.Array
    forced_retrain: jax.Array  # bool


class LoopCarry(NamedTuple):
    params: object
    ddm: DDMState | object  # detector state (DDMState for the default kernel)
    a_X: jax.Array  # [B, F]
    a_y: jax.Array  # [B]
    a_w: jax.Array  # [B] f32 validity weights
    retrain: jax.Array  # bool
    key: jax.Array


def _select(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _gather_row(rows, idx):
    """rows[idx] with −1 passthrough."""
    safe = jnp.clip(idx, 0, rows.shape[0] - 1)
    return jnp.where(idx >= 0, rows[safe], jnp.int32(-1))


def resolve_detector(ddm_params: DDMParams, detector=None):
    """The kernel an engine runs: ``detector`` if given, else DDM built from
    ``ddm_params`` (the reference's only statistic)."""
    if detector is not None:
        return detector
    from ..ops.detectors import make_detector

    return make_detector("ddm", ddm=ddm_params)


def _check_retrain_threshold(thr: float | None) -> None:
    """Reject a leaked RETRAIN_AUTO sentinel at the engine boundary.

    ``config.RunConfig.retrain_error_threshold`` defaults to −1.0 (auto);
    it is resolved to a per-family value by ``api.prepare`` /
    ``ChunkedDetector`` (``config.resolve_retrain_threshold``). The
    low-level engines take the *resolved* value only — a negative
    threshold here would silently mean "force a retrain on every nonempty
    batch" (``err_rate > −1`` is always true), destroying detection
    behaviour, so it fails loudly instead.
    """
    if thr is not None and thr < 0.0:
        raise ValueError(
            f"retrain_error_threshold={thr} is negative — the RETRAIN_AUTO "
            "sentinel must be resolved before reaching an engine "
            "(config.resolve_retrain_threshold); pass None to disable or a "
            "non-negative float to pin"
        )


def make_partition_step(
    model: Model,
    ddm_params: DDMParams,
    *,
    shuffle: bool = True,
    retrain_error_threshold: float | None = None,
    detector=None,
):
    """Build the scan body: ``(carry, batch) -> (carry, FlagRows)``.

    ``detector`` (a :class:`..ops.detectors.DetectorKernel`) swaps the drift
    statistic; ``None`` keeps the reference's DDM with ``ddm_params``.
    """
    _check_retrain_threshold(retrain_error_threshold)
    det = resolve_detector(ddm_params, detector)

    def step(carry: LoopCarry, batch) -> tuple[LoopCarry, FlagRows]:
        b_X, b_y, b_rows, b_valid = batch
        if b_X.dtype != jnp.float32:
            # Transport-dtype seam (io.stream.stripe_chunk feature_dtype):
            # narrower planes ship over the link, engines compute in f32.
            b_X = b_X.astype(jnp.float32)
        key, k_shuf, k_fit = jax.random.split(carry.key, 3)
        if shuffle:
            perm = jax.random.permutation(k_shuf, b_y.shape[0])
            b_X, b_y, b_rows, b_valid = (
                b_X[perm],
                b_y[perm],
                b_rows[perm],
                b_valid[perm],
            )
        b_w = b_valid.astype(jnp.float32)
        nonempty = jnp.any(b_valid)

        # Train-on-demand (C7 :194-196): fit always (SPMD), apply on retrain.
        fitted = model.fit(k_fit, carry.a_X, carry.a_y, carry.a_w)
        params = _select(carry.retrain & nonempty, fitted, carry.params)

        # Predict + per-row error indicators (C5; 'accuracy'→error, quirk #4).
        preds = model.predict(params, b_X)
        errs = (preds != b_y).astype(jnp.float32)

        # Detect (C6) — vectorised batch kernel, state carried across batches.
        new_ddm, res = det.batch(carry.ddm, errs, b_valid)
        change = (res.first_change >= 0) & nonempty

        # Optional fallback (config.retrain_error_threshold): a saturated
        # error rate with no DDM firing means the detector is blind-spotted;
        # rotate/reset/retrain without recording a change. Static no-op (same
        # compiled graph) when disabled.
        if retrain_error_threshold is not None:
            err_rate = jnp.sum(errs * b_w) / jnp.maximum(jnp.sum(b_w), 1.0)
            forced = nonempty & ~change & (err_rate > retrain_error_threshold)
        else:
            forced = jnp.bool_(False)
        rotate = change | forced

        flags = FlagRows(
            warning_local=res.first_warning,
            warning_global=_gather_row(b_rows, res.first_warning),
            change_local=res.first_change,
            change_global=_gather_row(b_rows, res.first_change),
            forced_retrain=forced,
        )

        # On change: rotate batch_a ← batch_b, reset detector, retrain (C7
        # :207-210). Empty (fully padded) batches are inert.
        new_carry = LoopCarry(
            params=params,
            ddm=_select(rotate, det.init(), new_ddm),
            a_X=_select(rotate, b_X, carry.a_X),
            a_y=_select(rotate, b_y, carry.a_y),
            a_w=_select(rotate, b_w, carry.a_w),
            retrain=jnp.where(nonempty, rotate, carry.retrain),
            key=key,
        )
        return new_carry, flags

    return step


def make_partition_runner(
    model: Model,
    ddm_params: DDMParams,
    *,
    shuffle: bool = True,
    retrain_error_threshold: float | None = None,
    detector=None,
):
    """Build ``run(batches: Batches, key) -> FlagRows`` for one partition.

    The returned function is pure and jit/vmap-compatible; ``FlagRows`` leaves
    have shape ``[NB-1]``.
    """
    det = resolve_detector(ddm_params, detector)
    step = make_partition_step(
        model,
        ddm_params,
        shuffle=shuffle,
        retrain_error_threshold=retrain_error_threshold,
        detector=det,
    )

    def run(batches: Batches, key: jax.Array) -> FlagRows:
        key, k_init = jax.random.split(key)
        carry = LoopCarry(
            params=model.init(k_init),
            ddm=det.init(),
            a_X=batches.X[0],
            a_y=batches.y[0],
            a_w=batches.valid[0].astype(jnp.float32),
            retrain=jnp.bool_(True),
            key=key,
        )
        rest = jax.tree.map(lambda x: x[1:], batches)
        _, flags = lax.scan(step, carry, rest)
        return flags

    return run
