from .chunked import ChunkedDetector
from .loop import (
    Batches,
    FlagRows,
    IndexedBatches,
    LoopCarry,
    PackedIndexedBatches,
    expand_packed,
    make_partition_runner,
    make_partition_step,
)
from .soak import (
    ChainedSoakSummary,
    SoakChainState,
    SoakLegFlags,
    SoakResult,
    make_soak_chain,
    make_soak_runner,
    run_soak_chained,
)
from .window import make_window_runner, make_window_span

__all__ = [
    "Batches",
    "ChainedSoakSummary",
    "ChunkedDetector",
    "FlagRows",
    "IndexedBatches",
    "PackedIndexedBatches",
    "expand_packed",
    "LoopCarry",
    "make_partition_runner",
    "make_partition_step",
    "make_soak_chain",
    "make_soak_runner",
    "make_window_runner",
    "make_window_span",
    "run_soak_chained",
    "SoakChainState",
    "SoakLegFlags",
    "SoakResult",
]
