from .chunked import ChunkedDetector
from .loop import Batches, FlagRows, LoopCarry, make_partition_runner, make_partition_step

__all__ = [
    "Batches",
    "ChunkedDetector",
    "FlagRows",
    "LoopCarry",
    "make_partition_runner",
    "make_partition_step",
]
