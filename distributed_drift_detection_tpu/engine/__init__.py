from .chunked import ChunkedDetector
from .loop import (
    Batches,
    FlagRows,
    IndexedBatches,
    LoopCarry,
    make_partition_runner,
    make_partition_step,
)
from .soak import SoakResult, make_soak_runner
from .window import make_window_runner, make_window_span

__all__ = [
    "Batches",
    "ChunkedDetector",
    "FlagRows",
    "IndexedBatches",
    "LoopCarry",
    "make_partition_runner",
    "make_partition_step",
    "make_soak_runner",
    "make_window_runner",
    "make_window_span",
    "SoakResult",
]
