from .chunked import ChunkedDetector
from .loop import (
    Batches,
    FlagRows,
    IndexedBatches,
    LoopCarry,
    make_partition_runner,
    make_partition_step,
)
from .window import make_window_runner

__all__ = [
    "Batches",
    "ChunkedDetector",
    "FlagRows",
    "IndexedBatches",
    "LoopCarry",
    "make_partition_runner",
    "make_partition_step",
    "make_window_runner",
]
