"""Top-level API: configure → run → metrics, with a backend seam.

This is the framework equivalent of executing ``DDM_Process.py`` end to end
(SURVEY.md §3.1), preserved as a function: load + synthesize the stream (C2),
stripe it over partitions (C8), run the compiled detection loop on the
selected backend, merge flags and compute the delay metric (C10), append a
results row (C11).

The ``backend=`` seam mirrors the north-star plugin boundary:

* ``'jax'`` — the TPU-native path: jit + vmap over partitions, sharded over a
  ``Mesh`` when more than one device is visible.
* ``'spark'`` — **formally retired** (round 5, recorded decision): the
  reference's execution model (``DDM_Process.py:58-72,216-226``) ran on a
  Spark standalone cluster; this framework's native path replaces it
  end-to-end, PySpark is not present in the supported environment, and a
  Spark *local-mode* reimplementation would exercise none of the cluster
  semantics that made the seam interesting. Selecting it raises a
  ``ValueError`` explaining the decision. Flag-level A/B against the
  reference's execution semantics is served by the pure-NumPy oracle loop
  (``tests/oracle.py`` + golden tests) and the delay-parity harness
  (``harness/parity.py``) instead.

The timed span matches the reference's ``Final Time``
(``DDM_Process.py:224→:260``): device upload + compiled loop + flag
collection + delay computation — not just the kernel.
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import OrderedDict
from typing import NamedTuple

import jax
import numpy as np

from .config import (
    COLLECT_MODES,
    RunConfig,
    auto_ph_threshold,
    auto_rotations,
    auto_window,
    host_shuffle_seed,
    replace,
    resolve_retrain_threshold,
    telemetry_config_payload,
)
from .engine.loop import FlagRows
from .io.stream import (
    StreamData,
    load_stream,
    stripe_geometry,
    stripe_partitions,
    stripe_partitions_packed,
)
from .metrics import (
    DelayMetrics,
    attribution_metrics,
    delay_metrics,
    result_row,
)
from .models import ModelSpec, build_model
from .parallel.mesh import (
    auto_compact_capacity,
    host_flags,
    make_mesh,
    make_mesh_runner,
    shard_batches,
)
from .resilience import faults
from .results import append_result
from .utils.timing import PhaseTimer, maybe_trace


class PreparedRun(NamedTuple):
    """Everything needed to execute a configured run (shared by api + bench)."""

    stream: StreamData
    batches: object  # engine.Batches, partition-major
    runner: object  # jitted (batches, keys) -> MeshRunResult
    keys: jax.Array
    mesh: object  # jax.sharding.Mesh | None
    config: RunConfig  # the resolved config (window=0 auto already applied)
    # Runner provenance for the telemetry compile_completed event: whether
    # the jitted runner came from the in-process cache, how long the
    # closure build took, and the AOT warm-start split (``aot_seconds``:
    # the prepare-phase ``lower().compile()`` span — ~0 on an AOT-cache
    # hit; with RunConfig.compile_cache_dir the XLA compile inside it is
    # served from the persistent cache across processes too).
    compile_info: "dict | None" = None
    # The callable the detect phase executes: the AOT-compiled executable
    # when warm-start succeeded (compile paid in prepare, outside the
    # Final Time span), else the jitted runner (compile lands lazily in
    # the first detect call — host-callback models and exotic backends).
    # ``runner`` stays the jitted function either way: the telemetry
    # lowering hooks (.lower()) need it.
    exec_fn: "object | None" = None


# Compiled-runner LRU: repeated run()/prepare() calls with the same static
# configuration (the 5-trial grid harness, C12-C14) reuse one jitted runner
# instead of re-tracing a fresh closure per call (~1s/trial on the remote-TPU
# path even with a warm persistent compile cache). model='rf' runners are
# never cached — their closures pin host-side fitted-forest state.
_RUNNER_CACHE: OrderedDict = OrderedDict()


def _cached_runner(
    cfg: RunConfig, spec: ModelSpec, n_dev: int, indexed: bool, model,
    compact_capacity: int = 0, tenant_devices: int = 0,
):
    """Returns ``(runner, mesh, compile_info)`` — see PreparedRun.compile_info."""

    def build():
        from .ops.detectors import make_detector

        t0 = time.perf_counter()
        mesh = (
            make_mesh(n_dev, tenant_devices=tenant_devices)
            if n_dev > 1
            else None
        )
        runner = make_mesh_runner(
            model,
            cfg.ddm,
            mesh,
            shuffle=False,  # batches are shuffled host-side at stripe time
            retrain_error_threshold=cfg.retrain_error_threshold,
            window=cfg.window,
            packed=indexed,  # compressed stream ships in the packed form
            detector=make_detector(
                cfg.detector,
                ddm=cfg.ddm,
                ph=cfg.ph,
                eddm=cfg.eddm,
                hddm=cfg.hddm,
                hddm_w=cfg.hddm_w,
                adwin=cfg.adwin,
                kswin=cfg.kswin,
                stepd=cfg.stepd,
            ),
            rotations=cfg.window_rotations,
            compact_capacity=compact_capacity,
        )
        return runner, mesh, {
            "cached": False,
            "build_seconds": time.perf_counter() - t0,
        }

    if model.host_callback:
        return build()  # never cached: closures pin host-side fitted state
    key = (
        cfg.model, cfg.fit_steps, cfg.learning_rate, cfg.mlp_hidden,
        cfg.mlp_learning_rate, cfg.forest_trees, cfg.forest_depth,
        cfg.per_batch, cfg.partitions, spec, cfg.ddm,
        cfg.window, indexed, n_dev, cfg.retrain_error_threshold,
        cfg.detector, cfg.ph, cfg.eddm, cfg.hddm, cfg.hddm_w, cfg.adwin,
        cfg.kswin, cfg.stepd, cfg.window_rotations, compact_capacity,
        tenant_devices,
    )
    if key in _RUNNER_CACHE:
        _RUNNER_CACHE.move_to_end(key)
        runner, mesh = _RUNNER_CACHE[key]
        return runner, mesh, {"cached": True, "build_seconds": 0.0}
    runner, mesh, info = build()
    _RUNNER_CACHE[key] = (runner, mesh)
    if len(_RUNNER_CACHE) > 8:
        _RUNNER_CACHE.popitem(last=False)
    return runner, mesh, info


# AOT-executable LRU (warm-start, tentpole c): repeated prepare() calls at
# the same runner + stripe geometry reuse one ``lower().compile()``d
# executable instead of re-tracing per call. Values keep a strong reference
# to the runner so an ``id()`` key cannot be reused by a new object while
# its entry is alive.
_AOT_CACHE: OrderedDict = OrderedDict()


def _aval_sig(tree) -> tuple:
    """Shape/dtype signature of a pytree — the AOT cache's geometry key."""
    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves(tree)
    )


def _guarded_exec(runner, compiled):
    """Dispatch to the AOT executable; an argument-compatibility refusal
    (TypeError/ValueError: layout/sharding/aval drift between the lowered
    program and the arrays the caller actually placed) falls back to the
    jitted runner — correctness must never depend on the warm-start fast
    path. The fallback is LOUD (RuntimeWarning) and sticky (the jitted
    runner serves every later call, so the lazy compile is paid once, not
    per call), and genuine runtime failures (OOM, a dying device) propagate
    — re-dispatching those would hide the root cause and silently re-run
    the whole program."""
    state = {"fallen_back": False}

    def exec_fn(batches, keys):
        if state["fallen_back"]:
            return runner(batches, keys)
        try:
            return compiled(batches, keys)
        except (TypeError, ValueError) as e:
            import warnings

            state["fallen_back"] = True
            warnings.warn(
                "AOT-compiled runner rejected its arguments "
                f"({type(e).__name__}: {e}); falling back to the jitted "
                "runner — the lazy XLA compile will land in this call",
                RuntimeWarning,
                stacklevel=2,
            )
            return runner(batches, keys)

    return exec_fn


def _aot_warm_start(runner, batches, keys):
    """AOT-compile ``runner`` against the stripe geometry (``jit(...)
    .lower().compile()``) so XLA compilation happens HERE — in the prepare
    phase, outside the Final Time span — instead of lazily inside the
    first detect call. With ``RunConfig.compile_cache_dir`` set the
    compile inside is additionally served from the persistent cache across
    processes (restarted sweeps/soaks skip it entirely — the
    ``cold_vs_warm_compile_s`` evidence in bench artifacts).

    Returns ``(exec_fn, aot_seconds, aot_cached)``; ``(None, 0.0, False)``
    when the runner refuses to lower (exec falls back to the lazy path).
    """
    sig = (id(runner), _aval_sig((batches, keys)))
    hit = _AOT_CACHE.get(sig)
    if hit is not None:
        _AOT_CACHE.move_to_end(sig)
        return hit[1], {"aot_seconds": 0.0, "aot_cached": True}
    # Timed in two halves: trace+lower is pure host work paid every cold
    # process; the backend .compile() is the half the persistent cache
    # serves — ``aot_compile_seconds`` collapsing to ~0 on a second run
    # against a populated cache is the warm-start evidence bench/CI gate.
    def _lazy_fallback(stage, exc):
        # Loud, like every other degraded path in this layer (host_flags
        # overflow, _guarded_exec): silently reverting would put the XLA
        # compile back inside the Final Time span with aot_seconds=0.0 as
        # the only (buried) trace.
        import warnings

        warnings.warn(
            f"AOT warm-start failed at {stage} "
            f"({type(exc).__name__}: {exc}); falling back to lazy "
            "compilation — the XLA compile will land inside the first "
            "detect phase",
            RuntimeWarning,
            stacklevel=3,
        )
        return None, {"aot_seconds": 0.0, "aot_cached": False}

    t0 = time.perf_counter()
    try:
        lowered = runner.lower(batches, keys)
    except Exception as e:
        return _lazy_fallback("lower()", e)
    t1 = time.perf_counter()
    try:
        compiled = lowered.compile()
    except Exception as e:
        return _lazy_fallback("compile()", e)
    t2 = time.perf_counter()
    exec_fn = _guarded_exec(runner, compiled)
    _AOT_CACHE[sig] = (runner, exec_fn)
    while len(_AOT_CACHE) > 16:
        _AOT_CACHE.popitem(last=False)
    return exec_fn, {
        "aot_seconds": t2 - t0,
        "aot_lower_seconds": t1 - t0,
        "aot_compile_seconds": t2 - t1,
        "aot_cached": False,
    }


def _load_stream_for(cfg: RunConfig) -> StreamData:
    """The config's stream, through the ingest contract (io.sanitize)."""
    from .config import resolve_quarantine_path

    # Ingest contract (io.sanitize): strict fails loudly on dirty
    # rows, quarantine masks them (sidecar next to the run's other
    # artifacts), repair imputes. The loader validates the policy
    # name before any work.
    return load_stream(
        cfg.dataset,
        cfg.mult_data,
        seed=cfg.seed,
        standardize=cfg.standardize,
        data_policy=cfg.data_policy,
        # repair quarantines what it cannot fix, so it writes the
        # sidecar too; strict never drops a row, so it never needs one
        quarantine_path=(
            resolve_quarantine_path(cfg)
            if cfg.data_policy in ("quarantine", "repair")
            else None
        ),
    )


def _resolve_policies(cfg: RunConfig, stream: StreamData) -> RunConfig:
    """Resolve every auto policy against the stream's geometry.

    window == 0 → auto-size from the stream's planted drift spacing;
    window_rotations == 0 → auto depth (needs the resolved window first);
    ph.threshold == 0 → auto-tune λ from the same geometry.
    retrain_error_threshold auto (RETRAIN_AUTO): per-model-family guard
    resolution — config.resolve_retrain_threshold. Resolved first so the
    runner cache keys on what actually runs. Shared by :func:`prepare`
    and :func:`prepare_multi` (per tenant) so the two paths cannot drift.
    """
    cfg = replace(cfg, retrain_error_threshold=resolve_retrain_threshold(cfg))
    cfg = replace(cfg, window=auto_window(cfg, stream.dist_between_changes))
    cfg = replace(
        cfg,
        window_rotations=auto_rotations(cfg, stream.dist_between_changes),
    )
    if cfg.detector == "ph":  # auto_ph_threshold passes an explicit λ through
        cfg = replace(
            cfg,
            ph=cfg.ph._replace(
                threshold=auto_ph_threshold(cfg, stream.dist_between_changes)
            ),
        )
    return cfg


def _check_collect_config(cfg: RunConfig) -> None:
    if cfg.collect not in COLLECT_MODES:
        raise ValueError(
            f"unknown collect mode {cfg.collect!r}; expected one of "
            f"{COLLECT_MODES}"
        )
    if cfg.collect_capacity < 0:
        # A negative value is truthy, so it would bypass the auto sizing
        # and surface as an opaque trace error inside jnp.nonzero.
        raise ValueError(
            f"collect_capacity must be >= 0 (0 = auto), got "
            f"{cfg.collect_capacity}"
        )


def _build_runner(cfg: RunConfig, spec, model, nb: int, indexed: bool = False):
    """Resolve the mesh width + compaction capacity and build (or fetch)
    the compiled runner — the ONE copy of the device-selection and
    capacity policy :func:`prepare` and :func:`prepare_multi` share, so
    the solo and stacked paths cannot drift (the tenant plane's
    bit-parity contract rides on them resolving identically). ``nb`` is
    the per-partition microbatch count the compaction epilogue is sized
    against (the stacked plane passes its NB_max)."""
    n_dev = cfg.mesh_devices or len(jax.devices())
    n_dev = min(n_dev, len(jax.devices()))
    if model.host_callback:
        # Host-callback models are single-device-only (models/base.py
        # require_shardable): inside a sharded program the per-device
        # callbacks serialize on the host while the other participants block
        # at the drift-vote all-reduce — XLA's rendezvous then aborts the
        # process. An *explicitly requested* mesh fails loudly; the default
        # (mesh_devices=0 = auto) quietly runs unsharded (vmap still applies).
        if cfg.mesh_devices > 1:
            raise ValueError(
                f"model {cfg.model!r} uses a host callback and cannot run on "
                f"a {cfg.mesh_devices}-device mesh; set mesh_devices=0"
            )
        n_dev = 1
    # The mesh size must divide the partition count; fall back toward fewer
    # devices (the reference likewise ran any instance count on whatever
    # cluster existed).
    while n_dev > 1 and cfg.partitions % n_dev:
        n_dev -= 1
    # Tenant mesh axis (ROADMAP item 1): an EXPLICIT mesh_tenant_devices
    # is a sharding request, so its constraints fail loudly instead of
    # silently falling back — the flags are bit-identical at every shape,
    # but the operator asked for THIS one.
    tenant_devices = int(cfg.mesh_tenant_devices or 0)
    if tenant_devices > 1:
        tenants = max(int(cfg.tenants), 1)
        if tenants % tenant_devices:
            raise ValueError(
                f"{tenants} tenant(s) do not split over the requested "
                f"{tenant_devices}-row tenant mesh axis "
                "(mesh_tenant_devices must divide tenants)"
            )
        if n_dev % tenant_devices:
            raise ValueError(
                f"{n_dev} usable device(s) do not split over the "
                f"requested {tenant_devices}-row tenant mesh axis"
            )
    # Compaction epilogue capacity (tentpole a): sized from the stripe
    # geometry unless pinned; 0 (= full-plane collect) for the escape
    # hatches — collect='full' and validate=True, whose structural audit
    # wants the plane the device produced, not a host reconstruction.
    if cfg.collect == "compact" and not cfg.validate:
        capacity = cfg.collect_capacity or auto_compact_capacity(
            cfg.partitions, max(nb - 1, 1)
        )
    else:
        capacity = 0
    return _cached_runner(
        cfg, spec, n_dev, indexed, model, compact_capacity=capacity,
        tenant_devices=tenant_devices if tenant_devices > 1 else 0,
    )


def prepare(cfg: RunConfig, stream: StreamData | None = None) -> PreparedRun:
    """Load, stripe and compile-build a run without executing it."""
    if cfg.tenants != 1:
        raise ValueError(
            f"prepare() is the single-stream path (tenants={cfg.tenants}); "
            "use prepare_multi/run_multi for the stacked tenant plane"
        )
    _check_collect_config(cfg)
    if cfg.compile_cache_dir:
        # Persistent XLA compilation cache (warm-start, tentpole c):
        # enabled before any compile below so the runner build, the AOT
        # warm-start AND the telemetry lowering hooks all hit it.
        from .utils.compile_cache import enable_persistent_cache

        enable_persistent_cache(cfg.compile_cache_dir)
    if stream is None:
        stream = _load_stream_for(cfg)
    if cfg.validate:
        # Host-side ingest audit (utils.validate): valid rows must be
        # finite with labels in 0..C-1 — the promotion of the in-jit
        # checkify guards to a run-level switch. Cheap relative to the
        # run; outside the Final Time span (prepare phase).
        from .utils.validate import validate_stream

        validate_stream(stream)
    # Per-batch shuffle (C7 :187,190) is applied host-side at stripe time —
    # each batch is visited once, so this is semantically identical to an
    # in-loop shuffle but free on device (see io.stream.stripe_chunk).
    # Streams synthesized by duplication keep a compressed (row table + index
    # planes) form; ship that across the host→device link in its *packed*
    # variant (row table + gather indices + 1-byte shuffle perms; the
    # geometry planes are synthesized in-jit) — identical flags, ~30× less
    # transfer than the materialized stream at mult=512 (~2.3× less than
    # the round-1 indexed form).
    cfg = _resolve_policies(cfg, stream)
    # Quarantine-masked streams ride the dense striper: the packed form
    # synthesizes `valid` from pure geometry in-jit, and a row mask is
    # data, not geometry (flags are bit-identical across stripers).
    indexed = (
        stream.src is not None and cfg.window > 1
        and not stream.has_masked_rows
    )
    striper = stripe_partitions_packed if indexed else stripe_partitions
    batches = striper(
        stream, cfg.partitions, cfg.per_batch, shuffle_seed=host_shuffle_seed(cfg)
    )
    spec = ModelSpec(stream.num_features, stream.num_classes)
    model = build_model(cfg.model, spec, cfg)
    _, nb = stripe_geometry(stream.num_rows, cfg.partitions, cfg.per_batch)
    runner, mesh, compile_info = _build_runner(cfg, spec, model, nb, indexed)
    keys = jax.random.split(jax.random.key(cfg.seed), cfg.partitions)
    # AOT warm-start (tentpole c): host-callback models keep the lazy path
    # (their executables pin host state and are never cached anyway).
    exec_fn, aot_info = None, {"aot_seconds": 0.0, "aot_cached": False}
    if not model.host_callback:
        exec_fn, aot_info = _aot_warm_start(runner, batches, keys)
    compile_info = {**compile_info, **aot_info}
    return PreparedRun(
        stream, batches, runner, keys, mesh, cfg, compile_info, exec_fn
    )


class PreparedMulti(NamedTuple):
    """A stacked T-tenant run, ready to execute (see :func:`prepare_multi`).

    ``batches``/``keys`` carry the ``[T·P, ...]`` tenant plane; ``config``
    is the stacked-kernel config (``partitions = T·P``, ``tenants = T``)
    the runner was built against, ``configs`` the per-tenant resolved solo
    configs, ``nb_list`` each tenant's real microbatch count (its flag
    width is ``nb_t − 1``; stacked columns beyond it are ragged padding).
    """

    streams: tuple
    batches: object  # engine.Batches, [T·P, NB_max, B] stacked plane
    runner: object
    keys: jax.Array  # [T·P] per-(tenant, partition) PRNG keys
    mesh: object
    configs: tuple  # per-tenant resolved RunConfigs
    config: RunConfig  # the stacked-kernel config (partitions = T·P)
    nb_list: tuple  # per-tenant microbatch counts
    compile_info: "dict | None" = None
    exec_fn: "object | None" = None


def _kernel_identity(cfg: RunConfig) -> tuple:
    """The config fields that shape the compiled kernel — every tenant of
    a stacked run must agree on these (streams/seeds may differ; the
    kernel is one program). Mirrors ``_cached_runner``'s cache key minus
    the per-run identity fields (seed/dataset ride the stream, not the
    program)."""
    return (
        cfg.model, cfg.fit_steps, cfg.learning_rate, cfg.mlp_hidden,
        cfg.mlp_learning_rate, cfg.forest_trees, cfg.forest_depth,
        cfg.per_batch, cfg.partitions, cfg.ddm, cfg.window,
        cfg.retrain_error_threshold, cfg.detector, cfg.ph, cfg.eddm,
        cfg.hddm, cfg.hddm_w, cfg.adwin, cfg.kswin, cfg.stepd,
        cfg.window_rotations, cfg.shuffle_batches, cfg.collect,
        cfg.collect_capacity, cfg.validate, cfg.backend,
        cfg.mesh_devices, cfg.mesh_tenant_devices,
    )


def prepare_multi(
    cfg: "RunConfig | list[RunConfig]", streams=None
) -> PreparedMulti:
    """Load, stripe, STACK and compile-build a T-tenant run.

    The multi-tenant twin of :func:`prepare` (ROADMAP item 1): T
    independent streams — each carrying its own detector + classifier
    state — run through ONE compiled kernel whose leading axis is the
    flattened ``(tenant, partition)`` plane. Per tenant: its stream loads
    through the same ingest contract, its auto policies resolve against
    its own geometry, and it stripes with its own shuffle seed — exactly
    the solo run — then the T ``[P, NB_t, B]`` grids stack into one
    ``[T·P, NB_max, B]`` plane (``engine.loop.stack_tenants``): ragged
    tenant lengths become masked trailing microbatches absorbed by the
    validity plane, so shapes stay static (zero recompiles across tenant
    mixes of the same NB_max) and per-tenant flags are bit-identical to T
    solo runs (tested). The kernel, sharding, compaction epilogue and AOT
    warm-start are the single-stream ones — only the leading axis width
    changed, which is why compile, dispatch and collect amortize across
    the whole tenant plane (the aggregate-throughput win ``bench.py
    --tenants`` measures).

    ``cfg`` is either a ``tenants = T`` config (expanded via
    ``config.tenant_configs``: tenant t gets ``seed + t`` and a
    ``{tenant}``-substituted dataset) or an explicit list of solo configs
    — which may differ in dataset/seed/mult_data (stream identity) but
    must agree on everything that shapes the kernel
    (:func:`_kernel_identity`; loudly checked). ``streams`` optionally
    supplies pre-built per-tenant streams (None entries load from the
    config). Multi-tenant runs always ride the dense striper — a tenant
    plane is data, not geometry, exactly like the quarantine mask.
    """
    from .config import tenant_configs

    if isinstance(cfg, RunConfig):
        cfgs = tenant_configs(cfg)
    else:
        cfgs = list(cfg)
        if not cfgs:
            raise ValueError("prepare_multi needs at least one tenant config")
        for i, c in enumerate(cfgs):
            if c.tenants != 1:
                raise ValueError(
                    f"tenant config {i} has tenants={c.tenants}; explicit "
                    "config lists must hold solo (tenants=1) configs"
                )
    tenants = len(cfgs)
    _check_collect_config(cfgs[0])
    if cfgs[0].compile_cache_dir:
        from .utils.compile_cache import enable_persistent_cache

        enable_persistent_cache(cfgs[0].compile_cache_dir)
    if streams is None:
        streams = [None] * tenants
    if len(streams) != tenants:
        raise ValueError(
            f"{len(streams)} streams for {tenants} tenant configs"
        )
    resolved, loaded = [], []
    for c, s in zip(cfgs, streams):
        if s is None:
            s = _load_stream_for(c)
        if c.validate:
            from .utils.validate import validate_stream

            validate_stream(s)
        if resolved:
            # One kernel, ONE execution policy: AUTO knobs (window=0,
            # window_rotations=0, ph.threshold=0) resolve against tenant
            # 0's stream geometry and are pinned plane-wide — ragged
            # tenants would otherwise auto-resolve different kernels from
            # their own drift spacing and fail the identity check below.
            # Each pin is guarded on the auto sentinel: an EXPLICIT
            # per-tenant value must reach the identity check untouched
            # (a disagreement there is a loud error, never silently
            # overwritten). Per-tenant solo parity is against the
            # RESOLVED configs (PreparedMulti.configs /
            # MultiRunResult.results[t].config), which carry the pins.
            c0 = resolved[0]
            if not c.window:
                c = replace(c, window=c0.window)
            if not c.window_rotations:
                c = replace(c, window_rotations=c0.window_rotations)
            if c.detector == "ph" and not c.ph.threshold:
                # Pin ONLY the auto λ — the tenant's other PH fields
                # (delta/alpha/...) are explicit configuration and must
                # reach the identity check untouched.
                c = replace(
                    c, ph=c.ph._replace(threshold=c0.ph.threshold)
                )
        resolved.append(_resolve_policies(c, s))
        loaded.append(s)
    ident0 = _kernel_identity(resolved[0])
    spec0 = (loaded[0].num_features, loaded[0].num_classes)
    for t in range(1, tenants):
        if _kernel_identity(resolved[t]) != ident0:
            raise ValueError(
                f"tenant {t}'s resolved config shapes a different kernel "
                "than tenant 0's (model/detector/geometry/window fields "
                "must agree across the stacked plane; streams and seeds "
                "may differ)"
            )
        spec_t = (loaded[t].num_features, loaded[t].num_classes)
        if spec_t != spec0:
            raise ValueError(
                f"tenant {t}'s stream geometry {spec_t} (features, classes)"
                f" disagrees with tenant 0's {spec0}; one kernel, one row "
                "contract"
            )
    cfg0 = resolved[0]
    p, b = cfg0.partitions, cfg0.per_batch
    batches_list, nb_list = [], []
    for c, s in zip(resolved, loaded):
        nb_list.append(stripe_geometry(s.num_rows, p, b)[1])
        batches_list.append(
            stripe_partitions(s, p, b, shuffle_seed=host_shuffle_seed(c))
        )
    from .engine.loop import stack_tenants

    batches = stack_tenants(batches_list)
    nb_max = int(batches.y.shape[1])
    total = replace(cfg0, partitions=p * tenants, tenants=tenants)
    spec = ModelSpec(loaded[0].num_features, loaded[0].num_classes)
    model = build_model(total.model, spec, total)
    runner, mesh, compile_info = _build_runner(total, spec, model, nb_max)
    # Per-(tenant, partition) keys: tenant t's block is EXACTLY the solo
    # run's key split — split(key(seed_t), P) — so the stacked kernel's
    # per-slice PRNG streams match the solo runs bit-for-bit.
    from .engine.loop import concat_keys

    keys = concat_keys(
        [
            jax.random.split(jax.random.key(c.seed), p)
            for c in resolved
        ]
    )
    exec_fn, aot_info = None, {"aot_seconds": 0.0, "aot_cached": False}
    if not model.host_callback:
        exec_fn, aot_info = _aot_warm_start(runner, batches, keys)
    compile_info = {**compile_info, **aot_info}
    return PreparedMulti(
        tuple(loaded), batches, runner, keys, mesh, tuple(resolved), total,
        tuple(nb_list), compile_info, exec_fn,
    )


class MultiRunResult(NamedTuple):
    """One stacked multi-tenant execution: per-tenant results + the shared
    span. ``results[t]`` is tenant t's :class:`RunResult` — its flags,
    vote and delay metrics are bit-identical to the solo run's; its
    ``total_time`` is the SHARED stacked span (one kernel ran all
    tenants), which is exactly the amortization being claimed."""

    results: tuple  # per-tenant RunResult
    total_time: float  # the one stacked Final-Time span
    rows: int  # aggregate rows across tenants
    agg_rows_per_sec: float
    timings: dict
    config: RunConfig  # the stacked-kernel config (partitions = T·P)
    telemetry_path: "str | None" = None


def run_multi(
    cfg: "RunConfig | list[RunConfig]", streams=None
) -> MultiRunResult:
    """Execute a stacked T-tenant run (see :func:`prepare_multi`).

    One upload, one kernel dispatch, one collect for the whole tenant
    plane; flags are split per tenant host-side
    (``parallel.mesh.split_tenant_flags`` — free slicing of the one
    collected table, O(detections) per tenant under compaction), the
    drift vote and delay metrics are computed per tenant, and per-tenant
    results-CSV rows are appended under each tenant's own config. With
    ``telemetry_dir`` set on tenant 0's config the run emits one
    run_started/run_completed pair (config payload carries ``tenants``)
    and registers in the directory's index.jsonl like every other run.
    """
    from .parallel.mesh import split_tenant_flags, tenant_drift_vote

    timer = PhaseTimer()
    if isinstance(cfg, RunConfig):
        bracket_cfg, t_count = cfg, max(int(cfg.tenants), 1)
    else:
        if not cfg:
            raise ValueError("run_multi needs at least one tenant config")
        bracket_cfg, t_count = cfg[0], len(cfg)
    if bracket_cfg.backend != "jax":
        raise ValueError(
            f"unknown backend {bracket_cfg.backend!r}; expected 'jax' "
            "(backend='spark' is retired — see api.run)"
        )

    # The run-lifecycle telemetry (open/run_started/registry/fail/close)
    # is the shared _telemetry_bracket — one copy with _run_jax, opened
    # BEFORE prepare so a prepare-time crash (bad dataset path, kernel
    # disagreement) leaves the same failed-record evidence a solo run
    # would. The payload carries the REQUESTED knob values + `tenants`
    # (the documented digest contract: 0 = auto, resolved later), and the
    # registry record rides kind="multi" so fleet tooling can tell the
    # plane from a solo cell.
    with _telemetry_bracket(
        bracket_cfg,
        telemetry_config_payload(replace(bracket_cfg, tenants=t_count)),
        kind="multi",
    ) as log:
        if log is not None:
            from .telemetry import registry as run_registry
        with timer.phase("prepare"):
            prep = prepare_multi(cfg, streams)
        tenants = len(prep.configs)
        cfg0 = prep.configs[0]
        # --- the stacked Final-Time span: ONE upload, ONE dispatch, ONE
        # collect for all T tenants — the amortization the tenant plane
        # exists for. ---
        start = time.perf_counter()
        with timer.phase("upload"):
            dev_batches, dev_keys = shard_batches(
                prep.batches, prep.keys, prep.mesh
            )
        with timer.phase("detect"):
            out = (prep.exec_fn or prep.runner)(dev_batches, dev_keys)
            jax.block_until_ready(out)
        with timer.phase("collect"):
            flags_all, collect_info = host_flags(out)
            per_tenant = split_tenant_flags(
                flags_all, tenants, flag_cols=[nb - 1 for nb in prep.nb_list]
            )
            votes = [tenant_drift_vote(f) for f in per_tenant]
            metrics = [
                delay_metrics(
                    f.change_global, s.dist_between_changes, c.per_batch
                )
                for f, s, c in zip(per_tenant, prep.streams, prep.configs)
            ]
        total_time = time.perf_counter() - start
        # --- span ends ---

        rows = sum(s.num_rows for s in prep.streams)
        results = []
        for t, (f, v, m, s, c) in enumerate(
            zip(per_tenant, votes, metrics, prep.streams, prep.configs)
        ):
            if c.validate:
                from .utils.validate import validate_flag_rows

                validate_flag_rows(
                    f, prep.nb_list[t], c.per_batch, s.num_rows
                )
            if c.results_csv:
                a = (
                    attribution_metrics(
                        f.change_global, s.dist_between_changes, s.num_rows
                    )
                    if s.dist_between_changes > 0
                    else None
                )
                append_result(
                    c.results_csv,
                    result_row(c, total_time, m, s.num_rows, attribution=a),
                )
            results.append(
                RunResult(f, v, m, total_time, timer.as_dict(), s, c, None)
            )
        telemetry_path = None
        if log is not None:
            log.emit(
                "run_completed",
                rows=rows,
                seconds=total_time,
                detections=sum(m.num_detections for m in metrics),
                rows_per_sec=rows / total_time if total_time > 0 else None,
                tenants=tenants,
                collect_mode=collect_info.get("mode"),
                collect_overflow=bool(collect_info.get("overflow", False)),
            )
            run_registry.record(
                cfg0.telemetry_dir,
                log.run_id,
                "completed",
                rows=rows,
                seconds=total_time,
                detections=sum(m.num_detections for m in metrics),
            )
            telemetry_path = log.path

    return MultiRunResult(
        tuple(results),
        total_time,
        rows,
        rows / total_time if total_time > 0 else 0.0,
        timer.as_dict(),
        prep.config,
        telemetry_path,
    )


def prepare_chunked(
    cfg: RunConfig,
    num_features: int,
    num_classes: int,
    *,
    chunk_batches: int = 4,
    mesh=None,
    validate: bool = False,
    tenant_seeds=None,
):
    """Streaming twin of :func:`prepare`: a RunConfig → an AOT-warmed
    :class:`~..engine.chunked.ChunkedDetector` ready to serve traffic.

    The batch :func:`prepare` loads a stream, infers its geometry, and
    AOT-compiles the one-shot mesh runner; a long-lived service has no
    stream yet — its row geometry (``num_features``/``num_classes``) is
    configuration — and runs the *chunked* engine, so this resolves the
    same config policies (detector construction, RETRAIN_AUTO via the
    model-spec flag, persistent compile cache) against the chunk program
    instead. The AOT warm-start (``ChunkedDetector.prepare`` against a
    zero-row chunk of the serving geometry) compiles both chunk shapes the
    serve loop will see *before* the first row arrives; with
    ``cfg.compile_cache_dir`` the backend compile is served from the
    persistent cache across daemon restarts. ``cfg.window`` must be
    explicit (the 0 = auto policy needs planted-drift geometry a live
    stream does not declare). Returns ``(detector, compile_info)``.

    ``cfg.tenants > 1`` builds the stacked tenant plane — the streaming
    twin of :func:`prepare_multi`: one ``[T·P, CB, B]`` chunk program
    whose per-tenant state blocks are bit-identical to T solo detectors
    (tenant seeds follow ``config.tenant_configs``: ``seed + t``); the
    AOT warm-start compiles against the stacked geometry. ``tenant_seeds``
    overrides the per-slot detector seeds — the fleet posture
    (``ServeParams.tenant_ids``), where slot s serves GLOBAL tenant
    ``ids[s]`` and must carry ``seed + ids[s]``'s solo identity.
    """
    import numpy as _np

    from .engine.chunked import ChunkedDetector
    from .io.stream import stripe_chunk
    from .ops.detectors import make_detector

    if cfg.window == 0:
        raise ValueError(
            "window=0 (auto) needs stream geometry a serving daemon does "
            "not have; pass an explicit width (config.auto_window can "
            "compute one from a known drift spacing)"
        )
    if num_features <= 0 or num_classes <= 0:
        raise ValueError(
            f"serving geometry must be explicit: num_features="
            f"{num_features}, num_classes={num_classes} (both must be > 0)"
        )
    if chunk_batches <= 0:
        raise ValueError(f"chunk_batches must be > 0, got {chunk_batches}")
    if cfg.compile_cache_dir:
        from .utils.compile_cache import enable_persistent_cache

        enable_persistent_cache(cfg.compile_cache_dir)
    if mesh is None and cfg.mesh_tenant_devices > 1:
        # Tenant-mesh serving (ROADMAP item 1): shard the stacked chunk
        # plane over a 2-D (tenant, partition) mesh. The detector
        # validates tenant-axis divisibility; flags stay bit-identical
        # at every shape (the serve parity contract over shardings).
        mesh = make_mesh(
            cfg.mesh_devices, tenant_devices=cfg.mesh_tenant_devices
        )
    t0 = time.perf_counter()
    spec = ModelSpec(num_features, num_classes)
    model = build_model(cfg.model, spec, cfg)
    det = ChunkedDetector(
        model,
        cfg.ddm,
        partitions=cfg.partitions,
        shuffle=False,  # serve stripes host-side (config.host_shuffle_seed)
        retrain_error_threshold=cfg.retrain_error_threshold,
        seed=cfg.seed,
        window=cfg.window,
        mesh=mesh,
        detector=make_detector(
            cfg.detector,
            ddm=cfg.ddm,
            ph=cfg.ph,
            eddm=cfg.eddm,
            hddm=cfg.hddm,
            hddm_w=cfg.hddm_w,
            adwin=cfg.adwin,
            kswin=cfg.kswin,
            stepd=cfg.stepd,
        ),
        rotations=cfg.window_rotations or 1,
        validate=validate,
        tenants=cfg.tenants,
        tenant_seeds=tenant_seeds,
    )
    build_seconds = time.perf_counter() - t0
    example = stripe_chunk(
        _np.zeros((0, num_features), _np.float32),
        _np.zeros((0,), _np.int32),
        0,
        cfg.partitions,
        cfg.per_batch,
        chunk_batches,
    )
    if cfg.tenants > 1:
        # The AOT warm-start must see the STACKED chunk geometry the
        # tenant plane will actually feed ([T·P, CB, B]).
        from .engine.loop import stack_tenants

        example = stack_tenants([example] * cfg.tenants)
    info = {"cached": False, "build_seconds": build_seconds}
    if not model.host_callback:
        info.update(det.prepare(example))
    return det, info


class RunResult(NamedTuple):
    flags: FlagRows  # numpy leaves [P, NB-1]
    drift_vote: np.ndarray  # [NB-1]
    metrics: DelayMetrics
    total_time: float  # the reference's "Final Time" span
    timings: dict  # per-phase breakdown (aux subsystem: tracing)
    stream: StreamData
    config: RunConfig
    # Path of the persisted JSONL run log (telemetry subsystem) — None
    # unless cfg.telemetry_dir was set.
    telemetry_path: "str | None" = None


@contextlib.contextmanager
def _telemetry_bracket(cfg: RunConfig, payload: dict, kind: "str | None" = None):
    """The run-lifecycle telemetry bracket shared by :func:`_run_jax` and
    :func:`run_multi` — one copy of the open/emit/record/fail/close
    contract, so the batch and multi-tenant paths cannot drift.

    On entry (telemetry enabled): open the run log, emit ``run_started``
    with ``payload`` + host identity, and write the registry ``running``
    record (``kind`` rides when given). Yields the log (None when
    telemetry is off — no telemetry code runs at all). On an exception the
    registry gets a best-effort ``failed`` record — the run's own
    exception is the one that must surface, so a failing append (e.g. the
    full volume that broke the run) is swallowed — and the log's fd is
    released either way: the partial log is the crash evidence (lines are
    flushed per emit), but a long-lived process catching per-run errors
    must not leak a descriptor per failure. The happy path's ``completed``
    record and final events stay with the caller (they carry run-shape-
    specific payloads); callers may close the log early (close is
    idempotent).
    """
    if not cfg.telemetry_dir:
        yield None
        return
    from .parallel.multihost import host_identity
    from .telemetry import registry as run_registry
    from .telemetry.events import EventLog

    ident = host_identity()
    log = EventLog.open_run(
        cfg.telemetry_dir,
        name=cfg.resolved_app_name(),
        process_index=ident["process_index"],
    )
    try:
        log.emit("run_started", run_id=log.run_id, config=payload, **ident)
        run_registry.record(
            cfg.telemetry_dir,
            log.run_id,
            "running",
            **({"kind": kind} if kind else {}),
            config_digest=run_registry.config_digest(payload),
            log=os.path.basename(log.path),
            **ident,
        )
        yield log
    except BaseException:
        try:
            run_registry.record(cfg.telemetry_dir, log.run_id, "failed")
        except Exception:
            pass
        raise
    finally:
        log.close()


def run(cfg: RunConfig, stream: StreamData | None = None) -> RunResult:
    if cfg.tenants != 1:
        raise ValueError(
            f"run() is the single-stream path (tenants={cfg.tenants}); the "
            "multi-tenant result is per-tenant structured — use run_multi"
        )
    if cfg.backend == "spark":
        # Recorded decision (round 5; PARITY.md C3, README "Spark seam"):
        # the seam is retired, not stubbed — see the module docstring.
        raise ValueError(
            "backend='spark' is retired: the reference's Spark execution "
            "model (DDM_Process.py:58-72) is fully replaced by the native "
            "backend='jax' path (same RunConfig, same results schema), "
            "PySpark is not part of the supported environment, and a "
            "local-mode reimplementation would exercise none of the "
            "cluster semantics. For flag-level A/B against the reference's "
            "loop semantics use the NumPy oracle (tests/oracle.py) or the "
            "delay-parity harness (harness/parity.py)."
        )
    if cfg.backend != "jax":
        raise ValueError(f"unknown backend {cfg.backend!r}; expected 'jax'")
    return _run_jax(cfg, stream)


def _run_jax(cfg: RunConfig, stream: StreamData | None) -> RunResult:
    timer = PhaseTimer()

    if cfg.profile_dir and cfg.trace_dir:
        raise ValueError(
            "profile_dir and trace_dir are mutually exclusive (jax rejects "
            "nested profiler sessions): profile_dir captures the whole "
            "Final Time span, trace_dir only the detect phase — pick one"
        )

    # Telemetry (off by default): the event log is opened before the work
    # and written AFTER the Final Time span closes — nothing below touches
    # the timed region, and with telemetry_dir unset no telemetry code runs.
    # Each process of a multi-host run opens its OWN log (the procN filename
    # segment + the run_started host-identity extras are what the correlate
    # CLI merges on), and registers it in the directory's index.jsonl so the
    # fleet view (which runs exist, did they finish) never requires parsing
    # every log. The open/emit/record/fail/close lifecycle is the shared
    # _telemetry_bracket (one copy with run_multi); the payload is shared
    # with resilience.heal — the heal planner recomputes these digests from
    # a sweep spec, so the field set lives in one place
    # (config.telemetry_config_payload).
    with _telemetry_bracket(cfg, telemetry_config_payload(cfg)) as log:
        if log is not None:
            from .telemetry import registry as run_registry
        # Fault-injection site (resilience.faults; no-op unless armed):
        # a whole-run crash inside the registry bracket, so the failed
        # record + partial log land exactly as a real crash would leave
        # them — what the supervised-retry and heal tests exercise.
        faults.fire("api.run", run_id=None if log is None else log.run_id)
        # Telemetered runs get a PER-RUN quarantine sidecar named after
        # the run log (<run>.quarantine.jsonl): the sidecar is append-only
        # by design, and a shared fixed path would interleave every
        # trial's records with no way to attribute them to a run. An
        # explicit quarantine_path still wins; without telemetry the
        # resolve_quarantine_path default applies.
        if (
            log is not None
            and cfg.data_policy in ("quarantine", "repair")
            and not cfg.quarantine_path
        ):
            cfg = replace(
                cfg,
                quarantine_path=os.path.splitext(log.path)[0]
                + ".quarantine.jsonl",
            )
        with timer.phase("prepare"):
            prep = prepare(cfg, stream)
        stream, batches, runner, keys, mesh = (
            prep.stream, prep.batches, prep.runner, prep.keys, prep.mesh
        )
        cfg = prep.config  # window=0 auto already resolved by prepare()

        # Device-memory snapshot BEFORE the detect phase (telemetry.profile)
        # — taken here, between prepare and the span open, so it is outside
        # the reference-parity timed region; None where the backend reports
        # nothing (XLA CPU). Gated on the log: with telemetry off no
        # profile code runs at all.
        pre_mem = None
        if log is not None:
            from .telemetry.profile import device_memory_stats

            pre_mem = device_memory_stats()
            # Ingest-quarantine evidence (io.sanitize, data_policy=
            # 'quarantine'/'repair'): emitted here, between prepare and
            # the span open — outside the reference-parity timed region,
            # like the memory snapshot above. Only when rows were
            # actually dropped: a clean stream leaves no trace.
            q = prep.stream.quarantine
            if q is not None and q.rows_quarantined:
                log.emit(
                    "rows_quarantined",
                    rows=q.rows_quarantined,
                    policy=q.policy,
                    sidecar=q.sidecar,
                    repaired=q.rows_repaired,
                )

        # --- the reference's Final Time span starts here (:224) ---
        # cfg.profile_dir (opt-in) wraps the WHOLE span in a jax.profiler
        # capture; the session opens before `start` and closes after
        # total_time is taken, so its start/stop overhead stays outside
        # the measured region (the in-span capture overhead is the point
        # of profiling and is documented as perturbing).
        with maybe_trace(cfg.profile_dir):
            start = time.perf_counter()
            with timer.phase("upload"):
                dev_batches, dev_keys = shard_batches(batches, keys, mesh)
            with timer.phase("detect"), maybe_trace(cfg.trace_dir):
                # The AOT-compiled executable when warm-start succeeded
                # (compile already paid in prepare), else the jitted runner.
                out = (prep.exec_fn or runner)(dev_batches, dev_keys)
                jax.block_until_ready(out)
            with timer.phase("collect"):
                # One latency-bound d2h transfer: the device-compacted
                # detection table (O(detections) bytes) when the compaction
                # epilogue ran, the packed flag plane otherwise — with a
                # loud full-plane fallback on table overflow
                # (parallel.mesh.host_flags). The drift vote is recomputed
                # host-side from the flags in f32, matching the device
                # reduction's dtype and arithmetic (sum of exact 0/1
                # indicators, one f32 divide).
                flags, collect_info = host_flags(out)
                changed = (flags.change_global >= 0).astype(np.float32)
                vote = changed.sum(axis=0, dtype=np.float32) / np.float32(
                    changed.shape[0]
                )
                m = delay_metrics(
                    flags.change_global,
                    stream.dist_between_changes,
                    cfg.per_batch,
                )
            total_time = time.perf_counter() - start
        # --- span ends (:260) ---

        if cfg.validate:
            from .utils.validate import validate_flag_rows

            from .io.stream import stripe_geometry

            # Expected batch count from the stripe geometry — independent of
            # the flags table, so the audit can catch a dropped/duplicated
            # boundary.
            _, nb = stripe_geometry(
                stream.num_rows, cfg.partitions, cfg.per_batch
            )
            validate_flag_rows(flags, nb, cfg.per_batch, stream.num_rows)

        if cfg.results_csv:
            # Boundary attribution (metrics.attribution_metrics) is computed
            # OUTSIDE the Final Time span: the reference's timed region ends
            # at the delay metric (:260) and the quality axes are bookkeeping
            # on the already-collected flag table, not part of the benchmarked
            # pipeline. Streams without planted-boundary geometry have no
            # ground truth to attribute against — their quality cells carry
            # the placeholder, not an every-detection-is-spurious fabrication.
            a = (
                attribution_metrics(
                    flags.change_global,
                    stream.dist_between_changes,
                    stream.num_rows,
                )
                if stream.dist_between_changes > 0
                else None
            )
            append_result(
                cfg.results_csv,
                result_row(cfg, total_time, m, stream.num_rows, attribution=a),
            )

        telemetry_path = None
        if log is not None:
            telemetry_path = _finish_telemetry(
                log, prep, timer, flags, m, stream, total_time, pre_mem,
                # The committed (mesh-sharded) arrays the runner actually
                # executed with: lowering with these analyzes the SAME
                # program the span ran, not a default-placement twin.
                runner_args=(dev_batches, dev_keys),
                collect_info=collect_info,
            )
            run_registry.record(
                cfg.telemetry_dir,
                log.run_id,
                "completed",
                rows=stream.num_rows,
                seconds=total_time,
                detections=m.num_detections,
            )

    return RunResult(
        flags, vote, m, total_time, timer.as_dict(), stream, cfg,
        telemetry_path,
    )


def _finish_telemetry(
    log, prep: PreparedRun, timer, flags: FlagRows, m: DelayMetrics,
    stream: StreamData, total_time: float, pre_mem: "dict | None" = None,
    runner_args: "tuple | None" = None,
    collect_info: "dict | None" = None,
) -> str:
    """Persist the run's events + metric exports (after the timed span).

    This is the ONLY place the compiler/device introspection
    (telemetry.profile) runs from inside a run — strictly after the Final
    Time span closed (the purity test pins this via the caller graph).
    ``runner_args`` are the committed device arrays the runner executed
    with, so the analyzed program is the executed one (sharding included).
    The real cost is re-lowering + AOT-compiling the runner for
    ``cost_analysis``/``memory_analysis`` — roughly one extra compile per
    telemetered run unless a persistent compile cache is enabled (bench.py
    enables one; api.run does not) — the opt-in observability trade.
    """
    from .telemetry import profile as _profile
    from .telemetry.events import emit_flag_events
    from .telemetry.metrics import MetricsRegistry, write_exports

    cfg = prep.config
    info = prep.compile_info or {"cached": False, "build_seconds": 0.0}
    log.emit(
        "compile_completed",
        cached=info["cached"],
        seconds=info["build_seconds"],
        window=cfg.window,  # the resolved execution policy (0=auto applied)
        window_rotations=cfg.window_rotations,
        # AOT warm-start split (extras; schema allows them): the prepare-
        # phase lower().compile() span and whether the in-process AOT cache
        # served it — with a persistent compile cache, a restarted process
        # shows aot_cached=False with near-zero aot_seconds (the cache-hit
        # evidence the warm-start CI asserts on through bench).
        aot_seconds=info.get("aot_seconds", 0.0),
        aot_cached=info.get("aot_cached", False),
    )
    for name, secs in timer.as_dict().items():
        log.emit("phase_completed", phase=name, seconds=secs)
    # Compiler introspection of the runner that just executed, at the
    # arguments it executed with (falling back to the host pytrees — same
    # avals, default placement — for callers without the device arrays).
    args = runner_args if runner_args is not None else (prep.batches, prep.keys)
    xla_stats = _profile.compiled_stats(prep.runner, *args)
    _profile.emit_compiled_events(log, xla_stats, where="detect_runner")
    post_mem = _profile.device_memory_stats()
    _profile.emit_device_memory_event(log, pre_mem, when="before_detect")
    _profile.emit_device_memory_event(log, post_mem, when="after_detect")
    emit_flag_events(
        log,
        flags.change_global,
        flags.forced_retrain,
        stream.dist_between_changes,
    )
    # Collect-transport provenance (extras; schema allows them): which
    # path the collect phase actually shipped — and, critically, whether
    # the compacted table OVERFLOWED into the full-plane fallback. A
    # stream that overflows every run silently pays the full-plane d2h
    # the compaction exists to remove; the fleet operator must be able to
    # see that in the run log, not just in a stderr RuntimeWarning.
    collect_extras = {}
    if collect_info is not None:
        collect_extras = {
            "collect_mode": collect_info.get("mode"),
            "collect_events": collect_info.get("events"),
            "collect_overflow": bool(collect_info.get("overflow", False)),
        }
    log.emit(
        "run_completed",
        rows=stream.num_rows,
        seconds=total_time,
        detections=m.num_detections,
        rows_per_sec=(
            stream.num_rows / total_time if total_time > 0 else None
        ),
        **collect_extras,
    )
    log.close()

    reg = MetricsRegistry()
    det = reg.counter(
        "detections_total", help="Drift detections by stream partition"
    )
    for q, n in enumerate(np.asarray(m.detections_per_partition)):
        if n:
            det.inc(int(n), partition=str(q))
    reg.counter(
        "rows_processed_total", help="Stream rows through the detection loop"
    ).inc(stream.num_rows)
    if stream.quarantine is not None and stream.quarantine.rows_quarantined:
        from .io.sanitize import QUARANTINE_METRIC, QUARANTINE_METRIC_HELP

        reg.counter(QUARANTINE_METRIC, help=QUARANTINE_METRIC_HELP).inc(
            stream.quarantine.rows_quarantined
        )
    reg.gauge(
        "compile_seconds", help="Runner build time (0 on runner-cache hit)"
    ).set(info["build_seconds"])
    phase_h = reg.histogram(
        "phase_seconds", help="Wall-clock seconds by run phase"
    )
    for name, secs in timer.as_dict().items():
        phase_h.observe(secs, phase=name)
    _profile.record_compiled_gauges(reg, xla_stats)
    _profile.record_device_memory_gauges(reg, pre_mem, when="before_detect")
    _profile.record_device_memory_gauges(reg, post_mem, when="after_detect")
    base, _ = os.path.splitext(log.path)
    write_exports(reg, base)
    return log.path


