"""Phase timers (aux subsystem: tracing/profiling, SURVEY.md §5).

The reference's only instrumentation is one wall-clock span
(``DDM_Process.py:224,260``). Here every run gets a per-phase breakdown
(load/stripe/build/upload/detect/collect) plus an optional ``jax.profiler``
trace for TPU work.
"""

from __future__ import annotations

import contextlib
import time


class PhaseTimer:
    def __init__(self):
        self.phases: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + time.perf_counter() - t0

    def as_dict(self) -> dict:
        return dict(self.phases)


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None):
    """``jax.profiler.trace`` when a directory is given, else a no-op."""
    if trace_dir:
        import jax

        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
