"""Phase timers (aux subsystem: tracing/profiling, SURVEY.md §5).

The reference's only instrumentation is one wall-clock span
(``DDM_Process.py:224,260``). Here every run gets a per-phase breakdown
(load/stripe/build/upload/detect/collect) plus an optional ``jax.profiler``
trace for TPU work.

``PhaseTimer`` is now a **compatibility shim** over
:class:`..telemetry.spans.SpanTracker` — same ``phase(name)`` context
manager, same cumulative ``as_dict()`` contract — with the tracker's
extras (nesting, call counts, first-call-vs-steady-state split via
``stats()``) available on the same object. New code should use
``SpanTracker`` directly.
"""

from __future__ import annotations

import contextlib

from ..telemetry.spans import SpanTracker


class PhaseTimer(SpanTracker):
    """``SpanTracker`` under the historical name/API: ``phase`` aliases
    ``span`` and the mutable ``phases`` attribute is a read view."""

    phase = SpanTracker.span

    @property
    def phases(self) -> dict[str, float]:
        return self.as_dict()


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None):
    """``jax.profiler.trace`` when a directory is given, else a no-op."""
    if trace_dir:
        import jax

        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
