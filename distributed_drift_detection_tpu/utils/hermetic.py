"""Hermetic CPU environment for subprocess re-execution.

Several entry points must run JAX on a virtual CPU mesh *no matter what the
host environment wants*: the driver's multi-chip dry run
(``__graft_entry__.dryrun_multichip`` — whose round-1 artifact recorded a
failure precisely because a TPU tunnel was probed first), the delay-parity
harness (``harness.parity``), and the multi-process multihost test. Each
re-executes itself in a fresh subprocess; this helper builds that
subprocess's environment in ONE place so every hardening (a new site-hook
variable, a new platform override) lands everywhere at once.

Three layers of defence:

* ``JAX_PLATFORMS=cpu`` (and dropping the legacy ``JAX_PLATFORM_NAME``);
* dropping ``PALLAS_AXON_POOL_IPS`` — a site hook keyed on it can pin an
  accelerator platform via ``jax.config`` at interpreter start, which
  *outranks* ``JAX_PLATFORMS``;
* ``--xla_force_host_platform_device_count=N`` in ``XLA_FLAGS`` (any
  pre-existing count flag is removed first; ``n_devices=None`` removes
  without re-adding, letting the child pin its own count).

The child should still call ``jax.config.update("jax_platforms", "cpu")``
before its first backend touch as a belt-and-braces config-level pin (see
``tests/conftest.py``).
"""

from __future__ import annotations

import os

# Environment variables that can override or outrank JAX_PLATFORMS.
_PLATFORM_OVERRIDES = ("JAX_PLATFORM_NAME", "PALLAS_AXON_POOL_IPS")


def hermetic_cpu_env(
    n_devices: int | None = None, base: dict | None = None
) -> dict:
    """A copy of ``base`` (default ``os.environ``) forced to CPU-only JAX."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    for var in _PLATFORM_OVERRIDES:
        env.pop(var, None)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    if n_devices is not None:
        flags.append(f"--xla_force_host_platform_device_count={int(n_devices)}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
