from .timing import PhaseTimer, maybe_trace

__all__ = ["PhaseTimer", "maybe_trace"]
