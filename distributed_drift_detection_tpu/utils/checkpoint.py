"""Checkpoint/resume of the streaming carry (aux subsystem, SURVEY.md §5).

The reference has **no** in-run checkpointing — its only cross-run
persistence is the results CSV append, and crash recovery is whole-run
re-execution (``README.md:13``). Here the entire resumable state — model
params, DDM statistics, carried batch_a, retrain flags, PRNG keys, stream
offset — is one small pytree (a few KB per partition), saved as a flat
``.npz`` plus JSON metadata. Loading requires a structurally-identical
template pytree (the natural situation on resume: rebuild the detector with
the same config, then restore). Typed PRNG-key arrays round-trip via their
uint32 key data.

Crash posture (resilience subsystem): :func:`save_checkpoint` is
**atomic** — it writes to a same-directory temp file, fsyncs, and
``os.replace``s into place, so a crash mid-write (including the injected
``checkpoint.save`` fault) can tear only the temp file, never a
previously good checkpoint. :func:`load_checkpoint` turns the raw numpy
zip errors a torn file produces into a clear
:class:`CheckpointCorruptError` naming the path, so a resume that finds
garbage says "torn/corrupt checkpoint", not ``BadZipFile``.
"""

from __future__ import annotations

import json
import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np

from ..resilience import faults


class CheckpointCorruptError(RuntimeError):
    """The checkpoint file is torn or corrupt (crash mid-write on a
    pre-atomic writer, bit rot, truncation). Subclasses ``RuntimeError``
    — a retry policy classifies it transient, but the standard recovery
    is to delete the file and restart the chain from scratch."""


def _is_key(leaf) -> bool:
    return hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key)


def _to_host(leaf) -> np.ndarray:
    if _is_key(leaf):
        return np.asarray(jax.random.key_data(leaf))
    return np.asarray(leaf)


def save_checkpoint(path: str, pytree, meta: dict | None = None) -> None:
    """Atomically persist ``pytree`` (+ JSON-able ``meta``) to ``path``.

    Write → flush → fsync → ``os.replace``: a reader never observes a
    partial file at ``path``, and a crash between the temp write and the
    rename leaves the previous checkpoint intact (the orphaned ``.tmp``
    is overwritten by the next save). The temp file lives in the target's
    directory so the rename stays same-filesystem (POSIX atomicity).
    """
    leaves = jax.tree.leaves(pytree)
    arrays = {f"leaf_{i}": _to_host(leaf) for i, leaf in enumerate(leaves)}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    # Fault-injection site (resilience.faults; no-op unless armed): a
    # kill between write and rename — kind='torn_write' truncates the
    # temp file mid-byte first, the shape a real mid-write crash leaves.
    faults.fire("checkpoint.save", file=tmp, path=path)
    os.replace(tmp, path)


def load_checkpoint(path: str, template) -> tuple[object, dict]:
    """Restore a pytree with the same structure/shapes/dtypes as ``template``.

    A file that cannot be parsed as a checkpoint archive raises
    :class:`CheckpointCorruptError`; structural disagreements with the
    template (leaf count, shapes) stay ``ValueError`` — that is a *wrong*
    checkpoint, not a broken one.
    """
    try:
        with np.load(path) as data:
            meta = json.loads(bytes(data["__meta__"]).decode())
            leaves = [data[f"leaf_{i}"] for i in range(len(data.files) - 1)]
    # Only parse-shaped failures mean corruption; genuine I/O errors
    # (permissions, a flaky mount) propagate as themselves — converting
    # them would tell an operator to delete a perfectly good checkpoint.
    except (zipfile.BadZipFile, EOFError, KeyError,
            json.JSONDecodeError, ValueError) as e:
        raise CheckpointCorruptError(
            f"torn/corrupt checkpoint {path!r}: cannot parse it as a saved "
            f"state archive ({type(e).__name__}: {e}) — it was likely cut "
            "off mid-write by a crash; delete it to restart from scratch"
        ) from e
    t_leaves, treedef = jax.tree.flatten(template)
    if len(t_leaves) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, template has {len(t_leaves)}"
        )
    restored = []
    for got, want in zip(leaves, t_leaves):
        if _is_key(want):
            restored.append(jax.random.wrap_key_data(jnp.asarray(got)))
            continue
        want_np = np.asarray(want)
        if got.shape != want_np.shape:
            raise ValueError(
                f"checkpoint leaf shape {got.shape} != template {want_np.shape}"
            )
        restored.append(got.astype(want_np.dtype) if got.dtype != want_np.dtype else got)
    return jax.tree.unflatten(treedef, restored), meta
