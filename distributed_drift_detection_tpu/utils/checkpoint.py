"""Checkpoint/resume of the streaming carry (aux subsystem, SURVEY.md §5).

The reference has **no** in-run checkpointing — its only cross-run
persistence is the results CSV append, and crash recovery is whole-run
re-execution (``README.md:13``). Here the entire resumable state — model
params, DDM statistics, carried batch_a, retrain flags, PRNG keys, stream
offset — is one small pytree (a few KB per partition), saved as a flat
``.npz`` plus JSON metadata. Loading requires a structurally-identical
template pytree (the natural situation on resume: rebuild the detector with
the same config, then restore). Typed PRNG-key arrays round-trip via their
uint32 key data.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np


def _is_key(leaf) -> bool:
    return hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key)


def _to_host(leaf) -> np.ndarray:
    if _is_key(leaf):
        return np.asarray(jax.random.key_data(leaf))
    return np.asarray(leaf)


def save_checkpoint(path: str, pytree, meta: dict | None = None) -> None:
    leaves = jax.tree.leaves(pytree)
    arrays = {f"leaf_{i}": _to_host(leaf) for i, leaf in enumerate(leaves)}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    )
    with open(path, "wb") as fh:
        np.savez(fh, **arrays)


def load_checkpoint(path: str, template) -> tuple[object, dict]:
    """Restore a pytree with the same structure/shapes/dtypes as ``template``."""
    with np.load(path) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files) - 1)]
    t_leaves, treedef = jax.tree.flatten(template)
    if len(t_leaves) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, template has {len(t_leaves)}"
        )
    restored = []
    for got, want in zip(leaves, t_leaves):
        if _is_key(want):
            restored.append(jax.random.wrap_key_data(jnp.asarray(got)))
            continue
        want_np = np.asarray(want)
        if got.shape != want_np.shape:
            raise ValueError(
                f"checkpoint leaf shape {got.shape} != template {want_np.shape}"
            )
        restored.append(got.astype(want_np.dtype) if got.dtype != want_np.dtype else got)
    return jax.tree.unflatten(treedef, restored), meta
