"""Counter-based host PRNG utilities.

``row_uniforms`` yields uniforms that depend only on (seed, stream_id, row
index) — chunking-invariant by construction (counter-based Philox advanced to
the absolute row), which is what lets generators and per-batch shuffles
produce identical results regardless of how the stream is chunked.
"""

from __future__ import annotations

import numpy as np


def row_uniforms(
    seed: int, start: int, n: int, per_row: int, stream_id: int
) -> np.ndarray:
    """``[n, per_row]`` f64 uniforms for absolute rows [start, start+n)."""
    width = -4 * (-per_row // 4)  # one Philox advance unit = one 4x64-bit
    bitgen = np.random.Philox(key=np.uint64(seed) ^ (np.uint64(stream_id) << 32))
    bitgen.advance(int(start) * (width // 4))  # block = 4 f64 draws
    return np.random.Generator(bitgen).random((n, width))[:, :per_row]
