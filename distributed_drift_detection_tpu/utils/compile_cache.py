"""Persistent XLA compilation cache — the warm-start knob.

One shared switch for every entry point that accepts a cache directory
(``RunConfig.compile_cache_dir`` / ``--compile-cache-dir`` / bench.py's
``.jax_cache`` default): point jax's persistent compilation cache at the
directory so compiled executables survive process restarts. A second
process compiling the same program (same HLO, same backend) deserializes
the cached executable instead of recompiling — repeated sweep cells,
restarted soak legs and re-run benchmarks then pay tracing only, with the
XLA compile split ≈ 0 (the ``cold_vs_warm_compile_s`` pair in bench
artifacts is the measured evidence).

The minimum-compile-time threshold defaults to 0 here (bench historically
used 0.5 s): sweep cells are small programs, and a threshold that skips
them caches exactly the executables that did not need caching.
"""

from __future__ import annotations

import os

# The directory currently enabled, or None. Enabling is process-global
# (jax config) and idempotent; switching directories mid-process is
# honored but unusual — the last call wins, matching jax's own semantics.
_enabled_dir: str | None = None


def enable_persistent_cache(
    path: str | None, min_compile_seconds: float = 0.0
) -> str | None:
    """Enable jax's persistent compilation cache at ``path``.

    No-op on an empty/None path (the knob's off state) and on repeat calls
    with the same directory. Returns the enabled directory (created if
    missing), or None when disabled. Lazy jax import: config-only callers
    (CLI validation) never initialise a backend through this module.
    """
    global _enabled_dir
    if not path:
        return None
    path = os.path.abspath(path)
    if path == _enabled_dir:
        return path
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_seconds)
    )
    # jax initialises the persistent-cache backend once, at the process's
    # first compile — enabling (or moving) the directory after any compile
    # has happened is otherwise a silent no-op (verified on jax 0.4.37).
    # Force a re-init; best-effort private API, so a jax that moved it
    # degrades to the first-compile-wins behaviour instead of crashing.
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:
        pass
    _enabled_dir = path
    return path


def enabled_dir() -> str | None:
    """The directory the persistent cache is currently pointed at (None =
    disabled) — artifact provenance for bench.py."""
    return _enabled_dir
