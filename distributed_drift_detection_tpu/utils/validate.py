"""Numeric-sanity validation — the framework's sanitizer subsystem.

SURVEY.md §5 (race detection / sanitizers): the reference has no shared
mutable state to race on, so the TPU-native equivalent is *jit purity plus
functional checks on the detector statistics*. Two layers:

* :func:`checked_ddm_window` — a ``jax.experimental.checkify`` wrapping of the
  DDM window kernel that validates its contract **inside jit**: error inputs
  are 0/1 indicators, the carried state is a coherent ``(count, err_sum)``
  pair, and the post-update statistics are finite. Use it when developing new
  feeders/models; the checks compile into the program and survive jit/vmap.
* :func:`validate_flag_rows` — a host-side structural audit of a run's flag
  table (sentinel domain, index ranges, warning/change exclusivity), cheap
  enough to run on every collect. Enabled in ``api.run`` via
  ``RunConfig(validate=True)``.

The reference's only analog is eyeballing the results CSV; these checks catch
the failure modes a TPU port actually risks — padding rows leaking into the
statistics, f32 overflow in long windows, index-plane corruption in the
compressed stream path.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax.experimental import checkify

from ..config import DDMParams
from ..ops.ddm import DDMState, ddm_window


def checked_ddm_window(
    state: DDMState,
    errs,
    valid,
    params: DDMParams = DDMParams(),
):
    """:func:`ops.ddm.ddm_window` with in-jit contract checks.

    Returns ``(err, (end_state, result))`` in checkify style;
    ``err.throw()`` raises on the first violated check.
    """

    def f(state, errs, valid):
        checkify.check(
            jnp.all((errs == 0.0) | (errs == 1.0)),
            "errs must be 0/1 error indicators",
        )
        checkify.check(
            state.count >= 0, "detector count must be non-negative"
        )
        checkify.check(
            (state.err_sum >= -1e-3)
            & (state.err_sum <= state.count.astype(jnp.float32) + 1e-3),
            "err_sum must lie in [0, count]",
        )
        # f32 error sums are exact below 2**24 elements between resets
        # (ops.ddm numerical note); past that the p statistic silently loses
        # precision, so fail loudly instead.
        checkify.check(
            state.count.astype(jnp.float32) + errs.size < 2.0**24,
            "detector count near f32 exactness limit (2^24); reset overdue",
        )
        end, res = ddm_window(state, errs, valid, params)
        checkify.check(
            jnp.isfinite(end.err_sum) & (end.count >= state.count),
            "post-update state must be finite and monotone in count",
        )
        return end, res

    return checkify.checkify(f)(state, errs, valid)


def validate_stream(stream) -> None:
    """Host-side ingest audit of a prepared ``io.stream.StreamData``.

    The promotion of the in-jit checkify contract to a run-level switch
    (``RunConfig(validate=True)`` — ``api.prepare`` calls this before any
    device work): every *valid* row's features must be finite, labels in
    ``0..C-1``, and the quarantine mask (when present) shape-aligned.
    Masked rows are exempt by definition — they never reach compute —
    which is exactly what the dirty-stream subsystem promises. Raises
    ``ValueError`` naming the first offending stream position.
    """
    # Audit the table for compressed streams (every stream row is a table
    # gather, so a finite table is a finite stream) — the dense planes
    # otherwise. The mask audited alongside is the matching one (table
    # mask for tables), so no [N] mask ever materializes here.
    if stream.src is not None:
        X, y = stream.base_X, stream.base_y
        t_ok = stream.base_ok
    else:
        X, y = stream.X, stream.y
        t_ok = stream.row_ok
    if t_ok is not None:
        t_ok = np.asarray(t_ok, bool)
        if t_ok.shape != (len(y),):
            raise ValueError(
                f"stream validation failed: row mask shape {t_ok.shape} "
                f"!= ({len(y)},)"
            )
        if not t_ok.any():
            raise ValueError(
                "stream validation failed: every row is masked"
            )
    sel_X = X if t_ok is None else X[t_ok]
    sel_y = y if t_ok is None else y[t_ok]
    if not np.isfinite(sel_X).all():
        bad = ~np.isfinite(np.asarray(X)).all(axis=1)
        if t_ok is not None:
            bad &= t_ok
        rows = np.nonzero(bad)[0]
        raise ValueError(
            "stream validation failed: non-finite feature value(s) in "
            f"valid row(s) {rows[:5].tolist()}"
        )
    if sel_y.size and (
        (sel_y < 0).any() or (sel_y >= max(stream.num_classes, 1)).any()
    ):
        bad = sel_y[(sel_y < 0) | (sel_y >= max(stream.num_classes, 1))]
        raise ValueError(
            "stream validation failed: label(s) outside 0.."
            f"{stream.num_classes - 1}: {bad[:5].tolist()}"
        )


def validate_flag_rows(
    flags, num_batches: int, per_batch: int, num_rows: int
) -> None:
    """Structural audit of a run's collected flag table (host side).

    ``flags`` is a host :class:`engine.loop.FlagRows` with ``[P, NB-1]``
    leaves (``api.RunResult.flags``). Raises ``ValueError`` with the first
    violation found.
    """
    wl = np.asarray(flags.warning_local)
    wg = np.asarray(flags.warning_global)
    cl = np.asarray(flags.change_local)
    cg = np.asarray(flags.change_global)

    def fail(msg):
        raise ValueError(f"flag-table validation failed: {msg}")

    if not (wl.shape == wg.shape == cl.shape == cg.shape):
        fail("flag planes disagree on shape")
    if wl.shape[1] != max(num_batches - 1, 0):
        # Exact, both directions: a dropped boundary (too few flag rows) is
        # as much a corruption as an extra one.
        fail(
            f"{wl.shape[1]} flag rows for {num_batches} batches "
            "(expected exactly num_batches - 1)"
        )
    for name, local in (("warning_local", wl), ("change_local", cl)):
        bad = (local < -1) | (local >= per_batch)
        if bad.any():
            fail(f"{name} outside [-1, per_batch): {local[bad][:5].tolist()}")
    for name, glob, local in (
        ("warning_global", wg, wl),
        ("change_global", cg, cl),
    ):
        if ((glob < -1) | (glob >= num_rows)).any():
            fail(f"{name} outside [-1, num_rows)")
        if ((glob >= 0) != (local >= 0)).any():
            fail(f"{name} sentinel disagrees with its local column")
    # The reference records a warning only when it precedes the change in the
    # same batch (first-warning scan stops at the change, C6 :147-152).
    both = (wl >= 0) & (cl >= 0)
    if (wl[both] > cl[both]).any():
        fail("warning recorded after the change within a batch")
