"""Results persistence (reference C11, ``DDM_Process.py:263-273``).

Append-one-row-per-run CSV with the reference's column schema (see
``metrics.RESULT_COLUMNS``). Fixes quirk #1 of the SURVEY register: the
reference *reads* ``ddm_cluster_runs.csv`` but *writes*
``sparse_cluster_runs.csv`` (``:266`` vs ``:273``), breaking its own append
chain; here one file is both read and written.
"""

from __future__ import annotations

import csv
import os

from .metrics import RESULT_COLUMNS


def append_result(path: str, row: list) -> None:
    exists = os.path.exists(path)
    with open(path, "a", newline="") as fh:
        writer = csv.writer(fh)
        if not exists:
            writer.writerow(RESULT_COLUMNS)
        writer.writerow([_fmt(v) for v in row])


def read_results(path: str) -> list[dict]:
    with open(path, newline="") as fh:
        return list(csv.DictReader(fh))


def _fmt(v):
    if isinstance(v, float):
        return repr(v)
    return v
