"""Results persistence (reference C11, ``DDM_Process.py:263-273``).

Append-one-row-per-run CSV with the reference's column schema (see
``metrics.RESULT_COLUMNS``). Fixes quirk #1 of the SURVEY register: the
reference *reads* ``ddm_cluster_runs.csv`` but *writes*
``sparse_cluster_runs.csv`` (``:266`` vs ``:273``), breaking its own append
chain; here one file is both read and written.

Crash posture (resilience subsystem): this CSV is the sweep harness's
resume ledger (``harness.grid.completed_trials``), so it gets the same
treatment as the telemetry sinks — every append is flushed **and
fsynced** before close (a run recorded as done survives the host dying a
millisecond later), and :func:`read_results` mirrors
``telemetry.events.read_events(allow_partial_tail=)``: opt-in tolerance
for exactly one torn *trailing* row (what a kill mid-append leaves),
never an interior one (that is corruption and raises either way).
"""

from __future__ import annotations

import csv
import os

from .metrics import RESULT_COLUMNS


def append_result(path: str, row: list) -> None:
    """Append one run row (writing the header on first use).

    Safe under concurrent writers (several grid processes sharing one
    results file — the reference's own usage pattern, where every
    ``run_experiments.sh`` invocation appends to the same CSV): an exclusive
    ``flock`` spans the header check and the row write, so rows can neither
    interleave mid-line nor race the header.
    """
    with open(path, "a+", newline="") as fh:
        try:
            import fcntl

            fcntl.flock(fh, fcntl.LOCK_EX)
        except (ImportError, OSError):  # non-POSIX / fs without flock:
            pass  # best-effort append
        # Torn-tail repair under the lock: a crashed writer can leave a
        # partial trailing row with no newline. Appending straight at
        # SEEK_END would merge this row with those bytes into one
        # overlong line that no reader tolerates — drop everything after
        # the last newline instead (the partial trial was never recorded,
        # so the idempotent resume re-runs it; a torn *header* truncates
        # to empty and is rewritten below).
        fh.seek(0, os.SEEK_END)
        if fh.tell():
            fh.seek(0)
            content = fh.read()
            if not content.endswith("\n"):
                fh.truncate(0)
                fh.write(content[: content.rfind("\n") + 1])
                fh.flush()
        # Header decision under the lock: another process may have written
        # it between our open and lock. Position is authoritative.
        fh.seek(0, os.SEEK_END)
        writer = csv.writer(fh)
        if fh.tell() == 0:
            writer.writerow(RESULT_COLUMNS)
        else:
            # The file may predate newer schema columns (the schema has
            # grown over time — Dataset…Detections, then Model/Detector).
            # Rows must match the header already in the file, or every
            # CSV consumer downstream chokes on ragged lines; project the
            # row onto the existing header, dropping columns it lacks.
            fh.seek(0)
            existing = next(csv.reader(fh), None)
            fh.seek(0, os.SEEK_END)
            if existing and existing != RESULT_COLUMNS:
                by_name = dict(zip(RESULT_COLUMNS, row))
                dropped = [c for c in RESULT_COLUMNS if c not in existing]
                if dropped:
                    import warnings

                    # Loud, not silent: projecting away e.g. the Detector
                    # column makes the aggregation layer pool rows that a
                    # fresh-schema CSV would keep apart.
                    warnings.warn(
                        f"results CSV {path!r} predates column(s) "
                        f"{dropped}; dropping "
                        f"{ {c: by_name.get(c, '-') for c in dropped} } "
                        "from this row — start a fresh CSV to keep them",
                        stacklevel=2,
                    )
                row = [by_name.get(col, "-") for col in existing]
        writer.writerow([_fmt(v) for v in row])
        # Durability before the lock releases: the grid treats a row in
        # this file as "trial done, never re-run it", so the row must
        # reach the platter before anyone can observe that promise.
        fh.flush()
        os.fsync(fh.fileno())


def read_results(path: str, *, allow_partial_tail: bool = False) -> list[dict]:
    """Read the results CSV as dict rows.

    ``allow_partial_tail=True`` tolerates exactly one **torn trailing
    row** — the crash/concurrent-append read path, mirroring
    ``telemetry.events.read_events``: a row is torn when the file does
    not end in a newline (the writer appends whole ``row + \\r\\n`` units)
    or the final row has fewer fields than the header; it is dropped,
    never a row before it. A short *interior* row is corruption and
    raises ``ValueError`` in both modes (the strict default also raises
    on a short trailing row). Overlong rows raise always — no tear can
    add fields.
    """
    with open(path, newline="") as fh:
        text = fh.read()
    rows = list(csv.reader(text.splitlines()))
    if not rows:
        return []
    header, body = rows[0], rows[1:]
    out = []
    for i, row in enumerate(body):
        last = i == len(body) - 1
        if not row and not last:
            continue  # interior blank line (csv.DictReader parity)
        if not row and text.endswith("\n"):
            continue  # trailing blank line after a complete final row
        torn = len(row) < len(header) or (last and not text.endswith("\n"))
        if len(row) > len(header) or (torn and not last):
            raise ValueError(
                f"{path}: corrupt interior row {i + 2} "
                f"({len(row)} fields, header has {len(header)})"
            )
        if torn:
            if allow_partial_tail:
                break  # the one torn trailing row; everything before stands
            raise ValueError(
                f"{path}: torn trailing row {i + 2} "
                f"({len(row)} fields, header has {len(header)}; pass "
                "allow_partial_tail=True to drop it)"
            )
        out.append(dict(zip(header, row)))
    return out


def _fmt(v):
    if isinstance(v, float):
        return repr(v)
    return v
