"""Results persistence (reference C11, ``DDM_Process.py:263-273``).

Append-one-row-per-run CSV with the reference's column schema (see
``metrics.RESULT_COLUMNS``). Fixes quirk #1 of the SURVEY register: the
reference *reads* ``ddm_cluster_runs.csv`` but *writes*
``sparse_cluster_runs.csv`` (``:266`` vs ``:273``), breaking its own append
chain; here one file is both read and written.
"""

from __future__ import annotations

import csv
import os

from .metrics import RESULT_COLUMNS


def append_result(path: str, row: list) -> None:
    """Append one run row (writing the header on first use).

    Safe under concurrent writers (several grid processes sharing one
    results file — the reference's own usage pattern, where every
    ``run_experiments.sh`` invocation appends to the same CSV): an exclusive
    ``flock`` spans the header check and the row write, so rows can neither
    interleave mid-line nor race the header.
    """
    with open(path, "a+", newline="") as fh:
        try:
            import fcntl

            fcntl.flock(fh, fcntl.LOCK_EX)
        except (ImportError, OSError):  # non-POSIX / fs without flock:
            pass  # best-effort append
        # Header decision under the lock: another process may have written
        # it between our open and lock. Position is authoritative.
        fh.seek(0, os.SEEK_END)
        writer = csv.writer(fh)
        if fh.tell() == 0:
            writer.writerow(RESULT_COLUMNS)
        else:
            # The file may predate newer schema columns (the schema has
            # grown over time — Dataset…Detections, then Model/Detector).
            # Rows must match the header already in the file, or every
            # CSV consumer downstream chokes on ragged lines; project the
            # row onto the existing header, dropping columns it lacks.
            fh.seek(0)
            existing = next(csv.reader(fh), None)
            fh.seek(0, os.SEEK_END)
            if existing and existing != RESULT_COLUMNS:
                by_name = dict(zip(RESULT_COLUMNS, row))
                dropped = [c for c in RESULT_COLUMNS if c not in existing]
                if dropped:
                    import warnings

                    # Loud, not silent: projecting away e.g. the Detector
                    # column makes the aggregation layer pool rows that a
                    # fresh-schema CSV would keep apart.
                    warnings.warn(
                        f"results CSV {path!r} predates column(s) "
                        f"{dropped}; dropping "
                        f"{ {c: by_name.get(c, '-') for c in dropped} } "
                        "from this row — start a fresh CSV to keep them",
                        stacklevel=2,
                    )
                row = [by_name.get(col, "-") for col in existing]
        writer.writerow([_fmt(v) for v in row])


def read_results(path: str) -> list[dict]:
    with open(path, newline="") as fh:
        return list(csv.DictReader(fh))


def _fmt(v):
    if isinstance(v, float):
        return repr(v)
    return v
