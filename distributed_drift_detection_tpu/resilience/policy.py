"""Retry policy: how many attempts, how long to wait, what is worth retrying.

The reference's fault-tolerance is out-of-band re-execution — a notebook
cell counts configs with missing trials and regenerates a
``missing_exps.sh`` "in the case of a cluster crash" (SURVEY.md C14). A
:class:`RetryPolicy` is the in-band half of replacing that dance: it
decides, per failed attempt, whether the failure is *transient* (a crashed
worker, a full disk, a timeout — re-running may heal it) or *fatal* (a bad
configuration — re-running reproduces it), and how long to back off before
the next attempt.

Everything here is deterministic under a fixed ``seed``: the jitter on the
exponential backoff is derived by hashing ``(seed, attempt)`` — no global
RNG, no wall-clock — so a supervised run's retry schedule is replayable
(pinned by tests) and two hosts retrying the same policy do not thundering-
herd each other when their seeds differ.

Pure stdlib, no jax: policies are consulted by the supervisor and the heal
CLI wherever they run.
"""

from __future__ import annotations

import hashlib
import struct
from typing import NamedTuple


class TransientError(RuntimeError):
    """Base class for failures that are transient *by construction* —
    raising (or subclassing) this is an explicit promise to the policy
    that a retry is meaningful. ``AttemptTimeout`` and the injected
    faults (``resilience.faults``) derive from it."""


class AttemptTimeout(TransientError):
    """A supervised attempt exceeded its per-attempt wall-clock budget
    (:attr:`RetryPolicy.timeout_s`). Always classified transient: a
    timeout is the canonical maybe-the-cluster-hiccuped failure."""


# Default fatal types: failures that re-running reproduces byte-for-byte.
# Configuration/programming errors (ValueError/TypeError/KeyError/
# AttributeError — a bad detector name, a shape mismatch), broken
# invariants (AssertionError), and resource exhaustion that backoff cannot
# return (MemoryError). Everything else — OSError, RuntimeError (XLA wraps
# device-side failures in RuntimeErrors), TransientError — defaults to
# transient: the supervisor exists for crashes whose exact type nobody
# predicted. KeyboardInterrupt/SystemExit never reach classification (the
# supervisor only catches ``Exception``).
FATAL_TYPES: tuple[type, ...] = (
    ValueError,
    TypeError,
    KeyError,
    AttributeError,
    AssertionError,
    MemoryError,
    NotImplementedError,
)


def _unit_interval(seed: int, *parts: object) -> float:
    """Deterministic uniform in [0, 1) from a seed and context parts:
    SHA-256 of the canonical tuple string, top 8 bytes as a fraction.
    Shared with ``resilience.faults`` for seeded Bernoulli sites."""
    h = hashlib.sha256(repr((int(seed),) + parts).encode()).digest()
    (n,) = struct.unpack(">Q", h[:8])
    return n / 2**64


class RetryPolicy(NamedTuple):
    """Retry/backoff policy for supervised execution.

    ``max_attempts`` counts the first try: 3 means one run plus up to two
    retries; 1 disables retrying (the supervisor then only adds the
    timeout bracket and the registry ``attempt`` field). ``timeout_s``
    (None = unlimited) is the per-attempt wall-clock budget — exceeding it
    raises :class:`AttemptTimeout`, which is transient.

    Backoff before retry ``n`` (1-based failed-attempt index) is
    ``min(backoff_base_s · backoff_factor^(n-1), backoff_max_s)``,
    stretched by a seeded jitter of up to ``±jitter`` (a fraction):
    deterministic under a fixed ``seed``, different across seeds — two
    workers with distinct seeds never resynchronize their retries.

    ``transient_types`` / ``fatal_types`` drive :meth:`classify`; fatal
    wins on overlap, unlisted exception types default to transient (see
    :data:`FATAL_TYPES` for the rationale).
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    jitter: float = 0.1
    seed: int = 0
    timeout_s: float | None = None
    transient_types: tuple[type, ...] = (TransientError,)
    fatal_types: tuple[type, ...] = FATAL_TYPES

    def validate(self) -> "RetryPolicy":
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.timeout_s is not None and self.timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {self.timeout_s}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        return self

    def classify(self, exc: BaseException) -> str:
        """``'transient'`` (retry may heal it) or ``'fatal'`` (it won't).

        Explicit ``transient_types`` outrank the fatal defaults — a caller
        who lists a ``ValueError`` subclass as transient has said so on
        purpose — but the stock ``TransientError`` base never shadows a
        genuine fatal type (no fatal type derives from it).
        """
        if isinstance(exc, self.transient_types):
            return "transient"
        if isinstance(exc, self.fatal_types):
            return "fatal"
        return "transient"

    def backoff_s(self, attempt: int) -> float:
        """Seconds to wait after failed attempt ``attempt`` (1-based).

        Deterministic: same (policy, attempt) → same float, always.
        """
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        delay = min(
            self.backoff_base_s * self.backoff_factor ** (attempt - 1),
            self.backoff_max_s,
        )
        if self.jitter:
            u = _unit_interval(self.seed, "backoff", attempt)
            delay *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return float(delay)


# The no-retry policy: one attempt, no timeout. The supervisor with this
# policy is a plain call plus the registry ``attempt`` bracket — what the
# grid harness uses when ``retries=0`` so the wiring has one shape.
NO_RETRY = RetryPolicy(max_attempts=1)
