"""Deterministic, seeded fault injection for the resilience subsystem.

The reference's crash story is untestable by construction: a "cluster
crash" (README.md:13) happens to you, and the recovery dance
(``missing_exps.sh``) is rehearsed only when it does. Here crashes are a
*first-class test input*: known **sites** in the production code call
:func:`fire`, which is a no-op unless that site was explicitly **armed**
— so the supervised-retry, checkpoint-resume and sweep-heal paths are
exercised by tests and a CI smoke job against real injected failures, not
mocks.

Sites (each named where the production code calls :func:`fire`):

=====================  ====================================================
``api.run``            start of a run, inside the registry bracket — a
                       whole-run crash that leaves a ``failed`` record
``grid.cell``          before each sweep trial (``harness.grid.run_grid``);
                       re-fired on supervised retries of the cell
``chunked.feed``       per chunk fed to ``engine.chunked.ChunkedDetector``
                       ("raise at batch K", chunk granularity)
``soak.leg``           before each chained-soak leg executes
                       (``engine.soak.run_soak_chained``)
``checkpoint.save``    between the checkpoint temp-file write and its
                       atomic rename (``utils.checkpoint.save_checkpoint``)
                       — ``kind='torn_write'`` truncates the temp file
                       mid-byte first, simulating a kill mid-write
``telemetry.emit``     inside ``telemetry.events.EventLog.emit`` —
                       ``kind='torn_write'`` appends a partial JSON prefix
                       (no newline) before raising: the torn-tail shape
                       ``read_events(allow_partial_tail=True)`` tolerates
``stream.load``        inside the sanitizing CSV loader
                       (``io.sanitize.load_csv_sane``) — the home of the
                       **data-corruption kinds** ``nan_cell`` /
                       ``bad_label`` / ``ragged_row``, which mutate the
                       raw CSV text lines deterministically instead of
                       raising, so the dirty-stream machinery (doctor,
                       quarantine, repair) is exercised by the same
                       seeded injection the process faults use
``serve.ingress``      per admitted line block in the serving daemon's
                       ingress (``serve.admission.AdmissionController``)
                       — corruption kinds mutate the incoming protocol
                       lines (dirty live traffic); ``raise``/``timeout``
                       poison the batcher, crashing the daemon loudly
``serve.flush``        per flushed microbatch, at verdict publication
                       (``serve.runner.ServeRunner``) — ``raise`` kills
                       the daemon after the chunk's state advanced but
                       before its verdict/checkpoint landed (the
                       kill-and-resume shape); ``kind='torn_write'``
                       tears the verdict sidecar's trailing line;
                       ``kind='stall',seconds=S`` genuinely sleeps the
                       serve loop for S seconds (the wedge the SLO
                       ``stall_s`` rule and ops ``/healthz`` must catch)
``sched.lease``        per lease grant in the sweep scheduler
                       (``sched.scheduler.Scheduler``) — ``raise``
                       rejects that one grant (the worker retries, the
                       cell stays queued, the daemon survives);
                       ``kind='stall',seconds=S`` wedges the grant
``sched.worker``       per leased cell at execution start in the worker
                       agent (``sched.worker.Worker.run``), OUTSIDE the
                       per-cell error handling — ``raise`` kills the
                       whole agent process, the deterministic worker
                       preemption the exactly-once acceptance test and
                       the sched-smoke CI job arm via ``DDD_FAULTS``
                       (Bernoulli arming de-correlates per worker: the
                       agent re-seeds with its ``--index``)
=====================  ====================================================

Arming is explicit (:func:`arm` in-process, or the ``DDD_FAULTS`` env var
via :func:`arm_from_env` for CLI-driven sweeps) and deterministic: either
positional — fire on the ``at``-th invocation of the site, for ``times``
consecutive invocations — or seeded-Bernoulli (``rate`` + ``seed``: the
decision hashes ``(seed, site, hit)``, so a given arming fires at the same
hits in every run). No global RNG, no wall-clock.

Pure stdlib, no jax; importing this module never arms anything.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from .policy import TransientError, _unit_interval


class InjectedFault(TransientError):
    """A deliberately injected failure. Subclasses ``TransientError`` so
    the default :class:`..policy.RetryPolicy` classification retries it —
    an injected crash stands in for the transient cluster failure the
    subsystem exists to survive."""


class InjectedTimeout(InjectedFault):
    """The simulated-timeout fault (``kind='timeout'``): stands in for an
    attempt that would have exceeded its wall-clock budget, without
    actually sleeping."""


ENV_VAR = "DDD_FAULTS"

KINDS = (
    "raise", "timeout", "stall", "torn_write",
    "nan_cell", "bad_label", "ragged_row",
)

# Data-corruption kinds: instead of raising, a firing mutates the CSV text
# lines the ``stream.load`` site hands in — ``times`` is reinterpreted as
# *rows corrupted per firing* (default 1), and a positionally-armed spec
# fires on every load from ``at`` onward (the corruption is deterministic,
# so repeated loads corrupt identically). Only meaningful at sites that
# pass ``lines=``; elsewhere a corruption-kind firing is a no-op.
CORRUPTION_KINDS = frozenset({"nan_cell", "bad_label", "ragged_row"})

# Every site a production call point declares; arming anything else is a
# typo and fails loudly (the silent-no-op failure mode of a misspelled
# site name would defeat the whole point of a fault test).
SITES = frozenset(
    {
        "api.run",
        "grid.cell",
        "chunked.feed",
        "soak.leg",
        "checkpoint.save",
        "telemetry.emit",
        "stream.load",
        "serve.ingress",
        "serve.flush",
        "sched.lease",
        "sched.worker",
    }
)


@dataclass
class FaultSpec:
    """One armed site. ``at``/``times`` are positional arming (fire on
    hits ``[at, at + times)``; ``times=0`` = from ``at`` onward forever);
    ``rate``/``seed`` (with ``at=0``) are seeded-Bernoulli arming."""

    site: str
    at: int = 1
    times: int = 1
    kind: str = "raise"
    rate: float = 0.0
    seed: int = 0
    # kind='stall' only: how long the firing site really sleeps. Unlike
    # 'timeout' (which *stands in* for a blown budget by raising
    # immediately), a stall genuinely wedges the calling thread — the
    # shape the serving SLO engine's `stall_s` rule and the watch CLI's
    # stall contract exist to detect.
    seconds: float = 5.0
    hits: int = 0  # invocations of the site seen since arming
    fired: int = 0  # faults actually raised

    def should_fire(self) -> bool:
        if self.kind in CORRUPTION_KINDS:
            # Corruption kinds: `times` means rows-per-firing, not
            # consecutive-firing count — positional arming fires on every
            # hit from `at` onward (deterministic, so re-loads corrupt
            # identically); Bernoulli arming decides per hit as usual.
            if self.at:
                return self.hits >= self.at
            return (
                self.rate > 0.0
                and _unit_interval(self.seed, self.site, self.hits) < self.rate
            )
        if self.at:
            if self.hits < self.at:
                return False
            return self.times == 0 or self.fired < self.times
        if self.rate > 0.0:
            if self.times and self.fired >= self.times:
                return False
            return _unit_interval(self.seed, self.site, self.hits) < self.rate
        return False


_ARMED: dict[str, FaultSpec] = {}


def arm(
    site: str,
    *,
    at: "int | None" = None,
    times: int = 1,
    kind: str = "raise",
    rate: float = 0.0,
    seed: int = 0,
    seconds: float = 5.0,
) -> FaultSpec:
    """Arm ``site``; returns the live spec (its counters update as the
    site is hit). Re-arming a site replaces its spec and resets counters.

    Positional (``at=``, default 1 when no ``rate``) and seeded-Bernoulli
    (``rate=`` + ``seed=``) arming are mutually exclusive: passing a
    ``rate`` selects Bernoulli mode outright, and combining it with a
    nonzero ``at`` is rejected rather than silently ignoring the rate."""
    if site not in SITES:
        raise ValueError(
            f"unknown fault site {site!r}; expected one of {sorted(SITES)}"
        )
    if kind not in KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; expected one of {KINDS}")
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"rate must be in [0, 1], got {rate}")
    if rate > 0.0 and at not in (None, 0):
        raise ValueError(
            "positional at= and Bernoulli rate= are mutually exclusive"
        )
    if at is None:
        at = 0 if rate > 0.0 else 1
    if at < 0 or times < 0:
        raise ValueError("at/times must be >= 0")
    if at == 0 and rate == 0.0:
        raise ValueError("arm needs a positional `at` or a Bernoulli `rate`")
    spec = FaultSpec(
        site=site, at=at, times=times, kind=kind, rate=rate, seed=seed,
        seconds=float(seconds),
    )
    _ARMED[site] = spec
    return spec


def disarm(site: str) -> None:
    _ARMED.pop(site, None)


def disarm_all() -> None:
    _ARMED.clear()


def armed(site: str) -> FaultSpec | None:
    return _ARMED.get(site)


def arm_from_env(spec: str | None = None) -> list[str]:
    """Arm sites from the ``DDD_FAULTS`` env var (or an explicit string):
    ``site:key=val,key=val`` entries separated by ``;`` — e.g.
    ``DDD_FAULTS="grid.cell:at=4"`` crashes the 4th sweep trial, and
    ``DDD_FAULTS="telemetry.emit:at=5,kind=torn_write"`` tears the 5th
    emitted event. Returns the armed site names ([] when unset/empty).
    Called by ``harness.grid.run_grid`` so CLI-driven sweeps can be
    crashed without writing Python; everything else requires in-process
    :func:`arm` calls.
    """
    if spec is None:
        spec = os.environ.get(ENV_VAR, "")
    sites = []
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        site, _, args = entry.partition(":")
        kw: dict = {}
        for pair in filter(None, (p.strip() for p in args.split(","))):
            key, _, val = pair.partition("=")
            if key in ("at", "times", "seed"):
                kw[key] = int(val)
            elif key in ("rate", "seconds"):
                kw[key] = float(val)
            elif key == "kind":
                kw[key] = val
            else:
                raise ValueError(
                    f"{ENV_VAR}: unknown key {key!r} in entry {entry!r}"
                )
        arm(site.strip(), **kw)
        sites.append(site.strip())
    return sites


def corrupt_lines(
    lines: list[str],
    kind: str,
    *,
    rows: int = 1,
    seed: int = 0,
    label_col: int = -1,
) -> list[tuple[int, int]]:
    """Deterministically corrupt ``rows`` distinct CSV data lines in place.

    ``kind='nan_cell'`` replaces one seeded cell with ``nan`` (a
    non-finite value the contract scan flags); ``'bad_label'`` makes the
    ``label_col`` field non-integral (``<y>.5``); ``'ragged_row'`` drops
    the last field. Row/column choices hash ``(seed, kind, k)`` — no
    global RNG, no wall-clock — and collisions probe linearly, so a given
    arming corrupts the same cells in every run. Returns the corrupted
    ``(row, column)`` pairs (column −1 for ragged rows). Also usable
    directly (the ``dirty-stream-smoke`` CI job corrupts a CSV copy with
    it); :func:`fire` routes ``stream.load`` firings here.
    """
    if kind not in CORRUPTION_KINDS:
        raise ValueError(
            f"unknown corruption kind {kind!r}; expected one of "
            f"{sorted(CORRUPTION_KINDS)}"
        )
    n = len(lines)
    if n == 0:
        return []
    out: list[tuple[int, int]] = []
    for k, r in enumerate(corrupt_row_indices(kind, n, rows, seed)):
        fields = lines[r].split(",")
        if kind == "ragged_row":
            fields = fields[:-1] if len(fields) > 1 else fields + ["0"]
            out.append((r, -1))
        elif kind == "bad_label":
            c = label_col % len(fields)
            try:
                base = int(float(fields[c]))
            except ValueError:
                base = 0
            fields[c] = f"{base}.5"
            out.append((r, c))
        else:  # nan_cell
            c = corrupt_cell_column(kind, seed, k, len(fields))
            fields[c] = "nan"
            out.append((r, c))
        lines[r] = ",".join(fields)
    return out


def corrupt_row_indices(kind: str, n: int, rows: int, seed: int) -> list[int]:
    """The seeded distinct-row selection behind :func:`corrupt_lines` —
    the ONE copy of the hash keys and linear collision probing. The
    loadgen v2 columnar stand-ins (``serve.loadgen.apply_dirty_frames``)
    reuse it so a v1 and a v2 replay of the same ``--dirty`` spec dirty
    the SAME stream positions — the cross-protocol verdict-parity
    contract the ingress-v2-smoke CI job pins."""
    out: list[int] = []
    used: set[int] = set()
    for k in range(min(max(rows, 1), n)):
        r = int(_unit_interval(seed, f"{kind}.row", k) * n) % n
        while r in used:
            r = (r + 1) % n
        used.add(r)
        out.append(r)
    return out


def corrupt_cell_column(kind: str, seed: int, k: int, num_fields: int) -> int:
    """The seeded column choice for the ``k``-th ``nan_cell`` corruption
    (shared with the loadgen v2 stand-ins, like :func:`corrupt_row_indices`)."""
    return int(_unit_interval(seed, f"{kind}.col", k) * num_fields) % num_fields


def fire(site: str, *, file: str | None = None, fh=None, payload: str | None = None, lines: "list[str] | None" = None, label_col: int = -1, **context) -> None:
    """Production-code hook: a no-op unless ``site`` is armed and its spec
    elects this hit. When it fires:

    * ``kind='raise'`` — raise :class:`InjectedFault`.
    * ``kind='timeout'`` — raise :class:`InjectedTimeout`.
    * ``kind='torn_write'`` — first *tear the write the site is about to
      finish*: with ``fh``+``payload`` (the telemetry sink) append the
      first half of the payload with no newline; with ``file`` (the
      checkpoint temp file) truncate it to half its bytes; then raise.
    * corruption kinds (``nan_cell``/``bad_label``/``ragged_row``) —
      mutate ``lines`` (raw CSV data lines, no header) in place via
      :func:`corrupt_lines` and return **without raising**: the dirt
      flows through the sanitizing loader like real dirt would.
      ``label_col`` tells ``bad_label`` which field is the target.

    ``context`` rides into the exception message for post-mortems.
    """
    if not _ARMED:
        return
    spec = _ARMED.get(site)
    if spec is None:
        return
    spec.hits += 1
    if not spec.should_fire():
        return
    spec.fired += 1
    if spec.kind in CORRUPTION_KINDS:
        if lines is not None:
            corrupt_lines(
                lines,
                spec.kind,
                rows=max(spec.times, 1),
                seed=spec.seed,
                label_col=label_col,
            )
        return
    if spec.kind == "stall":
        # A real wedge, not a raise: the site's thread sleeps and then
        # continues normally — observable only by the staleness it causes
        # (SLO `stall_s`, `watch --stall-after`), exactly as in the field.
        import time as _time

        _time.sleep(max(spec.seconds, 0.0))
        return
    detail = f"injected fault at {site!r} (hit {spec.hits})"
    if context:
        detail += " " + " ".join(f"{k}={v!r}" for k, v in sorted(context.items()))
    if spec.kind == "timeout":
        raise InjectedTimeout(detail)
    if spec.kind == "torn_write":
        if fh is not None and payload is not None:
            fh.write(payload[: max(len(payload) // 2, 1)])
            fh.flush()
        elif file is not None and os.path.exists(file):
            size = os.path.getsize(file)
            with open(file, "r+b") as tfh:
                tfh.truncate(size // 2)
        raise InjectedFault(detail + " (write torn)")
    raise InjectedFault(detail)
