"""Resilience subsystem: supervised execution, deterministic fault
injection, and registry-driven sweep healing.

The reference's fault-tolerance is a human loop — notice the crash, diff
the results CSV in a notebook, regenerate ``missing_exps.sh``, re-submit
(SURVEY.md C14). This package closes that loop in code, layered on the
telemetry registry (every run records running → completed/failed):

* :mod:`.policy` — :class:`RetryPolicy`: attempts, deterministic seeded
  exponential backoff, per-attempt wall-clock timeout, transient-vs-fatal
  exception classification.
* :mod:`.supervisor` — :func:`supervise` / :func:`supervised_run`: run a
  callable / ``api.run`` under a policy; every attempt is bracketed in
  the registry (``attempt`` field) and every retry emits a schema-v1
  ``run_retried`` event — all strictly outside the reference-parity
  Final Time span.
* :mod:`.faults` — seeded deterministic fault injection at named sites
  (crash a run, a sweep cell, a soak leg; tear a checkpoint or telemetry
  write mid-file; simulate a timeout). No-ops unless explicitly armed.
* :mod:`.heal` — the ``heal`` CLI: diff a sweep spec against the
  registry's completed runs, emit the re-run plan as JSON + shell script,
  ``--execute`` it under the supervisor until the sweep is whole.

``import distributed_drift_detection_tpu.resilience`` stays jax-free
(policy + faults are stdlib); :mod:`.supervisor` and :mod:`.heal` pull in
the api lazily, so plan-mode healing runs wherever ``index.jsonl`` lands.
"""

from .faults import InjectedFault, InjectedTimeout
from .policy import NO_RETRY, AttemptTimeout, RetryPolicy, TransientError

__all__ = [
    "RetryPolicy",
    "NO_RETRY",
    "TransientError",
    "AttemptTimeout",
    "InjectedFault",
    "InjectedTimeout",
    "supervise",
    "supervised_run",
]


def __getattr__(name):
    # Lazy (PEP 562): supervisor imports the telemetry core and, inside
    # supervised_run, api/jax — keeping the package import stdlib-light
    # and cycle-free (api itself imports `.faults` at module level).
    if name in ("supervise", "supervised_run"):
        from . import supervisor

        return getattr(supervisor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
