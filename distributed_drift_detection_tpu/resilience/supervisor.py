"""Supervised execution: retry a run under a :class:`RetryPolicy`.

The reference re-runs crashed work by hand (regenerate ``missing_exps.sh``,
re-submit). :func:`supervise` closes that loop in-process: it runs a
callable under a policy, classifies each failure transient-vs-fatal,
backs off deterministically, and makes every attempt *observable* —

* each attempt executes inside ``telemetry.registry.attempt_scope(n)``, so
  the ``running``/``completed``/``failed`` records the attempt writes into
  ``index.jsonl`` carry an ``attempt`` field (a healed run reads as
  ``failed(attempt=1) → completed(attempt=2)``, not as magic);
* each retry emits a schema-v1 ``run_retried`` event into a dedicated
  supervisor log in the telemetry directory (opened lazily — a run that
  never retries leaves no extra artifact).

Nothing here touches the reference-parity Final Time span: the supervisor
wraps ``api.run`` from the *outside*, and all its telemetry lands between
attempts (the purity test pins the span's instrumentation unchanged).

The per-attempt wall-clock timeout runs the attempt on a worker thread and
abandons it on expiry (Python cannot kill a thread): the abandoned attempt
may keep consuming resources until its current device program returns, but
the supervisor — and its caller's schedule — moves on. :class:`AttemptTimeout`
is transient by construction. One consequence to size timeouts around: an
abandoned attempt that later *finishes* still writes its side effects — a
results-CSV row, a ``completed`` registry record — concurrently with the
retry, so a timed-out-then-completed trial can leave duplicate artifacts
for that one trial (trial keys and config digests are per-trial unique,
so the resume/heal ledgers over-count that trial rather than skipping
another; the surplus row is visible in both ledgers). Prefer budgets
comfortably above the expected attempt time — the timeout is a hung-run
escape hatch, not a scheduler.

Imports jax only transitively and lazily (via ``api.run`` inside
:func:`supervised_run`); :func:`supervise` itself is stdlib + the jax-free
telemetry core.
"""

from __future__ import annotations

import contextvars
import threading
import time

from ..telemetry import registry as run_registry
from .policy import AttemptTimeout, RetryPolicy

_SENTINEL = object()


def _call_with_timeout(fn, timeout_s: float | None):
    """Run ``fn()`` with a wall-clock budget; raise :class:`AttemptTimeout`
    on expiry (the worker thread is abandoned — see module docstring)."""
    if not timeout_s:
        return fn()
    box: dict = {}
    # The attempt runs under the supervising thread's context (a fresh
    # thread starts with an empty one): without this, the registry's
    # attempt_scope contextvar would silently vanish from every record a
    # timed attempt writes.
    ctx = contextvars.copy_context()

    def target():
        try:
            box["value"] = ctx.run(fn)
        except BaseException as e:  # re-raised on the supervising thread
            box["error"] = e

    t = threading.Thread(target=target, daemon=True, name="supervised-attempt")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise AttemptTimeout(
            f"attempt exceeded its {timeout_s} s wall-clock budget "
            "(worker thread abandoned)"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def supervise(
    fn,
    policy: RetryPolicy = RetryPolicy(),
    *,
    telemetry_dir: str = "",
    name: str = "",
    sleep=time.sleep,
    on_retry=None,
):
    """Run ``fn()`` under ``policy``; returns its result.

    Retries transient failures up to ``policy.max_attempts`` total
    attempts with deterministic seeded backoff (``policy.backoff_s``);
    fatal failures and the final exhausted attempt re-raise the
    *original* exception (annotated with the attempt count), so callers
    keep their exception types — supervision changes how often something
    runs, never what its failure looks like.

    ``telemetry_dir`` enables the observability described in the module
    docstring; ``name`` labels the supervisor's retry log. ``sleep`` is
    injectable for tests (and anything that wants to veto the wait);
    ``on_retry(attempt, exc, backoff_s)`` is an optional observer fired
    before each backoff.
    """
    policy.validate()
    log = None
    try:
        for attempt in range(1, policy.max_attempts + 1):
            with run_registry.attempt_scope(attempt):
                try:
                    return _call_with_timeout(fn, policy.timeout_s)
                except Exception as exc:
                    kind = policy.classify(exc)
                    final = attempt >= policy.max_attempts
                    if kind == "fatal" or final:
                        if hasattr(exc, "add_note"):
                            exc.add_note(
                                f"supervisor: attempt {attempt}/"
                                f"{policy.max_attempts} "
                                + (
                                    "failed fatally (not retried)"
                                    if kind == "fatal"
                                    else "exhausted the retry budget"
                                )
                            )
                        raise
                    backoff = policy.backoff_s(attempt)
                    if telemetry_dir:
                        if log is None:
                            from ..telemetry.events import EventLog

                            log = EventLog.open_run(
                                telemetry_dir,
                                name=(name or "supervised") + "-retries",
                            )
                        log.emit(
                            "run_retried",
                            attempt=attempt,
                            max_attempts=policy.max_attempts,
                            reason=f"{type(exc).__name__}: {exc}",
                            backoff_s=backoff,
                            classification=kind,
                        )
                    if on_retry is not None:
                        on_retry(attempt, exc, backoff)
                    sleep(backoff)
    finally:
        if log is not None:
            log.close()
    raise AssertionError("unreachable: the loop returns or raises")


def supervised_run(
    cfg,
    policy: RetryPolicy = RetryPolicy(),
    *,
    stream=None,
    sleep=time.sleep,
    on_retry=None,
):
    """``api.run(cfg)`` under a retry policy — the resilience wrapper for
    one configured run; returns the :class:`..api.RunResult`.

    With ``cfg.telemetry_dir`` set, every attempt registers itself in the
    directory's ``index.jsonl`` with its ``attempt`` number (via
    ``api.run``'s own registry bracket + :func:`attempt_scope
    <..telemetry.registry.attempt_scope>`), and each retry emits a
    ``run_retried`` event. A fresh stream is NOT reloaded per attempt when
    the caller passed one in — pass ``stream=None`` (the default) if the
    failure mode under retry includes a corrupted in-memory stream.
    """
    from ..api import run  # lazy: keeps `import resilience` jax-free

    return supervise(
        lambda: run(cfg, stream),
        policy,
        telemetry_dir=cfg.telemetry_dir or "",
        name=cfg.resolved_app_name(),
        sleep=sleep,
        on_retry=on_retry,
    )
