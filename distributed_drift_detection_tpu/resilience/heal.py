"""Sweep healing: the reference's ``missing_exps.sh`` made real.

The reference's only recovery from a crashed sweep is notebook cell 3:
count configs with fewer than 5 trials in the results CSV, hand-edit a
``missing_exps.sh`` of re-run commands, re-submit (SURVEY.md C14). Here
the same diff is a subcommand over first-class artifacts:

    python -m distributed_drift_detection_tpu heal sweep.json \\
        --telemetry-dir runs/ [--json plan.json] [--script missing.sh] \\
        [--execute [--retries N] [--timeout-s S]] [--cell KEY ...] \\
        [--scheduler HOST:PORT]

A **sweep spec** is the ``run_experiments.sh``-style grid as JSON —
``{"dataset": ..., "mults": [...], "partitions": [...], "models": [...],
"detectors": [...], "trials": N, "per_batch": B, "seed": S,
"results_csv": ...}`` — expanded through the same
:func:`..harness.grid.grid_configs` the sweep itself ran, so expected
cells and executed cells can never drift. Each expected trial's
**config digest** (``telemetry.registry.config_digest`` over
``config.telemetry_config_payload`` — byte-identical to what ``api.run``
recorded) is diffed against the registry's ``completed`` records:

* the **plan** (``--json``) lists every missing trial with its digest and
  config — machine-readable re-run intent;
* the **script** (``--script``) is the regenerated ``missing_exps.sh``:
  one idempotent shell line per missing trial (each re-invokes ``heal
  --execute --cell KEY``, so a half-run script re-run skips what landed);
* ``--execute`` runs the missing trials in-process under the supervisor
  (:func:`..resilience.supervisor.supervised_run` with a retry policy),
  bracketed by a ``kind="heal"`` registry record, until the sweep is
  whole;
* ``--scheduler HOST:PORT`` pushes the plan to a running ``sched/``
  scheduler instead (jax-free, like plan mode): the scheduler's worker
  fleet runs the missing trials, and its exit code becomes the
  wholeness contract (docs/SCHEDULER.md).

Completed trials are never re-run: the diff is against the registry, the
same source of truth ``watch``/``report --dir`` read. Plan mode is
jax-free (runs wherever ``index.jsonl`` lands); only ``--execute``
initialises a backend.

Exit code contract (scriptable wholeness check): ``0`` = sweep whole
(after executing, if asked), ``1`` = trials still missing.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import sys
import time
from collections import Counter

from ..config import RunConfig, replace, telemetry_config_payload
from ..harness.grid import SWEEP_DEFAULTS, grid_configs, off_spec_reason
from ..telemetry import registry as run_registry
from .policy import RetryPolicy

# Spec keys beyond the required three, with their defaults — THE grid
# CLI's flag defaults (one shared constant, harness.grid.SWEEP_DEFAULTS):
# a spec omitting a knob must expand to the same configs the grid ran
# with the flag omitted, or digests drift.
_SPEC_DEFAULTS = SWEEP_DEFAULTS
_REQUIRED = ("dataset", "mults", "partitions")


def load_spec(path: str) -> dict:
    """Load and validate a sweep-spec JSON; unknown keys fail loudly (a
    typoed ``"model"`` silently healing the default sweep would be the
    exact class of bug this subsystem exists to prevent)."""
    with open(path) as fh:
        spec = json.load(fh)
    if not isinstance(spec, dict):
        raise ValueError(f"{path}: sweep spec must be a JSON object")
    missing = [k for k in _REQUIRED if k not in spec]
    if missing:
        raise ValueError(f"{path}: sweep spec missing required {missing}")
    unknown = set(spec) - set(_REQUIRED) - set(_SPEC_DEFAULTS)
    if unknown:
        raise ValueError(
            f"{path}: unknown sweep-spec key(s) {sorted(unknown)}; known: "
            f"{sorted(set(_REQUIRED) | set(_SPEC_DEFAULTS))}"
        )
    if spec.get("spec", "warn") not in ("warn", "skip", "off"):
        raise ValueError(f"{path}: spec must be 'warn', 'skip' or 'off'")
    return {**_SPEC_DEFAULTS, **spec}


def spec_configs(spec: dict) -> list[RunConfig]:
    """Expand a sweep spec into its trial configs — the exact expansion
    the sweep ran (``grid_configs``), including the ``spec='skip'``
    filtering: a cell the sweep never scheduled is not missing."""
    base = RunConfig(
        dataset=spec["dataset"],
        per_batch=int(spec["per_batch"]),
        seed=int(spec["seed"]),
        results_csv=spec["results_csv"],
        data_policy=str(spec["data_policy"]),
    )
    configs = grid_configs(
        base,
        mults=[float(m) for m in spec["mults"]],
        partitions=[int(p) for p in spec["partitions"]],
        models=list(spec["models"]),
        trials=int(spec["trials"]),
        detectors=list(spec["detectors"]),
    )
    if spec["spec"] == "skip":
        configs = [c for c in configs if off_spec_reason(c) is None]
    return configs


def completed_digests(telemetry_dir: str) -> Counter:
    """Multiset of config digests with a current ``completed`` status in
    the directory's registry (sweep/heal bracket records excluded) — the
    registry twin of ``harness.grid.completed_trials``'s CSV Counter."""
    return Counter(
        rec["config_digest"]
        for rec in run_registry.runs(telemetry_dir).values()
        if rec.get("kind") not in ("sweep", "heal")
        and rec.get("config_digest")
        and rec.get("status") == "completed"
    )


def sweep_plan(spec: dict, telemetry_dir: str) -> dict:
    """Diff the spec against the registry: which trials are still missing.

    Returns ``{"telemetry_dir", "cells_total", "completed", "missing":
    [{"app_name", "digest", "config"}, ...]}`` — ``missing`` preserves
    sweep order, and a digest completed N times covers at most N expected
    trials (the multiset decrement ``harness.grid.missing_configs`` uses
    on the CSV, here on the registry).
    """
    done = completed_digests(telemetry_dir)
    missing = []
    configs = spec_configs(spec)
    for cfg in configs:
        digest = run_registry.config_digest(telemetry_config_payload(cfg))
        if done[digest] > 0:
            done[digest] -= 1
        else:
            missing.append(
                {
                    "app_name": cfg.resolved_app_name(),
                    "digest": digest,
                    "config": telemetry_config_payload(cfg),
                }
            )
    return {
        "telemetry_dir": telemetry_dir,
        "cells_total": len(configs),
        "completed": len(configs) - len(missing),
        "missing": missing,
    }


def write_plan_json(plan: dict, path: str) -> None:
    with open(path, "w") as fh:
        json.dump(plan, fh, indent=2, sort_keys=True)
        fh.write("\n")


def write_plan_script(
    plan: dict,
    spec_path: str,
    path: str,
    *,
    retries: "int | None" = None,
    timeout_s: "float | None" = None,
) -> None:
    """Write the re-run plan as a shell script — ``missing_exps.sh`` with
    the hand-editing replaced by artifacts. Each line re-runs exactly one
    missing trial via ``heal --execute --cell``, so the script is
    idempotent: re-running it after a partial pass skips trials whose
    completed record already landed. ``retries``/``timeout_s`` ride onto
    every generated line (the CLI passes its own flags through), so the
    operator's retry budget survives into the script's execution."""
    extra = ""
    if retries is not None:
        extra += f" --retries {int(retries)}"
    if timeout_s:
        extra += f" --timeout-s {float(timeout_s)}"
    lines = [
        "#!/bin/sh",
        "# Generated by `python -m distributed_drift_detection_tpu heal`"
        " — the reference's",
        f"# missing_exps.sh (SURVEY.md C14) for {len(plan['missing'])} "
        f"missing of {plan['cells_total']} trials.",
        "set -e",
    ]
    for cell in plan["missing"]:
        lines.append(
            f"python -m distributed_drift_detection_tpu heal "
            f"{shlex.quote(spec_path)} "
            f"--telemetry-dir {shlex.quote(plan['telemetry_dir'])} "
            f"--execute --cell {shlex.quote(cell['app_name'])}{extra}"
        )
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    os.chmod(path, 0o755)


def submit_to_scheduler(spec: dict, plan: dict, addr: str) -> dict:
    """Submit the plan's missing cells to a running ``sched/`` scheduler
    over the jax-free control protocol — heal's push-mode alternative to
    emitting a shell script: the scheduler's worker fleet runs the
    missing trials instead of this process. The wire cells are built
    through the same ``cell_to_wire`` the scheduler's own spec expansion
    uses, so a heal-submitted cell and a spec-expanded cell are
    byte-identical (digest and all). Returns the scheduler's ack
    (``queued``/``duplicates`` counts — resubmitting a plan is
    idempotent, like re-running the generated script)."""
    from ..sched.protocol import ControlClient, cell_to_wire, parse_addr

    by_name = {cfg.resolved_app_name(): cfg for cfg in spec_configs(spec)}
    wires = [
        cell_to_wire(by_name[cell["app_name"]], digest=cell["digest"])
        for cell in plan["missing"]
    ]
    host, port = parse_addr(addr)
    with ControlClient(host, port) as client:
        return client.request({"op": "submit", "cells": wires})


def execute(
    spec: dict,
    telemetry_dir: str,
    *,
    policy: RetryPolicy = RetryPolicy(),
    only: "set[str] | None" = None,
    progress=print,
) -> int:
    """Run the sweep's missing trials under the supervisor; returns the
    number executed. ``only`` restricts to the named cells: a name whose
    trial already completed is skipped with a note (the idempotent
    contract the generated script relies on), but a name the sweep spec
    does not contain at all raises — a typoed ``--cell`` must not read as
    healed. The whole pass is bracketed by a ``kind="heal"`` registry
    record, so a crashed heal is itself visible fleet state.
    """
    from .supervisor import supervised_run  # lazy: pulls in api/jax

    plan = sweep_plan(spec, telemetry_dir)
    targets = plan["missing"]
    by_name = {cfg.resolved_app_name(): cfg for cfg in spec_configs(spec)}
    if only is not None:
        unknown = only - set(by_name)
        if unknown:
            raise ValueError(
                f"cell(s) {sorted(unknown)} are not in the sweep spec — "
                "check --cell against the plan's app names"
            )
        missing_names = {c["app_name"] for c in targets}
        for name in sorted(only - missing_names):
            progress(f"heal: cell {name!r} already completed — skipping")
        targets = [c for c in targets if c["app_name"] in only]
    if not targets:
        progress("heal: sweep is whole — nothing to run")
        return 0
    heal_id = f"heal-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
    run_registry.record(
        telemetry_dir, heal_id, "running", kind="heal",
        trials_to_run=len(targets),
    )
    try:
        for i, cell in enumerate(targets):
            cfg = replace(
                by_name[cell["app_name"]], telemetry_dir=telemetry_dir
            )
            res = supervised_run(cfg, policy)
            progress(
                f"heal [{i + 1}/{len(targets)}] {cell['app_name']}: "
                f"time={res.total_time:.2f}s "
                f"detections={res.metrics.num_detections}"
            )
    except BaseException:
        try:
            run_registry.record(telemetry_dir, heal_id, "failed", kind="heal")
        except Exception:
            pass  # best-effort: the heal's own error must surface
        raise
    run_registry.record(
        telemetry_dir, heal_id, "completed", kind="heal",
        trials_run=len(targets),
    )
    return len(targets)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu heal",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("spec", help="sweep-spec JSON (the grid as data)")
    ap.add_argument(
        "--telemetry-dir", required=True, metavar="DIR",
        help="telemetry directory whose registry records the sweep",
    )
    ap.add_argument(
        "--json", default=None, metavar="PATH",
        help="write the re-run plan as JSON",
    )
    ap.add_argument(
        "--script", default=None, metavar="PATH",
        help="write the re-run plan as an idempotent shell script "
        "(the regenerated missing_exps.sh)",
    )
    ap.add_argument(
        "--execute", action="store_true",
        help="run the missing trials under the supervisor until the "
        "sweep is whole",
    )
    ap.add_argument(
        "--scheduler", default=None, metavar="ADDR",
        help="submit the missing-cell plan to a running sched/ scheduler "
        "at HOST:PORT instead of running anything here (jax-free, like "
        "plan mode); exits 0 once the submission is accepted — the "
        "scheduler's own exit code is then the wholeness contract",
    )
    ap.add_argument(
        "--cell", action="append", default=None, metavar="KEY",
        help="with --execute: restrict to this cell (repeatable; the "
        "generated script uses one per line)",
    )
    ap.add_argument(
        "--retries", type=int, default=2,
        help="supervised retries per trial on transient failure "
        "(default 2)",
    )
    ap.add_argument(
        "--timeout-s", type=float, default=0.0,
        help="per-attempt wall-clock budget in seconds (0 = unlimited)",
    )
    args = ap.parse_args(argv)

    spec = load_spec(args.spec)
    if args.cell:
        known = {cfg.resolved_app_name() for cfg in spec_configs(spec)}
        unknown = set(args.cell) - known
        if unknown:
            raise SystemExit(
                f"heal: cell(s) {sorted(unknown)} are not in the sweep "
                "spec — check --cell against the plan's app names"
            )
    plan = sweep_plan(spec, args.telemetry_dir)
    print(
        f"sweep: {plan['cells_total']} trials, {plan['completed']} "
        f"completed, {len(plan['missing'])} missing"
    )
    for cell in plan["missing"]:
        print(f"  missing {cell['app_name']}  (digest {cell['digest']})")
    if args.json:
        write_plan_json(plan, args.json)
        print(f"plan JSON → {args.json}")
    if args.script:
        write_plan_script(
            plan, args.spec, args.script,
            retries=args.retries, timeout_s=args.timeout_s or None,
        )
        print(f"re-run script → {args.script}")
    if args.scheduler:
        if args.execute:
            raise SystemExit(
                "heal: --scheduler and --execute are mutually exclusive "
                "(push the plan to the fleet OR run it here, not both)"
            )
        if plan["missing"]:
            ack = submit_to_scheduler(spec, plan, args.scheduler)
            print(
                f"submitted {ack.get('queued', 0)} cell(s) to scheduler "
                f"{args.scheduler} ({ack.get('duplicates', 0)} already "
                "queued there)"
            )
        else:
            print("sweep is whole — nothing to submit")
        raise SystemExit(0)
    if args.execute and plan["missing"]:
        policy = RetryPolicy(
            max_attempts=max(args.retries, 0) + 1,
            timeout_s=args.timeout_s or None,
        )
        execute(
            spec,
            args.telemetry_dir,
            policy=policy,
            only=set(args.cell) if args.cell else None,
        )
        plan = sweep_plan(spec, args.telemetry_dir)
        print(
            f"after heal: {plan['completed']}/{plan['cells_total']} "
            f"completed, {len(plan['missing'])} missing"
        )
    still_missing = {c["app_name"] for c in plan["missing"]}
    if args.cell:
        # Scoped invocation (one generated-script line): the exit code
        # judges only the requested cells, or `set -e` would abort the
        # script on every line but the last.
        still_missing &= set(args.cell)
    raise SystemExit(0 if not still_missing else 1)


if __name__ == "__main__":
    main(sys.argv[1:])
