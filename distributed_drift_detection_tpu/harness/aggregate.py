"""Results aggregation and paper-style tables (reference C13/C15).

Mirrors the notebook pipeline (``Plot Results.ipynb``): load the runs CSV,
derive the dataset from the app name, group by (Dataset, Instances,
Multiplier, Memory, Cores), compute mean/variance/trial-count of Final Time
and Average Distance (cell 0); emit the LaTeX-ready CSV tables —
``time_table.csv`` (cell 8), ``drift_delay.csv`` (cell 11),
``drift_delay_var.csv`` (cell 12) — plus speedup/scaleup tables (cells 5-6).
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd

GROUP_COLS = [
    "Dataset", "Instances", "Data Multiplier", "Memory", "Cores",
    "Model", "Detector",
]
# Per-config identity *below* the instances axis: the tables/figures pivot
# Instances out of these.
CONFIG_COLS = ["Dataset", "Data Multiplier", "Cores", "Model", "Detector"]


def load_runs(results_csv: str) -> pd.DataFrame:
    df = pd.read_csv(results_csv)
    if "Dataset" not in df.columns:
        # Legacy rows (reference schema): dataset from the app name
        # "<dataset>-<time-string>" (C13). Fragile for hyphenated paths,
        # which is why the native schema carries an explicit Dataset column.
        df["Dataset"] = df["Spark App"].str.split("-").str[0].map(os.path.basename)
    for col in ("Model", "Detector"):
        # Rows written before the model/detector sweep columns existed: mark
        # unknown rather than conflating with any swept value.
        if col not in df.columns:
            df[col] = "-"
    for col in ("Final Time", "Average Distance", "Data Multiplier",
                "Rows", "Rows Per Sec", "Hits", "Spurious", "Recall"):
        # errors="coerce": the attribution cells carry "-" when a run had no
        # planted-boundary geometry to attribute against.
        if col in df.columns:
            df[col] = pd.to_numeric(df[col], errors="coerce")
    return df


def aggregate(df: pd.DataFrame) -> pd.DataFrame:
    """Per-config mean/variance/count over trials (notebook cell 0)."""
    spec = dict(
        mean_time=("Final Time", "mean"),
        var_time=("Final Time", "var"),
        mean_delay=("Average Distance", "mean"),
        var_delay=("Average Distance", "var"),
        trials=("Final Time", "count"),
    )
    if "Rows Per Sec" in df.columns:
        spec["mean_rows_per_sec"] = ("Rows Per Sec", "mean")
    if "Recall" in df.columns:
        # The quality axes (C11 schema extension): per-config mean recall /
        # hits / spurious over trials — the merge contract ("every device
        # finds the same changes") demonstrated numerically in the grid
        # study, like the delay-parity artifact does per model family.
        spec["mean_recall"] = ("Recall", "mean")
        spec["mean_hits"] = ("Hits", "mean")
        spec["mean_spurious"] = ("Spurious", "mean")
    if "Rows" in df.columns:
        # Stream length (constant across a config's trials): lets the delay-%
        # figures normalise by the actual row count instead of the legacy
        # rows-per-multiplier heuristic.
        spec["rows"] = ("Rows", "max")
    return df.groupby(GROUP_COLS, dropna=False).agg(**spec).reset_index()


def speedup_table(agg: pd.DataFrame) -> pd.DataFrame:
    """T(min instances) / T(n) per config (cell 5)."""
    rows = []
    for key, grp in agg.groupby(CONFIG_COLS, dropna=False):
        grp = grp.sort_values("Instances")
        base = grp["mean_time"].iloc[0]
        for _, r in grp.iterrows():
            row = dict(zip(CONFIG_COLS, key))
            row.update(
                {
                    "Instances": r["Instances"],
                    "mean_time": r["mean_time"],
                    "speedup": base / r["mean_time"] if r["mean_time"] else np.nan,
                }
            )
            rows.append(row)
    return pd.DataFrame(rows)


def scaleup_table(agg: pd.DataFrame, coupling: float = 16.0) -> pd.DataFrame:
    """Scaleup (cell 6): problem size grows ∝ instances; configs where
    Multiplier == coupling × Instances are comparable — perfect scaleup keeps
    time constant."""
    sel = agg[np.isclose(agg["Data Multiplier"], coupling * agg["Instances"])]
    sel = sel.sort_values(["Dataset", "Cores", "Instances"])
    out = sel.copy()
    base = sel.groupby(
        ["Dataset", "Cores", "Model", "Detector"], dropna=False
    )["mean_time"].transform("first")
    out["scaleup"] = base / out["mean_time"]
    return out


def write_tables(results_csv: str, out_dir: str = ".") -> dict[str, str]:
    """Emit the cell 8/11/12 CSV tables; returns {name: path}."""
    df = load_runs(results_csv)
    agg = aggregate(df)
    paths = {}

    def emit(name: str, frame: pd.DataFrame):
        path = os.path.join(out_dir, name)
        frame.to_csv(path, index=False)
        paths[name] = path

    emit(
        "time_table.csv",
        agg.pivot_table(
            index=CONFIG_COLS,
            columns="Instances",
            values="mean_time",
        ).reset_index(),
    )
    emit(
        "drift_delay.csv",
        agg.pivot_table(
            index=CONFIG_COLS,
            columns="Instances",
            values="mean_delay",
        ).reset_index(),
    )
    emit(
        "drift_delay_var.csv",
        agg.pivot_table(
            index=CONFIG_COLS,
            columns="Instances",
            values="var_delay",
        ).reset_index(),
    )
    emit("speedup_table.csv", speedup_table(agg))
    emit("scaleup_table.csv", scaleup_table(agg))
    return paths
