from .aggregate import aggregate, load_runs, scaleup_table, speedup_table, write_tables
from .grid import grid_configs, missing_configs, off_spec_reason, run_grid

__all__ = [
    "off_spec_reason",
    "aggregate",
    "load_runs",
    "scaleup_table",
    "speedup_table",
    "write_tables",
    "grid_configs",
    "missing_configs",
    "run_grid",
]
