"""Paper-figure suite (reference C15, ``Plot Results.ipynb`` cells 5-12).

Renders the five figures of the reference's evaluation from a runs CSV:
speedup vs instances (log2 x, cell 5), scaleup (cell 6), raw time (cell 7),
detection delay as % of stream (cell 9), delay variance (cell 10). Saved
under descriptive names (the notebook used ``0.pdf, 1.pdf, …``).

Matplotlib is imported lazily; :func:`render_all` degrades to tables-only
when it is unavailable.
"""

from __future__ import annotations

import os

from .aggregate import aggregate, load_runs, scaleup_table, speedup_table, write_tables


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _per_cores_lines(ax, frame, ycol, label_fmt="{} cores"):
    for cores, grp in frame.groupby("Cores"):
        grp = grp.sort_values("Instances")
        ax.plot(grp["Instances"], grp[ycol], marker="o", label=label_fmt.format(cores))
    ax.set_xscale("log", base=2)
    ax.set_xlabel("Instances (partitions)")
    ax.legend()


def plot_speedup(agg, out_path: str):
    plt = _plt()
    sp = speedup_table(agg)
    mults = sorted(sp["Data Multiplier"].unique())
    fig, axes = plt.subplots(1, max(len(mults), 1), figsize=(4 * max(len(mults), 1), 3.2))
    axes = [axes] if len(mults) <= 1 else list(axes)
    for ax, mult in zip(axes, mults):
        _per_cores_lines(ax, sp[sp["Data Multiplier"] == mult], "speedup")
        ax.set_title(f"mult={mult:g}")
        ax.set_ylabel("speedup  T(1)/T(n)")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def plot_time(agg, out_path: str):
    plt = _plt()
    mults = sorted(agg["Data Multiplier"].unique())
    fig, axes = plt.subplots(1, max(len(mults), 1), figsize=(4 * max(len(mults), 1), 3.2))
    axes = [axes] if len(mults) <= 1 else list(axes)
    for ax, mult in zip(axes, mults):
        _per_cores_lines(ax, agg[agg["Data Multiplier"] == mult], "mean_time")
        ax.set_title(f"mult={mult:g}")
        ax.set_ylabel("Final Time (s)")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def plot_scaleup(agg, out_path: str, coupling: float = 16.0):
    plt = _plt()
    sc = scaleup_table(agg, coupling)
    fig, ax = plt.subplots(figsize=(4.5, 3.2))
    if len(sc):
        _per_cores_lines(ax, sc, "scaleup")
    ax.set_ylabel(f"scaleup (size = {coupling:g}×instances)")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def plot_delay(agg, out_path: str, stream_rows_per_mult: int = 4000, variance=False):
    """Delay as % of stream length (cell 9) or its variance (cell 10).

    Stream length comes from the results' ``Rows`` column when present
    (native schema); ``stream_rows_per_mult`` is the legacy fallback for
    reference-style CSVs without it (4000 = outdoorStream rows per
    multiplier).
    """
    plt = _plt()
    col = "var_delay" if variance else "mean_delay"
    frame = agg.copy()
    if "rows" in frame.columns:
        stream_rows = frame["rows"]
    else:
        stream_rows = frame["Data Multiplier"] * stream_rows_per_mult
    frame["delay_pct"] = 100.0 * frame[col] / stream_rows
    mults = sorted(frame["Data Multiplier"].unique())
    fig, axes = plt.subplots(1, max(len(mults), 1), figsize=(4 * max(len(mults), 1), 3.2))
    axes = [axes] if len(mults) <= 1 else list(axes)
    for ax, mult in zip(axes, mults):
        _per_cores_lines(ax, frame[frame["Data Multiplier"] == mult], "delay_pct")
        ax.set_title(f"mult={mult:g}")
        ax.set_ylabel(("delay variance" if variance else "mean delay") + " (% stream)")
    fig.tight_layout()
    fig.savefig(out_path)
    plt.close(fig)


def render_all(results_csv: str, out_dir: str = "figures") -> dict[str, str]:
    """Tables + all five figures. Returns {artifact: path}.

    Each figure assumes one model/detector combination (the reference's
    figures have exactly one); a CSV holding a model/detector sweep is
    rendered as one figure set per combination, suffixed
    ``-<model>-<detector>`` — never mixed into one set of axes.
    """
    os.makedirs(out_dir, exist_ok=True)
    artifacts = write_tables(results_csv, out_dir)
    try:
        _plt()
    except ImportError:
        return artifacts
    agg = aggregate(load_runs(results_csv))
    combos = agg[["Model", "Detector"]].drop_duplicates()
    for _, combo in combos.iterrows():
        model, det = combo["Model"], combo["Detector"]
        sub = agg[(agg["Model"] == model) & (agg["Detector"] == det)]
        # Rows backfilled from legacy (pre-Model/Detector) CSVs carry the
        # "-" placeholder; map it to a readable token so filenames don't
        # degenerate to e.g. "speedup-----.pdf".
        mtok = "legacy" if model == "-" else model
        dtok = "legacy" if det == "-" else det
        suffix = "" if len(combos) == 1 else f"-{mtok}-{dtok}"
        for stem, fn in [
            ("speedup", plot_speedup),
            ("time", plot_time),
            ("scaleup", plot_scaleup),
        ]:
            path = os.path.join(out_dir, f"{stem}{suffix}.pdf")
            fn(sub, path)
            artifacts[f"{stem}{suffix}.pdf"] = path
        for stem, var in [("delay_pct", False), ("delay_var", True)]:
            path = os.path.join(out_dir, f"{stem}{suffix}.pdf")
            plot_delay(sub, path, variance=var)
            artifacts[f"{stem}{suffix}.pdf"] = path
    return artifacts


if __name__ == "__main__":
    import sys

    csv = sys.argv[1] if len(sys.argv) > 1 else "ddm_cluster_runs.csv"
    out = sys.argv[2] if len(sys.argv) > 2 else "figures"
    for k, v in render_all(csv, out).items():
        print(k, "->", v)
