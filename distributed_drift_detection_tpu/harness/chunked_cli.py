"""``chunked`` subcommand: drive a CSV through the streaming ingest
pipeline end to end.

    python -m distributed_drift_detection_tpu chunked stream.csv \\
        --classes 10 --partitions 8 --per-batch 100 --chunk-batches 16 \\
        --ingest-workers 4 --data-policy quarantine --telemetry-dir DIR

The batch CLI (``python -m distributed_drift_detection_tpu URL ...``)
materialises the whole stream through ``api.run``; this command is the
*streaming* twin — the disk-backed pipeline the chunked benchmark and the
serving daemon are built on, runnable on any CSV without writing Python:

    mmap'd line-aligned blocks → parse worker pool (``--ingest-workers``)
    → ordered sanitize (``--data-policy``) → pooled striper →
    ``prefetch_chunks`` producer → AOT-warmed ``ChunkedDetector``.

Labels must already be integral in ``0..classes-1`` (the streaming reader
never re-indexes — ``io.feeder.csv_chunks``); features default to the
header's column count minus the target. With ``--telemetry-dir`` the run
emits the standard chunk/heartbeat events plus the host-ingest pipeline
gauges (``ingest_stage_busy_seconds_total{stage=...}``,
``ingest_parse_queue_depth``, ``ingest_workers``) into the run log's
metric exports, and registers as ``kind="chunked"``. The final line on
stdout is one JSON object with rows/chunks/detections/rows_per_sec and
the per-stage busy breakdown — the CI ``ingest-smoke`` job asserts
worker-count invariance on it.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu chunked",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("csv", help="CSV path (named header incl. the target)")
    ap.add_argument(
        "--classes", type=int, required=True,
        help="label domain 0..C-1 (the streaming reader cannot re-index)",
    )
    ap.add_argument(
        "--features", type=int, default=0,
        help="feature count (default: header columns minus the target)",
    )
    ap.add_argument("--target-column", default="target")
    ap.add_argument("--partitions", type=int, default=8)
    ap.add_argument("--per-batch", type=int, default=100)
    ap.add_argument("--chunk-batches", type=int, default=8)
    ap.add_argument(
        "--window", type=int, default=8,
        help="speculative window width (explicit — auto needs planted "
        "geometry a raw CSV does not declare)",
    )
    ap.add_argument("--model", default="centroid")
    ap.add_argument("--detector", default="ddm")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--ingest-workers", type=int, default=0,
        help="parse worker fan-out (0 = auto; any count is bit-identical)",
    )
    ap.add_argument(
        "--block-bytes", type=int, default=16 << 20,
        help="parse block size in bytes (default 16 MiB)",
    )
    ap.add_argument(
        "--data-policy", choices=("strict", "quarantine", "repair"),
        default=None,
        help="ingest contract policy (default: trusting parse)",
    )
    ap.add_argument(
        "--quarantine-path", default="",
        help="quarantine sidecar path (default: per-run next to the run "
        "log when telemetered, <csv>.quarantine.jsonl otherwise)",
    )
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument(
        "--compile-cache-dir", default="",
        help="persistent XLA compile cache (warm restarts)",
    )
    ap.add_argument(
        "--trace-sample", type=float, default=0.0,
        help="head-sample chunks at this rate for ingest/kernel trace "
        "spans in the run log (telemetry.tracing; needs --telemetry-dir; "
        "0 = off, zero hot-path work)",
    )
    args = ap.parse_args(argv)

    with open(args.csv) as fh:
        header = fh.readline().strip().split(",")
    if args.target_column not in header:
        raise SystemExit(
            f"chunked: target column {args.target_column!r} not in header; "
            f"columns found: {header}"
        )
    features = args.features or (len(header) - 1)

    from ..api import _telemetry_bracket, prepare_chunked
    from ..config import RunConfig, telemetry_config_payload
    from ..config import host_shuffle_seed as _shuffle
    from ..io.feeder import (
        csv_chunks,
        prefetch_chunks,
        resolve_ingest_workers,
        stage_breakdown,
    )
    from ..telemetry.metrics import MetricsRegistry, write_exports

    cfg = RunConfig(
        dataset=args.csv,
        partitions=args.partitions,
        per_batch=args.per_batch,
        model=args.model,
        detector=args.detector,
        window=args.window,
        seed=args.seed,
        data_policy=args.data_policy or "strict",
        quarantine_path=args.quarantine_path,
        telemetry_dir=args.telemetry_dir,
        ingest_workers=args.ingest_workers,
        compile_cache_dir=args.compile_cache_dir,
        results_csv="",
    )
    workers = resolve_ingest_workers(cfg.ingest_workers)
    reg = MetricsRegistry()
    # ingest_workers stays OUT of the digested payload — execution knob,
    # not experiment identity (config.py's contract; any worker count is
    # bit-identical); it rides the run_completed extras + summary instead.
    payload = telemetry_config_payload(cfg)
    # cfg.data_policy has no "no policy" value; record what actually ran —
    # None = trusting parse (distinct from strict in the log AND the
    # digest; telemetry_config_payload omits the strict default).
    if args.data_policy is None:
        payload["data_policy"] = None
    with _telemetry_bracket(cfg, payload, kind="chunked") as log:
        # Prepare INSIDE the bracket (the run_multi contract, PR 9): a
        # prepare-time crash must leave the failed registry record.
        det, compile_info = prepare_chunked(
            cfg, features, args.classes, chunk_batches=args.chunk_batches
        )
        sidecar = args.quarantine_path
        if not sidecar:
            sidecar = (
                log.path[: -len(".jsonl")] + ".quarantine.jsonl"
                if log is not None
                else args.csv + ".quarantine.jsonl"
            )
        tracer = None
        if args.trace_sample > 0 and log is not None:
            from ..telemetry.tracing import ChunkTracer

            # one tracer for both pipeline halves: the ingest span and
            # the kernel span of a chunk share one trace
            tracer = ChunkTracer(log, rate=args.trace_sample, seed=args.seed)
        chunks = prefetch_chunks(
            csv_chunks(
                args.csv,
                args.partitions,
                args.per_batch,
                args.chunk_batches,
                target_column=args.target_column,
                shuffle_seed=_shuffle(cfg),
                block_bytes=args.block_bytes,
                metrics=reg,
                data_policy=args.data_policy,
                quarantine_path=sidecar,
                workers=workers,
                num_classes=args.classes,
                tracer=tracer,
            ),
            depth=2,
            metrics=reg,
        )
        t0 = time.perf_counter()
        flags = det.run(chunks, telemetry=log, metrics=reg, tracer=tracer)
        span = time.perf_counter() - t0

        import numpy as np

        detections = int((np.asarray(flags.change_global) >= 0).sum())
        rows = int(reg.counter("ingest_rows_total").values.get((), 0))
        n_chunks = int(reg.counter("ingest_chunks_total").values.get((), 0))
        quarantined = int(
            reg.counter("ingest_quarantined_total").values.get((), 0)
        )
        pipeline_s = stage_breakdown(reg)
        if log is not None:
            from ..telemetry import registry as run_registry

            log.emit(
                "run_completed",
                rows=rows,
                seconds=span,
                detections=detections,
                rows_per_sec=rows / span if span > 0 else None,
                ingest_workers=workers,
            )
            run_registry.record(cfg.telemetry_dir, log.run_id, "completed")
            import os

            write_exports(reg, os.path.splitext(log.path)[0])
    print(
        json.dumps(
            {
                "rows": rows,
                "chunks": n_chunks,
                "detections": detections,
                "quarantined": quarantined,
                "rows_per_sec": round(rows / span, 1) if span > 0 else None,
                "time_s": round(span, 4),
                "ingest_workers": workers,
                "pipeline_s": pipeline_s,
                "aot_seconds": round(compile_info.get("aot_seconds", 0.0), 4),
                "telemetry": log.path if log is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main(sys.argv[1:])
