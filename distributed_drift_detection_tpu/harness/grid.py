"""Experiment grid harness (reference C12, ``run_experiments.sh``).

The reference sweeps (data multiplier × instances × memory × cores) via a
bash loop re-invoking the whole script, with a companion notebook cell that
regenerates a ``missing_exps.sh`` for configs that lost trials to crashes
(C14, the repo's only fault-tolerance mechanism). Here the sweep is a
library/CLI function with the crash-recovery semantics built in: the grid is
*idempotent* — it counts completed trials per config in the results CSV and
only runs the missing ones, so re-running after a crash resumes exactly
(replacing the generated-bash-script dance; fixes quirk #2, the
``DDM_process.py`` case mismatch, by not shelling out at all).

Usage::

    python -m distributed_drift_detection_tpu.harness.grid \
        --dataset /root/reference/outdoorStream.csv \
        --mults 64,128 --partitions 1,2,4,8,16 --trials 5
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys
from collections import Counter

from ..config import (
    AUTO_POLICY_VERSION,
    DATA_POLICIES,
    DETECTOR_NAMES,
    RunConfig,
    replace,
    resolve_retrain_threshold,
)
from ..resilience import faults
from ..resilience.policy import RetryPolicy
from ..results import read_results

# Sweep-shape defaults, shared between this CLI's argparse flags and the
# heal subsystem's sweep-spec schema (resilience.heal._SPEC_DEFAULTS is
# this dict): a spec that omits a knob must expand to the SAME configs the
# grid CLI ran with the flag omitted, or the config digests drift and heal
# re-runs (or wrongly skips) completed trials. `seed` is the RunConfig
# default (the grid CLI exposes no flag for it).
SWEEP_DEFAULTS = {
    "models": ["centroid"],
    "detectors": ["ddm"],
    "trials": 5,
    "per_batch": 100,
    "seed": 0,
    "results_csv": "ddm_cluster_runs.csv",
    "spec": "warn",
    "data_policy": "strict",
}


def sweep_spec(dataset: str, mults, partitions, **knobs) -> dict:
    """The sweep **as data**: the spec JSON ``heal`` diffs and the
    ``sched/`` scheduler expands (``resilience.heal.load_spec`` is the
    reader; this is the one writer). Unknown knobs fail loudly — the
    same typo posture as the reader — and every omitted knob is filled
    from :data:`SWEEP_DEFAULTS`, so a spec written here expands to
    exactly the configs the grid CLI would run with those flags."""
    unknown = set(knobs) - set(SWEEP_DEFAULTS)
    if unknown:
        raise ValueError(
            f"unknown sweep knob(s) {sorted(unknown)}; known: "
            f"{sorted(SWEEP_DEFAULTS)}"
        )
    return {
        "dataset": str(dataset),
        "mults": [float(m) for m in mults],
        "partitions": [int(p) for p in partitions],
        **SWEEP_DEFAULTS,
        **knobs,
    }


def grid_configs(
    base: RunConfig,
    mults: list[float],
    partitions: list[int],
    models: list[str] | None = None,
    trials: int = 5,
    detectors: list[str] | None = None,
) -> list[RunConfig]:
    """All (mult × partitions × model × detector × trial) configs."""
    models = models or [base.model]
    detectors = detectors or [base.detector]
    out = []
    for m, p, mod, det, t in itertools.product(
        mults, partitions, models, detectors, range(trials)
    ):
        cfg = replace(
            base,
            mult_data=m,
            partitions=p,
            model=mod,
            detector=det,
            seed=base.seed + t,
        )
        out.append(replace(cfg, time_string=f"{_config_key(cfg)}-t{t}"))
    return out


def _config_key(cfg: RunConfig) -> str:
    """Trial-identity key for crash recovery: every knob that changes the
    result must appear, else a re-run with a changed knob silently skips
    trials recorded under the old settings."""
    if cfg.detector not in DETECTOR_NAMES:
        raise ValueError(
            f"unknown detector {cfg.detector!r}; expected one of {DETECTOR_NAMES}"
        )
    # Key on the *resolved* guard (RETRAIN_AUTO → per-family value): the key
    # must name what actually runs, so the auto default keeps non-guarded
    # families' completed trials valid while retiring guarded families'
    # pre-guard rows. 0.0 is an active setting; None resolves to no segment.
    rthr = resolve_retrain_threshold(cfg)
    thr = f"-r{rthr}" if rthr is not None else ""
    # The execution policy is part of every trial's identity: window and
    # speculation depth change the recorded Final Time for every model (the
    # grid's primary result column) and additionally the flags for
    # key-consuming fits (mlp/rf draw PRNG keys per window/level —
    # config.py's 'seed-equivalent but not bit-equal' caveat). Keying the
    # raw values means a *default change* (e.g. the r04 move 16×1 → auto)
    # retires old rows instead of silently resuming onto their timings —
    # the exact hazard this docstring warns about. Auto-mode keys (0
    # sentinels) additionally embed config.AUTO_POLICY_VERSION, because
    # '0' names the sentinel, not what it resolves to: a change to the
    # resolution *algorithm* must retire auto-mode rows too. Explicit pins
    # are self-describing and stay unversioned.
    win = f"-w{cfg.window}r{cfg.window_rotations}"
    if cfg.window == 0 or cfg.window_rotations == 0:
        win += f"v{AUTO_POLICY_VERSION}"
    if cfg.data_policy not in DATA_POLICIES:
        raise ValueError(
            f"unknown data_policy {cfg.data_policy!r}; expected one of "
            f"{DATA_POLICIES}"
        )
    # Non-default data policies change which rows reach the detector on a
    # dirty stream, so they are trial identity; the default stays
    # unsegmented so pre-policy completed trials remain valid.
    dp = "" if cfg.data_policy == "strict" else f"-dp{cfg.data_policy}"
    # The detector segment carries the active statistic's name + full
    # parameter tuple; non-DDM detectors embed only their own params — the
    # DDM tuple is inert for them and must not invalidate completed trials.
    # (Pre-r04 rows are all retired anyway by the W×R segment above — the
    # r04 default-policy change altered every trial's timing — so the
    # detector segment's job is only to keep *future* keys stable.)
    if cfg.detector == "ddm":
        d = cfg.ddm
        det = f"ddm{d.min_num_instances}_{d.warning_level}_{d.out_control_level}"
        if d.noise_floor:  # suffix only when active: pre-floor keys unchanged
            det += f"f{d.noise_floor}"
    else:
        det = cfg.detector + "_".join(
            str(v) for v in getattr(cfg, cfg.detector)
        )
    return (
        f"m{cfg.mult_data}-p{cfg.partitions}-{cfg.model}-b{cfg.per_batch}"
        f"{win}-{det}-s{cfg.seed}{thr}{dp}"
    )


def off_spec_reason(cfg: RunConfig) -> str | None:
    """The notebook's per-dataset grid-validity rule (C13/C14).

    ``Plot Results.ipynb`` cell 3 refuses to schedule missing trials for
    off-spec cells: outdoorStream only at ``Data Multiplier >= 64`` and
    ``Instances <= 16``; rialto-like streams at any ``mult >= 1``. The rule
    was convention in the reference (hand-enforced when regenerating
    ``missing_exps.sh``); here it is code, so an off-spec sweep is a choice
    (``spec='off'``), not an accident. Returns a human-readable reason when
    ``cfg`` falls outside its dataset's published grid, else ``None`` —
    including for datasets the notebook published no grid for (a user's own
    CSV sweeps whatever it likes, e.g. the supported ``mult_data < 1``
    subsampling mode).
    """
    name = os.path.basename(str(cfg.dataset))
    if name.startswith("outdoorStream"):
        if cfg.mult_data < 64:
            return (
                f"outdoorStream grid starts at mult_data=64 (got "
                f"{cfg.mult_data}; Plot Results.ipynb cell 3)"
            )
        if cfg.partitions > 16:
            return (
                f"outdoorStream grid caps partitions at 16 (got "
                f"{cfg.partitions}; Plot Results.ipynb cell 3)"
            )
    elif name.startswith("rialto") or str(cfg.dataset).startswith("synth:rialto"):
        # prefix, not equality: parameterized synth specs
        # ('synth:rialto,seed=1', io/stream.py) are the same published grid
        if cfg.mult_data < 1:
            return (
                f"rialto grid requires mult_data >= 1 (got {cfg.mult_data}; "
                "Plot Results.ipynb cell 3)"
            )
    return None


def completed_trials(results_csv: str) -> Counter:
    """Count completed trials per config key from the results CSV (the C13
    trial count / C14 missing-trial detection, done on live data).

    Torn-tail tolerant: a sweep killed mid-append leaves at most one
    partial trailing row, and the resume that healing exists for must not
    choke on exactly the artifact a crash produces."""
    try:
        rows = read_results(results_csv, allow_partial_tail=True)
    except FileNotFoundError:
        return Counter()
    return Counter(r["Spark App"] for r in rows)


def missing_configs(configs: list[RunConfig]) -> list[RunConfig]:
    """Crash recovery (C14): configs whose trial row is not yet in the CSV."""
    if not configs:
        return []
    done = completed_trials(configs[0].results_csv)
    todo = []
    for cfg in configs:
        key = cfg.resolved_app_name()
        if done[key] > 0:
            done[key] -= 1
        else:
            todo.append(cfg)
    return todo


def run_grid(
    base: RunConfig,
    mults: list[float],
    partitions: list[int],
    models: list[str] | None = None,
    trials: int = 5,
    progress=print,
    detectors: list[str] | None = None,
    warmup: bool = False,
    spec: str = "warn",
    telemetry_dir: str = "",
    profile_dir: str = "",
    retries: int = 0,
    timeout_s: float | None = None,
    on_error: str = "fail",
) -> int:
    """Run all missing trials of the sweep; returns number executed.

    ``warmup=True`` executes one *unrecorded* run before each config's first
    timed trial, so every recorded ``Final Time`` is warm — compile and
    first-touch device setup stay out of the 5-trial means, matching the
    reference's warm-cluster methodology (BASELINE.md: its numbers exclude
    cluster start-up; trials are config-major, so one warm run covers the
    whole trial block).

    ``spec`` applies the notebook's per-dataset grid-validity rule
    (:func:`off_spec_reason`): ``'warn'`` (default) runs off-spec cells but
    flags each once via ``progress``; ``'skip'`` drops them from the sweep;
    ``'off'`` disables the check entirely.

    ``telemetry_dir`` gives every executed trial its own JSONL run log in
    that directory (telemetry subsystem) — the filename embeds the cell's
    config key, so a crashed sweep leaves per-cell evidence of where time
    went and where drift fired, not just the missing CSV rows. Each trial
    additionally registers itself in the directory's ``index.jsonl``
    (``telemetry.registry``, via ``api.run``: running → completed/failed),
    and the sweep itself writes a bracketing ``kind="sweep"`` record with
    its trial totals — so ``watch``/``report --dir`` and a post-mortem
    both see the fleet state without parsing every log. Warm-up
    runs stay untelemetered (they are unrecorded by design).

    ``profile_dir`` wraps every executed trial's Final Time span in a
    ``jax.profiler`` capture under that directory (one timestamped
    session subdirectory per trial — ``RunConfig.profile_dir``). Profiling
    perturbs the very Final Times the grid records, so use it on
    diagnostic sweeps, not the 5-trial result grids. Warm-ups stay
    unprofiled, like telemetry.

    Resilience wiring (``resilience`` subsystem): every trial runs under
    the supervisor — ``retries`` transient-failure re-runs per cell with
    deterministic seeded backoff and ``timeout_s`` per-attempt wall-clock
    budget (``RetryPolicy``; with ``retries=0`` and no timeout the
    supervisor is a plain call plus the registry ``attempt`` bracket).
    ``on_error='continue'`` keeps sweeping past a cell whose attempts all
    failed: remaining cells run, each failure is reported via
    ``progress``, the sweep's registry record ends ``failed`` with the
    per-cell evidence next to it, and a summary ``RuntimeError`` is
    raised at the end (re-run the grid, or ``heal --execute``, to finish
    the sweep). The default ``'fail'`` stops at the first failed cell,
    matching the reference's crash behaviour. ``run_grid`` also arms any
    fault sites requested via the ``DDD_FAULTS`` env var
    (``resilience.faults.arm_from_env``) — inert unless set.
    """
    if spec not in ("warn", "skip", "off"):
        raise ValueError(f"spec must be 'warn', 'skip' or 'off', got {spec!r}")
    if on_error not in ("fail", "continue"):
        raise ValueError(
            f"on_error must be 'fail' or 'continue', got {on_error!r}"
        )

    from ..api import run  # lazy: keeps harness importable without jax init
    from ..resilience.supervisor import supervise

    armed = faults.arm_from_env()
    if armed:
        progress(f"grid: fault site(s) armed from DDD_FAULTS: {armed}")
    policy = RetryPolicy(
        max_attempts=max(retries, 0) + 1, timeout_s=timeout_s, seed=base.seed
    )

    configs = grid_configs(base, mults, partitions, models, trials, detectors)
    if spec != "off":
        flagged: set[str] = set()
        kept = []
        for cfg in configs:
            reason = off_spec_reason(cfg)
            if reason is None:
                kept.append(cfg)
                continue
            if reason not in flagged:
                flagged.add(reason)
                verb = "skipping" if spec == "skip" else "off-spec"
                progress(f"grid {verb}: {reason}")
            if spec == "warn":
                kept.append(cfg)
        configs = kept
    todo = missing_configs(configs)
    progress(f"grid: {len(configs)} trials total, {len(todo)} to run")

    # Sweep-level registry bracket: the fleet view of "a sweep is running
    # here, N trials to go" (per-trial records are api.run's job). A
    # crashed sweep reads as status=failed next to however many per-trial
    # records it got through — the registry equivalent of the idempotent
    # resume the CSV already provides.
    sweep_id = None
    if telemetry_dir:
        import time as _time

        from ..telemetry import registry as run_registry

        sweep_id = (
            f"sweep-{_time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
        )
        run_registry.record(
            telemetry_dir, sweep_id, "running", kind="sweep",
            trials_total=len(configs), trials_to_run=len(todo),
        )
    failures: list[tuple[str, Exception]] = []
    try:
        warmed = None
        for i, cfg in enumerate(todo):
            static_key = (
                cfg.dataset, cfg.mult_data, cfg.partitions, cfg.model,
                cfg.detector, cfg.per_batch, cfg.window, cfg.window_rotations,
            )
            if telemetry_dir:
                cfg = replace(cfg, telemetry_dir=telemetry_dir)
            if profile_dir:
                cfg = replace(cfg, profile_dir=profile_dir)
            key = cfg.resolved_app_name()

            # The fault site lives INSIDE the supervised attempt, so a
            # positional arming (`at=K`) fires once and the retry heals
            # it — the deterministic stand-in for a transient crash.
            def attempt(cfg=cfg, i=i, key=key):
                faults.fire("grid.cell", index=i, key=key)
                return run(cfg)

            try:
                # The warm-up runs OUTSIDE the supervised attempt: it must
                # not be charged against the per-attempt timeout budget or
                # repeated per retry (its whole point is once per config
                # block). Unrecorded on every axis: no CSV row, no
                # telemetry log/registry record, no profile capture.
                if warmup and static_key != warmed:
                    run(replace(
                        cfg, results_csv="", time_string="warmup",
                        telemetry_dir=None, profile_dir="",
                    ))
                    warmed = static_key
                res = supervise(
                    attempt, policy, telemetry_dir=telemetry_dir, name=key
                )
            except Exception as exc:
                if on_error != "continue":
                    raise
                failures.append((key, exc))
                progress(
                    f"[{i + 1}/{len(todo)}] {key}: FAILED "
                    f"({type(exc).__name__}: {exc}) — continuing"
                )
                continue
            progress(
                f"[{i + 1}/{len(todo)}] {key}: "
                f"time={res.total_time:.2f}s detections={res.metrics.num_detections} "
                f"delay={res.metrics.mean_delay_rows:.1f} rows"
            )
    except BaseException:
        if sweep_id is not None:
            try:
                run_registry.record(
                    telemetry_dir, sweep_id, "failed", kind="sweep"
                )
            except Exception:
                pass  # best-effort: the sweep's own error must surface
        raise
    if sweep_id is not None:
        run_registry.record(
            telemetry_dir, sweep_id,
            "failed" if failures else "completed", kind="sweep",
            trials_run=len(todo) - len(failures),
            trials_failed=len(failures),
        )
    if failures:
        # The sweep finished its schedule but is not whole: fail loudly
        # with the evidence pointer instead of returning a count that
        # reads as success (on_error='fail' never reaches here).
        raise RuntimeError(
            f"{len(failures)} of {len(todo)} trials failed "
            f"({', '.join(k for k, _ in failures)}); the registry/CSV have "
            "the evidence — re-run the grid or `heal --execute` to finish"
        )
    return len(todo)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", default="/root/reference/outdoorStream.csv")
    ap.add_argument("--mults", default="1,2,4")
    ap.add_argument("--partitions", default="1,2,4,8")
    ap.add_argument("--models", default=",".join(SWEEP_DEFAULTS["models"]))
    ap.add_argument("--detectors", default=",".join(SWEEP_DEFAULTS["detectors"]))
    ap.add_argument("--trials", type=int, default=SWEEP_DEFAULTS["trials"])
    ap.add_argument("--per-batch", type=int, default=SWEEP_DEFAULTS["per_batch"])
    ap.add_argument("--results-csv", default=SWEEP_DEFAULTS["results_csv"])
    ap.add_argument(
        "--warmup",
        action="store_true",
        help="one unrecorded warm run before each config's timed trials "
        "(warm-only Final Times; see run_grid)",
    )
    ap.add_argument(
        "--spec",
        default=SWEEP_DEFAULTS["spec"],
        choices=["warn", "skip", "off"],
        help="notebook grid-validity rule (off_spec_reason): warn on "
        "off-spec (dataset, mult, partitions) cells, skip them, or disable "
        "the check",
    )
    ap.add_argument(
        "--data-policy",
        default=SWEEP_DEFAULTS["data_policy"],
        choices=list(DATA_POLICIES),
        help="ingest contract policy for dirty CSVs (io.sanitize): strict "
        "= fail loudly on the first violating row; quarantine = mask "
        "violating rows (quarantine.jsonl sidecar) and continue; repair "
        "= impute NaN cells / clamp labels, quarantining the rest",
    )
    ap.add_argument(
        "--telemetry-dir",
        default="",
        help="per-trial JSONL run logs into this directory (telemetry "
        "subsystem; summarize with `python -m "
        "distributed_drift_detection_tpu report <run.jsonl>`)",
    )
    ap.add_argument(
        "--compile-cache-dir",
        default="",
        help="persistent XLA compilation cache directory "
        "(utils.compile_cache): sweep cells repeated across invocations — "
        "and heal re-runs — skip compilation entirely (warm-start)",
    )
    ap.add_argument(
        "--profile-dir",
        default="",
        help="wrap each trial's Final Time span in a jax.profiler capture "
        "under this directory (perturbs the recorded Final Times — "
        "diagnostic sweeps only; see run_grid)",
    )
    ap.add_argument(
        "--retries",
        type=int,
        default=0,
        help="supervised re-runs per trial on transient failure "
        "(resilience.RetryPolicy; deterministic seeded backoff)",
    )
    ap.add_argument(
        "--timeout-s",
        type=float,
        default=0.0,
        help="per-attempt wall-clock budget in seconds (0 = unlimited)",
    )
    ap.add_argument(
        "--continue-on-error",
        action="store_true",
        help="keep sweeping past a failed cell; the sweep exits nonzero "
        "at the end with the failed cells listed (heal --execute or a "
        "re-run finishes it)",
    )
    ap.add_argument(
        "--spec-out",
        default="",
        metavar="PATH",
        help="also write this sweep as a spec JSON (sweep_spec) — the "
        "artifact `heal` diffs and the sched/ scheduler re-runs, so the "
        "exact grid is recoverable without reconstructing the flags",
    )
    args = ap.parse_args(argv)

    if args.spec_out:
        import json

        spec = sweep_spec(
            args.dataset,
            [float(m) for m in args.mults.split(",")],
            [int(p) for p in args.partitions.split(",")],
            models=args.models.split(","),
            detectors=args.detectors.split(","),
            trials=args.trials,
            per_batch=args.per_batch,
            results_csv=args.results_csv,
            spec=args.spec,
            data_policy=args.data_policy,
        )
        with open(args.spec_out, "w") as fh:
            json.dump(spec, fh, indent=2, sort_keys=True)
            fh.write("\n")

    base = RunConfig(
        dataset=args.dataset,
        per_batch=args.per_batch,
        results_csv=args.results_csv,
        data_policy=args.data_policy,
        compile_cache_dir=args.compile_cache_dir,
    )
    run_grid(
        base,
        mults=[float(m) for m in args.mults.split(",")],
        partitions=[int(p) for p in args.partitions.split(",")],
        models=args.models.split(","),
        trials=args.trials,
        detectors=args.detectors.split(","),
        warmup=args.warmup,
        spec=args.spec,
        telemetry_dir=args.telemetry_dir,
        profile_dir=args.profile_dir,
        retries=args.retries,
        timeout_s=args.timeout_s or None,
        on_error="continue" if args.continue_on_error else "fail",
    )


if __name__ == "__main__":
    main(sys.argv[1:])
