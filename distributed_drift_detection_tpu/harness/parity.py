"""Detection-delay parity harness: the "≤ 1-batch change" acceptance proof.

The north star (BASELINE.json) requires the TPU-native model families to
match the reference's detection delay within one batch. The reference
published no recoverable delay numbers (SURVEY.md §6: its runs CSV is not
committed), so the baseline is this framework's own ``model='rf'`` — the
same model family, hyper-parameters and loop as the reference's workers
(sklearn RandomForest via host callback, ``models/rf.py``; reference
``DDM_Process.py:96-105``).

Methodology mirrors the reference's trial harness (``Plot Results.ipynb``
cell 0: ≥5 trials per config, mean/variance): each model runs the same
planted-drift stream over N seeds; the statistic is ``mean_delay_batches``
in **global-batch units** (one global batch = ``per_batch`` rows of the
merged stream). One *worker*-batch spans ``partitions × per_batch`` rows
= ``partitions`` global units, so the acceptance criterion is

    delay(model) − delay(rf) ≤ partitions   (global-batch units)

one-sided: a family may detect *earlier* than the RF baseline by any margin
(an improvement, not a parity failure — the north star bounds degradation),
but no more than one worker-batch later.

Delay alone is gameable — a family that fires more often looks "earlier"
on mean delay while spraying extra detections. Each run is therefore also
decomposed against the planted boundaries
(``metrics.attribution_metrics``): detections split into per-(partition,
boundary) *first hits* (reported with their own hit-based delay) and
*spurious* extra fires, with a second acceptance axis bounding
spurious-rate inflation vs rf (:func:`check_spurious`,
``SPURIOUS_TOLERANCE``) — the reference's merge contract is about *which*
changes are found (``DDM_Process.py:89-92``), not just how late.

Run ``python -m distributed_drift_detection_tpu.harness.parity`` to
regenerate the committed artifact ``results/delay_parity.csv`` (per-seed
rows) and print the PARITY.md summary table; ``tests/test_parity.py``
asserts the criterion at CI size.
"""

from __future__ import annotations

import argparse
import csv
import math
import os
import sys
from typing import NamedTuple

FIELDS = [
    "model",
    "seed",
    "mean_delay_batches",
    "mean_delay_rows",
    "detections",
    # Boundary attribution (metrics.attribution_metrics): decomposes
    # `detections` into first hits on planted boundaries vs spurious extra
    # fires, so "earlier" can't be bought by firing more often.
    "hits",
    "misses",
    "spurious",
    "precision",
    "recall",
    "first_hit_delay_batches",  # mean first-hit delay, global-batch units
    "partitions",
    "per_batch",
    "mult_data",
    "dataset",
]

# Model specs: a bare family name, or ``family@variant`` selecting a shipped
# preset (the ``model`` CSV column records the full spec). Variants:
#   @robust — detector preset ``config.DDM_ROBUST`` (band-width noise floor
#   at the reference's REGRESSION_THRESH constant): the shipped config for
#   residual-error families — ``linear``'s documented over-firing fix
#   (VERDICT r4 #5; measured in the committed artifact's linear@robust rows).
DEFAULT_MODELS = (
    "rf", "centroid", "gnb", "mlp", "linear", "forest", "linear@robust",
)

# The acceptance gate (``report(required=...)``) covers every shipped
# on-device family (VERDICT r4 #1 — the flagship-only gate let silent
# failures ship). Documented, tested opt-outs:
#   * ``linear`` at the reference's raw 3/0.5/1.5 sensitivity — measured
#     over-firing on rialto-like regimes (PARITY.md); its gated
#     configuration is ``linear@robust`` (the shipped preset above).
#   * ``majority`` — golden-oracle family, not part of the parity sweep
#     (bit-exact tests in tests/test_engine.py are its acceptance).
#   * ``rf`` — the baseline itself.
REQUIRED_MODELS = ("centroid", "gnb", "mlp", "forest", "linear@robust")

# The two benchmark geometries of the committed artifact (VERDICT r3 #3/#4:
# parity must hold on the reference's *primary published dataset*, not only
# the rialto stand-in). outdoorStream is consumed from the reference
# checkout (PARITY.md C16); mult=64 is the smallest on-spec cell of the
# notebook grid (harness.grid.off_spec_reason), p=8 keeps the CPU-mesh
# provenance of the artifact. Format: (dataset, mult_data, partitions).
DEFAULT_GEOMETRIES = (
    ("synth:rialto", 4.0, 8),
    ("/root/reference/outdoorStream.csv", 64.0, 8),
)

# Acceptance bound on spurious-rate inflation vs the rf baseline
# (check_spurious): at most 15 percentage points more of a model's
# detections may be non-first fires than rf's on the same streams.
SPURIOUS_TOLERANCE = 0.15


def measure_delay_parity(
    models=DEFAULT_MODELS,
    dataset: str = "synth:rialto",
    mult_data: float = 4.0,
    partitions: int = 8,
    per_batch: int = 100,
    seeds=range(5),
    rf_estimators: int = 100,
    progress=None,
) -> list[dict]:
    """Per-(model, seed) delay rows for the parity table.

    The stream geometry is identical across models and varies only by seed
    (``RunConfig.seed`` drives the duplicate-shuffle, the stripe-time batch
    shuffle and every model's fit keys), so differences are attributable to
    the model family alone — the comparison the criterion needs.
    """
    from ..api import run
    from ..config import RunConfig, parse_model_spec
    from ..metrics import attribution_metrics

    rows = []
    for model in models:
        family, extra = parse_model_spec(model)
        for seed in seeds:
            cfg = RunConfig(
                dataset=dataset,
                mult_data=mult_data,
                partitions=partitions,
                per_batch=per_batch,
                model=family,
                seed=seed,
                rf_estimators=rf_estimators,
                results_csv="",
                **extra,
            )
            res = run(cfg)
            m = res.metrics
            a = attribution_metrics(
                res.flags.change_global,
                res.stream.dist_between_changes,
                res.stream.num_rows,
            )
            rows.append(
                {
                    "model": model,
                    "seed": seed,
                    "mean_delay_batches": round(m.mean_delay_batches, 4),
                    "mean_delay_rows": round(m.mean_delay_rows, 2),
                    "detections": m.num_detections,
                    "hits": a.hits,
                    "misses": a.misses,
                    "spurious": a.spurious,
                    "precision": round(a.precision, 4),
                    "recall": round(a.recall, 4),
                    "first_hit_delay_batches": round(
                        a.mean_first_hit_delay_rows / per_batch, 4
                    ),
                    "partitions": partitions,
                    "per_batch": per_batch,
                    "mult_data": mult_data,
                    "dataset": dataset,
                }
            )
            if progress is not None:
                progress(
                    f"{model} seed={seed}: delay={m.mean_delay_batches:.2f} "
                    f"global batches (first-hit "
                    f"{a.mean_first_hit_delay_rows / per_batch:.2f}), "
                    f"detections={m.num_detections} = {a.hits} hits + "
                    f"{a.spurious} spurious, recall={a.recall:.3f}"
                )
    return rows


def group_by_geometry(rows: list[dict]) -> dict[tuple, list[dict]]:
    """Split measured rows by stream geometry (dataset, mult, partitions,
    per_batch). The acceptance criteria compare models *on the same
    streams*; a multi-geometry CSV (the committed artifact carries both
    benchmark geometries) must never pool a model's rialto rows against
    rf's outdoorStream rows."""
    out: dict[tuple, list[dict]] = {}
    for r in rows:
        key = (
            str(r["dataset"]),
            float(r["mult_data"]),
            int(r["partitions"]),
            int(r["per_batch"]),
        )
        out.setdefault(key, []).append(r)
    return out


class ParitySummary(NamedTuple):
    model: str
    mean: float  # mean over seeds of mean_delay_batches
    std: float  # population std over seeds
    detections: float  # mean detections over seeds
    # Attribution means over seeds (nan when the rows predate the columns —
    # a legacy CSV loaded through summarize still gets the delay fields).
    hits: float
    spurious: float
    recall: float
    first_hit_delay: float  # mean first-hit delay, global-batch units


def _mean_of(rs: list[dict], field: str) -> float:
    vals = [float(r[field]) for r in rs if field in r and r[field] != ""]
    return sum(vals) / len(vals) if vals else float("nan")


def summarize(rows: list[dict]) -> list[ParitySummary]:
    """Per-model mean ± std of the per-seed delays (the PARITY.md table)."""
    by_model: dict[str, list[dict]] = {}
    for r in rows:
        by_model.setdefault(str(r["model"]), []).append(r)
    out = []
    for model, rs in by_model.items():
        d = [float(r["mean_delay_batches"]) for r in rs]
        mu = sum(d) / len(d)
        var = sum((x - mu) ** 2 for x in d) / len(d)
        out.append(
            ParitySummary(
                model,
                mu,
                math.sqrt(var),
                _mean_of(rs, "detections"),
                _mean_of(rs, "hits"),
                _mean_of(rs, "spurious"),
                _mean_of(rs, "recall"),
                _mean_of(rs, "first_hit_delay_batches"),
            )
        )
    return out


def check_criterion(
    rows: list[dict], baseline: str = "rf"
) -> dict[str, float]:
    """Gap of each model vs the baseline family, in global-batch units.

    Returns ``{model: delay(model) − delay(baseline)}``; the acceptance
    criterion is the one-sided ``gap ≤ partitions`` (no more than one
    worker-batch *later* than the RF family; earlier is an improvement).
    Raises if the baseline family is absent.
    """
    summary = {s.model: s for s in summarize(rows)}
    if baseline not in summary:
        raise ValueError(f"baseline model {baseline!r} not in measured rows")
    base = summary[baseline].mean
    return {
        m: s.mean - base for m, s in summary.items() if m != baseline
    }


def check_spurious(
    rows: list[dict], baseline: str = "rf"
) -> dict[str, float]:
    """Spurious-rate inflation of each model vs the baseline family.

    The delay criterion alone is one-sided on lateness: a model that fires
    *more often* can buy a better mean delay with extra detections. This
    closes the loophole on the other axis: per model, the spurious rate is
    ``spurious / (hits + spurious)`` (the fraction of detections that are
    not first hits on a planted boundary), and the returned value is
    ``rate(model) − rate(baseline)``. Acceptance (tests/test_parity.py,
    results/README.md): inflation ≤ 0.15 — a model may spend at most 15
    percentage points more of its detections on non-first fires than the
    reference's RandomForest family on the same streams.
    """
    # summarize() tolerates pre-attribution CSV rows (nan means) for the
    # delay columns, but a rate criterion must not quietly compute over a
    # different row subset than the delay criterion (mixed CSV) or
    # propagate nan into a silent FAIL (all-legacy CSV) — demand the
    # columns on every row.
    for r in rows:
        if r.get("hits", "") == "" or r.get("spurious", "") == "":
            raise ValueError(
                f"row (model={r.get('model')!r}, seed={r.get('seed')!r}) "
                "lacks attribution columns (pre-r03 CSV?); regenerate with "
                "harness.parity"
            )
    summary = {s.model: s for s in summarize(rows)}
    if baseline not in summary:
        raise ValueError(f"baseline model {baseline!r} not in measured rows")

    def rate(s: ParitySummary) -> float:
        total = s.hits + s.spurious
        return s.spurious / total if total else 0.0

    base = rate(summary[baseline])
    return {
        m: rate(s) - base for m, s in summary.items() if m != baseline
    }


def write_csv(rows: list[dict], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as fh:
        w = csv.DictWriter(fh, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)


def report(
    rows: list[dict], progress=print, required: tuple = REQUIRED_MODELS
) -> bool:
    """Per-geometry summary table + both acceptance criteria; returns True
    when every ``required`` model passes both axes in every geometry that
    has the rf baseline. The default gate covers every shipped on-device
    family (``REQUIRED_MODELS``); the sweep additionally measures the
    documented opt-outs (bare ``linear`` at the reference's raw
    sensitivity — its gated form is ``linear@robust``) so the artifact
    still records them honestly without reporting failure for doing so."""
    all_ok = True
    for key, grp in group_by_geometry(rows).items():
        dataset, mult, partitions, _ = key
        progress(f"\n=== {dataset} ×{mult:g}, {partitions} partitions ===")
        progress(
            f"{'Model':<10} {'mean delay':>14} {'first-hit':>10} "
            f"{'detections':>11} {'hits':>6} {'spurious':>8} {'recall':>7}"
        )
        for s in summarize(grp):
            progress(
                f"{s.model:<10} {s.mean:>8.1f} ± {s.std:<4.1f} "
                f"{s.first_hit_delay:>10.1f} {s.detections:>11.0f} "
                f"{s.hits:>6.0f} {s.spurious:>8.0f} {s.recall:>7.3f}"
            )
        if "rf" not in {r["model"] for r in grp}:
            progress("(rf baseline not measured — criterion check skipped)")
            if required:
                # An unevaluable criterion is not a passed criterion: the
                # verdict must not be a vacuous True when the baseline is
                # absent from a geometry.
                all_ok = False
            continue
        spur = check_spurious(grp)
        gaps = check_criterion(grp)
        for model, gap in gaps.items():
            ok_delay = gap <= partitions
            ok_spur = spur[model] <= SPURIOUS_TOLERANCE
            if model in required:
                all_ok = all_ok and ok_delay and ok_spur
            progress(
                f"{model}: delay gap vs rf = {gap:+.1f} global batches "
                f"(criterion ≤ +{partitions}) "
                f"{'OK' if ok_delay else 'FAIL'}; spurious-rate inflation = "
                f"{spur[model]:+.3f} (criterion ≤ +{SPURIOUS_TOLERANCE}) "
                f"{'OK' if ok_spur else 'FAIL'}"
            )
        for m in required:
            if m not in gaps:  # required model never measured here
                all_ok = False
                progress(f"{m}: required but not measured in this geometry")
    return all_ok


def _parse_geometry(spec: str) -> tuple[str, float, int]:
    """'dataset|mult|partitions' (| because dataset specs may contain both
    ':' and ',' — e.g. 'synth:rialto,seed=1')."""
    parts = spec.split("|")
    if len(parts) != 3:
        raise ValueError(
            f"geometry {spec!r} is not 'dataset|mult|partitions'"
        )
    return parts[0], float(parts[1]), int(parts[2])


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--geometry",
        action="append",
        default=None,
        metavar="DATASET|MULT|PARTITIONS",
        help="a stream geometry to measure (repeatable); default: both "
        "benchmark geometries (rialto stand-in ×4 and outdoorStream ×64)",
    )
    ap.add_argument("--per-batch", type=int, default=100)
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--models", default=",".join(DEFAULT_MODELS))
    ap.add_argument("--rf-estimators", type=int, default=100)
    ap.add_argument("--out", default="results/delay_parity.csv")
    ap.add_argument(
        "--device",
        default="cpu",
        choices=["cpu", "default"],
        help="'cpu' (default) pins an 8-virtual-device CPU mesh — the "
        "committed artifact's provenance, deterministic and host-callback "
        "friendly for the rf baseline; 'default' uses whatever JAX picks",
    )
    args = ap.parse_args(argv)
    geometries = (
        [_parse_geometry(g) for g in args.geometry]
        if args.geometry
        else list(DEFAULT_GEOMETRIES)
    )
    # Fail fast on a missing dataset file: the expensive geometry runs
    # first, and a late FileNotFoundError would discard every measured row
    # (synthetic "synth:..." specs need no file).
    for ds, _, _ in geometries:
        if not ds.startswith("synth:") and not os.path.exists(ds):
            ap.error(f"dataset {ds!r} does not exist")

    if args.device == "cpu":
        # A site hook may have initialised an accelerator backend at
        # interpreter start, after which the device count can no longer be
        # changed — so re-exec in a fresh process whose environment forces
        # the CPU platform before any JAX touch (same hermetic trick as
        # __graft_entry__.dryrun_multichip; shared helper so every site-hook
        # hardening lands in all re-exec paths at once).
        import subprocess

        from ..utils.hermetic import hermetic_cpu_env

        env = hermetic_cpu_env(8)
        child_argv = [  # rebuilt from parsed args (not filtered raw argv)
            "--per-batch", str(args.per_batch),
            "--seeds", str(args.seeds),
            "--models", args.models,
            "--rf-estimators", str(args.rf_estimators),
            "--out", args.out,
            "--device", "default",
        ]
        for ds, mult, p in geometries:
            child_argv += ["--geometry", f"{ds}|{mult}|{p}"]
        raise SystemExit(
            subprocess.call(
                [
                    sys.executable,
                    "-m",
                    "distributed_drift_detection_tpu.harness.parity",
                    *child_argv,
                ],
                env=env,
            )
        )

    rows = []
    for ds, mult, partitions in geometries:
        rows += measure_delay_parity(
            models=args.models.split(","),
            dataset=ds,
            mult_data=mult,
            partitions=partitions,
            per_batch=args.per_batch,
            seeds=range(args.seeds),
            rf_estimators=args.rf_estimators,
            progress=lambda msg, _ds=ds: print(f"[{_ds}] {msg}"),
        )
        # Incremental write: a crash in a later geometry must not discard
        # the completed ones' measurements.
        write_csv(rows, args.out)
    print(f"\nwrote {args.out} ({len(rows)} rows)")
    # Exit status carries the acceptance verdict (CI/cron don't scrape
    # stdout for 'FAIL'). The gate covers the required families *that were
    # swept*: a deliberate --models subset is an informational run and must
    # not exit 1 for omitting families.
    required = tuple(
        m for m in REQUIRED_MODELS if m in args.models.split(",")
    )
    raise SystemExit(0 if report(rows, required=required) else 1)


if __name__ == "__main__":
    main(sys.argv[1:])
