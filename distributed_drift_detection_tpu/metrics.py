"""Detection-delay metrics and run records (reference C10/C11/L6).

The reference computes, per detected change, ``change_position %
dist_between_changes`` (``calc_change_dist``, ``DDM_Process.py:253-256``) —
valid because planted concepts are equal-length — then drops −1 sentinel rows
(``:259``) and appends the mean plus the run configuration to a results CSV
(``:265-273``). Reproduced here over the gathered flag tables, plus
throughput fields the reference lacks (records/sec, the BASELINE.json
metric).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import numpy as np

# Placeholder for results-CSV cells with nothing to attribute against —
# runs without planted-boundary geometry have no ground truth for the
# Hits/Spurious/Recall quality axes (the reference's own CSV uses "-" for
# Spark knobs with no meaning in a given mode, config.py's `memory`).
NO_ATTRIBUTION = "-"


@dataclasses.dataclass(frozen=True)
class DelayMetrics:
    num_detections: int
    mean_delay_rows: float  # mean(change_global % dist_between_changes)
    mean_delay_batches: float
    detections_per_partition: np.ndarray  # [P] i32
    delays: np.ndarray  # all individual delays (rows)


def delay_metrics(
    change_global: np.ndarray, dist_between_changes: int, per_batch: int
) -> DelayMetrics:
    """Compute delay stats from a ``[P, NB-1]`` change-position table."""
    change_global = np.asarray(change_global)
    detected = change_global >= 0
    positions = change_global[detected]
    delays = positions % dist_between_changes
    mean_rows = float(delays.mean()) if len(delays) else float("nan")
    return DelayMetrics(
        num_detections=int(detected.sum()),
        mean_delay_rows=mean_rows,
        mean_delay_batches=mean_rows / per_batch if len(delays) else float("nan"),
        detections_per_partition=detected.sum(axis=-1).astype(np.int32),
        delays=delays,
    )


@dataclasses.dataclass(frozen=True)
class AttributionMetrics:
    """Detections attributed to planted concept boundaries (quality axis).

    The reference's merge contract is about *which* changes are found —
    "every device will find the same changes" (``DDM_Process.py:89-92``) —
    so a delay number alone under-constrains quality: a model that fires
    more often can look "earlier" on mean delay while actually spraying
    extra detections. Attribution closes that loophole: each detection at
    global position ``g`` attributes to the most recent planted boundary
    (``g // dist``, boundaries at ``m·dist`` for ``m ≥ 1``); per
    (partition, boundary) the earliest attributed detection is the *first
    hit* (its delay is ``g % dist``), later ones and any detection before
    the first boundary are *spurious*. This generalises the soak's exact
    accounting (``engine.soak.planted_interior_boundaries``) to the striped
    api streams, where every partition sees every global boundary.

    ``precision`` = first hits / all detections; ``recall`` = hit
    (partition, boundary) pairs / (partitions × boundaries).
    """

    num_boundaries: int  # interior planted boundaries in the global stream
    hits: int  # (partition, boundary) pairs with >= 1 attributed detection
    misses: int  # partitions * num_boundaries - hits
    spurious: int  # non-first attributed + pre-first-boundary detections
    precision: float  # hits / num_detections (nan when no detections)
    recall: float  # hits / (partitions * num_boundaries)
    mean_first_hit_delay_rows: float  # over hit pairs only (nan when none)
    first_hit_delays: np.ndarray  # [hits] i64, rows past the boundary


def attribution_metrics(
    change_global: np.ndarray, dist_between_changes: int, num_rows: int
) -> AttributionMetrics:
    """Attribute a ``[P, NB-1]`` change-position table to planted boundaries.

    ``dist_between_changes`` is the planted concept length of the *global*
    stream (``StreamData.dist_between_changes``); boundaries sit at
    ``m·dist`` for ``1 ≤ m ≤ (num_rows − 1) // dist``. Positions are global
    row ids, so the same boundary set applies to every partition's stripe.
    """
    change_global = np.asarray(change_global)
    p = change_global.shape[0]
    dist = int(dist_between_changes)
    nb = (int(num_rows) - 1) // dist if dist > 0 else 0
    detected = change_global >= 0
    num_detections = int(detected.sum())
    if nb <= 0 or num_detections == 0:
        return AttributionMetrics(
            num_boundaries=nb,
            hits=0,
            misses=p * nb,
            spurious=num_detections,
            precision=float("nan") if num_detections == 0 else 0.0,
            recall=0.0 if nb else float("nan"),
            mean_first_hit_delay_rows=float("nan"),
            first_hit_delays=np.empty(0, np.int64),
        )

    part, _ = np.nonzero(detected)
    pos = change_global[detected].astype(np.int64)
    boundary = pos // dist  # 0 = before the first boundary -> spurious
    in_range = (boundary >= 1) & (boundary <= nb)
    # First (earliest-by-position) detection per (partition, boundary):
    # sort by position, then np.unique's first occurrence per pair is the
    # earliest (flag tables are batch-ordered and already ascending, but
    # position order is the contract, not column order).
    pb = part[in_range] * np.int64(nb + 1) + boundary[in_range]
    pos_ir = pos[in_range]
    order = np.argsort(pos_ir, kind="stable")
    _, first_idx = np.unique(pb[order], return_index=True)
    hits = int(first_idx.size)
    delays = (pos_ir[order][first_idx] % dist).astype(np.int64)
    return AttributionMetrics(
        num_boundaries=nb,
        hits=hits,
        misses=p * nb - hits,
        spurious=num_detections - hits,
        precision=hits / num_detections,
        recall=hits / (p * nb),
        # hits == 0 is reachable with detections present (all spurious —
        # e.g. every fire lands before the first boundary): nan, silently.
        mean_first_hit_delay_rows=(
            float(delays.mean()) if hits else float("nan")
        ),
        first_hit_delays=delays,
    )


# Reference C11 column schema (``DDM_Process.py:272``), kept verbatim so the
# notebook-style aggregation (C13-C15) ports unchanged; extended with
# throughput columns and the boundary-attribution quality axes (Hits /
# Spurious / Recall — the merge contract "every device finds the same
# changes", ``DDM_Process.py:89-92``, as a number per run, not only in the
# delay-parity artifact). "Spark Address" carries the backend string here.
RESULT_COLUMNS = [
    "Spark App",
    "Exp Start Time",
    "Spark Address",
    "Instances",
    "Data Multiplier",
    "Memory",
    "Cores",
    "Final Time",
    "Average Distance",
    "Dataset",
    "Per Batch",
    "Rows",
    "Rows Per Sec",
    "Detections",
    "Model",
    "Detector",
    "Hits",
    "Spurious",
    "Recall",
]


def result_row(
    cfg: Any,
    total_time: float,
    metrics: DelayMetrics,
    num_rows: int,
    attribution: AttributionMetrics | None = None,
) -> list:
    """One results-CSV row. ``attribution`` is optional so callers without
    planted-boundary geometry still record the reference columns; absent, the
    quality cells carry :data:`NO_ATTRIBUTION`."""
    return [
        cfg.resolved_app_name(),
        cfg.time_string,
        cfg.url,
        cfg.partitions,
        float(cfg.mult_data),
        cfg.memory,
        cfg.cores,
        total_time,
        metrics.mean_delay_rows,
        os.path.basename(cfg.dataset),
        cfg.per_batch,
        num_rows,
        num_rows / total_time if total_time > 0 else float("nan"),
        metrics.num_detections,
        cfg.model,
        cfg.detector,
        attribution.hits if attribution else NO_ATTRIBUTION,
        attribution.spurious if attribution else NO_ATTRIBUTION,
        attribution.recall if attribution else NO_ATTRIBUTION,
    ]
