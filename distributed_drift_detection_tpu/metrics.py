"""Detection-delay metrics and run records (reference C10/C11/L6).

The reference computes, per detected change, ``change_position %
dist_between_changes`` (``calc_change_dist``, ``DDM_Process.py:253-256``) —
valid because planted concepts are equal-length — then drops −1 sentinel rows
(``:259``) and appends the mean plus the run configuration to a results CSV
(``:265-273``). Reproduced here over the gathered flag tables, plus
throughput fields the reference lacks (records/sec, the BASELINE.json
metric).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np


@dataclasses.dataclass(frozen=True)
class DelayMetrics:
    num_detections: int
    mean_delay_rows: float  # mean(change_global % dist_between_changes)
    mean_delay_batches: float
    detections_per_partition: np.ndarray  # [P] i32
    delays: np.ndarray  # all individual delays (rows)


def delay_metrics(
    change_global: np.ndarray, dist_between_changes: int, per_batch: int
) -> DelayMetrics:
    """Compute delay stats from a ``[P, NB-1]`` change-position table."""
    change_global = np.asarray(change_global)
    detected = change_global >= 0
    positions = change_global[detected]
    delays = positions % dist_between_changes
    mean_rows = float(delays.mean()) if len(delays) else float("nan")
    return DelayMetrics(
        num_detections=int(detected.sum()),
        mean_delay_rows=mean_rows,
        mean_delay_batches=mean_rows / per_batch if len(delays) else float("nan"),
        detections_per_partition=detected.sum(axis=-1).astype(np.int32),
        delays=delays,
    )


# Reference C11 column schema (``DDM_Process.py:272``), kept verbatim so the
# notebook-style aggregation (C13-C15) ports unchanged; extended with
# throughput columns. "Spark Address" carries the backend string here.
RESULT_COLUMNS = [
    "Spark App",
    "Exp Start Time",
    "Spark Address",
    "Instances",
    "Data Multiplier",
    "Memory",
    "Cores",
    "Final Time",
    "Average Distance",
    "Dataset",
    "Per Batch",
    "Rows",
    "Rows Per Sec",
    "Detections",
    "Model",
    "Detector",
]


def result_row(
    cfg: Any, total_time: float, metrics: DelayMetrics, num_rows: int
) -> list:
    import os

    return [
        cfg.resolved_app_name(),
        cfg.time_string,
        cfg.url,
        cfg.partitions,
        float(cfg.mult_data),
        cfg.memory,
        cfg.cores,
        total_time,
        metrics.mean_delay_rows,
        os.path.basename(cfg.dataset),
        cfg.per_batch,
        num_rows,
        num_rows / total_time if total_time > 0 else float("nan"),
        metrics.num_detections,
        cfg.model,
        cfg.detector,
    ]
