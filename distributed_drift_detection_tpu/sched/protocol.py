"""The scheduler control protocol: newline-delimited JSON over TCP.

One request line → exactly one reply line, on a persistent connection
(a worker holds one for its whole life; ``heal --scheduler`` opens one
per submission). Pure stdlib, no jax — the protocol is the jax-free
seam between the scheduler daemon and whatever runs cells.

Requests (``op`` discriminates; unknown ops get an ``error`` reply,
never a dropped connection):

=============  ==========================================================
``hello``      worker enrollment: ``{worker, pid, hostname, ...}`` →
               ``welcome`` carrying the scheduler's identity and the
               knobs the worker must honor (``telemetry_dir``,
               ``lease_s``, ``heartbeat_s``, ``poll_s``)
``lease``      request one cell: → ``lease`` (a wire cell + the TTL),
               ``wait`` (cells exist but none grantable — poll again in
               ``poll_s``), or ``drain`` (sweep is whole; exit 0)
``heartbeat``  liveness + progress while a cell runs: → ``ack``, or
               ``revoked`` (the scheduler already re-leased this cell —
               the worker MUST abandon it without recording anything),
               or ``drain``
``done``       cell finished; ``ack`` carries ``accepted`` (False for a
               revoked/unknown lease — the completion is discarded)
``fail``       cell attempt failed (the supervisor's retries are
               exhausted); the scheduler requeues or marks the cell
               failed (``ack`` carries ``requeued``)
``submit``     enqueue extra cells (the ``heal --scheduler`` path):
               ``{cells: [wire cells]}`` → ``ack`` with ``queued`` /
               ``duplicates`` counts
``status``     one ``/statusz``-shaped JSON snapshot (CLI pokes, tests)
``bye``        graceful worker exit → ``ack``
=============  ==========================================================

A **wire cell** is the self-contained description a worker needs to run
one trial and nothing more: the digest payload
(``config.telemetry_config_payload`` — the registry identity), the
bookkeeping fields that stay out of the digest (``results_csv``,
``time_string``, ``data_policy``), the resolved ``app_name`` and the
``digest`` itself. The worker rebuilds the ``RunConfig``
(``config.config_from_payload``) and refuses to run a cell whose
rebuilt config digests differently — the byte-identity contract that
keeps a scheduler-run sweep and a serial ``grid`` run the same cells.
"""

from __future__ import annotations

import json
import socket

# Knob defaults, shared by the scheduler CLI and the worker agent (the
# welcome reply carries the scheduler's actual values; these are the
# one copy of the fallbacks).
DEFAULT_LEASE_S = 120.0  # heartbeat-refreshed lease TTL (stall budget)
DEFAULT_HEARTBEAT_S = 2.0  # worker heartbeat period while a cell runs
DEFAULT_POLL_S = 0.5  # worker re-poll period on a `wait` reply

MAX_LINE_BYTES = 4 << 20  # one request/reply line; a bigger one is abuse


class ProtocolError(ValueError):
    """A malformed message (not JSON, no ``op``, oversized line) or a
    connection that died mid-reply."""


def encode(msg: dict) -> bytes:
    """One wire line: compact JSON + newline."""
    return (json.dumps(msg, separators=(",", ":")) + "\n").encode()


def decode_line(line: "bytes | str") -> dict:
    """Parse one complete wire line into a message dict; raises
    :class:`ProtocolError` on anything that is not a JSON object with an
    ``op`` (untrusted input: the scheduler must reject, never crash)."""
    try:
        msg = json.loads(line)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ProtocolError(f"control line is not JSON ({e})") from None
    if not isinstance(msg, dict) or not isinstance(msg.get("op"), str):
        raise ProtocolError("control message must be a JSON object with 'op'")
    return msg


def error_reply(exc: "BaseException | str") -> dict:
    detail = exc if isinstance(exc, str) else f"{type(exc).__name__}: {exc}"
    return {"op": "error", "error": detail}


def cell_to_wire(cfg, digest: "str | None" = None) -> dict:
    """One trial config → its self-contained wire cell. jax-free
    (``config`` + ``telemetry.registry`` only)."""
    from ..config import telemetry_config_payload
    from ..telemetry.registry import config_digest

    payload = telemetry_config_payload(cfg)
    return {
        "app_name": cfg.resolved_app_name(),
        "digest": digest or config_digest(payload),
        "payload": payload,
        # Bookkeeping the digest deliberately excludes but a worker needs
        # to reproduce the serial grid run byte-for-byte:
        "results_csv": cfg.results_csv,
        "time_string": cfg.time_string,
        "data_policy": cfg.data_policy,
    }


def cell_from_wire(cell: dict, **overrides):
    """Rebuild the runnable ``RunConfig`` from a wire cell, verifying the
    round trip digests identically (a schema drift between scheduler and
    worker must fail loudly, not run the wrong experiment)."""
    from ..config import config_from_payload, telemetry_config_payload
    from ..telemetry.registry import config_digest

    cfg = config_from_payload(
        cell["payload"],
        results_csv=cell.get("results_csv", ""),
        time_string=cell.get("time_string", ""),
        data_policy=cell.get("data_policy", "strict"),
        **overrides,
    )
    rebuilt = config_digest(telemetry_config_payload(cfg))
    if rebuilt != cell["digest"]:
        raise ProtocolError(
            f"cell {cell.get('app_name')!r} rebuilds to digest {rebuilt}, "
            f"scheduler sent {cell['digest']} — config schema drift between "
            "scheduler and worker; refusing to run the wrong experiment"
        )
    return cfg


class ControlClient:
    """One persistent request/reply connection to a scheduler.

    Blocking, line-buffered, with a per-request timeout. Thread-safety is
    the caller's problem by design: the worker agent serializes its own
    traffic (the heartbeat thread and the main loop share one lock).
    """

    def __init__(self, host: str, port: int, *, timeout: float = 30.0):
        self.addr = (host, int(port))
        self.timeout = timeout
        self._sock: "socket.socket | None" = None
        self._buf = b""

    def connect(self) -> None:
        if self._sock is not None:
            return
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._buf = b""

    def request(self, msg: dict) -> dict:
        """Send one message, wait for its one reply. An ``error`` reply
        raises :class:`ProtocolError` (the scheduler rejected the
        request); transport failures raise ``OSError`` after closing the
        connection so the next request reconnects cleanly."""
        self.connect()
        assert self._sock is not None
        try:
            self._sock.sendall(encode(msg))
            while True:
                nl = self._buf.find(b"\n")
                if nl >= 0:
                    line, self._buf = self._buf[:nl], self._buf[nl + 1 :]
                    reply = decode_line(line)
                    if reply.get("op") == "error":
                        raise ProtocolError(reply.get("error", "rejected"))
                    return reply
                if len(self._buf) > MAX_LINE_BYTES:
                    raise ProtocolError("oversized control reply")
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise OSError("scheduler closed the control connection")
                self._buf += chunk
        except OSError:
            self.close()
            raise

    def __enter__(self) -> "ControlClient":
        self.connect()
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def parse_addr(addr: str) -> "tuple[str, int]":
    """``host:port`` (or bare ``:port`` / ``port`` for loopback) → tuple;
    the one parser behind ``--scheduler`` / ``--connect`` flags."""
    host, _, port = addr.rpartition(":")
    host = host or "127.0.0.1"
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(
            f"scheduler address {addr!r} must be HOST:PORT"
        ) from None
