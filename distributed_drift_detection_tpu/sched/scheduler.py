"""The scheduler daemon: a lease-based work queue over the run registry.

    python -m distributed_drift_detection_tpu sched [SPEC] \\
        --telemetry-dir DIR [--port P] [--ops-port P] [--workers N] \\
        [--lease-s S] [--max-attempts N] [--compact-at N] [--json]

Inverts ``heal`` from pull to push. At startup the sweep-spec JSON is
expanded into cells through the exact machinery heal diffs with
(``heal.load_spec``/``spec_configs`` → ``telemetry_config_payload`` →
``config_digest``), cells the registry already shows ``completed`` are
pre-completed (resume semantics — recorded work is never re-run), and
the rest become the queue. Worker agents (:mod:`.worker`) connect over
the jax-free control protocol (:mod:`.protocol`) and pull leases; the
daemon:

* grants **heartbeat-refreshed leases** (TTL ``--lease-s``): a worker
  silent longer than the TTL is dead or wedged either way — the
  ``watch --stall-after`` contract (``telemetry.watch.staleness_s``)
  applied to control-plane heartbeats — and its cells re-lease;
* revokes **immediately on disconnect** (a killed worker's socket EOF),
  so crash recovery costs one select tick, not a stall budget;
* accepts each cell's completion **at most once** (the live lease
  holder's report; late/revoked completions are discarded) and audits
  the registry at exit (:func:`..sched.leases.audit_exactly_once`);
* journals every placement decision to ``sched.journal.jsonl`` (the
  PR-14 router-journal pattern) and brackets the whole sweep with a
  ``kind="sched"`` registry record;
* serves its own ops plane (``--ops-port``): ``/metrics`` ``sched_*``
  counters/gauges, ``/healthz`` (503 once any cell fails terminally),
  ``/statusz`` (queue depths, leases, per-worker rates — rendered by
  the ``top`` dashboard's scheduler row);
* optionally **auto-compacts** the registry (``--compact-at N``): a
  long-lived scheduler appends a record per attempt, and
  ``telemetry.registry.compact_index`` keeps ``index.jsonl`` bounded
  without breaking ``newest_run_log``/heal digest matching.

``--workers N`` spawns N local worker agents pointed at the daemon (the
zero-to-sweep path; production fleets start ``sched-worker`` wherever
capacity lives). The scheduler exits 0 only when every cell completed
and the registry audit is clean — the scriptable wholeness contract,
same as ``heal``.

Everything here is jax-free (stdlib + the jax-free telemetry/heal
modules): the scheduler runs on a head node, in CI, anywhere
``index.jsonl`` lands. Fault site ``sched.lease`` fires per grant
(``DDD_FAULTS="sched.lease:at=2"`` makes the 2nd grant fail: the reply
is an ``error``, the cell stays queued, the daemon survives — armed by
the CI job to prove grant failures are not crashes).
"""

from __future__ import annotations

import argparse
import json
import os
import selectors
import socket
import subprocess
import sys
import threading
import time

from ..resilience import faults
from ..telemetry import registry as run_registry
from ..telemetry.watch import staleness_s
from . import protocol
from .leases import CellQueue, audit_exactly_once

JOURNAL_NAME = "sched.journal.jsonl"


class _Conn:
    __slots__ = ("sock", "buf", "worker")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""
        self.worker: "str | None" = None  # set by hello


class _WorkerState:
    __slots__ = (
        "worker", "pid", "hostname", "joined_mono", "last_mono",
        "cells_done", "cells_failed", "rows_done", "alive",
    )

    def __init__(self, worker: str, now: float, pid=None, hostname=None):
        self.worker = worker
        self.pid = pid
        self.hostname = hostname
        self.joined_mono = now
        self.last_mono = now
        self.cells_done = 0
        self.cells_failed = 0
        self.rows_done = 0
        self.alive = True


class Scheduler:
    """The daemon object (embeddable: tests and ``bench --sched`` drive
    it in-process; the CLI wraps it). ``start()`` binds, brackets the
    registry, and spins the select loop on a daemon thread; ``stop()``
    finalizes the bracket with the audit verdict."""

    def __init__(
        self,
        telemetry_dir: str,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        lease_s: float = protocol.DEFAULT_LEASE_S,
        heartbeat_s: float = protocol.DEFAULT_HEARTBEAT_S,
        poll_s: float = protocol.DEFAULT_POLL_S,
        max_attempts: int = 3,
        ops_port: "int | None" = None,
        compact_at: int = 0,
        clock=time.monotonic,
    ):
        self.telemetry_dir = telemetry_dir
        self.queue = CellQueue(lease_s=lease_s, max_attempts=max_attempts)
        self.heartbeat_s = float(heartbeat_s)
        self.poll_s = float(poll_s)
        self.compact_at = int(compact_at)
        self._clock = clock
        self.sched_id = (
            f"sched-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
        )
        self.workers: "dict[str, _WorkerState]" = {}
        # Accounting the ops plane renders (GIL-atomic ints, mutated
        # under the lock anyway).
        self.leases_granted = 0
        self.leases_revoked = 0
        self.lease_errors = 0
        self.evictions = 0
        self.submissions = 0
        self.pre_completed = 0
        self._lock = threading.Lock()
        self._whole_evt = threading.Event()
        self._stop_evt = threading.Event()
        self._t0_mono: "float | None" = None
        # The journal opens with the object, not with start(): the CLI
        # enqueues its spec before starting the loop, and that
        # spec_added record is exactly the forensics the journal exists
        # to keep.
        os.makedirs(telemetry_dir, exist_ok=True)
        self._journal_fh = open(
            os.path.join(telemetry_dir, JOURNAL_NAME), "a"
        )
        self._host = host
        self._ops = None
        self._ops_port_req = ops_port
        self._metrics = None
        self._thread: "threading.Thread | None" = None
        self._sel = selectors.DefaultSelector()
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(64)
        self._listen.setblocking(False)
        self._conns: "dict[socket.socket, _Conn]" = {}

    # -- intake ----------------------------------------------------------

    def add_spec(self, spec: dict) -> dict:
        """Expand a (loaded) sweep spec into cells and enqueue whatever
        the registry does not already show completed. Returns the heal
        plan shape ``{cells_total, completed, queued}``."""
        from ..resilience.heal import completed_digests, spec_configs

        wires = [protocol.cell_to_wire(cfg) for cfg in spec_configs(spec)]
        done = completed_digests(self.telemetry_dir)
        pre: "set[str]" = set()
        for wire in wires:
            if done[wire["digest"]] > 0:
                done[wire["digest"]] -= 1
                pre.add(wire["app_name"])
        with self._lock:
            queued, dups = self.queue.add(wires)
            n_pre = self.queue.mark_completed(pre)
            self.pre_completed += n_pre
            self._check_whole()
        self._journal(
            "spec_added", cells=queued, duplicates=dups, pre_completed=n_pre
        )
        return {
            "cells_total": len(wires),
            "completed": n_pre,
            "queued": queued - n_pre,
        }

    def submit(self, wires: "list[dict]") -> "tuple[int, int]":
        """Enqueue extra cells (the ``heal --scheduler`` path)."""
        with self._lock:
            queued, dups = self.queue.add(wires)
            self.submissions += 1
            if queued:
                self._whole_evt.clear()
        self._journal("submit", cells=queued, duplicates=dups)
        return queued, dups

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> int:
        return self._listen.getsockname()[1]

    @property
    def ops_port(self) -> "int | None":
        return self._ops.port if self._ops is not None else None

    def start(self) -> dict:
        """Bind the ops plane, bracket the registry, start the loop;
        returns the startup banner."""
        self._t0_mono = self._clock()
        counts = self.queue.counts()
        run_registry.record(
            self.telemetry_dir, self.sched_id, "running", kind="sched",
            cells_total=counts["total"], cells_to_run=counts["queued"],
        )
        self._journal(
            "scheduler_started", port=self.port, pid=os.getpid(), **counts
        )
        if self._ops_port_req is not None:
            from ..telemetry.metrics import MetricsRegistry
            from ..telemetry.ops import OpsServer

            self._metrics = MetricsRegistry()
            self._c_granted = self._metrics.counter(
                "sched_leases_granted_total",
                help="Cell leases granted to workers",
            )
            self._c_revoked = self._metrics.counter(
                "sched_leases_revoked_total",
                help="Leases revoked (worker dead or stalled), by reason",
            )
            self._c_completed = self._metrics.counter(
                "sched_cells_completed_total",
                help="Cells whose completion was accepted exactly once",
            )
            self._c_failed = self._metrics.counter(
                "sched_cells_failed_total",
                help="Cells terminally failed (lease-attempt budget spent)",
            )
            self._c_evicted = self._metrics.counter(
                "sched_workers_evicted_total",
                help="Workers evicted (disconnect or stall contract)",
            )
            self._g_queued = self._metrics.gauge(
                "sched_cells_queued", help="Cells waiting for a lease"
            )
            self._g_leased = self._metrics.gauge(
                "sched_cells_leased", help="Cells currently leased out"
            )
            self._g_workers = self._metrics.gauge(
                "sched_workers_connected", help="Live worker agents"
            )
            self._g_rate = self._metrics.gauge(
                "sched_cells_per_sec",
                help="Accepted completions per second of scheduler uptime",
            )
            self._ops = OpsServer(
                self._host, self._ops_port_req,
                metrics_fn=self._metrics_text,
                health_fn=self._health,
                status_fn=self.status,
                fleetz_fn=self.fleetz,
            )
            self._ops.start()
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        self._thread = threading.Thread(
            target=self._run, name="sched-loop", daemon=True
        )
        self._thread.start()
        return {
            "scheduler": self.sched_id,
            "host": self._listen.getsockname()[0],
            "port": self.port,
            "ops_port": self.ops_port,
            "telemetry_dir": self.telemetry_dir,
            **counts,
        }

    def stop(self) -> dict:
        """Stop the loop and finalize: registry bracket status from the
        queue + the exactly-once audit; returns the summary."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._ops is not None:
            self._ops.stop()
        with self._lock:
            counts = self.queue.counts()
            expected = self.queue.expected_digests()
        audit = audit_exactly_once(self.telemetry_dir, expected)
        whole = (
            counts["total"] > 0
            and counts["completed"] == counts["total"]
            and audit["ok"]
        )
        status = "completed" if whole else "failed"
        summary = {
            "scheduler": self.sched_id,
            "whole": whole,
            "audit": audit,
            "evictions": self.evictions,
            "leases_granted": self.leases_granted,
            "leases_revoked": self.leases_revoked,
            **counts,
        }
        self._journal("scheduler_stopped", **summary)
        try:
            run_registry.record(
                self.telemetry_dir, self.sched_id, status, kind="sched",
                cells_completed=counts["completed"],
                cells_failed=counts["failed"],
                evictions=self.evictions,
                audit_ok=audit["ok"],
            )
        except Exception:
            pass  # best-effort: the summary must surface either way
        if self._journal_fh is not None:
            self._journal_fh.close()
            self._journal_fh = None
        self._listen.close()
        self._sel.close()
        return summary

    def wait_whole(self, timeout: "float | None" = None) -> bool:
        """Block until every cell is terminal (or ``timeout``)."""
        return self._whole_evt.wait(timeout)

    def spawn_workers(
        self, n: int, *, start_index: int = 0,
        extra_args: "list[str] | None" = None, env=None,
    ) -> "list[subprocess.Popen]":
        """Launch ``n`` local worker agents pointed at this daemon — the
        ``--workers N`` zero-to-sweep path. Each gets ``--index i`` so
        Bernoulli-armed ``sched.worker`` faults de-correlate across the
        fleet (same ``DDD_FAULTS``, different hit sequences); respawned
        replacements get fresh indices for the same reason."""
        procs = []
        for i in range(start_index, start_index + n):
            cmd = [
                sys.executable, "-m", "distributed_drift_detection_tpu",
                "sched-worker",
                "--connect", f"127.0.0.1:{self.port}",
                "--index", str(i),
                *(extra_args or []),
            ]
            procs.append(
                subprocess.Popen(cmd, env=env)
            )
        return procs

    # -- ops plane -------------------------------------------------------

    def _metrics_text(self) -> "str | None":
        from ..telemetry.pipeline import fleet_metrics_lines

        with self._lock:
            counts = self.queue.counts()
            # Under the lock: the select-loop thread mutates self.workers
            # (a hello inserting a respawned replacement) concurrently
            # with this ops-thread scrape.
            alive = sum(1 for w in self.workers.values() if w.alive)
        self._g_queued.set(counts["queued"])
        self._g_leased.set(counts["leased"])
        self._g_workers.set(alive)
        self._g_rate.set(self.cells_per_sec() or 0.0)
        # the fleet_* series ride the scheduler's scrape too, so one
        # Prometheus target covers queue state AND per-worker load
        fleet = "\n".join(fleet_metrics_lines(self.fleetz())) + "\n"
        return self._metrics.to_prometheus_text() + fleet

    def fleetz(self) -> dict:
        """The merged fleet view (``/fleetz``), shaped like the tenant
        router's. Workers expose no ops endpoints — the scheduler IS
        their state plane — so each snapshot comes from the lease
        registry's worker accounting: cumulative rows and the average
        rows/s since the worker joined."""
        from ..telemetry.pipeline import aggregate_fleet

        now = self._clock()
        with self._lock:
            snaps = [
                {
                    "name": w.worker,
                    "alive": w.alive,
                    "rows": w.rows_done,
                    "rows_per_sec": round(
                        w.rows_done / max(now - w.joined_mono, 1e-9), 3
                    ),
                    # workers have no incident plane (no ops endpoint to
                    # capture from); the fleet INC column reads 0 here
                    "incidents": 0,
                }
                for w in self.workers.values()
            ]
        return aggregate_fleet(snaps)

    def _health(self) -> "tuple[int, dict]":
        with self._lock:
            counts = self.queue.counts()
        reasons = []
        if counts["failed"]:
            reasons.append(f"{counts['failed']} cell(s) terminally failed")
        return (503 if reasons else 200), {
            "healthy": not reasons,
            "reasons": reasons,
            **counts,
        }

    def cells_per_sec(self) -> "float | None":
        """Accepted completions per second of uptime (pre-completed
        resume cells excluded — they cost no work this run)."""
        if self._t0_mono is None:
            return None
        up = self._clock() - self._t0_mono
        with self._lock:
            done = self.queue.counts()["completed"] - self.pre_completed
        return round(done / up, 4) if up > 0 and done >= 0 else None

    def status(self) -> dict:
        """The ``/statusz`` snapshot (also the ``status`` protocol
        reply) — the fields the ``top`` dashboard's scheduler row
        renders."""
        now = self._clock()
        with self._lock:
            counts = self.queue.counts()
            leases = [
                {
                    "lease_id": lease.lease_id,
                    "cell": lease.cell.app_name,
                    "worker": lease.worker,
                    "expires_in_s": round(lease.expires_mono - now, 2),
                }
                for lease in self.queue.leases.values()
            ]
            workers = [
                {
                    "worker": w.worker,
                    "alive": w.alive,
                    "pid": w.pid,
                    "hostname": w.hostname,
                    "cells_done": w.cells_done,
                    "cells_failed": w.cells_failed,
                    "rows_done": w.rows_done,
                    "age_s": round(staleness_s(w.last_mono, now=now), 2),
                }
                for w in self.workers.values()
            ]
        return {
            "sched": True,
            "run_id": self.sched_id,
            "pid": os.getpid(),
            "uptime_s": (
                round(now - self._t0_mono, 3)
                if self._t0_mono is not None
                else None
            ),
            "cells": counts,
            "workers": workers,
            "leases": leases,
            "leases_granted": self.leases_granted,
            "leases_revoked": self.leases_revoked,
            "lease_errors": self.lease_errors,
            "evictions": self.evictions,
            "submissions": self.submissions,
            "cells_per_sec": self.cells_per_sec(),
            "whole": self._whole_evt.is_set(),
        }

    # -- journal ---------------------------------------------------------

    def _journal(self, event: str, **fields) -> None:
        if self._journal_fh is None:
            return
        rec = {"ts": time.time(), "event": event, **fields}
        try:
            self._journal_fh.write(json.dumps(rec) + "\n")
            self._journal_fh.flush()
        except (OSError, ValueError):
            pass  # the journal is evidence, never a failure mode

    # -- the select loop -------------------------------------------------

    def _run(self) -> None:
        tick = min(self.queue.lease_s / 4, 0.25)
        while not self._stop_evt.is_set():
            for key, _ in self._sel.select(timeout=tick):
                if key.data is None:
                    self._accept()
                else:
                    self._service(key.data)
            self._sweep_stalls()
        for conn in list(self._conns.values()):
            self._close(conn)
        try:
            self._sel.unregister(self._listen)
        except (KeyError, ValueError):
            pass

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listen.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _close(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _eof(self, conn: _Conn) -> None:
        """Worker connection died: revoke everything it held, NOW — a
        killed worker must not cost a stall budget."""
        worker = conn.worker
        self._close(conn)
        if worker is None:
            return
        with self._lock:
            held = self.queue.revoke_worker(worker)
            self.leases_revoked += len(held)
            state = self.workers.get(worker)
            if state is not None:
                state.alive = False
            if held:
                self.evictions += 1
            self._check_whole()
        if self._metrics is not None and held:
            self._c_revoked.inc(len(held), reason="disconnect")
            self._c_evicted.inc()
        for lease in held:
            self._journal(
                "lease_revoked", lease=lease.lease_id, worker=worker,
                cell=lease.cell.app_name, reason="disconnect",
                requeued=lease.cell.state == "queued",
            )
        if held:
            self._journal("worker_evicted", worker=worker, reason="disconnect")

    def _service(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._eof(conn)
            return
        if not data:
            self._eof(conn)
            return
        conn.buf += data
        while True:
            nl = conn.buf.find(b"\n")
            if nl < 0:
                if len(conn.buf) > protocol.MAX_LINE_BYTES:
                    self._reply(conn, protocol.error_reply("oversized line"))
                    self._eof(conn)
                return
            line, conn.buf = conn.buf[:nl], conn.buf[nl + 1 :]
            if not line.strip():
                continue
            try:
                msg = protocol.decode_line(line)
            except protocol.ProtocolError as e:
                self._reply(conn, protocol.error_reply(e))
                continue
            try:
                reply = self._handle(conn, msg)
            except Exception as e:
                # A handler failure (including an armed `sched.lease`
                # fault) rejects THIS request; the daemon survives.
                self.lease_errors += 1
                reply = protocol.error_reply(e)
            self._reply(conn, reply)

    def _reply(self, conn: _Conn, msg: dict) -> None:
        try:
            conn.sock.sendall(protocol.encode(msg))
        except (BlockingIOError, InterruptedError, OSError):
            self._eof(conn)

    def _sweep_stalls(self) -> None:
        """The stall contract: revoke leases whose heartbeat-refreshed
        TTL expired (``staleness_s`` past the lease budget — the `watch
        --stall-after` semantics on the control plane)."""
        now = self._clock()
        with self._lock:
            expired = self.queue.revoke_expired(now)
            self.leases_revoked += len(expired)
            stalled_workers = {lease.worker for lease in expired}
            for worker in stalled_workers:
                state = self.workers.get(worker)
                if state is not None:
                    state.alive = False
                self.evictions += 1
            if expired:
                self._check_whole()
        if self._metrics is not None and expired:
            self._c_revoked.inc(len(expired), reason="stall")
            self._c_evicted.inc(len(stalled_workers))
        for lease in expired:
            self._journal(
                "lease_revoked", lease=lease.lease_id, worker=lease.worker,
                cell=lease.cell.app_name, reason="stall",
                requeued=lease.cell.state == "queued",
            )
        for worker in sorted(stalled_workers) if expired else ():
            self._journal("worker_evicted", worker=worker, reason="stall")

    def _check_whole(self) -> None:
        # Caller holds the lock.
        if self.queue.whole():
            self._whole_evt.set()

    # -- request handlers ------------------------------------------------

    def _handle(self, conn: _Conn, msg: dict) -> dict:
        op = msg["op"]
        now = self._clock()
        worker = msg.get("worker")
        if worker is not None:
            with self._lock:
                state = self.workers.get(worker)
                if state is not None:
                    state.last_mono = now
                    state.alive = True
        if op == "hello":
            if not worker:
                return protocol.error_reply("hello needs a worker id")
            conn.worker = worker
            with self._lock:
                self.workers[worker] = _WorkerState(
                    worker, now,
                    pid=msg.get("pid"), hostname=msg.get("hostname"),
                )
            self._journal(
                "worker_joined", worker=worker, pid=msg.get("pid"),
                hostname=msg.get("hostname"),
            )
            return {
                "op": "welcome",
                "scheduler": self.sched_id,
                "telemetry_dir": self.telemetry_dir,
                "lease_s": self.queue.lease_s,
                "heartbeat_s": self.heartbeat_s,
                "poll_s": self.poll_s,
            }
        if op == "lease":
            if not worker:
                return protocol.error_reply("lease needs a worker id")
            conn.worker = conn.worker or worker
            # Fault site: a grant that raises rejects THIS request (the
            # worker retries after poll_s); the cell stays queued.
            faults.fire("sched.lease", worker=worker)
            with self._lock:
                if self._whole_evt.is_set():
                    return {"op": "drain"}
                lease = self.queue.grant(worker, now)
                if lease is not None:
                    self.leases_granted += 1
            if lease is None:
                return {"op": "wait", "poll_s": self.poll_s}
            if self._metrics is not None:
                self._c_granted.inc()
            self._journal(
                "lease_granted", lease=lease.lease_id, worker=worker,
                cell=lease.cell.app_name, digest=lease.cell.digest,
                attempt=lease.cell.attempts,
            )
            return {
                "op": "lease",
                "lease_id": lease.lease_id,
                "cell": lease.cell.wire,
                "lease_s": self.queue.lease_s,
                "heartbeat_s": self.heartbeat_s,
                "attempt": lease.cell.attempts,
            }
        if op == "heartbeat":
            lease_id = msg.get("lease_id")
            rows = msg.get("rows_done")
            if worker and rows is not None:
                with self._lock:
                    state = self.workers.get(worker)
                    if state is not None:
                        state.rows_done = int(rows)
            if lease_id is None:
                return {"op": "ack"}
            with self._lock:
                live = self.queue.heartbeat(lease_id, now)
            if not live:
                return {"op": "revoked", "lease_id": lease_id}
            return {"op": "ack"}
        if op == "done":
            lease_id = msg.get("lease_id", "")
            with self._lock:
                cell = self.queue.complete(lease_id, worker or "")
                if cell is not None:
                    state = self.workers.get(worker or "")
                    if state is not None:
                        state.cells_done += 1
                    self._check_whole()
            if cell is None:
                self._journal(
                    "completion_discarded", lease=lease_id, worker=worker,
                )
                return {"op": "ack", "accepted": False}
            if self._metrics is not None:
                self._c_completed.inc()
            self._journal(
                "cell_completed", lease=lease_id, worker=worker,
                cell=cell.app_name, digest=cell.digest,
                result=msg.get("result"),
            )
            self._maybe_compact()
            return {"op": "ack", "accepted": True}
        if op == "fail":
            lease_id = msg.get("lease_id", "")
            with self._lock:
                out = self.queue.fail(lease_id, worker or "")
                if out is not None:
                    state = self.workers.get(worker or "")
                    if state is not None:
                        state.cells_failed += 1
                    self._check_whole()
            if out is None:
                return {"op": "ack", "accepted": False}
            cell, requeued = out
            if self._metrics is not None and not requeued:
                self._c_failed.inc()
            self._journal(
                "cell_failed", lease=lease_id, worker=worker,
                cell=cell.app_name, error=str(msg.get("error", ""))[:300],
                requeued=requeued,
            )
            return {"op": "ack", "accepted": True, "requeued": requeued}
        if op == "submit":
            cells = msg.get("cells")
            if not isinstance(cells, list) or not all(
                isinstance(c, dict)
                and c.get("app_name") and c.get("digest")
                and isinstance(c.get("payload"), dict)
                for c in cells
            ):
                return protocol.error_reply(
                    "submit needs cells: [wire cells] "
                    "(app_name/digest/payload)"
                )
            queued, dups = self.submit(cells)
            return {"op": "ack", "queued": queued, "duplicates": dups}
        if op == "status":
            return {"op": "status", **self.status()}
        if op == "bye":
            if worker:
                with self._lock:
                    state = self.workers.get(worker)
                    if state is not None:
                        state.alive = False
                self._journal("worker_left", worker=worker)
            return {"op": "ack"}
        return protocol.error_reply(f"unknown op {op!r}")

    def _maybe_compact(self) -> None:
        if self.compact_at <= 0:
            return
        try:
            compacted = run_registry.maybe_compact(
                self.telemetry_dir, max_records=self.compact_at
            )
        except (OSError, ValueError):
            return  # compaction is an optimization, never a failure mode
        if compacted:
            self._journal("registry_compacted", **compacted)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu sched",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "spec", nargs="?", default=None,
        help="sweep-spec JSON (the grid as data; omit to start empty and "
        "wait for `heal --scheduler` submissions)",
    )
    ap.add_argument(
        "--telemetry-dir", required=True, metavar="DIR",
        help="telemetry directory whose registry is the work ledger",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--port", type=int, default=0,
        help="control-protocol port (0 = OS-assigned, see banner)",
    )
    ap.add_argument(
        "--ops-port", type=int, default=None, metavar="P",
        help="ops plane (/metrics /healthz /statusz; 0 = OS-assigned, "
        "omit = no ops server)",
    )
    ap.add_argument(
        "--workers", type=int, default=0, metavar="N",
        help="spawn N local worker agents pointed at this daemon",
    )
    ap.add_argument(
        "--lease-s", type=float, default=protocol.DEFAULT_LEASE_S,
        help="heartbeat-refreshed lease TTL = the worker stall budget "
        f"(default {protocol.DEFAULT_LEASE_S:g})",
    )
    ap.add_argument(
        "--heartbeat-s", type=float, default=protocol.DEFAULT_HEARTBEAT_S,
        help="heartbeat period workers are told to honor "
        f"(default {protocol.DEFAULT_HEARTBEAT_S:g})",
    )
    ap.add_argument(
        "--max-attempts", type=int, default=3,
        help="lease attempts per cell before it is terminally failed "
        "(default 3)",
    )
    ap.add_argument(
        "--compact-at", type=int, default=0, metavar="N",
        help="auto-compact the registry when index.jsonl exceeds N "
        "records (0 = never; telemetry.registry.compact_index)",
    )
    ap.add_argument(
        "--retries", type=int, default=2,
        help="supervised in-worker retries per cell attempt (default 2)",
    )
    ap.add_argument(
        "--compile-cache-dir", default="", metavar="DIR",
        help="forwarded to spawned workers: one shared persistent XLA "
        "compilation cache for the fleet (utils.compile_cache)",
    )
    ap.add_argument(
        "--timeout", type=float, default=0.0, metavar="S",
        help="give up if the sweep is not whole after S seconds "
        "(0 = wait forever)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="print the final summary as one JSON line",
    )
    args = ap.parse_args(argv)

    armed = faults.arm_from_env()
    sched = Scheduler(
        args.telemetry_dir,
        host=args.host,
        port=args.port,
        lease_s=args.lease_s,
        heartbeat_s=args.heartbeat_s,
        max_attempts=args.max_attempts,
        ops_port=args.ops_port,
        compact_at=args.compact_at,
    )
    if args.spec:
        from ..resilience.heal import load_spec

        plan = sched.add_spec(load_spec(args.spec))
        print(
            f"sched: {plan['cells_total']} cells, {plan['completed']} "
            f"already completed, {plan['queued']} to run",
            file=sys.stderr,
        )
    banner = sched.start()
    print(json.dumps(banner), flush=True)
    if armed:
        print(f"sched: fault site(s) armed: {armed}", file=sys.stderr)
    worker_args = ["--retries", str(args.retries)]
    if args.compile_cache_dir:
        worker_args += ["--compile-cache-dir", args.compile_cache_dir]
    procs = []
    if args.workers:
        procs = sched.spawn_workers(args.workers, extra_args=worker_args)
    next_index = args.workers
    # Respawn budget: an **elastic** fleet replaces crashed workers (the
    # whole point of injected preemption is that the sweep still
    # converges), but a deterministic crash-at-hello loop must not fork
    # forever — past the budget the remaining cells exhaust their lease
    # attempts and fail terminally, which is the loud outcome.
    respawns_left = 10 * max(args.workers, 1)
    deadline = (
        time.monotonic() + args.timeout if args.timeout else None
    )
    try:
        timed_out = False
        while not sched.wait_whole(timeout=0.5):
            if deadline is not None and time.monotonic() > deadline:
                print(
                    f"sched: sweep not whole after {args.timeout:g}s",
                    file=sys.stderr,
                )
                timed_out = True
                break
            for i, proc in enumerate(procs):
                if proc.poll() is None or proc.returncode == 0:
                    continue  # alive, or drained cleanly
                if respawns_left <= 0:
                    continue
                respawns_left -= 1
                print(
                    f"sched: worker exited rc={proc.returncode} — "
                    f"respawning as index {next_index}",
                    file=sys.stderr,
                )
                procs[i] = sched.spawn_workers(
                    1, start_index=next_index, extra_args=worker_args
                )[0]
                next_index += 1
        # Give spawned workers their drain replies, then a bounded join —
        # but only when the sweep actually closed: after a timeout no
        # drain will ever arrive, so waiting 30s per worker just delays
        # the exit (the finally kills them immediately instead).
        if not timed_out:
            for proc in procs:
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        summary = sched.stop()
    if args.json:
        print(json.dumps(summary), flush=True)
    else:
        print(
            f"sched: {summary['completed']}/{summary['total']} completed, "
            f"{summary['failed']} failed, {summary['evictions']} "
            f"eviction(s); audit "
            + ("clean" if summary["audit"]["ok"] else
               f"VIOLATED {summary['audit']}")
        )
    raise SystemExit(0 if summary["whole"] else 1)


if __name__ == "__main__":
    main(sys.argv[1:])
