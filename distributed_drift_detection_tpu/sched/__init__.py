"""Elastic sweep scheduler: the registry-driven fleet controller.

The reference's experimental campaign is a 14-line bash loop
(``run_experiments.sh``) walking a (multiplier × instances × memory ×
cores) grid *serially*, with crash recovery done by hand from the
notebook. PRs 3–5 built every primitive a real controller needs — the
append-only run registry, the ``watch`` stall contract, ``heal``'s
completed-cell diff, supervised retries, deterministic fault injection
and atomic checkpoints — but a sweep was still one process walking a
grid. This package inverts heal from pull to push:

* :mod:`.scheduler` — the **scheduler daemon**: expands a sweep-spec
  JSON into cells (the exact ``grid_configs`` expansion heal diffs),
  treats the telemetry registry as the durable work ledger, grants
  time-bounded **leases** to worker processes over a jax-free TCP
  control protocol, revokes the leases of dead or wedged workers (the
  ``watch`` stall contract applied to their heartbeats) and re-leases
  their cells until the registry shows every cell completed exactly
  once. Own ops plane (``/statusz``, ``/metrics`` ``sched_*``) and a
  placement journal (``sched.journal.jsonl``).
* :mod:`.worker` — the **worker agent** (``python -m … sched-worker``):
  leases cells, runs each under ``resilience.supervisor`` with the
  standard telemetry bracketing (so ``report``/``watch``/``correlate``/
  ``top`` work unchanged), heartbeats while a cell runs, and reports
  done/fail.
* :mod:`.protocol` — the newline-JSON wire contract both sides speak
  (and ``heal --scheduler`` submits plans through).
* :mod:`.leases` — the pure lease/queue state machine + the
  exactly-once registry audit.

Everything except the worker's cell execution is jax-free: the
scheduler runs wherever ``index.jsonl`` lands, exactly like
``heal``'s plan mode. See ``docs/SCHEDULER.md``.
"""

from .leases import Cell, CellQueue, audit_exactly_once  # noqa: F401
from .protocol import ControlClient, cell_to_wire, cell_from_wire  # noqa: F401
