"""Lease/queue state machine + the exactly-once registry audit.

Pure data structures, no sockets, no jax: the scheduler daemon drives
this under its one lock, and the unit tests drive it directly with a
fake clock. A **cell** is one expected trial (heal's plan unit — app
name, config digest, wire config); its lifecycle is

    queued ──grant──▶ leased ──complete──▶ completed
      ▲                 │ │
      │◀──── revoke ────┘ └──fail──▶ queued (attempts left) | failed

**Exactly-once contract** (docs/SCHEDULER.md): execution is
at-least-once — a revoked worker may have died anywhere in its cell —
but *recorded completion* is at-most-once per expected trial: a lease is
granted to one worker, a ``complete``/``fail`` is accepted only from the
worker currently holding the live lease, and a revoked lease's late
completion is discarded (``accepted=False``). The registry is the
ground truth the final :func:`audit_exactly_once` checks: every expected
digest completed exactly as many times as the sweep expects it, no more.
The one hole — a *wedged* (not dead) worker that unwedges after its
lease was revoked and still writes its registry record — is the same
documented caveat as ``resilience.supervisor``'s abandoned-attempt
timeout, and the worker narrows it by aborting a cell the moment a
heartbeat reply says ``revoked``.
"""

from __future__ import annotations

from collections import Counter

# Terminal cell states; everything else is in flight.
QUEUED, LEASED, COMPLETED, FAILED = "queued", "leased", "completed", "failed"


class Cell:
    """One expected trial: identity + wire config + lifecycle state."""

    __slots__ = (
        "app_name", "digest", "wire", "geometry", "state", "attempts",
        "worker",
    )

    def __init__(self, wire: dict):
        self.app_name = str(wire["app_name"])
        self.digest = str(wire["digest"])
        self.wire = wire
        # Static geometry = the payload minus the per-trial seed: trials
        # of one sweep config share compiled programs (runner cache,
        # persistent XLA cache), so the grant path prefers handing a
        # worker geometries it has already paid compilation for.
        payload = wire.get("payload") or {}
        self.geometry = tuple(
            sorted((k, str(v)) for k, v in payload.items() if k != "seed")
        )
        self.state = QUEUED
        self.attempts = 0  # leases granted (≠ the supervisor's retries)
        self.worker: "str | None" = None  # current/last holder

    def snapshot(self) -> dict:
        return {
            "app_name": self.app_name,
            "digest": self.digest,
            "state": self.state,
            "attempts": self.attempts,
            "worker": self.worker,
        }


class Lease:
    """One live grant: (cell, worker, monotonic expiry). The expiry is
    heartbeat-refreshed — the lease TTL *is* the stall budget, the
    ``watch --stall-after`` contract applied to the worker's beats."""

    __slots__ = ("lease_id", "cell", "worker", "expires_mono")

    def __init__(self, lease_id: str, cell: Cell, worker: str, expires: float):
        self.lease_id = lease_id
        self.cell = cell
        self.worker = worker
        self.expires_mono = expires


class CellQueue:
    """The scheduler's work ledger. NOT thread-safe — the daemon owns
    one lock around every call (and the tests need none)."""

    def __init__(self, *, lease_s: float, max_attempts: int = 3):
        if lease_s <= 0:
            raise ValueError(f"lease_s must be > 0, got {lease_s}")
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.lease_s = float(lease_s)
        self.max_attempts = int(max_attempts)
        self.cells: "dict[str, Cell]" = {}  # app_name → cell (sweep order)
        self.leases: "dict[str, Lease]" = {}
        self._lease_seq = 0
        # Geometry affinity (see Cell.geometry): worker → geometries it
        # has held leases for. Never evicted — a dead worker's entry is
        # just never matched again.
        self._seen: "dict[str, set]" = {}

    # -- intake --------------------------------------------------------------

    def add(self, wires: "list[dict]") -> "tuple[int, int]":
        """Enqueue wire cells; returns ``(queued, duplicates)``. A cell
        already known (by app name — the per-trial-unique key) is a
        duplicate and is NOT re-queued: submissions are idempotent, the
        same contract as heal's generated script."""
        queued = dups = 0
        for wire in wires:
            cell = Cell(wire)
            if cell.app_name in self.cells:
                dups += 1
                continue
            self.cells[cell.app_name] = cell
            queued += 1
        return queued, dups

    def mark_completed(self, app_names: "set[str]") -> int:
        """Pre-complete cells the registry already shows done (resume
        semantics — the scheduler never re-runs recorded work)."""
        n = 0
        for name in app_names:
            cell = self.cells.get(name)
            if cell is not None and cell.state == QUEUED:
                cell.state = COMPLETED
                n += 1
        return n

    # -- lease lifecycle -----------------------------------------------------

    def grant(self, worker: str, now: float) -> "Lease | None":
        """Lease the next queued cell to ``worker``; ``None`` when
        nothing is grantable right now.

        **Geometry-affinity placement**: among queued cells, one whose
        static geometry this worker has already held wins (its compiled
        programs are warm in that worker's runner cache — the measured
        difference between ~1.3× and >1.5× sweep speedup at 3 workers);
        otherwise a geometry *no* worker has held yet (spread the cold
        compiles across the fleet); otherwise plain sweep order."""
        seen = self._seen.get(worker, set())
        taken = set()
        for group in self._seen.values():
            taken |= group
        first = affine = fresh = None
        for cell in self.cells.values():
            if cell.state != QUEUED:
                continue
            if first is None:
                first = cell
            if affine is None and cell.geometry in seen:
                affine = cell
                break  # best class; sweep order within it
            if fresh is None and cell.geometry not in taken:
                fresh = cell
        cell = affine or fresh or first
        if cell is None:
            return None
        self._lease_seq += 1
        lease = Lease(
            f"L{self._lease_seq}", cell, worker, now + self.lease_s
        )
        cell.state = LEASED
        cell.attempts += 1
        cell.worker = worker
        self.leases[lease.lease_id] = lease
        self._seen.setdefault(worker, set()).add(cell.geometry)
        return lease

    def heartbeat(self, lease_id: str, now: float) -> bool:
        """Refresh a live lease's TTL; False = the lease is gone (the
        worker must abandon the cell)."""
        lease = self.leases.get(lease_id)
        if lease is None:
            return False
        lease.expires_mono = now + self.lease_s
        return True

    def complete(self, lease_id: str, worker: str) -> "Cell | None":
        """Accept a completion from the live lease holder; ``None`` =
        discarded (revoked/unknown lease, or another worker's — the
        at-most-once-recorded half of the contract)."""
        lease = self.leases.get(lease_id)
        if lease is None or lease.worker != worker:
            return None
        del self.leases[lease_id]
        lease.cell.state = COMPLETED
        return lease.cell

    def fail(self, lease_id: str, worker: str) -> "tuple[Cell, bool] | None":
        """A reported attempt failure: requeue while lease-attempts
        remain, else mark the cell failed. Returns ``(cell, requeued)``;
        ``None`` = stale lease, report discarded."""
        lease = self.leases.get(lease_id)
        if lease is None or lease.worker != worker:
            return None
        del self.leases[lease_id]
        cell = lease.cell
        requeued = cell.attempts < self.max_attempts
        cell.state = QUEUED if requeued else FAILED
        return cell, requeued

    def revoke_expired(self, now: float) -> "list[Lease]":
        """Revoke every lease past its (heartbeat-refreshed) expiry — the
        stall contract: a worker silent longer than ``lease_s`` is dead
        or wedged either way. Revoked cells requeue (or fail past the
        attempt budget)."""
        expired = [
            lease for lease in self.leases.values()
            if now >= lease.expires_mono
        ]
        for lease in expired:
            self._revoke(lease)
        return expired

    def revoke_worker(self, worker: str) -> "list[Lease]":
        """Revoke every lease a (disconnected) worker holds."""
        held = [
            lease for lease in self.leases.values() if lease.worker == worker
        ]
        for lease in held:
            self._revoke(lease)
        return held

    def _revoke(self, lease: Lease) -> None:
        del self.leases[lease.lease_id]
        cell = lease.cell
        cell.state = (
            QUEUED if cell.attempts < self.max_attempts else FAILED
        )

    # -- views ---------------------------------------------------------------

    def counts(self) -> dict:
        c = Counter(cell.state for cell in self.cells.values())
        return {
            "total": len(self.cells),
            "queued": c[QUEUED],
            "leased": c[LEASED],
            "completed": c[COMPLETED],
            "failed": c[FAILED],
        }

    def whole(self) -> bool:
        """Every cell terminal (completed or failed) and no lease live —
        the scheduler's exit condition. An empty ledger is NOT whole:
        a scheduler started bare waits for its first submission."""
        return bool(self.cells) and not self.leases and all(
            cell.state in (COMPLETED, FAILED)
            for cell in self.cells.values()
        )

    def expected_digests(self) -> Counter:
        """Digest multiset of every cell the sweep expects — the audit's
        left-hand side (trials of one config digest distinctly, so the
        multiset degenerates to a set in practice but never assumes it)."""
        return Counter(cell.digest for cell in self.cells.values())


def audit_exactly_once(telemetry_dir: str, expected: Counter) -> dict:
    """The registry-ground-truth audit: did every expected trial complete
    **exactly once**? Diffs ``expected`` (digest multiset) against the
    registry's current ``completed`` records (``heal.completed_digests``
    — same fold ``watch``/``report`` read). Returns ``{ok, missing,
    duplicates}`` where ``missing``/``duplicates`` map digest → count;
    a duplicate means two completed records landed for one expected
    trial — the exactly-once violation the scheduler exists to prevent.
    jax-free."""
    from ..resilience.heal import completed_digests

    done = completed_digests(telemetry_dir)
    missing = {
        d: n - done.get(d, 0) for d, n in expected.items()
        if done.get(d, 0) < n
    }
    duplicates = {
        d: done[d] - expected.get(d, 0) for d in done
        if d in expected and done[d] > expected[d]
    }
    return {
        "ok": not missing and not duplicates,
        "missing": missing,
        "duplicates": duplicates,
    }
