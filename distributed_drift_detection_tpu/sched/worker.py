"""The worker agent: lease cells, run them, heartbeat, repeat.

    python -m distributed_drift_detection_tpu sched-worker \\
        --connect HOST:PORT [--worker-id ID] [--index I] [--retries N]

One agent = one process = one cell at a time (cells are whole device
programs; parallelism comes from running more agents, not threads). The
loop:

1. ``hello`` → the scheduler's ``welcome`` carries the knobs the agent
   must honor (``telemetry_dir``, ``lease_s``, ``heartbeat_s``,
   ``poll_s``) — workers are configured by the control plane, not by
   flags, so a fleet can never disagree with its scheduler.
2. ``lease`` → a wire cell. The agent rebuilds the ``RunConfig``
   (:func:`..sched.protocol.cell_from_wire` — refusing digest drift)
   and runs it under ``resilience.supervisor.supervised_run`` with the
   scheduler's telemetry directory, so the cell gets the standard
   telemetry bracketing: per-attempt registry records, a per-cell run
   log, ``run_retried`` events — ``report``/``watch``/``correlate``/
   ``top`` work unchanged on a scheduler-run sweep.
3. While the cell runs, a **heartbeat thread** refreshes the lease every
   ``heartbeat_s``. A ``revoked`` reply means the scheduler already
   re-leased the cell (this agent was presumed dead): the agent abandons
   the cell's result — no ``done`` report — and moves on. (Work already
   recorded by ``api.run`` mid-flight is the narrow documented hole; see
   ``leases.py``.)
4. ``done``/``fail`` close the lease; ``wait`` backs off ``poll_s``;
   ``drain`` exits 0 — the sweep is whole.

Fault site ``sched.worker`` fires once per leased cell at execution
start, *outside* the per-cell error handling: an armed ``raise`` kills
the whole agent process — the deterministic stand-in for a preempted VM
or an OOM-killed worker the acceptance test and CI job inject via
``DDD_FAULTS``. Bernoulli arming de-correlates across a spawned fleet:
the agent re-seeds the armed spec with its ``--index`` so three workers
sharing one ``DDD_FAULTS`` string die at *different* cells.

Cell execution is the only jax-dependent part (and it is lazy):
``run_cell=`` is injectable, so the protocol/lease tests drive agents
with a jax-free stub executor.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
import time

from ..resilience import faults
from . import protocol


def _identity() -> dict:
    """Fleet identity extras for the hello (hostname/pid, plus the
    multihost process identity when the jax runtime is importable —
    jax-free fallback keeps stub-executor agents dependency-free)."""
    ident = {"hostname": socket.gethostname(), "pid": os.getpid()}
    try:
        from ..parallel.multihost import fleet_worker_identity

        ident.update(fleet_worker_identity())
    except Exception:
        pass
    return ident


def default_run_cell(
    cell: dict, telemetry_dir: str, *, retries: int = 2,
    compile_cache_dir: str = "",
):
    """The production executor: rebuild the cell's ``RunConfig`` (digest
    round trip verified) and run it under the supervisor with the
    scheduler's telemetry directory. ``compile_cache_dir`` points the
    fleet at one shared persistent XLA cache (bookkeeping, outside the
    digest — repeated cell geometries warm-start across workers).
    Returns the result summary the ``done`` report carries. Lazy jax
    (via ``api.run``)."""
    from ..resilience.policy import RetryPolicy
    from ..resilience.supervisor import supervised_run

    cfg = protocol.cell_from_wire(
        cell,
        telemetry_dir=telemetry_dir,
        compile_cache_dir=compile_cache_dir,
    )
    res = supervised_run(
        cfg, RetryPolicy(max_attempts=max(retries, 0) + 1)
    )
    return {
        "rows": int(res.stream.num_rows),
        "total_time": float(res.total_time),
        "detections": int(res.metrics.num_detections),
    }


class Worker:
    """One agent. ``run()`` drives the loop until drain (returns 0),
    ``--max-cells`` (returns 0), or a fatal control-plane error
    (raises). Injectables (``run_cell``, ``sleep``) keep tests fast and
    jax-free."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        worker_id: "str | None" = None,
        index: int = 0,
        retries: int = 2,
        max_cells: int = 0,
        compile_cache_dir: str = "",
        run_cell=None,
        sleep=time.sleep,
        progress=lambda msg: print(msg, file=sys.stderr),
    ):
        self.client = protocol.ControlClient(host, port)
        self.worker_id = worker_id or f"w{index}-{socket.gethostname()}-{os.getpid()}"
        self.index = int(index)
        self.retries = int(retries)
        self.max_cells = int(max_cells)
        self.compile_cache_dir = compile_cache_dir
        if run_cell is None and compile_cache_dir:
            run_cell = lambda cell, tele, retries=2: default_run_cell(  # noqa: E731
                cell, tele, retries=retries,
                compile_cache_dir=compile_cache_dir,
            )
        self.run_cell = run_cell or default_run_cell
        self.sleep = sleep
        self.progress = progress
        self.telemetry_dir = ""
        self.heartbeat_s = protocol.DEFAULT_HEARTBEAT_S
        self.poll_s = protocol.DEFAULT_POLL_S
        self.cells_done = 0
        self.rows_done = 0
        # One lock serializes the heartbeat thread and the main loop on
        # the shared control connection (strict request/reply protocol).
        self._io_lock = threading.Lock()

    def _request(self, msg: dict) -> dict:
        with self._io_lock:
            return self.client.request(msg)

    def hello(self) -> dict:
        welcome = self._request(
            {"op": "hello", "worker": self.worker_id, **_identity()}
        )
        self.telemetry_dir = welcome.get("telemetry_dir", "") or ""
        self.heartbeat_s = float(
            welcome.get("heartbeat_s", self.heartbeat_s)
        )
        self.poll_s = float(welcome.get("poll_s", self.poll_s))
        return welcome

    def _beat(self, lease_id: str, revoked: threading.Event,
              stop: threading.Event) -> None:
        while not stop.wait(self.heartbeat_s):
            try:
                reply = self._request(
                    {
                        "op": "heartbeat",
                        "worker": self.worker_id,
                        "lease_id": lease_id,
                        "rows_done": self.rows_done,
                    }
                )
            except (OSError, protocol.ProtocolError):
                return  # control plane gone; the main loop finds out next
            if reply.get("op") == "revoked":
                revoked.set()
                return

    def run_one(self, lease: dict) -> None:
        """Execute one leased cell with heartbeats, then report."""
        lease_id = lease["lease_id"]
        cell = lease["cell"]
        revoked, stop = threading.Event(), threading.Event()
        beat = threading.Thread(
            target=self._beat, args=(lease_id, revoked, stop),
            name="sched-heartbeat", daemon=True,
        )
        beat.start()
        try:
            result = self.run_cell(
                cell, self.telemetry_dir, retries=self.retries
            )
        except Exception as e:
            stop.set()
            beat.join(timeout=5)
            if revoked.is_set():
                return  # already re-leased elsewhere; nothing to report
            self._request(
                {
                    "op": "fail",
                    "worker": self.worker_id,
                    "lease_id": lease_id,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
            self.progress(
                f"sched-worker {self.worker_id}: cell "
                f"{cell.get('app_name')!r} FAILED ({type(e).__name__}: {e})"
            )
            return
        finally:
            stop.set()
        beat.join(timeout=5)
        if revoked.is_set():
            # The scheduler presumed us dead and re-leased the cell: the
            # completion must NOT be reported (at-most-once-recorded).
            self.progress(
                f"sched-worker {self.worker_id}: lease {lease_id} revoked "
                f"mid-cell — abandoning {cell.get('app_name')!r}"
            )
            return
        self.rows_done += int(result.get("rows", 0) or 0)
        reply = self._request(
            {
                "op": "done",
                "worker": self.worker_id,
                "lease_id": lease_id,
                "result": result,
            }
        )
        if reply.get("accepted"):
            self.cells_done += 1
        self.progress(
            f"sched-worker {self.worker_id}: {cell.get('app_name')} "
            f"done ({result.get('detections')} detections, "
            f"accepted={bool(reply.get('accepted'))})"
        )

    def run(self) -> int:
        self.hello()
        while True:
            try:
                reply = self._request(
                    {"op": "lease", "worker": self.worker_id}
                )
            except protocol.ProtocolError as e:
                # A rejected grant (e.g. an armed `sched.lease` fault) is
                # the scheduler's problem, not ours: back off and retry —
                # the cell stayed queued.
                self.progress(
                    f"sched-worker {self.worker_id}: lease rejected "
                    f"({e}) — retrying"
                )
                self.sleep(self.poll_s)
                continue
            op = reply.get("op")
            if op == "drain":
                try:
                    self._request({"op": "bye", "worker": self.worker_id})
                except (OSError, protocol.ProtocolError):
                    pass
                return 0
            if op == "wait":
                self.sleep(float(reply.get("poll_s", self.poll_s)))
                continue
            if op != "lease":
                raise protocol.ProtocolError(
                    f"unexpected reply {op!r} to a lease request"
                )
            # The preemption fault site: OUTSIDE the per-cell handling,
            # so an armed raise kills the whole agent — the injected
            # worker death the exactly-once contract is tested against.
            faults.fire(
                "sched.worker",
                worker=self.worker_id,
                cell=reply["cell"].get("app_name"),
            )
            self.run_one(reply)
            if self.max_cells and self.cells_done >= self.max_cells:
                try:
                    self._request({"op": "bye", "worker": self.worker_id})
                except (OSError, protocol.ProtocolError):
                    pass
                return 0


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_drift_detection_tpu sched-worker",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--connect", required=True, metavar="HOST:PORT",
        help="the scheduler's control endpoint (its banner's host/port)",
    )
    ap.add_argument(
        "--worker-id", default=None,
        help="stable identity (default: w<index>-<host>-<pid>)",
    )
    ap.add_argument(
        "--index", type=int, default=0,
        help="fleet ordinal (de-correlates Bernoulli-armed sched.worker "
        "faults across a spawned fleet)",
    )
    ap.add_argument(
        "--retries", type=int, default=2,
        help="supervised retries per cell attempt (default 2)",
    )
    ap.add_argument(
        "--max-cells", type=int, default=0,
        help="exit 0 after N accepted completions (0 = until drain)",
    )
    ap.add_argument(
        "--compile-cache-dir", default="", metavar="DIR",
        help="shared persistent XLA compilation cache for this fleet "
        "(utils.compile_cache): repeated cell geometries warm-start "
        "across workers",
    )
    args = ap.parse_args(argv)

    armed = faults.arm_from_env()
    spec = faults.armed("sched.worker")
    if spec is not None and spec.rate > 0.0 and args.index:
        # Same DDD_FAULTS string across a spawned fleet, different death
        # schedule per worker: the Bernoulli decision hashes the seed.
        faults.arm(
            "sched.worker", rate=spec.rate, seed=spec.seed + args.index,
            times=spec.times, kind=spec.kind, seconds=spec.seconds,
        )
    if armed:
        print(f"sched-worker: fault site(s) armed: {armed}", file=sys.stderr)
    host, port = protocol.parse_addr(args.connect)
    worker = Worker(
        host, port,
        worker_id=args.worker_id,
        index=args.index,
        retries=args.retries,
        max_cells=args.max_cells,
        compile_cache_dir=args.compile_cache_dir,
    )
    raise SystemExit(worker.run())


if __name__ == "__main__":
    main(sys.argv[1:])
