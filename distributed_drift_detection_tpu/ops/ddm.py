"""DDM (Drift Detection Method, Gama et al. 2004) as pure JAX kernels.

The reference delegates this statistic to ``skmultiflow.drift_detection.DDM``
(``DDM_Process.py:133,139``) and feeds it one error indicator at a time from a
Python ``iterrows()`` loop (``DDM_Process.py:144-152``) — the scalar hot loop
identified in SURVEY.md §3.2. Here it becomes two TPU-native kernels:

* :func:`ddm_step` — the per-element recurrence as a ``(carry, err) ->
  (carry, flags)`` function (scan-able; kept as the executable spec).
* :func:`ddm_batch` — the same semantics over a whole microbatch with **no
  sequential dependency**: the running error mean is a ``cumsum``, and the
  running minimum of ``p+s`` (with its ``(p_min, s_min)`` payload) is an
  associative combine, so the per-batch detector runs as a handful of
  vectorised O(B) primitives instead of B Python iterations. This is what
  makes the detector essentially free on the MXU-adjacent VPU and lets
  throughput come from ``vmap`` over partitions.

Semantics reproduced exactly (spec: SURVEY.md §3.3; behaviour of
``skmultiflow.DDM`` as constructed at ``DDM_Process.py:139``):

  with sample index i (1-based since the last reset),

    p_i = mean(err_1..err_i)            # incremental form p += (err-p)/i
    s_i = sqrt(p_i * (1 - p_i) / i)
    after the update, the sample counter is i+1; the min/warn/change section
    runs only when  i + 1 >= min_num_instances;
    if p_i + s_i <= (p+s)_min:  (p+s)_min, p_min, s_min ← p_i + s_i, p_i, s_i
      (ties update — a later equal minimum wins)
    change  when p_i + s_i > p_min + out_control_level * s_min
    warning when p_i + s_i > p_min + warning_level    * s_min  (and not change)

The detector is *reset by the caller* on change (the reference sets
``ddm = None`` at ``DDM_Process.py:209``; skmultiflow's lazy self-reset on the
next ``add_element`` is therefore never observed and is not reproduced).

Numerical note: state carries ``(count, err_sum)`` rather than ``p``, so the
scalar and batch paths compute identical expressions; f32 is exact for error
sums below 2^24 elements between resets, far beyond any realistic run between
drifts.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import DDMParams

_INF = jnp.inf


class DDMState(NamedTuple):
    """Carried detector state. All leaves are scalars (vmap adds axes)."""

    count: jax.Array  # i32: elements absorbed since last reset
    err_sum: jax.Array  # f32: sum of error indicators since last reset
    ps_min: jax.Array  # f32: running min of p+s (inf until first update)
    p_min: jax.Array  # f32: p at the running min
    s_min: jax.Array  # f32: s at the running min


class DDMBatchResult(NamedTuple):
    """Per-microbatch detection summary (−1 sentinels, reference C6)."""

    first_warning: jax.Array  # i32: index in batch of first warning, or −1
    first_change: jax.Array  # i32: index in batch of first change, or −1


class DDMWindowResult(NamedTuple):
    """Per-batch detection summary over a window of W microbatches."""

    first_warning: jax.Array  # i32 [W]: index within batch, or −1
    first_change: jax.Array  # i32 [W]: index within batch, or −1


def ddm_init() -> DDMState:
    """Fresh detector state (equivalent to a new skmultiflow ``DDM``)."""
    f = jnp.float32
    return DDMState(
        count=jnp.int32(0),
        err_sum=f(0.0),
        ps_min=f(_INF),
        p_min=f(_INF),
        s_min=f(_INF),
    )


def _band_s(s_min: jax.Array, cnt_f: jax.Array, params: DDMParams):
    """Effective band-width std: ``max(s_min, Δ / out_control_level)``.

    Δ = ``params.noise_floor`` (``config.DDMParams``): the minimum
    running-error-rate excursion treated as change. Guards the zero-minima
    trap — an error-free stretch captures ``s_min = 0``, making the
    warning/change bands zero-width so one residual error fires a change.
    With the floor, the change band is ``max(L·s_min, Δ)`` and the warning
    band scales with it (``(w/L)·Δ``), preserving the reference's band
    geometry; Δ = 0 is exactly classic DDM (compile-time branch: no extra
    ops in the reference-exact default). Applied to the band width only;
    minima tracking is untouched. ``cnt_f`` is unused but kept in the
    signature so an n-aware floor stays a local change.
    """
    nf = params.noise_floor
    if isinstance(nf, (int, float)) and float(nf) == 0.0:
        return s_min  # reference-exact default: literally no extra ops
    # Traced-params path (property tests jit over params): all-array math.
    # f32 divide, mirrored exactly by the oracle (tests/oracle.py).
    return jnp.maximum(
        s_min,
        jnp.float32(nf) / jnp.float32(params.out_control_level),
    )


def ddm_step(
    state: DDMState, err: jax.Array, params: DDMParams = DDMParams()
) -> tuple[DDMState, tuple[jax.Array, jax.Array]]:
    """One ``add_element`` (executable spec; see module docstring).

    Args:
      state: carried :class:`DDMState`.
      err: scalar error indicator in {0, 1} (f32).
      params: detector thresholds.

    Returns:
      ``(new_state, (warning, change))`` with boolean flags.
    """
    cnt = state.count + 1
    esum = state.err_sum + err
    cnt_f = cnt.astype(jnp.float32)
    p = esum / cnt_f
    s = jnp.sqrt(jnp.clip(p * (1.0 - p), 0.0) / cnt_f)
    ps = p + s

    check = (cnt + 1) >= params.min_num_instances
    take = check & (ps <= state.ps_min)
    ps_min = jnp.where(take, ps, state.ps_min)
    p_min = jnp.where(take, p, state.p_min)
    s_min = jnp.where(take, s, state.s_min)

    s_band = _band_s(s_min, cnt_f, params)
    change = check & (ps > p_min + params.out_control_level * s_band)
    warning = check & ~change & (ps > p_min + params.warning_level * s_band)

    new_state = DDMState(cnt, esum, ps_min, p_min, s_min)
    return new_state, (warning, change)


def ddm_scan(
    state: DDMState, errs: jax.Array, params: DDMParams = DDMParams()
) -> tuple[DDMState, tuple[jax.Array, jax.Array]]:
    """Sequential reference path: ``lax.scan`` of :func:`ddm_step` over errs."""

    def body(carry, err):
        return ddm_step(carry, err, params)

    return lax.scan(body, state, errs)


def _run_min(ps_masked: jax.Array, p: jax.Array, s: jax.Array):
    """Running (min of ps, payload p, payload s), later elements win ties."""

    def combine(a, b):  # a earlier, b later
        take_b = b[0] <= a[0]
        return tuple(jnp.where(take_b, bb, aa) for aa, bb in zip(a, b))

    return lax.associative_scan(combine, (ps_masked, p, s))


def _prefix_masks(
    state: DDMState, errs: jax.Array, valid: jax.Array, params: DDMParams
):
    """Shared core: per-element prefix statistics + warning/change masks.

    ``errs``/``valid`` are flat ``[N]``; returns ``(end_state, warning[N],
    change[N])`` where the masks hold at each prefix position and
    ``end_state`` is the detector state after absorbing every valid element.
    """
    v = valid.astype(jnp.int32)
    cnt = state.count + jnp.cumsum(v)  # i32 [N]
    esum = state.err_sum + jnp.cumsum(errs * valid.astype(errs.dtype))
    cnt_f = jnp.maximum(cnt, 1).astype(jnp.float32)
    p = esum / cnt_f
    s = jnp.sqrt(jnp.clip(p * (1.0 - p), 0.0) / cnt_f)
    ps = p + s

    check = valid & ((cnt + 1) >= params.min_num_instances)
    ps_masked = jnp.where(check, ps, _INF)
    run_ps, run_p, run_s = _run_min(ps_masked, p, s)

    # Merge the carried minima (strictly earlier than every batch element, so
    # a batch minimum that ties it wins — same `<=` rule).
    use_run = run_ps <= state.ps_min
    ps_min = jnp.where(use_run, run_ps, state.ps_min)
    p_min = jnp.where(use_run, run_p, state.p_min)
    s_min = jnp.where(use_run, run_s, state.s_min)

    s_band = _band_s(s_min, cnt_f, params)
    change = check & (ps > p_min + params.out_control_level * s_band)
    warning = check & ~change & (ps > p_min + params.warning_level * s_band)

    end_state = DDMState(
        count=cnt[-1],
        err_sum=esum[-1],
        ps_min=ps_min[-1],
        p_min=p_min[-1],
        s_min=s_min[-1],
    )
    return end_state, warning, change


def _first_true(mask: jax.Array, limit: jax.Array | None = None):
    """Index of the first True along the last axis, −1 when none.

    ``limit`` (optional, same leading shape) restricts the search to
    ``index <= limit`` (the reference's early-break visibility window).
    """
    if limit is not None:
        idx = jnp.arange(mask.shape[-1], dtype=jnp.int32)
        mask = mask & (idx <= limit[..., None])
    has = jnp.any(mask, axis=-1)
    pos = jnp.argmax(mask, axis=-1).astype(jnp.int32)
    return jnp.where(has, pos, jnp.int32(-1))


def summarise_batch(warning: jax.Array, change: jax.Array) -> DDMBatchResult:
    """Per-element masks → first-warning/first-change summary.

    Implements the early-break protocol shared by every detector
    (``DDM_Process.py:147-152``): the first change wins, and warnings at
    positions the reference loop never reached don't count.
    """
    b = change.shape[-1]
    first_change = _first_true(change)
    limit = jnp.where(first_change >= 0, first_change, jnp.int32(b))
    first_warning = _first_true(warning, limit)
    return DDMBatchResult(first_warning, first_change)


def summarise_window(
    warning: jax.Array, change: jax.Array, w: int, b: int
) -> DDMWindowResult:
    """Flattened ``[W·B]`` masks → per-batch ``[W]`` summaries."""
    res = summarise_batch(warning.reshape(w, b), change.reshape(w, b))
    return DDMWindowResult(res.first_warning, res.first_change)


def ddm_batch(
    state: DDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: DDMParams = DDMParams(),
) -> tuple[DDMState, DDMBatchResult]:
    """Vectorised microbatch update — semantics of the reference's per-row
    loop + first-warning/first-change/early-break protocol
    (``DDM_Process.py:141-152``), in O(B) parallel primitives.

    Elements after the first change are ignored (the reference ``break``s at
    ``:152``); on change the caller is expected to reset the state (the
    reference discards the detector at ``:209``), so the returned state is only
    meaningful when ``first_change == -1``.

    Args:
      state: carried :class:`DDMState`.
      errs: ``[B]`` f32 error indicators.
      valid: ``[B]`` bool mask (False = padding row; contributes nothing).
      params: detector thresholds.

    Returns:
      ``(state_after_full_batch, DDMBatchResult)``.
    """
    new_state, warning, change = _prefix_masks(state, errs, valid, params)
    return new_state, summarise_batch(warning, change)


def ddm_window(
    state: DDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: DDMParams = DDMParams(),
) -> tuple[DDMState, DDMWindowResult]:
    """Speculative multi-batch update: W consecutive microbatches in one shot.

    Semantically identical to applying :func:`ddm_batch` to each of the W
    batches in order **with no reset in between** — the detector state flows
    across batch boundaries exactly as the engine carries it
    (``DDM_Process.py:202``). The caller speculates that no change occurs in
    the window; per-batch results for batches *after* the first changed batch
    are garbage (the engine would have reset + retrained there) and must be
    discarded and recomputed by the caller (see ``engine.window``).

    Args:
      state: carried :class:`DDMState`.
      errs: ``[W, B]`` f32 error indicators, batch-major.
      valid: ``[W, B]`` bool mask.
      params: detector thresholds.

    Returns:
      ``(state_after_full_window, DDMWindowResult)`` with ``[W]`` leaves.
    """
    w, b = errs.shape
    end_state, warning, change = _prefix_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)
