from .ddm import (
    DDMBatchResult,
    DDMState,
    ddm_batch,
    ddm_init,
    ddm_scan,
    ddm_step,
)

__all__ = [
    "DDMBatchResult",
    "DDMState",
    "ddm_batch",
    "ddm_init",
    "ddm_scan",
    "ddm_step",
]
