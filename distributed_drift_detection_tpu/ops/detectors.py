"""Detector zoo: pluggable drift detectors behind one kernel interface.

The reference is a single-detector artifact — its only statistic is
skmultiflow's ``DDM`` (``DDM_Process.py:133,139``; rebuilt TPU-native in
``ops.ddm``). A drift-detection *framework* owes its users the standard
alternatives, so this module adds six classic error-stream detectors (a
seventh, adaptive windowing, lives in ``ops.adwin`` — structurally a
different beast) and a uniform :class:`DetectorKernel` seam the engines
consume — together the registry covers every detector in skmultiflow's
``drift_detection`` module (DDM, EDDM, HDDM-A/W, PH, ADWIN, KSWIN) plus
STEPD (Nishida & Yamauchi 2007):

* **Page–Hinkley** (:func:`ph_batch`) — the clamped CUSUM test (Page 1954;
  the streaming form popularised by Gama et al.'s drift surveys): per error
  indicator ``x_i`` with running mean ``x̄_i``,

      m_i = max(0, α·m_{i−1} + (x_i − x̄_i − δ)),   m_0 = 0

  change when ``m_i > λ`` (after ``min_num_instances`` elements). Warnings
  are a framework extension (the classic test has none): reported — like the
  reference's DDM warning zone, reported-only (``DDM_Process.py:147-148``) —
  when ``m_i > warning_fraction·λ``.

* **EDDM** (:func:`eddm_batch`) — *Early Drift Detection Method* (Baena-
  García et al. 2006): tracks the distance (in elements) between consecutive
  errors. With ``k`` errors seen since reset, distance mean ``μ_k`` and
  population std ``σ_k``, the statistic is ``m2s_k = μ_k + 2σ_k`` and its
  running maximum ``m2s_max``. At an error that does **not** raise the
  maximum and once ``k ≥ min_num_errors``: warning when ``m2s_k/m2s_max <
  α``, change when ``< β`` (shrinking error distances ⇒ drift).

  **Documented deviation from Baena-García 2006 (default mode):** the first
  error after init/reset contributes a distance measured from the
  stream/reset start (``d = t`` with ``last_err_t = 0``), whereas the paper
  only measures distances *between consecutive* errors (the first error
  would merely arm ``last_err_t``). This seeds the mean/std/``m2s_max``
  with one synthetic distance per reset, in exchange for one uniform
  ``d = t − last_err_t`` recurrence across every code path. The effect is
  **measured**, not argued (r04; methodology + numbers in PARITY.md "EDDM
  deviation", test ``test_eddm_deviation_quantified``): at benchmark-like
  geometry the two variants are quality-equivalent (boundary recall 99.7%
  vs 99.5%, spurious within ~4.5%) but not flag-equivalent (detection
  positions drift by a median ~20 elements via compounding reset-phase
  shifts). ``EDDMParams(paper_exact=True)`` therefore selects the
  paper-exact semantics — same state layout, the first post-reset error
  merely arms the origin and ``min_num_errors`` counts distances — for
  paper-comparable runs; the default preserves the framework's historical
  flags.

* **HDDM-W** (:func:`hddm_w_batch`) — the "W-test" companion of HDDM-A
  (Frías-Blanco et al. 2015): the same cut-and-compare scheme on
  *exponentially weighted* means. Maintain the stream EWMA ``z`` (weight
  ``λ``: ``z ← λx + (1−λ)z``, initialised to the first element) and its
  squared-relative-weight sum ``v`` (``v ← λ² + (1−λ)²v``, initialised to
  1), which plays n⁻¹'s role in the McDiarmid-style deviation bound
  ``ε(v, δ) = sqrt(v·ln(1/δ)/2)``. The stored cut is the prefix minimising
  ``z + ε(v)`` (strict improvement — see below); elements after the cut
  feed a second, freshly initialised EWMA ``(z₂, v₂)``, and change fires
  when ``z₂ − z₁ ≥ sqrt((v₁+v₂)·ln(1/δ)/2)`` (one-sided increase, like the
  A-test). Unlike the zoo's other minima (DDM, HDDM-A), the cut moves only
  on **strict** key improvement: a tie-taking cut would also reset the
  monitoring sample and discard accumulated post-cut evidence, so later
  ties must *not* win here.

* **HDDM-A** (:func:`hddm_batch`) — drift detection via Hoeffding's
  inequality, "A-test" (Frías-Blanco et al. 2015; the moving-average form
  popularised by skmultiflow's ``HDDM_A``): maintain the stream mean since
  reset and a stored *cut* — the prefix ``(n_min, c_min)`` minimising the
  optimistic bound ``mean + ε(n)`` with ``ε(n, δ) = sqrt(ln(1/δ)/2n)`` —
  and signal change when the whole-stream mean exceeds the cut's mean by
  the two-sample bound ``sqrt(m/2 · ln(2/δ))``, ``m = (n − n_min)/(n_min
  n)``. Warnings use the same test at ``warning_confidence``. One-sided
  (error *increase* — the direction the engines' rotate-on-drift loop
  consumes); the paper's symmetric decrease test is deliberately not
  implemented. Both knobs are scale-free confidences, so ``hddm`` needs no
  per-stream auto-resolution (contrast ``ph``'s λ).

* **KSWIN** (:func:`kswin_batch`) — sliding-window Kolmogorov–Smirnov test
  (Raab, Heusinger & Schleif 2020): the newest ``stat_size`` of the last
  ``window_size`` elements against the older remainder, change when the KS
  test rejects at ``alpha``. On the engines' Bernoulli error indicators
  the KS statistic *is* the proportion gap (the empirical CDFs step only
  at 0), so the kernel is a rolling-mean comparison against the
  closed-form critical value — see :func:`kswin_step` and the two
  documented deviations in :class:`config.KSWINParams`.

* **STEPD** (:func:`stepd_batch`) — *Statistical Test of Equal
  Proportions* (Nishida & Yamauchi 2007): the error rate of the most
  recent ``window_size`` elements against the overall rate since reset,
  via the pooled two-proportion z-test with continuity correction —
  drift/warning at its classic two significance levels (the one windowed
  member with a real warning zone). Shares KSWIN's ring-buffer state and
  scan-free skeleton.

All six are implemented exactly like ``ops.ddm_batch``: the whole microbatch
(or flattened speculative window) in O(B) vectorised primitives — prefix
sums for the running statistics and an ``associative_scan`` for the
sequential part. For Page–Hinkley the recurrence ``m → max(0, α·m + c)`` is
closed under composition in the family ``m → max(K, A·m + B)``, so the
per-element maps compose associatively as ``(A, B, K)`` triples. For EDDM
the between-error distances telescope through prefix sums over error
events, and the running maximum is an ordinary ``cummax``. For HDDM-A the
stored cut is a running minimum of ``mean + ε(n)`` with the ``(n, c)``
prefix as payload — the same min-with-payload associative combine as DDM's
``(p+s)`` minima (``ops.ddm._run_min``). For HDDM-W every recurrence is an
*affine map* ``y → Ay + B`` (the two EWMAs, their weight sums, with reset /
initialise expressed as ``A = 0``), and affine maps compose associatively —
the cut positions are a running strict min of a key computable from prefix
statistics alone, which then segments the second EWMA's resets. KSWIN is
the degenerate case: its windowed statistic needs no scan of any kind —
every position's two window means are differences of one prefix-sum
vector over the valid-compacted batch.

State-reset protocol matches the engines' DDM contract (``ops.ddm``): the
*caller* resets on change (the reference discards its detector at
``DDM_Process.py:209``), elements after a batch's first change are dead, and
the returned end-state is only meaningful when ``first_change == -1``.

``make_detector`` packages each statistic (params baked in) as a
:class:`DetectorKernel` — the seam ``engine.loop`` / ``engine.window`` /
``parallel.mesh`` accept via their ``detector=`` argument and
``RunConfig(detector=...)`` selects by name.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import (
    ADWINParams,
    DDMParams,
    DETECTOR_NAMES,
    EDDMParams,
    HDDMParams,
    HDDMWParams,
    KSWINParams,
    PHParams,
    STEPDParams,
)
from .ddm import (
    DDMBatchResult,
    DDMWindowResult,
    _run_min,
    ddm_batch,
    ddm_init,
    ddm_window,
    summarise_batch,
    summarise_window,
)

_INF = jnp.inf
# Finite stand-in for "no clamp" in the Page–Hinkley associative compose:
# a true -inf would produce 0·(-inf) = NaN when an element with alpha = 0
# follows an identity (invalid/padded) element. Finite, it multiplies and
# maxes exactly like -inf for every reachable magnitude (|A| ≤ 1, |B| tiny).
# Python float, not jnp.float32(...): a module-level jnp call would create a
# device array at import time and initialise the XLA backend — breaking
# jax.distributed.initialize for any program that imports this package first
# (multihost rule, parallel/multihost.py). Cast where consumed.
_NO_CLAMP = float(-1e30)


class DetectorKernel(NamedTuple):
    """A drift detector as the engines consume it (params already bound).

    ``batch`` maps ``(state, errs [B] f32, valid [B] bool)`` to
    ``(end_state, DDMBatchResult)``; ``window`` is the multi-batch form over
    ``[W, B]`` planes returning ``[W]`` result leaves (state flowing across
    batch boundaries, exactly :func:`ops.ddm.ddm_window`'s contract).
    ``params`` is the statistic's hyper-parameter tuple — the single source
    of truth for any alternate implementation of the same kernel.
    """

    name: str
    init: Callable[[], object]
    batch: Callable[..., tuple[object, DDMBatchResult]]
    window: Callable[..., tuple[object, DDMWindowResult]]
    params: object


# --------------------------------------------------------------------------
# Page–Hinkley
# --------------------------------------------------------------------------


class PHState(NamedTuple):
    """Carried Page–Hinkley state (scalar leaves; vmap adds axes)."""

    count: jax.Array  # i32: elements absorbed since last reset
    x_sum: jax.Array  # f32: sum of inputs since last reset
    m: jax.Array  # f32: clamped cumulative statistic


def ph_init() -> PHState:
    return PHState(jnp.int32(0), jnp.float32(0.0), jnp.float32(0.0))


def _validate_ph(params: PHParams) -> None:
    """Reject out-of-range concrete PH params at every public kernel entry
    (scalar step, batch and window passes) so no path can silently diverge
    from the others. Only a tracer (params passed as a jit argument,
    ``float()`` unavailable) is waved through — there the registry/engine
    path has already checked. The (A, B, K)-triple compose assumes
    ``alpha ≥ 0`` (max doesn't distribute over multiplication by a
    negative); ``threshold = 0`` is the unresolved auto sentinel
    (``config.auto_ph_threshold``) and would fire on every excess-error
    element."""
    try:
        alpha = float(params.alpha)
    except TypeError:  # jax ConcretizationTypeError is a TypeError
        alpha = None
    if alpha is not None and not 0.0 <= alpha <= 1.0:
        raise ValueError(f"PHParams.alpha must be in [0, 1], got {alpha}")
    try:
        thr = float(params.threshold)
    except TypeError:
        thr = None
    if thr is not None and thr <= 0.0:
        raise ValueError(
            f"PHParams.threshold must be > 0, got {thr} (0 = auto, resolved "
            "from stream geometry by api.prepare / config.auto_ph_threshold "
            "— pass a resolved λ to the kernels)"
        )


def ph_step(
    state: PHState, err: jax.Array, params: PHParams
) -> tuple[PHState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec — see module docstring).

    ``params`` is required on every PH kernel (here, :func:`ph_batch`,
    :func:`ph_window`): ``PHParams()``'s threshold default is the 0 = auto
    sentinel (``config.auto_ph_threshold``), which the kernels reject — a
    default argument would be a guaranteed ``ValueError``.
    """
    _validate_ph(params)
    cnt = state.count + 1
    xsum = state.x_sum + err
    mean = xsum / cnt.astype(jnp.float32)
    m = jnp.maximum(0.0, params.alpha * state.m + (err - mean - params.delta))
    check = cnt >= params.min_num_instances
    change = check & (m > params.threshold)
    warning = check & ~change & (m > params.warning_fraction * params.threshold)
    return PHState(cnt, xsum, m), (warning, change)


def _ph_masks(state: PHState, errs: jax.Array, valid: jax.Array, params: PHParams):
    """Flat ``[N]`` prefix pass → ``(end_state, warning[N], change[N])``."""
    _validate_ph(params)
    v = valid.astype(jnp.int32)
    cnt = state.count + jnp.cumsum(v)
    xsum = state.x_sum + jnp.cumsum(errs * valid.astype(errs.dtype))
    mean = xsum / jnp.maximum(cnt, 1).astype(jnp.float32)

    # Per-element map m -> max(0, alpha*m + c); invalid elements are the
    # identity. The family m -> max(K, A*m + B) (A > 0) is closed under
    # composition, so prefix-compose the (A, B, K) triples associatively.
    c = errs - mean - params.delta
    a_el = jnp.where(valid, jnp.float32(params.alpha), 1.0)
    b_el = jnp.where(valid, c, 0.0)
    k_el = jnp.where(valid, jnp.float32(0.0), _NO_CLAMP)

    def compose(first, second):  # apply `first`, then `second`
        a1, b1, k1 = first
        a2, b2, k2 = second
        return (a2 * a1, a2 * b1 + b2, jnp.maximum(k2, a2 * k1 + b2))

    a, b, k = lax.associative_scan(compose, (a_el, b_el, k_el))
    m = jnp.maximum(k, a * state.m + b)

    check = valid & (cnt >= params.min_num_instances)
    change = check & (m > params.threshold)
    warning = check & ~change & (m > params.warning_fraction * params.threshold)
    end_state = PHState(cnt[-1], xsum[-1], m[-1])
    return end_state, warning, change


def ph_batch(
    state: PHState,
    errs: jax.Array,
    valid: jax.Array,
    params: PHParams,
) -> tuple[PHState, DDMBatchResult]:
    """Vectorised microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _ph_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def ph_window(
    state: PHState,
    errs: jax.Array,
    valid: jax.Array,
    params: PHParams,
) -> tuple[PHState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _ph_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)


# --------------------------------------------------------------------------
# EDDM
# --------------------------------------------------------------------------


class EDDMState(NamedTuple):
    """Carried EDDM state (scalar leaves; vmap adds axes).

    f32 prefix sums of distances and squared distances are exact below 2^24
    between resets — far beyond any realistic between-drift span.
    """

    count: jax.Array  # i32: elements absorbed since last reset
    num_errors: jax.Array  # i32: errors seen since last reset
    d_sum: jax.Array  # f32: sum of between-error distances
    d2_sum: jax.Array  # f32: sum of squared distances
    last_err_t: jax.Array  # i32: element index of the last error (0 = none)
    m2s_max: jax.Array  # f32: running max of mean + 2*std


def eddm_init() -> EDDMState:
    f = jnp.float32
    return EDDMState(
        count=jnp.int32(0),
        num_errors=jnp.int32(0),
        d_sum=f(0.0),
        d2_sum=f(0.0),
        last_err_t=jnp.int32(0),
        m2s_max=f(0.0),
    )


def eddm_step(
    state: EDDMState, err: jax.Array, params: EDDMParams = EDDMParams()
) -> tuple[EDDMState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec — see module docstring).

    ``params.paper_exact`` is a trace-time constant selecting whether the
    first error since init/reset *contributes* a distance (the framework's
    uniform recurrence) or merely arms the distance origin (Baena-García
    2006). ``last_err_t > 0`` already encodes "an error has been seen", so
    both modes share one state layout and one recurrence — exact mode just
    masks the first contribution.
    """
    t = state.count + 1
    is_err = err >= 0.5
    contributes = (
        is_err & (state.last_err_t > 0) if params.paper_exact else is_err
    )
    k = state.num_errors + contributes.astype(jnp.int32)
    d = (t - state.last_err_t).astype(jnp.float32)
    d_sum = state.d_sum + jnp.where(contributes, d, 0.0)
    d2_sum = state.d2_sum + jnp.where(contributes, d * d, 0.0)
    k_f = jnp.maximum(k, 1).astype(jnp.float32)
    mean = d_sum / k_f
    var = jnp.maximum(0.0, d2_sum / k_f - mean * mean)
    m2s = mean + 2.0 * jnp.sqrt(var)

    update_max = contributes & (m2s > state.m2s_max)
    check = contributes & ~update_max & (k >= params.min_num_errors)
    ratio = m2s / jnp.maximum(state.m2s_max, 1e-30)
    change = check & (ratio < params.change_beta)
    warning = check & ~change & (ratio < params.warning_alpha)

    new_state = EDDMState(
        count=t,
        num_errors=k,
        d_sum=d_sum,
        d2_sum=d2_sum,
        last_err_t=jnp.where(is_err, t, state.last_err_t),
        m2s_max=jnp.where(update_max, m2s, state.m2s_max),
    )
    return new_state, (warning, change)


def _eddm_masks(
    state: EDDMState, errs: jax.Array, valid: jax.Array, params: EDDMParams
):
    """Flat ``[N]`` prefix pass → ``(end_state, warning[N], change[N])``."""
    v = valid.astype(jnp.int32)
    t = state.count + jnp.cumsum(v)  # i32 [N] element index
    is_err = valid & (errs >= 0.5)

    # Element index of the previous error, strictly before each position:
    # inclusive cummax of (is_err ? t : -1), shifted right, carry-merged.
    err_t = jnp.where(is_err, t, jnp.int32(-1))
    incl = lax.cummax(err_t)
    excl = jnp.concatenate([jnp.full((1,), -1, jnp.int32), incl[:-1]])
    prev_t = jnp.where(excl > 0, excl, state.last_err_t)

    # paper_exact (trace-time constant): the first error since init/reset —
    # the one with no prior error anywhere before it (prev_t == 0) — only
    # arms the distance origin; it contributes no distance, no k count, no
    # m2s event (Baena-García 2006). Default mode: every error contributes
    # (the framework's uniform recurrence; first d is synthetic from reset).
    contributes = is_err & (prev_t > 0) if params.paper_exact else is_err
    k = state.num_errors + jnp.cumsum(contributes.astype(jnp.int32))

    d = (t - prev_t).astype(jnp.float32)
    d_mask = jnp.where(contributes, d, 0.0)
    d_sum = state.d_sum + jnp.cumsum(d_mask)
    d2_sum = state.d2_sum + jnp.cumsum(d_mask * d_mask)
    k_f = jnp.maximum(k, 1).astype(jnp.float32)
    mean = d_sum / k_f
    var = jnp.maximum(0.0, d2_sum / k_f - mean * mean)
    m2s = mean + 2.0 * jnp.sqrt(var)

    # Running max of m2s over contributing error events, merged with the
    # carried max. The detection at an event uses the max *excluding* that
    # event (an event that raises the max never also signals — see module
    # docstring).
    m2s_ev = jnp.where(contributes, m2s, -_INF)
    ev_cummax = lax.cummax(m2s_ev)
    incl_max = jnp.maximum(ev_cummax, state.m2s_max)
    excl_max = jnp.maximum(
        jnp.concatenate([jnp.full((1,), -_INF), ev_cummax[:-1]]),
        state.m2s_max,
    )
    update_max = contributes & (m2s > excl_max)

    check = contributes & ~update_max & (k >= params.min_num_errors)
    ratio = m2s / jnp.maximum(excl_max, 1e-30)
    change = check & (ratio < params.change_beta)
    warning = check & ~change & (ratio < params.warning_alpha)

    end_state = EDDMState(
        count=t[-1],
        num_errors=k[-1],
        d_sum=d_sum[-1],
        d2_sum=d2_sum[-1],
        last_err_t=jnp.where(incl[-1] > 0, incl[-1], state.last_err_t),
        m2s_max=incl_max[-1],
    )
    return end_state, warning, change


def eddm_batch(
    state: EDDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: EDDMParams = EDDMParams(),
) -> tuple[EDDMState, DDMBatchResult]:
    """Vectorised microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _eddm_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def eddm_window(
    state: EDDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: EDDMParams = EDDMParams(),
) -> tuple[EDDMState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _eddm_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)


# --------------------------------------------------------------------------
# HDDM-A
# --------------------------------------------------------------------------


class HDDMState(NamedTuple):
    """Carried HDDM-A state (scalar leaves; vmap adds axes).

    ``(n_min, c_min)`` is the stored prefix cut — the prefix minimising the
    optimistic bound ``mean + ε(n)`` — against which later stream means are
    tested. ``n_min == 0`` means no cut stored yet."""

    count: jax.Array  # i32: elements absorbed since last reset (total_n)
    err_sum: jax.Array  # f32: sum of error indicators (total_c)
    n_min: jax.Array  # i32: element count at the stored cut (0 = none)
    c_min: jax.Array  # f32: error sum at the stored cut


def hddm_init() -> HDDMState:
    return HDDMState(
        jnp.int32(0), jnp.float32(0.0), jnp.int32(0), jnp.float32(0.0)
    )


def _hddm_eps(n_f: jax.Array, confidence: float) -> jax.Array:
    """Hoeffding deviation bound ε(n, δ) = sqrt(ln(1/δ) / 2n)."""
    import math

    return jnp.sqrt(jnp.float32(math.log(1.0 / confidence)) / (2.0 * n_f))


def _hddm_bound(n: jax.Array, n_min: jax.Array, confidence: float) -> jax.Array:
    """Two-sample Hoeffding bound between the stored cut and the whole
    stream: sqrt(m/2 · ln(2/δ)) with m = (n − n_min) / (n_min · n)."""
    import math

    n_f = jnp.maximum(n, 1).astype(jnp.float32)
    nm_f = jnp.maximum(n_min, 1).astype(jnp.float32)
    m = (n_f - nm_f) / (nm_f * n_f)
    return jnp.sqrt(
        jnp.maximum(m, 0.0) / 2.0 * jnp.float32(math.log(2.0 / confidence))
    )


def hddm_step(
    state: HDDMState, err: jax.Array, params: HDDMParams = HDDMParams()
) -> tuple[HDDMState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec — see module docstring).

    Update order matches the A-test: the candidate cut (the current prefix)
    is considered *before* testing, so an element that becomes the new cut
    never also signals (``n_min == n`` ⇒ no between-sample to test)."""
    n = state.count + 1
    c = state.err_sum + err
    n_f = n.astype(jnp.float32)
    mean = c / n_f
    key = mean + _hddm_eps(n_f, params.drift_confidence)
    nm_f = jnp.maximum(state.n_min, 1).astype(jnp.float32)
    stored_key = jnp.where(
        state.n_min > 0,
        state.c_min / nm_f + _hddm_eps(nm_f, params.drift_confidence),
        jnp.float32(_INF),
    )
    take = key <= stored_key  # later ties win (the DDM minima rule)
    n_min = jnp.where(take, n, state.n_min)
    c_min = jnp.where(take, c, state.c_min)

    testable = (n_min > 0) & (n_min < n)
    diff = mean - c_min / jnp.maximum(n_min, 1).astype(jnp.float32)
    change = testable & (
        diff >= _hddm_bound(n, n_min, params.drift_confidence)
    )
    warning = (
        testable
        & ~change
        & (diff >= _hddm_bound(n, n_min, params.warning_confidence))
    )
    return HDDMState(n, c, n_min, c_min), (warning, change)


def _hddm_masks(
    state: HDDMState, errs: jax.Array, valid: jax.Array, params: HDDMParams
):
    """Flat ``[N]`` prefix pass → ``(end_state, warning[N], change[N])``.

    The stored cut is a running minimum of ``mean_i + ε(n_i)`` with the
    ``(n_i, c_i)`` prefix as payload — exactly the DDM minima formulation
    (``ops.ddm._run_min``), so the whole batch runs as cumsums + one
    associative scan."""
    v = valid.astype(jnp.int32)
    n = state.count + jnp.cumsum(v)
    c = state.err_sum + jnp.cumsum(errs * valid.astype(errs.dtype))
    n_f = jnp.maximum(n, 1).astype(jnp.float32)
    mean = c / n_f
    key = jnp.where(
        valid, mean + _hddm_eps(n_f, params.drift_confidence), _INF
    )
    # DDM's min-with-payload combine, verbatim — one tie rule, one place.
    run_key, run_n, run_c = _run_min(key, n, c)

    nm_f = jnp.maximum(state.n_min, 1).astype(jnp.float32)
    carried_key = jnp.where(
        state.n_min > 0,
        state.c_min / nm_f + _hddm_eps(nm_f, params.drift_confidence),
        jnp.float32(_INF),
    )
    use_run = run_key <= carried_key
    n_min = jnp.where(use_run, run_n, state.n_min)
    c_min = jnp.where(use_run, run_c, state.c_min)

    testable = valid & (n_min > 0) & (n_min < n)
    diff = mean - c_min / jnp.maximum(n_min, 1).astype(jnp.float32)
    change = testable & (
        diff >= _hddm_bound(n, n_min, params.drift_confidence)
    )
    warning = (
        testable
        & ~change
        & (diff >= _hddm_bound(n, n_min, params.warning_confidence))
    )
    end_state = HDDMState(n[-1], c[-1], n_min[-1], c_min[-1])
    return end_state, warning, change


def hddm_batch(
    state: HDDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: HDDMParams = HDDMParams(),
) -> tuple[HDDMState, DDMBatchResult]:
    """Vectorised microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _hddm_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def hddm_window(
    state: HDDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: HDDMParams = HDDMParams(),
) -> tuple[HDDMState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _hddm_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)


# --------------------------------------------------------------------------
# HDDM-W
# --------------------------------------------------------------------------


class HDDMWState(NamedTuple):
    """Carried HDDM-W state (scalar leaves; vmap adds axes).

    ``(z, v)`` are the whole-stream EWMA and its squared-relative-weight sum
    since reset; ``(z1, v1)`` the same pair frozen at the stored cut
    (``v1 == 0`` = no cut yet — any real cut has ``v1 ≥ λ² > 0``); ``(n2,
    z2, v2)`` the monitoring EWMA over the elements after the cut. The
    stored cut *key* is not carried: it is recomputable as ``z1 + ε(v1)``
    — the key was minimised at the very prefix whose ``(z, v)`` became the
    payload."""

    count: jax.Array  # i32: elements absorbed since last reset
    z: jax.Array  # f32: stream EWMA
    v: jax.Array  # f32: stream Σ(relative weight)², the bound condition
    z1: jax.Array  # f32: EWMA frozen at the stored cut
    v1: jax.Array  # f32: bound condition frozen at the cut (0 = no cut)
    n2: jax.Array  # i32: elements absorbed after the cut
    z2: jax.Array  # f32: post-cut EWMA
    v2: jax.Array  # f32: post-cut bound condition


def hddm_w_init() -> HDDMWState:
    f = jnp.float32
    return HDDMWState(
        jnp.int32(0), f(0.0), f(0.0), f(0.0), f(0.0), jnp.int32(0), f(0.0),
        f(0.0),
    )


def _validate_hddm_w(params: HDDMWParams) -> None:
    """Reject out-of-range concrete params at every public kernel entry (the
    ``_validate_ph`` pattern — a tracer is waved through; the registry has
    already checked there). ``lam`` outside (0, 1) breaks both the EWMA
    semantics and the affine compose's forgetting direction."""
    try:
        lam = float(params.lam)
    except TypeError:  # jax ConcretizationTypeError is a TypeError
        lam = None
    if lam is not None and not 0.0 < lam < 1.0:
        raise ValueError(f"HDDMWParams.lam must be in (0, 1), got {lam}")
    for knob in ("drift_confidence", "warning_confidence"):
        try:
            conf = float(getattr(params, knob))
        except TypeError:
            conf = None
        if conf is not None and not 0.0 < conf < 1.0:
            raise ValueError(
                f"HDDMWParams.{knob} must be in (0, 1), got {conf}"
            )


def _hddm_w_eps(v: jax.Array, confidence: float) -> jax.Array:
    """Weighted deviation bound ε(v, δ) = sqrt(v · ln(1/δ) / 2) — the
    McDiarmid/independent-bounded-difference analog of the A-test's
    Hoeffding ε(n, δ); ``v = Σ(relative weight)²`` degenerates to ``1/n``
    under uniform weights, recovering :func:`_hddm_eps` exactly."""
    import math

    return jnp.sqrt(v * jnp.float32(math.log(1.0 / confidence)) / 2.0)


def hddm_w_step(
    state: HDDMWState, err: jax.Array, params: HDDMWParams = HDDMWParams()
) -> tuple[HDDMWState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec — see module docstring).

    Update order matches the A-test's: the stream EWMA absorbs the element,
    the candidate cut is considered *before* testing, and an element that
    moves the cut resets the monitoring sample without joining it — so a
    cut-moving element never signals (there is nothing after the cut yet).
    """
    _validate_hddm_w(params)
    lam = jnp.float32(params.lam)
    first = state.count == 0
    n = state.count + 1
    z = jnp.where(first, err, lam * err + (1.0 - lam) * state.z)
    v = jnp.where(first, 1.0, lam * lam + (1.0 - lam) ** 2 * state.v)

    key = z + _hddm_w_eps(v, params.drift_confidence)
    stored = jnp.where(
        state.v1 > 0,
        state.z1 + _hddm_w_eps(state.v1, params.drift_confidence),
        jnp.float32(_INF),
    )
    take = key < stored  # STRICT: ties keep the cut (and the sample2 evidence)
    z1 = jnp.where(take, z, state.z1)
    v1 = jnp.where(take, v, state.v1)

    init2 = ~take & (state.n2 == 0)
    n2 = jnp.where(take, 0, state.n2 + 1)
    z2 = jnp.where(
        take,
        0.0,
        jnp.where(init2, err, lam * err + (1.0 - lam) * state.z2),
    )
    v2 = jnp.where(
        take,
        0.0,
        jnp.where(init2, 1.0, lam * lam + (1.0 - lam) ** 2 * state.v2),
    )

    testable = ~take  # n2 >= 1 by construction on this branch
    diff = z2 - z1
    change = testable & (
        diff >= _hddm_w_eps(v1 + v2, params.drift_confidence)
    )
    warning = (
        testable
        & ~change
        & (diff >= _hddm_w_eps(v1 + v2, params.warning_confidence))
    )
    return HDDMWState(n, z, v, z1, v1, n2, z2, v2), (warning, change)


def _hddm_w_masks(
    state: HDDMWState, errs: jax.Array, valid: jax.Array, params: HDDMWParams
):
    """Flat ``[N]`` prefix pass → ``(end_state, warning[N], change[N])``.

    Every sequential recurrence here is an affine map ``y → Ay + B`` per
    element — EWMA absorb is ``(1−λ, λx)``, initialise-to-first-element is
    ``(0, x)``, reset is ``(0, 0)``, invalid is the identity ``(1, 0)`` —
    and affine maps compose associatively, so one ``associative_scan`` per
    (z, v) pair closes each chain. The cut needs no payload scan: its key
    ``z + ε(v)`` depends only on prefix statistics, strict improvements are
    exactly where the inclusive running min moves, and the frozen ``(z1,
    v1)`` is a gather at the last improvement. Those improvement positions
    then delimit the monitoring EWMA's reset segments."""
    _validate_hddm_w(params)
    lam = jnp.float32(params.lam)
    one_m = 1.0 - lam
    n_el = errs.shape[0]

    v_i = valid.astype(jnp.int32)
    n = state.count + jnp.cumsum(v_i)

    def compose(f, g):  # apply `f`, then `g` — two independent affine maps
        az1, bz1, av1, bv1 = f
        az2, bz2, av2, bv2 = g
        return (
            az2 * az1,
            az2 * bz1 + bz2,
            av2 * av1,
            av2 * bv1 + bv2,
        )

    # Stream EWMA (z, v): the first-ever valid element initialises.
    is_init = valid & (n == 1)
    absorb = valid & ~is_init
    f0, f1 = jnp.float32(0.0), jnp.float32(1.0)
    az = jnp.where(is_init, f0, jnp.where(absorb, one_m, f1))
    bz = jnp.where(is_init, errs, jnp.where(absorb, lam * errs, f0))
    av = jnp.where(is_init, f0, jnp.where(absorb, one_m * one_m, f1))
    bv = jnp.where(is_init, f1, jnp.where(absorb, lam * lam, f0))
    acz, bcz, acv, bcv = lax.associative_scan(compose, (az, bz, av, bv))
    z = acz * state.z + bcz
    v = acv * state.v + bcv

    # Cut: strict running min of z + ε(v) (invalid elements can't cut).
    key = jnp.where(
        valid, z + _hddm_w_eps(v, params.drift_confidence), jnp.float32(_INF)
    )
    carried_key = jnp.where(
        state.v1 > 0,
        state.z1 + _hddm_w_eps(state.v1, params.drift_confidence),
        jnp.float32(_INF),
    )
    incl_min = lax.cummin(key)
    excl_min = jnp.concatenate(
        [jnp.full((1,), _INF, key.dtype), incl_min[:-1]]
    )
    improve = valid & (key < jnp.minimum(excl_min, carried_key))

    idx = jnp.where(improve, jnp.arange(n_el, dtype=jnp.int32), jnp.int32(-1))
    last_imp = lax.cummax(idx)
    has_cut = last_imp >= 0
    gi = jnp.clip(last_imp, 0)
    z1 = jnp.where(has_cut, z[gi], state.z1)
    v1 = jnp.where(has_cut, v[gi], state.v1)

    # Monitoring EWMA (z2, v2): segmented by the improvements. n2 counts the
    # absorbed elements of the live segment (improvement positions absorb
    # nothing — the cut-moving element never joins the sample it resets).
    e2 = valid & ~improve
    ce = jnp.cumsum(e2.astype(jnp.int32))
    n2 = jnp.where(has_cut, ce - ce[gi], state.n2 + ce)
    is_init2 = e2 & (n2 == 1)
    absorb2 = e2 & ~is_init2
    rz = improve | is_init2  # A = 0 positions of the z2/v2 chains
    az2 = jnp.where(rz, f0, jnp.where(absorb2, one_m, f1))
    bz2 = jnp.where(
        improve, f0, jnp.where(is_init2, errs, jnp.where(absorb2, lam * errs, f0))
    )
    av2 = jnp.where(rz, f0, jnp.where(absorb2, one_m * one_m, f1))
    bv2 = jnp.where(
        improve, f0, jnp.where(is_init2, f1, jnp.where(absorb2, lam * lam, f0))
    )
    acz2, bcz2, acv2, bcv2 = lax.associative_scan(
        compose, (az2, bz2, av2, bv2)
    )
    z2 = acz2 * state.z2 + bcz2
    v2 = acv2 * state.v2 + bcv2

    testable = e2 & (n2 >= 1)
    diff = z2 - z1
    change = testable & (
        diff >= _hddm_w_eps(v1 + v2, params.drift_confidence)
    )
    warning = (
        testable
        & ~change
        & (diff >= _hddm_w_eps(v1 + v2, params.warning_confidence))
    )
    end_state = HDDMWState(
        n[-1], z[-1], v[-1], z1[-1], v1[-1], n2[-1], z2[-1], v2[-1]
    )
    return end_state, warning, change


def hddm_w_batch(
    state: HDDMWState,
    errs: jax.Array,
    valid: jax.Array,
    params: HDDMWParams = HDDMWParams(),
) -> tuple[HDDMWState, DDMBatchResult]:
    """Vectorised microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _hddm_w_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def hddm_w_window(
    state: HDDMWState,
    errs: jax.Array,
    valid: jax.Array,
    params: HDDMWParams = HDDMWParams(),
) -> tuple[HDDMWState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _hddm_w_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)


# --------------------------------------------------------------------------
# KSWIN
# --------------------------------------------------------------------------


class KSWINState(NamedTuple):
    """Carried KSWIN state (fixed shapes; vmap adds axes).

    ``buf[w]`` holds the last ``min(t, w)`` valid error indicators
    *right-aligned* (newest at index w−1); slots left of ``w − t`` are
    zero-padding that no gated test can reach. ``t`` counts elements
    absorbed since reset."""

    t: jax.Array  # i32: elements absorbed since reset
    buf: jax.Array  # f32 [window_size]: last w elements, right-aligned


def kswin_init(params: KSWINParams = KSWINParams()) -> KSWINState:
    return KSWINState(
        jnp.int32(0), jnp.zeros((params.window_size,), jnp.float32)
    )


def _validate_kswin(params: KSWINParams) -> None:
    """Reject out-of-range concrete params at every public kernel entry
    (the ``_validate_ph`` pattern; like ADWIN's these size arrays, so
    there is no traced-params path to wave through)."""
    if not 0.0 < float(params.alpha) < 1.0:
        raise ValueError(
            f"KSWINParams.alpha must be in (0, 1), got {params.alpha}"
        )
    if not 0 < int(params.stat_size) < int(params.window_size):
        raise ValueError(
            "KSWINParams needs 0 < stat_size < window_size, got "
            f"stat_size={params.stat_size}, window_size={params.window_size}"
        )


def _kswin_crit(params: KSWINParams) -> float:
    """Closed-form two-sample KS critical value at significance α:
    c(α)·sqrt((n₁+n₂)/(n₁·n₂)) with c(α) = sqrt(−ln(α/2)/2), n₁ =
    stat_size (recent), n₂ = window_size − stat_size (older). A Python
    float — the whole decision boundary is a trace-time constant."""
    import math

    r = int(params.stat_size)
    m = int(params.window_size) - r
    c = math.sqrt(-math.log(float(params.alpha) / 2.0) / 2.0)
    return c * math.sqrt((r + m) / (r * m))


def kswin_step(
    state: KSWINState, err: jax.Array, params: KSWINParams = KSWINParams()
) -> tuple[KSWINState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec): shift the window, then — once it is
    full — compare the newest ``stat_size`` elements' mean against the
    older remainder's mean at the KS critical value.

    Why a mean comparison *is* the KS test here: the engines feed 0/1
    error indicators, whose empirical CDF steps only at 0 — so the KS
    statistic ``sup_x |F₁(x) − F₂(x)|`` is exactly ``|(1−p̂₁) − (1−p̂₂)| =
    |p̂₁ − p̂₂|``. No warning zone (the reference implementation reports
    none); ``warning`` is constantly False.
    """
    _validate_kswin(params)
    w, r = int(params.window_size), int(params.stat_size)
    m = w - r
    buf = jnp.roll(state.buf, -1).at[-1].set(err.astype(jnp.float32))
    t = state.t + 1
    p_recent = jnp.sum(buf[m:]) / r
    p_old = jnp.sum(buf[:m]) / m
    change = (t >= w) & (
        jnp.abs(p_recent - p_old) > jnp.float32(_kswin_crit(params))
    )
    return KSWINState(t, buf), (jnp.bool_(False), change)



def _ring_compact(buf: jax.Array, errs: jax.Array, valid: jax.Array):
    """Shared skeleton of the ring-buffer detectors (KSWIN, STEPD): compact
    the valid elements into consecutive slots (invalid → drop bin), prepend
    the carried right-aligned window, and return everything a windowed
    statistic needs —

    ``full``  [w+N]: carried buffer ++ compacted batch,
    ``ps``    [w+N+1]: its zero-led prefix sums (``ps[k] = sum(full[:k])``),
    ``j``     [N]: each position's compaction index (clipped ``vcnt−1``),
    ``vcnt``  [N]: running valid count, ``nv`` its total,
    ``end_buf`` [w]: the next carried window (last w stream elements).

    The w-offset convention: the valid element with compaction index ``j``
    sits at ``full[w + j]``, so a window of the last ``k`` elements ending
    at it sums to ``ps[w+j+1] − ps[w+j+1−k]``."""
    n_el = errs.shape[0]
    w = buf.shape[0]
    vcnt = jnp.cumsum(valid.astype(jnp.int32))
    nv = vcnt[-1]
    slot = jnp.where(valid, vcnt - 1, n_el)
    ev = errs.astype(jnp.float32) * valid
    compact = jnp.zeros((n_el + 1,), jnp.float32).at[slot].set(ev)[:n_el]
    full = jnp.concatenate([buf, compact])
    ps = jnp.concatenate([jnp.zeros((1,), jnp.float32), jnp.cumsum(full)])
    j = jnp.clip(vcnt - 1, 0, n_el - 1)
    end_buf = lax.dynamic_slice_in_dim(full, nv, w)
    return full, ps, j, vcnt, nv, end_buf


def _kswin_masks(
    state: KSWINState, errs: jax.Array, valid: jax.Array, params: KSWINParams
):
    """Flat ``[N]`` pass → ``(end_state, warning[N], change[N])``.

    Fully vectorised — the zoo's only *windowed* statistic needs no scan
    at all: compact the valid elements, concatenate the carried window,
    and every position's recent/old sums are two differences of one
    prefix-sum vector. The new carried window is a dynamic slice."""
    _validate_kswin(params)
    w, r = int(params.window_size), int(params.stat_size)
    m = w - r

    _full, ps, j, vcnt, nv, end_buf = _ring_compact(state.buf, errs, valid)
    # Window of element j: full[(j+1) .. (w+j)] — recent r, then older m.
    hi = ps[w + j + 1]
    mid = ps[w + j + 1 - r]
    lo = ps[j + 1]
    p_recent = (hi - mid) / r
    p_old = (mid - lo) / m
    t_at = state.t + vcnt
    change = (
        valid
        & (t_at >= w)
        & (jnp.abs(p_recent - p_old) > jnp.float32(_kswin_crit(params)))
    )
    warning = jnp.zeros_like(change)

    end_state = KSWINState(state.t + nv, end_buf)
    return end_state, warning, change


def kswin_batch(
    state: KSWINState,
    errs: jax.Array,
    valid: jax.Array,
    params: KSWINParams = KSWINParams(),
) -> tuple[KSWINState, DDMBatchResult]:
    """Vectorised microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _kswin_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def kswin_window(
    state: KSWINState,
    errs: jax.Array,
    valid: jax.Array,
    params: KSWINParams = KSWINParams(),
) -> tuple[KSWINState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _kswin_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)


# --------------------------------------------------------------------------
# STEPD
# --------------------------------------------------------------------------


class STEPDState(NamedTuple):
    """Carried STEPD state (fixed shapes; vmap adds axes).

    The same right-aligned ring buffer as :class:`KSWINState` (newest at
    index w−1; slots left of ``w − t`` are unreachable zero-padding) plus
    the since-reset error total — the "overall" side of the test."""

    t: jax.Array  # i32: elements absorbed since reset
    total: jax.Array  # f32: errors since reset
    buf: jax.Array  # f32 [window_size]: last w elements, right-aligned


def stepd_init(params: STEPDParams = STEPDParams()) -> STEPDState:
    return STEPDState(
        jnp.int32(0),
        jnp.float32(0.0),
        jnp.zeros((params.window_size,), jnp.float32),
    )


def _validate_stepd(params: STEPDParams) -> None:
    """Reject out-of-range concrete params at every public kernel entry
    (the ``_validate_kswin`` pattern — array-sizing knobs, no traced
    path)."""
    for knob in ("alpha_drift", "alpha_warning"):
        if not 0.0 < float(getattr(params, knob)) < 1.0:
            raise ValueError(
                f"STEPDParams.{knob} must be in (0, 1), got "
                f"{getattr(params, knob)}"
            )
    if int(params.window_size) < 2:
        raise ValueError(
            f"STEPDParams.window_size must be >= 2, got {params.window_size}"
        )


def _z_crit(alpha: float) -> float:
    """Upper critical value of the standard normal at two-sided level α:
    z with 2·(1 − Φ(z)) = α. Solved once at trace time by bisection on
    ``erf`` (no scipy dependency; 80 iterations ≈ double precision)."""
    import math

    target = 1.0 - alpha / 2.0  # Φ(z) = target
    lo, hi = 0.0, 10.0
    for _ in range(80):
        mid = 0.5 * (lo + hi)
        if 0.5 * (1.0 + math.erf(mid / math.sqrt(2.0))) < target:
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)


def _stepd_signal(t, total, recent_sum, params: STEPDParams):
    """The two-proportion test shared by the scalar step and the batch
    pass. ``t``/``total`` are since-reset counts, ``recent_sum`` the error
    sum of the last ``w`` elements; all inputs may be vectors.

    Nishida & Yamauchi 2007: with recent proportion p̂_r over n_r = w and
    older proportion p̂_o over n_o = t − w, pooled p̂ = total/t, reject
    when

        |p̂_o − p̂_r| − ½(1/n_o + 1/n_r)
        ───────────────────────────────── > z_crit(α)
          sqrt(p̂(1−p̂)(1/n_o + 1/n_r))

    — drift at ``alpha_drift``, warning at ``alpha_warning``, both gated
    on the recent rate being the *higher* one (error increase; the
    engines' rotate-on-drift loop consumes no "improvement" signal) and
    on ``t ≥ 2w`` (both sides populated)."""
    w = int(params.window_size)
    n_o = (t - w).astype(jnp.float32)
    n_of = jnp.maximum(n_o, 1.0)
    p_r = recent_sum / w
    p_o = (total - recent_sum) / n_of
    p_hat = total / jnp.maximum(t, 1).astype(jnp.float32)
    inv = 1.0 / n_of + 1.0 / w
    num = jnp.abs(p_o - p_r) - 0.5 * inv
    den = jnp.sqrt(jnp.maximum(p_hat * (1.0 - p_hat) * inv, 1e-30))
    z = num / den
    gate = (t >= 2 * w) & (p_r > p_o)
    change = gate & (z > jnp.float32(_z_crit(params.alpha_drift)))
    warning = (
        gate & ~change & (z > jnp.float32(_z_crit(params.alpha_warning)))
    )
    return warning, change


def stepd_step(
    state: STEPDState, err: jax.Array, params: STEPDParams = STEPDParams()
) -> tuple[STEPDState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec — see :func:`_stepd_signal`)."""
    _validate_stepd(params)
    buf = jnp.roll(state.buf, -1).at[-1].set(err.astype(jnp.float32))
    t = state.t + 1
    total = state.total + err.astype(jnp.float32)
    warning, change = _stepd_signal(t, total, jnp.sum(buf), params)
    return STEPDState(t, total, buf), (warning, change)


def _stepd_masks(
    state: STEPDState, errs: jax.Array, valid: jax.Array, params: STEPDParams
):
    """Flat ``[N]`` pass → ``(end_state, warning[N], change[N])``.

    The same scan-free skeleton as :func:`_kswin_masks`: compact the valid
    elements, concatenate the carried ring buffer, and every position's
    recent-window sum is one difference of one prefix-sum vector; the
    overall totals are an ordinary cumsum."""
    _validate_stepd(params)
    w = int(params.window_size)

    _full, ps, j, vcnt, nv, end_buf = _ring_compact(state.buf, errs, valid)
    ev = errs.astype(jnp.float32) * valid
    recent = ps[w + j + 1] - ps[j + 1]
    t_at = state.t + vcnt
    total_at = state.total + jnp.cumsum(ev)
    warning, change = _stepd_signal(t_at, total_at, recent, params)
    warning = warning & valid
    change = change & valid

    end_state = STEPDState(state.t + nv, state.total + jnp.sum(ev), end_buf)
    return end_state, warning, change


def stepd_batch(
    state: STEPDState,
    errs: jax.Array,
    valid: jax.Array,
    params: STEPDParams = STEPDParams(),
) -> tuple[STEPDState, DDMBatchResult]:
    """Vectorised microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _stepd_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def stepd_window(
    state: STEPDState,
    errs: jax.Array,
    valid: jax.Array,
    params: STEPDParams = STEPDParams(),
) -> tuple[STEPDState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _stepd_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def make_detector(
    name: str,
    *,
    ddm: DDMParams = DDMParams(),
    ph: PHParams = PHParams(),
    eddm: EDDMParams = EDDMParams(),
    hddm: HDDMParams = HDDMParams(),
    hddm_w: HDDMWParams = HDDMWParams(),
    adwin: ADWINParams = ADWINParams(),
    kswin: KSWINParams = KSWINParams(),
    stepd: STEPDParams = STEPDParams(),
) -> DetectorKernel:
    """Build a :class:`DetectorKernel` by config name (``RunConfig.detector``)."""
    if name == "ddm":
        return DetectorKernel(
            "ddm",
            ddm_init,
            lambda s, e, v: ddm_batch(s, e, v, ddm),
            lambda s, e, v: ddm_window(s, e, v, ddm),
            ddm,
        )
    if name == "ph":
        if not 0.0 <= ph.alpha <= 1.0:
            raise ValueError(
                f"PHParams.alpha must be in [0, 1], got {ph.alpha}"
            )
        if ph.threshold <= 0.0:
            raise ValueError(
                f"PHParams.threshold must be > 0, got {ph.threshold} "
                "(0 = auto: let api.prepare resolve it via "
                "config.auto_ph_threshold, or pass an explicit λ)"
            )
        return DetectorKernel(
            "ph",
            ph_init,
            lambda s, e, v: ph_batch(s, e, v, ph),
            lambda s, e, v: ph_window(s, e, v, ph),
            ph,
        )
    if name == "eddm":
        return DetectorKernel(
            "eddm",
            eddm_init,
            lambda s, e, v: eddm_batch(s, e, v, eddm),
            lambda s, e, v: eddm_window(s, e, v, eddm),
            eddm,
        )
    if name == "hddm":
        if not 0.0 < hddm.drift_confidence < 1.0:
            raise ValueError(
                f"HDDMParams.drift_confidence must be in (0, 1), got "
                f"{hddm.drift_confidence}"
            )
        if not 0.0 < hddm.warning_confidence < 1.0:
            raise ValueError(
                f"HDDMParams.warning_confidence must be in (0, 1), got "
                f"{hddm.warning_confidence}"
            )
        return DetectorKernel(
            "hddm",
            hddm_init,
            lambda s, e, v: hddm_batch(s, e, v, hddm),
            lambda s, e, v: hddm_window(s, e, v, hddm),
            hddm,
        )
    if name == "hddm_w":
        _validate_hddm_w(hddm_w)
        return DetectorKernel(
            "hddm_w",
            hddm_w_init,
            lambda s, e, v: hddm_w_batch(s, e, v, hddm_w),
            lambda s, e, v: hddm_w_window(s, e, v, hddm_w),
            hddm_w,
        )
    if name == "adwin":
        from .adwin import _validate_adwin, adwin_batch, adwin_init, adwin_window

        _validate_adwin(adwin)
        return DetectorKernel(
            "adwin",
            lambda: adwin_init(adwin),
            lambda s, e, v: adwin_batch(s, e, v, adwin),
            lambda s, e, v: adwin_window(s, e, v, adwin),
            adwin,
        )
    if name == "kswin":
        _validate_kswin(kswin)
        return DetectorKernel(
            "kswin",
            lambda: kswin_init(kswin),
            lambda s, e, v: kswin_batch(s, e, v, kswin),
            lambda s, e, v: kswin_window(s, e, v, kswin),
            kswin,
        )
    if name == "stepd":
        _validate_stepd(stepd)
        return DetectorKernel(
            "stepd",
            lambda: stepd_init(stepd),
            lambda s, e, v: stepd_batch(s, e, v, stepd),
            lambda s, e, v: stepd_window(s, e, v, stepd),
            stepd,
        )
    raise ValueError(
        f"unknown detector {name!r}; expected one of {DETECTOR_NAMES}"
    )
