"""ADWIN: adaptive-windowing drift detection (Bifet & Gavaldà 2007).

The zoo's other detectors (``ops.ddm``, ``ops.detectors``) are O(1)-state
recurrences whose batch passes close into prefix sums and associative
scans. ADWIN is structurally different: it maintains a *variable-length*
window of recent error indicators in an exponential histogram — up to ``M``
buckets per dyadic size 2^k, merged oldest-first on overflow — and signals
change when any split of that window into old/new halves shows a mean gap
exceeding the cut bound

    ε_cut = sqrt((2/m)·σ²_W·ln(2/δ′)) + (2/(3m))·ln(2/δ′),
    1/m = 1/n₀ + 1/n₁,   δ′ = δ/n

(paper Thm 3.2 form, with the classic implementation's per-split δ′ = δ/n).
Which buckets merge when is data-*independent* (a pure function of the
insert count), but the histogram update is inherently sequential per
element, so this kernel is the zoo's one scan-of-steps member: a
``lax.scan`` over elements whose step does O(L·M) fixed-shape vector work
(bucket cascade + masked cut scan). Amortisation comes from ``clock`` —
the cut scan only *counts* (is only unmasked) every clock-th element, the
classic default 32 — and from the engines' vmap over partitions, which
shares one scan across every lane. Budget ~1–3 µs/element of scan overhead
per sequential step; prefer the prefix-scan detectors where their
assumptions fit and ADWIN where its distribution-free adaptive window is
worth the sequential cost.

Two deliberate simplifications, both documented invariants of this
framework rather than of the paper:

* **Bernoulli inputs.** The engines feed 0/1 error indicators
  (``DDM_Process.py:117,126`` semantics), so the window variance needed by
  ε_cut is ``p(1−p)`` with ``p = window mean`` — bucket variances
  (the paper's within-bucket Welford terms) need not be tracked at all.
  Feeding non-indicator reals would silently mis-scale ε_cut; the scalar
  spec documents the contract.
* **Reset-on-change, not shrink-on-change.** ADWIN classically *shrinks*
  the window (dropping oldest buckets) when a cut fires and carries on;
  this framework's engines own the reset — on change the caller discards
  detector state and retrains (the reference's protocol at
  ``DDM_Process.py:207-210``, shared by every zoo member). The kernel
  therefore only ever *reports* the first violated cut; elements after a
  batch's first change are dead and the returned end-state is meaningful
  only when ``first_change == -1`` (``ops.ddm`` contract). The histogram
  still forgets at capacity (oldest bucket dropped, totals adjusted) so
  state stays bounded on drift-free streams.

No warning zone: the statistic has no natural warning analog (unlike DDM's
two-level minima test), and the classic implementations report none —
``first_warning`` is always −1 for this detector.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import ADWINParams
from .ddm import DDMBatchResult, DDMWindowResult, summarise_batch, summarise_window


class ADWINState(NamedTuple):
    """Carried ADWIN state (fixed shapes; vmap adds axes).

    ``sums[L, C]`` holds bucket sums oldest-first per level (level k buckets
    span 2^k elements; ``C = max_buckets + 1`` slots so one overflow fits
    before the cascade trims); ``counts[L]`` the live buckets per level.
    ``n``/``total`` are the window length and sum (they lag ``t``, the
    absorb counter driving the clock, once capacity forgetting starts)."""

    t: jax.Array  # i32: elements absorbed since reset (clock phase)
    n: jax.Array  # i32: elements currently represented in the window
    total: jax.Array  # f32: window sum
    sums: jax.Array  # f32 [L, C]: bucket sums, oldest-first per level
    counts: jax.Array  # i32 [L]: live buckets per level


def adwin_init(params: ADWINParams = ADWINParams()) -> ADWINState:
    L, C = params.max_levels, params.max_buckets + 1
    return ADWINState(
        t=jnp.int32(0),
        n=jnp.int32(0),
        total=jnp.float32(0.0),
        sums=jnp.zeros((L, C), jnp.float32),
        counts=jnp.zeros((L,), jnp.int32),
    )


def _validate_adwin(params: ADWINParams) -> None:
    """Reject out-of-range concrete params at every public kernel entry
    (the ``_validate_ph`` pattern). These are Python ints/floats in
    practice — they size arrays and gate masks — so unlike the other
    zoo members there is no traced-params path to wave through."""
    if not 0.0 < float(params.delta) < 1.0:
        raise ValueError(f"ADWINParams.delta must be in (0, 1), got {params.delta}")
    if int(params.clock) < 1:
        raise ValueError(f"ADWINParams.clock must be >= 1, got {params.clock}")
    if int(params.max_buckets) < 2:
        raise ValueError(
            f"ADWINParams.max_buckets must be >= 2, got {params.max_buckets}"
        )
    if not 1 <= int(params.max_levels) <= 30:
        raise ValueError(
            "ADWINParams.max_levels must be in [1, 30] (2^k bucket sizes in "
            f"int32), got {params.max_levels}"
        )
    capacity = int(params.max_buckets) * ((1 << int(params.max_levels)) - 1)
    if capacity > 2**31 - 1:
        raise ValueError(
            "ADWINParams window capacity max_buckets*(2^max_levels - 1) = "
            f"{capacity} overflows the int32 n counter; shrink max_levels "
            "or max_buckets (the defaults' ~84M is far past any practical "
            "between-reset span)"
        )
    if int(params.min_side) < 1 or int(params.min_window) < 2 * int(params.min_side):
        raise ValueError(
            "ADWINParams needs min_side >= 1 and min_window >= 2*min_side, "
            f"got min_window={params.min_window}, min_side={params.min_side}"
        )


def adwin_step(
    state: ADWINState, err: jax.Array, params: ADWINParams = ADWINParams()
) -> tuple[ADWINState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec): insert → cascade → (clocked) cut scan.

    ``err`` must be a 0/1 error indicator (module docstring: the window
    variance is derived as ``p(1−p)``). Returns ``(state, (warning,
    change))`` with ``warning`` constantly False.
    """
    _validate_adwin(params)
    L, M = int(params.max_levels), int(params.max_buckets)
    C = M + 1

    # --- insert: a fresh single-element bucket at level 0 --------------
    c0 = state.counts[0]  # ≤ M post-cascade, so slot c0 ≤ C-1 exists
    sums = state.sums.at[0, c0].set(err.astype(jnp.float32))
    counts = state.counts.at[0].add(1)
    t = state.t + 1
    n = state.n + 1
    total = state.total + err.astype(jnp.float32)

    # --- cascade: one top-down pass suffices (each level gains ≤ 1) ----
    def level(k, carry):
        sums, counts, n, total = carry
        over = counts[k] > M
        top = k == L - 1
        row = sums[k]
        merged = row[0] + row[1]
        # Candidate rows: drop the oldest two (merge) or the oldest one
        # (top-level capacity forgetting). C is tiny, rolls are free.
        drop2 = jnp.roll(row, -2).at[-2:].set(0.0)
        drop1 = jnp.roll(row, -1).at[-1].set(0.0)
        new_row = jnp.where(over, jnp.where(top, drop1, drop2), row)
        sums = sums.at[k].set(new_row)
        counts = counts.at[k].add(jnp.where(over, jnp.where(top, -1, -2), 0))
        # Push the merged bucket one level up (guarded index write: when at
        # the top, tgt folds back to k and the delta/value are no-ops).
        push = over & ~top
        tgt = jnp.minimum(k + 1, L - 1)
        slot = counts[tgt]  # ≤ M pre-push (invariant), so the slot exists
        cur = sums[tgt, slot]
        sums = sums.at[tgt, slot].set(jnp.where(push, merged, cur))
        counts = counts.at[tgt].add(jnp.where(push, 1, 0))
        # Top-level forgetting: the dropped oldest bucket leaves the window.
        n = n - jnp.where(over & top, jnp.int32(1 << (L - 1)), 0)
        total = total - jnp.where(over & top, row[0], 0.0)
        return sums, counts, n, total

    sums, counts, n, total = lax.fori_loop(
        0, L, level, (sums, counts, n, total)
    )

    # --- clocked cut scan over every bucket boundary -------------------
    do_check = (t % params.clock == 0) & (n >= params.min_window)
    # Flatten oldest→newest: highest level first, slot 0 first within one.
    lvl_sizes = (jnp.int32(1) << jnp.arange(L, dtype=jnp.int32))[::-1]
    valid_slot = jnp.arange(C, dtype=jnp.int32)[None, :] < counts[::-1, None]
    szs = jnp.where(valid_slot, lvl_sizes[:, None], 0).reshape(-1)
    sms = jnp.where(valid_slot, sums[::-1], 0.0).reshape(-1)
    n0 = jnp.cumsum(szs)
    s0 = jnp.cumsum(sms)
    n1 = n - n0
    s1 = total - s0
    n0f = jnp.maximum(n0, 1).astype(jnp.float32)
    n1f = jnp.maximum(n1, 1).astype(jnp.float32)
    mu0 = s0 / n0f
    mu1 = s1 / n1f
    p = total / jnp.maximum(n, 1).astype(jnp.float32)
    var_w = p * (1.0 - p)  # Bernoulli inputs: σ²_W = p(1−p)
    # ln(2/δ′) with δ′ = δ/n
    lg = jnp.float32(math.log(2.0 / float(params.delta))) + jnp.log(
        jnp.maximum(n, 1).astype(jnp.float32)
    )
    inv_m = 1.0 / n0f + 1.0 / n1f
    eps_cut = jnp.sqrt(2.0 * inv_m * var_w * lg) + (2.0 / 3.0) * inv_m * lg
    testable = (
        valid_slot.reshape(-1)
        & (n0 >= params.min_side)
        & (n1 >= params.min_side)
    )
    viol = testable & (jnp.abs(mu0 - mu1) >= eps_cut)
    change = do_check & viol.any()

    new_state = ADWINState(t, n, total, sums, counts)
    return new_state, (jnp.bool_(False), change)


def _adwin_masks(
    state: ADWINState, errs: jax.Array, valid: jax.Array, params: ADWINParams
):
    """Flat ``[N]`` scan-of-steps → ``(end_state, warning[N], change[N])``.

    Invalid (padded) elements are the identity: the step runs, its state is
    discarded leaf-wise. XLA computes both sides of the select, but the
    step is O(L·M) scalar-vector work — the scan's sequential latency, not
    its per-step FLOPs, is the cost (module docstring)."""
    _validate_adwin(params)

    def body(carry, ev):
        e, v = ev
        stepped, (_w, ch) = adwin_step(carry, e, params)
        keep = jax.tree.map(
            lambda new, old: jnp.where(v, new, old), stepped, carry
        )
        return keep, ch & v

    end_state, change = lax.scan(body, state, (errs, valid))
    warning = jnp.zeros_like(change)
    return end_state, warning, change


def adwin_batch(
    state: ADWINState,
    errs: jax.Array,
    valid: jax.Array,
    params: ADWINParams = ADWINParams(),
) -> tuple[ADWINState, DDMBatchResult]:
    """Microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _adwin_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def adwin_window(
    state: ADWINState,
    errs: jax.Array,
    valid: jax.Array,
    params: ADWINParams = ADWINParams(),
) -> tuple[ADWINState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _adwin_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)
