"""ADWIN: adaptive-windowing drift detection (Bifet & Gavaldà 2007).

The zoo's other detectors (``ops.ddm``, ``ops.detectors``) are O(1)-state
recurrences whose batch passes close into prefix sums and associative
scans. ADWIN is structurally different: it maintains a *variable-length*
window of recent error indicators in an exponential histogram — up to ``M``
buckets per dyadic size, merged oldest-first on overflow — and signals
change when any split of that window into old/new halves shows a mean gap
exceeding the cut bound

    ε_cut = sqrt((2/m)·σ²_W·ln(2/δ′)) + (2/(3m))·ln(2/δ′),
    1/m = 1/n₀ + 1/n₁,   δ′ = δ/n

(paper Thm 3.2 form, with the classic implementation's per-split δ′ = δ/n).

**The TPU restructuring.** The histogram update is inherently sequential,
and on TPU a ``lax.scan`` iteration costs ~tens of µs of loop latency
regardless of how small its body is — a per-*element* scan (the classic
formulation) was measured at ~25 µs/element on hardware, i.e. seconds for
a benchmark stream. But ADWIN's own amortisation knob already concedes
that per-element checking is wasted work: the classic implementations only
test cuts every ``clock``-th element (default 32). This kernel therefore
makes the *bucket granularity itself* the clock chunk: every bucket at
level k spans exactly ``clock·2^k`` elements, a completed chunk's sum is a
plain masked segment-sum over the batch (vector work), and the sequential
scan runs over *chunks*, not elements — ``clock``× fewer iterations, with
cut tests at exactly the same stream positions as the element formulation
(a chunk completes precisely when ``t % clock == 0``). The trade is split
*resolution*: cuts land on chunk boundaries, so the window can only be
split at ``clock``-element granularity — the same spirit as the paper's M
(bounded buckets-per-level) approximation, one level coarser, and far
finer than the concept lengths the engines care about. Elements that do
not complete a chunk can never signal.

Two further simplifications, both documented invariants of this framework
rather than of the paper:

* **Bernoulli inputs.** The engines feed 0/1 error indicators
  (``DDM_Process.py:117,126`` semantics), so the window variance needed by
  ε_cut is ``p(1−p)`` with ``p = window mean`` — bucket variances
  (the paper's within-bucket Welford terms) need not be tracked at all.
  Feeding non-indicator reals would silently mis-scale ε_cut; the scalar
  spec documents the contract, and the opt-in debug guard
  (``DDD_DEBUG_INDICATORS=1`` or :func:`set_debug_indicator_checks`)
  enforces it with a host assert at every kernel entry. Because errors are integral, every sum
  (bucket, pending chunk, window total) is carried in **int32**, exact up
  to the validated int32 window capacity — a float32 total would round
  away +1 increments past 2²⁴ (~16.7 M) accumulated errors on long
  drift-free streams, silently corrupting the window mean (ADVICE r4);
  means/ε_cut convert to f32 only at the one divide.
* **Reset-on-change, not shrink-on-change.** ADWIN classically *shrinks*
  the window (dropping oldest buckets) when a cut fires and carries on;
  this framework's engines own the reset — on change the caller discards
  detector state and retrains (the reference's protocol at
  ``DDM_Process.py:207-210``, shared by every zoo member). The kernel
  therefore only ever *reports* a violated cut; elements after a batch's
  first change are dead and the returned end-state is meaningful only when
  ``first_change == -1`` (``ops.ddm`` contract). The histogram still
  forgets at capacity (oldest bucket dropped, totals adjusted) so state
  stays bounded on drift-free streams.

No warning zone: the statistic has no natural warning analog (unlike DDM's
two-level minima test), and the classic implementations report none —
``first_warning`` is always −1 for this detector.
"""

from __future__ import annotations

import math
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import ADWINParams
from .ddm import DDMBatchResult, DDMWindowResult, summarise_batch, summarise_window

# --- opt-in indicator debug guard (advisor round-5 finding) ----------------
#
# The Bernoulli-input contract (module docstring) is otherwise enforced
# only by documentation: a caller feeding real-valued errors (e.g. raw
# losses instead of 0/1 indicators) has them silently truncated toward 0
# by the exact-int32 casts below, corrupting the window mean with no
# error. With the guard on, every public kernel entry inserts a host
# callback that asserts the (valid) inputs are exact 0/1 and fails the
# device program loudly (XlaRuntimeError wrapping the ValueError) instead.
# Opt-in because the callback is a host round-trip per traced call site —
# debug tool, not production path. Enable via the DDD_DEBUG_INDICATORS
# env var or set_debug_indicator_checks(True); takes effect at TRACE
# time, so already-jitted executables are unaffected until re-traced.

_DEBUG_ENV = "DDD_DEBUG_INDICATORS"
_debug_indicators: bool | None = None  # None = defer to the env var


def set_debug_indicator_checks(enabled: bool | None) -> None:
    """Force the 0/1-indicator guard on/off; ``None`` defers to the
    ``DDD_DEBUG_INDICATORS`` env var (the default)."""
    global _debug_indicators
    _debug_indicators = enabled


def _indicator_checks_enabled() -> bool:
    if _debug_indicators is not None:
        return _debug_indicators
    # Conventional boolean env semantics: "0"/"false"/"off"/"no"/"" all
    # mean off — a user exporting DDD_DEBUG_INDICATORS=0 to disable must
    # not get the guard's host round-trip enabled.
    return os.environ.get(_DEBUG_ENV, "").strip().lower() not in (
        "", "0", "false", "off", "no",
    )


def _host_assert_indicator(errs, valid) -> None:
    import numpy as np

    e, v = np.asarray(errs), np.asarray(valid, bool)
    bad = v & ~((e == 0) | (e == 1))
    if bad.any():
        vals = np.unique(e[bad])[:5]
        raise ValueError(
            f"ADWIN received non-indicator error values {vals.tolist()} — "
            "the kernel's exact-int32 sums require 0/1 indicators (module "
            "docstring); real-valued errors would be silently truncated "
            "to 0 by the int32 cast"
        )


def _maybe_check_indicator(errs, valid=None) -> None:
    """Insert the host assert when the guard is enabled (trace-time gate:
    a static no-op — same compiled graph — when off)."""
    if not _indicator_checks_enabled():
        return
    if valid is None:
        valid = jnp.ones(jnp.shape(errs), bool)
    jax.debug.callback(_host_assert_indicator, errs, valid)


class ADWINState(NamedTuple):
    """Carried ADWIN state (fixed shapes; vmap adds axes).

    ``sums[L, C]`` holds bucket sums oldest-first per level (a level-k
    bucket spans ``clock·2^k`` elements; ``C = max_buckets + 1`` slots so
    one overflow fits before the cascade trims); ``counts[L]`` the live
    buckets per level. ``pend_sum`` buffers the current partial chunk
    (its element count is implicit: ``t % clock``). ``n``/``total`` are
    the *bucketed* window length and sum — they exclude the pending
    buffer, and ``n`` lags ``t − t % clock`` once capacity forgetting
    starts."""

    t: jax.Array  # i32: elements absorbed since reset
    pend_sum: jax.Array  # i32: sum of the current partial chunk
    n: jax.Array  # i32: elements represented in the bucketed window
    total: jax.Array  # i32: their sum
    sums: jax.Array  # i32 [L, C]: bucket sums, oldest-first per level
    counts: jax.Array  # i32 [L]: live buckets per level


def adwin_init(params: ADWINParams = ADWINParams()) -> ADWINState:
    L, C = params.max_levels, params.max_buckets + 1
    return ADWINState(
        t=jnp.int32(0),
        pend_sum=jnp.int32(0),
        n=jnp.int32(0),
        total=jnp.int32(0),
        sums=jnp.zeros((L, C), jnp.int32),
        counts=jnp.zeros((L,), jnp.int32),
    )


def _validate_adwin(params: ADWINParams) -> None:
    """Reject out-of-range concrete params at every public kernel entry
    (the ``_validate_ph`` pattern). These are Python ints/floats in
    practice — they size arrays and gate masks — so unlike the other
    zoo members there is no traced-params path to wave through."""
    if not 0.0 < float(params.delta) < 1.0:
        raise ValueError(f"ADWINParams.delta must be in (0, 1), got {params.delta}")
    if int(params.clock) < 1:
        raise ValueError(f"ADWINParams.clock must be >= 1, got {params.clock}")
    if int(params.max_buckets) < 2:
        raise ValueError(
            f"ADWINParams.max_buckets must be >= 2, got {params.max_buckets}"
        )
    if not 1 <= int(params.max_levels) <= 30:
        raise ValueError(
            "ADWINParams.max_levels must be in [1, 30] (2^k bucket sizes in "
            f"int32), got {params.max_levels}"
        )
    capacity = (
        int(params.max_buckets)
        * int(params.clock)
        * ((1 << int(params.max_levels)) - 1)
    )
    if capacity > 2**31 - 1:
        raise ValueError(
            "ADWINParams window capacity max_buckets*clock*(2^max_levels - 1)"
            f" = {capacity} overflows the int32 n counter; shrink max_levels,"
            " max_buckets or clock (the defaults' ~168M is far past any "
            "practical between-reset span)"
        )
    if int(params.min_side) < 1 or int(params.min_window) < 2 * int(params.min_side):
        raise ValueError(
            "ADWINParams needs min_side >= 1 and min_window >= 2*min_side, "
            f"got min_window={params.min_window}, min_side={params.min_side}"
        )


def _flush_chunk(sums, counts, n, total, chunk_sum, live, params: ADWINParams):
    """Insert one completed chunk bucket (masked by ``live``), cascade the
    histogram, and run the cut scan. Shared verbatim by the scalar step
    (one chunk at a time) and the batch kernel's chunk scan.

    Returns ``(sums, counts, n, total, fired)``. When ``live`` is False
    nothing is inserted, no level overflows and ``fired`` is False — the
    body is its own identity, so callers never need a cond.

    **Closed-form cascade (r05).** One insert can trigger at most one
    merge per level, along a *contiguous* chain from level 0 (level k+1
    can only overflow by receiving level k's merge), and every flush
    leaves every level at ≤ M buckets — so level k overflows iff it is
    exactly full AND receives, which makes the whole receive chain
    ``live`` gated by a prefix-AND of ``counts == M``: one shifted
    ``cumprod``, no recurrence at all. Each level's row update is then
    the same two-step transform applied in one ``[L, C]`` vector pass:
    drop the ``2·ovf`` oldest slots (a ``take_along_axis`` gather — the
    sequential semantics merges the two oldest *pre-existing* buckets, so
    dropping before appending is equivalent), and append the received
    bucket (level k+1 gets level k's pre-merge ``sums[k,0]+sums[k,1]``;
    level 0 gets the chunk — the insert IS a receive) at the post-drop
    count via an equality mask; the top level forgets its oldest (shift
    1) instead of pushing up. No scatters, no dynamic control flow,
    bit-identical to the sequential cascade (pinned by the golden traces
    including the textbook clock=1 coincidence). Two dynamic
    formulations were measured and rejected on TPU (A/B at outdoorStream
    ×64, warm): an early-exit ``lax.while_loop`` (~1–2 loop-iteration
    latencies per chunk: p=1 Final Time 0.74 s) and a 20-level static
    Python loop of per-level scatter updates (~5× slower still); this
    closed form runs the same cell at 0.39 s with identical detections.
    """
    L, M = int(params.max_levels), int(params.max_buckets)
    C = M + 1
    clock = int(params.clock)
    i32 = jnp.int32

    live_i = live.astype(i32) if hasattr(live, "astype") else i32(live)

    # --- overflow chain (closed form) ----------------------------------
    # Invariant: pre-flush ``counts[k] <= M`` (each flush leaves every
    # level at <= M). So level k overflows iff it is exactly full AND
    # receives a bucket, and level k+1 receives iff level k overflowed —
    # the receive chain is ``live`` gated by a prefix-AND of
    # ``counts == M``, i.e. one shifted cumprod. No scalar recurrence.
    full = (counts == M).astype(i32)  # [L]
    chain = jnp.concatenate([jnp.ones((1,), i32), jnp.cumprod(full)])[:L]
    received = live_i * chain  # i32 [L]: gets a new bucket this flush
    ovf = received * full  # i32 [L]: merges (top: forgets) this flush
    top_ovf = ovf[L - 1]

    # --- one vectorised [L, C] row transform ---------------------------
    # shift = how many oldest slots each level drops (2 = merge up,
    # top level 1 = capacity forgetting).
    shift = (2 * ovf).at[L - 1].set(ovf[L - 1])
    col = jnp.arange(C, dtype=i32)[None, :]  # [1, C]
    src = col + shift[:, None]  # [L, C]
    base = jnp.take_along_axis(sums, jnp.minimum(src, C - 1), axis=1)
    base = jnp.where(src < C, base, 0)
    # Value each level receives: level 0 the chunk, level k+1 the merge of
    # level k's two oldest (read from the ORIGINAL rows).
    merged = sums[:, 0] + sums[:, 1]  # [L]
    val = jnp.concatenate([chunk_sum[None].astype(i32), merged[:-1]])
    app_pos = counts - shift  # [L]: append slot after the drop
    new_sums = jnp.where(
        (received[:, None] > 0) & (col == app_pos[:, None]),
        val[:, None],
        base,
    )
    new_counts = counts + received - shift

    # --- window bookkeeping -------------------------------------------
    # The inserted chunk joins the window; the top level's forgotten
    # oldest bucket (the ORIGINAL slot 0) leaves it.
    n = n + live_i * i32(clock) - top_ovf * i32(clock * (1 << (L - 1)))
    total = total + live_i * chunk_sum.astype(i32) - top_ovf * sums[L - 1, 0]
    sums, counts = new_sums, new_counts

    # --- cut scan over every bucket boundary --------------------------
    # Flatten oldest→newest: highest level first, slot 0 first within one.
    lvl_sizes = (jnp.int32(clock) * (1 << jnp.arange(L, dtype=jnp.int32)))[::-1]
    valid_slot = jnp.arange(C, dtype=jnp.int32)[None, :] < counts[::-1, None]
    szs = jnp.where(valid_slot, lvl_sizes[:, None], 0).reshape(-1)
    sms = jnp.where(valid_slot, sums[::-1], 0).reshape(-1)
    n0 = jnp.cumsum(szs)
    s0 = jnp.cumsum(sms)  # exact: int32 counts of 0/1 errors
    n1 = n - n0
    s1 = total - s0
    n0f = jnp.maximum(n0, 1).astype(jnp.float32)
    n1f = jnp.maximum(n1, 1).astype(jnp.float32)
    mu0 = s0.astype(jnp.float32) / n0f
    mu1 = s1.astype(jnp.float32) / n1f
    p = total.astype(jnp.float32) / jnp.maximum(n, 1).astype(jnp.float32)
    var_w = p * (1.0 - p)  # Bernoulli inputs: σ²_W = p(1−p)
    # ln(2/δ′) with δ′ = δ/n
    lg = jnp.float32(math.log(2.0 / float(params.delta))) + jnp.log(
        jnp.maximum(n, 1).astype(jnp.float32)
    )
    inv_m = 1.0 / n0f + 1.0 / n1f
    eps_cut = jnp.sqrt(2.0 * inv_m * var_w * lg) + (2.0 / 3.0) * inv_m * lg
    testable = (
        valid_slot.reshape(-1)
        & (n0 >= params.min_side)
        & (n1 >= params.min_side)
    )
    viol = testable & (jnp.abs(mu0 - mu1) >= eps_cut)
    fired = live & (n >= params.min_window) & viol.any()
    return sums, counts, n, total, fired


def adwin_step(
    state: ADWINState, err: jax.Array, params: ADWINParams = ADWINParams()
) -> tuple[ADWINState, tuple[jax.Array, jax.Array]]:
    """One element (executable spec): buffer into the pending chunk; on the
    ``clock``-th buffered element, flush it as a bucket (insert → cascade
    → cut scan). ``err`` must be a 0/1 error indicator (module docstring).
    Returns ``(state, (warning, change))`` with ``warning`` constantly
    False; ``change`` can only be True at chunk-completing elements.
    """
    _validate_adwin(params)
    _maybe_check_indicator(err)
    t = state.t + 1
    ps = state.pend_sum + err.astype(jnp.int32)
    flush = t % params.clock == 0
    sums, counts, n, total, fired = _flush_chunk(
        state.sums, state.counts, state.n, state.total, ps, flush, params
    )
    new_state = ADWINState(
        t=t,
        pend_sum=jnp.where(flush, jnp.int32(0), ps),
        n=n,
        total=total,
        sums=sums,
        counts=counts,
    )
    return new_state, (jnp.bool_(False), fired)


def _adwin_masks(
    state: ADWINState, errs: jax.Array, valid: jax.Array, params: ADWINParams
):
    """Flat ``[N]`` pass → ``(end_state, warning[N], change[N])``.

    All per-element work is vector math: the chunk each valid element
    feeds is ``(t−1) // clock``, chunk sums are one ``segment_sum``, and a
    chunk completes at the element where ``t % clock == 0``. Only the
    per-chunk histogram update is sequential — a scan of ``⌈N/clock⌉+1``
    iterations over :func:`_flush_chunk` (dead slots are the identity),
    ``clock``× shorter than the element scan it replaces."""
    _validate_adwin(params)
    _maybe_check_indicator(errs, valid)
    clock = int(params.clock)
    n_el = errs.shape[0]
    nc = n_el // clock + 1  # ≥ chunks any (carry, valid-pattern) can finish

    ev = errs.astype(jnp.int32) * valid  # exact int32 0/1 counts
    vcnt = jnp.cumsum(valid.astype(jnp.int32))
    t = state.t + vcnt  # absorb counter at each element
    nvalid = vcnt[-1]
    buffered = state.t % clock  # pending elements carried in (spec invariant)
    n_flush = (buffered + nvalid) // clock

    # Chunk sums: valid element with absorb counter t lands in chunk
    # (t-1)//clock; re-base so the first chunk this batch can finish is 0.
    base = state.t // clock
    sid = jnp.where(valid, (t - 1) // clock - base, nc)  # nc = drop bin
    chunk_sums = jax.ops.segment_sum(ev, sid, num_segments=nc + 1)[:nc]
    chunk_sums = chunk_sums.at[0].add(state.pend_sum)

    def body(carry, xs):
        sums, counts, n, total = carry
        csum, j = xs
        sums, counts, n, total, fired = _flush_chunk(
            sums, counts, n, total, csum, j < n_flush, params
        )
        return (sums, counts, n, total), fired

    # unroll: the chunk scan is iteration-latency-bound on TPU (a lax.scan
    # iteration costs ~10-30µs of loop latency regardless of body size —
    # the same measurement that motivated chunking by `clock` in the first
    # place); unrolling 8 bodies per XLA while-iteration cuts that latency
    # 8× for a body that is a few hundred vector ops (measured r05: the
    # committed-grid ADWIN throughput gap vs the prefix-scan members closes
    # from ~3× to within ~1.5×).
    (sums, counts, n, total), fired = lax.scan(
        body,
        (state.sums, state.counts, state.n, state.total),
        (chunk_sums, jnp.arange(nc, dtype=jnp.int32)),
        unroll=8,
    )

    complete = valid & (t % clock == 0)
    cid = jnp.clip(t // clock - base - 1, 0, nc - 1)
    change = complete & fired[cid]
    warning = jnp.zeros_like(change)

    # Pending buffer after the batch: everything buffered minus flushed.
    all_sum = state.pend_sum + jnp.sum(ev)
    flushed = jnp.where(
        n_flush > 0,
        jnp.cumsum(chunk_sums)[jnp.maximum(n_flush - 1, 0)],
        jnp.int32(0),
    )
    end_state = ADWINState(
        t=state.t + nvalid,
        pend_sum=all_sum - flushed,
        n=n,
        total=total,
        sums=sums,
        counts=counts,
    )
    return end_state, warning, change


def adwin_batch(
    state: ADWINState,
    errs: jax.Array,
    valid: jax.Array,
    params: ADWINParams = ADWINParams(),
) -> tuple[ADWINState, DDMBatchResult]:
    """Microbatch update (contract of :func:`ops.ddm.ddm_batch`)."""
    end_state, warning, change = _adwin_masks(state, errs, valid, params)
    return end_state, summarise_batch(warning, change)


def adwin_window(
    state: ADWINState,
    errs: jax.Array,
    valid: jax.Array,
    params: ADWINParams = ADWINParams(),
) -> tuple[ADWINState, DDMWindowResult]:
    """W batches in one flattened pass (contract of :func:`ops.ddm.ddm_window`)."""
    w, b = errs.shape
    end_state, warning, change = _adwin_masks(
        state, errs.reshape(-1), valid.reshape(-1), params
    )
    return end_state, summarise_window(warning, change, w, b)
