"""Pallas TPU kernel for the DDM window statistic — the framework's hot op.

``ops.ddm`` expresses the per-window detector update (the reference's per-row
``ddm.add_element`` loop, ``DDM_Process.py:144-152``, batched over a
speculative window) as XLA primitives: two ``cumsum``s, an
``associative_scan`` for the running min-with-payload, and a handful of
elementwise ops — several passes over the window. This module fuses the whole
statistic into **one Pallas kernel**: a single VMEM-resident pass computing

  * prefix counts/error-sums (log₂N doubling steps on the VPU),
  * the per-prefix ``p``/``s``/``p+s`` statistics,
  * the running minimum of ``p+s`` with its ``(p_min, s_min)`` payload
    (log₂N doubling steps of a 3-way select),
  * the carried-state merge and the warning/change threshold masks.

Layout: partitions ride the **sublane axis** — the kernel takes ``[P, N]``
planes, so the engine's ``vmap`` over partitions becomes rows of the same
kernel invocation (via ``jax.custom_batching.custom_vmap``), not a sequential
grid. For the benchmark shape (P=16, N=W·B=1600 → padded 1664 lanes) the
whole working set is ~200 KB of VMEM.

Semantics are bit-compatible with :func:`ops.ddm.ddm_window` (same f32
arithmetic, same tie rules); ``tests/test_pallas.py`` checks exact equality
against the XLA path and the NumPy oracle. Select it with
``RunConfig(ddm_kernel='pallas')``; CPU runs fall back to the Pallas
interpreter automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import DDMParams
from .ddm import DDMState, DDMWindowResult, _first_true

_LANES = 128  # TPU lane width: last-dim padding granularity


def _shift_right(x: jax.Array, k: int, fill) -> jax.Array:
    """``out[:, i] = x[:, i-k]`` (``fill`` for ``i < k``), along the lane axis."""
    rolled = pltpu.roll(x, shift=k, axis=1)
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(col >= k, rolled, jnp.asarray(fill, x.dtype))


def _make_kernel(params: DDMParams, n: int):
    """Kernel body for padded window length ``n`` (static)."""
    warn_l = float(params.warning_level)
    out_l = float(params.out_control_level)
    min_n = int(params.min_num_instances)

    def kernel(
        cnt0_ref, esum0_ref, psmin0_ref, pmin0_ref, smin0_ref,
        errs_ref, valid_ref,
        warn_ref, chg_ref,
        cnt1_ref, esum1_ref, psmin1_ref, pmin1_ref, smin1_ref,
    ):
        valid = valid_ref[:]  # [P, N] i32 (0/1)
        v_f = valid.astype(jnp.float32)
        e = errs_ref[:] * v_f

        # Inclusive prefix sums by doubling: log2(n) VPU steps.
        cs_v, cs_e = valid, e
        k = 1
        while k < n:
            cs_v = cs_v + _shift_right(cs_v, k, 0)
            cs_e = cs_e + _shift_right(cs_e, k, 0.0)
            k *= 2

        cnt = cnt0_ref[:] + cs_v  # [P, N] i32, carried count included
        esum = esum0_ref[:] + cs_e
        cnt_f = jnp.maximum(cnt, 1).astype(jnp.float32)
        p = esum / cnt_f
        s = jnp.sqrt(jnp.clip(p * (1.0 - p), 0.0, None) / cnt_f)
        ps = p + s

        check = (valid > 0) & ((cnt + 1) >= min_n)
        inf = jnp.float32(jnp.inf)
        mn_ps = jnp.where(check, ps, inf)
        mn_p, mn_s = p, s

        # Running min of ps with (p, s) payload; within the window a later
        # equal minimum wins (combine(earlier, later) keeps later on <=),
        # matching ops.ddm._run_min.
        k = 1
        while k < n:
            sh_ps = _shift_right(mn_ps, k, inf)
            sh_p = _shift_right(mn_p, k, 0.0)
            sh_s = _shift_right(mn_s, k, 0.0)
            keep = mn_ps <= sh_ps  # current (later) wins ties
            mn_ps = jnp.where(keep, mn_ps, sh_ps)
            mn_p = jnp.where(keep, mn_p, sh_p)
            mn_s = jnp.where(keep, mn_s, sh_s)
            k *= 2

        # Merge the carried minima (strictly earlier than the window, so the
        # window minimum wins ties — same `<=` rule as ops.ddm).
        use_run = mn_ps <= psmin0_ref[:]
        ps_min = jnp.where(use_run, mn_ps, psmin0_ref[:])
        p_min = jnp.where(use_run, mn_p, pmin0_ref[:])
        s_min = jnp.where(use_run, mn_s, smin0_ref[:])

        change = check & (ps > p_min + out_l * s_min)
        warning = check & ~change & (ps > p_min + warn_l * s_min)
        warn_ref[:] = warning.astype(jnp.int32)
        chg_ref[:] = change.astype(jnp.int32)

        # End-of-window carried state = last lane (padding lanes are invalid
        # and advance nothing).
        cnt1_ref[:] = cnt[:, n - 1:n]
        esum1_ref[:] = esum[:, n - 1:n]
        psmin1_ref[:] = ps_min[:, n - 1:n]
        pmin1_ref[:] = p_min[:, n - 1:n]
        smin1_ref[:] = s_min[:, n - 1:n]

    return kernel


@functools.lru_cache(maxsize=32)
def _prefix_call(params: DDMParams, n_pad: int, interpret: bool):
    kernel = _make_kernel(params, n_pad)

    def call(cnt, esum, psmin, pmin, smin, errs, valid):
        p = errs.shape[0]
        vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
        f32 = jnp.float32
        out_shape = (
            jax.ShapeDtypeStruct((p, n_pad), jnp.int32),  # warning
            jax.ShapeDtypeStruct((p, n_pad), jnp.int32),  # change
            jax.ShapeDtypeStruct((p, 1), jnp.int32),      # count'
            jax.ShapeDtypeStruct((p, 1), f32),            # err_sum'
            jax.ShapeDtypeStruct((p, 1), f32),            # ps_min'
            jax.ShapeDtypeStruct((p, 1), f32),            # p_min'
            jax.ShapeDtypeStruct((p, 1), f32),            # s_min'
        )
        return pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=[vspec] * 7,
            out_specs=(vspec,) * 7,
            interpret=interpret,
        )(
            cnt[:, None], esum[:, None], psmin[:, None], pmin[:, None],
            smin[:, None], errs, valid,
        )

    return call


def _prefix_batched(
    state: DDMState, errs: jax.Array, valid: jax.Array, params: DDMParams
):
    """``[P, N]`` fused prefix masks; returns ``(end_state, warning, change)``
    with ``[P]``-leaved state and ``[P, N]`` bool masks."""
    p, n = errs.shape
    n_pad = max(_LANES, -(-n // _LANES) * _LANES)
    if n_pad != n:
        pad = [(0, 0), (0, n_pad - n)]
        errs = jnp.pad(errs, pad)
        valid = jnp.pad(valid, pad)
    interpret = jax.default_backend() != "tpu"
    call = _prefix_call(params, n_pad, interpret)
    warn, chg, cnt, esum, psmin, pmin, smin = call(
        state.count,
        state.err_sum,
        state.ps_min,
        state.p_min,
        state.s_min,
        errs.astype(jnp.float32),
        valid.astype(jnp.int32),
    )
    end = DDMState(
        count=cnt[:, 0],
        err_sum=esum[:, 0],
        ps_min=psmin[:, 0],
        p_min=pmin[:, 0],
        s_min=smin[:, 0],
    )
    return end, warn[:, :n] > 0, chg[:, :n] > 0


@functools.lru_cache(maxsize=32)
def _window_fn(params: DDMParams):
    """Per-partition (unbatched) window update with a custom vmap rule that
    maps the partition axis onto the kernel's sublane axis."""

    @jax.custom_batching.custom_vmap
    def window(state: DDMState, errs: jax.Array, valid: jax.Array):
        w, b = errs.shape
        st = jax.tree.map(lambda x: x[None], state)
        end, warning, change = _prefix_batched(
            st, errs.reshape(1, w * b), valid.reshape(1, w * b), params
        )
        return (
            jax.tree.map(lambda x: x[0], end),
            warning.reshape(w, b),
            change.reshape(w, b),
        )

    @window.def_vmap
    def _rule(axis_size, in_batched, state, errs, valid):
        st_b, errs_b, valid_b = in_batched
        bcast = lambda x, bt: x if bt else jnp.broadcast_to(  # noqa: E731
            x[None], (axis_size, *x.shape)
        )
        state = jax.tree.map(bcast, state, st_b)
        errs = bcast(errs, errs_b)
        valid = bcast(valid, valid_b)
        p, w, b = errs.shape
        end, warning, change = _prefix_batched(
            state, errs.reshape(p, w * b), valid.reshape(p, w * b), params
        )
        out = (end, warning.reshape(p, w, b), change.reshape(p, w, b))
        return out, jax.tree.map(lambda _: True, out)

    return window


def ddm_window_pallas(
    state: DDMState,
    errs: jax.Array,
    valid: jax.Array,
    params: DDMParams = DDMParams(),
) -> tuple[DDMState, DDMWindowResult]:
    """Drop-in replacement for :func:`ops.ddm.ddm_window` backed by the fused
    Pallas kernel (same contract, same f32 arithmetic, bit-identical flags)."""
    end, warning, change = _window_fn(params)(state, errs, valid)
    b = errs.shape[-1]
    first_change = _first_true(change)
    limit = jnp.where(first_change >= 0, first_change, jnp.int32(b))
    first_warning = _first_true(warning, limit)
    return end, DDMWindowResult(first_warning, first_change)
