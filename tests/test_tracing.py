"""Trace plane: span schema, head sampling, wire propagation, the serving
span chain, and the timeline CLI's Chrome-trace merge.

The headline acceptance (ISSUE 11): a loadgen replay with sampling on
yields at least one complete sampled trace whose span chain covers
ingress → admission → batch → kernel → verdict, the merged
``.trace.json`` validates as a Chrome trace, and with sampling off the
hot path does no tracing work at all.
"""

import json
import threading

import numpy as np
import pytest

from distributed_drift_detection_tpu import RunConfig
from distributed_drift_detection_tpu.config import ServeParams
from distributed_drift_detection_tpu.io import planted_prototypes
from distributed_drift_detection_tpu.serve import ServeRunner
from distributed_drift_detection_tpu.serve.loadgen import (
    format_lines,
    run_loadgen,
    sample_traces,
)
from distributed_drift_detection_tpu.telemetry import tracing
from distributed_drift_detection_tpu.telemetry.events import (
    EventLog,
    SchemaError,
    read_events,
    validate_event,
)
from distributed_drift_detection_tpu.telemetry.timeline import (
    TimelineError,
    build_timeline,
    validate_chrome_trace,
)


# --- schema round-trip (span + drift_forensics) ----------------------------


def _emit_and_read(tmp_path, etype, **fields):
    log = EventLog(str(tmp_path / "roundtrip.jsonl"))
    log.emit(etype, **fields)
    log.close()
    (event,) = read_events(log.path)
    return event


def test_span_event_schema_round_trip(tmp_path):
    event = _emit_and_read(
        tmp_path,
        "span",
        name="kernel",
        trace_id=tracing.new_trace_id(),
        span_id=tracing.new_span_id(),
        parent_id=None,  # root spans: nullable by contract
        start_ts=123.5,
        dur_s=0.25,
        chunk=7,  # extra fields ride through (forward compat)
    )
    assert event["name"] == "kernel" and event["chunk"] == 7
    assert event["parent_id"] is None


def test_drift_forensics_event_schema_round_trip(tmp_path):
    event = _emit_and_read(
        tmp_path,
        "drift_forensics",
        chunk=3,
        partition=1,
        global_pos=588,
        bundle="run.forensics/drift-c3-p1-r588.json",
        tenant=0,  # extra field tolerated
    )
    assert event["global_pos"] == 588 and event["tenant"] == 0


@pytest.mark.parametrize(
    "etype,fields",
    [
        ("span", dict(name="x", trace_id="a", span_id="b", parent_id=None,
                      start_ts=1.0)),  # dur_s missing
        ("span", dict(name="x", trace_id="a", span_id="b", start_ts=1.0,
                      dur_s=0.1)),  # parent_id missing entirely
        ("drift_forensics", dict(chunk=1, partition=0, global_pos=5)),
        # null where not nullable:
        ("drift_forensics", dict(chunk=1, partition=0, global_pos=None,
                                 bundle="b.json")),
        ("span", dict(name="x", trace_id=None, span_id="b", parent_id=None,
                      start_ts=1.0, dur_s=0.1)),
    ],
)
def test_new_event_types_reject_missing_or_null_required(tmp_path, etype, fields):
    log = EventLog(str(tmp_path / "bad.jsonl"))
    with pytest.raises(SchemaError):
        log.emit(etype, **fields)
    log.close()
    # the refused emit left nothing behind (producer-side validation)
    assert read_events(log.path) == []


def test_span_extra_fields_tolerated_by_reader():
    validate_event(
        {
            "v": 1, "type": "span", "ts": 1.0, "seq": 0,
            "name": "serve", "trace_id": "t", "span_id": "s",
            "parent_id": "p", "start_ts": 1.0, "dur_s": 0.5,
            "some_future_field": {"nested": True},
        }
    )


# --- head sampling ---------------------------------------------------------


def test_head_sampler_rate_zero_is_falsy_and_samples_nothing():
    s = tracing.HeadSampler(0.0)
    assert not s
    assert s.sample() is False
    assert s.sample_block(1000) == []


def test_head_sampler_rate_one_samples_everything():
    s = tracing.HeadSampler(1.0)
    assert s and s.sample()
    assert s.sample_block(5) == [0, 1, 2, 3, 4]


def test_head_sampler_seeded_and_rate_respected():
    a = tracing.HeadSampler(0.3, seed=42)
    b = tracing.HeadSampler(0.3, seed=42)
    got_a, got_b = a.sample_block(10_000), b.sample_block(10_000)
    assert got_a == got_b  # deterministic under a seed
    assert 0.2 < len(got_a) / 10_000 < 0.4


def test_trace_token_validation():
    tracing.check_trace_token(tracing.new_trace_id())
    tracing.check_trace_token(tracing.new_span_id())
    for bad in ("", "UPPER", "has space", "x" * 65, "nonhex-!"):
        with pytest.raises(ValueError):
            tracing.check_trace_token(bad)


def test_loadgen_sample_traces_rate_zero_empty():
    assert sample_traces(100, 0.0) == {}
    ctx = sample_traces(100, 1.0, seed=1)
    assert len(ctx) == 100
    tid, sid = ctx[0]
    assert len(tid) == 32 and len(sid) == 16


# --- the serving span chain ------------------------------------------------


def test_emit_row_spans_chain_and_parenting(tmp_path):
    log = EventLog(str(tmp_path / "spans.jsonl"))
    ingest = np.array([100.0, 100.5, 101.0])
    meta = {
        "chunk": 4,
        "traces": [
            {"idx": 0, "trace_id": "a" * 32, "parent_id": "b" * 16},
            {"idx": 2, "trace_id": "c" * 32, "parent_id": None,
             "tenant": 1},
        ],
        "ingest_mono": ingest,
        "sealed_mono": 101.5,
        "fed_mono": 101.6,
    }
    ids = tracing.emit_row_spans(
        log, meta, collected_mono=101.9, published_mono=102.0
    )
    log.close()
    assert ids == ["a" * 32, "c" * 32]
    events = read_events(log.path)
    by_trace = {}
    for e in events:
        assert e["type"] == "span" and e["dur_s"] >= 0
        by_trace.setdefault(e["trace_id"], []).append(e)
    assert set(by_trace) == {"a" * 32, "c" * 32}
    for tid, spans in by_trace.items():
        names = [s["name"] for s in spans]
        assert names == ["serve", *tracing.ROW_STAGES]
        serve = spans[0]
        # stage spans parent to the serve span; serve parents to the wire
        for child in spans[1:]:
            assert child["parent_id"] == serve["span_id"]
    assert by_trace["a" * 32][0]["parent_id"] == "b" * 16
    assert by_trace["c" * 32][0]["parent_id"] is None
    assert all(s["tenant"] == 1 for s in by_trace["c" * 32])
    # durations decompose: serve covers ingest -> published
    serve = by_trace["a" * 32][0]
    assert serve["dur_s"] == pytest.approx(102.0 - 100.0)


def _serve(seed, tmp_path, trace_sample=0.0, **cfg_kw):
    stream = planted_prototypes(seed, concepts=3, rows_per_concept=480,
                                features=7)
    cfg = RunConfig(
        partitions=4, per_batch=50, model="centroid", shuffle_batches=True,
        results_csv="", seed=seed, window=1, data_policy="quarantine",
        telemetry_dir=str(tmp_path / "tele"), **cfg_kw,
    )
    params = ServeParams(
        num_features=stream.num_features, num_classes=stream.num_classes,
        port=0, chunk_batches=2, linger_s=0.05, trace_sample=trace_sample,
    )
    runner = ServeRunner(cfg, params, keep_flags=True)
    banner = runner.start()
    t = threading.Thread(target=runner.serve_forever)
    t.start()
    return stream, runner, banner, t


def test_socket_traced_replay_end_to_end(tmp_path, monkeypatch):
    """The acceptance: a sampled loadgen replay yields complete traces
    whose span chain covers ingress→admission→batch→kernel→verdict,
    verdicts join back to their packets, and the merged timeline is a
    valid Chrome trace."""
    monkeypatch.chdir(tmp_path)
    stream, runner, banner, t = _serve(12, tmp_path)
    lines = format_lines(stream.X, stream.y)
    clog = EventLog.open_run(str(tmp_path / "tele"), name="loadgen")
    clog.emit("run_started", run_id=clog.run_id, config={"kind": "loadgen"})
    rep = run_loadgen(
        banner["host"], banner["port"], lines, rate=0.0,
        verdicts=banner["verdicts"], timeout=120, stop=True,
        trace_sample=0.1, trace_seed=3, trace_log=clog,
    )
    t.join(timeout=120)
    assert not t.is_alive()
    clog.emit("run_completed", rows=rep["rows_sent"], seconds=1.0,
              detections=rep["detections"])
    clog.close()
    assert not rep["timeout"] and rep["rows_covered"] == len(lines)
    assert rep["rows_traced"] > 0
    assert rep["traces_covered"] == rep["rows_traced"]  # all joined back

    # daemon side: every traced row has the full chain
    events = read_events(banner["run_log"])
    chains = {}
    for e in events:
        if e["type"] == "span":
            chains.setdefault(e["trace_id"], []).append(e["name"])
    assert len(chains) == rep["rows_traced"]
    for names in chains.values():
        assert names == ["serve", *tracing.ROW_STAGES]

    # client side: one root ingress span per covered trace, same ids
    client_spans = [
        e for e in read_events(clog.path) if e["type"] == "span"
    ]
    assert len(client_spans) == rep["rows_traced"]
    assert {s["trace_id"] for s in client_spans} == set(chains)
    assert all(
        s["name"] == "ingress" and s["parent_id"] is None
        for s in client_spans
    )

    # verdict records name the trace ids they cover
    from distributed_drift_detection_tpu.serve import read_verdicts

    verd_traces = set()
    for rec in read_verdicts(banner["verdicts"]):
        verd_traces.update(rec.get("traces") or [])
    assert verd_traces == set(chains)

    # statusz counts the traced rows
    st = runner._statusz()
    assert st["tracing"]["rows_traced"] == rep["rows_traced"]

    # timeline: daemon + client logs merge into a valid Chrome trace
    trace = build_timeline([banner["run_log"], clog.path])
    n = validate_chrome_trace(trace)
    assert n > 0
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    ingress = [e for e in slices if e["name"] == "ingress"]
    kernels = [e for e in slices if e["name"] == "kernel"]
    assert ingress and kernels
    assert {e["pid"] for e in trace["traceEvents"]} == {0, 1}
    out = tmp_path / "merged.trace.json"
    out.write_text(json.dumps(trace))
    validate_chrome_trace(json.loads(out.read_text()))


def test_sampling_off_leaves_no_trace_artifacts(tmp_path, monkeypatch):
    """rate 0 = zero trace output: no spans in the log, no traces field
    on any verdict, no trace work counted."""
    monkeypatch.chdir(tmp_path)
    stream, runner, banner, t = _serve(5, tmp_path)
    lines = format_lines(stream.X, stream.y)
    rep = run_loadgen(
        banner["host"], banner["port"], lines, rate=0.0,
        verdicts=banner["verdicts"], timeout=120, stop=True,
    )
    t.join(timeout=120)
    assert not t.is_alive() and not rep["timeout"]
    assert rep["rows_traced"] == 0
    events = read_events(banner["run_log"])
    assert not [e for e in events if e["type"] == "span"]
    from distributed_drift_detection_tpu.serve import read_verdicts

    assert all(
        "traces" not in rec for rec in read_verdicts(banner["verdicts"])
    )
    assert runner._statusz()["tracing"]["rows_traced"] == 0


def test_daemon_side_sampling_of_unstamped_rows(tmp_path, monkeypatch):
    """ServeParams.trace_sample samples rows the client never stamped:
    fresh root traces, full chains."""
    monkeypatch.chdir(tmp_path)
    stream, runner, banner, t = _serve(7, tmp_path, trace_sample=1.0)
    lines = format_lines(stream.X, stream.y)
    rep = run_loadgen(
        banner["host"], banner["port"], lines, rate=0.0,
        verdicts=banner["verdicts"], timeout=120, stop=True,
    )
    t.join(timeout=120)
    assert not t.is_alive() and not rep["timeout"]
    chains = {}
    for e in read_events(banner["run_log"]):
        if e["type"] == "span":
            chains.setdefault(e["trace_id"], []).append(e["name"])
    # the pipeline observatory's per-CHUNK serve.* stage spans ride the
    # same plane on their own trace ids; split them from the row chains
    chunk_chains = {
        t: n for t, n in chains.items()
        if all(name.startswith("serve.") for name in n)
    }
    row_chains = {t: n for t, n in chains.items() if t not in chunk_chains}
    assert len(row_chains) == len(lines)  # rate 1.0: every row traced
    assert all(
        names == ["serve", *tracing.ROW_STAGES]
        for names in row_chains.values()
    )
    assert chunk_chains and all(
        set(n) <= {"serve.feed", "serve.device", "serve.collect",
                   "serve.publish"}
        for n in chunk_chains.values()
    )


def test_ingress_rejects_malformed_trace_line(tmp_path, monkeypatch):
    """A malformed TRACE wire line is untrusted client input: ERR + drop
    THIS connection, daemon keeps serving (the TENANT contract)."""
    import socket

    monkeypatch.chdir(tmp_path)
    stream, runner, banner, t = _serve(9, tmp_path)
    lines = format_lines(stream.X, stream.y)
    with socket.create_connection(
        (banner["host"], banner["port"]), timeout=10
    ) as sock:
        sock.sendall(b"TRACE not-hex!\n" + (lines[0] + "\n").encode())
        reply = sock.recv(1024)
    assert reply.startswith(b"ERR ")
    # the daemon survived: a fresh connection still serves
    rep = run_loadgen(
        banner["host"], banner["port"], lines, rate=0.0,
        verdicts=banner["verdicts"], timeout=120, stop=True,
    )
    t.join(timeout=120)
    assert not t.is_alive() and not rep["timeout"]
    assert rep["rows_covered"] == len(lines)


def test_multi_tenant_traced_replay(tmp_path, monkeypatch):
    """TRACE stamps survive the TENANT wire routing: spans carry the
    tenant, per-tenant attribution still joins every trace back."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(6, concepts=3, rows_per_concept=480,
                                features=6)
    cfg = RunConfig(
        partitions=2, per_batch=50, tenants=2, model="centroid",
        shuffle_batches=True, results_csv="", seed=6, window=1,
        data_policy="quarantine", telemetry_dir=str(tmp_path / "tele"),
    )
    params = ServeParams(
        num_features=6, num_classes=3, port=0, chunk_batches=2,
        linger_s=0.05,
    )
    runner = ServeRunner(cfg, params, keep_flags=True)
    banner = runner.start()
    t = threading.Thread(target=runner.serve_forever)
    t.start()
    lines = format_lines(stream.X, stream.y)
    rep = run_loadgen(
        banner["host"], banner["port"], lines, rate=0.0,
        verdicts=banner["verdicts"], timeout=120, stop=True, tenants=2,
        trace_sample=0.1, trace_seed=5,
    )
    t.join(timeout=120)
    assert not t.is_alive() and not rep["timeout"]
    assert rep["rows_traced"] > 0
    assert rep["traces_covered"] == rep["rows_traced"]
    chains = {}
    tenants_seen = set()
    for e in read_events(banner["run_log"]):
        if e["type"] == "span":
            chains.setdefault(e["trace_id"], []).append(e["name"])
            tenants_seen.add(e.get("tenant"))
    assert len(chains) == rep["rows_traced"]
    assert all(
        names == ["serve", *tracing.ROW_STAGES] for names in chains.values()
    )
    assert tenants_seen == {0, 1}  # both tenant slots produced traces


# --- batch-pipeline tracer (ChunkTracer) -----------------------------------


def test_chunk_tracer_falsy_forms_emit_nothing(tmp_path):
    assert not tracing.ChunkTracer(None, rate=1.0)
    log = EventLog(str(tmp_path / "t.jsonl"))
    assert not tracing.ChunkTracer(log, rate=0.0)
    tr = tracing.ChunkTracer(log, rate=0.0)
    assert tr.span("kernel", 0, 0.0, 1.0) is None
    log.close()
    assert read_events(log.path) == []


def test_chunk_tracer_spans_share_trace_and_root(tmp_path):
    log = EventLog(str(tmp_path / "t.jsonl"))
    tr = tracing.ChunkTracer(log, rate=1.0, seed=0)
    a = tr.span("ingest", 0, 10.0, 10.5, rows=100)
    b = tr.span("kernel", 0, 10.5, 11.0)
    c = tr.span("ingest", 1, 11.0, 11.5, rows=100)
    log.close()
    events = read_events(log.path)
    assert [e["name"] for e in events] == ["ingest", "kernel", "ingest"]
    # one trace per CHUNK: chunk 0's two stages share one, chunk 1 is new
    assert events[0]["trace_id"] == events[1]["trace_id"]
    assert events[2]["trace_id"] != events[0]["trace_id"]
    assert events[0]["span_id"] == a and events[0]["parent_id"] is None
    assert events[1]["span_id"] == b and events[1]["parent_id"] == a
    assert events[2]["span_id"] == c and events[2]["parent_id"] is None


def test_chunked_cli_trace_sample_emits_pipeline_spans(tmp_path, monkeypatch):
    """--trace-sample on the chunked CLI: ingest + kernel spans land in
    the run log and the timeline CLI renders them."""
    from distributed_drift_detection_tpu.harness.chunked_cli import main

    monkeypatch.chdir(tmp_path)
    rng = np.random.default_rng(0)
    n, f = 900, 4
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (np.arange(n) // 300) % 3
    csv = tmp_path / "s.csv"
    header = ",".join(f"f{i}" for i in range(f)) + ",target"
    rows = "\n".join(
        ",".join(repr(float(v)) for v in X[i]) + f",{y[i]}" for i in range(n)
    )
    csv.write_text(header + "\n" + rows + "\n")
    tele = tmp_path / "tele"
    main([
        str(csv), "--classes", "3", "--partitions", "2", "--per-batch", "25",
        "--chunk-batches", "4", "--window", "1", "--telemetry-dir", str(tele),
        "--trace-sample", "1.0",
    ])
    import glob
    import os

    from distributed_drift_detection_tpu.telemetry.registry import INDEX_NAME

    (log_path,) = [
        p
        for p in glob.glob(str(tele / "*.jsonl"))
        if os.path.basename(p) != INDEX_NAME
        and ".quarantine." not in p
    ]
    spans = [e for e in read_events(log_path) if e["type"] == "span"]
    names = {e["name"] for e in spans}
    assert names == {"ingest", "kernel"}
    # one trace per chunk: its ingest + kernel stages share it, and no
    # two chunks collide on one trace (separate timeline lanes)
    by_chunk = {}
    traces_by_chunk = {}
    for e in spans:
        by_chunk.setdefault(e["chunk"], set()).add(e["name"])
        traces_by_chunk.setdefault(e["chunk"], set()).add(e["trace_id"])
    assert all(v == {"ingest", "kernel"} for v in by_chunk.values())
    assert all(len(v) == 1 for v in traces_by_chunk.values())
    all_traces = [next(iter(v)) for v in traces_by_chunk.values()]
    assert len(set(all_traces)) == len(all_traces)
    trace = build_timeline([log_path])
    assert validate_chrome_trace(trace) > 0


# --- timeline clock alignment ----------------------------------------------


def _synthetic_log(
    tmp_path, name, t0, process_index, config, events, process_count=2
):
    """Write a schema-valid per-process run log with a fixed clock."""
    clock_holder = {"now": t0}
    log = EventLog(
        str(tmp_path / f"{name}.jsonl"),
        clock=lambda: clock_holder["now"],
    )
    ident = (
        {"process_index": process_index, "process_count": process_count}
        if process_count
        else {}
    )
    log.emit(
        "run_started", run_id=name, config=config, hostname=name, **ident,
    )
    for dt, etype, fields in events:
        clock_holder["now"] = t0 + dt
        log.emit(etype, **fields)
    log.close()
    return log.path


def test_timeline_clock_skew_alignment(tmp_path):
    """Satellite: two per-process logs of ONE run with a known wall-clock
    offset merge into one monotonic, skew-rebased trace — same-program
    events land at the same timeline instant."""
    config = {"dataset": "synth", "seed": 1}
    shared = [
        (1.0, "phase_completed", {"phase": "detect", "seconds": 0.5}),
        (2.0, "chunk_completed",
         {"chunk": 0, "batches_done": 4, "detections": 1}),
        (3.0, "run_completed", {"rows": 100, "seconds": 3.0, "detections": 1}),
    ]
    skew = 500.0  # proc1's wall clock is 500 s ahead
    a = _synthetic_log(tmp_path, "proc0", 1000.0, 0, config, shared)
    b = _synthetic_log(tmp_path, "proc1", 1000.0 + skew, 1, config, shared)
    trace = build_timeline([a, b])
    validate_chrome_trace(trace)
    per_pid = {}
    for e in trace["traceEvents"]:
        if e["ph"] == "M":
            continue
        per_pid.setdefault(e["pid"], []).append(e)
    assert set(per_pid) == {0, 1}
    # monotonic within each process and ALIGNED across them: the skew
    # cancelled exactly, so the same program points coincide
    for pid, evs in per_pid.items():
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)
    t_a = {e["name"]: e["ts"] for e in per_pid[0]}
    t_b = {e["name"]: e["ts"] for e in per_pid[1]}
    assert set(t_a) == set(t_b)
    for name in t_a:
        assert t_a[name] == pytest.approx(t_b[name], abs=1.0), name


def test_timeline_wall_clock_placement_for_distinct_programs(tmp_path):
    """Logs with different config digests (daemon + loadgen) sit on the
    shared wall clock: their relative offset is preserved, not rebased."""
    a = _synthetic_log(
        tmp_path, "daemon", 1000.0, 0, {"kind": "serve"},
        [(1.0, "heartbeat", {"rows_done": 10, "elapsed_s": 1.0})],
    )
    b = _synthetic_log(
        tmp_path, "client", 1010.0, 0, {"kind": "loadgen"},
        [(1.0, "heartbeat", {"rows_done": 10, "elapsed_s": 1.0})],
    )
    trace = build_timeline([a, b])
    starts = {
        e["args"]["run_id"]: e["ts"]
        for e in trace["traceEvents"]
        if e["name"] == "run_started"
    }
    # the client started 10 s after the daemon, and the merge says so
    assert (starts["client"] - starts["daemon"]) == pytest.approx(
        10.0 * 1e6, abs=1e3
    )


def test_timeline_repeated_runs_of_one_config_stay_on_wall_clock(tmp_path):
    """Two independent runs of one config (same digest, no declared
    multi-process identity — e.g. two identical loadgen replays) must
    NOT be skew-rebased onto a common origin: they are not one run, and
    their real 100 s separation is the signal."""
    config = {"kind": "loadgen", "source": "synth"}
    ev = [(1.0, "heartbeat", {"rows_done": 10, "elapsed_s": 1.0})]
    a = _synthetic_log(tmp_path, "replay1", 1000.0, 0, config, ev,
                       process_count=None)
    b = _synthetic_log(tmp_path, "replay2", 1100.0, 0, config, ev,
                       process_count=None)
    trace = build_timeline([a, b])
    starts = {
        e["args"]["run_id"]: e["ts"]
        for e in trace["traceEvents"]
        if e["name"] == "run_started"
    }
    assert (starts["replay2"] - starts["replay1"]) == pytest.approx(
        100.0 * 1e6, abs=1e3
    )


def test_timeline_rejects_garbage():
    with pytest.raises(TimelineError):
        validate_chrome_trace({"nope": 1})
    with pytest.raises(TimelineError):
        validate_chrome_trace(
            {"traceEvents": [{"name": "x", "ph": "X", "pid": 0, "tid": 0,
                              "ts": 0.0}]}  # X without dur
        )
    with pytest.raises(TimelineError):
        build_timeline([])
