"""Native C++ ingest library: parity with the NumPy path."""

import os

import numpy as np
import pytest

from distributed_drift_detection_tpu.io import load_csv
from distributed_drift_detection_tpu.io.native import load_csv_native, native_available
from conftest import needs_reference

OUTDOOR = "/root/reference/outdoorStream.csv"

needs_native = pytest.mark.skipif(
    not native_available(), reason="native library unavailable (no toolchain)"
)


@needs_native
@pytest.mark.skipif(
    not os.path.exists(OUTDOOR), reason="reference dataset not mirrored here"
)
def test_native_matches_numpy():
    # Whole-file parity on the reference dataset; the dataset-free twin
    # (tests/test_io.py test_parse_block_native_matches_numpy) covers the
    # block parser on hosts without the mirror.
    raw_native = load_csv_native(OUTDOOR)
    raw_numpy = np.loadtxt(OUTDOOR, delimiter=",", skiprows=1, dtype=np.float32)
    assert raw_native.shape == raw_numpy.shape
    np.testing.assert_allclose(raw_native, raw_numpy, rtol=1e-6)


@needs_native
def test_native_handles_crlf_and_no_trailing_newline(tmp_path):
    p = tmp_path / "x.csv"
    p.write_bytes(b"a,b,target\r\n1.5,2.5,0\r\n3.25,-4.5,1")
    raw = load_csv_native(str(p))
    np.testing.assert_allclose(raw, [[1.5, 2.5, 0.0], [3.25, -4.5, 1.0]])


@needs_reference
def test_load_csv_uses_some_path():
    """load_csv works regardless of which backend parsed (native or numpy)."""
    X, y = load_csv(OUTDOOR)
    assert X.shape == (4000, 21)
    assert y.shape == (4000,)
    assert y.min() >= 0


def test_native_missing_file_returns_none():
    if not native_available():
        pytest.skip("native library unavailable")
    assert load_csv_native("/nonexistent/file.csv") is None
