"""Property-based tests (hypothesis): the kernels against the NumPy oracle
and the native parser against NumPy, on adversarially-generated inputs.

Shapes are held fixed inside each test so jit compiles once per test, not per
example; hypothesis varies contents, carried state, and thresholds.
"""

import numpy as np
import pytest

# Optional dependency (the `test`/`dev` extras install it): a bare
# environment must still *collect* this suite cleanly — skip, not error.
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import jax
import jax.numpy as jnp

from distributed_drift_detection_tpu import DDMParams
from distributed_drift_detection_tpu.ops import ddm_init
from distributed_drift_detection_tpu.ops.ddm import ddm_batch, ddm_window

from oracle import oracle_run_ddm

B = 24  # fixed batch length → one jit compile per test


# Module-level jitted kernels with params as a *traced argument*: one compile
# serves every hypothesis example. (A fresh `jax.jit(lambda ...)` per example
# — or params captured by closure — retraces per draw and used to dominate
# the suite's runtime at ~1 s/example.)
@jax.jit
def _jit_batch(state, errs, params):
    return ddm_batch(state, errs, jnp.ones(B, bool), params)


@jax.jit
def _jit_window(state, errs, valid, params):
    return ddm_window(state, errs, valid, params)


def run_kernel(params: DDMParams, errs: np.ndarray):
    """One fresh-state batch through the jitted kernel."""
    return _jit_batch(ddm_init(), jnp.asarray(errs), params)


@settings(max_examples=30, deadline=None)
@given(
    data=st.data(),
    err_p=st.floats(0.0, 1.0),
    min_n=st.integers(1, 6),
    warn=st.floats(0.1, 2.0),
    out=st.floats(0.5, 4.0),
)
def test_ddm_batch_matches_oracle(data, err_p, min_n, warn, out):
    """ddm_batch == the sequential oracle for arbitrary error patterns,
    thresholds, and warm-up lengths (no carried state)."""
    if out < warn:
        warn, out = out, warn
    params = DDMParams(min_num_instances=min_n, warning_level=warn,
                       out_control_level=out)
    errs = np.asarray(
        data.draw(st.lists(st.sampled_from([0.0, 1.0]), min_size=B, max_size=B)),
        np.float32,
    )
    # Inject structure: a clean run then errors fires realistic patterns.
    if err_p < 0.3:
        k = int(err_p * 3 * B)
        errs = np.concatenate([np.zeros(B - k, np.float32),
                               np.ones(k, np.float32)])

    _, res = run_kernel(params, errs)
    rows = np.arange(B)
    (wl, _, cl, _), _ = oracle_run_ddm(
        errs, rows, None, min_num_instances=min_n, warning_level=warn,
        out_control_level=out,
    )
    assert int(res.first_change) == cl
    assert int(res.first_warning) == wl


@settings(max_examples=20, deadline=None)
@given(data=st.data())
def test_ddm_window_matches_chained_batches(data):
    """ddm_window over [W, B] == W sequential ddm_batch calls with threaded
    state, for every batch up to (and including) the first change."""
    w = 5
    params = DDMParams()
    errs = np.asarray(
        data.draw(
            st.lists(
                st.floats(0.0, 1.0).map(lambda p: 1.0 if p > 0.85 else 0.0),
                min_size=w * B, max_size=w * B,
            )
        ),
        np.float32,
    ).reshape(w, B)
    valid = np.ones((w, B), bool)

    end_w, res_w = _jit_window(
        ddm_init(), jnp.asarray(errs), jnp.asarray(valid), params
    )
    st_ = ddm_init()
    stop = w
    for k in range(w):
        st_, rb = _jit_batch(st_, jnp.asarray(errs[k]), params)
        if k <= stop:
            assert int(res_w.first_change[k]) == int(rb.first_change), k
            assert int(res_w.first_warning[k]) == int(rb.first_warning), k
        if stop == w and int(rb.first_change) >= 0:
            stop = k
    if stop == w:
        for a, b in zip(end_w, st_):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@settings(max_examples=30, deadline=None)
@given(
    vals=st.lists(
        st.floats(allow_nan=False, allow_infinity=False,
                  min_value=-1e30, max_value=1e30),
        min_size=1, max_size=120,
    ),
    cols=st.integers(1, 6),
    crlf=st.booleans(),
    trailing_newline=st.booleans(),
)
def test_native_parse_block_matches_numpy(vals, cols, crlf, trailing_newline):
    from distributed_drift_detection_tpu.io.native import (
        native_available,
        parse_block,
    )

    if not native_available():
        pytest.skip("native library unavailable")
    n = (len(vals) // cols) * cols
    if n == 0:
        return
    # Round through f32 first so the written decimal is exactly representable
    # and both parsers (from_chars-double→f32 and NumPy) agree bit-for-bit.
    arr = np.asarray(vals[:n], np.float32).reshape(-1, cols)
    eol = "\r\n" if crlf else "\n"
    text = eol.join(",".join(repr(float(v)) for v in row) for row in arr)
    if trailing_newline:
        text += eol
    out = parse_block(text.encode(), cols)
    np.testing.assert_array_equal(out, arr)


_ZOO = {}


def _zoo(name):
    """One jitted batch kernel per zoo case (params static, compiled once
    across hypothesis examples)."""
    if not _ZOO:
        from test_detectors import CASES

        for cname, ocls, params, init, _step, batch, _window in CASES:
            _ZOO[cname] = (
                ocls,
                params,
                init,
                jax.jit(lambda s, e, v, _b=batch, _p=params: _b(s, e, v, _p)),
            )
    return _ZOO[name]


ZB = 48  # fixed zoo-batch length → one compile per case


@settings(max_examples=15, deadline=None)
@given(
    data=st.data(),
    name=st.sampled_from(["ph", "eddm", "eddm_exact", "hddm", "hddm_w", "adwin", "kswin", "stepd"]),
)
def test_zoo_batch_matches_oracle_on_fuzzed_streams(data, name):
    """Detector-zoo batch kernels == their per-element oracles under fuzzed
    error patterns AND fuzzed validity masks AND carried state across a
    batch boundary (the engines' state-threading contract) — the
    oracle-fuzzing net of test_ddm extended to every zoo member, including
    the r04 hddm/hddm_w and paper-exact eddm paths."""
    from test_detectors import firsts, oracle_flags

    ocls, params, init, jbatch = _zoo(name)
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    # Clustered bursts (realistic post-drift shapes) atop i.i.d. noise.
    p_base = data.draw(st.floats(0.02, 0.5))
    errs = (rng.random(2 * ZB) < p_base).astype(np.float32)
    if data.draw(st.booleans()):
        at = data.draw(st.integers(0, 2 * ZB - 8))
        errs[at : at + 8] = 1.0
    valid = rng.random(2 * ZB) < data.draw(st.floats(0.5, 1.0))

    o_warn, o_change, _ = oracle_flags(ocls, params, errs, valid)
    e1, e2 = errs[:ZB], errs[ZB:]
    v1, v2 = valid[:ZB], valid[ZB:]

    s1, r1 = jbatch(init(), jnp.asarray(e1), jnp.asarray(v1))
    fw1, fc1 = firsts(o_warn[:ZB], o_change[:ZB])
    assert int(r1.first_change) == fc1
    assert int(r1.first_warning) == fw1
    if fc1 < 0:  # no reset: carried state must continue the oracle's stream
        _, r2 = jbatch(s1, jnp.asarray(e2), jnp.asarray(v2))
        fw2, fc2 = firsts(o_warn[ZB:], o_change[ZB:])
        assert int(r2.first_change) == fc2
        assert int(r2.first_warning) == fw2


_ENGINES = {}


def _engines(window):
    """One jitted (sequential, window) runner pair per width — compiled once
    across hypothesis examples (fresh closures would recompile per draw)."""
    if window not in _ENGINES:
        from distributed_drift_detection_tpu.engine import make_partition_runner
        from distributed_drift_detection_tpu.engine.window import (
            make_window_runner,
        )
        from distributed_drift_detection_tpu.models import (
            ModelSpec,
            make_centroid,
        )

        model = make_centroid(ModelSpec(3, 3))
        _ENGINES[window] = (
            jax.jit(make_partition_runner(model, DDMParams(), shuffle=False)),
            jax.jit(
                make_window_runner(
                    model, DDMParams(), window=window, shuffle=False
                )
            ),
        )
    return _ENGINES[window]


@pytest.mark.slow
@settings(max_examples=12, deadline=None)
@given(data=st.data())
def test_window_engine_matches_sequential_on_adversarial_streams(data):
    """Speculative window engine == sequential engine, bit-exact, under
    fuzzed streams: random class layouts (drift anywhere), random validity
    masks (padding holes, empty batches, ragged tails)."""
    from distributed_drift_detection_tpu.engine import Batches

    nb, b, f, c = 12, 10, 3, 3
    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    # Concept id per batch: nondecreasing with random switch points.
    switches = sorted(data.draw(st.lists(st.integers(1, nb - 1), max_size=2)))
    concept = np.zeros(nb, np.int32)
    for s_ in switches:
        concept[s_:] += 1
    protos = rng.normal(size=(c, f)).astype(np.float32) * 3
    y = np.repeat(concept % c, b).astype(np.int32)
    X = protos[y] + 0.05 * rng.normal(size=(nb * b, f)).astype(np.float32)
    valid = np.asarray(
        data.draw(
            st.lists(st.booleans(), min_size=nb * b, max_size=nb * b)
        )
    ).reshape(nb, b)
    valid[0, 0] = True  # keep the seed batch minimally nonempty; the rest
    # of batch 0 stays fuzzed so partially-valid batch_a fits are exercised
    batches = Batches(
        X=jnp.asarray(X.reshape(nb, b, f)),
        y=jnp.asarray(y.reshape(nb, b)),
        rows=jnp.arange(nb * b, dtype=jnp.int32).reshape(nb, b),
        valid=jnp.asarray(valid),
    )
    key = jax.random.key(data.draw(st.integers(0, 1000)))
    seq, win = _engines(data.draw(st.sampled_from([2, 5, 16])))
    fs, fw = seq(batches, key), win(batches, key)
    for a, b_ in zip(fs, fw):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


# --- wire protocol v2: the frame decoder under adversarial bytes -----------


@settings(max_examples=200, deadline=None)
@given(blob=st.binary(min_size=0, max_size=200))
def test_wire_decode_arbitrary_bytes_never_crashes(blob):
    """The v2 frame decoder on arbitrary bytes: wait-for-more (None), a
    structurally valid frame, or WireError — nothing else ever escapes
    (the daemon-side contract: a malformed frame is an ERR + connection
    close, never a crash)."""
    from distributed_drift_detection_tpu.serve import wire

    try:
        out = wire.decode_frame(blob)
    except wire.WireError:
        return
    if out is None:
        return
    header, X, y, consumed = out
    assert 0 < consumed <= len(blob)
    if header.is_control:
        assert X is None and y is None
    else:
        assert X.shape == (header.rows, header.features)
        assert len(y) == header.rows


@settings(max_examples=100, deadline=None)
@given(
    data=st.data(),
    rows=st.integers(1, 40),
    features=st.integers(1, 8),
    tenant=st.integers(0, 2**32 - 1),
)
def test_wire_round_trip_and_mutation_fuzz(data, rows, features, tenant):
    """encode→decode round-trips any geometry exactly; a mutated or
    truncated copy of the same frame decodes, waits, or raises WireError
    — and a *header*-intact mutation can only corrupt payload VALUES,
    never the geometry (no misattributed rows)."""
    from distributed_drift_detection_tpu.serve import wire

    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    X = rng.normal(size=(rows, features)).astype(np.float32)
    y = rng.integers(-5, 10, rows).astype(np.int32)
    blob = wire.encode_frame(X, y, tenant=tenant)
    header, Xd, yd, consumed = wire.decode_frame(blob)
    assert consumed == len(blob) and header.tenant == tenant
    np.testing.assert_array_equal(Xd, X)
    np.testing.assert_array_equal(yd, y)

    mutated = bytearray(blob)
    pos = data.draw(st.integers(0, len(blob) - 1))
    mutated[pos] = data.draw(st.integers(0, 255))
    cut = data.draw(st.integers(0, len(blob)))
    try:
        out = wire.decode_frame(bytes(mutated[:cut]))
    except wire.WireError:
        return
    if out is not None and pos >= wire.HEADER_SIZE and cut == len(blob):
        h2, X2, y2, _ = out
        # payload-only mutation: geometry identical, rows stay attributed
        assert (h2.rows, h2.features, h2.tenant) == (rows, features, tenant)
