"""Device-side flag compaction (ISSUE 6 tentpole a).

The collect phase's contract: flags reconstructed host-side from the
device-compacted detection table are **bit-identical** to the full-plane
path — on both engines (the sequential batch-per-step scan, window=1, and
the speculative window engine, window>1), across seeds, on streams with
zero detections, and under table overflow (which must fall back to the
full plane loudly, never truncate silently).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_drift_detection_tpu.api import prepare, run
from distributed_drift_detection_tpu.config import RunConfig, replace
from distributed_drift_detection_tpu.engine.loop import FlagRows
from distributed_drift_detection_tpu.parallel.mesh import (
    auto_compact_capacity,
    compact_flag_table,
    expand_flag_table,
    host_flags,
    unpack_flags,
)


def _random_flags(rng, p, nbf, flag_fraction):
    """A synthetic FlagRows plane with `flag_fraction` of slots flagged in
    every combination the engines can produce (warning-only, change-only,
    both, forced-retrain-only, padding-row globals = −1)."""
    shape = (p, nbf)
    wl = np.full(shape, -1, np.int32)
    wg = np.full(shape, -1, np.int32)
    cl = np.full(shape, -1, np.int32)
    cg = np.full(shape, -1, np.int32)
    fr = np.zeros(shape, bool)
    flagged = rng.random(shape) < flag_fraction
    kind = rng.integers(0, 4, shape)  # 0=warn 1=change 2=both 3=forced
    warn = flagged & ((kind == 0) | (kind == 2))
    change = flagged & ((kind == 1) | (kind == 2))
    forced = flagged & (kind == 3)
    wl[warn] = rng.integers(0, 100, int(warn.sum()))
    # a detected row that was padding carries global −1 with local >= 0
    wg[warn] = np.where(
        rng.random(int(warn.sum())) < 0.9,
        rng.integers(0, 10_000, int(warn.sum())),
        -1,
    )
    cl[change] = rng.integers(0, 100, int(change.sum()))
    cg[change] = np.where(
        rng.random(int(change.sum())) < 0.9,
        rng.integers(0, 10_000, int(change.sum())),
        -1,
    )
    fr[forced] = True
    return FlagRows(wl, wg, cl, cg, fr)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("flag_fraction", [0.0, 0.05, 1.0])
def test_table_roundtrip_property(seed, flag_fraction):
    """compact (in-jit) → expand (host) is the identity on any flag plane
    that fits the capacity — including all-sentinel and fully-flagged."""
    rng = np.random.default_rng(seed)
    p, nbf = 5, 37
    flags = _random_flags(rng, p, nbf, flag_fraction)
    capacity = p * nbf  # covers every slot: overflow impossible
    table = np.asarray(
        jax.jit(compact_flag_table, static_argnums=1)(
            jax.tree.map(jnp.asarray, flags), capacity
        )
    )
    got = expand_flag_table(table, p, nbf)
    assert got is not None
    for name in FlagRows._fields:
        np.testing.assert_array_equal(
            getattr(got, name), getattr(flags, name), err_msg=name
        )
    # the embedded counter is the true flagged-slot count
    want_n = int(
        (
            (flags.warning_local >= 0)
            | (flags.change_local >= 0)
            | flags.forced_retrain
        ).sum()
    )
    assert int(table[-1, 0]) == want_n


def test_overflow_expand_refuses():
    """A table whose embedded count exceeds capacity is partial: expand
    returns None (the caller must fall back), never a truncated plane."""
    rng = np.random.default_rng(7)
    flags = _random_flags(rng, 4, 32, 0.5)
    n = int(
        (
            (flags.warning_local >= 0)
            | (flags.change_local >= 0)
            | flags.forced_retrain
        ).sum()
    )
    assert n > 3
    table = np.asarray(
        compact_flag_table(jax.tree.map(jnp.asarray, flags), 3)
    )
    assert int(table[-1, 0]) == n  # the true count survives the overflow
    assert expand_flag_table(table, 4, 32) is None


def test_auto_capacity_bounds():
    assert auto_compact_capacity(1, 10) == 10  # clamped to the slot count
    assert auto_compact_capacity(16, 1280) == 16 * 1280 // 8
    assert auto_compact_capacity(4, 100) == 64  # the floor


def _flags_equal(a: FlagRows, b: FlagRows):
    for name in FlagRows._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=name,
        )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("window", [1, 8])
def test_api_compact_matches_full_plane(seed, window):
    """The acceptance pin: compacted-collect drift flags reconstructed
    host-side are bit-identical to the full-plane path, ≥3 seeds × both
    engines (window=1 sequential scan, window>1 speculative window)."""
    cfg = RunConfig(
        dataset=f"synth:rialto,seed={seed}",
        mult_data=2,
        partitions=4,
        per_batch=50,
        model="centroid",
        window=window,
        window_rotations=1,
        seed=seed,
        results_csv="",
    )
    full = run(replace(cfg, collect="full"))
    comp = run(cfg)
    _flags_equal(full.flags, comp.flags)
    np.testing.assert_array_equal(full.drift_vote, comp.drift_vote)
    assert full.metrics.num_detections == comp.metrics.num_detections
    # the streams plant drift — a vacuous zero-detection pass would prove
    # nothing here (the zero case has its own test below)
    assert comp.metrics.num_detections > 0


def test_api_zero_detection_stream_compact():
    """Zero detections: the table is all sentinel fill with counter 0 and
    the reconstruction equals the (all-sentinel) full plane."""
    from distributed_drift_detection_tpu.io.stream import synthesize_stream

    # One concept, zero planted boundaries: a majority model on a
    # constant-label stream never errs, so no detector ever arms.
    rng = np.random.default_rng(0)
    X = rng.normal(size=(800, 4)).astype(np.float32)
    y = np.zeros(800, np.int64)
    stream = synthesize_stream(X, y, mult_data=1.0, standardize=False)
    cfg = RunConfig(
        dataset="unused",
        partitions=4,
        per_batch=50,
        model="majority",
        window=1,
        window_rotations=1,
        results_csv="",
    )
    full = run(replace(cfg, collect="full"), stream=stream)
    comp = run(cfg, stream=stream)
    assert comp.metrics.num_detections == 0
    assert not comp.flags.forced_retrain.any()
    _flags_equal(full.flags, comp.flags)


def test_api_overflow_falls_back_loudly():
    """A synthetic stream overflowing the compaction capacity must fall
    back to the full plane with a RuntimeWarning — flags still exact."""
    cfg = RunConfig(
        dataset="synth:rialto,seed=0",
        mult_data=2,
        partitions=4,
        per_batch=50,
        model="centroid",
        results_csv="",
    )
    full = run(replace(cfg, collect="full"))
    assert full.metrics.num_detections > 1  # capacity=1 must overflow
    with pytest.warns(RuntimeWarning, match="overflowed"):
        comp = run(replace(cfg, collect_capacity=1))
    _flags_equal(full.flags, comp.flags)
    np.testing.assert_array_equal(full.drift_vote, comp.drift_vote)


def test_validate_forces_full_plane():
    """validate=True is an escape hatch: the runner must not compact (the
    audit wants the device-produced plane), and the run still validates."""
    cfg = RunConfig(
        dataset="synth:rialto,seed=0",
        mult_data=2,
        partitions=4,
        per_batch=50,
        model="centroid",
        validate=True,
        results_csv="",
    )
    prep = prepare(cfg)
    out = (prep.exec_fn or prep.runner)(
        jax.tree.map(jnp.asarray, prep.batches), prep.keys
    )
    assert out.compact is None
    res = run(cfg)  # validate_flag_rows runs; must not raise
    assert res.metrics.num_detections > 0


def test_unknown_collect_mode_rejected():
    with pytest.raises(ValueError, match="collect mode"):
        prepare(
            RunConfig(
                dataset="synth:rialto,seed=0", collect="zip", results_csv=""
            )
        )


def test_host_flags_matches_unpack_on_full_runner():
    """host_flags on a full-plane result is exactly unpack_flags."""
    cfg = RunConfig(
        dataset="synth:rialto,seed=0",
        mult_data=2,
        partitions=2,
        per_batch=50,
        model="centroid",
        collect="full",
        results_csv="",
    )
    prep = prepare(cfg)
    out = (prep.exec_fn or prep.runner)(
        jax.tree.map(jnp.asarray, prep.batches), prep.keys
    )
    flags, info = host_flags(out)
    assert info["mode"] == "full" and not info["overflow"]
    _flags_equal(flags, unpack_flags(np.asarray(out.packed)))


def test_negative_collect_capacity_rejected():
    with pytest.raises(ValueError, match="collect_capacity"):
        prepare(
            RunConfig(
                dataset="synth:rialto,seed=0", collect_capacity=-1,
                results_csv="",
            )
        )


def test_run_completed_carries_collect_provenance(tmp_path):
    """The run log records which collect transport actually shipped —
    including the overflow fallback — so a fleet operator can see a
    stream that overflows the compaction capacity every run."""
    from distributed_drift_detection_tpu.telemetry.events import read_events

    cfg = RunConfig(
        dataset="synth:rialto,seed=0",
        mult_data=2,
        partitions=4,
        per_batch=50,
        model="centroid",
        telemetry_dir=str(tmp_path),
        results_csv="",
    )
    res = run(cfg)
    (done,) = [
        e for e in read_events(res.telemetry_path)
        if e["type"] == "run_completed"
    ]
    assert done["collect_mode"] == "compact"
    assert done["collect_overflow"] is False
    assert done["collect_events"] == res.metrics.num_detections

    with pytest.warns(RuntimeWarning, match="overflowed"):
        res2 = run(replace(cfg, collect_capacity=1))
    (done2,) = [
        e for e in read_events(res2.telemetry_path)
        if e["type"] == "run_completed"
    ]
    assert done2["collect_mode"] == "full" and done2["collect_overflow"] is True
