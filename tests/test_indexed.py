"""Compressed (indexed) stream path: bit-exact parity with materialized.

The indexed representation (``engine.loop.IndexedBatches``) is a transport
optimization — row table + index planes instead of the duplicated stream —
and must change nothing observable: striping, shuffling, flags, metrics.
"""

import numpy as np
import pytest

import jax

from distributed_drift_detection_tpu import DDMParams, RunConfig, replace, run
from distributed_drift_detection_tpu.engine import Batches, IndexedBatches
from distributed_drift_detection_tpu.engine.window import make_window_runner
from distributed_drift_detection_tpu.io import (
    materialize_batches,
    stripe_partitions,
    stripe_partitions_indexed,
    synthesize_stream,
)
from distributed_drift_detection_tpu.models import ModelSpec, build_model
from conftest import needs_reference

OUTDOOR = "/root/reference/outdoorStream.csv"


def small_stream(mult=4, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(120, 5)).astype(np.float32)
    y = rng.integers(0, 4, 120).astype(np.int64)
    return synthesize_stream(X, y, mult_data=mult, seed=seed)


def test_synthesize_keeps_compressed_form():
    s = small_stream(mult=4)
    assert s.src is not None and s.base_X is not None
    np.testing.assert_array_equal(s.X, s.base_X[s.src])
    np.testing.assert_array_equal(s.y, s.base_y[s.src])
    # every table row appears exactly `mult` times
    np.testing.assert_array_equal(np.bincount(s.src), np.full(120, 4))


def test_subsampled_stream_has_no_compressed_form():
    s = small_stream(mult=0.5)
    assert s.src is None


@pytest.mark.parametrize("shuffle_seed", [None, 7])
def test_indexed_striping_materializes_identically(shuffle_seed):
    s = small_stream(mult=6)
    p, b = 4, 11  # 720 rows / 4 → 180 → ragged 11-row grid (pad slots)
    dense = stripe_partitions(s, p, b, shuffle_seed=shuffle_seed)
    compressed = stripe_partitions_indexed(s, p, b, shuffle_seed=shuffle_seed)
    assert compressed.idx.dtype == np.int16  # 120-row table fits
    mat = materialize_batches(compressed)
    for a, c in zip(dense, mat):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


@pytest.mark.parametrize("shuffle", [False, True])
def test_window_runner_indexed_equals_dense(shuffle):
    """Same key, same window: the engine must not observe the representation."""
    s = small_stream(mult=7, seed=11)  # 840 rows / 2 → 420 → ragged at b=25
    p, b = 2, 25
    seed = None if shuffle else 5
    dense = stripe_partitions(s, p, b, shuffle_seed=seed)
    comp = stripe_partitions_indexed(s, p, b, shuffle_seed=seed)
    spec = ModelSpec(s.num_features, s.num_classes)
    model = build_model("centroid", spec)
    keys = jax.random.split(jax.random.key(0), p)

    run_d = make_window_runner(model, DDMParams(), window=5, shuffle=shuffle)
    run_i = make_window_runner(model, DDMParams(), window=5, shuffle=shuffle)
    fd = jax.jit(jax.vmap(run_d))(dense, keys)
    fi = jax.jit(jax.vmap(run_i, in_axes=(IndexedBatches(None, None, 0, 0, 0), 0)))(
        comp, keys
    )
    for a, c in zip(fd, fi):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_window_indexed_row_table_computes_in_f32():
    """The 'engines compute in f32' invariant (advisor round-5) must hold
    for the indexed plane layout too: a narrower transport dtype on the
    row table is cast on device before any model math — every predict and
    the carried batch_a see float32, and with values exactly representable
    in the narrow dtype the flags stay bit-identical to the f32 table."""
    rng = np.random.default_rng(0)
    T, F, nb, b = 24, 4, 12, 8
    # quarter-step values: exact in float16, so the cast is the ONLY
    # difference between the two runs
    base_X = (rng.integers(-32, 32, (T, F)).astype(np.float32) / 4.0)
    base_y = rng.integers(0, 3, T).astype(np.int32)
    idx = rng.integers(0, T, (nb, b)).astype(np.int32)
    rows = np.arange(nb * b, dtype=np.int32).reshape(nb, b)
    valid = np.ones((nb, b), bool)
    f32 = IndexedBatches(base_X, base_y, idx, rows, valid)
    f16 = f32._replace(base_X=base_X.astype(np.float16))

    model = build_model("centroid", ModelSpec(F, 3))
    seen = []
    orig_predict = model.predict
    spy = model._replace(
        predict=lambda p, X: (seen.append(X.dtype), orig_predict(p, X))[1]
    )
    run_w = jax.jit(make_window_runner(spy, DDMParams(), window=4, shuffle=False))
    key = jax.random.key(7)
    out16 = run_w(f16, key)
    assert seen and all(d == np.float32 for d in seen)  # recorded at trace
    out32 = run_w(f32, key)
    for a, c in zip(out32, out16):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


@needs_reference
def test_api_run_uses_indexed_path_and_matches_dense():
    """End-to-end: api.run on a duplicated outdoorStream must produce the
    same flags/metrics whether the compressed path is taken (window>1) or
    the dense sequential path (window=1)."""
    base = RunConfig(
        dataset=OUTDOOR,
        mult_data=8,
        partitions=4,
        per_batch=100,
        model="centroid",
        results_csv="",
    )
    fast = run(replace(base, window=8))
    slow = run(replace(base, window=1))
    np.testing.assert_array_equal(
        np.asarray(fast.flags.change_global), np.asarray(slow.flags.change_global)
    )
    np.testing.assert_array_equal(
        np.asarray(fast.flags.warning_global), np.asarray(slow.flags.warning_global)
    )
    assert fast.metrics.num_detections == slow.metrics.num_detections > 0
    np.testing.assert_array_equal(fast.metrics.delays, slow.metrics.delays)


# --------------------------------------------------------------------------
# Packed form (geometry planes synthesized on device)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("shuffle_seed", [None, 7])
def test_packed_expands_to_indexed_bitwise(shuffle_seed):
    """expand_packed must rebuild exactly the planes the host striper would
    have shipped — including the ragged padded tail."""
    from distributed_drift_detection_tpu.engine import expand_packed
    from distributed_drift_detection_tpu.io import stripe_partitions_packed

    s = small_stream(mult=6)
    p, b = 4, 11  # ragged grid: pad slots exercise the validity mask
    indexed = stripe_partitions_indexed(s, p, b, shuffle_seed=shuffle_seed)
    packed = stripe_partitions_packed(s, p, b, shuffle_seed=shuffle_seed)
    assert packed.perm.dtype == np.uint8  # b=11 ≤ 256 → one byte per element
    expanded = jax.jit(expand_packed)(packed)
    for name in indexed._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(indexed, name)),
            np.asarray(getattr(expanded, name)),
            err_msg=name,
        )


@pytest.mark.slow
def test_mesh_runner_packed_equals_indexed_sharded():
    """The packed transport changes nothing observable, sharded or not."""
    from distributed_drift_detection_tpu.io import stripe_partitions_packed
    from distributed_drift_detection_tpu.parallel.mesh import (
        make_mesh,
        make_mesh_runner,
        shard_batches,
    )

    s = small_stream(mult=8, seed=2)  # 960 rows
    p, b, seed = 8, 10, 9
    indexed = stripe_partitions_indexed(s, p, b, shuffle_seed=seed)
    packed = stripe_partitions_packed(s, p, b, shuffle_seed=seed)
    model = build_model("centroid", ModelSpec(s.num_features, s.num_classes))
    keys = jax.random.split(jax.random.key(0), p)

    outs = {}
    for mesh in (None, make_mesh(8)):
        r_idx = make_mesh_runner(
            model, DDMParams(), mesh, shuffle=False, window=4, indexed=True
        )
        r_pk = make_mesh_runner(
            model, DDMParams(), mesh, shuffle=False, window=4, packed=True
        )
        di, ki = shard_batches(indexed, keys, mesh)
        dp, kp = shard_batches(packed, keys, mesh)
        outs[mesh is None] = (r_idx(di, ki), r_pk(dp, kp))
    for _, (oi, op) in outs.items():
        np.testing.assert_array_equal(
            np.asarray(oi.packed), np.asarray(op.packed)
        )
        np.testing.assert_array_equal(
            np.asarray(oi.drift_vote), np.asarray(op.drift_vote)
        )
    # sharded == unsharded for the packed path too
    np.testing.assert_array_equal(
        np.asarray(outs[True][1].packed), np.asarray(outs[False][1].packed)
    )
