"""TRUE multi-process multihost test: ``jax.process_count() > 1`` in CI.

Spawns 2 (and 4) fresh processes on the CPU backend, wired together by
``jax.distributed`` over a local coordinator — the same control plane a TPU
pod uses over DCN — and drives the full ``parallel/multihost.py`` path in
each (see ``tests/multihost_worker.py``). This is the in-anger coverage the
single-process tests in ``test_multihost.py`` cannot give:
``shard_batches_global`` actually calls
``jax.make_array_from_process_local_data`` with per-host stripes, the mesh
spans processes, and the drift-vote all-reduce crosses the process
boundary. Matches the reference's central multi-node claim
(``DDM_Process.py:61-72``).

Takes ~1 min per topology (fresh JAX processes + distributed init).
"""

import os
import socket
import subprocess
import sys
import threading
import time

import pytest

# Fresh-process jax.distributed launches: ~30-60 s per topology × mode —
# the heaviest contracts in the suite, slow-tier by file (test_multihost.py
# keeps the single-process multihost seams in the fast tier).
pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(
    nproc: int,
    timeout: int = 420,
    mode: str = "plain",
    extra_env: dict | None = None,
) -> list:
    coord = f"127.0.0.1:{_free_port()}"
    from distributed_drift_detection_tpu.utils.hermetic import hermetic_cpu_env

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(_WORKER)))
    # n_devices=None: scrub inherited count-forcing; workers pin their own.
    env = hermetic_cpu_env(None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coord, str(nproc), str(pid), mode],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=repo_root,
        )
        for pid in range(nproc)
    ]
    # Collect concurrently under one shared deadline: if one worker dies at
    # distributed init, its peers hang at the coordinator rendezvous — a
    # sequential communicate() would time out on the hung peer first and
    # discard the real failure's output.
    outs = [None] * nproc
    threads = []
    for i, p in enumerate(procs):
        def drain(i=i, p=p):
            out, _ = p.communicate()
            outs[i] = (p.returncode, out)

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        threads.append(t)
    deadline = time.monotonic() + timeout
    try:
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(30)
    return [
        o if o is not None else (-9, "<no output: killed at deadline>")
        for o in outs
    ]


@pytest.mark.parametrize("mode", ["plain", "packed"])
@pytest.mark.parametrize("nproc", [2, 4])
def test_multiprocess_flags_match_single_device(nproc, mode):
    """Both data planes with process_count() > 1, both topologies: the
    dense/window=4 plane and the shipped flagship transport (packed
    compressed stream + window=64 — what bench.py measures)."""
    outs = _launch(nproc, mode=mode)
    for pid, (rc, out) in enumerate(outs):
        assert rc == 0, f"worker {pid}/{nproc} [{mode}] failed:\n{out[-4000:]}"
        assert f"worker {pid}/{nproc} [{mode}]: OK" in out, out[-2000:]


def test_two_process_correlate_smoke(tmp_path):
    """Fleet-observability smoke with a REAL process_count() == 2 control
    plane (ISSUE 3 CI criterion): each process writes its own identified
    run log; the merged timeline is deterministic (input order must not
    matter), and the correlator names the injected straggler — process 1
    sleeps 1.5 s inside its timed detect phase."""
    from distributed_drift_detection_tpu.telemetry.correlate import (
        correlate,
        group_run_logs,
        render_correlation,
    )

    tdir = str(tmp_path / "fleet")
    outs = _launch(
        2, mode="telemetry", extra_env={"DDD_FLEET_TELEMETRY_DIR": tdir}
    )
    for pid, (rc, out) in enumerate(outs):
        assert rc == 0, f"worker {pid}/2 [telemetry] failed:\n{out[-4000:]}"

    paths = group_run_logs(tdir)
    assert len(paths) == 2, paths
    one = correlate(paths)
    two = correlate(list(reversed(paths)))
    assert one["timeline"] == two["timeline"]  # deterministic merge
    assert render_correlation(one) == render_correlation(two)
    assert [h["process_index"] for h in one["hosts"]] == [0, 1]
    assert {h["hostname"] for h in one["hosts"]}  # identity extras present

    st = one["stragglers"]["detect"]
    assert st["slowest"] == 1, st  # the injected sleep
    assert st["spread_s"] > 0.5, st
    assert "slowest proc1" in render_correlation(one)
