"""Stream synthesis + striping semantics (reference C2/C8)."""

import numpy as np
import pytest

from distributed_drift_detection_tpu.io import (
    StreamData,
    load_stream,
    stripe_partitions,
    synthesize_stream,
)

from conftest import needs_reference

OUTDOOR = "/root/reference/outdoorStream.csv"


def toy_xy(n=100, f=3, classes=5, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.normal(size=(n, f)).astype(np.float32),
        rng.integers(0, classes, n).astype(np.int64),
    )


def test_synthesize_sorted_and_scaled():
    X, y = toy_xy()
    s = synthesize_stream(X, y, mult_data=3, seed=1, standardize=False)
    assert s.num_rows == 300
    assert np.all(np.diff(s.y) >= 0)  # sorted by target (:51)
    # duplication ×3 preserves per-class row counts ×3
    _, counts0 = np.unique(y, return_counts=True)
    _, counts = np.unique(s.y, return_counts=True)
    np.testing.assert_array_equal(counts, counts0 * 3)
    assert s.dist_between_changes == 300 // s.num_classes


def test_synthesize_subsample():
    X, y = toy_xy(n=200)
    s = synthesize_stream(X, y, mult_data=0.25, seed=2)
    assert s.num_rows == 50


@needs_reference
def test_outdoor_stream_geometry():
    """The shipped dataset: 4000 rows, 21 features, 40 equal concepts
    (SURVEY.md C16, verified empirically there)."""
    s = load_stream(OUTDOOR, mult_data=1)
    assert s.num_rows == 4000
    assert s.num_features == 21
    assert s.num_classes == 40
    assert s.dist_between_changes == 100
    counts = np.bincount(s.y)
    assert counts.min() == counts.max() == 100


@pytest.mark.parametrize("n,p,b", [(1000, 4, 50), (997, 8, 25), (40, 16, 7)])
def test_striping_round_robin(n, p, b):
    rng = np.random.default_rng(0)
    s = StreamData(
        X=rng.normal(size=(n, 3)).astype(np.float32),
        y=rng.integers(0, 4, n).astype(np.int32),
        num_classes=4,
        dist_between_changes=n // 4,
    )
    batches = stripe_partitions(s, p, b)
    assert batches.X.shape[0] == p
    valid = np.asarray(batches.valid)
    rows = np.asarray(batches.rows)
    assert valid.sum() == n  # no row lost, no row duplicated
    for part in range(p):
        r = rows[part][valid[part]]
        assert np.all(r % p == part)  # row i → partition i % P (:225)
        assert np.all(np.diff(r) == p)  # stream order preserved within part
    # content follows the rows index
    flatX = np.asarray(batches.X).reshape(-1, 3)[valid.reshape(-1)]
    np.testing.assert_array_equal(flatX, s.X[rows[valid]])


def test_striping_rectangular_equal_shapes():
    s = StreamData(
        X=np.zeros((103, 2), np.float32),
        y=np.zeros(103, np.int32),
        num_classes=1,
        dist_between_changes=103,
    )
    b = stripe_partitions(s, 4, 10)
    # 103/4 → 26 rows max per partition → 3 batches of 10
    assert b.X.shape == (4, 3, 10, 2)
    assert np.asarray(b.valid).sum() == 103


def test_prefetch_chunks_transparent():
    """prefetch_chunks yields the same chunks in order, and propagates
    producer exceptions."""
    from distributed_drift_detection_tpu.io import (
        generator_chunks,
        prefetch_chunks,
    )
    from distributed_drift_detection_tpu.io.synth import sea_chunk

    def chunks():
        return generator_chunks(
            lambda s, e: sea_chunk(seed=3, start=s, stop=e, drift_every=500),
            total_rows=20_000, partitions=4, per_batch=50, chunk_batches=5,
        )

    plain = list(chunks())
    fetched = list(prefetch_chunks(chunks(), depth=3))
    assert len(plain) == len(fetched)
    for a, b in zip(plain, fetched):
        for la, lb in zip(a, b):
            np.testing.assert_array_equal(la, lb)

    def boom():
        yield plain[0]
        raise RuntimeError("producer failed")

    it = prefetch_chunks(boom())
    next(it)
    try:
        next(it)
    except RuntimeError as e:
        assert "producer failed" in str(e)
    else:
        raise AssertionError("expected producer exception to propagate")


def test_prefetch_chunks_abandoned_consumer_stops_producer():
    import threading
    import time

    from distributed_drift_detection_tpu.io import prefetch_chunks

    produced = []

    def endless():
        i = 0
        while True:
            produced.append(i)
            yield i
            i += 1

    before = threading.active_count()
    it = prefetch_chunks(endless(), depth=1)
    assert next(it) == 0
    it.close()  # abandon: must release the parked producer thread
    time.sleep(0.6)
    assert threading.active_count() <= before + 1  # thread gone (or finishing)
    n = len(produced)
    time.sleep(0.4)
    assert len(produced) == n  # production actually stopped


def test_csv_chunks_equals_in_memory_chunking(tmp_path):
    """Streaming CSV ingest yields bit-identical chunks to loading the file
    and chunking in memory, across block-boundary carries and the padded
    final partial chunk."""
    from distributed_drift_detection_tpu.io import (
        chunk_stream_arrays,
        csv_chunks,
    )

    rng = np.random.default_rng(5)
    n, f = 2357, 4  # deliberately not a multiple of any chunk geometry
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = rng.integers(0, 7, n).astype(np.int32)
    path = tmp_path / "s.csv"
    cols = [f"f{i}" for i in range(2)] + ["target"] + [f"g{i}" for i in range(2)]
    with open(path, "w") as fh:
        fh.write(",".join(cols) + "\n")
        for i in range(n):
            row = [*X[i, :2], float(y[i]), *X[i, 2:]]
            fh.write(",".join(repr(float(v)) for v in row) + "\n")

    kw = dict(partitions=4, per_batch=25, chunk_batches=3, shuffle_seed=9)
    want = list(chunk_stream_arrays(X, y, **kw))
    # Tiny block size forces many partial-line carries.
    got = list(csv_chunks(str(path), 4, 25, 3, shuffle_seed=9, block_bytes=999))
    assert len(want) == len(got)
    for a, c in zip(want, got):
        for la, lb in zip(a, c):
            np.testing.assert_array_equal(la, lb)


def test_csv_chunks_malformed_raises(tmp_path):
    from distributed_drift_detection_tpu.io import csv_chunks

    path = tmp_path / "bad.csv"
    path.write_text("a,target\n1.0,0\nnope,1\n")
    with pytest.raises(ValueError):
        list(csv_chunks(str(path), 1, 2, 1))


def test_parse_block_native_matches_numpy():
    from distributed_drift_detection_tpu.io.native import (
        native_available,
        parse_block,
    )

    if not native_available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(300, 5)).astype(np.float32)
    block = "\n".join(
        ",".join(repr(float(v)) for v in row) for row in arr
    ).encode()
    out = parse_block(block, 5)
    np.testing.assert_allclose(out, arr, rtol=1e-6)
