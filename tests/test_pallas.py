"""Fused Pallas DDM kernel: exact parity with the XLA path.

``ops.ddm_pallas.ddm_window_pallas`` must be a bit-identical drop-in for
``ops.ddm.ddm_window`` — same f32 arithmetic, same tie rules, same −1
sentinels — on CPU it runs in the Pallas interpreter, so these tests validate
the kernel's logic (doubling prefix sums, payload min-scan, carried-state
merge) everywhere, not just on a TPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_drift_detection_tpu import DDMParams
from distributed_drift_detection_tpu.ops import ddm_init
from distributed_drift_detection_tpu.ops.ddm import DDMState, ddm_window
from distributed_drift_detection_tpu.ops.ddm_pallas import ddm_window_pallas

REF = DDMParams()


def random_state(rng) -> DDMState:
    """A plausible carried state mid-stream."""
    cnt = int(rng.integers(0, 400))
    p = float(rng.random() * 0.5)
    esum = p * cnt
    s = float(np.sqrt(max(p * (1 - p), 0.0) / max(cnt, 1)))
    return DDMState(
        count=jnp.int32(cnt),
        err_sum=jnp.float32(esum),
        ps_min=jnp.float32(p + s) if cnt else jnp.float32(np.inf),
        p_min=jnp.float32(p) if cnt else jnp.float32(np.inf),
        s_min=jnp.float32(s) if cnt else jnp.float32(np.inf),
    )


def assert_same(a, b):
    for la, lb, name in zip(a, b, type(a)._fields):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=name)


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("shape", [(1, 7), (4, 25), (6, 100), (16, 17)])
def test_window_parity_unbatched(seed, shape):
    rng = np.random.default_rng(seed)
    w, b = shape
    errs = (rng.random((w, b)) < rng.random() * 0.4).astype(np.float32)
    valid = rng.random((w, b)) < 0.9
    state = ddm_init() if seed % 2 else random_state(rng)

    end_x, res_x = jax.jit(lambda s, e, v: ddm_window(s, e, v, REF))(
        state, jnp.asarray(errs), jnp.asarray(valid)
    )
    end_p, res_p = jax.jit(lambda s, e, v: ddm_window_pallas(s, e, v, REF))(
        state, jnp.asarray(errs), jnp.asarray(valid)
    )
    assert_same(res_x, res_p)
    # End state comparable only when no change fired anywhere (after a change
    # the caller resets; ops.ddm documents the state as meaningless then).
    if not (np.asarray(res_x.first_change) >= 0).any():
        assert_same(end_x, end_p)


@pytest.mark.parametrize("seed", range(3))
def test_window_parity_vmapped(seed):
    """The engine's usage: vmap over partitions → kernel sublane axis."""
    rng = np.random.default_rng(100 + seed)
    p, w, b = 5, 4, 33
    errs = (rng.random((p, w, b)) < 0.2).astype(np.float32)
    valid = rng.random((p, w, b)) < 0.95
    states = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[random_state(rng) for _ in range(p)]
    )

    f_x = jax.jit(jax.vmap(lambda s, e, v: ddm_window(s, e, v, REF)))
    f_p = jax.jit(jax.vmap(lambda s, e, v: ddm_window_pallas(s, e, v, REF)))
    end_x, res_x = f_x(states, jnp.asarray(errs), jnp.asarray(valid))
    end_p, res_p = f_p(states, jnp.asarray(errs), jnp.asarray(valid))
    assert_same(res_x, res_p)
    ok = ~(np.asarray(res_x.first_change) >= 0).any(axis=(1,))
    for la, lb in zip(end_x, end_p):
        np.testing.assert_array_equal(np.asarray(la)[ok], np.asarray(lb)[ok])


def test_engine_end_to_end_parity():
    """Full window engine with ddm_impl='pallas' commits identical flags."""
    from distributed_drift_detection_tpu.engine.window import make_window_runner
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    from test_engine import planted_classification_stream, to_batches

    X, y = planted_classification_stream(
        np.random.default_rng(7), concepts=4, rows_per_concept=300, f=6
    )
    batches = to_batches(X, y, per_batch=40)
    model = build_model("centroid", ModelSpec(6, 4))
    key = jax.random.key(3)

    run_x = make_window_runner(model, REF, window=5)
    run_p = make_window_runner(model, REF, window=5, ddm_impl="pallas")
    fx = jax.jit(run_x)(batches, key)
    fp = jax.jit(run_p)(batches, key)
    assert_same(fx, fp)
    assert (np.asarray(fx.change_global) >= 0).any()
