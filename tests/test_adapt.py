"""adapt/ subsystem: drift-triggered live retraining with
champion/challenger serving (ISSUE 12).

Headline acceptance: on a planted-drift stream the adapting daemon's
post-drift error returns to pre-drift levels while ``on_drift=alert_only``
reproduces the policy-free daemon bit-exactly (flags AND verdict sidecar,
modulo wall-clock stamps); per-tenant adaptation triggers zero recompiles
of the serving chunk program (AOT executables keep serving, the jit
dispatch cache stays empty); champion/challenger promotion + demotion and
drain → checkpoint → bit-identical resume of mid-adaptation state are
covered below.
"""

import os

import numpy as np
import pytest

from distributed_drift_detection_tpu import RunConfig
from distributed_drift_detection_tpu.adapt import (
    AdaptPolicy,
    parse_policy,
    resolve_policies,
    should_demote,
    should_promote,
)
from distributed_drift_detection_tpu.adapt.policy import (
    resolve_cooldown_rows,
    resolve_window_rows,
)
from distributed_drift_detection_tpu.config import ServeParams
from distributed_drift_detection_tpu.io.synth import recurring_drift_xy
from distributed_drift_detection_tpu.serve import ServeRunner, read_verdicts
from distributed_drift_detection_tpu.serve.loadgen import format_lines
from distributed_drift_detection_tpu.telemetry.events import read_events


# -- policy grammar (jax-free) ----------------------------------------------


def test_parse_policy_grammar():
    assert parse_policy("retrain") == (None, AdaptPolicy(on_drift="retrain"))
    t, p = parse_policy("2=shadow,window_rows=400,margin=0.05")
    assert t == 2 and p.on_drift == "shadow"
    assert p.window_rows == 400 and p.margin == pytest.approx(0.05)
    with pytest.raises(ValueError, match="unknown on_drift policy"):
        parse_policy("promote")
    with pytest.raises(ValueError, match="knob"):
        parse_policy("retrain,bogus=1")
    with pytest.raises(ValueError, match="tenant prefix"):
        parse_policy("x=retrain")


def test_resolve_policies_overrides_and_defaults():
    ps = resolve_policies((), 3)
    assert all(p.on_drift == "alert_only" and not p.active for p in ps)
    ps = resolve_policies(["retrain", "1=shadow"], 3)
    assert [p.on_drift for p in ps] == ["retrain", "shadow", "retrain"]
    with pytest.raises(ValueError, match="targets tenant"):
        resolve_policies(["5=retrain"], 2)
    p = AdaptPolicy(on_drift="retrain")
    assert resolve_window_rows(p, 100) == 100
    assert resolve_cooldown_rows(p, 100) == 200
    assert resolve_window_rows(p._replace(window_rows=64), 100) == 64


def test_promotion_demotion_gates():
    assert should_promote(0.4, 0.1, margin=0.02)
    assert not should_promote(0.1, 0.09, margin=0.02)  # inside the margin
    assert not should_promote(None, 0.0, margin=0.02)  # no evidence
    assert should_demote(0.1, 0.4, margin=0.02)
    assert not should_demote(0.4, 0.1, margin=0.02)
    assert not should_demote(None, None, margin=0.02)


# -- in-process serving harness ---------------------------------------------


def _cfg(tmp, seed=0, tenants=1, **kw):
    return RunConfig(
        partitions=2,
        per_batch=25,
        model="centroid",
        results_csv="",
        seed=seed,
        window=1,
        tenants=tenants,
        telemetry_dir=str(tmp),
        data_policy="quarantine",
        **kw,
    )


def _params(features, classes, **kw):
    kw.setdefault("port", None)
    kw.setdefault("chunk_batches", 2)
    kw.setdefault("linger_s", 0.05)
    kw.setdefault("slo", ("none",))
    kw.setdefault("forensics", False)
    return ServeParams(num_features=features, num_classes=classes, **kw)


def _drive(runner, lines, block=100):
    for i in range(0, len(lines), block):
        runner.admission.admit_lines(lines[i : i + block])
    runner.batcher.flush()
    runner.request_stop()
    assert runner.serve_forever() == 0
    return runner


def _adapt_events(tmp):
    out = []
    for name in sorted(os.listdir(tmp)):
        if (
            not name.endswith(".jsonl")
            or ".verdicts" in name
            or ".quarantine" in name
            or name == "index.jsonl"
        ):
            continue
        out += [
            e
            for e in read_events(os.path.join(tmp, name))
            if e["type"] == "adaptation"
        ]
    return out


STREAM = recurring_drift_xy(seed=1, concepts=4, rows_per_concept=600)


# -- retrain: the paper's loop closed live ----------------------------------


def test_retrain_recovers_post_drift_error(tmp_path):
    X, y = STREAM
    r = ServeRunner(
        _cfg(tmp_path),
        _params(X.shape[1], 8, on_drift=("retrain",)),
        keep_flags=True,
    )
    r.start()
    _drive(r, format_lines(X, y))
    events = _adapt_events(tmp_path)
    assert events, "planted drift must trigger adaptations"
    assert all(e["policy"] == "retrain" and e["promoted"] for e in events)
    # the window refit measurably beats the stale/kernel-refit params on
    # the post-drift window (the headline error drop)
    drops = [
        e for e in events if e["err_before"] is not None and e["err_after"] is not None
    ]
    assert drops
    assert any(e["err_after"] < e["err_before"] - 0.1 for e in drops)
    # ... and the recovery watch saw post-drift chunk error return within
    # epsilon of the pre-drift running level
    assert r._adapt.recovery_rows() is not None
    # the adaptation left its statusz evidence (a final trigger may
    # legitimately still be accumulating when the stream ends)
    snap = r._adapt.snapshot()
    assert snap["adaptations"] == len(events)


def test_adaptation_events_schema_and_span(tmp_path):
    X, y = STREAM
    r = ServeRunner(
        _cfg(tmp_path), _params(X.shape[1], 8, on_drift=("retrain",))
    )
    r.start()
    _drive(r, format_lines(X, y))
    logs = [
        n
        for n in os.listdir(tmp_path)
        if n.endswith(".jsonl")
        and ".verdicts" not in n
        and ".quarantine" not in n
        and n != "index.jsonl"
    ]
    events = read_events(os.path.join(tmp_path, logs[0]))  # schema-valid
    adapts = [e for e in events if e["type"] == "adaptation"]
    spans = [
        e for e in events if e["type"] == "span" and e["name"] == "adaptation"
    ]
    assert adapts and len(spans) == len(adapts)
    for e in adapts:
        assert e["rows_to_apply"] >= 0 and e["rows_refit"] > 0
        assert e["applied_chunk"] >= e["trigger_chunk"]


# -- alert_only: bit-exact with the policy-free daemon -----------------------


def test_alert_only_matches_policy_free_daemon(tmp_path):
    X, y = STREAM
    runs = {}
    for tag, on_drift in (("free", ()), ("alert", ("alert_only",))):
        d = tmp_path / tag
        d.mkdir()
        r = ServeRunner(
            _cfg(d), _params(X.shape[1], 8, on_drift=on_drift),
            keep_flags=True,
        )
        r.start()
        _drive(r, format_lines(X, y))
        verdicts = read_verdicts(r.verdicts_path)
        runs[tag] = (r.flags(), verdicts, r._adapt)
    flags_free, v_free, adapt_free = runs["free"]
    flags_alert, v_alert, adapt_alert = runs["alert"]
    assert adapt_free is None and adapt_alert is None  # nothing built
    for name in flags_free._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(flags_free, name)),
            np.asarray(getattr(flags_alert, name)),
            err_msg=name,
        )
    # verdict sidecars byte-identical modulo the wall-clock fields
    # (ts, and the observatory's per-chunk lat_ms stage stamps)
    strip = lambda recs: [
        {k: v for k, v in r.items() if k not in ("ts", "lat_ms")}
        for r in recs
    ]
    assert strip(v_free) == strip(v_alert)
    # and the adapting run's flags genuinely differ (the reaction is real)
    e = tmp_path / "adapt"
    e.mkdir()
    r = ServeRunner(
        _cfg(e), _params(X.shape[1], 8, on_drift=("retrain",)),
        keep_flags=True,
    )
    r.start()
    _drive(r, format_lines(X, y))
    assert not np.array_equal(
        np.asarray(r.flags().change_global),
        np.asarray(flags_free.change_global),
    )


# -- shadow: champion/challenger --------------------------------------------


def test_shadow_promotes_on_measured_error(tmp_path):
    X, y = STREAM
    r = ServeRunner(
        _cfg(tmp_path), _params(X.shape[1], 8, on_drift=("shadow",))
    )
    r.start()
    _drive(r, format_lines(X, y))
    events = _adapt_events(tmp_path)
    promoted = [e for e in events if e["promoted"]]
    assert promoted, "the stale champion must lose on a real drift"
    for e in promoted:
        assert e["err_after"] < e["err_before"]  # the measured gate


def test_shadow_demotes_regressed_challenger(tmp_path):
    # Unit-drive the probation path: promote a challenger, then hand the
    # probation window rows the CHAMPION still wins on — the controller
    # must restore the champion's params (a params-only swap).
    import jax

    from distributed_drift_detection_tpu.adapt.refit import (
        AdaptationController,
    )
    from distributed_drift_detection_tpu.engine.chunked import ChunkedDetector
    from distributed_drift_detection_tpu.io.stream import stripe_chunk
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    X, y = recurring_drift_xy(
        seed=3, concepts=2, rows_per_concept=400, features=6, classes=4
    )
    model = build_model("centroid", ModelSpec(6, 4), RunConfig())
    P, B, CB = 2, 20, 2
    span = P * B * CB
    det = ChunkedDetector(model, partitions=P, seed=0, window=1)
    policies = resolve_policies(
        ["shadow,window_rows=80,margin=0.0,cooldown_rows=80"], 1
    )
    ctl = AdaptationController(
        det, policies, per_batch=B, num_features=6, rows_per_chunk=span
    )

    chunks = [
        stripe_chunk(X[s : s + span], y[s : s + span], s, P, B, CB)
        for s in range(0, 800, span)
    ]
    outcomes = []
    orig_count = ctl._count
    ctl._count = lambda t, st, outcome: (
        outcomes.append(outcome), orig_count(t, st, outcome)
    )
    rows = 0
    champ_host = None
    for i, c in enumerate(chunks):
        flags = jax.tree.map(np.asarray, det.feed(det.place(c)))
        rows += span
        st = ctl.states[0]
        if st.phase == "probation":
            # the retained champion of THIS probation cycle; the
            # probation window below replays the PRE-drift concept, so
            # the champion wins and the controller must demote
            champ_host = st.champion
        ctl.on_chunk(
            {"chunk": i, "rows_through": rows}, flags,
            chunks[0] if st.phase == "probation" else c,
        )
        if "demoted" in outcomes:
            break
    assert "promoted" in outcomes, "no promotion happened"
    assert "demoted" in outcomes, "regressed challenger was not demoted"
    restored = jax.device_get(ctl._tenant_params(0))
    for a, b in zip(jax.tree.leaves(champ_host), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- zero recompiles at T=64 -------------------------------------------------


def test_tenant_adaptation_zero_recompiles(tmp_path):
    from distributed_drift_detection_tpu.config import replace

    T = 64
    X, y = recurring_drift_xy(
        seed=1, concepts=2, rows_per_concept=100, features=6, classes=4
    )
    cfg = replace(_cfg(tmp_path, tenants=T), partitions=1, per_batch=10)
    r = ServeRunner(cfg, _params(6, 4, on_drift=("retrain",)))
    r.start()
    lines = format_lines(X, y)
    span = 20  # one tenant chunk span (P*B*CB = 1*10*2)
    # balanced dealing: every tenant receives the SAME planted-drift
    # stream, one span per round, so full-grid seals stay aligned
    for s in range(0, len(lines), span):
        for t in range(T):
            r.admissions[t].admit_lines(lines[s : s + span])
    r.batcher.flush()
    r.request_stop()
    assert r.serve_forever() == 0
    # the planted boundary adapts across the tenant plane (tenant seeds
    # differ, so a few detectors may fire a chunk early/late)
    assert r._adapt.snapshot()["adaptations"] >= T // 2
    # the serving chunk program NEVER recompiled: the AOT executables
    # served every feed (no sticky fallback) and the jit dispatch cache
    # stayed empty — the PR-6 counters, flat through T=64 adaptation
    assert r.det._exec_fallen is False
    assert r.det._run_chunk._cache_size() == 0
    assert len(r.det._exec) > 0
    # ... and every adaptation program compiled exactly once
    for name in ("_fit_window", "_score_pair", "_chunk_err", "_swap_full"):
        assert getattr(r._adapt, name)._cache_size() <= 1, name


# -- drain -> checkpoint -> bit-identical resume -----------------------------


def test_mid_adaptation_drain_resume_bit_identical(tmp_path):
    X, y = STREAM
    lines = format_lines(X, y)
    policy = "retrain,window_rows=200"  # spans 2 chunks: drains land mid-window

    def run_segments(d, segments):
        d.mkdir()
        ckpt = str(d / "serve.ckpt")
        flags = []
        for seg in segments:
            r = ServeRunner(
                _cfg(d),
                _params(
                    X.shape[1], 8, on_drift=(policy,), checkpoint=ckpt
                ),
                keep_flags=True,
            )
            r.start()
            _drive(r, seg)
            if r.flags() is not None:
                flags.append(r.flags())
        cg = np.concatenate(
            [np.asarray(f.change_global) for f in flags], axis=1
        )
        return cg, _adapt_events(d)

    cut = 700  # chunk-span aligned (7 x 100), mid-accumulation
    cg_split, ev_split = run_segments(
        tmp_path / "split", [lines[:cut], lines[cut:]]
    )
    cg_full, ev_full = run_segments(tmp_path / "full", [lines])
    assert os.path.exists(tmp_path / "split" / "serve.ckpt.adapt")
    np.testing.assert_array_equal(cg_split, cg_full)
    key = lambda e: {
        k: e[k]
        for k in ("tenant", "trigger_chunk", "policy", "rows_refit", "promoted")
    }
    assert [key(e) for e in ev_split] == [key(e) for e in ev_full]


# -- the chunked engine shares the code path ---------------------------------


def test_chunked_on_drift_hook():
    import jax

    from distributed_drift_detection_tpu.engine.chunked import ChunkedDetector
    from distributed_drift_detection_tpu.io.stream import stripe_chunk
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    X, y = STREAM
    model = build_model("centroid", ModelSpec(X.shape[1], 8), RunConfig())
    P, B, CB = 2, 25, 2
    span = P * B * CB

    def chunks():
        for s in range(0, len(X), span):
            yield stripe_chunk(X[s : s + span], y[s : s + span], s, P, B, CB)

    det = ChunkedDetector(
        model, partitions=P, seed=0, window=1, on_drift="retrain"
    )
    flags = det.run(chunks())
    assert det.adapt is not None and det.adapt.snapshot()["adaptations"] > 0
    assert det.adapt.recovery_rows() is not None

    # alert_only through the hook == no hook at all, bit-for-bit
    det0 = ChunkedDetector(model, partitions=P, seed=0, window=1)
    f0 = det0.run(chunks())
    det1 = ChunkedDetector(
        model, partitions=P, seed=0, window=1, on_drift="alert_only"
    )
    f1 = det1.run(chunks())
    for name in f0._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(f0, name)),
            np.asarray(getattr(f1, name)),
            err_msg=name,
        )
    # ... while the adapting drain genuinely reacted
    assert not np.array_equal(
        np.asarray(flags.change_global), np.asarray(f0.change_global)
    )
    del jax


# -- explain: cause and reaction in one view ---------------------------------


def test_explain_renders_adaptation_next_to_forensics(tmp_path, capsys):
    from distributed_drift_detection_tpu.telemetry.forensics import (
        main as explain_main,
    )

    X, y = STREAM
    r = ServeRunner(
        _cfg(tmp_path),
        _params(X.shape[1], 8, on_drift=("retrain",), forensics=True),
    )
    r.start()
    _drive(r, format_lines(X, y))
    explain_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert "drift @ row" in out
    assert "reaction" in out
    assert "policy=retrain" in out and "promoted" in out


# -- loadgen: delayed labels + refit attribution -----------------------------


def test_adapt_attribution_joins_verdicts_and_events():
    from distributed_drift_detection_tpu.serve.loadgen import (
        adapt_attribution,
    )

    verdicts = [
        {"chunk": 3, "ts": 100.0},
        {"chunk": 4, "ts": 101.0},
    ]
    events = [
        {
            "type": "adaptation",
            "trigger_chunk": 3,
            "ts": 100.5,
            "promoted": True,
            "rows_to_apply": 120,
        },
        {"type": "span"},
    ]
    rep = adapt_attribution(verdicts, events)
    assert rep["adaptations"] == 1 and rep["adapt_promoted"] == 1
    assert rep["adapt_latency_ms_p50"] == pytest.approx(500.0)
    assert rep["adapt_rows_to_apply_p50"] == 120
    empty = adapt_attribution([], [])
    assert empty["adaptations"] == 0
    assert empty["adapt_latency_ms_p50"] is None


def test_loadgen_delayed_labels_paces_rows():
    import time as _time

    from distributed_drift_detection_tpu.serve.loadgen import _send_rows

    class _Sock:
        def __init__(self):
            self.sent = []

        def sendall(self, data):
            self.sent.append((_time.monotonic(), data))

    lines = [f"{i},0" for i in range(20)]
    rate = 400.0
    sock_lag = _Sock()
    t0 = _time.monotonic()
    _send_rows(sock_lag, lines, rate, batch=4, label_lag=40)
    lag_first = sock_lag.sent[0][0] - t0
    # row 0 ships at row 40's pace slot: >= 40/rate seconds in
    assert lag_first >= 40 / rate * 0.8
    sock_now = _Sock()
    t0 = _time.monotonic()
    _send_rows(sock_now, lines, rate, batch=4)
    assert sock_now.sent[0][0] - t0 < 40 / rate * 0.8


def test_serve_params_on_drift_cli_roundtrip(tmp_path):
    # the serve CLI validates --on-drift specs jax-free at argv time
    from distributed_drift_detection_tpu.serve.runner import main as serve_main

    with pytest.raises(SystemExit) as exc:
        serve_main(
            [
                "--features", "5", "--classes", "3",
                "--on-drift", "nonsense",
            ]
        )
    assert exc.value.code == 2  # argparse error, before any jax work
