"""Multi-tenant stream plane (ISSUE 9 tentpole).

The acceptance contract: N tenants stacked into ONE compiled kernel —
each carrying its own detector + classifier state on the flattened
``(tenant, partition)`` leading axis — produce drift flags bit-identical
to N solo runs, on clean and quarantine-masked streams, across engines
(one-shot, chunked, soak) and collect transports; ragged tenant lengths
are absorbed by the validity plane (static shapes, no recompiles); and a
``tenants = 1`` plane is bit-identical to the pre-tenancy single-stream
path (the satellite property test, 3 seeds, both engines).
"""

import os

import numpy as np
import pytest

import jax

from distributed_drift_detection_tpu import RunConfig, run, run_multi
from distributed_drift_detection_tpu.config import (
    replace,
    tenant_configs,
    tenant_dataset,
)
from distributed_drift_detection_tpu.engine.chunked import ChunkedDetector
from distributed_drift_detection_tpu.engine.loop import stack_tenants
from distributed_drift_detection_tpu.io import planted_prototypes
from distributed_drift_detection_tpu.io.stream import stripe_chunk
from distributed_drift_detection_tpu.io.synth import rialto_like_xy
from distributed_drift_detection_tpu.models import ModelSpec, build_model
from distributed_drift_detection_tpu.parallel.mesh import (
    split_tenant_flags,
    tenant_drift_vote,
)

SEEDS = [0, 1, 2]


def _cfg(**kw):
    kw.setdefault("dataset", "synth:rialto,seed=3,rows_per_class=160")
    kw.setdefault("partitions", 4)
    kw.setdefault("per_batch", 50)
    kw.setdefault("model", "centroid")
    kw.setdefault("results_csv", "")
    return RunConfig(**kw)


def _assert_flags_equal(got, ref, msg=""):
    for name in ref._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(ref, name)),
            err_msg=f"{msg} {name}",
        )


# --- the satellite property test: T=1 == the single-stream path ----------


@pytest.mark.parametrize("seed", SEEDS)
def test_t1_one_shot_bit_identical_to_single_stream(seed):
    """A (tenant, partition) run with T=1 is the existing path, bit for
    bit: flags, vote, delay metrics — one-shot engine."""
    cfg = _cfg(seed=seed)
    solo = run(cfg)
    multi = run_multi(cfg)  # tenants=1: one tenant, the same config
    assert len(multi.results) == 1
    got = multi.results[0]
    _assert_flags_equal(got.flags, solo.flags, f"seed {seed}")
    np.testing.assert_array_equal(got.drift_vote, solo.drift_vote)
    assert got.metrics.num_detections == solo.metrics.num_detections
    np.testing.assert_array_equal(
        np.asarray(got.metrics.detections_per_partition),
        np.asarray(solo.metrics.detections_per_partition),
    )
    assert multi.rows == solo.stream.num_rows


@pytest.mark.parametrize("seed", SEEDS)
def test_t1_chunked_bit_identical_to_single_stream(seed):
    """T=1 through the tenant machinery (stack_tenants of one grid, a
    tenants=1 detector) equals the plain chunked path — chunked engine."""
    P, B, CB = 4, 50, 2
    span = P * B * CB
    X, y = rialto_like_xy(seed=seed, rows_per_class=3 * span // 10)
    model = build_model("centroid", ModelSpec(X.shape[1], 10))
    chunks = [
        stripe_chunk(
            X[k * span : (k + 1) * span],
            y[k * span : (k + 1) * span],
            k * span,
            P, B, CB,
            shuffle_seed=seed + 0x5EED,
        )
        for k in range(3)
    ]
    plain = ChunkedDetector(model, partitions=P, seed=seed)
    tenantized = ChunkedDetector(model, partitions=P, seed=seed, tenants=1)
    assert tenantized.partitions == P and tenantized.tenant_seeds == (seed,)
    for c in chunks:
        ref = plain.feed(c)
        got = tenantized.feed(stack_tenants([c]))  # T=1 stack == identity
        _assert_flags_equal(
            jax.tree.map(np.asarray, got),
            jax.tree.map(np.asarray, ref),
            f"seed {seed}",
        )


# --- N tenants in one kernel == N solo runs -------------------------------


def test_multi_tenant_ragged_one_shot_matches_solo_runs():
    """The headline acceptance: ragged per-tenant streams (different
    lengths AND seeds) stacked into one kernel produce per-tenant flags,
    votes and metrics bit-identical to the solo runs."""
    cfg = _cfg(
        dataset="synth:rialto,seed={tenant},rows_per_class=16{tenant}",
        tenants=3,
        seed=0,
    )
    assert tenant_dataset(cfg.dataset, 2).endswith("rows_per_class=162")
    multi = run_multi(cfg)
    lengths = set()
    for t, c in enumerate(tenant_configs(cfg)):
        solo = run(c)
        lengths.add(solo.stream.num_rows)
        got = multi.results[t]
        _assert_flags_equal(got.flags, solo.flags, f"tenant {t}")
        np.testing.assert_array_equal(got.drift_vote, solo.drift_vote)
        assert got.metrics.num_detections == solo.metrics.num_detections
    assert len(lengths) == 3  # genuinely ragged
    assert multi.rows == sum(lengths)
    assert multi.agg_rows_per_sec > 0


def test_multi_tenant_quarantine_masked_matches_solo():
    """Dirty-stream tenants: a quarantine-masked tenant stream through
    the stacked kernel equals its solo quarantine-masked run (the PR-5
    validity plane carries both the mask AND the ragged padding)."""
    from distributed_drift_detection_tpu.io.stream import StreamData

    streams = []
    for t in range(2):
        s = planted_prototypes(
            t, concepts=3, rows_per_concept=240, features=7
        )
        ok = np.ones(s.num_rows, bool)
        ok[np.arange(5 + 3 * t) * 7] = False  # tenant-specific mask
        streams.append(
            StreamData(
                X=s.X, y=s.y, num_classes=s.num_classes,
                dist_between_changes=s.dist_between_changes, row_ok=ok,
            )
        )
    cfgs = [_cfg(seed=t) for t in range(2)]
    multi = run_multi(cfgs, streams=streams)
    for t in range(2):
        solo = run(cfgs[t], stream=streams[t])
        _assert_flags_equal(
            multi.results[t].flags, solo.flags, f"tenant {t}"
        )


def test_multi_tenant_collect_full_matches_compact():
    """The tenant-aware collect: compacted detection table and full
    plane agree bit-for-bit on the stacked plane (overflow-free and the
    loud-fallback path are both exercised elsewhere; this pins tenant
    splitting on top)."""
    cfg = _cfg(
        dataset="synth:rialto,seed={tenant},rows_per_class=200",
        tenants=2,
    )
    compact = run_multi(cfg)
    full = run_multi(replace(cfg, collect="full"))
    for t in range(2):
        _assert_flags_equal(
            compact.results[t].flags, full.results[t].flags, f"tenant {t}"
        )


def test_multi_tenant_chunked_matches_solo_detectors():
    """Chunked engine: a tenants=T detector fed stacked chunks equals T
    solo detectors fed the per-tenant chunks — state carried across
    chunks per (tenant, partition)."""
    P, B, CB, T = 4, 50, 2, 3
    span = P * B * CB
    model = build_model("centroid", ModelSpec(27, 10))

    def chunks_for(seed):
        X, y = rialto_like_xy(seed=seed, rows_per_class=3 * span // 10)
        return [
            stripe_chunk(
                X[k * span : (k + 1) * span],
                y[k * span : (k + 1) * span],
                k * span, P, B, CB,
                shuffle_seed=seed + 0x5EED,
            )
            for k in range(3)
        ]

    tenant_chunks = [chunks_for(7 + t) for t in range(T)]
    solos = [
        ChunkedDetector(model, partitions=P, seed=7 + t) for t in range(T)
    ]
    plane = ChunkedDetector(model, partitions=P, seed=7, tenants=T)
    assert plane.tenant_seeds == (7, 8, 9)
    assert plane.partitions == T * P
    for k in range(3):
        stacked = plane.feed(
            stack_tenants([tenant_chunks[t][k] for t in range(T)])
        )
        per = plane.tenant_flags(jax.tree.map(np.asarray, stacked))
        for t in range(T):
            ref = jax.tree.map(np.asarray, solos[t].feed(tenant_chunks[t][k]))
            _assert_flags_equal(per[t], ref, f"chunk {k} tenant {t}")


def test_tenant_checkpoint_roundtrip(tmp_path):
    """save_tenant writes a solo-shaped checkpoint a T=1 detector can
    restore (tenant migration), and restore_tenant scatters one back into
    a slot without touching the others."""
    P, B, CB, T = 4, 50, 2, 2
    span = P * B * CB
    model = build_model("centroid", ModelSpec(27, 10))

    def chunks_for(seed):
        X, y = rialto_like_xy(seed=seed, rows_per_class=2 * span // 10)
        return [
            stripe_chunk(
                X[k * span : (k + 1) * span],
                y[k * span : (k + 1) * span],
                k * span, P, B, CB,
                shuffle_seed=seed + 0x5EED,
            )
            for k in range(2)
        ]

    tenant_chunks = [chunks_for(11 + t) for t in range(T)]
    plane = ChunkedDetector(model, partitions=P, seed=11, tenants=T)
    for k in range(2):
        plane.feed(stack_tenants([tenant_chunks[t][k] for t in range(T)]))
    path = os.path.join(tmp_path, "t1.ckpt")
    plane.save_tenant(path, 1)

    def leaves_np(tree):
        import jax.numpy as jnp

        def conv(x):
            if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
                return np.asarray(jax.random.key_data(x))
            return np.asarray(x)

        return [conv(x) for x in jax.tree.leaves(tree)]

    # solo restore == a solo detector that consumed the same stream
    solo = ChunkedDetector(model, partitions=P, seed=99)
    meta = solo.restore(path, example_chunk=tenant_chunks[0][0])
    assert meta["tenant"] == 1 and meta["partitions"] == P
    ref = ChunkedDetector(model, partitions=P, seed=12)
    for c in tenant_chunks[1]:
        ref.feed(c)
    for a, b in zip(leaves_np(solo.carry), leaves_np(ref.carry)):
        np.testing.assert_array_equal(a, b)

    # scatter into slot 0: slot 0 becomes tenant 1's state, slot 1 intact
    before_t1 = leaves_np(plane.tenant_carry(1))
    plane.restore_tenant(path, 0)
    for a, b in zip(leaves_np(plane.tenant_carry(0)), before_t1):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(leaves_np(plane.tenant_carry(1)), before_t1):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_soak_tenants_match_solo_runs():
    """Soak engine: tenants=T generates and detects exactly what T solo
    soaks keyed by split(key, T) would — one device program."""
    from distributed_drift_detection_tpu.engine.soak import make_soak_runner

    model = build_model("centroid", ModelSpec(8, 8))
    geo = dict(partitions=4, per_batch=100, num_batches=40, drift_every=1000)
    multi = jax.jit(make_soak_runner(model, tenants=3, **geo))
    key = jax.random.key(5)
    out = multi(key)
    assert out.rows_processed == 3 * 4 * 40 * 100
    solo = jax.jit(make_soak_runner(model, **geo))
    tkeys = jax.random.split(key, 3)
    for t in range(3):
        ref = solo(tkeys[t])
        got = jax.tree.map(
            lambda x: np.asarray(x)[t * 4 : (t + 1) * 4], out.flags
        )
        _assert_flags_equal(got, jax.tree.map(np.asarray, ref.flags), f"t{t}")


# --- plane plumbing -------------------------------------------------------


def test_stack_tenants_ragged_padding_and_geometry_checks():
    a = stripe_chunk(
        np.ones((100, 3), np.float32), np.zeros(100, np.int32), 0, 2, 10, 5
    )
    b = stripe_chunk(
        np.ones((40, 3), np.float32), np.zeros(40, np.int32), 0, 2, 10, 2
    )
    stacked = stack_tenants([a, b])
    assert stacked.y.shape == (4, 5, 10)
    # tenant 1's ragged padding is fully masked, sentinel rows
    assert not stacked.valid[2:, 2:].any()
    assert (stacked.rows[2:, 2:] == -1).all()
    # real content untouched
    np.testing.assert_array_equal(stacked.X[:2], a.X)
    np.testing.assert_array_equal(stacked.valid[2:, :2], b.valid[:, :2])
    with pytest.raises(ValueError, match="partitions/per_batch"):
        stack_tenants(
            [a, stripe_chunk(
                np.ones((10, 3), np.float32), np.zeros(10, np.int32),
                0, 4, 10, 1,
            )]
        )


def test_split_tenant_flags_and_votes():
    from distributed_drift_detection_tpu.engine.loop import FlagRows

    tp, nbf = 6, 5
    rng = np.random.default_rng(0)
    cg = rng.integers(-1, 30, size=(tp, nbf)).astype(np.int32)
    flags = FlagRows(
        warning_local=cg.copy(), warning_global=cg.copy(),
        change_local=cg.copy(), change_global=cg,
        forced_retrain=cg >= 0,
    )
    per = split_tenant_flags(flags, 3, flag_cols=[5, 4, 2])
    assert [f.change_global.shape for f in per] == [(2, 5), (2, 4), (2, 2)]
    np.testing.assert_array_equal(per[1].change_global, cg[2:4, :4])
    v = tenant_drift_vote(per[0])
    np.testing.assert_allclose(
        v, (cg[:2] >= 0).astype(np.float32).mean(axis=0)
    )
    with pytest.raises(ValueError, match="does not split"):
        split_tenant_flags(flags, 4)


def test_run_and_prepare_reject_multi_tenant_config():
    from distributed_drift_detection_tpu.api import prepare

    cfg = _cfg(tenants=2)
    with pytest.raises(ValueError, match="run_multi"):
        run(cfg)
    with pytest.raises(ValueError, match="prepare_multi"):
        prepare(cfg)


def test_prepare_multi_rejects_kernel_mismatch():
    from distributed_drift_detection_tpu.api import prepare_multi

    a = _cfg(seed=0)
    b = _cfg(seed=1, per_batch=25)
    with pytest.raises(ValueError, match="different kernel"):
        prepare_multi([a, b])


def test_prepare_multi_keeps_explicit_window_disagreement_loud():
    """Plane-wide pinning covers AUTO knobs only: an EXPLICIT per-tenant
    window disagreement must reach the kernel-identity check and raise —
    never be silently overwritten with tenant 0's value."""
    from distributed_drift_detection_tpu.api import prepare_multi

    a = _cfg(seed=0, window=1)
    b = _cfg(seed=1, window=4)
    with pytest.raises(ValueError, match="different kernel"):
        prepare_multi([a, b])


def test_prepare_multi_pins_only_the_auto_ph_threshold():
    """The PH pin covers the auto λ alone: explicit per-tenant
    delta/alpha fields must reach the identity check and raise on
    disagreement, not be clobbered by tenant 0's whole PHParams."""
    from distributed_drift_detection_tpu.api import prepare_multi
    from distributed_drift_detection_tpu.config import PHParams

    a = _cfg(seed=0, detector="ph")  # threshold=0 (auto)
    b = _cfg(seed=1, detector="ph", ph=PHParams(delta=0.02))  # auto λ too
    with pytest.raises(ValueError, match="different kernel"):
        prepare_multi([a, b])


def test_tenant_configs_expansion():
    cfg = _cfg(dataset="synth:rialto,seed={tenant}", tenants=3, seed=10)
    cfgs = tenant_configs(cfg)
    assert [c.seed for c in cfgs] == [10, 11, 12]
    assert [c.dataset for c in cfgs] == [
        f"synth:rialto,seed={t}" for t in range(3)
    ]
    assert all(c.tenants == 1 for c in cfgs)
    with pytest.raises(ValueError, match=">= 1"):
        tenant_configs(replace(cfg, tenants=0))


def test_telemetry_payload_carries_tenants():
    from distributed_drift_detection_tpu.config import (
        telemetry_config_payload,
    )

    solo = telemetry_config_payload(_cfg())
    assert "tenants" not in solo  # pre-tenancy digests must keep matching
    multi = telemetry_config_payload(_cfg(tenants=4))
    assert multi["tenants"] == 4


# --- serving plane --------------------------------------------------------


def _serve_params(features, classes, **kw):
    from distributed_drift_detection_tpu.config import ServeParams

    kw.setdefault("port", None)
    kw.setdefault("chunk_batches", 2)
    kw.setdefault("linger_s", 0.05)
    return ServeParams(num_features=features, num_classes=classes, **kw)


def test_tenant_microbatcher_balanced_seal_and_ragged_linger():
    from distributed_drift_detection_tpu.serve import TenantMicroBatcher

    tb = TenantMicroBatcher(
        2, 2, 10, 2, num_features=3, linger_s=0.01, shuffle_seeds=[None, None]
    )
    span = tb.rows_per_chunk  # 40 per tenant
    X = np.arange(span * 3, dtype=np.float32).reshape(span, 3)
    y = np.zeros(span, np.int32)
    # balanced: both tenants full -> seal immediately, full grid
    tb.push(0, X, y)
    assert tb.depth()["queued_chunks"] == 0  # waits for tenant 1
    tb.push(1, X, y)
    item = tb.get(0.5)
    assert item is not None and not item.meta["short"]
    assert item.meta["tenants"] == 2
    assert item.meta["t_rows"] == [span, span]
    assert item.chunk.y.shape == (4, 2, 10)  # stacked [T·P, CB, B]
    assert item.chunk.valid.all()
    # ragged: only tenant 0 has rows -> linger seal, tenant 1 fully masked
    tb.push(0, X[: span // 2], y[: span // 2])
    item = tb.get(1.0)
    assert item is not None and item.meta["short"]
    assert item.meta["t_rows"] == [span // 2, 0]
    assert not item.chunk.valid[2:].any()  # tenant 1's block is padding
    # every tenant's position advanced by the full span both times
    assert tb.start_rows == [2 * span, 2 * span]


def test_tenant_microbatcher_skew_bound_keeps_hot_tenant_live():
    """Under skewed traffic (one hot tenant, one idle) the hot tenant's
    buffer is bounded: crossing max_buffer_spans forces a partial seal
    even though the balanced full seal can never fire and the linger
    deadline is far away."""
    from distributed_drift_detection_tpu.serve import TenantMicroBatcher

    tb = TenantMicroBatcher(
        2, 2, 10, 2, num_features=3, linger_s=60.0,
        shuffle_seeds=[None, None], max_buffer_spans=2,
    )
    span = tb.rows_per_chunk
    X = np.zeros((span, 3), np.float32)
    y = np.zeros(span, np.int32)
    tb.push(0, X, y)
    assert tb.depth()["queued_chunks"] == 0  # below the bound: buffered
    tb.push(0, X, y)  # crosses 2 spans -> forced partial seal
    d = tb.depth()
    assert d["queued_chunks"] == 1
    assert d["tenant_buffered_rows"] == [span, 0]
    item = tb.get(0.5)
    assert item.meta["t_rows"] == [span, 0]
    assert not item.chunk.valid[2:].any()  # idle tenant fully masked


def test_serve_multi_tenant_parity_and_verdict_attribution(tmp_path,
                                                           monkeypatch):
    """The serving acceptance: a 2-tenant daemon fed balanced interleaved
    per-tenant traffic produces per-tenant flags bit-identical to the
    solo batch runs, with per-tenant verdict attribution in the sidecar."""
    from distributed_drift_detection_tpu.serve import (
        ServeRunner,
        read_verdicts,
    )
    from distributed_drift_detection_tpu.serve.loadgen import format_lines

    monkeypatch.chdir(tmp_path)
    T, P, B, CB = 2, 4, 50, 2
    span = P * B * CB
    cfg = RunConfig(
        partitions=P, per_batch=B, model="centroid", seed=5,
        data_policy="quarantine", results_csv="", window=1, tenants=T,
    )
    streams = [
        planted_prototypes(5 + t, concepts=3, rows_per_concept=400,
                           features=7)
        for t in range(T)
    ]
    params = _serve_params(7, streams[0].num_classes)
    runner = ServeRunner(cfg, params, keep_flags=True)
    runner.start()
    lines = [format_lines(s.X, s.y) for s in streams]
    for base in range(0, len(lines[0]), span):
        for t in range(T):
            runner.admissions[t].admit_lines(lines[t][base : base + span])
    runner.batcher.flush()
    runner.request_stop()
    assert runner.serve_forever() == 0
    per = split_tenant_flags(runner.flags(), T)
    any_detections = False
    for t, c in enumerate(tenant_configs(cfg)):
        ref = run(replace(c, data_policy="strict"), stream=streams[t]).flags
        w = np.asarray(ref.change_global).shape[1]
        any_detections = any_detections or (
            np.asarray(ref.change_global) >= 0
        ).any()
        for name in ref._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(per[t], name))[:, :w],
                np.asarray(getattr(ref, name)),
                err_msg=f"tenant {t} {name}",
            )
        assert np.all(np.asarray(per[t].change_global)[:, w:] == -1)
    assert any_detections  # parity of all-sentinel tables proves nothing
    recs = read_verdicts(runner.verdicts_path)
    assert recs and all(len(r["tenants"]) == T for r in recs)
    for r in recs:
        assert sum(e["detections"] for e in r["tenants"]) == r["detections"]
        # tenant-local change indices stay inside the tenant's partitions
        for e in r["tenants"]:
            assert all(0 <= p < P for p, _, _ in e["changes"])


def test_ingress_tenant_line_routes(tmp_path, monkeypatch):
    """Wire-level routing: TENANT k sends a connection's rows to tenant
    k's admission controller; an out-of-range id rejects ONLY that
    connection (ERR + drop) — the daemon and the other tenants keep
    serving (tenant isolation)."""
    import socket
    import threading

    from distributed_drift_detection_tpu.serve import ServeRunner
    from distributed_drift_detection_tpu.serve.loadgen import format_lines

    monkeypatch.chdir(tmp_path)
    T = 2
    s = planted_prototypes(3, concepts=2, rows_per_concept=200, features=7)
    cfg = RunConfig(
        partitions=2, per_batch=20, model="centroid", seed=1,
        data_policy="quarantine", results_csv="", window=1, tenants=T,
    )
    params = _serve_params(7, s.num_classes, port=0, chunk_batches=2,
                           linger_s=0.05)
    runner = ServeRunner(cfg, params)
    banner = runner.start()
    th = threading.Thread(target=runner.serve_forever, daemon=True)
    th.start()
    lines = format_lines(s.X[:60], s.y[:60])
    with socket.create_connection(("127.0.0.1", banner["port"])) as sock:
        sock.sendall(
            ("\n".join(lines[:30]) + "\nTENANT 1\n"
             + "\n".join(lines[30:]) + "\nFLUSH\n").encode()
        )
    deadline = 30
    import time as _t

    t0 = _t.monotonic()
    while _t.monotonic() - t0 < deadline:
        if (runner.admissions[0].rows_seen == 30
                and runner.admissions[1].rows_seen == 30):
            break
        _t.sleep(0.05)
    assert runner.admissions[0].rows_seen == 30
    assert runner.admissions[1].rows_seen == 30
    # out-of-range tenant: ERR + that connection dropped, daemon alive
    with socket.create_connection(("127.0.0.1", banner["port"])) as sock:
        sock.sendall(b"TENANT 9\n")
        resp = sock.recv(1024)
        assert b"ERR" in resp
        # the connection was closed by the server after the rejection
        sock.settimeout(10)
        assert sock.recv(1024) == b""
    assert runner.batcher.poisoned() is None
    assert th.is_alive()  # other tenants keep serving
    # a fresh connection still admits (tenant isolation held)
    with socket.create_connection(("127.0.0.1", banner["port"])) as sock:
        sock.sendall(("TENANT 1\n" + lines[0] + "\nFLUSH\n").encode())
    t0 = _t.monotonic()
    while _t.monotonic() - t0 < deadline:
        if runner.admissions[1].rows_seen == 31:
            break
        _t.sleep(0.05)
    assert runner.admissions[1].rows_seen == 31
    runner.request_stop()
    th.join(timeout=60)
    assert not th.is_alive()
