"""Serve-pipeline observatory (telemetry.pipeline + the serve runner's
stage clock): conservation, attribution, sidecar bit-parity with the
instrumentation off, the jax-free ``pipeline`` CLI, and the fleet
aggregation plane (``/fleetz`` + ``fleet_*`` series).

The two properties ISSUE 16's acceptance pins:

* **Conservation** — the serve loop is single-threaded, so the sum of
  per-stage busy seconds can never exceed serve-loop wall-clock, and the
  row ledger balances: rows admitted == rows sealed == rows published.
* **Bit-parity** — the stage clocks live outside the dispatch path:
  verdict sidecars with instrumentation on vs ``--no-pipeline-metrics``
  are identical modulo wall-clock fields (``ts``, ``lat_ms``).
"""

import json
import os

import numpy as np
import pytest

from distributed_drift_detection_tpu import RunConfig
from distributed_drift_detection_tpu.config import ServeParams
from distributed_drift_detection_tpu.io import planted_prototypes
from distributed_drift_detection_tpu.serve import ServeRunner
from distributed_drift_detection_tpu.serve.loadgen import (
    _stage_split,
    format_lines,
)
from distributed_drift_detection_tpu.telemetry import pipeline as pl
from distributed_drift_detection_tpu.telemetry.metrics import MetricsRegistry
from distributed_drift_detection_tpu.telemetry.ops import OpsServer


# -- attribution units (jax-free) --------------------------------------------


def test_dominant_stage_excludes_seal_wait():
    busy = {"seal_wait": 10.0, "device": 2.0, "publish": 1.0}
    assert pl.dominant_stage(busy) == "device"


def test_dominant_stage_idle_loop_names_seal_wait():
    assert pl.dominant_stage({"seal_wait": 3.0}) == "seal_wait"
    assert pl.dominant_stage({}) is None
    assert pl.dominant_stage({"device": 0.0}) is None


def test_attribute_shares_utilization_ceiling():
    busy = {"device": 3.0, "collect": 1.0}
    rep = pl.attribute(busy, wall_s=8.0, rows=4000)
    assert rep["dominant_stage"] == "device"
    assert rep["busy_total_s"] == 4.0
    assert rep["coverage"] == 0.5
    # stages come busy-ordered, dominant first
    assert list(rep["stages"]) == ["device", "collect"]
    dev = rep["stages"]["device"]
    assert dev["share"] == 0.75
    assert dev["utilization"] == 0.375
    assert dev["ceiling_rows_per_sec"] == pytest.approx(4000 / 3.0, rel=1e-3)
    assert sum(c["share"] for c in rep["stages"].values()) == pytest.approx(1.0)


def test_stage_clock_mirrors_registry_and_guards_negatives():
    reg = MetricsRegistry()
    clock = pl.ServeStageClock(reg)
    clock.add("device", 1.5)
    clock.add("device", 0.5)
    clock.add("publish", -3.0)  # clock skew: dropped, not crashed
    clock.add("publish", 0.25)
    assert clock.busy == {"device": 2.0, "publish": 0.25}
    assert pl.serve_stage_breakdown(reg) == {"device": 2.0, "publish": 0.25}


def test_render_report_names_dominant_stage():
    rep = pl.attribute({"device": 3.0, "feed": 1.0}, wall_s=5.0, rows=100)
    rep["source"] = "unit.prom"
    text = pl.render_report(rep)
    assert "dominant stage: device" in text
    assert "unit.prom" in text
    assert "coverage 80.0%" in text


# -- CLI (jax-free) ----------------------------------------------------------


_PROM = """\
# HELP serve_stage_busy_seconds_total busy
# TYPE serve_stage_busy_seconds_total counter
serve_stage_busy_seconds_total{stage="device"} 6.0
serve_stage_busy_seconds_total{stage="collect"} 1.0
serve_stage_busy_seconds_total{stage="seal_wait"} 2.0
# HELP serve_loop_wall_seconds wall
# TYPE serve_loop_wall_seconds gauge
serve_loop_wall_seconds 10.0
# HELP serve_rows_published rows
# TYPE serve_rows_published gauge
serve_rows_published 1200
"""


def test_pipeline_cli_prom_golden(tmp_path, capsys):
    prom = tmp_path / "run.prom"
    prom.write_text(_PROM)
    assert pl.main([str(prom)]) == 0
    out = capsys.readouterr().out
    assert "dominant stage: device" in out
    assert "rows published 1200" in out

    assert pl.main([str(prom), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["dominant_stage"] == "device"
    assert rep["wall_s"] == 10.0
    assert rep["rows"] == 1200
    assert rep["stages"]["device"]["ceiling_rows_per_sec"] == 200.0
    assert rep["coverage"] == pytest.approx(0.9)


def test_pipeline_cli_run_log_sibling(tmp_path, capsys):
    (tmp_path / "run.prom").write_text(_PROM)
    (tmp_path / "run.jsonl").write_text("")
    assert pl.main([str(tmp_path / "run.jsonl")]) == 0
    assert "dominant stage: device" in capsys.readouterr().out


def test_pipeline_cli_errors_exit_2(tmp_path, capsys):
    assert pl.main([str(tmp_path / "missing.prom")]) == 2
    empty = tmp_path / "empty.prom"
    empty.write_text("# nothing here\n")
    assert pl.main([str(empty)]) == 2
    err = capsys.readouterr().err
    assert "no serve" in err or "no-pipeline-metrics" in err


# -- fleet aggregation (jax-free) --------------------------------------------


def _statusz(rows, rps, busy, wall):
    return {
        "rows": {"published": rows},
        "rows_per_sec": rps,
        "pipeline": {"busy_s": busy, "wall_s": wall},
    }


def test_aggregate_fleet_sums_and_maxes():
    b0 = pl.backend_snapshot(
        "b0", _statusz(100, 50.0, {"device": 3.0, "collect": 1.0}, 5.0)
    )
    b1 = pl.backend_snapshot(
        "b1", _statusz(300, 150.0, {"publish": 2.0, "device": 0.5}, 5.0)
    )
    dead = pl.backend_snapshot("b2", None)
    fz = pl.aggregate_fleet([b0, b1, dead])
    fleet = fz["fleet"]
    assert fleet["backends"] == 3 and fleet["alive"] == 2
    assert fleet["rows"] == 400
    assert fleet["rows_per_sec"] == pytest.approx(200.0)
    assert fleet["bottlenecks"] == {"b0": "device", "b1": "publish"}
    assert fleet["stage_busy_share_max"]["device"] == {
        "share": 0.75,
        "backend": "b0",
    }
    assert fleet["stage_busy_share_max"]["publish"]["backend"] == "b1"
    assert fz["backends"][2] == {"name": "b2", "alive": False}


def test_backend_snapshot_metrics_text_fallback():
    # a backend whose /statusz predates the pipeline section still gets
    # attributed from its /metrics exposition scrape
    snap = pl.backend_snapshot(
        "old", {"rows": {"published": 7}, "rows_per_sec": 3.5}, _PROM
    )
    assert snap["alive"] and snap["bottleneck"] == "device"
    assert snap["busy_share"]["device"] == pytest.approx(6.0 / 9.0, rel=1e-3)


def test_fleet_metrics_lines_prometheus_shape():
    fz = pl.aggregate_fleet(
        [pl.backend_snapshot("b0", _statusz(10, 5.0, {"device": 1.0}, 2.0))]
    )
    text = "\n".join(pl.fleet_metrics_lines(fz))
    assert "fleet_rows_per_sec 5.0" in text
    assert "fleet_backends_alive 1" in text
    assert 'fleet_stage_busy_share_max{stage="device"} 1.0' in text
    assert 'fleet_backend_bottleneck{backend="b0",stage="device"} 1' in text


def test_fleetz_endpoint_serves_aggregate():
    import urllib.request

    fz = pl.aggregate_fleet(
        [pl.backend_snapshot("b0", _statusz(10, 5.0, {"device": 1.0}, 2.0))]
    )
    srv = OpsServer(
        "127.0.0.1",
        0,
        metrics_fn=lambda: "",
        health_fn=lambda: (200, {}),
        status_fn=dict,
        fleetz_fn=lambda: fz,
    )
    srv.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/fleetz", timeout=5
        ) as resp:
            got = json.loads(resp.read().decode())
        assert got["fleet"]["rows_per_sec"] == 5.0
    finally:
        srv.stop()


def test_fleetz_404_without_aggregator():
    import urllib.error
    import urllib.request

    srv = OpsServer(
        "127.0.0.1",
        0,
        metrics_fn=lambda: "",
        health_fn=lambda: (200, {}),
        status_fn=dict,
    )
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/fleetz", timeout=5
            )
        assert ei.value.code == 404
    finally:
        srv.stop()


# -- loadgen stage split (jax-free) ------------------------------------------


def test_stage_split_percentiles_and_absence():
    recs = [
        {"lat_ms": {"queue": 1.0, "device": 10.0}},
        {"lat_ms": {"queue": 3.0, "device": 30.0, "collect": 0.5}},
    ]
    split = _stage_split(recs)
    assert set(split) == {"queue", "device", "collect"}
    assert split["queue"]["p50"] == pytest.approx(2.0)
    assert split["device"]["p99"] == pytest.approx(29.8, rel=1e-3)
    # pre-observatory daemons: no stamps anywhere → None, not {}
    assert _stage_split([{"rows_through": 5}]) is None
    assert _stage_split([]) is None


# -- top BUSY cell (jax-free) ------------------------------------------------


def test_top_busy_cell_and_column():
    from distributed_drift_detection_tpu.telemetry import top

    assert ("BUSY", "busy", 14) in top._COLUMNS
    cell = top._busy_cell(
        {"dominant_stage": "device", "shares": {"device": 0.62, "feed": 0.1}}
    )
    assert cell == "device:62%"
    assert top._busy_cell({}) is None


# -- serve-loop conservation + parity (jax) ----------------------------------


def _cfg(seed, telemetry_dir=None):
    return RunConfig(
        partitions=4,
        per_batch=50,
        model="centroid",
        shuffle_batches=True,
        results_csv="",
        seed=seed,
        window=1,
        data_policy="quarantine",
        telemetry_dir=telemetry_dir,
    )


def _params(stream, **kw):
    kw.setdefault("port", None)
    kw.setdefault("chunk_batches", 2)
    kw.setdefault("linger_s", 0.05)
    return ServeParams(
        num_features=stream.num_features,
        num_classes=stream.num_classes,
        **kw,
    )


def _drive(runner, lines, block=150):
    for i in range(0, len(lines), block):
        runner.admission.admit_lines(lines[i : i + block])
    runner.batcher.flush()
    runner.request_stop()
    assert runner.serve_forever() == 0
    return runner


def _serve(tmp_path, name, **params_kw):
    stream = planted_prototypes(3, concepts=2, rows_per_concept=400,
                                features=5)
    cfg = _cfg(3, telemetry_dir=str(tmp_path / name))
    runner = ServeRunner(cfg, _params(stream, **params_kw))
    banner = runner.start()
    _drive(runner, format_lines(stream.X, stream.y))
    return runner, banner, stream


def test_serve_conservation_and_statusz(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runner, banner, stream = _serve(tmp_path, "on")

    snap = runner.pipeline_snapshot()
    assert snap is not None
    busy = snap["busy_s"]
    # every publish-path stage measured something on a drained run
    # (seal_wait is accounted but ~0 here: rows are pre-admitted, the
    # loop never blocks for input)
    for stage in ("feed", "device", "collect", "publish"):
        assert busy.get(stage, 0.0) > 0.0, stage
    assert "seal_wait" in busy
    # conservation: single-threaded loop → busy sum <= wall
    assert sum(busy.values()) <= snap["wall_s"] + 1e-6
    assert 0.0 < snap["coverage"] <= 1.0 + 1e-9
    assert snap["dominant_stage"] in pl.SERVE_STAGES

    # the row ledger balances end to end
    admitted = runner.batcher.rows_admitted
    sealed = runner.batcher.depth()["rows_sealed"]
    assert admitted == sealed == runner._rows_published == stream.num_rows

    # /statusz carries the pipeline section + rows_per_sec
    st = runner._statusz()
    assert st["pipeline"]["dominant_stage"] == snap["dominant_stage"]
    assert st["rows_per_sec"] > 0

    # the registry exposition is self-sufficient for the CLI
    text = runner.metrics.to_prometheus_text()
    p_busy, p_wall, p_rows = pl._samples_from_prom(text)
    assert p_rows == stream.num_rows
    assert sum(p_busy.values()) <= p_wall + 1e-6
    prom = tmp_path / "live.prom"
    prom.write_text(text)
    assert pl.main([str(prom)]) == 0


def test_health_names_bottleneck_on_stall_alert(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runner, _, _ = _serve(tmp_path, "hb")

    class _SLO:
        def active(self):
            return [{"rule": "stall_s", "value": 99.0}]

    runner._slo = _SLO()
    code, payload = runner._health()
    assert code == 503
    assert payload["bottleneck_stage"] == runner.pipeline_snapshot()[
        "dominant_stage"
    ]


def _canon(path):
    """Verdict records modulo wall-clock: ts and the per-chunk latency
    stamps (lat_ms) are timing, everything else must be bit-identical."""
    out = []
    with open(path) as fh:
        for line in fh:
            rec = json.loads(line)
            rec.pop("ts", None)
            rec.pop("lat_ms", None)
            out.append(rec)
    return out


def test_sidecar_bit_parity_instrumentation_on_off(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    r_on, b_on, _ = _serve(tmp_path, "on", pipeline_metrics=True)
    r_off, b_off, _ = _serve(tmp_path, "off", pipeline_metrics=False)

    assert r_off.pipeline_snapshot() is None
    on, off = _canon(b_on["verdicts"]), _canon(b_off["verdicts"])
    assert on == off and on

    # lat_ms itself is schema-stable: present in BOTH modes with the
    # same component keys (the loadgen split never depends on the flag)
    with open(b_off["verdicts"]) as fh:
        rec = json.loads(fh.readline())
    assert rec["lat_ms"] and set(rec["lat_ms"]) <= {
        "admission", "queue", "device", "collect",
    }
    split = _stage_split([rec])
    assert split and "device" in split
