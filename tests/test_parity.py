"""Delay-parity harness: the BASELINE.json "≤ 1-batch change" criterion.

Makes the PARITY.md rf-vs-flagship table checkable by pytest + one command
(``python -m distributed_drift_detection_tpu.harness.parity`` regenerates
the committed ``results/delay_parity.csv``). The live test here runs the
same measurement at CI size: fewer seeds and a smaller forest, same stream
family and criterion.
"""

import numpy as np

from distributed_drift_detection_tpu.harness.parity import (
    check_criterion,
    measure_delay_parity,
    summarize,
    write_csv,
)


def _rows(model, delays, detections=100, partitions=8):
    return [
        {
            "model": model,
            "seed": i,
            "mean_delay_batches": d,
            "mean_delay_rows": d * 100,
            "detections": detections,
            "partitions": partitions,
            "per_batch": 100,
            "mult_data": 4.0,
            "dataset": "synth:rialto",
        }
        for i, d in enumerate(delays)
    ]


def test_summarize_and_criterion_units():
    rows = _rows("rf", [50.0, 48.0]) + _rows("centroid", [40.0, 42.0]) + _rows(
        "slowpoke", [61.0, 59.0]
    )
    s = {x.model: x for x in summarize(rows)}
    assert s["rf"].mean == 49.0 and s["centroid"].mean == 41.0
    assert abs(s["rf"].std - 1.0) < 1e-9
    gaps = check_criterion(rows)
    # centroid is 8 units EARLIER (favourable, passes the one-sided bound);
    # slowpoke is 11 units later — more than one worker-batch (8) → fails.
    assert gaps["centroid"] == -8.0 and gaps["slowpoke"] == 11.0
    assert gaps["centroid"] <= 8 and not gaps["slowpoke"] <= 8


def test_flagship_meets_parity_criterion_vs_rf(tmp_path):
    """Live CI-sized measurement: the flagship detects no more than one
    worker-batch later than the reference's RandomForest family on the
    rialto stand-in (it actually detects earlier — PARITY.md)."""
    partitions = 8
    rows = measure_delay_parity(
        models=("rf", "centroid"),
        mult_data=2.0,
        partitions=partitions,
        seeds=range(2),
        rf_estimators=25,
    )
    by_model = {m: [r for r in rows if r["model"] == m] for m in ("rf", "centroid")}
    for m, rs in by_model.items():
        assert len(rs) == 2
        assert all(np.isfinite(r["mean_delay_batches"]) for r in rs), m
        assert all(r["detections"] > 0 for r in rs), m
    gap = check_criterion(rows)["centroid"]
    assert gap <= partitions, (
        f"flagship detects {gap:.1f} global batches later than rf — "
        f"beyond one worker-batch ({partitions})"
    )
    # Round-trip the artifact writer on the measured rows.
    out = tmp_path / "delay_parity.csv"
    write_csv(rows, str(out))
    assert out.read_text().count("\n") == len(rows) + 1
