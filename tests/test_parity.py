"""Delay-parity harness: the BASELINE.json "≤ 1-batch change" criterion.

Makes the PARITY.md rf-vs-flagship table checkable by pytest + one command
(``python -m distributed_drift_detection_tpu.harness.parity`` regenerates
the committed ``results/delay_parity.csv``). The live test here runs the
same measurement at CI size: fewer seeds and a smaller forest, same stream
family and criteria — the one-sided delay bound AND the spurious-rate
bound (boundary attribution closes the fire-more-often loophole).
"""

import numpy as np
import pytest

from distributed_drift_detection_tpu.harness.parity import (
    DEFAULT_MODELS,
    SPURIOUS_TOLERANCE,
    check_criterion,
    check_spurious,
    group_by_geometry,
    measure_delay_parity,
    report,
    summarize,
    write_csv,
)


def _rows(model, delays, detections=100, partitions=8, hits=None, spurious=None):
    hits = detections if hits is None else hits
    spurious = detections - hits if spurious is None else spurious
    return [
        {
            "model": model,
            "seed": i,
            "mean_delay_batches": d,
            "mean_delay_rows": d * 100,
            "detections": detections,
            "hits": hits,
            "misses": 0,
            "spurious": spurious,
            "precision": hits / max(hits + spurious, 1),
            "recall": 1.0,
            "first_hit_delay_batches": d,
            "partitions": partitions,
            "per_batch": 100,
            "mult_data": 4.0,
            "dataset": "synth:rialto",
        }
        for i, d in enumerate(delays)
    ]


def test_summarize_and_criterion_units():
    rows = _rows("rf", [50.0, 48.0]) + _rows("centroid", [40.0, 42.0]) + _rows(
        "slowpoke", [61.0, 59.0]
    )
    s = {x.model: x for x in summarize(rows)}
    assert s["rf"].mean == 49.0 and s["centroid"].mean == 41.0
    assert abs(s["rf"].std - 1.0) < 1e-9
    gaps = check_criterion(rows)
    # centroid is 8 units EARLIER (favourable, passes the one-sided bound);
    # slowpoke is 11 units later — more than one worker-batch (8) → fails.
    assert gaps["centroid"] == -8.0 and gaps["slowpoke"] == 11.0
    assert gaps["centroid"] <= 8 and not gaps["slowpoke"] <= 8


def test_spurious_criterion_catches_overfiring():
    """A model that buys a better mean delay by firing more often passes the
    delay bound but fails the spurious-rate bound."""
    rows = (
        _rows("rf", [50.0], hits=96, spurious=4)  # 4% spurious
        + _rows("sprayer", [30.0], detections=140, hits=96, spurious=44)
        + _rows("clean", [45.0], hits=100, spurious=0)
    )
    gaps = check_criterion(rows)
    assert gaps["sprayer"] <= 8  # "earlier" on mean delay...
    spur = check_spurious(rows)
    # ...but 44/140 ≈ 0.314 spurious vs rf's 0.04 → +0.274 inflation.
    assert spur["sprayer"] > SPURIOUS_TOLERANCE
    assert spur["clean"] <= 0.0  # cleaner than the baseline is fine
    # summaries carry the attribution means
    s = {x.model: x for x in summarize(rows)}
    assert s["sprayer"].spurious == 44.0 and s["rf"].hits == 96.0


def _legacy_row(model="rf", seed=0):
    return {
        "model": model,
        "seed": seed,
        "mean_delay_batches": 50.0,
        "mean_delay_rows": 5000.0,
        "detections": 100,
        "partitions": 8,
        "per_batch": 100,
        "mult_data": 4.0,
        "dataset": "synth:rialto",
    }


def test_gnb_is_a_measured_family():
    """Every shipped on-device model family appears in the default parity
    sweep — gnb was half-shipped without a quality artifact (VERDICT r3
    weak #3)."""
    assert "gnb" in DEFAULT_MODELS


def test_group_by_geometry_keeps_criteria_per_stream():
    """A multi-geometry CSV must never pool a model's rows from one stream
    against the baseline's rows from another: grouping splits by (dataset,
    mult, partitions, per_batch) and report() checks criteria per group."""
    rialto = _rows("rf", [50.0]) + _rows("centroid", [40.0])
    outdoor = [
        dict(r, dataset="outdoorStream.csv", mult_data=64.0)
        for r in _rows("rf", [20.0]) + _rows("centroid", [24.0])
    ]
    groups = group_by_geometry(rialto + outdoor)
    assert len(groups) == 2
    for key, grp in groups.items():
        assert len({r["dataset"] for r in grp}) == 1
    # criteria computed per group: centroid is earlier on rialto, 4 units
    # later (within one worker-batch = 8) on outdoorStream — both pass.
    msgs = []
    # required pinned to the swept family: this synthetic fixture measures
    # only centroid (the shipped default REQUIRED_MODELS gate covers every
    # on-device family and would correctly refuse this partial sweep).
    assert report(rialto + outdoor, progress=msgs.append,
                  required=("centroid",))
    assert sum("===" in m for m in msgs) == 2
    # pooled (the bug the grouping prevents) would compare 32.0 vs 35.0 and
    # hide the per-stream structure entirely
    pooled_gap = check_criterion(rialto + outdoor)["centroid"]
    per_stream_gaps = [check_criterion(g)["centroid"] for g in groups.values()]
    assert pooled_gap not in per_stream_gaps


def test_report_verdict_semantics():
    """report() prints one correctly-named criterion line per non-baseline
    model, gates the verdict on `required` only, and handles an
    empty/absent required model without a vacuous pass (or a crash)."""
    rows = (
        _rows("rf", [50.0])
        + _rows("centroid", [40.0])
        + _rows("slowpoke", [61.0])
    )
    msgs = []
    ok = report(rows, progress=msgs.append, required=("centroid",))
    assert sum(m.startswith("centroid:") for m in msgs) == 1
    assert sum(m.startswith("slowpoke:") for m in msgs) == 1
    assert ok  # slowpoke FAILs both axes but is not required
    assert any(m.startswith("slowpoke:") and "FAIL" in m for m in msgs)
    assert not report(rows, progress=lambda *_: None, required=("slowpoke",))

    # Baseline-only rows: a required model that was never measured is an
    # unevaluated criterion, not a pass — and must not crash.
    rf_only = _rows("rf", [50.0])
    msgs2 = []
    assert not report(rf_only, progress=msgs2.append)
    assert any("required but not measured" in m for m in msgs2)
    # Informational subset runs (nothing required) report and pass.
    assert report(rf_only, progress=lambda *_: None, required=())


def test_summarize_tolerates_legacy_rows_without_attribution():
    """Rows from a pre-attribution CSV still summarize (nan attribution)."""
    s = summarize([_legacy_row()])[0]
    assert s.mean == 50.0 and np.isnan(s.hits) and np.isnan(s.first_hit_delay)


def test_check_spurious_rejects_rows_without_attribution():
    """The spurious-rate criterion refuses pre-attribution rows loudly —
    all-legacy AND mixed CSVs (a mixed file would otherwise compute the
    rate over a different seed subset than the delay criterion)."""
    import pytest

    legacy = [_legacy_row("rf", 0), _legacy_row("centroid", 0)]
    with pytest.raises(ValueError, match="attribution columns"):
        check_spurious(legacy)
    mixed = _rows("rf", [50.0]) + [_legacy_row("centroid", 0)]
    with pytest.raises(ValueError, match="attribution columns"):
        check_spurious(mixed)
    # delay criterion still works on the same legacy rows
    assert check_criterion(legacy)["centroid"] == 0.0


@pytest.mark.slow
def test_flagship_meets_parity_criteria_vs_rf(tmp_path):
    """Live CI-sized measurement: the flagship detects no more than one
    worker-batch later than the reference's RandomForest family on the
    rialto stand-in (it actually detects earlier — PARITY.md), and does not
    buy that delay with spurious fires beyond the tolerance. (gnb is
    asserted on the outdoorStream geometry instead — on rialto-like streams
    its failure is a *documented domain limit*, PARITY.md, like linear's.)"""
    partitions = 8
    models = ("rf", "centroid")
    rows = measure_delay_parity(
        models=models,
        mult_data=2.0,
        partitions=partitions,
        seeds=range(2),
        rf_estimators=25,
    )
    by_model = {m: [r for r in rows if r["model"] == m] for m in models}
    for m, rs in by_model.items():
        assert len(rs) == 2
        assert all(np.isfinite(r["mean_delay_batches"]) for r in rs), m
        assert all(r["detections"] > 0 for r in rs), m
        # attribution invariants: detections decompose exactly; recall>0
        assert all(r["hits"] + r["spurious"] == r["detections"] for r in rs), m
        assert all(r["recall"] > 0 for r in rs), m
    gaps = check_criterion(rows)
    spur = check_spurious(rows)
    for m in ("centroid",):
        assert gaps[m] <= partitions, (
            f"{m} detects {gaps[m]:.1f} global batches later than rf — "
            f"beyond one worker-batch ({partitions})"
        )
        assert spur[m] <= SPURIOUS_TOLERANCE, (
            f"{m} spends {spur[m]:+.3f} more of its detections on "
            f"spurious fires than rf (tolerance {SPURIOUS_TOLERANCE})"
        )
    # Round-trip the artifact writer on the measured rows.
    out = tmp_path / "delay_parity.csv"
    write_csv(rows, str(out))
    assert out.read_text().count("\n") == len(rows) + 1


@pytest.mark.slow
def test_parity_criteria_hold_on_outdoorstream_geometry():
    """The second benchmark geometry (VERDICT r3 weak #4): the criteria are
    proven on the reference's primary published dataset, not only the
    rialto stand-in — CI-sized outdoorStream cell (the committed artifact
    uses the on-spec mult=64 cell at full seed count)."""
    partitions = 4
    rows = measure_delay_parity(
        models=("rf", "centroid", "gnb"),
        dataset="/root/reference/outdoorStream.csv",
        mult_data=16.0,
        partitions=partitions,
        seeds=range(2),
        rf_estimators=25,
    )
    for r in rows:
        assert r["detections"] > 0, r["model"]
        assert r["hits"] + r["spurious"] == r["detections"], r["model"]
    gaps = check_criterion(rows)
    spur = check_spurious(rows)
    for m in ("centroid", "gnb"):
        assert gaps[m] <= partitions, (m, gaps[m])
        assert spur[m] <= SPURIOUS_TOLERANCE, (m, spur[m])


@pytest.mark.slow
def test_guarded_families_detect_on_rialto_standin():
    """VERDICT r4 #1 end-to-end: at DEFAULT config (auto saturation guard)
    the memorizer families no longer ship recall 0.000 on the rialto
    stand-in, and the shipped linear@robust preset (DDM_ROBUST noise
    floor) detects without the raw-sensitivity over-firing loop."""
    rows = measure_delay_parity(
        models=("gnb", "forest", "linear", "linear@robust"),
        mult_data=2.0,
        partitions=8,
        seeds=range(1),
    )
    by_model = {r["model"]: r for r in rows}
    for m in ("gnb", "forest", "linear@robust"):
        r = by_model[m]
        assert r["recall"] > 0.5, (m, r)
        assert np.isfinite(r["mean_delay_batches"]), (m, r)
    # The preset's point: same family, ~an order of magnitude fewer
    # spurious fires than the raw 3/0.5/1.5 sensitivity.
    assert (
        by_model["linear@robust"]["spurious"]
        < by_model["linear"]["spurious"] / 4
    ), (by_model["linear"], by_model["linear@robust"])
