"""Fleet-scale serving (ISSUE 14): the mesh-sharded tenant plane and the
tenant router.

Tentpole (a) acceptance: per-tenant drift flags are **bit-identical** to
solo runs under every tested tenant-mesh shape — the PR-9 parity
contract quantified over shardings (`RunConfig.mesh_tenant_devices`,
`parallel.mesh.make_mesh(tenant_devices=...)`, the regex→PartitionSpec
`match_partition_rules` tree).

Tentpole (b) acceptance: a router-fronted fleet of N daemons serves
global tenants with flags and verdict sidecar records bit-identical to
solo runs, ACROSS a live migration (drain → ship checkpoint → resume on
another daemon) — and no verdict is lost past the shipped checkpoint.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

import jax

from distributed_drift_detection_tpu import RunConfig, run_multi
from distributed_drift_detection_tpu.config import (
    ServeParams,
    replace,
    tenant_configs,
)
from distributed_drift_detection_tpu.engine.chunked import ChunkedDetector
from distributed_drift_detection_tpu.engine.loop import stack_tenants
from distributed_drift_detection_tpu.io import planted_prototypes
from distributed_drift_detection_tpu.io.stream import stripe_chunk
from distributed_drift_detection_tpu.io.synth import rialto_like_xy
from distributed_drift_detection_tpu.models import ModelSpec, build_model
from distributed_drift_detection_tpu.parallel.mesh import (
    PARTITION_AXIS,
    TENANT_AXIS,
    make_mesh,
    match_partition_rules,
    plane_axes,
    plane_sharding,
    plane_shardings,
    split_tenant_flags,
)
from distributed_drift_detection_tpu.serve import (
    BackendSpec,
    HashRing,
    ServeRunner,
    TenantRouter,
    plan_fleet,
    read_verdicts,
)
from distributed_drift_detection_tpu.serve.loadgen import (
    format_lines,
    run_loadgen,
)
from distributed_drift_detection_tpu.serve.router import (
    plan_rebalance,
)

from jax.sharding import PartitionSpec as P


def _assert_flags_equal(a, b, msg=""):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=f"{msg} {name}",
        )


# ---------------------------------------------------------------------------
# tentpole (a): the 2-D (tenant, partition) mesh
# ---------------------------------------------------------------------------


def test_make_mesh_tenant_axis_shapes():
    """make_mesh grows a (tenants, partitions) axis pair; 0/1 keeps the
    historical 1-D mesh; a non-dividing row count is a loud error."""
    m1 = make_mesh()
    assert m1.axis_names == (PARTITION_AXIS,)
    assert plane_axes(m1) == PARTITION_AXIS
    m2 = make_mesh(tenant_devices=2)
    assert m2.axis_names == (TENANT_AXIS, PARTITION_AXIS)
    assert m2.devices.shape[0] == 2
    assert plane_axes(m2) == (TENANT_AXIS, PARTITION_AXIS)
    assert make_mesh(tenant_devices=1).axis_names == (PARTITION_AXIS,)
    with pytest.raises(ValueError, match="tenant axis"):
        make_mesh(tenant_devices=3)  # 8 CPU devices don't split by 3


def test_plane_sharding_divisibility():
    mesh = make_mesh(tenant_devices=2)
    sh = plane_sharding(mesh, mesh.devices.size * 2)
    assert sh.spec == P((TENANT_AXIS, PARTITION_AXIS))
    with pytest.raises(ValueError, match="not divisible"):
        plane_sharding(mesh, mesh.devices.size + 1)


def test_match_partition_rules_tree():
    """The SNIPPETS.md [1] pattern: per-leaf regex → PartitionSpec with
    scalar and unmatched-leaf replication fallbacks; ordered first-match
    wins; mesh= returns NamedSharding leaves."""
    mesh = make_mesh(tenant_devices=2)
    spec = P(plane_axes(mesh))
    tree = {
        "params": {"centroids": np.zeros((8, 3, 5))},
        "count": np.zeros(()),  # scalar → replicate, rules ignored
        "odd_leaf": np.zeros((8, 2)),  # no rule → replicate
    }
    rules = ((r"params/", spec),)
    specs = match_partition_rules(rules, tree)
    assert specs["params"]["centroids"] == spec
    assert specs["count"] == P()
    assert specs["odd_leaf"] == P()  # replication fallback
    # catch-all tail makes unmatched leaves impossible
    specs = match_partition_rules(rules + ((r".*", spec),), tree)
    assert specs["odd_leaf"] == spec
    assert specs["count"] == P()  # scalars still replicate
    # ordered: first match wins over the catch-all
    specs = match_partition_rules(
        ((r"centroids", P()),) + ((r".*", spec),), tree
    )
    assert specs["params"]["centroids"] == P()
    # mesh= resolves to NamedSharding, ready for device_put
    sharded = match_partition_rules(rules, tree, mesh=mesh)
    assert sharded["params"]["centroids"].spec == spec
    assert sharded["params"]["centroids"].mesh.shape_tuple == (
        mesh.shape_tuple
    )


@pytest.mark.parametrize("tenant_devices", [2, 4])
def test_one_shot_mesh_shape_parity(tenant_devices):
    """The tentpole-(a) acceptance: run_multi flags bit-identical at
    every tenant-mesh shape (vs the historical 1-D mesh)."""
    base = dict(
        dataset="synth:rialto,seed=3,rows_per_class=160",
        partitions=4, per_batch=50, model="centroid", results_csv="",
        tenants=4,
    )
    ref = run_multi(RunConfig(**base))
    got = run_multi(RunConfig(**base, mesh_tenant_devices=tenant_devices))
    for t in range(4):
        _assert_flags_equal(
            got.results[t].flags, ref.results[t].flags,
            f"td={tenant_devices} tenant={t}",
        )
        np.testing.assert_array_equal(
            got.results[t].drift_vote, ref.results[t].drift_vote
        )


def test_one_shot_mesh_constraint_errors():
    base = dict(
        dataset="synth:rialto,seed=3,rows_per_class=160",
        partitions=4, per_batch=50, model="centroid", results_csv="",
        tenants=3,
    )
    with pytest.raises(ValueError, match="tenant"):
        run_multi(RunConfig(**base, mesh_tenant_devices=2))  # 3 % 2


def test_chunked_tenant_mesh_parity():
    """ChunkedDetector on a 2-D tenant mesh: per-chunk flags
    bit-identical to the unmeshed stacked plane, and the carry's leaves
    actually land on the plane sharding (per-leaf rules applied)."""
    P_, B, CB, T, F = 2, 50, 2, 4, 27
    span = P_ * B * CB

    def chunks_for(seed):
        X, y = rialto_like_xy(seed=seed, rows_per_class=3 * span // 10)
        return [
            stripe_chunk(
                X[k * span : (k + 1) * span],
                y[k * span : (k + 1) * span],
                k * span, P_, B, CB, shuffle_seed=seed + 0x5EED,
            )
            for k in range(3)
        ]

    model = build_model("centroid", ModelSpec(F, 10))
    per_tenant = [chunks_for(100 + t) for t in range(T)]
    stacked = [
        stack_tenants([per_tenant[t][k] for t in range(T)])
        for k in range(3)
    ]
    ref = ChunkedDetector(model, partitions=P_, seed=7, tenants=T)
    mesh = make_mesh(tenant_devices=2)
    det = ChunkedDetector(
        model, partitions=P_, seed=7, tenants=T, mesh=mesh
    )
    for k, c in enumerate(stacked):
        got = det.feed(c)
        want = ref.feed(c)
        _assert_flags_equal(
            jax.tree.map(np.asarray, got),
            jax.tree.map(np.asarray, want),
            f"chunk {k}",
        )
    # the carry is sharded by the rule tree, not accidentally replicated
    shardings = plane_shardings(mesh, det.carry)
    leaf = det.carry.params
    got_sh = jax.tree.leaves(jax.tree.map(lambda x: x.sharding, leaf))[0]
    want_sh = jax.tree.leaves(shardings.params)[0]
    assert got_sh.spec == want_sh.spec


def test_chunked_tenant_mesh_constraint():
    model = build_model("centroid", ModelSpec(5, 4))
    mesh = make_mesh(tenant_devices=2)
    with pytest.raises(ValueError, match="tenant"):
        ChunkedDetector(model, partitions=4, seed=0, tenants=3, mesh=mesh)


# ---------------------------------------------------------------------------
# tentpole (b) units: placement, rebalance planning, replay slicing
# ---------------------------------------------------------------------------


def test_hashring_stable_under_exclusion():
    """Excluding a dead backend moves ONLY its keys — everyone else's
    placement is untouched (the consistent-hashing contract)."""
    ring = HashRing(["a", "b", "c"])
    before = {g: ring.place(g) for g in range(64)}
    after = {g: ring.place(g, exclude=["b"]) for g in range(64)}
    assert all(v in ("a", "c") for v in after.values())
    for g in range(64):
        if before[g] != "b":
            assert after[g] == before[g], f"tenant {g} moved needlessly"
    moved = [g for g in range(64) if before[g] == "b"]
    assert moved  # 64 keys over 3 backends: b owns some
    with pytest.raises(RuntimeError, match="no live backend"):
        ring.place(0, exclude=["a", "b", "c"])
    with pytest.raises(ValueError, match="duplicate"):
        HashRing(["a", "a"])


def test_plan_fleet_covers_all_tenants_with_spares():
    assign = plan_fleet(16, ["b0", "b1", "b2"], spares=2)
    placed = sorted(
        g for ids in assign.values() for g in ids if g >= 0
    )
    assert placed == list(range(16))
    for ids in assign.values():
        assert ids.count(-1) >= 1  # landing capacity everywhere
        assert len(ids) >= 1


def test_plan_rebalance():
    # imbalanced: hottest tenant moves hot → cold
    move = plan_rebalance(
        {"a": 1000.0, "b": 10.0},
        {"a": {0: 800.0, 1: 200.0}, "b": {2: 10.0}},
        {"a": 0, "b": 1},
        ratio=2.0,
    )
    assert move == (0, "a", "b")
    # a cold fleet never rebalances
    assert plan_rebalance(
        {"a": 30.0, "b": 20.0},
        {"a": {0: 20.0, 1: 10.0}, "b": {2: 20.0}},
        {"a": 1, "b": 1},
    ) is None
    # moving the only tenant just moves the imbalance
    assert plan_rebalance(
        {"a": 1000.0, "b": 10.0},
        {"a": {0: 1000.0}, "b": {2: 10.0}},
        {"a": 1, "b": 1},
    ) is None
    # no vacancy on the cold side
    assert plan_rebalance(
        {"a": 1000.0, "b": 10.0},
        {"a": {0: 800.0, 1: 200.0}, "b": {2: 10.0}},
        {"a": 1, "b": 0},
    ) is None


def test_top_renders_router_status():
    """The `top` dashboard reads a router's /statusz like a daemon row:
    status 'router', fleet health (backends alive, migrations,
    failovers, rows lost) riding the WIRE column."""
    from distributed_drift_detection_tpu.telemetry import top as top_mod

    status = {
        "router": True,
        "run_id": "router",
        "uptime_s": 5.0,
        "draining": False,
        "rows": {"published": 1000, "admitted": 1000},
        "detections": None,
        "ingress": {"frames_v1": 3, "frames_v2": 7, "decode_errors": 0},
        "migrations": 1,
        "failovers": 2,
        "rows_lost": 9,
        "alerts": [{"rule": "backend_dead:b1"}],
        "backends": [
            {"name": "b0", "alive": True},
            {"name": "b1", "alive": False},
        ],
        "placements": {},
    }
    import io as _io
    import json as _json
    from unittest import mock

    src = top_mod.StatuszSource("http://127.0.0.1:1/statusz")

    class _Resp(_io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    with mock.patch.object(
        top_mod.urllib.request,
        "urlopen",
        return_value=_Resp(_json.dumps(status).encode()),
    ):
        row = src.poll(0.0)
    assert row["status"] == "router"
    assert "be:1/2" in row["wire"]
    assert "mig:1" in row["wire"] and "fo:2" in row["wire"]
    assert "lost:9" in row["wire"]
    assert row["alerts"] == ["backend_dead:b1"]
    frame = top_mod.render([row], 0.0)
    assert "be:1/2" in frame


def test_backend_spec_parse():
    spec = BackendSpec("10.0.0.1:7007:7008")
    assert (spec.host, spec.port, spec.ops_port) == ("10.0.0.1", 7007, 7008)
    with pytest.raises(ValueError, match="host:port:ops_port"):
        BackendSpec("10.0.0.1:7007")


def test_slice_entry_drops_covered_rows():
    """The failover re-send drops rows the checkpoint already covers —
    v1 keeps a TRACE stamp only with its surviving row; v2 re-encodes
    the frame tail."""
    from distributed_drift_detection_tpu.serve import wire

    entry = (
        "v1",
        ["TRACE t0 s0", "1.0,2.0,0", "1.5,2.5,1", "TRACE t2 s2",
         "2.0,3.0,0"],
        3,
    )
    kind, payload, rows = TenantRouter._slice_entry(entry, 2)
    assert (kind, rows) == ("v1", 1)
    assert payload == ["TRACE t2 s2", "2.0,3.0,0"]

    X = np.arange(12, dtype=np.float32).reshape(4, 3)
    y = np.arange(4, dtype=np.int32)
    frame = wire.encode_frame(X, y, tenant=5)
    kind, payload, rows = TenantRouter._slice_entry(("v2", frame, 4), 1)
    assert (kind, rows) == ("v2", 3)
    header, X2, y2, _ = wire.decode_frame(payload)
    np.testing.assert_array_equal(np.asarray(X2), X[1:])
    np.testing.assert_array_equal(np.asarray(y2), y[1:])


def _stub_router():
    """A TenantRouter wired to two stub backends without start(): src
    serves global tenant 0 in slot 0, dst is full (no vacancy)."""
    from collections import deque

    r = TenantRouter(
        [BackendSpec("127.0.0.1:1:2"), BackendSpec("127.0.0.1:3:4")]
    )
    src, dst = r.backends
    src.name, dst.name = "src", "dst"
    src.slot_ids, dst.slot_ids = [0], [7]
    r._by_name = {"src": src, "dst": dst}
    r.ring = HashRing(["src", "dst"])
    r.place[0] = (src, 0)
    r._state[0] = "active"
    r._buffer[0] = deque()
    r._buffered_rows[0] = 0
    r._pending[0] = []
    r._pending_rows[0] = 0
    r.rows_forwarded[0] = 0
    return r, src, dst


def test_migrate_failure_resumes_at_source():
    """A migration that cannot land (destination has no vacant slot)
    must RESUME the tenant at its still-live source — never leave it
    orphaned with its rows held forever (the source still has the state;
    SAVETENANT is non-destructive)."""
    r, src, dst = _stub_router()
    sent = []
    src.send = lambda payload: sent.append(payload)
    src.control = lambda line, timeout=120.0: f"OK {line.split()[0]} done"
    src.statusz = lambda timeout=5.0: {
        "tenant_detail": [
            {"id": 0, "rows_admitted": 0, "buffered": 0}
        ]
    }
    assert r.migrate_tenant(0, "dst", drain_timeout=0.5) is False
    assert r._state[0] == "active"
    assert r.place[0] == (src, 0)
    assert src.slot_ids == [0] and dst.slot_ids == [7]
    # a held row dispatched mid-quiesce flushed on the resume
    assert r._pending[0] == [] and r._pending_rows[0] == 0


def test_orphaned_pending_is_capped():
    """An orphaned tenant's held rows are bounded like the replay
    buffer — dropped rows count LOUDLY in rows_lost, never OOM the
    router."""
    r, src, _ = _stub_router()
    r.replay_rows = 8
    r._state[0] = "orphaned"
    for i in range(5):
        r._dispatch(0, ("v1", [f"{i},0"] * 4, 4))
    assert r._pending_rows[0] == 8
    assert len(r._pending[0]) == 2
    assert r.rows_lost == 12
    assert 0 in r._pending_overflowed


def test_rebalance_survives_migration_race(monkeypatch):
    """A rebalance plan that races a failover/quiesce (migrate_tenant
    raises) must skip the round, not kill the rebalance thread."""
    from distributed_drift_detection_tpu.serve import router as router_mod

    r, src, dst = _stub_router()
    monkeypatch.setattr(
        router_mod, "plan_rebalance", lambda *a: (0, "src", "dst")
    )

    def _boom(g, dst_name, **kw):
        raise RuntimeError("tenant 0 is quiesced; cannot migrate")

    monkeypatch.setattr(r, "migrate_tenant", _boom)
    import urllib.error

    for b in (src, dst):
        b.statusz = lambda timeout=5.0: (_ for _ in ()).throw(
            urllib.error.URLError("down")
        )
    assert r.rebalance_once() is None


# ---------------------------------------------------------------------------
# the fleet end to end: router parity + live migration
# ---------------------------------------------------------------------------

SPAN = 4 * 25 * 2  # partitions * per_batch * chunk_batches


def _cfg(tele=None, tenants=2, **kw):
    kw.setdefault("seed", 5)
    return RunConfig(
        partitions=4, per_batch=25, model="centroid",
        shuffle_batches=True, results_csv="", window=1,
        data_policy="quarantine", telemetry_dir=tele, tenants=tenants,
        **kw,
    )


def _params(stream, **kw):
    kw.setdefault("port", 0)
    kw.setdefault("ops_port", 0)
    kw.setdefault("chunk_batches", 2)
    kw.setdefault("linger_s", 0.05)
    return ServeParams(
        num_features=stream.num_features,
        num_classes=stream.num_classes,
        **kw,
    )


def _start(runner):
    banner = runner.start()
    t = threading.Thread(target=runner.serve_forever, daemon=True)
    t.start()
    return banner, t


def _tenant_records(paths, gid):
    """Per-tenant verdict entries for global tenant ``gid`` across a
    fleet's sidecars, in rows_through order: the placement-invariant
    parity surface (positions and changes are stream-global)."""
    out = []
    for p in paths:
        if not p or not os.path.exists(p):
            continue
        for rec in read_verdicts(p):
            for ent in rec.get("tenants") or []:
                if int(ent.get("id", ent["tenant"])) == gid and ent["rows"]:
                    out.append(ent)
    out.sort(key=lambda e: int(e["rows_through"]))
    return out


def _assert_tenant_records_equal(got, ref, msg=""):
    assert len(got) == len(ref), (
        f"{msg}: {len(got)} vs {len(ref)} per-tenant verdict entries"
    )
    for i, (g, r) in enumerate(zip(got, ref)):
        for k in ("rows", "rows_through", "start_row", "detections"):
            assert int(g[k]) == int(r[k]), f"{msg} entry {i} {k}"
        assert [tuple(c) for c in g["changes"]] == [
            tuple(c) for c in r["changes"]
        ], f"{msg} entry {i} changes"


@pytest.mark.parametrize("wire_version", ["v1", "v2"])
def test_fleet_router_replay_parity(wire_version, tmp_path, monkeypatch):
    """2 backends + router on loopback, a dealt 2-tenant loadgen replay
    through the ROUTER endpoint (`--router` posture: global ids, fleet
    verdict tailing): full coverage, per-tenant latency attribution, and
    per-tenant flags bit-identical to each tenant's SOLO daemon fed the
    same dealt sub-stream. Both wire protocols cross the router — v2
    exercises the header-only frame relay (a payload view over the live
    buffer would be a BufferError on the resize; pinned here)."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(5, concepts=3, rows_per_concept=220,
                                features=6)
    lines = format_lines(stream.X, stream.y)

    backends, threads = [], []
    for name, gid in (("A", 0), ("B", 1)):
        # A fleet backend with a vacant spare can never full-seal (the
        # spare never spans), so a short linger would seal at arbitrary
        # timing-dependent boundaries and break bit-parity with the
        # solo reference. A long linger pins every seal to the wire's
        # FLUSH/STOP drain — span-aligned, deterministic.
        r = ServeRunner(
            _cfg(f"tele{name}", tenants=2),
            _params(stream, tenant_ids=(gid, -1), name=name,
                    linger_s=30.0),
            keep_flags=True,
        )
        banner, t = _start(r)
        backends.append((r, banner))
        threads.append(t)
    router = TenantRouter(
        [
            BackendSpec(f"127.0.0.1:{b['port']}:{b['ops_port']}")
            for _, b in backends
        ],
        telemetry_dir=str(tmp_path / "teleR"),
        ops_port=0,
    )
    banner = router.start()
    assert banner["tenants"] == [0, 1]

    X = np.ascontiguousarray(stream.X, np.float32)
    y = np.ascontiguousarray(stream.y, np.int32)
    rep = run_loadgen(
        banner["host"], banner["port"],
        lines if wire_version == "v1" else None,
        rate=0.0, timeout=180, stop=True, tenants=2,
        wire_version=wire_version,
        arrays=(X, y) if wire_version == "v2" else None,
        frame_rows=64,
        fleet_dirs=["teleA", "teleB"],
    )
    for t in threads:
        t.join(timeout=180)
        assert not t.is_alive()
    router.stop()
    assert not rep["timeout"]
    assert rep["rows_covered"] == len(lines)
    assert rep["tenant_rows_covered"] == rep["tenant_rows_sent"]
    assert rep["p99_ms"] is not None

    # per-tenant flag parity vs each tenant's solo run on its dealt
    # sub-stream (the loadgen dealing: round-robin blocks of 64)
    streams = [[], []]
    for base in range(0, len(lines), 64):
        streams[(base // 64) % 2].extend(
            range(base, min(base + 64, len(lines)))
        )
    any_detections = False
    for (r, _), gid in zip(backends, (0, 1)):
        sub = [lines[i] for i in streams[gid]]
        solo = ServeRunner(
            tenant_configs(_cfg(tenants=2))[gid],
            _params(stream, port=None, ops_port=None),
            keep_flags=True,
        )
        solo.start()
        solo.admission.admit_lines(sub)
        solo.batcher.flush()
        solo.request_stop()
        assert solo.serve_forever() == 0
        got = split_tenant_flags(r.flags(), 2)[0]  # slot 0 serves gid
        ref = solo.flags()
        _assert_flags_equal(got, ref, f"tenant {gid}")
        any_detections = any_detections or (
            np.asarray(ref.change_global) >= 0
        ).any()
    assert any_detections


def test_live_migration_bit_parity(tmp_path, monkeypatch):
    """The migration acceptance: drain → ship checkpoint → resume on a
    second in-process daemon. The migrated tenant's drift flags and
    verdict sidecar records are bit-identical to an unmigrated solo run,
    no verdict is lost past the shipped checkpoint, and the OTHER tenant
    keeps serving throughout."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(7, concepts=3, rows_per_concept=300,
                                features=6)
    lines = format_lines(stream.X, stream.y)
    half = 2 * SPAN  # migrate at a chunk boundary's worth of rows
    subs = [lines[0::2], lines[1::2]]  # dealt: even rows → 0, odd → 1

    backends = {}
    threads = []
    for name, gid in (("A", 0), ("B", 1)):
        r = ServeRunner(
            _cfg(f"tele{name}", tenants=2, seed=7),
            _params(
                stream,
                tenant_ids=(gid, -1),
                name=name,
                checkpoint=str(tmp_path / f"{name}.ckpt"),
                tenant_checkpoints=True,
                # seal ONLY at the wire's FLUSH points (see the parity
                # test): deterministic span-aligned boundaries
                linger_s=30.0,
            ),
            keep_flags=True,
        )
        banner, t = _start(r)
        backends[name] = (r, banner)
        threads.append(t)
    router = TenantRouter(
        [
            BackendSpec(f"127.0.0.1:{b['port']}:{b['ops_port']}")
            for _, b in backends.values()
        ],
        telemetry_dir=str(tmp_path / "teleR"),
    )
    router.start()

    def send(sock, gid, block):
        sock.sendall(
            (f"TENANT {gid}\n" + "\n".join(block) + "\n").encode()
        )

    with socket.create_connection(
        ("127.0.0.1", router.port), timeout=30
    ) as sock:
        # phase 1: both tenants, then FLUSH so everything seals
        send(sock, 0, subs[0][:half])
        send(sock, 1, subs[1][:half])
        sock.sendall(b"FLUSH\n")
        # the router forwards asynchronously — pin the migration point
        # to the phase boundary (all phase-1 rows forwarded) so the
        # checkpoint ships exactly `half` rows and the bit-parity
        # reference's FLUSH pattern matches
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with router._lock:
                fwd = router.rows_forwarded[0]
            if fwd == half:
                break
            time.sleep(0.02)
        else:
            pytest.fail("router never forwarded phase 1")
        # live migration: tenant 0 moves A → B mid-replay
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if router.migrate_tenant(0, "B"):
                break
            time.sleep(0.2)
        else:
            pytest.fail("migration never succeeded")
        assert router.place[0][0].name == "B"
        # phase 2: tenant 0's remaining rows land on B; tenant 1 kept
        # serving on B throughout
        send(sock, 0, subs[0][half:])
        send(sock, 1, subs[1][half:])
        sock.sendall(b"FLUSH\nSTOP\n")
    for t in threads:
        t.join(timeout=180)
        assert not t.is_alive()
    status = router.status()
    router.stop()
    assert status["migrations"] == 1
    assert status["rows_lost"] == 0

    # the unmigrated reference: ONE 2-tenant daemon (identity placement
    # — global tenant g in slot g, the same seed/shuffle identities the
    # fleet's slots carry) fed both substreams at the same FLUSH points.
    solo = ServeRunner(
        _cfg("teleSolo", tenants=2, seed=7),
        _params(stream, port=None, ops_port=None),
        keep_flags=True,
    )
    solo.start()
    for t in range(2):
        solo.admissions[t].admit_lines(subs[t][:half])
    solo.batcher.flush()
    for t in range(2):
        solo.admissions[t].admit_lines(subs[t][half:])
    solo.batcher.flush()
    solo.request_stop()
    assert solo.serve_forever() == 0

    # drift flags + verdict records: the flags a served tenant publishes
    # ARE its verdict entries' change tuples (partition, batch, global
    # position) — the placement-invariant surface. Tenant 0's entries
    # across BOTH daemons' sidecars must equal the unmigrated solo
    # run's, in rows_through order, with no gap past the shipped
    # checkpoint.
    rA, _ = backends["A"]
    rB, _ = backends["B"]
    assert rB.tenant_ids.index(0) == 1  # landed in B's spare slot
    got_recs = _tenant_records(
        [rA.verdicts_path, rB.verdicts_path], 0
    )
    ref_recs = _tenant_records([solo.verdicts_path], 0)
    _assert_tenant_records_equal(got_recs, ref_recs, "tenant 0")
    # parity of all-empty change lists proves nothing
    assert sum(int(e["detections"]) for e in ref_recs) > 0
    assert got_recs[-1]["rows_through"] == len(subs[0])
    covered = 0
    for ent in got_recs:
        assert int(ent["rows_through"]) - int(ent["rows"]) <= covered
        covered = max(covered, int(ent["rows_through"]))
    assert covered == len(subs[0])  # every admitted row verdicted

    # tenant 1 was never disturbed: its records match the reference too
    got1 = _tenant_records([rB.verdicts_path], 1)
    ref1 = _tenant_records([solo.verdicts_path], 1)
    _assert_tenant_records_equal(got1, ref1, "tenant 1")


def test_serve_mesh_tenants_matches_unmeshed(tmp_path, monkeypatch):
    """ServeRunner accepts the tenant-mesh spec: a daemon on a 2-D
    (tenant, partition) mesh produces flags bit-identical to the
    unmeshed daemon on the same traffic."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(9, concepts=2, rows_per_concept=260,
                                features=6)
    lines = format_lines(stream.X, stream.y)

    def drive(cfg):
        r = ServeRunner(cfg, _params(stream, port=None, ops_port=None),
                        keep_flags=True)
        r.start()
        for t in range(2):
            r.admissions[t].admit_lines(lines[t::2])
        r.batcher.flush()
        r.request_stop()
        assert r.serve_forever() == 0
        return r.flags()

    ref = drive(_cfg(tenants=2, seed=9))
    got = drive(_cfg(tenants=2, seed=9, mesh_tenant_devices=2))
    _assert_flags_equal(got, ref, "mesh-tenants daemon")


def test_solo_fleet_posture_emits_tenant_entries(tmp_path, monkeypatch):
    """A SINGLE-tenant backend in fleet posture (--tenants 1
    --tenant-ids g) must emit per-tenant verdict entries carrying its
    GLOBAL id — the fleet verdict tail joins on them, so without the
    entry `loadgen --router` could never cover that tenant."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(3, concepts=2, rows_per_concept=200,
                                features=6)
    cfg = _cfg(str(tmp_path / "tele"), tenants=1)
    r = ServeRunner(cfg, _params(stream, tenant_ids=(3,)), keep_flags=True)
    banner, t = _start(r)
    lines = format_lines(stream.X, stream.y)
    with socket.create_connection(
        ("127.0.0.1", banner["port"]), timeout=30
    ) as sock:
        sock.sendall(("\n".join(lines[:SPAN]) + "\n").encode())
        sock.sendall(b"FLUSH\nSTOP\n")
    t.join(timeout=120)
    assert not t.is_alive()
    recs = list(read_verdicts(banner["verdicts"]))
    assert recs, "no verdicts published"
    for rec in recs:
        ents = rec.get("tenants")
        assert ents and len(ents) == 1
        assert int(ents[0]["id"]) == 3
        assert int(ents[0]["rows_through"]) == int(rec["rows_through"])
        assert int(ents[0]["start_row"]) == int(rec["start_row"])


def test_savetenant_refuses_buffered_rows(tmp_path, monkeypatch):
    """The control surface's safety rail: SAVETENANT under buffered
    (unsealed) rows answers ERR and the daemon keeps serving."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(3, concepts=2, rows_per_concept=200,
                                features=6)
    # linger long enough that the 7-row partial can NEVER seal under the
    # test's feet — the ERR must come from the buffered-rows guard
    r = ServeRunner(_cfg(None, tenants=2), _params(stream, linger_s=60.0),
                    keep_flags=True)
    banner, t = _start(r)
    lines = format_lines(stream.X, stream.y)
    with socket.create_connection(
        ("127.0.0.1", banner["port"]), timeout=30
    ) as sock:
        # a partial span buffers without sealing
        sock.sendall(
            ("TENANT 0\n" + "\n".join(lines[:7]) + "\n").encode()
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if r.batcher.tenant_state(0)["buffered"] == 7:
                break
            time.sleep(0.02)
        sock.sendall(
            f"SAVETENANT 0 {tmp_path / 'x.ckpt'}\n".encode()
        )
        sock.settimeout(60)
        buf = b""
        while b"\n" not in buf:
            buf += sock.recv(4096)
        assert buf.startswith(b"ERR SAVETENANT 0")
        assert b"buffered" in buf
        # the daemon still serves: flush + stop drain cleanly
        sock.sendall(b"FLUSH\nSTOP\n")
    t.join(timeout=120)
    assert not t.is_alive()
    assert not os.path.exists(tmp_path / "x.ckpt")
