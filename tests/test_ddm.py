"""Golden tests: JAX DDM kernels vs the NumPy oracle (SURVEY.md §4 strategy)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_drift_detection_tpu import DDMParams
from distributed_drift_detection_tpu.ops import ddm_batch, ddm_init, ddm_scan, ddm_step

from oracle import OracleDDM, oracle_run_ddm

REF_PARAMS = DDMParams()  # 3 / 0.5 / 1.5, the reference's settings


def planted_stream(rng, n, flip_at, p0=0.05, p1=0.6):
    """Bernoulli error stream whose rate jumps at ``flip_at``."""
    probs = np.where(np.arange(n) < flip_at, p0, p1)
    return (rng.random(n) < probs).astype(np.float32)


def run_oracle_stream(errs, params=REF_PARAMS, incremental=False):
    ddm = OracleDDM(
        min_num_instances=params.min_num_instances,
        warning_level=params.warning_level,
        out_control_level=params.out_control_level,
        incremental=incremental,
    )
    warns, changes = [], []
    for e in errs:
        ddm.add_element(float(e))
        warns.append(ddm.in_warning)
        changes.append(ddm.in_change)
    return np.array(warns), np.array(changes), ddm


@pytest.mark.parametrize("seed", range(5))
def test_step_matches_oracle_flags_and_state(seed):
    rng = np.random.default_rng(seed)
    errs = planted_stream(rng, 200, flip_at=120)
    o_warn, o_change, o = run_oracle_stream(errs)

    state, (warns, changes) = ddm_scan(ddm_init(), jnp.asarray(errs), REF_PARAMS)
    np.testing.assert_array_equal(np.asarray(warns), o_warn)
    np.testing.assert_array_equal(np.asarray(changes), o_change)
    assert int(state.count) == o.count
    np.testing.assert_allclose(float(state.err_sum), o.err_sum, rtol=1e-6)
    np.testing.assert_allclose(float(state.p_min), o.p_min, rtol=1e-5)
    np.testing.assert_allclose(float(state.s_min), o.s_min, rtol=1e-5)


@pytest.mark.parametrize("seed", range(5))
def test_step_matches_incremental_form(seed):
    """skmultiflow's p += (err-p)/i form detects at the same positions."""
    rng = np.random.default_rng(100 + seed)
    errs = planted_stream(rng, 300, flip_at=200, p0=0.1, p1=0.7)
    _, o_change, _ = run_oracle_stream(errs, incremental=True)
    _, (_, changes) = ddm_scan(ddm_init(), jnp.asarray(errs), REF_PARAMS)
    np.testing.assert_array_equal(np.asarray(changes), o_change)


@pytest.mark.parametrize("seed", range(8))
def test_batch_matches_sequential_single_batch(seed):
    """Vectorised kernel == sequential loop + first-flag/early-break protocol."""
    rng = np.random.default_rng(1000 + seed)
    n = 100
    errs = planted_stream(rng, n, flip_at=rng.integers(10, 90), p0=0.05, p1=0.8)
    rows = np.arange(n)

    (ow_l, ow_g, oc_l, oc_g), o = oracle_run_ddm(errs, rows, None)

    state, res = ddm_batch(
        ddm_init(), jnp.asarray(errs), jnp.ones(n, bool), REF_PARAMS
    )
    assert int(res.first_change) == oc_l
    assert int(res.first_warning) == ow_l
    if oc_l == -1:
        # No change: carried state must match the oracle's.
        assert int(state.count) == o.count
        np.testing.assert_allclose(float(state.ps_min), o.ps_min, rtol=1e-5)


@pytest.mark.parametrize("seed", range(8))
def test_batch_carries_state_across_batches(seed):
    """Chained ddm_batch calls == one long sequential run (reference C7:202)."""
    rng = np.random.default_rng(2000 + seed)
    b, nb = 50, 6
    errs = planted_stream(rng, b * nb, flip_at=rng.integers(120, 250), p0=0.02, p1=0.9)
    rows = np.arange(b * nb)

    # Oracle: feed batches, reset on change like the engine does.
    ddm = None
    oracle_flags = []
    for k in range(nb):
        fl, ddm = oracle_run_ddm(errs[k * b : (k + 1) * b], rows[k * b : (k + 1) * b], ddm)
        oracle_flags.append(fl)
        if fl[2] > -1:
            ddm = None

    state = ddm_init()
    for k in range(nb):
        state, res = ddm_batch(
            state,
            jnp.asarray(errs[k * b : (k + 1) * b]),
            jnp.ones(b, bool),
            REF_PARAMS,
        )
        assert int(res.first_change) == oracle_flags[k][2], f"batch {k}"
        assert int(res.first_warning) == oracle_flags[k][0], f"batch {k}"
        if int(res.first_change) >= 0:
            state = ddm_init()


def test_batch_padding_is_inert():
    rng = np.random.default_rng(7)
    errs = planted_stream(rng, 60, flip_at=40, p0=0.05, p1=0.9)
    valid = np.ones(100, bool)
    valid[60:] = False
    padded = np.zeros(100, np.float32)
    padded[:60] = errs

    s_full, r_full = ddm_batch(ddm_init(), jnp.asarray(errs), jnp.ones(60, bool), REF_PARAMS)
    s_pad, r_pad = ddm_batch(ddm_init(), jnp.asarray(padded), jnp.asarray(valid), REF_PARAMS)
    assert int(r_full.first_change) == int(r_pad.first_change)
    assert int(r_full.first_warning) == int(r_pad.first_warning)
    assert int(s_full.count) == int(s_pad.count)
    np.testing.assert_allclose(float(s_full.err_sum), float(s_pad.err_sum))


def test_all_invalid_batch_is_noop():
    state0 = ddm_init()
    state, res = ddm_batch(
        state0, jnp.ones(32, jnp.float32), jnp.zeros(32, bool), REF_PARAMS
    )
    assert int(res.first_change) == -1 and int(res.first_warning) == -1
    assert int(state.count) == 0
    assert float(state.err_sum) == 0.0
    assert np.isinf(float(state.ps_min))


def test_warmup_gate():
    """min_num_instances=3 with post-increment counter: checks start at the
    2nd element; a detector fed all-1 errors never fires (p+s at its min)."""
    errs = jnp.ones(10, jnp.float32)
    _, (warns, changes) = ddm_scan(ddm_init(), errs, REF_PARAMS)
    assert not bool(jnp.any(changes))
    # First element is inside warm-up regardless of value.
    errs2 = jnp.asarray([1.0, 0.0, 0.0, 1.0, 1.0, 1.0], jnp.float32)
    _, (w2, c2) = ddm_scan(ddm_init(), errs2, REF_PARAMS)
    assert not bool(w2[0]) and not bool(c2[0])


def test_step_and_batch_jit_and_vmap():
    errs = jnp.asarray(np.random.default_rng(0).random((4, 64)) < 0.3, jnp.float32)
    valid = jnp.ones((4, 64), bool)
    states = jax.vmap(lambda _: ddm_init())(jnp.arange(4))
    f = jax.jit(jax.vmap(lambda s, e, v: ddm_batch(s, e, v, REF_PARAMS)))
    out_state, res = f(states, errs, valid)
    assert out_state.count.shape == (4,)
    assert res.first_change.shape == (4,)


# ---------------------------------------------------------------------------
# noise_floor (config.DDMParams.noise_floor; DDM_ROBUST preset)
# ---------------------------------------------------------------------------

ROBUST = DDMParams(noise_floor=0.1)


def run_oracle_floor(errs, params):
    ddm = OracleDDM(
        min_num_instances=params.min_num_instances,
        warning_level=params.warning_level,
        out_control_level=params.out_control_level,
        noise_floor=params.noise_floor,
    )
    warns, changes = [], []
    for e in errs:
        ddm.add_element(float(e))
        warns.append(ddm.in_warning)
        changes.append(ddm.in_change)
    return np.array(warns), np.array(changes)


@pytest.mark.parametrize("seed", range(3))
def test_floor_step_and_batch_match_oracle(seed):
    """Scalar scan and batch kernel agree with the floored oracle."""
    rng = np.random.default_rng(seed)
    errs = planted_stream(rng, 400, 250, p0=0.01, p1=0.7)
    ow, oc = run_oracle_floor(errs, ROBUST)
    _, (kw, kc) = ddm_scan(ddm_init(), jnp.asarray(errs), ROBUST)
    assert np.array_equal(np.asarray(kw), ow)
    assert np.array_equal(np.asarray(kc), oc)
    # Batch kernel: first change position equals the oracle's first change.
    _, res = ddm_batch(
        ddm_init(), jnp.asarray(errs), jnp.ones(len(errs), bool), ROBUST
    )
    ofc = int(np.argmax(oc)) if oc.any() else -1
    assert int(res.first_change) == ofc


def test_floor_disarms_zero_minima_trap():
    """A clean warm-up stretch then one stray error: classic DDM fires a
    change off the zero-width band (the measured r04 'linear' over-firing
    loop); the floored preset stays quiet but still detects a real jump."""
    errs = np.zeros(200, np.float32)
    errs[100] = 1.0  # single residual error after a clean stretch
    _, (_, c_classic) = ddm_scan(ddm_init(), jnp.asarray(errs), REF_PARAMS)
    _, (_, c_floor) = ddm_scan(ddm_init(), jnp.asarray(errs), ROBUST)
    assert bool(np.asarray(c_classic).any())  # the trap, reproduced
    assert not np.asarray(c_floor).any()  # the fix

    jump = np.concatenate([np.zeros(100, np.float32), np.ones(60, np.float32)])
    _, (_, c_jump) = ddm_scan(ddm_init(), jnp.asarray(jump), ROBUST)
    fired = np.asarray(c_jump)
    assert fired.any() and int(np.argmax(fired)) < 130  # prompt real detection


def test_floor_zero_is_classic_ddm_bitwise():
    rng = np.random.default_rng(7)
    errs = planted_stream(rng, 300, 180)
    explicit = DDMParams(noise_floor=0.0)
    _, (w0, c0) = ddm_scan(ddm_init(), jnp.asarray(errs), REF_PARAMS)
    _, (w1, c1) = ddm_scan(ddm_init(), jnp.asarray(errs), explicit)
    assert np.array_equal(np.asarray(w0), np.asarray(w1))
    assert np.array_equal(np.asarray(c0), np.asarray(c1))
