"""Sanitizer subsystem: checkify'd DDM contract + host-side flag audit."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_drift_detection_tpu import RunConfig, run
from distributed_drift_detection_tpu.config import replace
from distributed_drift_detection_tpu.engine.loop import FlagRows
from distributed_drift_detection_tpu.ops import ddm_init
from distributed_drift_detection_tpu.utils.validate import (
    checked_ddm_window,
    validate_flag_rows,
)

from conftest import needs_reference


def test_checked_window_accepts_valid_input():
    rng = np.random.default_rng(0)
    errs = (rng.random((4, 20)) < 0.2).astype(np.float32)
    valid = np.ones((4, 20), bool)
    err, (end, res) = jax.jit(checked_ddm_window)(
        ddm_init(), jnp.asarray(errs), jnp.asarray(valid)
    )
    err.throw()  # no violation
    assert int(end.count) == 80


@pytest.mark.parametrize(
    "bad_errs",
    [np.full((2, 10), 2.0, np.float32), np.full((2, 10), np.nan, np.float32)],
)
def test_checked_window_rejects_non_indicator_errs(bad_errs):
    err, _ = jax.jit(checked_ddm_window)(
        ddm_init(), jnp.asarray(bad_errs), jnp.ones((2, 10), bool)
    )
    with pytest.raises(checkify_error_type()):
        err.throw()


def checkify_error_type():
    from jax.experimental import checkify

    return checkify.JaxRuntimeError


def _good_flags(p=3, nbf=8, b=10):
    i32 = np.int32
    return FlagRows(
        warning_local=np.full((p, nbf), -1, i32),
        warning_global=np.full((p, nbf), -1, i32),
        change_local=np.full((p, nbf), -1, i32),
        change_global=np.full((p, nbf), -1, i32),
        forced_retrain=np.zeros((p, nbf), bool),
    )


def test_flag_audit_passes_clean_table():
    f = _good_flags()
    f.change_local[1, 3] = 4
    f.change_global[1, 3] = 34
    validate_flag_rows(f, num_batches=9, per_batch=10, num_rows=90)


@pytest.mark.parametrize(
    "corrupt,msg",
    [
        (lambda f: f.change_local.__setitem__((0, 0), 10), "per_batch"),
        (lambda f: f.change_global.__setitem__((0, 0), 9000), "num_rows"),
        (
            lambda f: f.warning_global.__setitem__((0, 0), 5),
            "sentinel disagrees",
        ),
        (
            lambda f: (
                f.warning_local.__setitem__((0, 0), 7),
                f.warning_global.__setitem__((0, 0), 7),
                f.change_local.__setitem__((0, 0), 2),
                f.change_global.__setitem__((0, 0), 2),
            ),
            "warning recorded after the change",
        ),
    ],
)
def test_flag_audit_catches_corruption(corrupt, msg):
    f = _good_flags()
    corrupt(f)
    with pytest.raises(ValueError, match=msg):
        validate_flag_rows(f, num_batches=9, per_batch=10, num_rows=90)


@needs_reference
def test_api_run_with_validation():
    """End-to-end: validate=True audits the real flag table silently."""
    res = run(
        RunConfig(
            dataset="/root/reference/outdoorStream.csv",
            mult_data=8,
            partitions=4,
            per_batch=50,
            model="centroid",
            results_csv="",
            validate=True,
        )
    )
    assert res.metrics.num_detections > 0
