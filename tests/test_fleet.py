"""Fleet observability (ISSUE 3): run registry, cross-host trace
correlation, the live `watch` CLI with stall detection, heartbeat events,
torn-tail reads, and the flag-event ordering the correlator depends on."""

import json
import os
import time

import numpy as np
import pytest

from distributed_drift_detection_tpu.telemetry import (
    EventLog,
    SchemaError,
    emit_flag_events,
    read_events,
)
from distributed_drift_detection_tpu.telemetry import registry
from distributed_drift_detection_tpu.telemetry.correlate import (
    CorrelationError,
    correlate,
    group_run_logs,
    render_correlation,
)
from distributed_drift_detection_tpu.telemetry.watch import (
    EXIT_NO_LOG,
    EXIT_OK,
    EXIT_STALLED,
    LogTail,
    WatchState,
    watch,
)

# ---------------------------------------------------------------------------
# read_events: torn-tail tolerance (crash / live-tail read path)
# ---------------------------------------------------------------------------


def _write_lines(path, lines):
    with open(path, "w") as fh:
        fh.write("\n".join(lines))


def _event_line(etype="phase_completed", seq=0, ts=0.0, **payload):
    payload = payload or {"phase": "detect", "seconds": 1.0}
    return json.dumps(
        {"v": 1, "type": etype, "ts": ts, "seq": seq, **payload}
    )


def test_partial_tail_skips_exactly_one_torn_trailing_line(tmp_path):
    path = str(tmp_path / "run.jsonl")
    good = _event_line(seq=0)
    torn = _event_line(seq=1)[:17]  # cut mid-object: invalid JSON prefix
    _write_lines(path, [good, torn])
    # strict default: the gate contract is unchanged
    with pytest.raises(SchemaError, match="not JSON"):
        read_events(path)
    events = read_events(path, allow_partial_tail=True)
    assert [e["seq"] for e in events] == [0]


def test_partial_tail_never_skips_interior_or_invalid_lines(tmp_path):
    path = str(tmp_path / "run.jsonl")
    # torn INTERIOR line: corruption, not a tear — always raises
    _write_lines(path, [_event_line(seq=0)[:17], _event_line(seq=1)])
    with pytest.raises(SchemaError, match="not JSON"):
        read_events(path, allow_partial_tail=True)
    # complete-but-schema-invalid last line: producer bug, not a tear
    _write_lines(
        path, [_event_line(seq=0), json.dumps({"v": 1, "type": "nope"})]
    )
    with pytest.raises(SchemaError, match="unknown event type"):
        read_events(path, allow_partial_tail=True)


def test_open_run_embeds_process_index(tmp_path):
    log = EventLog.open_run(str(tmp_path), name="x", process_index=3)
    log.close()
    assert "-proc3-" in os.path.basename(log.path)
    log = EventLog.open_run(str(tmp_path), name="x")
    log.close()
    assert "-proc" not in os.path.basename(log.path)


# ---------------------------------------------------------------------------
# host identity (parallel.multihost.host_identity)
# ---------------------------------------------------------------------------


def test_host_identity_shape():
    from distributed_drift_detection_tpu.parallel.multihost import (
        host_identity,
    )

    ident = host_identity()
    assert set(ident) == {"hostname", "process_index", "process_count"}
    assert ident["hostname"]
    assert ident["process_index"] == 0  # single-process test run
    assert ident["process_count"] >= 1


def test_host_identity_env_fallback_without_backend(monkeypatch):
    from distributed_drift_detection_tpu.parallel import multihost

    # The jax-init-safety contract: with no live backend the probe must not
    # create one — identity comes from the launcher env, else (0, 1).
    monkeypatch.setattr(multihost, "_backend_initialized", lambda: False)
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    monkeypatch.setenv("JAX_PROCESS_COUNT", "4")
    ident = multihost.host_identity()
    assert (ident["process_index"], ident["process_count"]) == (2, 4)
    monkeypatch.setenv("JAX_PROCESS_ID", "bogus")
    assert multihost.host_identity()["process_index"] == 0
    # cluster-manager ranks (what jax's own autodetection reads) also work
    monkeypatch.delenv("JAX_PROCESS_ID")
    monkeypatch.delenv("JAX_PROCESS_COUNT")
    monkeypatch.setenv("SLURM_PROCID", "5")
    monkeypatch.setenv("SLURM_NTASKS", "8")
    ident = multihost.host_identity()
    assert (ident["process_index"], ident["process_count"]) == (5, 8)


def test_host_identity_prefers_distributed_control_plane(monkeypatch):
    # The pod window between jax.distributed.initialize() and the first
    # device op: no backend exists yet, but the control plane knows the
    # topology — it must win over both the backend probe and the env.
    from distributed_drift_detection_tpu.parallel import multihost

    # the real probe reports None in this single-process test run
    assert multihost._distributed_identity() is None
    monkeypatch.setattr(multihost, "_distributed_identity", lambda: (3, 8))
    monkeypatch.setattr(multihost, "_backend_initialized", lambda: True)
    ident = multihost.host_identity()
    assert (ident["process_index"], ident["process_count"]) == (3, 8)


# ---------------------------------------------------------------------------
# run registry (telemetry.registry)
# ---------------------------------------------------------------------------


def test_registry_round_trip_and_fold(tmp_path):
    d = str(tmp_path)
    registry.record(
        d, "r1", "running", config_digest="abc", log="r1.jsonl",
        process_index=0,
    )
    registry.record(d, "r2", "running", config_digest="def", log="r2.jsonl")
    registry.record(d, "r1", "completed", rows=100)
    recs = registry.read_index(d)
    assert [r["run_id"] for r in recs] == ["r1", "r2", "r1"]
    folded = registry.runs(d)
    assert folded["r1"]["status"] == "completed"
    # the terminal record inherits the start's extras and keeps started_ts
    assert folded["r1"]["log"] == "r1.jsonl"
    assert folded["r1"]["config_digest"] == "abc"
    assert folded["r1"]["started_ts"] == recs[0]["ts"]
    assert folded["r2"]["status"] == "running"
    with pytest.raises(ValueError, match="unknown registry status"):
        registry.record(d, "r3", "exploded")


def test_registry_torn_tail_and_empty(tmp_path):
    d = str(tmp_path)
    assert registry.read_index(d) == []
    registry.record(d, "r1", "running")
    with open(registry.index_path(d), "a") as fh:
        fh.write('{"ts": 1, "run_id": "r2", "status": "runn')  # torn append
    assert [r["run_id"] for r in registry.read_index(d)] == ["r1"]
    # interior corruption is NOT a tear
    with open(registry.index_path(d), "a") as fh:
        fh.write("\n" + json.dumps({"ts": 2, "run_id": "r3", "status": "running"}) + "\n")
    with pytest.raises(ValueError, match="corrupt registry record"):
        registry.read_index(d)


def test_config_digest_canonical():
    a = registry.config_digest({"model": "centroid", "seed": 0})
    b = registry.config_digest({"seed": 0, "model": "centroid"})
    assert a == b and len(a) == 12
    assert a != registry.config_digest({"model": "centroid", "seed": 1})


def _fake_run_log(tmp_path, name, t0, *, proc=0, nproc=1, config=None,
                  detect_s=1.0, completed=True, extra=()):
    """A synthetic per-process run log with a controllable clock: events at
    t0, t0+1, ... — the correlate/watch fixtures' workhorse."""
    ticks = iter(t0 + 0.5 * i for i in range(1000))
    log = EventLog.open_run(
        str(tmp_path), name=name, process_index=proc if nproc > 1 else None
    )
    log._clock = lambda: next(ticks)
    log.emit(
        "run_started",
        run_id=log.run_id,
        config=config or {"model": "centroid", "seed": 0},
        hostname=f"host{proc}",
        process_index=proc,
        process_count=nproc,
    )
    for phase, secs in [("prepare", 0.2), ("detect", detect_s)]:
        log.emit("phase_completed", phase=phase, seconds=secs)
    for etype, payload in extra:
        log.emit(etype, **payload)
    if completed:
        log.emit(
            "run_completed",
            rows=100_000,
            seconds=detect_s + 0.2,
            detections=7,
        )
    log.close()
    return log.path


# ---------------------------------------------------------------------------
# newest-run resolution (shared by report --dir and watch <dir>)
# ---------------------------------------------------------------------------


def test_newest_run_log_recency_semantics(tmp_path):
    d = str(tmp_path)
    assert registry.newest_run_log(d) is None
    old = _fake_run_log(tmp_path, "old", 1000.0)
    new = _fake_run_log(tmp_path, "new", 2000.0)
    # no index yet: mtime fallback — give the OLD log the newer mtime to
    # prove the fallback really is mtime
    now = time.time()
    os.utime(old, (now + 60, now + 60))
    os.utime(new, (now - 60, now - 60))
    assert registry.newest_run_log(d) == old
    # registered: recency = max(started, last write). With stale mtimes on
    # both, registration order (b started after a) decides...
    os.utime(old, (now - 3600, now - 3600))
    registry.record(d, "a", "running", log=os.path.basename(old))
    registry.record(d, "b", "running", log=os.path.basename(new))
    assert registry.newest_run_log(d) == new
    # ...but a registered run STILL BEING WRITTEN outranks a newer start —
    # the live log is the one to watch, not the one that started last
    os.utime(old, (now + 120, now + 120))
    assert registry.newest_run_log(d) == old
    # a registered-but-pruned log falls through to the survivor
    os.utime(old, (now - 3600, now - 3600))
    os.remove(new)
    assert registry.newest_run_log(d) == old


def test_newest_run_log_mixed_registered_and_unregistered(tmp_path):
    # Producers that drive EventLog.open_run directly never register; a
    # directory mixing both must resolve to whichever run is truly newest.
    d = str(tmp_path)
    reg = _fake_run_log(tmp_path, "registered", 100.0)
    registry.record(d, "a", "running", log=os.path.basename(reg))
    unreg = _fake_run_log(tmp_path, "unregistered", 200.0)
    now = time.time()
    os.utime(unreg, (now + 60, now + 60))  # written after `a` started
    assert registry.newest_run_log(d) == unreg
    os.utime(unreg, (now - 7 * 86400,) * 2)  # a week stale: registered wins
    assert registry.newest_run_log(d) == reg


# ---------------------------------------------------------------------------
# cross-host correlation (telemetry.correlate)
# ---------------------------------------------------------------------------


def test_correlate_identifies_slower_host_across_clock_skew(tmp_path):
    # Host clocks 5000 s apart (t0 offsets): correlation must rebase, not
    # compare wall-clocks. Host 1's detect takes 2.5x host 0's.
    a = _fake_run_log(tmp_path, "w", 1000.0, proc=0, nproc=2, detect_s=1.0)
    b = _fake_run_log(tmp_path, "w", 6000.0, proc=1, nproc=2, detect_s=2.5)
    corr = correlate([a, b])
    assert [h["process_index"] for h in corr["hosts"]] == [0, 1]
    st = corr["stragglers"]["detect"]
    assert st["slowest"] == 1 and st["fastest"] == 0
    assert st["spread_s"] == pytest.approx(1.5)
    # every host's timeline starts at its own run_started: skew rebased
    first_t = {
        h: min(e["t"] for e in corr["timeline"] if e["host"] == h)
        for h in (0, 1)
    }
    assert first_t == {0: 0.0, 1: 0.0}
    out = render_correlation(corr)
    assert "slowest proc1" in out and "fastest proc0" in out
    assert "host1" in out


def test_correlate_merged_timeline_deterministic(tmp_path):
    a = _fake_run_log(tmp_path, "w", 1000.0, proc=0, nproc=2)
    b = _fake_run_log(tmp_path, "w", 9000.0, proc=1, nproc=2)
    one = correlate([a, b])
    two = correlate([b, a])  # argument order must not matter
    assert one["timeline"] == two["timeline"]
    assert render_correlation(one) == render_correlation(two)
    key = [(e["t"], e["host"], e["seq"]) for e in one["timeline"]]
    assert key == sorted(key)


def test_correlate_rejects_mixed_configs(tmp_path):
    a = _fake_run_log(tmp_path, "w", 1000.0, config={"model": "centroid"})
    b = _fake_run_log(tmp_path, "w", 2000.0, config={"model": "mlp"})
    with pytest.raises(CorrelationError, match="different config digests"):
        correlate([a, b])


def test_correlate_rejects_two_runs_of_one_config(tmp_path):
    # Same digest but a repeated process index: two successive runs of one
    # cell, not one fleet — merging would interleave unrelated timelines.
    a = _fake_run_log(tmp_path, "w", 1000.0, proc=0, nproc=2)
    b = _fake_run_log(tmp_path, "w", 2000.0, proc=0, nproc=2)
    with pytest.raises(CorrelationError, match="same process index"):
        correlate([a, b])


def test_group_run_logs_picks_newest_coherent_group(tmp_path):
    cfg_old = {"model": "centroid", "seed": 0}
    cfg_new = {"model": "centroid", "seed": 1}
    _fake_run_log(tmp_path, "old", 100.0, proc=0, nproc=2, config=cfg_old)
    _fake_run_log(tmp_path, "old", 100.0, proc=1, nproc=2, config=cfg_old)
    new = [
        _fake_run_log(tmp_path, "new", 500.0, proc=0, nproc=2, config=cfg_new),
        _fake_run_log(tmp_path, "new", 505.0, proc=1, nproc=2, config=cfg_new),
    ]
    # the registry index in the dir must not confuse the grouper
    registry.record(str(tmp_path), "sweep-1", "running", kind="sweep")
    assert sorted(group_run_logs(str(tmp_path))) == sorted(new)
    corr = correlate(group_run_logs(str(tmp_path)))
    assert len(corr["hosts"]) == 2
    assert corr["config"] == cfg_new


def test_group_run_logs_rerun_of_older_config_wins(tmp_path):
    # A re-run of config A groups WITH A's first run; the group must rank
    # by its newest member, else this-morning's config B shadows the
    # actually-newest A re-run.
    cfg_a = {"model": "centroid", "seed": 0}
    cfg_b = {"model": "mlp", "seed": 0}
    _fake_run_log(tmp_path, "a1", 100.0, config=cfg_a)  # A, yesterday
    _fake_run_log(tmp_path, "b", 500.0, config=cfg_b)  # B, this morning
    rerun = _fake_run_log(tmp_path, "a2", 900.0, config=cfg_a)  # A, newest
    assert group_run_logs(str(tmp_path)) == [rerun]


def test_correlate_rate_is_resume_safe(tmp_path):
    # A checkpoint-resumed soak host: rows_done is stream-absolute (50k
    # resumed offset), elapsed_s is this-process. The single-beat ratio
    # would claim 26,000 rows/s and name the FRESH host as straggler;
    # deltas give the true 1,000 vs 2,000.
    resumed = [
        ("heartbeat", dict(rows_done=50_000 + 1000 * t, elapsed_s=float(t)))
        for t in (1, 2)
    ]
    fresh = [
        ("heartbeat", dict(rows_done=2000 * t, elapsed_s=float(t)))
        for t in (1, 2)
    ]
    a = _fake_run_log(tmp_path, "w", 0.0, proc=0, nproc=2, completed=False,
                      extra=resumed)
    b = _fake_run_log(tmp_path, "w", 0.0, proc=1, nproc=2, completed=False,
                      extra=fresh)
    st = correlate([a, b])["stragglers"]["throughput"]
    assert st["per_host"] == pytest.approx({0: 1000.0, 1: 2000.0})
    assert st["slowest"] == 0
    assert st["skew"] == pytest.approx(2.0)


def test_correlate_throughput_skew_from_heartbeats(tmp_path):
    beats = lambda rate: [  # noqa: E731 — tiny fixture builder
        ("heartbeat", dict(rows_done=rate * t, elapsed_s=float(t)))
        for t in (1, 2)
    ]
    a = _fake_run_log(
        tmp_path, "w", 0.0, proc=0, nproc=2, completed=False,
        extra=beats(1000),
    )
    b = _fake_run_log(
        tmp_path, "w", 0.0, proc=1, nproc=2, completed=False,
        extra=beats(250),
    )
    st = correlate([a, b])["stragglers"]["throughput"]
    assert st["slowest"] == 1
    assert st["skew"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# watch CLI (telemetry.watch)
# ---------------------------------------------------------------------------


def test_logtail_partial_line_tolerant(tmp_path):
    path = str(tmp_path / "run.jsonl")
    full = _event_line(seq=0, ts=1.0)
    with open(path, "w") as fh:
        fh.write(full + "\n" + _event_line(seq=1, ts=2.0)[:13])
    tail = LogTail(path)
    assert [e["seq"] for e in tail.poll()] == [0]
    assert tail.poll() == []  # torn tail is not consumed...
    with open(path, "a") as fh:
        fh.write(_event_line(seq=1, ts=2.0)[13:] + "\n")
    assert [e["seq"] for e in tail.poll()] == [1]  # ...and completes later


def test_watch_state_progress_eta_and_delta_rate():
    st = WatchState()
    st.fold(
        [
            json.loads(_event_line("run_started", 0, 0.0, run_id="r",
                                   config={"total_rows": 10_000})),
            # resumed soak shape: rows_done stream-absolute, elapsed local —
            # the single-beat ratio would claim 5000 rows/s
            json.loads(_event_line("heartbeat", 1, 1.0, rows_done=5000,
                                   elapsed_s=1.0)),
            json.loads(_event_line("heartbeat", 2, 2.0, rows_done=6000,
                                   elapsed_s=2.0)),
        ]
    )
    assert st.rate() == pytest.approx(1000.0)  # delta rate, not 3000
    line = st.status_line(now=3.0)
    assert "rows 6,000/10,000 (60.0%)" in line
    assert "1,000 rows/s" in line
    assert "eta 4s" in line
    assert "last heartbeat 1.0s ago" in line


def _stalled_log(tmp_path, age_s=3600.0):
    """A log whose last event is `age_s` old with no run_completed."""
    return _fake_run_log(
        tmp_path, "stalled", time.time() - age_s, completed=False
    )


def test_watch_once_exit_codes(tmp_path):
    healthy = _fake_run_log(tmp_path / "ok", "ok", time.time() - 3600)
    stalled = _stalled_log(tmp_path / "bad")
    assert (
        watch(healthy, once=True, stall_after=60, out=lambda *_: None)
        == EXIT_OK  # completed: old but finished is healthy
    )
    assert (
        watch(stalled, once=True, stall_after=60, out=lambda *_: None)
        == EXIT_STALLED
    )
    # in-progress within the window: healthy so far
    fresh = _fake_run_log(tmp_path / "live", "live", time.time() - 1,
                          completed=False)
    assert (
        watch(fresh, once=True, stall_after=3600, out=lambda *_: None)
        == EXIT_OK
    )
    assert (
        watch(str(tmp_path / "nope"), once=True, out=lambda *_: None)
        == EXIT_NO_LOG
    )


def test_watch_resolves_directory_to_newest_run(tmp_path):
    older = _fake_run_log(tmp_path, "older", 100.0)
    newest = _fake_run_log(tmp_path, "newer", 200.0)
    now = time.time()  # pin mtimes: same-second creation must not tie
    os.utime(older, (now - 60, now - 60))
    os.utime(newest, (now, now))
    lines = []
    assert watch(str(tmp_path), once=True, out=lines.append) == EXIT_OK
    assert lines[0] == f"watching {newest}"


def test_watch_loop_detects_stall_then_completion(tmp_path):
    path = _stalled_log(tmp_path, age_s=100.0)
    fake_now = [time.time()]
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        fake_now[0] += s

    rc = watch(
        path, stall_after=150.0, interval=30.0,
        clock=lambda: fake_now[0], sleep=sleep, out=lambda *_: None,
    )
    assert rc == EXIT_STALLED
    assert len(sleeps) == 2  # polled until the age crossed 150 s
    # the same log completing is detected and exits 0
    with open(path, "a") as fh:
        fh.write(
            _event_line("run_completed", 99, time.time(), rows=1,
                        seconds=1.0, detections=0) + "\n"
        )
    rc = watch(
        path, stall_after=150.0, clock=lambda: fake_now[0],
        sleep=sleep, out=lambda *_: None,
    )
    assert rc == EXIT_OK


def test_watch_and_correlate_cli_entrypoints(tmp_path, capsys):
    from distributed_drift_detection_tpu.__main__ import main as cli_main

    path = _fake_run_log(tmp_path, "cli", 100.0)
    with pytest.raises(SystemExit) as exc:
        cli_main(["watch", path, "--once", "--stall-after", "60"])
    assert exc.value.code == EXIT_OK
    assert "completed" in capsys.readouterr().out
    cli_main(["correlate", str(tmp_path)])
    assert "correlated 1 process log(s)" in capsys.readouterr().out
    cli_main(["report", "--dir", str(tmp_path)])
    assert "throughput" in capsys.readouterr().out


def test_report_cli_renders_torn_log(tmp_path, capsys):
    """The post-mortem CLI must render exactly the logs it exists for:
    crashed or still-writing, torn final line included."""
    from distributed_drift_detection_tpu.__main__ import main as cli_main

    path = _fake_run_log(tmp_path, "torn", 100.0, completed=False)
    with open(path, "a") as fh:
        fh.write('{"v": 1, "type": "run_comp')  # crash mid-append
    cli_main(["report", path])
    out = capsys.readouterr().out
    assert "run incomplete" in out


def test_api_run_registers_and_completes_in_registry(tmp_path):
    from distributed_drift_detection_tpu import RunConfig, run

    d = str(tmp_path / "tele")
    res = run(
        RunConfig(
            dataset="synth:rialto,seed=0", mult_data=1, partitions=2,
            per_batch=50, model="centroid", results_csv="",
            telemetry_dir=d,
        )
    )
    folded = registry.runs(d)
    (rec,) = folded.values()
    assert rec["status"] == "completed"
    assert rec["process_index"] == 0 and rec["process_count"] >= 1
    assert rec["hostname"]
    assert os.path.join(d, rec["log"]) == res.telemetry_path
    assert registry.newest_run_log(d) == res.telemetry_path
    # identity extras ride run_started; a one-shot run emits no heartbeat
    events = read_events(res.telemetry_path)
    started = events[0]
    assert started["process_index"] == 0 and started["hostname"]
    assert not any(e["type"] == "heartbeat" for e in events)


def test_api_run_failure_is_recorded_as_failed(tmp_path):
    from distributed_drift_detection_tpu import RunConfig, run

    d = str(tmp_path / "tele")
    with pytest.raises(FileNotFoundError):
        run(
            RunConfig(
                dataset="/does/not/exist.csv", results_csv="",
                telemetry_dir=d,
            )
        )
    (rec,) = registry.runs(d).values()
    assert rec["status"] == "failed"
    assert rec["log"]  # the partial log is the evidence; registry points at it


def test_api_run_failed_record_is_best_effort(tmp_path, monkeypatch):
    """A registry append that fails on the crash path (e.g. the same full
    volume that killed the run) must not mask the run's own exception."""
    from distributed_drift_detection_tpu import RunConfig, run

    orig = registry.record

    def flaky(d, run_id, status, **kw):
        if status == "failed":
            raise OSError("telemetry volume full")
        return orig(d, run_id, status, **kw)

    monkeypatch.setattr(registry, "record", flaky)
    with pytest.raises(FileNotFoundError):  # the run's error, not OSError
        run(
            RunConfig(
                dataset="/does/not/exist.csv", results_csv="",
                telemetry_dir=str(tmp_path / "tele"),
            )
        )


def test_grid_sweep_writes_registry_bracket(tmp_path):
    from distributed_drift_detection_tpu.config import RunConfig
    from distributed_drift_detection_tpu.harness.grid import run_grid

    d = str(tmp_path / "tele")
    base = RunConfig(
        dataset="synth:rialto,seed=0", per_batch=50, model="centroid",
        results_csv=str(tmp_path / "res.csv"),
    )
    n = run_grid(
        base, mults=[1.0], partitions=[2], trials=1,
        progress=lambda *_: None, telemetry_dir=d,
    )
    assert n == 1
    folded = registry.runs(d)
    sweeps = [r for r in folded.values() if r.get("kind") == "sweep"]
    trials = [r for r in folded.values() if r.get("kind") != "sweep"]
    assert len(sweeps) == 1 and sweeps[0]["status"] == "completed"
    assert sweeps[0]["trials_total"] == 1 and sweeps[0]["trials_run"] == 1
    assert len(trials) == 1 and trials[0]["status"] == "completed"


# ---------------------------------------------------------------------------
# emit_flag_events ordering: the property the correlator leans on
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case", range(25))
def test_emit_flag_events_column_major_property(tmp_path, case):
    """Property test (seeded random tables — hypothesis is not available in
    every supported environment): the emitted drift/retrain timelines are
    column-major (batch ascending, partition ascending within a batch),
    ``batch`` is the 1-based flag-table column, delays are ``pos % dist``
    (None without geometry), and ``forced`` mirrors the forced_retrain
    table exactly — the order the correlator's merged timeline inherits."""
    rng = np.random.default_rng(1234 + case)
    p = int(rng.integers(1, 6))
    nb = int(rng.integers(1, 9))
    dist = int(rng.choice([0, 100, 517]))
    changed = rng.random((p, nb)) < 0.3
    cg = np.where(changed, rng.integers(0, 10_000, (p, nb)), -1)
    fr = rng.random((p, nb)) < 0.2

    log = EventLog(str(tmp_path / f"flags{case}.jsonl"))
    with log:
        n = emit_flag_events(log, cg, fr, dist)
    events = read_events(log.path)
    drifts = [e for e in events if e["type"] == "drift_detected"]
    retrains = [e for e in events if e["type"] == "retrain"]

    assert n == len(drifts) == int(changed.sum())
    # drift events first, then retrains — each internally column-major
    assert [e["type"] for e in events] == (
        ["drift_detected"] * len(drifts) + ["retrain"] * len(retrains)
    )
    for group in (drifts, retrains):
        key = [(e["batch"], e["partition"]) for e in group]
        assert key == sorted(key), "timeline must be batch-then-partition"
    # batch = column + 1 and delay semantics per drift
    for e in drifts:
        b, q = e["batch"] - 1, e["partition"]
        assert changed[q, b]
        assert e["global_pos"] == int(cg[q, b])
        expect = (int(cg[q, b]) % dist) if dist > 0 else None
        assert e["delay_rows"] == expect
    # retrains cover changed | forced, with the forced flag verbatim
    expect_rt = sorted(
        (int(b) + 1, int(q))
        for q, b in zip(*np.nonzero(changed | fr))
    )
    assert [(e["batch"], e["partition"]) for e in retrains] == expect_rt
    for e in retrains:
        assert e["forced"] == bool(fr[e["partition"], e["batch"] - 1])
