"""api.run (one-shot) and the chunked path agree bit-exactly when the
chunked feeder uses config.host_shuffle_seed — the cross-path contract."""

import numpy as np
import pytest

from distributed_drift_detection_tpu import RunConfig, run
from distributed_drift_detection_tpu.config import host_shuffle_seed
from distributed_drift_detection_tpu.engine import ChunkedDetector
from distributed_drift_detection_tpu.io import chunk_stream_arrays, planted_prototypes
from distributed_drift_detection_tpu.models import ModelSpec, build_model


@pytest.mark.parametrize(
    "concepts,rpc",
    [
        (3, 160),  # fast-tier representative of the cross-path contract
        pytest.param(6, 400, marks=pytest.mark.slow),  # full size
    ],
)
def test_chunked_matches_api_run_with_host_shuffle(concepts, rpc):
    stream = planted_prototypes(2, concepts=concepts, rows_per_concept=rpc,
                                features=7)
    cfg = RunConfig(
        partitions=4, per_batch=50, model="centroid",
        shuffle_batches=True, results_csv="", seed=3,
    )
    res = run(cfg, stream=stream)
    ref = np.asarray(res.flags.change_global)
    assert (ref >= 0).any()  # the contract must be detection-bearing

    det = ChunkedDetector(
        build_model(cfg.model, ModelSpec(stream.num_features, stream.num_classes), cfg),
        cfg.ddm, partitions=cfg.partitions, seed=cfg.seed,
    )
    chunks = chunk_stream_arrays(
        stream.X, stream.y, cfg.partitions, cfg.per_batch,
        chunk_batches=3, shuffle_seed=host_shuffle_seed(cfg),
    )
    got = det.run(chunks)
    w = ref.shape[1]
    np.testing.assert_array_equal(got.change_global[:, :w], ref)
    assert np.all(got.change_global[:, w:] == -1)
