"""Host-callback RandomForest parity model (models/rf.py).

The RF path exists to run the reference's actual model family
(``DDM_Process.py:96-105``) through the TPU-native engine for parity
experiments; these tests check it composes with jit/vmap/scan and detects
the same planted drifts as the pytree flagships.
"""

import numpy as np
import pytest

from distributed_drift_detection_tpu.api import run
from distributed_drift_detection_tpu.config import RunConfig, replace
from distributed_drift_detection_tpu.io.synth import planted_prototypes


@pytest.fixture(scope="module")
def stream():
    return planted_prototypes(seed=3, concepts=6, rows_per_concept=200, features=8)


def _cfg(**kw):
    base = RunConfig(
        dataset="<in-memory>",
        per_batch=50,
        partitions=2,
        model="rf",
        rf_estimators=10,  # small forest: the test cares about plumbing
        results_csv="",
        window=1,
    )
    return replace(base, **kw)


def test_rf_detects_planted_drifts(stream):
    res = run(_cfg(), stream=stream)
    # 6 concepts → 5 planted changes per partition; clean prototype geometry
    # means the forest nails every one (like the reference's RF would).
    per_part = (res.flags.change_global >= 0).sum(axis=1)
    assert per_part.shape == (2,)
    assert (per_part == 5).all()
    assert res.metrics.mean_delay_batches <= 1.5


def test_rf_matches_centroid_detections(stream):
    rf = run(_cfg(), stream=stream)
    cent = run(_cfg(model="centroid"), stream=stream)
    # Same planted stream, both models near-perfect → identical detection
    # batch positions (flags are per-batch, model-agnostic on clean data).
    np.testing.assert_array_equal(
        rf.flags.change_global >= 0, cent.flags.change_global >= 0
    )


@pytest.mark.slow
def test_rf_window_engine(stream):
    """The speculative window engine composes with the host callback.

    Bit-equality of flags across window sizes holds here only because the
    clean planted-prototype fixture makes forest predictions seed-insensitive
    — rf's fit consumes a PRNG key (the sklearn random_state), and the window
    engine splits keys per window rather than per batch, so on noisy data rf
    (like mlp) is seed-equivalent but not bit-reproducible across `window`
    values (see the `model` comment in config.py).
    """
    seq = run(_cfg(), stream=stream)
    win = run(_cfg(window=8), stream=stream)
    np.testing.assert_array_equal(
        seq.flags.change_global, win.flags.change_global
    )


@pytest.mark.slow
def test_rf_runs_unsharded_on_multidevice_host():
    """model='rf' must not build a sharded mesh program: host callbacks
    inside an SPMD computation deadlock the CPU collective rendezvous (one
    device thread blocks in the callback while the rest wait at the
    drift-vote all-reduce). prepare() pins rf to one device."""
    from distributed_drift_detection_tpu import RunConfig, run
    from distributed_drift_detection_tpu.api import prepare

    cfg = RunConfig(dataset="synth:rialto,seed=0", mult_data=0.2, partitions=8,
                    per_batch=50, model="rf", rf_estimators=5, results_csv="")
    prep = prepare(cfg)
    assert prep.mesh is None
    res = run(cfg)
    assert res.metrics.num_detections >= 0  # completes without deadlock
