"""Dirty-stream hardening: ingest validation, row quarantine, guard plane.

Covers the io.sanitize subsystem end to end: the three-policy contract
(strict / quarantine / repair), the doctor CLI's exit-code contract, the
quarantine sidecar (schema + torn-tail tolerance), the stream.load
fault-injection kinds, and the headline acceptance — a stream with k
corrupted rows under data_policy='quarantine' emits drift flags
bit-identical to the clean stream with those k rows masked, on both the
one-shot and chunked engines.
"""

import json
import os

import numpy as np
import pytest

from distributed_drift_detection_tpu import RunConfig, run
from distributed_drift_detection_tpu.config import (
    host_shuffle_seed,
    replace,
    resolve_quarantine_path,
)
from distributed_drift_detection_tpu.io.sanitize import (
    POLICIES,
    QuarantineWriter,
    RowIssue,
    StreamContractError,
    load_csv_sane,
    main as doctor_main,
    mask_rows,
    parse_rows,
    read_quarantine,
    scan_csv,
    validate_header,
)
from distributed_drift_detection_tpu.io.stream import (
    load_csv,
    load_stream,
    stripe_partitions,
    stripe_partitions_packed,
    synthesize_stream,
)
from distributed_drift_detection_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


def write_csv(path, X, y, corrupt=None):
    """Reference-schema CSV (header 0..F-1,target); ``corrupt`` maps a
    0-based data-row index to a corruption kind."""
    corrupt = corrupt or {}
    n, f = X.shape
    with open(path, "w") as fh:
        fh.write(",".join([*map(str, range(f)), "target"]) + "\n")
        for i in range(n):
            row = ",".join(repr(float(v)) for v in X[i]) + f",{int(y[i])}"
            kind = corrupt.get(i)
            if kind == "non_numeric":
                row = "junk," + row.split(",", 1)[1]
            elif kind == "nan_cell":
                row = "nan," + row.split(",", 1)[1]
            elif kind == "ragged":
                row = row.rsplit(",", 1)[0]
            elif kind == "bad_label":
                row = row.rsplit(",", 1)[0] + ",1.5"
            elif kind == "nan_label":
                row = row.rsplit(",", 1)[0] + ",nan"
            fh.write(row + "\n")
    return str(path)


def toy(n=80, f=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(classes, f)) * 3
    y = rng.integers(0, classes, n)
    X = (protos[y] + rng.normal(size=(n, f)) * 0.1).astype(np.float64)
    return X, y.astype(np.int64)


# --- contract + policies ----------------------------------------------------


def test_strict_raises_structured_error(tmp_path):
    X, y = toy()
    path = write_csv(tmp_path / "d.csv", X, y, {7: "non_numeric"})
    with pytest.raises(StreamContractError) as ei:
        load_csv_sane(path, policy="strict")
    e = ei.value
    assert e.file == path and e.row == 7 and e.column == 0
    assert "non-numeric" in str(e) and "data row 7" in str(e)


def test_header_errors_always_raise(tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("a,b,a\n1,2,3\n")
    for policy in POLICIES:
        with pytest.raises(StreamContractError, match="duplicate"):
            load_csv_sane(str(p), target_column="b", policy=policy)
    with pytest.raises(StreamContractError, match="columns found"):
        validate_header(["a", "b"], "target", str(p))


def test_quarantine_masks_rows_and_writes_sidecar(tmp_path):
    X, y = toy()
    bad = {3: "non_numeric", 20: "ragged", 41: "bad_label", 66: "nan_label"}
    path = write_csv(tmp_path / "d.csv", X, y, bad)
    qp = str(tmp_path / "q.jsonl")
    res = load_csv_sane(path, policy="quarantine", quarantine_path=qp)
    assert res.report.rows_quarantined == len(bad)
    assert res.row_ok.sum() == len(y) - len(bad)
    assert not res.row_ok[list(bad)].any()
    assert np.isfinite(res.X).all()  # masked rows canonicalized
    recs = read_quarantine(qp)
    assert sorted(r["row"] for r in recs) == sorted(bad)
    assert all(r["v"] == 1 and r["file"] == path for r in recs)
    by_row = {r["row"]: r for r in recs}
    assert "ragged" in by_row[20]["reason"]
    assert by_row[41]["column_name"] == "target"


def test_repair_imputes_means_and_clamps_labels(tmp_path):
    X, y = toy(seed=2)
    path = write_csv(
        tmp_path / "d.csv", X, y,
        {5: "nan_cell", 11: "bad_label", 30: "ragged"},
    )
    res = load_csv_sane(
        path, policy="repair", quarantine_path=str(tmp_path / "q.jsonl")
    )
    assert res.report.rows_repaired == 2
    assert res.report.rows_quarantined == 1  # the ragged row
    assert res.y[11] == 2  # 1.5 clamped via np.round (half-to-even)
    # imputed cell = finite column mean over non-quarantined rows
    want = np.mean(
        np.concatenate([X[:5, 0], X[6:30, 0], X[31:, 0]]).astype(np.float32)
    )
    assert res.X[5, 0] == pytest.approx(want, rel=1e-5)
    assert np.isfinite(res.X).all()


def test_repair_imputes_every_bad_cell_in_a_row(tmp_path):
    """Regression: a row with several non-finite feature cells must leave
    repair fully finite — imputing only the first reported cell would let
    the survivor NaN poison the detector statistics downstream."""
    X, y = toy(n=30, f=4, classes=3, seed=13)
    path = tmp_path / "d.csv"
    with open(path, "w") as fh:
        fh.write("0,1,2,3,target\n")
        for i in range(len(y)):
            row = [repr(float(v)) for v in X[i]]
            if i == 6:
                row[0] = "nan"
                row[2] = "inf"
            fh.write(",".join(row) + f",{int(y[i])}\n")
    res = load_csv_sane(str(path), policy="repair")
    assert res.report.rows_repaired == 1 and res.report.rows_quarantined == 0
    assert np.isfinite(res.X).all()


def test_repair_clamp_uses_np_round(tmp_path):
    # pin the clamp semantics: np.round (banker's rounding), 1.5 -> 2
    X, y = toy(n=20, seed=3)
    path = write_csv(tmp_path / "d.csv", X, y, {4: "bad_label"})
    res = load_csv_sane(path, policy="repair")
    assert res.y[4] == round(1.5)  # python round == np.round here (2)


def test_all_rows_bad_raises(tmp_path):
    p = tmp_path / "all.csv"
    p.write_text("0,target\nx,0\ny,1\n")
    with pytest.raises(StreamContractError, match="all 2 data rows"):
        load_csv_sane(str(p), policy="quarantine")


def test_unknown_policy_fails_loudly(tmp_path):
    X, y = toy(n=10)
    path = write_csv(tmp_path / "d.csv", X, y)
    with pytest.raises(ValueError, match="unknown data_policy"):
        load_csv_sane(path, policy="lenient")
    with pytest.raises(ValueError, match="unknown data_policy"):
        load_stream(path, data_policy="lenient")


def test_clean_stream_identical_under_every_policy(tmp_path):
    X, y = toy(seed=4)
    path = write_csv(tmp_path / "c.csv", X, y)
    ref = load_stream(path, mult_data=2, seed=1)  # legacy trusting load
    for policy in POLICIES:
        s = load_stream(path, mult_data=2, seed=1, data_policy=policy)
        assert s.quarantine is None and not s.has_masked_rows
        np.testing.assert_array_equal(s.base_X, ref.base_X)
        np.testing.assert_array_equal(s.src, ref.src)


# --- sidecar torn-tail contract ---------------------------------------------


def test_quarantine_sidecar_torn_tail(tmp_path):
    qp = str(tmp_path / "q.jsonl")
    w = QuarantineWriter(qp, "quarantine")
    for r in range(3):
        w.append("f.csv", RowIssue(r, 0, "non-numeric cell 'x'"), ["0", "t"])
    w.close()
    with open(qp, "a") as fh:
        fh.write('{"v": 1, "file": "f.csv", "ro')  # torn mid-append
    assert [r["row"] for r in read_quarantine(qp, allow_partial_tail=True)] \
        == [0, 1, 2]
    with pytest.raises(ValueError, match="not JSON"):
        read_quarantine(qp)


# --- doctor CLI -------------------------------------------------------------


def test_doctor_exit_codes(tmp_path, capsys):
    X, y = toy()
    clean = write_csv(tmp_path / "clean.csv", X, y)
    dirty = write_csv(
        tmp_path / "dirty.csv", X, y, {2: "ragged", 9: "non_numeric"}
    )
    with pytest.raises(SystemExit) as ei:
        doctor_main([clean])
    assert ei.value.code == 0
    with pytest.raises(SystemExit) as ei:
        doctor_main([dirty, "--max-report", "1"])
    assert ei.value.code == 1
    out = capsys.readouterr().out
    assert "2 of" in out and "data row 2" in out and "1 more" in out
    with pytest.raises(SystemExit) as ei:
        doctor_main(["synth:rialto,seed=0"])
    assert ei.value.code == 0  # synth specs have nothing to validate


def test_doctor_unreadable_input_exits_2(tmp_path, capsys):
    with pytest.raises(SystemExit) as ei:
        doctor_main([str(tmp_path / "missing.csv")])
    assert ei.value.code == 2  # environment error, not "dirty data"


def test_repair_run_writes_sidecar_for_unrepairable_rows(tmp_path):
    """data_policy='repair' must leave the per-row sidecar evidence for
    the rows it quarantined (not just the ones it fixed)."""
    X, y = toy(n=120, f=4, classes=3, seed=12)
    dirty = write_csv(tmp_path / "d.csv", X, y, {9: "ragged"})
    tdir = str(tmp_path / "tele")
    cfg = RunConfig(
        dataset=dirty, mult_data=1, partitions=2, per_batch=20,
        model="centroid", results_csv="", data_policy="repair",
        telemetry_dir=tdir,
    )
    from distributed_drift_detection_tpu.telemetry.events import read_events

    res = run(cfg)
    (q,) = [
        e
        for e in read_events(res.telemetry_path)
        if e["type"] == "rows_quarantined"
    ]
    recs = read_quarantine(q["sidecar"])
    assert [r["row"] for r in recs] == [9]
    assert recs[0]["policy"] == "repair"


def test_default_policy_digest_unchanged():
    """The default data policy must not perturb config digests: heal
    diffs new digests against registries recorded before the policy
    existed, and a schema change would re-run whole completed sweeps."""
    from distributed_drift_detection_tpu.config import (
        telemetry_config_payload,
    )

    cfg = RunConfig()
    assert "data_policy" not in telemetry_config_payload(cfg)
    assert (
        telemetry_config_payload(replace(cfg, data_policy="quarantine"))[
            "data_policy"
        ]
        == "quarantine"
    )


def test_scan_csv_reports_all_kinds(tmp_path):
    X, y = toy()
    path = write_csv(
        tmp_path / "d.csv", X, y,
        {1: "non_numeric", 2: "ragged", 3: "bad_label", 4: "nan_label"},
    )
    issues, n = scan_csv(path)
    assert n == len(y)
    reasons = {i.row: i.reason for i in issues}
    assert "non-numeric" in reasons[1]
    assert "ragged" in reasons[2]
    assert "non-integral" in reasons[3]
    assert "non-finite label" in reasons[4]


# --- loader satellite fixes -------------------------------------------------


def test_load_csv_names_missing_target_column(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("a,b\n1,2\n")
    with pytest.raises(ValueError, match="columns found.*'a', 'b'"):
        load_csv(str(p))


def test_load_csv_raises_when_both_parsers_disagree_with_header(tmp_path):
    # header names 4 columns, every data row has 3: the native parser
    # refuses (or returns 3 columns) and NumPy parses 3 — a silent
    # np.loadtxt fallback would previously have mis-assigned columns.
    p = tmp_path / "w.csv"
    p.write_text("0,1,2,target\n" + "1.0,2.0,0\n" * 5)
    with pytest.raises(ValueError, match="both parsers disagree|data rows have 3"):
        load_csv(str(p))


def test_synthesize_constant_column_no_nan():
    """Regression: a zero-variance feature column must standardize to 0,
    not 0/0 = NaN for the whole stream."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(60, 3)).astype(np.float32)
    X[:, 1] = 2.5  # constant column
    y = rng.integers(0, 3, 60).astype(np.int64)
    s = synthesize_stream(X, y, mult_data=2, seed=0)
    assert np.isfinite(s.base_X).all()
    assert (s.base_X[:, 1] == 0).all()
    s2 = synthesize_stream(X, y, mult_data=0.5, seed=0)
    assert np.isfinite(s2.X).all()


# --- guard plane: mask folds into the stripe validity -----------------------


def test_stripe_folds_row_mask_into_validity():
    rng = np.random.default_rng(3)
    n = 103
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = rng.integers(0, 4, n).astype(np.int64)
    ok = np.ones(n, bool)
    ok[[0, 50, 102]] = False
    X[0] = np.nan  # dirty content must never cross the stripe
    s = synthesize_stream(X, y, mult_data=1, seed=0, row_ok=ok)
    assert s.src is not None and s.has_masked_rows
    b = stripe_partitions(s, 4, 10)
    valid = np.asarray(b.valid)
    assert valid.sum() == n - 3
    assert np.isfinite(np.asarray(b.X)).all()
    # masked slots carry the padding fill exactly
    assert (np.asarray(b.X)[~valid] == 0).all()
    assert (np.asarray(b.y)[~valid] == 0).all()


def test_packed_striper_refuses_masked_streams():
    X, y = toy(n=40)
    ok = np.ones(40, bool)
    ok[5] = False
    s = synthesize_stream(
        X.astype(np.float32), y, mult_data=2, seed=0, row_ok=ok
    )
    with pytest.raises(ValueError, match="quarantine-masked"):
        stripe_partitions_packed(s, 4, 10)


def test_mask_rows_canonicalization_is_shared():
    X = np.arange(12, dtype=np.float32).reshape(4, 3)
    y = np.array([3, 1, 2, 1])
    ok = np.array([True, False, True, True])
    Xm, ym = mask_rows(X, y, ok)
    assert (Xm[1] == 0).all() and ym[1] == 1  # smallest valid label
    with pytest.raises(ValueError, match="no valid rows"):
        mask_rows(X, y, np.zeros(4, bool))


# --- the headline acceptance ------------------------------------------------


def _flags_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            getattr(a, name), getattr(b, name), err_msg=name
        )


@pytest.mark.parametrize("mult", [1, 2])
def test_quarantine_flags_bit_identical_to_clean_masked(tmp_path, mult):
    """k corrupted rows under data_policy='quarantine' → drift flags
    bit-identical to the clean stream with those k rows masked (the
    engine-level guard plane makes them padding)."""
    X, y = toy(n=400, f=6, classes=4, seed=7)
    bad = {17: "nan_cell", 60: "ragged", 123: "bad_label", 250: "nan_cell",
           399: "ragged"}
    dirty = write_csv(tmp_path / "dirty.csv", X, y, bad)
    cfg = RunConfig(
        dataset=dirty, mult_data=mult, partitions=4, per_batch=10,
        model="centroid", results_csv="", seed=3,
        data_policy="quarantine",
        quarantine_path=str(tmp_path / "q.jsonl"),
    )
    res_q = run(cfg)
    assert (np.asarray(res_q.flags.change_global) >= 0).any()

    mask = np.ones(len(y), bool)
    mask[list(bad)] = False
    clean = synthesize_stream(
        X.astype(np.float32), y, mult_data=mult, seed=3, row_ok=mask
    )
    res_c = run(replace(cfg, data_policy="strict"), stream=clean)
    _flags_equal(res_q.flags, res_c.flags)
    np.testing.assert_array_equal(res_q.drift_vote, res_c.drift_vote)


def test_property_random_masks_quarantine_equals_clean_masked():
    """Seeded property sweep: for random streams + random masks, the
    masked one-shot run equals the chunked run fed the same mask, and
    both treat masked rows as padding (flags independent of masked-row
    content)."""
    from distributed_drift_detection_tpu.engine import ChunkedDetector
    from distributed_drift_detection_tpu.io import chunk_stream_arrays
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    for seed in range(3):
        rng = np.random.default_rng(100 + seed)
        n = int(rng.integers(200, 400))
        X, y = toy(n=n, f=5, classes=3, seed=seed)
        mask = rng.random(n) > 0.05  # ~5% masked
        if not mask.any():
            mask[0] = True
        X_dirty = X.copy()
        X_dirty[~mask] = np.nan  # poison masked rows' content
        cfg = RunConfig(
            partitions=4, per_batch=20, model="centroid",
            results_csv="", seed=seed, window=1,
        )
        s_clean = synthesize_stream(
            X.astype(np.float32), y, mult_data=1, seed=seed, row_ok=mask
        )
        s_dirty = synthesize_stream(
            X_dirty.astype(np.float32), y, mult_data=1, seed=seed,
            row_ok=mask,
        )
        res_a = run(cfg, stream=s_clean)
        res_b = run(cfg, stream=s_dirty)
        _flags_equal(res_a.flags, res_b.flags)

        det = ChunkedDetector(
            build_model(
                "centroid",
                ModelSpec(s_clean.num_features, s_clean.num_classes), cfg,
            ),
            cfg.ddm, partitions=4, seed=seed, validate=True,
        )
        got = det.run(chunk_stream_arrays(
            s_dirty.X, s_dirty.y, 4, 20, chunk_batches=3,
            shuffle_seed=host_shuffle_seed(cfg), row_valid=s_dirty.row_ok,
        ))
        ref = np.asarray(res_a.flags.change_global)
        w = ref.shape[1]
        np.testing.assert_array_equal(got.change_global[:, :w], ref)
        assert np.all(got.change_global[:, w:] == -1)


# --- chunked validate wiring (satellite) ------------------------------------


def test_chunked_validate_catches_corrupted_index_plane():
    from distributed_drift_detection_tpu.engine import ChunkedDetector
    from distributed_drift_detection_tpu.io import chunk_stream_arrays
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    X, y = toy(n=300, f=5, classes=3, seed=1)
    cfg = RunConfig(partitions=4, per_batch=20, model="centroid", seed=1)
    s = synthesize_stream(X.astype(np.float32), y, mult_data=1, seed=1)

    def corrupted_chunks():
        for chunk in chunk_stream_arrays(
            s.X, s.y, 4, 20, chunk_batches=3,
            shuffle_seed=host_shuffle_seed(cfg),
        ):
            yield chunk._replace(rows=chunk.rows + 10_000_000)

    det = ChunkedDetector(
        build_model("centroid", ModelSpec(s.num_features, s.num_classes), cfg),
        cfg.ddm, partitions=4, seed=1, validate=True,
    )
    with pytest.raises(ValueError, match="num_rows"):
        det.run(corrupted_chunks())
    # and the same stream un-corrupted passes the audit silently
    det2 = ChunkedDetector(
        build_model("centroid", ModelSpec(s.num_features, s.num_classes), cfg),
        cfg.ddm, partitions=4, seed=1, validate=True,
    )
    det2.run(chunk_stream_arrays(
        s.X, s.y, 4, 20, chunk_batches=3,
        shuffle_seed=host_shuffle_seed(cfg),
    ))


# --- fault kinds ------------------------------------------------------------


def test_corrupt_lines_deterministic_and_distinct():
    base = [f"{i}.0,{i}.5,{i % 3}" for i in range(30)]
    a, b = list(base), list(base)
    hits_a = faults.corrupt_lines(a, "nan_cell", rows=5, seed=9)
    hits_b = faults.corrupt_lines(b, "nan_cell", rows=5, seed=9)
    assert hits_a == hits_b and a == b  # deterministic
    assert len({r for r, _ in hits_a}) == 5  # distinct rows
    assert sum("nan" in ln for ln in a) == 5
    c = list(base)
    faults.corrupt_lines(c, "ragged_row", rows=2, seed=0)
    assert sum(ln.count(",") == 1 for ln in c) == 2
    d = list(base)
    hits = faults.corrupt_lines(d, "bad_label", rows=2, seed=0, label_col=2)
    for r, col in hits:
        assert col == 2 and d[r].endswith(".5")
    with pytest.raises(ValueError, match="unknown corruption kind"):
        faults.corrupt_lines(list(base), "raise")


def test_stream_load_site_injects_through_loader(tmp_path):
    X, y = toy(n=60, seed=5)
    path = write_csv(tmp_path / "c.csv", X, y)
    faults.arm("stream.load", kind="nan_cell", times=3, seed=5)
    with pytest.raises(StreamContractError):
        load_csv_sane(path, policy="strict")
    qp = str(tmp_path / "q.jsonl")
    res = load_csv_sane(path, policy="quarantine", quarantine_path=qp)
    assert res.report.rows_quarantined == 3
    # deterministic: a second load corrupts the same rows
    res2 = load_csv_sane(
        path, policy="quarantine", quarantine_path=str(tmp_path / "q2.jsonl")
    )
    np.testing.assert_array_equal(res.row_ok, res2.row_ok)
    faults.disarm_all()
    assert load_csv_sane(path, policy="strict").row_ok is None


def test_stream_load_env_arming(tmp_path):
    X, y = toy(n=40, seed=6)
    path = write_csv(tmp_path / "c.csv", X, y)
    faults.arm_from_env("stream.load:kind=ragged_row,times=2,seed=1")
    res = load_csv_sane(
        path, policy="quarantine", quarantine_path=str(tmp_path / "q.jsonl")
    )
    assert res.report.rows_quarantined == 2
    assert all("ragged" in i.reason for i in res.report.issues)


# --- telemetry + end-to-end wiring ------------------------------------------


def test_run_emits_rows_quarantined_event_and_counter(tmp_path):
    from distributed_drift_detection_tpu.telemetry.events import read_events
    from distributed_drift_detection_tpu.telemetry.report import render_report

    X, y = toy(n=200, f=5, classes=4, seed=8)
    dirty = write_csv(
        tmp_path / "dirty.csv", X, y, {4: "nan_cell", 77: "ragged"}
    )
    tdir = str(tmp_path / "tele")
    cfg = RunConfig(
        dataset=dirty, mult_data=1, partitions=2, per_batch=25,
        model="centroid", results_csv="", seed=0,
        data_policy="quarantine", telemetry_dir=tdir,
    )
    res = run(cfg)
    events = read_events(res.telemetry_path)
    (q,) = [e for e in events if e["type"] == "rows_quarantined"]
    assert q["rows"] == 2 and q["policy"] == "quarantine"
    # per-run sidecar, named after the run log: appended records stay
    # attributable when the same dirty stream runs repeatedly
    assert q["sidecar"] == (
        os.path.splitext(res.telemetry_path)[0] + ".quarantine.jsonl"
    )
    assert len(read_quarantine(q["sidecar"])) == 2
    # a second run of the same config gets its OWN sidecar
    res2 = run(cfg)
    (q2,) = [
        e
        for e in read_events(res2.telemetry_path)
        if e["type"] == "rows_quarantined"
    ]
    assert q2["sidecar"] != q["sidecar"]
    assert len(read_quarantine(q["sidecar"])) == 2  # first is untouched
    # the sidecars never shadow the run logs in newest-run resolution
    from distributed_drift_detection_tpu.telemetry.registry import (
        newest_run_log,
    )

    assert newest_run_log(tdir) == res2.telemetry_path
    out = render_report(events)
    assert "quarantine 2 row(s) masked out" in out
    metrics = json.load(open(os.path.splitext(res.telemetry_path)[0]
                             + ".metrics.json"))
    points = {
        m["name"]: m["points"] for m in metrics["metrics"]
    } if isinstance(metrics, dict) and "metrics" in metrics else {}
    # counter export format is checked loosely: the name must appear
    assert "ingest_quarantined_total" in json.dumps(metrics)


def test_clean_run_emits_no_quarantine_trace(tmp_path):
    from distributed_drift_detection_tpu.telemetry.events import read_events

    X, y = toy(n=100, f=4, classes=4, seed=9)
    clean = write_csv(tmp_path / "clean.csv", X, y)
    cfg = RunConfig(
        dataset=clean, mult_data=1, partitions=2, per_batch=25,
        model="centroid", results_csv="", seed=0,
        data_policy="quarantine", telemetry_dir=str(tmp_path / "tele"),
    )
    import glob

    res = run(cfg)
    events = read_events(res.telemetry_path)
    assert not [e for e in events if e["type"] == "rows_quarantined"]
    assert not os.path.exists(resolve_quarantine_path(cfg))
    assert not glob.glob(
        os.path.join(cfg.telemetry_dir, "*.quarantine.jsonl")
    )


def test_strict_default_run_fails_loudly_on_dirty_csv(tmp_path):
    X, y = toy(n=100, f=4, classes=4, seed=10)
    dirty = write_csv(tmp_path / "dirty.csv", X, y, {13: "non_numeric"})
    cfg = RunConfig(
        dataset=dirty, mult_data=1, partitions=2, per_batch=25,
        model="centroid", results_csv="",
    )
    with pytest.raises(StreamContractError, match="data row 13"):
        run(cfg)


def test_validate_stream_audit(tmp_path):
    from distributed_drift_detection_tpu.utils.validate import validate_stream

    X, y = toy(n=60, f=4, classes=3, seed=11)
    s = synthesize_stream(X.astype(np.float32), y, mult_data=2, seed=0)
    validate_stream(s)  # clean passes
    s.base_X[3, 1] = np.inf
    with pytest.raises(ValueError, match="non-finite feature"):
        validate_stream(s)
    # the same corruption on a *masked* row is exempt by definition
    ok = np.ones(len(s.base_y), bool)
    ok[3] = False
    s.base_ok = ok
    validate_stream(s)


def test_grid_config_key_segments_data_policy():
    from distributed_drift_detection_tpu.harness.grid import _config_key

    cfg = RunConfig(model="centroid")
    assert "-dp" not in _config_key(cfg)  # default stays unsegmented
    assert _config_key(replace(cfg, data_policy="quarantine")).endswith(
        "-dpquarantine"
    )
    with pytest.raises(ValueError, match="unknown data_policy"):
        _config_key(replace(cfg, data_policy="nope"))


# --- csv_chunks policy (streaming reader) -----------------------------------


def test_csv_chunks_strict_and_quarantine(tmp_path):
    from distributed_drift_detection_tpu.io import (
        chunk_stream_arrays,
        csv_chunks,
    )

    rng = np.random.default_rng(5)
    n, f = 537, 4
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = rng.integers(0, 7, n).astype(np.int32)
    bad = [3, 99, 300, 536]
    path = tmp_path / "s.csv"
    with open(path, "w") as fh:
        fh.write("f0,f1,target,f2,f3\n")
        for i in range(n):
            row = [repr(float(v)) for v in X[i, :2]] + [str(int(y[i]))] + [
                repr(float(v)) for v in X[i, 2:]
            ]
            line = ",".join(row)
            if i in bad:
                line = "x," + line.split(",", 1)[1]
            fh.write(line + "\n")

    with pytest.raises(StreamContractError, match="data row 3"):
        list(csv_chunks(str(path), 4, 25, 2, data_policy="strict",
                        block_bytes=777))
    # repair streams block-wise since r10 (running-mean imputation —
    # serve-admission semantics; full parity pins live in
    # tests/test_ingest_pipeline.py): a non-numeric FEATURE cell is
    # repairable, so nothing lands in the sidecar here.
    qp_r = str(tmp_path / "qr.jsonl")
    repaired = list(csv_chunks(
        str(path), 4, 25, 2, data_policy="repair", quarantine_path=qp_r,
        block_bytes=777,
    ))
    assert sum(int(c.valid.sum()) for c in repaired) == n  # no row dropped
    assert not os.path.exists(qp_r)

    qp = str(tmp_path / "q.jsonl")
    got = list(csv_chunks(
        str(path), 4, 25, 2, shuffle_seed=9, data_policy="quarantine",
        quarantine_path=qp, block_bytes=777,
    ))
    assert sorted(r["row"] for r in read_quarantine(qp)) == bad
    ok = np.ones(n, bool)
    ok[bad] = False
    want = list(chunk_stream_arrays(
        np.where(ok[:, None], X, 0.0), np.where(ok, y, 0), 4, 25, 2,
        shuffle_seed=9, row_valid=ok,
    ))
    assert len(want) == len(got)
    for a, c in zip(want, got):
        for la, lb in zip(a, c):
            np.testing.assert_array_equal(la, lb)


def test_csv_chunks_all_rows_dirty_raises(tmp_path):
    """A stream that quarantined EVERY row must not read as a successful
    (empty) run — matching the whole-file loader's degenerate-case
    guard."""
    from distributed_drift_detection_tpu.io import csv_chunks

    p = tmp_path / "all.csv"
    p.write_text("0,target\n" + "x,0\n" * 10)
    with pytest.raises(StreamContractError, match="all 10 data rows"):
        list(csv_chunks(
            str(p), 1, 2, 1, data_policy="quarantine",
            quarantine_path=str(tmp_path / "q.jsonl"),
        ))
