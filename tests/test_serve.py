"""serve/ subsystem: admission batching, served-vs-batch flag parity,
verdict publication, kill-and-resume, and the graceful drain.

The headline acceptance (ISSUE 7): the same stream pushed through the
serving path produces drift flags **bit-identical** to a one-shot
``api.run`` on that stream — clean and quarantine-policy dirty variants,
across seeds, including a short padded final microbatch — and a daemon
killed mid-serve resumes from its checkpoint with identical downstream
flags.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distributed_drift_detection_tpu import RunConfig, run
from distributed_drift_detection_tpu.config import ServeParams
from distributed_drift_detection_tpu.io import planted_prototypes
from distributed_drift_detection_tpu.io.sanitize import (
    RunningColumnStats,
    read_quarantine,
)
from distributed_drift_detection_tpu.io.stream import StreamData, stripe_chunk
from distributed_drift_detection_tpu.resilience import faults
from distributed_drift_detection_tpu.serve import (
    MicroBatcher,
    ServeRunner,
    read_verdicts,
)
from distributed_drift_detection_tpu.serve.loadgen import (
    apply_dirty,
    format_lines,
    run_loadgen,
)
from distributed_drift_detection_tpu.telemetry import registry


def _cfg(seed, telemetry_dir=None, **kw):
    kw.setdefault("data_policy", "quarantine")
    return RunConfig(
        partitions=4,
        per_batch=50,
        model="centroid",
        shuffle_batches=True,
        results_csv="",
        seed=seed,
        window=1,
        telemetry_dir=telemetry_dir,
        **kw,
    )


def _params(stream, **kw):
    kw.setdefault("port", None)
    kw.setdefault("chunk_batches", 2)
    kw.setdefault("linger_s", 0.05)
    return ServeParams(
        num_features=stream.num_features,
        num_classes=stream.num_classes,
        **kw,
    )


def _drive(runner, lines, block=150):
    """Synchronous in-process serve: admit → flush → drain. Returns the
    runner (its kept flags are the served result)."""
    for i in range(0, len(lines), block):
        runner.admission.admit_lines(lines[i : i + block])
    runner.batcher.flush()
    runner.request_stop()
    assert runner.serve_forever() == 0
    return runner


def _masked_batch_flags(stream, cfg, bad_rows=()):
    """One-shot api.run flags on the stream with ``bad_rows`` masked —
    the serving path's bit-parity reference."""
    ok = None
    if len(bad_rows):
        ok = np.ones(stream.num_rows, bool)
        ok[list(bad_rows)] = False
    ref_stream = StreamData(
        X=stream.X,
        y=stream.y,
        num_classes=stream.num_classes,
        dist_between_changes=stream.dist_between_changes,
        row_ok=ok,
    )
    return run(cfg, stream=ref_stream).flags


def _assert_flag_parity(got, ref):
    """Served flags == batch flags on every FlagRows leaf; extra served
    columns (grid padding beyond the one-shot width) must be sentinels."""
    w = np.asarray(ref.change_global).shape[1]
    for name in ref._fields:
        g = np.asarray(getattr(got, name))
        r = np.asarray(getattr(ref, name))
        np.testing.assert_array_equal(g[:, :w], r, err_msg=name)
    assert np.all(np.asarray(got.change_global)[:, w:] == -1)
    assert np.all(~np.asarray(got.forced_retrain)[:, w:])


def _table_from_verdicts(records, partitions):
    """Reconstruct the ``change_global`` table from verdict records —
    the wire-format's parity surface."""
    total = max(r["flag_base"] + r["cols"] for r in records)
    cg = np.full((partitions, total), -1, np.int64)
    for r in records:
        for p, b, pos in r["changes"]:
            cg[p, r["flag_base"] + b] = pos
    return cg


# --- served-vs-batch parity (the headline acceptance) ----------------------


@pytest.mark.parametrize("seed", [3, 11])
def test_served_vs_batch_parity_clean(seed, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # 4*50*2 = 400 rows/chunk; 1440 rows → 3 full chunks + a SHORT final
    # chunk (240 rows) padded through the validity plane.
    stream = planted_prototypes(seed, concepts=3, rows_per_concept=480,
                                features=7)
    cfg = _cfg(seed)
    ref = run(cfg, stream=stream).flags
    assert (np.asarray(ref.change_global) >= 0).any()

    runner = ServeRunner(cfg, _params(stream), keep_flags=True)
    runner.start()
    _drive(runner, format_lines(stream.X, stream.y))
    assert runner._published == 4  # multi-chunk, short tail included
    _assert_flag_parity(runner.flags(), ref)


@pytest.mark.parametrize("seed", [5, 9])
def test_served_vs_batch_parity_dirty_quarantine(seed, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(seed, concepts=3, rows_per_concept=440,
                                features=6)
    cfg = _cfg(seed, telemetry_dir=str(tmp_path / "tele"))
    lines = format_lines(stream.X, stream.y)
    corrupted = apply_dirty(lines, f"nan_cell:6:{seed}")
    corrupted += apply_dirty(lines, f"bad_label:3:{seed + 1}")
    bad_rows = sorted({r for r, _ in corrupted})
    assert bad_rows

    runner = ServeRunner(cfg, _params(stream), keep_flags=True)
    banner = runner.start()
    _drive(runner, lines)
    ref = _masked_batch_flags(stream, _cfg(seed), bad_rows)
    assert (np.asarray(ref.change_global) >= 0).any()
    _assert_flag_parity(runner.flags(), ref)

    # the quarantine machinery ran unchanged: sidecar rows + counter
    assert runner.admission.rows_quarantined == len(bad_rows)
    sidecar = os.path.splitext(banner["run_log"])[0] + ".quarantine.jsonl"
    recs = read_quarantine(sidecar)
    assert {r["row"] for r in recs} == set(bad_rows)


def test_padding_parity_short_flush_equals_masked_grid():
    """A short (lingered/flushed) microbatch is bit-identical to a full
    grid carrying the same rows with the tail masked out."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(130, 5)).astype(np.float32)
    y = (np.arange(130) % 3).astype(np.int32)
    short = MicroBatcher(2, 25, 4, shuffle_seed=77, linger_s=10.0)
    short.push(X, y)
    short.flush()
    a = short.get(1.0)
    assert a is not None and a.meta["short"] and a.meta["rows"] == 130

    # the same 130 rows striped as a full grid with the tail invalid
    pad = 2 * 25 * 4 - 130
    Xf = np.concatenate([X, rng.normal(size=(pad, 5)).astype(np.float32)])
    yf = np.concatenate([y, np.ones(pad, np.int32)])
    ok = np.concatenate([np.ones(130, bool), np.zeros(pad, bool)])
    b = stripe_chunk(Xf, yf, 0, 2, 25, 4, 77, row_valid=ok)
    for name in a.chunk._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a.chunk, name)),
            np.asarray(getattr(b, name)),
            err_msg=name,
        )


def test_linger_deadline_flushes_partial():
    mb = MicroBatcher(2, 10, 2, linger_s=0.05)
    mb.push(np.zeros((7, 3), np.float32), np.zeros(7, np.int32))
    t0 = time.monotonic()
    item = mb.get(2.0)
    assert item is not None and item.meta["rows"] == 7 and item.meta["short"]
    assert time.monotonic() - t0 < 1.0  # sealed by linger, not caller flush
    # positions advance by the full grid span (grid-slot semantics)
    assert mb.start_row == 2 * 10 * 2


def test_drain_flushes_partial_batch(tmp_path, monkeypatch):
    """request_stop (the SIGTERM path) must flush the lingering partial
    microbatch before completing — no admitted row is ever dropped."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(1, concepts=2, rows_per_concept=90, features=5)
    cfg = _cfg(1, telemetry_dir=str(tmp_path / "t"))
    runner = ServeRunner(
        cfg, _params(stream, linger_s=60.0), keep_flags=True
    )
    runner.start()
    t = threading.Thread(target=runner.serve_forever)
    t.start()
    runner.admission.admit_lines(format_lines(stream.X, stream.y))
    runner.request_stop()  # no FLUSH line: the drain itself must seal
    t.join(timeout=60)
    assert not t.is_alive()
    assert runner._rows_published == stream.num_rows
    rec = list(registry.runs(str(tmp_path / "t")).values())
    assert [r["status"] for r in rec] == ["completed"]
    assert rec[0]["kind"] == "serve"


# --- kill-and-resume (serve.flush fault + checkpoint) ----------------------


def test_kill_and_resume_bit_identical(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # 1200 rows = exactly 3 full [4,2,50] chunks — the crash lands on a
    # chunk boundary, so the replayed stream stays position-contiguous.
    stream = planted_prototypes(4, concepts=3, rows_per_concept=400,
                                features=7)
    cfg = _cfg(4, telemetry_dir=str(tmp_path / "tele"))
    ckpt = str(tmp_path / "serve.ckpt")
    lines = format_lines(stream.X, stream.y)
    ref = run(_cfg(4), stream=stream).flags

    # first daemon: dies at the 3rd verdict publication (state for chunk 2
    # advanced, verdict/checkpoint not yet written — the worst-case crash)
    faults.arm("serve.flush", at=3)
    try:
        r1 = ServeRunner(
            cfg, _params(stream, checkpoint=ckpt), keep_flags=True
        )
        r1.start()
        for i in range(0, len(lines), 150):
            r1.admission.admit_lines(lines[i : i + 150])
        r1.batcher.flush()
        r1.request_stop()
        with pytest.raises(faults.InjectedFault):
            r1.serve_forever()
    finally:
        faults.disarm_all()
    assert r1._published == 2 and os.path.exists(ckpt)
    runs = registry.runs(str(tmp_path / "tele"))
    assert [r["status"] for r in runs.values()] == ["failed"]

    # resumed daemon: restores the carry + stream position, the client
    # replays from rows_admitted, downstream flags are bit-identical
    r2 = ServeRunner(cfg, _params(stream, checkpoint=ckpt), keep_flags=True)
    banner = r2.start()
    assert banner["resumed"] and r2.resumed_meta["chunk_index"] == 2
    replay_from = int(r2.resumed_meta["rows_admitted"])
    assert replay_from == 800
    _drive(r2, lines[replay_from:])
    flags1, flags2 = r1.flags(), r2.flags()
    combined = type(flags1)(
        *(
            np.concatenate([np.asarray(a), np.asarray(b)], axis=1)
            for a, b in zip(flags1, flags2)
        )
    )
    _assert_flag_parity(combined, ref)
    runs = registry.runs(str(tmp_path / "tele"))
    assert sorted(r["status"] for r in runs.values()) == [
        "completed",
        "failed",
    ]


def test_serve_flush_torn_write_tears_sidecar(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(2, concepts=2, rows_per_concept=120,
                                features=5)
    cfg = _cfg(2, telemetry_dir=str(tmp_path / "t"))
    faults.arm("serve.flush", at=1, kind="torn_write")
    try:
        runner = ServeRunner(cfg, _params(stream), keep_flags=True)
        banner = runner.start()
        for i in range(0, stream.num_rows, 100):
            runner.admission.admit_lines(
                format_lines(stream.X[i : i + 100], stream.y[i : i + 100])
            )
        runner.batcher.flush()
        runner.request_stop()
        with pytest.raises(faults.InjectedFault):
            runner.serve_forever()
    finally:
        faults.disarm_all()
    # the torn trailing line is tolerated, complete records parse
    assert read_verdicts(banner["verdicts"]) == []
    with open(banner["verdicts"]) as fh:
        assert fh.read()  # the torn prefix is really there


def test_ingress_fault_poisons_daemon(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(6, concepts=2, rows_per_concept=100,
                                features=5)
    cfg = _cfg(6, telemetry_dir=str(tmp_path / "t"))
    runner = ServeRunner(cfg, _params(stream))
    runner.start()
    loop_exc = []

    def _loop():
        try:
            runner.serve_forever()
        except BaseException as e:
            loop_exc.append(e)

    t = threading.Thread(target=_loop)
    t.start()
    faults.arm("serve.ingress", at=2)
    try:
        runner.admission.admit_lines(format_lines(stream.X[:50], stream.y[:50]))
        with pytest.raises(faults.InjectedFault) as ei:
            runner.admission.admit_lines(
                format_lines(stream.X[50:], stream.y[50:])
            )
        runner.batcher.poison(ei.value)  # what the socket handler does
    finally:
        faults.disarm_all()
    t.join(timeout=60)
    assert not t.is_alive()
    assert loop_exc and isinstance(loop_exc[0], faults.InjectedFault)
    runs = registry.runs(str(tmp_path / "t"))
    assert [r["status"] for r in runs.values()] == ["failed"]


def test_ingress_corruption_kind_quarantines(tmp_path, monkeypatch):
    """An armed corruption kind on serve.ingress dirties live traffic;
    the admission policy quarantines it — no crash, flags still flow."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(8, concepts=2, rows_per_concept=120,
                                features=5)
    cfg = _cfg(8)
    runner = ServeRunner(cfg, _params(stream), keep_flags=True)
    runner.start()
    faults.arm("serve.ingress", at=1, times=2, kind="nan_cell", seed=5)
    try:
        _drive(runner, format_lines(stream.X, stream.y), block=120)
    finally:
        faults.disarm_all()
    assert runner.admission.rows_quarantined > 0
    assert runner._rows_published == stream.num_rows  # positions kept


# --- admission policies ----------------------------------------------------


def test_admission_strict_rejects_rows_not_daemon(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(3, concepts=2, rows_per_concept=100,
                                features=5)
    runner = ServeRunner(
        _cfg(3, data_policy="strict"), _params(stream), keep_flags=True
    )
    runner.start()
    lines = format_lines(stream.X, stream.y)
    bad = {r for r, _ in apply_dirty(lines, "nan_cell:4:2")}
    res = runner.admission.admit_lines(lines)
    assert "rejected 4 row(s)" in res["error"]
    assert res["admitted"] == len(lines) - len(bad)
    assert runner.admission.rows_rejected == len(bad)
    # rejected rows are gone (no positions), clean rows admitted
    assert runner.batcher.rows_admitted == len(lines) - len(bad)


def test_admission_repair_imputes_from_running_means(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stats = RunningColumnStats(3)
    stats.update(np.array([[1.0, 2.0, 0.0], [3.0, 6.0, 1.0]], np.float32))
    np.testing.assert_allclose(stats.means(), [2.0, 4.0, 0.5])

    stream = planted_prototypes(7, concepts=2, rows_per_concept=100,
                                features=4)
    runner = ServeRunner(
        _cfg(7, data_policy="repair"), _params(stream), keep_flags=True
    )
    runner.start()
    lines = format_lines(stream.X, stream.y)
    runner.admission.admit_lines(lines[:50])  # clean evidence first
    dirty = lines[50:60]
    nan_row = dirty[0].split(",")
    nan_row[1] = "nan"
    dirty[0] = ",".join(nan_row)  # repairable: imputed from running means
    dirty[1] = ",".join(dirty[1].split(",")[:-2])  # ragged: unrepairable
    res = runner.admission.admit_lines(dirty)
    assert res["admitted"] == 10  # ragged row kept positionally, masked
    assert runner.admission.rows_repaired == 1
    assert runner.admission.rows_quarantined == 1


def test_admission_json_rows_equal_csv_rows(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(9, concepts=2, rows_per_concept=150,
                                features=4)
    cfg = _cfg(9)
    a = ServeRunner(cfg, _params(stream), keep_flags=True)
    a.start()
    _drive(a, format_lines(stream.X, stream.y))
    b = ServeRunner(cfg, _params(stream), keep_flags=True)
    b.start()
    json_lines = [
        json.dumps({"x": [float(v) for v in row], "y": int(label)})
        for row, label in zip(stream.X, stream.y)
    ]
    _drive(b, json_lines)
    fa, fb = a.flags(), b.flags()
    for name in fa._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(fa, name)),
            np.asarray(getattr(fb, name)),
            err_msg=name,
        )


def test_admission_json_non_numeric_value_is_dirty_not_fatal(
    tmp_path, monkeypatch
):
    """A syntactically valid JSON row with a non-float value must flow
    through the contract scan as a dirty cell (quarantined), never crash
    admission — one malformed row must not kill the daemon."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(2, concepts=2, rows_per_concept=60,
                                features=4)
    runner = ServeRunner(_cfg(2), _params(stream), keep_flags=True)
    runner.start()
    lines = format_lines(stream.X, stream.y)
    lines[3] = json.dumps({"x": [1.0, "oops", 2.0, 3.0], "y": 1})
    lines[4] = json.dumps({"x": [1.0, None, 2.0, 3.0], "y": 0})
    res = runner.admission.admit_lines(lines)
    assert res["admitted"] == len(lines)  # kept positionally, masked
    assert runner.admission.rows_quarantined == 2


def test_admission_repair_label_rounding_respects_domain(
    tmp_path, monkeypatch
):
    """Under repair, a label that would ROUND outside 0..C-1 (1.6 → 2 at
    C=2) is an unrepairable violation — quarantined, never handed to the
    engine as an out-of-range index; one that rounds inside (0.6 → 1) is
    repaired."""
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(4, concepts=2, rows_per_concept=60,
                                features=4)
    runner = ServeRunner(
        _cfg(4, data_policy="repair"), _params(stream), keep_flags=True
    )
    runner.start()
    lines = format_lines(stream.X, stream.y)
    good = lines[2].split(",")
    good[-1] = "0.6"
    lines[2] = ",".join(good)
    bad = lines[3].split(",")
    bad[-1] = "1.6"
    lines[3] = ",".join(bad)
    runner.admission.admit_lines(lines)
    assert runner.admission.rows_repaired == 1
    assert runner.admission.rows_quarantined == 1


def test_reconcile_torn_tail(tmp_path):
    from distributed_drift_detection_tpu.serve.runner import (
        reconcile_torn_tail,
    )

    p = str(tmp_path / "v.verdicts.jsonl")
    whole = json.dumps(
        {"kind": "verdict", "rows_through": 10, "flag_base": 0, "cols": 1,
         "ts": 1.0, "detections": 0, "changes": []}
    )
    with open(p, "w") as fh:
        fh.write(whole + "\n" + whole[: len(whole) // 2])  # torn tail
    assert reconcile_torn_tail(p)
    assert len(read_verdicts(p, allow_partial_tail=False)) == 1
    assert not reconcile_torn_tail(p)  # clean file untouched


def test_admission_out_of_range_label_quarantined(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(1, concepts=2, rows_per_concept=60,
                                features=4)
    runner = ServeRunner(_cfg(1), _params(stream), keep_flags=True)
    runner.start()
    lines = format_lines(stream.X, stream.y)
    fields = lines[5].split(",")
    fields[-1] = "7"  # integral, finite — but outside 0..1
    lines[5] = ",".join(fields)
    runner.admission.admit_lines(lines)
    assert runner.admission.rows_quarantined == 1


# --- sidecar resolution (registry/watch fix) -------------------------------


def test_newest_run_log_skips_serve_sidecars(tmp_path):
    from distributed_drift_detection_tpu.telemetry.events import EventLog
    from distributed_drift_detection_tpu.telemetry.watch import resolve_log

    d = str(tmp_path)
    log = EventLog.open_run(d, name="serve")
    log.emit("run_started", run_id=log.run_id, config={})
    log.close()
    time.sleep(0.02)
    stem = os.path.splitext(log.path)[0]
    # live-service sidecars, strictly newer than the run log
    for suffix in (".verdicts.jsonl", ".heartbeat.jsonl", ".quarantine.jsonl"):
        with open(stem + suffix, "w") as fh:
            fh.write('{"kind": "verdict", "rows_through": 1}\n')
    assert registry.newest_run_log(d) == log.path
    assert resolve_log(d) == log.path


# --- the wire: socket ingress + loadgen + SIGTERM --------------------------


def test_socket_loadgen_latency_and_watch(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    stream = planted_prototypes(12, concepts=3, rows_per_concept=220,
                                features=6)
    cfg = _cfg(12, telemetry_dir=str(tmp_path / "tele"))
    runner = ServeRunner(cfg, _params(stream, port=0), keep_flags=True)
    banner = runner.start()
    t = threading.Thread(target=runner.serve_forever)
    t.start()
    lines = format_lines(stream.X, stream.y)
    rep = run_loadgen(
        banner["host"],
        banner["port"],
        lines,
        rate=0.0,
        verdicts=banner["verdicts"],
        timeout=120,
        stop=True,
    )
    t.join(timeout=120)
    assert not t.is_alive()
    assert rep["rows_covered"] == len(lines) and not rep["timeout"]
    assert rep["p50_ms"] is not None and rep["p99_ms"] >= rep["p50_ms"]
    _assert_flag_parity(runner.flags(), run(_cfg(12), stream=stream).flags)

    # the fleet CLIs work unchanged against the serving directory
    from distributed_drift_detection_tpu.telemetry.watch import watch

    assert registry.newest_run_log(str(tmp_path / "tele")) == banner["run_log"]
    assert watch(str(tmp_path / "tele"), once=True, out=lambda *_: None) == 0

    # verdict records reconstruct the flag table (the wire-format parity)
    cg = _table_from_verdicts(
        read_verdicts(banner["verdicts"]), cfg.partitions
    )
    np.testing.assert_array_equal(
        cg, np.asarray(runner.flags().change_global)
    )


def test_sigterm_drain_and_restart_resume(tmp_path):
    """The real daemon process: SIGTERM drains (exit 0, registry
    completed, checkpoint on disk); a restarted daemon resumes and the
    combined verdict stream reconstructs the batch run's flags."""
    stream = planted_prototypes(15, concepts=2, rows_per_concept=300,
                                features=5)
    ref = run(_cfg(15), stream=stream).flags
    tele = str(tmp_path / "tele")
    ckpt = str(tmp_path / "serve.ckpt")
    lines = format_lines(stream.X, stream.y)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # the daemon runs from tmp_path; make the checkout importable
        # whether or not the package is pip-installed
        "PYTHONPATH": repo_root
        + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    }
    argv = [
        sys.executable, "-m", "distributed_drift_detection_tpu", "serve",
        "--features", "5", "--classes", "2", "--partitions", "4",
        "--per-batch", "50", "--chunk-batches", "1", "--port", "0",
        "--seed", "15", "--telemetry-dir", tele, "--checkpoint", ckpt,
        "--linger-s", "0.1",
    ]

    def _run_daemon(send_lines, cover_through):
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, env=env, text=True, cwd=tmp_path
        )
        try:
            banner = json.loads(proc.stdout.readline())
            with socket.create_connection(
                (banner["host"], banner["port"]), timeout=10
            ) as sock:
                sock.sendall(("\n".join(send_lines) + "\nFLUSH\n").encode())
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                recs = read_verdicts(banner["verdicts"])
                if recs and recs[-1]["rows_through"] >= cover_through:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("verdicts never covered the replay")
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=120) == 0
            return banner
        finally:
            if proc.poll() is None:
                proc.kill()

    # split aligned to the [4,1,50] = 200-row chunk grid, so the resumed
    # stream stays position-contiguous with the batch reference
    half = 400
    b1 = _run_daemon(lines[:half], half)
    runs = registry.runs(tele)
    assert [r["status"] for r in runs.values()] == ["completed"]
    assert os.path.exists(ckpt)
    with np.load(ckpt) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
    assert meta["rows_admitted"] == half

    b2 = _run_daemon(lines[half:], len(lines))
    recs = read_verdicts(b1["verdicts"]) + read_verdicts(b2["verdicts"])
    cg = _table_from_verdicts(recs, 4)
    w = np.asarray(ref.change_global).shape[1]
    np.testing.assert_array_equal(cg[:, :w], np.asarray(ref.change_global))
    assert np.all(cg[:, w:] == -1)
    statuses = sorted(r["status"] for r in registry.runs(tele).values())
    assert statuses == ["completed", "completed"]
