"""Speculative window engine: exact parity with the sequential engine.

The window engine (``engine.window``) must commit *bit-identical* flags to
the batch-per-step scan (``engine.loop``) for deterministic-fit models with
host-side shuffling — speculation is an execution strategy, not a semantics
change. These tests drive both engines over planted-drift streams (including
partial and fully-empty tail batches) and diff every flag row.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_drift_detection_tpu import DDMParams
from distributed_drift_detection_tpu.engine import Batches, make_partition_runner
from distributed_drift_detection_tpu.engine.window import make_window_runner
from distributed_drift_detection_tpu.models import (
    ModelSpec,
    build_model,
    make_majority,
)
from distributed_drift_detection_tpu.ops import ddm_batch, ddm_init
from distributed_drift_detection_tpu.ops.ddm import ddm_window

from test_engine import planted_classification_stream, to_batches

REF = DDMParams()


# ---------------------------------------------------------------------------
# ops.ddm_window vs chained ops.ddm_batch
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_ddm_window_matches_chained_ddm_batch(seed):
    """With no reset in the window, ddm_window == ddm_batch applied W times
    with the state threaded through — per-batch flags for every batch up to
    (and including) the first changed one, and end state when none change."""
    rng = np.random.default_rng(seed)
    w_, b_ = 6, 25
    errs = (rng.random((w_, b_)) < 0.15).astype(np.float32)
    valid = rng.random((w_, b_)) < 0.95
    state0 = ddm_init()

    end, res = jax.jit(ddm_window)(state0, jnp.asarray(errs), jnp.asarray(valid), REF)

    st = state0
    first_changed = w_
    for k in range(w_):
        st, rb = ddm_batch(st, jnp.asarray(errs[k]), jnp.asarray(valid[k]), REF)
        if k <= first_changed:
            assert int(res.first_change[k]) == int(rb.first_change), k
            assert int(res.first_warning[k]) == int(rb.first_warning), k
        if first_changed == w_ and int(rb.first_change) >= 0:
            first_changed = k
    if first_changed == w_:  # no change anywhere → end states identical
        for a, b in zip(end, st):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


# ---------------------------------------------------------------------------
# engine.window vs engine.loop — exact flag parity
# ---------------------------------------------------------------------------


def _flags_to_array(flags):
    return np.stack([np.asarray(leaf) for leaf in flags], axis=0)


@pytest.mark.parametrize("window", [1, 3, 16, 64])
@pytest.mark.parametrize("model_name", ["majority", "centroid", "gnb", "linear"])
def test_window_runner_matches_sequential(window, model_name):
    """Deterministic-fit models, shuffle=False: every flag row identical for
    any window width (including W=1 and W > drift spacing)."""
    rng = np.random.default_rng(window * 31 + len(model_name))
    X, y = planted_classification_stream(
        rng, concepts=7, rows_per_concept=230, label_flip=0
    )
    per_batch = 50  # 230·7/50 → partial tail batch
    spec = ModelSpec(X.shape[1], int(y.max()) + 1)
    model = build_model(model_name, spec)
    batches = to_batches(X, y, per_batch)
    key = jax.random.key(9)

    seq = jax.jit(make_partition_runner(model, REF, shuffle=False))(batches, key)
    win = jax.jit(
        make_window_runner(model, REF, window=window, shuffle=False)
    )(batches, key)
    np.testing.assert_array_equal(_flags_to_array(win), _flags_to_array(seq))


@pytest.mark.parametrize(
    "window,rotations",
    [
        (3, 2), (3, 4), (3, 11),
        (16, 2), (16, 4),
        (64, 2), (64, 4),
        # deep-speculation × wide-window corners are the two heaviest
        # compiles in the fast tier (~45 s together); (3, 11) pins max
        # depth and (16|64, 2|4) pin each width, so only the combined
        # corners ride in the slow tier
        pytest.param(16, 11, marks=pytest.mark.slow),
        pytest.param(64, 11, marks=pytest.mark.slow),
    ],
)
def test_multi_rotation_speculation_matches_sequential(window, rotations):
    """Speculation depth > 1 (rotate-and-replay inside one step) commits
    bit-identical flags to the sequential engine for every (W, R) — the
    depth is an execution strategy, not a semantics change. W=64 spans
    several concepts, so one step genuinely commits multiple rotations."""
    rng = np.random.default_rng(rotations * 7 + window)
    X, y = planted_classification_stream(
        rng, concepts=7, rows_per_concept=230, label_flip=0
    )
    spec = ModelSpec(X.shape[1], int(y.max()) + 1)
    model = build_model("centroid", spec)
    batches = to_batches(X, y, 50)
    key = jax.random.key(9)

    seq = jax.jit(make_partition_runner(model, REF, shuffle=False))(batches, key)
    win = jax.jit(
        make_window_runner(
            model, REF, window=window, shuffle=False, rotations=rotations
        )
    )(batches, key)
    np.testing.assert_array_equal(_flags_to_array(win), _flags_to_array(seq))


def test_multi_rotation_rejects_bad_depth():
    with pytest.raises(ValueError, match="rotations"):
        make_window_runner(
            make_majority(ModelSpec(4, 2)), REF, window=4, rotations=0
        )


@pytest.mark.parametrize("rotations", [1, 4])
def test_window_runner_with_noise_and_forced_retrain(rotations):
    """Noisy labels + retrain_error_threshold: rotates from both DDM changes
    and forced retrains still commit identically (at any speculation depth)."""
    rng = np.random.default_rng(123)
    X, y = planted_classification_stream(
        rng, concepts=5, rows_per_concept=300, label_flip=0.05
    )
    spec = ModelSpec(X.shape[1], int(y.max()) + 1)
    model = make_majority(spec)
    batches = to_batches(X, y, 60)
    key = jax.random.key(4)
    kw = dict(shuffle=False, retrain_error_threshold=0.3)

    seq = jax.jit(make_partition_runner(model, REF, **kw))(batches, key)
    win = jax.jit(
        make_window_runner(model, REF, window=8, rotations=rotations, **kw)
    )(batches, key)
    np.testing.assert_array_equal(_flags_to_array(win), _flags_to_array(seq))


def test_window_runner_empty_tail_batches():
    """A stream shorter than the batch grid (fully-empty trailing batches)
    must not fire, rotate, or corrupt carried state."""
    rng = np.random.default_rng(5)
    X, y = planted_classification_stream(rng, concepts=3, rows_per_concept=90)
    per_batch = 40
    b = to_batches(X, y, per_batch)
    # Extend with 3 fully-empty batches.
    pad = Batches(
        X=jnp.zeros((3, per_batch, X.shape[1]), jnp.float32),
        y=jnp.zeros((3, per_batch), jnp.int32),
        rows=jnp.full((3, per_batch), -1, jnp.int32),
        valid=jnp.zeros((3, per_batch), bool),
    )
    batches = jax.tree.map(lambda a, p: jnp.concatenate([a, p]), b, pad)
    spec = ModelSpec(X.shape[1], 3)
    model = make_majority(spec)
    key = jax.random.key(0)

    seq = jax.jit(make_partition_runner(model, REF, shuffle=False))(batches, key)
    win = jax.jit(make_window_runner(model, REF, window=4, shuffle=False))(
        batches, key
    )
    np.testing.assert_array_equal(_flags_to_array(win), _flags_to_array(seq))
    assert np.all(np.asarray(win.change_global[-3:]) == -1)


def test_window_runner_vmap_lanes_are_independent():
    """Under vmap, partitions with different drift positions (hence different
    window-loop trip counts) each match their own solo run exactly."""
    rng = np.random.default_rng(11)
    p, per_batch = 4, 30
    spec = ModelSpec(8, 5)
    model = make_majority(spec)
    runner = make_window_runner(model, REF, window=8, shuffle=False)
    keys = jax.random.split(jax.random.key(2), p)

    raw = []
    for i in range(p):
        # Varying concept lengths → different change positions per lane.
        X, y = planted_classification_stream(
            rng, concepts=3 + i % 2, rows_per_concept=120 + 30 * i
        )
        raw.append(to_batches(X, y, per_batch))
    nb_target = max(bt.y.shape[0] for bt in raw)

    batch_list, solo = [], []
    for i, bt in enumerate(raw):
        pad_n = nb_target - bt.y.shape[0]
        padb = Batches(
            X=jnp.zeros((pad_n, per_batch, 8), jnp.float32),
            y=jnp.zeros((pad_n, per_batch), jnp.int32),
            rows=jnp.full((pad_n, per_batch), -1, jnp.int32),
            valid=jnp.zeros((pad_n, per_batch), bool),
        )
        bt = jax.tree.map(lambda a, q: jnp.concatenate([a, q]), bt, padb)
        batch_list.append(bt)
        solo.append(jax.jit(runner)(bt, keys[i]))

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batch_list)
    vflags = jax.jit(jax.vmap(runner))(stacked, keys)
    for i in range(p):
        np.testing.assert_array_equal(
            _flags_to_array(jax.tree.map(lambda x: x[i], vflags)),
            _flags_to_array(solo[i]),
        )


def test_window_shuffle_mode_detects_boundaries():
    """In-jit shuffle mode (no host pre-shuffle): statistical behaviour —
    every planted boundary found, no spurious detections, delay ≤ 2 batches."""
    rng = np.random.default_rng(42)
    concepts, rpc, per_batch = 6, 400, 100
    X, y = planted_classification_stream(
        rng, concepts, rpc, noise=0.01, label_flip=0
    )
    spec = ModelSpec(X.shape[1], concepts)
    runner = make_window_runner(
        build_model("centroid", spec), REF, window=16, shuffle=True
    )
    flags = jax.jit(runner)(to_batches(X, y, per_batch), jax.random.key(1))
    detected = np.asarray(flags.change_global)
    detected = detected[detected >= 0]
    assert set((detected // rpc).tolist()) == set(range(1, concepts))
    assert (detected % rpc).max() <= 2 * per_batch


def test_mesh_runner_rejects_rotations_without_window():
    from distributed_drift_detection_tpu.parallel.mesh import make_mesh_runner

    with pytest.raises(ValueError, match="rotations"):
        make_mesh_runner(
            make_majority(ModelSpec(4, 2)), REF, None, window=1, rotations=4
        )


def test_auto_rotations_resolves_from_geometry():
    """window_rotations=0 = auto: round(concepts-per-window), clamped [1, 8];
    explicit depths pass through; no geometry or sequential engine -> 1."""
    from distributed_drift_detection_tpu import RunConfig
    from distributed_drift_detection_tpu.config import auto_rotations

    auto = RunConfig(window_rotations=0, window=64, per_batch=100, partitions=16)
    # headline-like: concept_pp = 51200/16 = 3200, window covers 6400 -> 2
    assert auto_rotations(auto, 51_200) == 2
    assert auto_rotations(auto, 1 << 30) == 1  # window ≪ concept: stay at 1
    assert auto_rotations(auto, 100) == 8  # tiny concepts: clamped at 8
    assert auto_rotations(auto, 0) == 1  # no planted geometry
    seq = RunConfig(window_rotations=0, window=1)
    assert auto_rotations(seq, 51_200) == 1  # sequential engine
    explicit = RunConfig(window_rotations=5)
    assert auto_rotations(explicit, 51_200) == 5

    # api.prepare applies the resolution (and the runner accepts it).
    import numpy as np

    from distributed_drift_detection_tpu.api import prepare
    from distributed_drift_detection_tpu.io.stream import synthesize_stream

    rng = np.random.default_rng(0)
    y0 = (np.arange(512) * 4 // 512).astype(np.int64)
    X0 = rng.normal(size=(512, 8)).astype(np.float32)
    stream = synthesize_stream(X0, y0, mult_data=16, seed=0)  # dist 2048
    prep = prepare(
        RunConfig(
            dataset="<mem>", partitions=16, per_batch=4, window=64,
            window_rotations=0, results_csv="",
        ),
        stream,
    )
    # concept_pp = 128, window covers 256 elements -> round(2) = 2
    assert prep.config.window_rotations == 2


def test_default_policy_resolves_to_measured_optimum_at_headline():
    """The shipped defaults (window=0, window_rotations=0) co-resolve to the
    r03 W×R sweep's measured optimum 128×4 at the headline benchmark
    geometry (outdoorStream ×512, 16 partitions, per_batch=100 → dist
    51,200 rows) — VERDICT r3 task 1: the library default IS the published
    configuration, like the reference's run_experiments.sh defaults."""
    from distributed_drift_detection_tpu import RunConfig
    from distributed_drift_detection_tpu.config import (
        auto_rotations,
        auto_window,
        replace,
    )

    cfg = RunConfig(partitions=16, per_batch=100)
    assert cfg.window == 0 and cfg.window_rotations == 0  # auto is default
    dist = 51_200
    cfg = replace(cfg, window=auto_window(cfg, dist))
    cfg = replace(cfg, window_rotations=auto_rotations(cfg, dist))
    assert (cfg.window, cfg.window_rotations) == (128, 4)

    # A pinned depth of 1 degrades to the round-2 single-rotation policy
    # (W ≈ concept spacing), not a replay-wasting wide window.
    pinned = RunConfig(partitions=16, per_batch=100, window_rotations=1)
    assert auto_window(pinned, dist) == 32
