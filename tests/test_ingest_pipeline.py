"""Parallel host-ingest pipeline (ISSUE 10): bit-identity of the
parse→stripe→upload path at any worker count, streaming repair, the
pooled striper, the block planner, and doctor --jobs."""

import json
import os

import numpy as np
import pytest

from distributed_drift_detection_tpu.io import csv_chunks
from distributed_drift_detection_tpu.io.blocks import line_block_ranges
from distributed_drift_detection_tpu.io.sanitize import (
    RunningColumnStats,
    read_quarantine,
    scan_csv,
)


def _write_csv(path, X, y, fmt=lambda v: repr(float(v))):
    f = X.shape[1]
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(f)) + ",target\n")
        for i in range(len(y)):
            fh.write(
                ",".join(fmt(v) for v in X[i]) + f",{int(y[i])}\n"
            )


def _dirty_csv(path, n=900, f=4, seed=7):
    """Deterministic dirty stream: NaN cells, non-numeric cells, bad
    labels, ragged rows — each kind straddling block edges at small
    block_bytes."""
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        row = [repr(float(v)) for v in rng.normal(size=f)]
        row.append(str(int(rng.integers(0, 5))))
        if i % 83 == 3:
            row[1] = "nan"
        if i % 127 == 5:
            row[0] = "junk"
        if i % 149 == 7:
            row[f] = "bad"
        if i % 211 == 9:
            row = row[:f]
        lines.append(",".join(row))
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(f)) + ",target\n")
        fh.write("\n".join(lines) + "\n")
    return n, f


def _chunks_equal(a, b):
    assert len(a) == len(b)
    for ca, cb in zip(a, b):
        for name, la, lb in zip(ca._fields, ca, cb):
            np.testing.assert_array_equal(
                np.asarray(la), np.asarray(lb), err_msg=name
            )


# ---------------------------------------------------------------------------
# Parallel parse == serial parse, bit-identical
# ---------------------------------------------------------------------------


def test_parallel_chunks_bit_identical_clean(tmp_path):
    """Clean stream: every worker count yields the serial path's chunks
    exactly, including block edges straddling rows and the padded final
    partial chunk."""
    rng = np.random.default_rng(0)
    n, f = 2357, 4  # not a multiple of any chunk geometry
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = rng.integers(0, 7, n).astype(np.int32)
    path = str(tmp_path / "clean.csv")
    _write_csv(path, X, y)

    serial = list(
        csv_chunks(path, 4, 25, 3, shuffle_seed=9, block_bytes=999, workers=1)
    )
    for workers in (2, 4):
        got = list(
            csv_chunks(
                path, 4, 25, 3, shuffle_seed=9, block_bytes=999,
                workers=workers,
            )
        )
        _chunks_equal(serial, got)


def test_parallel_chunks_bit_identical_dirty_quarantine(tmp_path):
    """Quarantine-dirty stream: chunks AND sidecar contents identical at
    any worker count (ordered sidecar writes are the sequential stage's
    contract)."""
    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path)
    outs = {}
    for workers in (1, 4):
        qp = str(tmp_path / f"q{workers}.jsonl")
        outs[workers] = (
            list(
                csv_chunks(
                    path, 4, 25, 2, data_policy="quarantine",
                    quarantine_path=qp, block_bytes=777, workers=workers,
                )
            ),
            read_quarantine(qp),
        )
    _chunks_equal(outs[1][0], outs[4][0])
    assert outs[1][1] == outs[4][1]
    assert len(outs[1][1]) > 0  # the stream really was dirty


def test_parallel_flags_and_detections_identical(tmp_path):
    """The acceptance pin: drift flags and detection counts from the
    chunked engine are bit-identical across worker counts, clean and
    quarantine-dirty."""
    from distributed_drift_detection_tpu.engine import ChunkedDetector
    from distributed_drift_detection_tpu.io.synth import planted_prototypes
    from distributed_drift_detection_tpu.models import ModelSpec, build_model

    stream = planted_prototypes(0, concepts=6, rows_per_concept=300, features=6)
    clean = str(tmp_path / "clean.csv")
    _write_csv(clean, stream.X, stream.y)
    dirty = str(tmp_path / "dirty.csv")
    with open(clean) as fh:
        header = fh.readline()
        lines = fh.read().splitlines()
    for i in range(0, len(lines), 173):
        lines[i] = "nan," + lines[i].split(",", 1)[1]
    with open(dirty, "w") as fh:
        fh.write(header)
        fh.write("\n".join(lines) + "\n")

    model = build_model("centroid", ModelSpec(6, stream.num_classes))

    def flags_for(path, workers, policy=None, qp=None):
        det = ChunkedDetector(model, partitions=4, seed=0, window=4)
        chunks = csv_chunks(
            path, 4, 30, 3, shuffle_seed=5, block_bytes=2048,
            workers=workers, data_policy=policy, quarantine_path=qp,
        )
        return det.run(chunks)

    ref = flags_for(clean, 1)
    got = flags_for(clean, 4)
    for name, a, b in zip(ref._fields, ref, got):
        np.testing.assert_array_equal(a, b, err_msg=name)
    assert int((np.asarray(ref.change_global) >= 0).sum()) > 0

    ref_d = flags_for(dirty, 1, "quarantine", str(tmp_path / "qa.jsonl"))
    got_d = flags_for(dirty, 4, "quarantine", str(tmp_path / "qb.jsonl"))
    for name, a, b in zip(ref_d._fields, ref_d, got_d):
        np.testing.assert_array_equal(a, b, err_msg=name)
    assert read_quarantine(str(tmp_path / "qa.jsonl")) == read_quarantine(
        str(tmp_path / "qb.jsonl")
    )


def test_property_random_block_sizes_and_workers(tmp_path):
    """Seeded property sweep: random block sizes × worker counts all
    reproduce the reference chunks on a dirty stream (block boundaries
    are implementation detail, never semantics)."""
    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path, n=600)
    qp0 = str(tmp_path / "q_ref.jsonl")
    ref = list(
        csv_chunks(
            path, 4, 20, 2, data_policy="quarantine", quarantine_path=qp0,
            workers=1,
        )
    )
    sidecar_ref = read_quarantine(qp0)
    rng = np.random.default_rng(42)
    for trial in range(6):
        block_bytes = int(rng.integers(200, 20_000))
        workers = int(rng.integers(1, 6))
        qp = str(tmp_path / f"q_{trial}.jsonl")
        got = list(
            csv_chunks(
                path, 4, 20, 2, data_policy="quarantine",
                quarantine_path=qp, block_bytes=block_bytes, workers=workers,
            )
        )
        _chunks_equal(ref, got)
        assert read_quarantine(qp) == sidecar_ref, (block_bytes, workers)


def test_strict_raises_first_violation_any_worker_count(tmp_path):
    from distributed_drift_detection_tpu.io.sanitize import (
        StreamContractError,
    )

    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path)
    msgs = []
    for workers in (1, 4):
        with pytest.raises(StreamContractError) as ei:
            list(
                csv_chunks(
                    path, 4, 25, 2, data_policy="strict", block_bytes=777,
                    workers=workers,
                )
            )
        msgs.append(str(ei.value))
    assert msgs[0] == msgs[1]
    assert "data row 3" in msgs[0]  # first violation in ROW order


# ---------------------------------------------------------------------------
# Streaming repair (satellite: csv_chunks data_policy='repair')
# ---------------------------------------------------------------------------


def test_csv_chunks_streaming_repair_matches_running_means(tmp_path):
    """Block-wise repair imputes each NaN feature cell from the running
    column means over rows admitted in PRIOR blocks — the serve-admission
    semantics (RunningColumnStats), deliberately not the one-shot
    loader's whole-file means."""
    n, f = 120, 3
    rng = np.random.default_rng(3)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (np.arange(n) % 4).astype(np.int32)
    path = str(tmp_path / "repair.csv")
    _write_csv(path, X, y)
    # poison one known cell deep in the stream (beyond the first blocks)
    with open(path) as fh:
        header = fh.readline()
        lines = fh.read().splitlines()
    bad_row = 100
    fields = lines[bad_row].split(",")
    fields[1] = "nan"
    lines[bad_row] = ",".join(fields)
    with open(path, "w") as fh:
        fh.write(header)
        fh.write("\n".join(lines) + "\n")

    # small blocks so the bad row is NOT in the first block
    block_bytes = 1500
    chunks = list(
        csv_chunks(
            path, 2, 10, 2, data_policy="repair", block_bytes=block_bytes,
            workers=1,
            quarantine_path=str(tmp_path / "qr.jsonl"),
        )
    )
    # reconstruct the expected imputed value: running mean over all rows
    # of the blocks BEFORE the bad row's block (exactly what the feeder's
    # sequential sanitize stage has seen when the block arrives) — same
    # planner, same whole-file offsets, so boundaries agree exactly
    with open(path, "rb") as fh:
        buf = fh.read()
    data_start = buf.index(b"\n") + 1
    ranges = line_block_ranges(buf, data_start, block_bytes)
    rows_before = 0
    stats = RunningColumnStats(f + 1)
    for lo, hi in ranges:
        block_lines = buf[lo:hi].decode().splitlines()
        rows_here = len(block_lines)
        if rows_before + rows_here > bad_row:
            break
        arr = np.array(
            [ln.split(",") for ln in block_lines], dtype=np.float64
        ).astype(np.float32)
        stats.update(arr)
        rows_before += rows_here
    expected = stats.means()[1]

    # find the repaired cell in the striped output: global position ==
    # bad_row, partition bad_row % 2
    part = bad_row % 2
    found = []
    for chunk in chunks:
        rows = np.asarray(chunk.rows[part])
        hit = np.argwhere(rows == bad_row)
        for b_i, j in hit:
            if np.asarray(chunk.valid[part])[b_i, j]:
                found.append(np.asarray(chunk.X[part])[b_i, j, 1])
    assert len(found) == 1
    np.testing.assert_allclose(found[0], expected, rtol=1e-6)
    # the repaired row was NOT quarantined
    assert not os.path.exists(str(tmp_path / "qr.jsonl"))


def test_streaming_repair_parallel_identical_and_quarantines_rest(tmp_path):
    """repair at any worker count: identical chunks; unrepairable rows
    (ragged, non-finite label) land in the sidecar like the whole-file
    repair policy."""
    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path)
    outs = {}
    for workers in (1, 3):
        qp = str(tmp_path / f"qr{workers}.jsonl")
        outs[workers] = (
            list(
                csv_chunks(
                    path, 4, 25, 2, data_policy="repair",
                    quarantine_path=qp, block_bytes=777, workers=workers,
                )
            ),
            read_quarantine(qp),
        )
    _chunks_equal(outs[1][0], outs[3][0])
    assert outs[1][1] == outs[3][1]
    reasons = {r["reason"].split(":")[0] for r in outs[1][1]}
    assert any("ragged" in r for r in reasons)  # unrepairable → sidecar
    # NaN-cell rows were repaired, not quarantined: fewer sidecar rows
    # than the quarantine policy drops
    qq = str(tmp_path / "qq.jsonl")
    list(
        csv_chunks(
            path, 4, 25, 2, data_policy="quarantine", quarantine_path=qq,
            workers=1,
        )
    )
    assert len(outs[1][1]) < len(read_quarantine(qq))


def test_streaming_repair_label_domain_guard(tmp_path):
    """Repair never fabricates an out-of-domain class index: a
    non-integral label rounds only when num_classes proves the rounded
    value stays in 0..C-1 (serve admission's clause); otherwise — out of
    domain, or domain unknown — the row is quarantined."""
    rng = np.random.default_rng(8)
    n, f = 60, 3
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (np.arange(n) % 5).astype(np.int32)
    path = str(tmp_path / "labels.csv")
    _write_csv(path, X, y)
    with open(path) as fh:
        header = fh.readline()
        lines = fh.read().splitlines()
    lines[10] = lines[10].rsplit(",", 1)[0] + ",2.6"  # rounds to 3: in domain
    lines[20] = lines[20].rsplit(",", 1)[0] + ",4.6"  # rounds to 5: OUT
    with open(path, "w") as fh:
        fh.write(header)
        fh.write("\n".join(lines) + "\n")

    def run(num_classes, tag):
        qp = str(tmp_path / f"q_{tag}.jsonl")
        chunks = list(
            csv_chunks(
                path, 2, 10, 1, data_policy="repair", quarantine_path=qp,
                num_classes=num_classes, workers=1,
            )
        )
        quarantined = sorted(
            r["row"]
            for r in (read_quarantine(qp) if os.path.exists(qp) else [])
        )
        labels = {}
        for c in chunks:
            for part in range(2):
                rows = np.asarray(c.rows[part])
                valid = np.asarray(c.valid[part])
                ys = np.asarray(c.y[part])
                for idx in np.argwhere(valid):
                    labels[int(rows[tuple(idx)])] = int(ys[tuple(idx)])
        return quarantined, labels

    # domain known: 2.6 rounds to 3 (admitted), 4.6 would round out → drop
    quarantined, labels = run(5, "known")
    assert quarantined == [20]
    assert labels[10] == 3 and 20 not in labels
    # domain unknown: both conservatively quarantined, never rounded
    quarantined, labels = run(None, "unknown")
    assert quarantined == [10, 20]
    assert 10 not in labels and 20 not in labels


# ---------------------------------------------------------------------------
# ChunkStriper (pooled striper) == stripe_chunk
# ---------------------------------------------------------------------------


def test_chunk_striper_bit_identical_to_stripe_chunk():
    from distributed_drift_detection_tpu.io.stream import (
        ChunkStriper,
        stripe_chunk,
    )

    rng = np.random.default_rng(11)
    p, b, nb = 4, 10, 3
    span = p * b * nb
    for seed in (None, 17):
        striper = ChunkStriper(p, b, nb, shuffle_seed=seed)
        for k, n in enumerate([span, span, span // 2, 37]):
            X = rng.normal(size=(n, 5)).astype(np.float32)
            y = rng.integers(0, 3, n).astype(np.int32)
            rv = None
            if k % 2:
                rv = rng.random(n) > 0.2
            start = k * span
            want = stripe_chunk(X, y, start, p, b, nb, seed, row_valid=rv)
            got = striper.stripe(X, y, start, row_valid=rv)
            for name, a, c in zip(want._fields, want, got):
                np.testing.assert_array_equal(a, c, err_msg=f"{seed}/{k}/{name}")


def test_chunk_striper_bf16_transport():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    from distributed_drift_detection_tpu.io.stream import (
        ChunkStriper,
        stripe_chunk,
    )

    rng = np.random.default_rng(2)
    p, b, nb = 2, 8, 2
    X = rng.normal(size=(25, 3)).astype(np.float32)
    y = rng.integers(0, 2, 25).astype(np.int32)
    striper = ChunkStriper(p, b, nb, feature_dtype=ml_dtypes.bfloat16)
    want = stripe_chunk(
        X, y, 0, p, b, nb, feature_dtype=ml_dtypes.bfloat16
    )
    got = striper.stripe(X, y, 0)
    assert got.X.dtype == ml_dtypes.bfloat16
    for name, a, c in zip(want._fields, want, got):
        np.testing.assert_array_equal(a, c, err_msg=name)


def test_striper_output_independent_of_staging_reuse():
    """Chunks handed downstream must not alias the pooled staging: a
    later stripe() cannot mutate an earlier chunk."""
    from distributed_drift_detection_tpu.io.stream import ChunkStriper

    rng = np.random.default_rng(4)
    striper = ChunkStriper(2, 5, 2)
    X1 = rng.normal(size=(20, 3)).astype(np.float32)
    y1 = rng.integers(0, 2, 20).astype(np.int32)
    first = striper.stripe(X1, y1, 0)
    snapshot = np.array(first.X, copy=True)
    striper.stripe(-X1, y1, 20)  # reuses staging with different content
    np.testing.assert_array_equal(first.X, snapshot)


# ---------------------------------------------------------------------------
# Block planner
# ---------------------------------------------------------------------------


def test_line_block_ranges_invariants():
    data = b"aa\nbbbb\nc\n" + b"d" * 50 + b"\ne\n"
    for bb in (1, 3, 7, 100):
        ranges = line_block_ranges(data, 0, bb)
        # contiguous, disjoint, covering
        assert ranges[0][0] == 0 and ranges[-1][1] == len(data)
        for (alo, ahi), (blo, bhi) in zip(ranges, ranges[1:]):
            assert ahi == blo
        # every boundary (except EOF) lands one past a newline
        for lo, hi in ranges[:-1]:
            assert data[hi - 1 : hi] == b"\n"
    # offset start + no trailing newline
    tail = b"x,1\ny,2"
    ranges = line_block_ranges(tail, 2, 3)
    assert ranges[0][0] == 2 and ranges[-1][1] == len(tail)
    with pytest.raises(ValueError):
        line_block_ranges(tail, 0, 0)


# ---------------------------------------------------------------------------
# doctor --jobs (satellite)
# ---------------------------------------------------------------------------


def test_scan_csv_jobs_identical_ordering(tmp_path):
    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path, n=800)
    serial = scan_csv(path)
    for jobs in (2, 3, 8):
        assert scan_csv(path, jobs=jobs) == serial
    assert len(serial[0]) > 0 and serial[1] == 800


def test_doctor_cli_jobs_output_identical(tmp_path, capsys):
    from distributed_drift_detection_tpu.io.sanitize import main as doctor

    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path, n=400)
    outs = []
    for jobs in ("1", "4"):
        with pytest.raises(SystemExit) as ei:
            doctor([path, "--jobs", jobs, "--max-report", "50"])
        assert ei.value.code == 1
        outs.append(capsys.readouterr().out)
    assert outs[0] == outs[1]
    assert "data row" in outs[0]


# ---------------------------------------------------------------------------
# Pipeline telemetry (tentpole d)
# ---------------------------------------------------------------------------


def test_pipeline_stage_gauges_recorded(tmp_path):
    from distributed_drift_detection_tpu.io.feeder import STAGE_BUSY_METRIC
    from distributed_drift_detection_tpu.telemetry.metrics import (
        MetricsRegistry,
    )

    rng = np.random.default_rng(1)
    X = rng.normal(size=(500, 3)).astype(np.float32)
    y = rng.integers(0, 4, 500).astype(np.int32)
    path = str(tmp_path / "s.csv")
    _write_csv(path, X, y)
    reg = MetricsRegistry()
    chunks = list(
        csv_chunks(path, 2, 10, 2, metrics=reg, workers=2, block_bytes=4096)
    )
    assert chunks
    stages = {
        dict(k)["stage"]
        for k in reg.counter(STAGE_BUSY_METRIC).values
    }
    assert {"read", "parse", "sanitize", "stripe"} <= stages
    assert reg.gauge("ingest_workers").values[()] == 2
    assert ("ingest_parse_queue_depth" in reg.to_json())
    n_rows = reg.counter("ingest_rows_total").values[()]
    assert n_rows == 500


def test_chunked_run_records_upload_stage(tmp_path):
    from distributed_drift_detection_tpu.engine import ChunkedDetector
    from distributed_drift_detection_tpu.io import chunk_stream_arrays
    from distributed_drift_detection_tpu.io.feeder import STAGE_BUSY_METRIC
    from distributed_drift_detection_tpu.io.synth import planted_prototypes
    from distributed_drift_detection_tpu.models import ModelSpec, build_model
    from distributed_drift_detection_tpu.telemetry.metrics import (
        MetricsRegistry,
    )

    stream = planted_prototypes(0, concepts=4, rows_per_concept=200, features=5)
    model = build_model("centroid", ModelSpec(5, stream.num_classes))
    det = ChunkedDetector(model, partitions=2, seed=0)
    reg = MetricsRegistry()
    det.run(
        chunk_stream_arrays(stream.X, stream.y, 2, 20, 2), metrics=reg
    )
    key = (("stage", "upload"),)
    assert reg.counter(STAGE_BUSY_METRIC).values.get(key, 0) > 0


def test_chunked_cli_worker_invariance(tmp_path, capsys):
    """The `chunked` subcommand (the CI smoke's driver): identical
    detections + quarantine sidecar at 1 vs 3 workers, pipeline gauges in
    the metric exports."""
    from distributed_drift_detection_tpu.harness.chunked_cli import main

    path = str(tmp_path / "dirty.csv")
    _dirty_csv(path, n=500)
    reports = []
    for workers in (1, 3):
        tele = str(tmp_path / f"tele{workers}")
        qp = str(tmp_path / f"q{workers}.jsonl")
        main(
            [
                path, "--classes", "5", "--partitions", "2",
                "--per-batch", "20", "--chunk-batches", "2",
                "--window", "2", "--ingest-workers", str(workers),
                "--data-policy", "quarantine", "--quarantine-path", qp,
                "--telemetry-dir", tele, "--block-bytes", "2048",
            ]
        )
        reports.append(json.loads(capsys.readouterr().out.strip()))
        prom = [
            p for p in os.listdir(tele) if p.endswith(".prom")
        ]
        assert prom, "metric exports missing"
        text = open(os.path.join(tele, prom[0])).read()
        assert "ingest_stage_busy_seconds_total" in text
        assert "ingest_parse_queue_depth" in text
    a, b = reports
    assert a["detections"] == b["detections"]
    assert a["rows"] == b["rows"] and a["quarantined"] == b["quarantined"]
    assert read_quarantine(str(tmp_path / "q1.jsonl")) == read_quarantine(
        str(tmp_path / "q3.jsonl")
    )
